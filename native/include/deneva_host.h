/* C API of the native host runtime (reference `transport/`, SURVEY §2.6).
 *
 * The reference's communication backend is an N×N nanomsg PAIR mesh with
 * per-send-thread batching mbufs (`transport/transport.cpp:171-304`,
 * `transport/msg_thread.cpp:44-118`).  This library provides the same
 * capability over raw sockets (TCP or Unix-domain; nanomsg is not in the
 * image and adds nothing over length-framed streams):
 *
 *   - full mesh of stream sockets, one connection per peer pair,
 *     established by a bind/connect handshake keyed on node id;
 *   - length-framed binary messages with a fixed header
 *     {len, rtype, flags, src};
 *   - per-destination send batching up to msg_size_max bytes or a flush
 *     timeout (the reference's mbuf, `transport/msg_thread.cpp:96-101`);
 *   - a sender thread and a poll-based receiver thread feeding a bounded
 *     MPMC queue (the reference's output/input threads,
 *     `system/io_thread.cpp`);
 *   - artificial send-delay injection (NETWORK_DELAY_TEST,
 *     `system/msg_queue.cpp:104-125`) and a ping-pong self test
 *     (NETWORK_TEST, `system/main.cpp:346-387`);
 *   - monotonically increasing stats counters.
 *
 * Consumed from Python via ctypes (no pybind11 in the image); the Python
 * side never touches sockets.
 */

#ifndef DENEVA_HOST_H
#define DENEVA_HOST_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct dt_transport dt_transport;

/* Message types on the wire (reference RemReqType, system/global.h:237-262).
 * Payloads are opaque to the transport; the columnar query codecs below
 * and the Python runtime define the bodies. */
enum dt_rtype {
  DT_INIT_DONE = 1,   /* setup barrier (reference INIT_DONE) */
  DT_CL_QRY_BATCH = 2,/* columnar client query block (CL_QRY batch) */
  DT_CL_RSP = 3,      /* per-txn client response (CL_RSP) */
  DT_RDONE = 4,       /* epoch done marker (Calvin RDONE) */
  DT_EPOCH_BLOB = 5,  /* server<->server epoch payload (RW-sets/verdicts) */
  DT_LOG_MSG = 6,     /* replica log shipping (LOG_MSG) */
  DT_LOG_RSP = 7,     /* replica ack (LOG_MSG_RSP) */
  DT_PING = 8,        /* NETWORK_TEST ping */
  DT_PONG = 9,        /* NETWORK_TEST pong */
  DT_SHUTDOWN = 10,   /* orderly teardown */
  DT_MEASURE = 11,    /* epoch-aligned measure-window start */
  DT_VOTE = 12,       /* batched 2PC prepare votes (RPREPARE/RACK_PREP) */
  DT_VOTE2 = 13,      /* MAAT verify-round votes (second RACK_PREP) */
  DT_REJOIN = 14,     /* crash-recovery: restarted node announces resume */
};

/* Stats slot indices for dt_stats(). */
enum dt_stat {
  DT_STAT_MSG_SENT = 0,
  DT_STAT_MSG_RCVD = 1,
  DT_STAT_BYTES_SENT = 2,
  DT_STAT_BYTES_RCVD = 3,
  DT_STAT_BATCHES_SENT = 4,
  DT_STAT_SEND_QUEUE_DEPTH = 5,
  DT_STAT_RECV_QUEUE_DEPTH = 6,
  DT_STAT_MSG_DROPPED = 7,   /* fault injection: frames dropped at send */
  DT_STAT_MSG_DUP = 8,       /* fault injection: frames duplicated */
  DT_STAT_RECONNECTS = 9,    /* links re-established after a peer restart */
  DT_STAT_MSG_BLACKHOLED = 10, /* partition injection: frames blackholed */
  DT_STAT_COUNT = 11
};

/* Per-link partition blackhole directions (dt_set_partition). */
enum dt_part_mode {
  DT_PART_NONE = 0,
  DT_PART_TX = 1,   /* frames WE send to the peer vanish */
  DT_PART_RX = 2,   /* frames the peer sends US vanish on arrival */
};

/* endpoints: n_nodes lines "node_id proto addr", e.g.
 *   "0 ipc /tmp/dt_node0.sock\n1 tcp 127.0.0.1:17001\n"
 * (the reference's ifconfig.txt, transport/transport.cpp:28-44).
 * Returns NULL on parse error. */
dt_transport *dt_create(uint32_t node_id, const char *endpoints,
                        uint32_t n_nodes, uint32_t msg_size_max,
                        uint32_t flush_timeout_us);

/* Bind own endpoint, connect the full mesh, start sender+receiver threads.
 * Blocks until every peer link is up or timeout_ms elapses.
 * Returns 0 on success. */
int dt_start(dt_transport *t, int timeout_ms);

/* Enqueue one message to dest (batched; thread-safe).  Returns 0 on
 * success, -1 if the transport is shut down or dest invalid. */
int dt_send(dt_transport *t, uint32_t dest, uint16_t rtype,
            const uint8_t *payload, uint32_t len);

/* Scatter-gather variant of dt_send (writev-shaped): the payload is the
 * concatenation of n_iov segments.  The frame (header + all segments) is
 * assembled ONCE into the transport's internal buffer — callers ship
 * multi-part bodies (codec header + column arrays) without building a
 * contiguous payload first, so Python-side framing stops copying bodies.
 * Segment memory may be reused as soon as the call returns.  A segment
 * with len 0 is skipped (base may be NULL).  Same fault-injection and
 * loopback semantics as dt_send.  Returns 0 on success. */
typedef struct dt_iov {
  const void *base;
  size_t len;
} dt_iov;
int dt_sendv(dt_transport *t, uint32_t dest, uint16_t rtype,
             const dt_iov *iov, uint32_t n_iov);

/* Pop one received message.  Returns payload length >= 0 and fills
 * src/rtype, or -1 on timeout, -2 if buf too small (message stays
 * queued; required size in *len_needed if non-NULL). timeout_us < 0
 * blocks indefinitely. */
long dt_recv(dt_transport *t, uint8_t *buf, uint32_t cap, uint32_t *src,
             uint16_t *rtype, long timeout_us, uint32_t *len_needed);

/* Force all batching buffers onto the wire now. */
void dt_flush(dt_transport *t);

/* Artificial send delay (NETWORK_DELAY_TEST): frames stay in the batch
 * queue for at least delay_us before hitting the socket. */
void dt_set_delay_us(dt_transport *t, uint64_t delay_us);

/* Per-destination extra send delay (geo-replication WAN profiles: one
 * value per link, added on top of the global dt_set_delay_us).  May be
 * called before or after dt_start; 0 (the default) disables.  Returns
 * 0, -1 on a bad peer id. */
int dt_set_peer_delay_us(dt_transport *t, uint32_t peer,
                         uint64_t delay_us);

/* Per-link partition blackhole (chaos harness, partition scenarios):
 * mode is a dt_part_mode bitmask.  DT_PART_TX discards frames enqueued
 * toward the peer; DT_PART_RX discards frames arriving from it (both
 * counted as DT_STAT_MSG_BLACKHOLED).  Unlike dt_set_fault this hits
 * EVERY rtype — a partition takes the whole link — but the sockets
 * stay open, so dt_peer_alive keeps reporting 1: exactly the gray
 * failure the transport-level flag cannot see (the failure detector
 * in runtime/faildet.py is what notices).  Loopback frames are exempt.
 * May be called before or after dt_start; 0 restores the link.
 * Returns 0, -1 on a bad peer id. */
int dt_set_partition(dt_transport *t, uint32_t peer, uint32_t mode);

/* Gray-slow peer (chaos harness): hold frames to `peer` for an extra
 * stall_us before they hit the wire, on top of dt_set_delay_us /
 * dt_set_peer_delay_us.  A separate knob from the geo WAN profile so a
 * scenario can model "this process went slow" without disturbing the
 * configured topology delays.  0 (default) disables.  Returns 0, -1 on
 * a bad peer id. */
int dt_set_peer_stall_us(dt_transport *t, uint32_t peer,
                         uint64_t stall_us);

/* Seeded fault injection (chaos harness; the reference has none).
 * Applied at enqueue time to frames whose rtype bit is set in rtype_mask
 * (bit i = rtype i, rtypes >= 32 never match): drop with probability
 * drop_ppm/1e6, duplicate with dup_ppm/1e6, and park for a uniform
 * [0, jitter_us) extra delay.  Decisions come from a splitmix64 stream
 * over (seed, per-transport frame counter), so a single-threaded sender
 * replays the identical fault pattern from the same seed.  Loopback
 * frames are exempt.  May be called before or after dt_start; all-zero
 * arguments disable injection (the default).  Returns 0. */
int dt_set_fault(dt_transport *t, uint32_t drop_ppm, uint32_t dup_ppm,
                 uint64_t jitter_us, uint64_t seed, uint32_t rtype_mask);

/* Crash-recovery rejoin: call BEFORE dt_start on a restarted node.
 * dt_start then dials EVERY peer (instead of the bind/connect split) —
 * live peers accept the redial on their listening socket at any time,
 * replace the dead link and clear the peer's dead flag.  Returns 0,
 * -1 after start. */
int dt_set_rejoin(dt_transport *t, int on);

/* Copy DT_STAT_COUNT counters into out. */
void dt_stats(const dt_transport *t, uint64_t *out);

/* 1 while the link to peer is up, 0 after a read/write on it failed
 * (failure detection — the reference has none, SURVEY §5.3). */
int dt_peer_alive(const dt_transport *t, uint32_t peer);

/* IO-thread axes (reference SEND_THREAD_CNT / REM_THREAD_CNT,
 * system/main.cpp:196-310): destinations shard over n_send sender
 * threads (dest % n_send; per-destination FIFO preserved) and peers
 * shard over n_recv receiver threads (src % n_recv).  Call BEFORE
 * dt_start; returns -1 after start.  0 means 1. */
int dt_set_io_threads(dt_transport *t, uint32_t n_send, uint32_t n_recv);

/* Ping-pong round trips against peer; returns mean round-trip ns, or -1.
 * (reference NETWORK_TEST, system/main.cpp:346-387) */
long dt_ping(dt_transport *t, uint32_t peer, uint32_t rounds,
             uint32_t payload_len);

/* Stop threads, close sockets, free. Safe on NULL. */
void dt_destroy(dt_transport *t);

/* ---- columnar query-batch codec -------------------------------------
 * CL_QRY batches travel as columnar blocks so the server can hand them
 * straight to the device pool: n queries × fixed width key/type arrays
 * plus per-query scalars.  Layout (little-endian):
 *   uint32 n, uint32 width, uint32 n_scalars
 *   int64 client_startts[n]
 *   int32 keys[n*width], int8 types[n*width]
 *   int32 scalars[n*n_scalars]
 * Returns bytes written (call with out=NULL to size), -1 on error. */
long dt_qrybatch_encode(uint32_t n, uint32_t width, uint32_t n_scalars,
                        const int64_t *startts, const int32_t *keys,
                        const int8_t *types, const int32_t *scalars,
                        uint8_t *out, size_t cap);
long dt_qrybatch_decode(const uint8_t *buf, size_t len, uint32_t *n,
                        uint32_t *width, uint32_t *n_scalars,
                        int64_t *startts, int32_t *keys, int8_t *types,
                        int32_t *scalars, size_t arrays_cap);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* DENEVA_HOST_H */
