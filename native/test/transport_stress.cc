// Concurrency stress for the native transport, built under TSAN/ASAN
// (SURVEY §5.2: the reference has a dead DEBUG_RACE flag and a
// commented-out ASan line, Makefile:3 — sanitizer builds are the modern
// equivalent, and this binary is their workload).
//
// Exercises: full-mesh setup, concurrent dt_send from several threads,
// loopback delivery, concurrent dt_recv, dt_flush tickets racing the
// sender, delay injection, stats reads, ping-pong, and teardown racing
// in-flight traffic.  Exits 0 iff every message is accounted for; any
// data race / leak is the sanitizer's to report (nonzero exit).

#include "../include/deneva_host.h"

#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kNodes = 3;
constexpr int kSendersPerNode = 3;
constexpr int kMsgsPerSender = 2000;

std::string endpoints(const char* dir) {
  // pid-unique socket paths: concurrent tsan/asan runs must not steal
  // each other's listeners (dt_start unlinks before bind)
  std::string pid = std::to_string(::getpid());
  std::string s;
  for (uint32_t i = 0; i < kNodes; ++i)
    s += std::to_string(i) + " ipc " + dir + "/stress_" + pid + "_n" +
         std::to_string(i) + ".sock\n";
  return s;
}

}  // namespace

int main() {
  const char* dir = "/tmp";
  std::string eps = endpoints(dir);

  dt_transport* t[kNodes];
  for (uint32_t i = 0; i < kNodes; ++i) {
    t[i] = dt_create(i, eps.c_str(), kNodes, 4096, 100);
    if (!t[i]) {
      std::fprintf(stderr, "dt_create %u failed\n", i);
      return 1;
    }
  }
  std::vector<std::thread> starters;
  std::atomic<int> start_fail{0};
  for (uint32_t i = 0; i < kNodes; ++i)
    starters.emplace_back([&, i] {
      if (dt_start(t[i], 10000) != 0) start_fail.fetch_add(1);
    });
  for (auto& th : starters) th.join();
  if (start_fail.load()) {
    std::fprintf(stderr, "mesh setup failed\n");
    return 1;
  }

  // receivers count everything that arrives
  std::atomic<uint64_t> rcvd{0};
  std::atomic<bool> stop{false};
  std::vector<std::thread> rxs;
  for (uint32_t i = 0; i < kNodes; ++i)
    rxs.emplace_back([&, i] {
      std::vector<uint8_t> buf(1 << 16);
      uint32_t src;
      uint16_t rt;
      uint32_t need;
      while (!stop.load(std::memory_order_relaxed)) {
        long n = dt_recv(t[i], buf.data(), buf.size(), &src, &rt, 2000,
                         &need);
        if (n >= 0) rcvd.fetch_add(1, std::memory_order_relaxed);
      }
    });

  // senders hammer every destination (including loopback), racing flushes
  std::vector<std::thread> txs;
  std::atomic<uint64_t> sent{0};
  for (uint32_t i = 0; i < kNodes; ++i) {
    for (int s = 0; s < kSendersPerNode; ++s) {
      txs.emplace_back([&, i, s] {
        uint8_t payload[64];
        std::memset(payload, 0x5A, sizeof(payload));
        for (int m = 0; m < kMsgsPerSender; ++m) {
          uint32_t dest = static_cast<uint32_t>((i + 1 + m) % kNodes);
          if (dt_send(t[i], dest, DT_EPOCH_BLOB, payload,
                      sizeof(payload)) == 0)
            sent.fetch_add(1, std::memory_order_relaxed);
          if ((m & 255) == 0) dt_flush(t[i]);
          if (s == 0 && (m & 511) == 0)
            dt_set_delay_us(t[i], (m & 1024) ? 50 : 0);
        }
        dt_flush(t[i]);
      });
    }
  }
  for (auto& th : txs) th.join();

  // ping-pong while receivers still run
  long rtt = dt_ping(t[0], 1, 5, 8);
  if (rtt < 0) std::fprintf(stderr, "warn: ping failed\n");

  // drain until everything sent has been received (bounded)
  uint64_t stat[DT_STAT_COUNT];
  for (int spins = 0; spins < 4000; ++spins) {
    if (rcvd.load() >= sent.load()) break;
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  stop.store(true);
  for (auto& th : rxs) th.join();
  dt_stats(t[0], stat);

  uint64_t s_total = sent.load(), r_total = rcvd.load();
  for (uint32_t i = 0; i < kNodes; ++i) dt_destroy(t[i]);
  if (r_total < s_total) {
    std::fprintf(stderr, "lost messages: sent=%llu rcvd=%llu\n",
                 (unsigned long long)s_total, (unsigned long long)r_total);
    return 1;
  }
  std::printf("stress ok: sent=%llu rcvd=%llu rtt=%ldns\n",
              (unsigned long long)s_total, (unsigned long long)r_total, rtt);
  return 0;
}
