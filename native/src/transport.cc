// Native transport: full-mesh stream sockets + batching + IO threads.
// See include/deneva_host.h for the contract and the reference mapping
// (`transport/transport.cpp`, `transport/msg_thread.cpp`,
// `system/io_thread.cpp`).

#include "deneva_host.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "mpmc_queue.h"

namespace {

using Clock = std::chrono::steady_clock;

uint64_t now_us() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             Clock::now().time_since_epoch())
      .count();
}

// Deterministic fault stream (chaos harness): one splitmix64 draw per
// eligible frame, chained for the per-frame drop/dup/jitter decisions.
uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e9b5ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

constexpr uint32_t kHelloMagic = 0xD27EAF01u;

// Wire frame header (little-endian; both ends are x86/ARM LE here —
// the reference's COPY_BUF serialization makes the same assumption).
struct FrameHdr {
  uint32_t paylen;
  uint16_t rtype;
  uint16_t pad;
  uint32_t src;
};
static_assert(sizeof(FrameHdr) == 12, "frame header must be 12 bytes");

struct Endpoint {
  bool ipc = false;
  std::string addr;  // path (ipc) or host:port (tcp)
};

struct RecvMsg {
  uint32_t src = 0;
  uint16_t rtype = 0;
  std::vector<uint8_t> payload;
};

struct OutFrame {
  uint32_t dest;
  uint64_t ready_us;  // delay injection
  std::vector<uint8_t> bytes;  // header + payload
};

// MSG_NOSIGNAL: a half-closed peer must surface as EPIPE, not SIGPIPE
// (CPython ignores SIGPIPE; a bare C++ embedder would die).
ssize_t write_all(int fd, const uint8_t *p, size_t n) {
  size_t done = 0;
  while (done < n) {
    ssize_t w = ::send(fd, p + done, n - done, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    done += static_cast<size_t>(w);
  }
  return static_cast<ssize_t>(done);
}

}  // namespace

struct dt_transport {
  uint32_t node_id = 0;
  uint32_t n_nodes = 0;
  uint32_t msg_size_max = 4096;
  uint32_t flush_timeout_us = 200;
  std::vector<Endpoint> eps;

  // peer_fd slots are atomic: besides dt_start (before IO threads exist)
  // they are swapped by receiver shard 0 when a restarted peer redials
  // (crash-recovery rejoin).  Replaced fds are parked in a graveyard and
  // closed only at teardown, so a sender mid-write can never touch a
  // recycled descriptor; a failed write/read marks peer_dead only if the
  // slot still holds the fd it used (a stale-fd failure must not smear
  // the freshly reconnected link).
  std::vector<std::atomic<int>> peer_fd;  // fd per node id (-1 = none/self)
  std::vector<std::atomic<bool>> peer_dead;
  std::vector<int> fd_graveyard;
  std::mutex graveyard_mu;
  int listen_fd = -1;
  bool rejoin = false;  // dt_start dials every peer instead of split

  // bounded (SURVEY §2.6: the reference's queues are bounded rings);
  // a full shard queue blocks dt_send, full recv_q pauses the reader ->
  // TCP backpressure reaches the remote sender.
  deneva::MpmcQueue<RecvMsg> recv_q{1 << 16};

  // per-dest batch accumulation (owned by one sender shard)
  struct Mbuf {
    std::vector<uint8_t> buf;
    uint64_t first_us = 0;
  };

  // IO-thread axes (reference SEND_THREAD_CNT / REM_THREAD_CNT,
  // transport/transport.cpp:171-221 one socket pair per (peer,
  // send-thread)): destinations shard over n_send sender threads
  // (dest % n_send -> per-dest FIFO preserved, which the runtime's
  // MEASURE/SHUTDOWN-before-blob ordering relies on) and peers shard
  // over n_recv receiver threads (src % n_recv).  Each sender shard
  // owns its queue, its mbufs and its flush ticket pair; dt_flush
  // tickets every shard.  Set via dt_set_io_threads BEFORE dt_start.
  struct IoShard {
    deneva::MpmcQueue<OutFrame> q{1 << 16};
    std::atomic<uint64_t> flush_req{0};
    std::atomic<uint64_t> flush_done{0};
    std::vector<Mbuf> mbufs;
  };
  uint32_t n_send = 1, n_recv = 1;
  std::vector<std::unique_ptr<IoShard>> shards;
  std::vector<std::thread> senders, receivers;

  std::atomic<bool> stop{false};
  std::atomic<uint64_t> delay_us{0};
  // per-destination extra delay (geo WAN profiles): added on top of the
  // global delay_us; sized at dt_create, all-zero by default
  std::vector<std::atomic<uint64_t>> peer_delay_us;
  // gray-slow stall (dt_set_peer_stall_us): a separate additive term so
  // fault scenarios compose with configured WAN profiles
  std::vector<std::atomic<uint64_t>> peer_stall_us;
  // per-link partition blackhole (dt_set_partition): dt_part_mode bits.
  // TX drops at enqueue, RX drops at delivery — the sockets stay open,
  // so peer_alive cannot see a partition (by design; that blindness is
  // what the fencing layer's suspicion score exists for).
  std::vector<std::atomic<uint32_t>> part_mode;
  // fault injection (dt_set_fault): all-zero = disabled (default)
  std::atomic<uint32_t> fault_drop_ppm{0};
  std::atomic<uint32_t> fault_dup_ppm{0};
  std::atomic<uint64_t> fault_jitter_us{0};
  std::atomic<uint32_t> fault_mask{0};
  std::atomic<uint64_t> fault_seed{0};
  std::atomic<uint64_t> fault_ctr{0};
  std::atomic<uint64_t> stats[DT_STAT_COUNT]{};

  // ping bookkeeping: receiver thread answers pings itself and routes
  // pongs here instead of the application queue
  deneva::MpmcQueue<uint64_t> pong_q;

  ~dt_transport() {
    stop.store(true);
    for (auto &sh : shards) sh->q.stop();
    recv_q.stop();
    pong_q.stop();
    for (auto &th : senders)
      if (th.joinable()) th.join();
    for (auto &th : receivers)
      if (th.joinable()) th.join();
    for (auto &slot : peer_fd) {
      int fd = slot.load(std::memory_order_relaxed);
      if (fd >= 0) ::close(fd);
    }
    for (int fd : fd_graveyard)
      if (fd >= 0) ::close(fd);
    if (listen_fd >= 0) ::close(listen_fd);
    if (node_id < eps.size() && eps[node_id].ipc)
      ::unlink(eps[node_id].addr.c_str());
  }

  void bump(dt_stat s, uint64_t v = 1) {
    stats[s].fetch_add(v, std::memory_order_relaxed);
  }

  // ---- mesh setup ----------------------------------------------------

  int make_listen() {
    const Endpoint &ep = eps[node_id];
    if (ep.ipc) {
      listen_fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
      if (listen_fd < 0) return -1;
      sockaddr_un sa{};
      sa.sun_family = AF_UNIX;
      std::snprintf(sa.sun_path, sizeof(sa.sun_path), "%s", ep.addr.c_str());
      ::unlink(ep.addr.c_str());
      if (::bind(listen_fd, reinterpret_cast<sockaddr *>(&sa), sizeof(sa)) < 0)
        return -1;
    } else {
      listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
      if (listen_fd < 0) return -1;
      int one = 1;
      ::setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
      sockaddr_in sa{};
      if (parse_tcp(ep.addr, &sa) != 0) return -1;
      if (::bind(listen_fd, reinterpret_cast<sockaddr *>(&sa), sizeof(sa)) < 0)
        return -1;
    }
    return ::listen(listen_fd, static_cast<int>(n_nodes));
  }

  static int parse_tcp(const std::string &addr, sockaddr_in *sa) {
    auto colon = addr.rfind(':');
    if (colon == std::string::npos) return -1;
    std::string host = addr.substr(0, colon);
    int port = std::atoi(addr.c_str() + colon + 1);
    sa->sin_family = AF_INET;
    sa->sin_port = htons(static_cast<uint16_t>(port));
    if (host.empty() || host == "*") {
      sa->sin_addr.s_addr = INADDR_ANY;
    } else if (::inet_pton(AF_INET, host.c_str(), &sa->sin_addr) != 1) {
      return -1;
    }
    return 0;
  }

  int connect_peer(uint32_t peer, uint64_t deadline_us) {
    const Endpoint &ep = eps[peer];
    while (!stop.load()) {
      int fd;
      int rc;
      if (ep.ipc) {
        fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
        sockaddr_un sa{};
        sa.sun_family = AF_UNIX;
        std::snprintf(sa.sun_path, sizeof(sa.sun_path), "%s",
                      ep.addr.c_str());
        rc = ::connect(fd, reinterpret_cast<sockaddr *>(&sa), sizeof(sa));
      } else {
        fd = ::socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in sa{};
        if (parse_tcp(ep.addr, &sa) != 0) {
          ::close(fd);
          return -1;
        }
        rc = ::connect(fd, reinterpret_cast<sockaddr *>(&sa), sizeof(sa));
      }
      if (rc == 0) {
        uint32_t hello[2] = {kHelloMagic, node_id};
        if (write_all(fd, reinterpret_cast<uint8_t *>(hello),
                      sizeof(hello)) < 0) {
          ::close(fd);
          return -1;
        }
        tune(fd);
        peer_fd[peer].store(fd, std::memory_order_release);
        peer_dead[peer].store(false, std::memory_order_relaxed);
        return 0;
      }
      ::close(fd);
      if (now_us() > deadline_us) return -1;
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
    return -1;
  }

  int accept_one(uint64_t deadline_us) {
    while (!stop.load()) {
      pollfd pf{listen_fd, POLLIN, 0};
      int pr = ::poll(&pf, 1, 50);
      if (pr > 0) {
        int fd = ::accept(listen_fd, nullptr, nullptr);
        if (fd < 0) continue;
        uint32_t hello[2] = {0, 0};
        size_t got = 0;
        while (got < sizeof(hello)) {
          ssize_t r = ::read(fd, reinterpret_cast<uint8_t *>(hello) + got,
                             sizeof(hello) - got);
          if (r <= 0) break;
          got += static_cast<size_t>(r);
        }
        if (got != sizeof(hello) || hello[0] != kHelloMagic ||
            hello[1] >= n_nodes) {
          ::close(fd);
          continue;
        }
        tune(fd);
        peer_fd[hello[1]].store(fd, std::memory_order_release);
        return 0;
      }
      if (now_us() > deadline_us) return -1;
    }
    return -1;
  }

  // Runtime re-accept (crash-recovery rejoin): a restarted peer redials
  // our listening socket mid-run; swap its link in and revive it.  The
  // hello read is bounded so a junk connection cannot stall the
  // receiver shard that owns the listen fd.
  void accept_rejoin() {
    int fd = ::accept(listen_fd, nullptr, nullptr);
    if (fd < 0) return;
    uint32_t hello[2] = {0, 0};
    size_t got = 0;
    uint64_t deadline = now_us() + 500'000;
    while (got < sizeof(hello) && now_us() < deadline) {
      pollfd pf{fd, POLLIN, 0};
      if (::poll(&pf, 1, 50) <= 0) continue;
      ssize_t r = ::read(fd, reinterpret_cast<uint8_t *>(hello) + got,
                         sizeof(hello) - got);
      if (r <= 0) break;
      got += static_cast<size_t>(r);
    }
    if (got != sizeof(hello) || hello[0] != kHelloMagic ||
        hello[1] >= n_nodes || hello[1] == node_id) {
      ::close(fd);
      return;
    }
    tune(fd);
    int old = peer_fd[hello[1]].exchange(fd, std::memory_order_acq_rel);
    if (old >= 0) {
      std::lock_guard<std::mutex> g(graveyard_mu);
      fd_graveyard.push_back(old);
    }
    peer_dead[hello[1]].store(false, std::memory_order_release);
    bump(DT_STAT_RECONNECTS);
  }

  static void tune(int fd) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    // harmless EOPNOTSUPP on unix sockets
  }

  // ---- sender --------------------------------------------------------

  void flush_dest(IoShard &sh, uint32_t dest) {
    Mbuf &mb = sh.mbufs[dest];
    if (mb.buf.empty()) return;
    int fd = peer_fd[dest].load(std::memory_order_acquire);
    if (fd >= 0 && !peer_dead[dest].load(std::memory_order_relaxed)) {
      if (write_all(fd, mb.buf.data(), mb.buf.size()) >= 0) {
        bump(DT_STAT_BATCHES_SENT);
        bump(DT_STAT_BYTES_SENT, mb.buf.size());
      } else if (peer_fd[dest].load(std::memory_order_acquire) == fd) {
        // failed write = dead peer; later sends to it drop visibly
        // (peer_dead readable via stats going flat) instead of silently.
        // If the slot changed under us the failure was on a replaced
        // link — the reconnected peer must not be re-flagged dead.
        peer_dead[dest].store(true, std::memory_order_relaxed);
      }
    }
    mb.buf.clear();
    mb.first_us = 0;
  }

  void sender_loop(IoShard &sh) {
    std::vector<OutFrame> delayed;
    while (!stop.load()) {
      OutFrame f;
      // wait at most the flush timeout so timed flushes happen
      long wait = static_cast<long>(
          flush_timeout_us ? flush_timeout_us : 100);
      if (!delayed.empty() || sh.flush_req.load() != sh.flush_done.load())
        wait = 100;  // stay responsive while frames are parked
      bool got = sh.q.pop(&f, wait);
      uint64_t now = now_us();
      // release matured delayed frames BEFORE accepting fresh pops:
      // a popped frame that is already mature (the sender woke late)
      // must not leapfrog an earlier same-destination frame still
      // parked here — per-link FIFO is an invariant the runtime leans
      // on (replica log streams replay order-sensitively).  Within one
      // pass maturity is monotonic per destination for un-jittered
      // frames (ready_us = enqueue time + a per-dest-constant delay),
      // so releasing parked frames first restores FIFO.
      for (size_t i = 0; i < delayed.size();) {
        if (delayed[i].ready_us <= now) {
          append(sh, std::move(delayed[i]), now);
          delayed.erase(delayed.begin() + static_cast<long>(i));
        } else {
          ++i;
        }
      }
      if (got) {
        accept(sh, std::move(f), now, delayed);
        // drain the whole queue per wake: one blocking pop then
        // non-blocking pops until empty (batching amortizes syscalls)
        OutFrame g;
        while (sh.q.pop(&g, 0)) accept(sh, std::move(g), now, delayed);
      }
      // flush full/timed-out buffers; when idle (or told to) flush all
      uint64_t freq = sh.flush_req.load(std::memory_order_acquire);
      bool force = freq != sh.flush_done.load(std::memory_order_relaxed);
      if (force) {
        // flush contract: everything enqueued before dt_flush must hit
        // the wire before the ticket is acked — drain the queue again in
        // case frames raced in after the drain above
        OutFrame g;
        while (sh.q.pop(&g, 0)) accept(sh, std::move(g), now, delayed);
      }
      for (uint32_t d = 0; d < n_nodes; ++d) {
        Mbuf &mb = sh.mbufs[d];
        if (mb.buf.empty()) continue;
        bool full = mb.buf.size() >= msg_size_max;
        bool timed = flush_timeout_us == 0 ||
                     now - mb.first_us >= flush_timeout_us;
        bool idle = !got && delayed.empty();
        if (full || timed || idle || force) flush_dest(sh, d);
      }
      if (force) sh.flush_done.store(freq, std::memory_order_release);
    }
    // drain on shutdown: parked delayed frames FIRST (they were
    // enqueued before anything still in the queue — appending the
    // queue first would invert per-link FIFO at the stream tail),
    // then the queued frames
    for (auto &df : delayed) append(sh, std::move(df), now_us());
    OutFrame f;
    while (sh.q.pop(&f, 0)) append(sh, std::move(f), now_us());
    for (uint32_t d = 0; d < n_nodes; ++d) flush_dest(sh, d);
  }

  void accept(IoShard &sh, OutFrame f, uint64_t now,
              std::vector<OutFrame> &delayed) {
    if (f.ready_us > now) {
      delayed.push_back(std::move(f));
    } else {
      append(sh, std::move(f), now);
    }
  }

  void append(IoShard &sh, OutFrame f, uint64_t now) {
    Mbuf &mb = sh.mbufs[f.dest];
    if (mb.buf.empty()) mb.first_us = now;
    mb.buf.insert(mb.buf.end(), f.bytes.begin(), f.bytes.end());
    bump(DT_STAT_MSG_SENT);
    if (mb.buf.size() >= msg_size_max) flush_dest(sh, f.dest);
  }

  // ---- receiver ------------------------------------------------------

  void receiver_loop(uint32_t shard) {
    std::vector<std::vector<uint8_t>> streams(n_nodes);
    // fd the bytes in streams[p] came from: a different fd means a
    // rejoin swapped the link, so the old incarnation's partial frame
    // is discarded before the new link's bytes append.  Keyed on the
    // fd itself (race-free: the stale-fd check below guarantees bytes
    // only append from the CURRENT fd, and parked graveyard fds are
    // never recycled while we run), not on a separate generation
    // counter whose update could interleave with the fd swap.
    std::vector<int> seen_fd(n_nodes, -1);
    std::vector<pollfd> pfds;
    std::vector<uint32_t> ids;  // ids[i] valid for peer entries only
    // shard 0 also watches the listening socket so a crashed-and-
    // restarted peer can redial mid-run (accept_rejoin swaps the link)
    bool watch_listen = shard == 0 && listen_fd >= 0;
    while (!stop.load()) {
      pfds.clear();
      ids.clear();
      for (uint32_t p = 0; p < n_nodes; ++p) {
        int fd = peer_fd[p].load(std::memory_order_acquire);
        if (p % n_recv == shard && fd >= 0 &&
            !peer_dead[p].load(std::memory_order_relaxed)) {
          pfds.push_back({fd, POLLIN, 0});
          ids.push_back(p);
        }
      }
      size_t n_peers = pfds.size();
      if (watch_listen) pfds.push_back({listen_fd, POLLIN, 0});
      if (pfds.empty()) {
        std::this_thread::sleep_for(std::chrono::milliseconds(5));
        continue;
      }
      int pr = ::poll(pfds.data(), pfds.size(), 20);
      if (pr <= 0) continue;
      if (watch_listen && (pfds[n_peers].revents & POLLIN)) accept_rejoin();
      for (size_t i = 0; i < n_peers; ++i) {
        if (!(pfds[i].revents & (POLLIN | POLLHUP | POLLERR))) continue;
        uint8_t chunk[65536];
        ssize_t r = ::read(pfds[i].fd, chunk, sizeof(chunk));
        if (r <= 0) {
          if ((r == 0 || (errno != EINTR && errno != EAGAIN)) &&
              peer_fd[ids[i]].load(std::memory_order_acquire) ==
                  pfds[i].fd) {
            // flag only; the fd stays open until the destructor so the
            // sender never races a close/recycle.  Skip if the slot was
            // already swapped by a rejoin — the old link's EOF must not
            // kill the new one; any half-frame from the old incarnation
            // is dropped with its stream buffer.
            peer_dead[ids[i]].store(true, std::memory_order_relaxed);
            streams[ids[i]].clear();
          }
          continue;
        }
        if (peer_fd[ids[i]].load(std::memory_order_acquire) != pfds[i].fd)
          continue;  // stale fd drained after a rejoin swap: discard
        bump(DT_STAT_BYTES_RCVD, static_cast<uint64_t>(r));
        auto &st = streams[ids[i]];
        if (pfds[i].fd != seen_fd[ids[i]]) {
          st.clear();  // drop the old incarnation's partial frame
          seen_fd[ids[i]] = pfds[i].fd;
        }
        st.insert(st.end(), chunk, chunk + r);
        parse_stream(st);
      }
    }
  }

  void parse_stream(std::vector<uint8_t> &st) {
    size_t off = 0;
    while (st.size() - off >= sizeof(FrameHdr)) {
      FrameHdr h;
      std::memcpy(&h, st.data() + off, sizeof(h));
      if (st.size() - off < sizeof(h) + h.paylen) break;
      const uint8_t *pay = st.data() + off + sizeof(h);
      deliver(h, pay);
      off += sizeof(h) + h.paylen;
    }
    if (off) st.erase(st.begin(), st.begin() + static_cast<long>(off));
  }

  void deliver(const FrameHdr &h, const uint8_t *pay) {
    // RX side of a partition blackhole: frames from the peer vanish on
    // arrival (every rtype — a partition takes the whole link).
    // Loopback delivery never reaches here with src == node_id faulted
    // (self links cannot be partitioned), but guard anyway.
    if (h.src < n_nodes && h.src != node_id &&
        (part_mode[h.src].load(std::memory_order_relaxed) & DT_PART_RX)) {
      bump(DT_STAT_MSG_BLACKHOLED);
      return;
    }
    bump(DT_STAT_MSG_RCVD);
    if (h.rtype == DT_PING) {
      // answer at transport level: echo payload back as PONG
      enqueue(h.src, DT_PONG, pay, h.paylen);
      return;
    }
    if (h.rtype == DT_PONG && h.paylen == sizeof(uint64_t)) {
      uint64_t t0;
      std::memcpy(&t0, pay, sizeof(t0));
      pong_q.push(t0);
      return;
    }
    RecvMsg m;
    m.src = h.src;
    m.rtype = h.rtype;
    m.payload.assign(pay, pay + h.paylen);
    recv_q.push(std::move(m));
  }

  int enqueue(uint32_t dest, uint16_t rtype, const uint8_t *payload,
              uint32_t len) {
    dt_iov one{payload, len};
    return enqueue_v(dest, rtype, &one, 1);
  }

  // Scatter-gather enqueue: the frame is assembled ONCE (header + every
  // segment) into the OutFrame — the single unavoidable copy of the
  // async send path.  Callers pass column arrays / codec headers as
  // segments and never build a contiguous payload themselves.
  int enqueue_v(uint32_t dest, uint16_t rtype, const dt_iov *iov,
                uint32_t n_iov) {
    if (dest >= n_nodes || stop.load()) return -1;
    size_t len = 0;
    for (uint32_t i = 0; i < n_iov; ++i) len += iov[i].len;
    if (len > UINT32_MAX) return -1;
    FrameHdr h{static_cast<uint32_t>(len), rtype, 0, node_id};
    if (dest == node_id) {
      // loopback: skip the wire entirely (and the fault model with it);
      // gather into a scratch buffer only on this local-delivery path
      std::vector<uint8_t> pay;
      pay.reserve(len);
      for (uint32_t i = 0; i < n_iov; ++i)
        if (iov[i].len)
          pay.insert(pay.end(), static_cast<const uint8_t *>(iov[i].base),
                     static_cast<const uint8_t *>(iov[i].base) + iov[i].len);
      deliver(h, pay.data());
      bump(DT_STAT_MSG_SENT);
      return 0;
    }
    // TX side of a partition blackhole: the frame is discarded before
    // it ever reaches a sender shard (the peer sees pure silence)
    if (part_mode[dest].load(std::memory_order_relaxed) & DT_PART_TX) {
      bump(DT_STAT_MSG_BLACKHOLED);
      return 0;
    }
    uint64_t jitter = 0;
    bool duplicate = false;
    uint32_t mask = fault_mask.load(std::memory_order_relaxed);
    if (mask && rtype < 32 && (mask & (1u << rtype))) {
      uint64_t r = splitmix64(
          fault_seed.load(std::memory_order_relaxed) +
          fault_ctr.fetch_add(1, std::memory_order_relaxed));
      uint32_t drop = fault_drop_ppm.load(std::memory_order_relaxed);
      if (drop && static_cast<uint32_t>(r % 1000000u) < drop) {
        bump(DT_STAT_MSG_DROPPED);
        return 0;  // silently lost, exactly like a lossy network
      }
      r = splitmix64(r);
      uint32_t dup = fault_dup_ppm.load(std::memory_order_relaxed);
      if (dup && static_cast<uint32_t>(r % 1000000u) < dup)
        duplicate = true;
      r = splitmix64(r);
      uint64_t jmax = fault_jitter_us.load(std::memory_order_relaxed);
      if (jmax) jitter = r % jmax;
    }
    OutFrame f;
    f.dest = dest;
    uint64_t d = delay_us.load(std::memory_order_relaxed) +
                 peer_delay_us[dest].load(std::memory_order_relaxed) +
                 peer_stall_us[dest].load(std::memory_order_relaxed) +
                 jitter;
    f.ready_us = d ? now_us() + d : 0;
    f.bytes.resize(sizeof(h) + len);
    std::memcpy(f.bytes.data(), &h, sizeof(h));
    uint8_t *p = f.bytes.data() + sizeof(h);
    for (uint32_t i = 0; i < n_iov; ++i) {
      if (!iov[i].len) continue;
      std::memcpy(p, iov[i].base, iov[i].len);
      p += iov[i].len;
    }
    if (duplicate) {
      OutFrame g = f;  // byte-identical twin rides the same shard queue
      bump(DT_STAT_MSG_DUP);
      shards[dest % n_send]->q.push(std::move(g));
    }
    shards[dest % n_send]->q.push(std::move(f));
    return 0;
  }
};

// ---- C API -----------------------------------------------------------

extern "C" {

dt_transport *dt_create(uint32_t node_id, const char *endpoints,
                        uint32_t n_nodes, uint32_t msg_size_max,
                        uint32_t flush_timeout_us) {
  if (!endpoints || node_id >= n_nodes || n_nodes == 0) return nullptr;
  auto *t = new dt_transport();
  t->node_id = node_id;
  t->n_nodes = n_nodes;
  t->msg_size_max = msg_size_max ? msg_size_max : 4096;
  t->flush_timeout_us = flush_timeout_us;
  t->eps.resize(n_nodes);
  t->peer_fd = std::vector<std::atomic<int>>(n_nodes);
  for (auto &slot : t->peer_fd) slot.store(-1, std::memory_order_relaxed);
  t->peer_dead = std::vector<std::atomic<bool>>(n_nodes);
  t->peer_delay_us = std::vector<std::atomic<uint64_t>>(n_nodes);
  t->peer_stall_us = std::vector<std::atomic<uint64_t>>(n_nodes);
  t->part_mode = std::vector<std::atomic<uint32_t>>(n_nodes);

  std::string text(endpoints);
  size_t pos = 0;
  uint32_t seen = 0;
  while (pos < text.size()) {
    size_t eol = text.find('\n', pos);
    if (eol == std::string::npos) eol = text.size();
    std::string line = text.substr(pos, eol - pos);
    pos = eol + 1;
    if (line.empty()) continue;
    char proto[16];
    char addr[256];
    unsigned id;
    if (std::sscanf(line.c_str(), "%u %15s %255s", &id, proto, addr) != 3 ||
        id >= n_nodes) {
      delete t;
      return nullptr;
    }
    t->eps[id].ipc = std::strcmp(proto, "ipc") == 0;
    t->eps[id].addr = addr;
    ++seen;
  }
  if (seen < n_nodes) {
    delete t;
    return nullptr;
  }
  t->shards.emplace_back(new dt_transport::IoShard());
  t->shards.back()->mbufs.resize(n_nodes);
  return t;
}

int dt_start(dt_transport *t, int timeout_ms) {
  if (!t) return -1;
  uint64_t deadline = now_us() + static_cast<uint64_t>(timeout_ms) * 1000;
  if (t->n_nodes > 1) {
    if (t->make_listen() != 0) return -1;
    if (t->rejoin) {
      // crash-recovery restart: every live peer already holds a (dead)
      // link to the old incarnation and will not redial — WE dial all
      // of them; their receiver shards accept and swap the link in
      for (uint32_t p = 0; p < t->n_nodes; ++p)
        if (p != t->node_id && t->connect_peer(p, deadline) != 0)
          return -1;
    } else {
      // accept from higher ids in a helper thread while we dial lower ids
      uint32_t n_accept = t->n_nodes - 1 - t->node_id;
      std::thread acceptor([t, n_accept, deadline] {
        for (uint32_t k = 0; k < n_accept; ++k)
          if (t->accept_one(deadline) != 0) return;
      });
      int rc = 0;
      for (uint32_t p = 0; p < t->node_id; ++p)
        if (t->connect_peer(p, deadline) != 0) rc = -1;
      acceptor.join();
      if (rc != 0) return -1;
    }
    for (uint32_t p = 0; p < t->n_nodes; ++p)
      if (p != t->node_id &&
          t->peer_fd[p].load(std::memory_order_relaxed) < 0)
        return -1;
  }
  for (uint32_t k = 0; k < t->n_send; ++k) {
    dt_transport::IoShard *sh = t->shards[k].get();
    t->senders.emplace_back([t, sh] { t->sender_loop(*sh); });
  }
  for (uint32_t k = 0; k < t->n_recv; ++k)
    t->receivers.emplace_back([t, k] { t->receiver_loop(k); });
  return 0;
}

int dt_set_io_threads(dt_transport *t, uint32_t n_send, uint32_t n_recv) {
  if (!t || !t->senders.empty()) return -1;  /* must precede dt_start */
  t->n_send = n_send ? n_send : 1;
  t->n_recv = n_recv ? n_recv : 1;
  /* rebuild the shard set at the new width, rerouting any frames queued
   * before the resize (sends are legal from construction on) */
  std::vector<std::unique_ptr<dt_transport::IoShard>> old;
  old.swap(t->shards);
  for (uint32_t k = 0; k < t->n_send; ++k) {
    t->shards.emplace_back(new dt_transport::IoShard());
    t->shards.back()->mbufs.resize(t->n_nodes);
  }
  for (auto &sh : old) {
    OutFrame f;
    while (sh->q.pop(&f, 0))
      t->shards[f.dest % t->n_send]->q.push(std::move(f));
  }
  return 0;
}

int dt_send(dt_transport *t, uint32_t dest, uint16_t rtype,
            const uint8_t *payload, uint32_t len) {
  if (!t) return -1;
  return t->enqueue(dest, rtype, payload, len);
}

int dt_sendv(dt_transport *t, uint32_t dest, uint16_t rtype,
             const dt_iov *iov, uint32_t n_iov) {
  if (!t || (n_iov && !iov)) return -1;
  return t->enqueue_v(dest, rtype, iov, n_iov);
}

long dt_recv(dt_transport *t, uint8_t *buf, uint32_t cap, uint32_t *src,
             uint16_t *rtype, long timeout_us, uint32_t *len_needed) {
  if (!t) return -1;
  RecvMsg m;
  uint32_t need = 0;
  // single-lock conditional pop: a too-large head stays at the front
  // (FIFO preserved) and its size is reported for buffer growth
  int rc = t->recv_q.pop_if(
      &m,
      [&](const RecvMsg &head) {
        if (head.payload.size() > cap) {
          need = static_cast<uint32_t>(head.payload.size());
          return false;
        }
        return true;
      },
      timeout_us);
  if (rc == -1) return -1;
  if (rc == 0) {
    if (len_needed) *len_needed = need;
    return -2;
  }
  if (src) *src = m.src;
  if (rtype) *rtype = m.rtype;
  if (!m.payload.empty()) std::memcpy(buf, m.payload.data(), m.payload.size());
  return static_cast<long>(m.payload.size());
}

void dt_flush(dt_transport *t) {
  if (!t || t->senders.empty()) return;
  uint64_t deadline = now_us() + 1'000'000;  // 1s bound
  std::vector<uint64_t> tickets(t->shards.size());
  for (size_t k = 0; k < t->shards.size(); ++k)
    tickets[k] =
        t->shards[k]->flush_req.fetch_add(1, std::memory_order_acq_rel) + 1;
  for (size_t k = 0; k < t->shards.size(); ++k) {
    while (t->shards[k]->flush_done.load(std::memory_order_acquire) <
               tickets[k] &&
           !t->stop.load() && now_us() < deadline) {
      std::this_thread::sleep_for(std::chrono::microseconds(50));
    }
  }
}

void dt_set_delay_us(dt_transport *t, uint64_t delay_us) {
  if (t) t->delay_us.store(delay_us, std::memory_order_relaxed);
}

int dt_set_peer_delay_us(dt_transport *t, uint32_t peer,
                         uint64_t delay_us) {
  if (!t || peer >= t->n_nodes) return -1;
  t->peer_delay_us[peer].store(delay_us, std::memory_order_relaxed);
  return 0;
}

int dt_set_partition(dt_transport *t, uint32_t peer, uint32_t mode) {
  if (!t || peer >= t->n_nodes) return -1;
  t->part_mode[peer].store(mode, std::memory_order_relaxed);
  return 0;
}

int dt_set_peer_stall_us(dt_transport *t, uint32_t peer,
                         uint64_t stall_us) {
  if (!t || peer >= t->n_nodes) return -1;
  t->peer_stall_us[peer].store(stall_us, std::memory_order_relaxed);
  return 0;
}

int dt_set_fault(dt_transport *t, uint32_t drop_ppm, uint32_t dup_ppm,
                 uint64_t jitter_us, uint64_t seed, uint32_t rtype_mask) {
  if (!t) return -1;
  t->fault_drop_ppm.store(drop_ppm, std::memory_order_relaxed);
  t->fault_dup_ppm.store(dup_ppm, std::memory_order_relaxed);
  t->fault_jitter_us.store(jitter_us, std::memory_order_relaxed);
  t->fault_seed.store(seed, std::memory_order_relaxed);
  t->fault_mask.store(rtype_mask, std::memory_order_relaxed);
  return 0;
}

int dt_set_rejoin(dt_transport *t, int on) {
  if (!t || !t->senders.empty()) return -1; /* must precede dt_start */
  t->rejoin = on != 0;
  return 0;
}

int dt_peer_alive(const dt_transport *t, uint32_t peer) {
  if (!t || peer >= t->n_nodes) return 0;
  if (peer == t->node_id) return 1;
  return (t->peer_fd[peer].load(std::memory_order_relaxed) >= 0 &&
          !t->peer_dead[peer].load(std::memory_order_relaxed))
             ? 1
             : 0;
}

void dt_stats(const dt_transport *t, uint64_t *out) {
  if (!t || !out) return;
  for (int i = 0; i < DT_STAT_COUNT; ++i)
    out[i] = t->stats[i].load(std::memory_order_relaxed);
  uint64_t sq = 0;
  for (const auto &sh : t->shards) sq += sh->q.size();
  out[DT_STAT_SEND_QUEUE_DEPTH] = sq;
  out[DT_STAT_RECV_QUEUE_DEPTH] = t->recv_q.size();
}

long dt_ping(dt_transport *t, uint32_t peer, uint32_t rounds,
             uint32_t payload_len) {
  if (!t || peer >= t->n_nodes || rounds == 0) return -1;
  (void)payload_len;  // round-trip carries the 8-byte timestamp
  uint64_t total_ns = 0;
  uint64_t stale;
  while (t->pong_q.pop(&stale, 0)) {  // drop pongs from timed-out rounds
  }
  for (uint32_t i = 0; i < rounds; ++i) {
    uint64_t t0 = now_us();
    if (t->enqueue(peer, DT_PING, reinterpret_cast<uint8_t *>(&t0),
                   sizeof(t0)) != 0)
      return -1;
    uint64_t echoed = 0;
    do {  // skip any pong that is not the echo of this round's t0
      if (!t->pong_q.pop(&echoed, 2'000'000)) return -1;  // 2s timeout
    } while (echoed != t0);
    total_ns += (now_us() - t0) * 1000;
  }
  return static_cast<long>(total_ns / rounds);
}

void dt_destroy(dt_transport *t) { delete t; }

// ---- columnar query-batch codec ---------------------------------------

long dt_qrybatch_encode(uint32_t n, uint32_t width, uint32_t n_scalars,
                        const int64_t *startts, const int32_t *keys,
                        const int8_t *types, const int32_t *scalars,
                        uint8_t *out, size_t cap) {
  size_t need = 12 + size_t(n) * 8 + size_t(n) * width * 4 +
                size_t(n) * width + size_t(n) * n_scalars * 4;
  if (!out) return static_cast<long>(need);
  if (cap < need) return -1;
  uint32_t hdr[3] = {n, width, n_scalars};
  uint8_t *p = out;
  std::memcpy(p, hdr, 12);
  p += 12;
  std::memcpy(p, startts, size_t(n) * 8);
  p += size_t(n) * 8;
  std::memcpy(p, keys, size_t(n) * width * 4);
  p += size_t(n) * width * 4;
  std::memcpy(p, types, size_t(n) * width);
  p += size_t(n) * width;
  if (n_scalars) std::memcpy(p, scalars, size_t(n) * n_scalars * 4);
  return static_cast<long>(need);
}

long dt_qrybatch_decode(const uint8_t *buf, size_t len, uint32_t *n,
                        uint32_t *width, uint32_t *n_scalars,
                        int64_t *startts, int32_t *keys, int8_t *types,
                        int32_t *scalars, size_t arrays_cap) {
  if (!buf || len < 12) return -1;
  uint32_t hdr[3];
  std::memcpy(hdr, buf, 12);
  uint32_t N = hdr[0], W = hdr[1], S = hdr[2];
  size_t need = 12 + size_t(N) * 8 + size_t(N) * W * 4 + size_t(N) * W +
                size_t(N) * S * 4;
  if (len < need) return -1;
  if (n) *n = N;
  if (width) *width = W;
  if (n_scalars) *n_scalars = S;
  if (!startts) return static_cast<long>(need);  // size-probe call
  if (arrays_cap < size_t(N) * W) return -2;
  const uint8_t *p = buf + 12;
  std::memcpy(startts, p, size_t(N) * 8);
  p += size_t(N) * 8;
  std::memcpy(keys, p, size_t(N) * W * 4);
  p += size_t(N) * W * 4;
  std::memcpy(types, p, size_t(N) * W);
  p += size_t(N) * W;
  if (S && scalars) std::memcpy(scalars, p, size_t(N) * S * 4);
  return static_cast<long>(need);
}

}  // extern "C"
