// Host-CPU OCC baseline (stand-in for the unbuildable reference binary).
//
// The reference's nanomsg dependency is absent from this image, so its
// rundb executable cannot be built; this program reproduces the part the
// headline ratio needs — the single-node OCC validate/commit loop on a
// YCSB-style workload — faithfully to the reference's design:
//
//  * central validation with a global critical section
//    (concurrency_control/occ.cpp:116-239: sem_wait(_semaphore), snapshot
//    of the active set, finish-ts draw, history scan, set-intersection
//    test_valid, occ.cpp:241-263)
//  * per-thread worker loop: read phase against the table, validate,
//    write phase, retry-on-abort (system/worker_thread.cpp)
//  * pre-generated zipfian queries (Gray's method with precomputed zeta,
//    benchmarks/ycsb_query.cpp:280-301 zipf()), generated OUTSIDE the
//    measured window like the reference client's query pregeneration
//    (client/client_query.cpp)
//
// Usage: host_occ [rows] [threads] [reqs] [zipf_theta] [write_perc] [secs]
// Prints one line: host_occ tput=... commits=... aborts=... threads=...
//
// Build: make host_occ (native/Makefile).

#include <atomic>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct SetEnt {            // reference set_ent (occ.h:23-30)
  uint64_t tn = 0;         // commit (finish) timestamp
  std::vector<uint32_t> keys;
};

struct Query {
  uint32_t keys[64];
  uint64_t write_mask;     // bit i: request i is a write
  int n;
};

// --- Gray zipfian, identical construction to ycsb_query.cpp:280-301 ---
struct Zipf {
  uint64_t n;
  double theta, alpha, zetan, eta, zeta2;
  Zipf(uint64_t n_, double t) : n(n_), theta(t) {
    zetan = zeta(n);
    zeta2 = zeta(2);
    alpha = 1.0 / (1.0 - theta);
    eta = (1.0 - std::pow(2.0 / double(n), 1.0 - theta)) /
          (1.0 - zeta2 / zetan);
  }
  double zeta(uint64_t m) const {
    double s = 0;
    for (uint64_t i = 1; i <= m; i++) s += std::pow(1.0 / double(i), theta);
    return s;
  }
  uint64_t sample(double u) const {
    if (theta <= 0.0) return uint64_t(u * double(n)) % n;
    double uz = u * zetan;
    if (uz < 1) return 0;
    if (uz < 1 + std::pow(0.5, theta)) return 1;
    return uint64_t(double(n) * std::pow(eta * u - eta + 1.0, alpha)) % n;
  }
};

struct Rng {               // xorshift64*
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed * 2685821657736338717ULL + 1) {}
  uint64_t next() {
    s ^= s >> 12; s ^= s << 25; s ^= s >> 27;
    return s * 2685821657736338717ULL;
  }
  double uniform() { return double(next() >> 11) / 9007199254740992.0; }
};

// --- central validation state (occ.cpp: active/history under _semaphore) ---
std::mutex g_latch;
std::deque<SetEnt> g_active;          // currently-validating write sets
std::deque<SetEnt> g_history;         // committed write sets, newest first
std::atomic<uint64_t> g_ts{1};
constexpr size_t kHistoryCap = 4096;  // bounded like HIS_RECYCLE_LEN

bool test_valid(const SetEnt& a, const std::vector<uint32_t>& b) {
  // reference test_valid (occ.cpp:241-263): set intersection over rows
  for (uint32_t x : a.keys)
    for (uint32_t y : b)
      if (x == y) return true;        // conflict
  return false;
}

struct Shared {
  std::vector<uint32_t> table;
  std::atomic<uint64_t> commits{0}, aborts{0};
  std::atomic<bool> stop{false};
};

void worker(Shared* sh, const std::vector<Query>* queries, int tid) {
  size_t qi = size_t(tid) * 7919 % queries->size();
  uint64_t commits = 0, aborts = 0;
  std::vector<uint32_t> rset, wset;
  while (!sh->stop.load(std::memory_order_relaxed)) {
    const Query& q = (*queries)[qi];
    qi = (qi + 1) % queries->size();
    bool done = false;
    while (!done && !sh->stop.load(std::memory_order_relaxed)) {
      // read phase (ycsb_txn.cpp:177-209): reads + deferred writes
      uint64_t start_tn = g_ts.load(std::memory_order_acquire);
      rset.clear(); wset.clear();
      uint32_t checksum = 0;
      for (int i = 0; i < q.n; i++) {
        if (q.write_mask >> i & 1) wset.push_back(q.keys[i]);
        else {
          rset.push_back(q.keys[i]);
          checksum += sh->table[q.keys[i]];
        }
      }
      (void)checksum;
      // central validate (occ.cpp:116-239)
      uint64_t finish_tn;
      std::vector<SetEnt> active_snapshot;
      std::vector<SetEnt> hist_snapshot;
      {
        std::lock_guard<std::mutex> lk(g_latch);
        finish_tn = g_ts.fetch_add(1) + 1;
        active_snapshot.assign(g_active.begin(), g_active.end());
        if (!wset.empty()) {
          SetEnt mine; mine.tn = finish_tn; mine.keys = wset;
          g_active.push_back(std::move(mine));
        }
        for (const SetEnt& h : g_history) {
          if (h.tn <= start_tn) break;        // newest-first list
          if (h.tn <= finish_tn) hist_snapshot.push_back(h);
        }
      }
      bool valid = true;
      for (const SetEnt& h : hist_snapshot)
        if (test_valid(h, rset)) { valid = false; break; }
      if (valid)
        for (const SetEnt& a : active_snapshot) {
          if (a.tn == finish_tn) continue;
          if (test_valid(a, rset) || test_valid(a, wset)) {
            valid = false; break;
          }
        }
      {
        std::lock_guard<std::mutex> lk(g_latch);
        // remove self from active (occ.cpp finish/abort paths)
        for (auto it = g_active.begin(); it != g_active.end(); ++it)
          if (it->tn == finish_tn) { g_active.erase(it); break; }
        if (valid && !wset.empty()) {
          SetEnt mine; mine.tn = finish_tn; mine.keys = wset;
          // keep the list tn-ordered (newest first): validators that
          // reach this critical section out of finish_tn order would
          // otherwise let the history scan's early break skip a
          // conflicting writer; inversions are near the front, so the
          // insertion walk is short
          auto it = g_history.begin();
          while (it != g_history.end() && it->tn > mine.tn) ++it;
          g_history.insert(it, std::move(mine));
          if (g_history.size() > kHistoryCap) g_history.pop_back();
        }
      }
      if (valid) {
        // write phase: apply after validation (occ write rule)
        for (uint32_t k : wset)
          sh->table[k] = uint32_t(k * 2654435761u ^ uint32_t(finish_tn));
        commits++; done = true;
      } else {
        aborts++;               // retry same txn (abort_queue restart)
      }
    }
  }
  sh->commits += commits;
  sh->aborts += aborts;
}

}  // namespace

int main(int argc, char** argv) {
  uint64_t rows = argc > 1 ? strtoull(argv[1], nullptr, 10) : (1ull << 23);
  int threads = argc > 2 ? atoi(argv[2]) : 4;
  int reqs = argc > 3 ? atoi(argv[3]) : 10;
  double theta = argc > 4 ? atof(argv[4]) : 0.9;
  double wperc = argc > 5 ? atof(argv[5]) : 0.5;
  double secs = argc > 6 ? atof(argv[6]) : 5.0;
  if (reqs > 64) { fprintf(stderr, "reqs must be <= 64\n"); return 1; }

  Shared sh;
  sh.table.assign(rows, 1u);
  Zipf zipf(rows, theta);

  // pre-generate queries outside the measured window (client pregen)
  std::vector<Query> queries(1 << 16);
  Rng rng(12345);
  for (Query& q : queries) {
    q.n = reqs; q.write_mask = 0;
    for (int i = 0; i < reqs; i++) {
      q.keys[i] = uint32_t(zipf.sample(rng.uniform()));
      if (rng.uniform() < wperc) q.write_mask |= 1ull << i;
    }
  }

  std::vector<std::thread> ts;
  for (int t = 0; t < threads; t++)
    ts.emplace_back(worker, &sh, &queries, t);
  auto t0 = std::chrono::steady_clock::now();
  std::this_thread::sleep_for(std::chrono::duration<double>(secs));
  sh.stop = true;
  for (auto& th : ts) th.join();
  double el = std::chrono::duration<double>(
      std::chrono::steady_clock::now() - t0).count();

  printf("host_occ tput=%.0f commits=%llu aborts=%llu threads=%d rows=%llu "
         "zipf=%.2f secs=%.2f\n",
         double(sh.commits.load()) / el,
         (unsigned long long)sh.commits.load(),
         (unsigned long long)sh.aborts.load(), threads,
         (unsigned long long)rows, theta, el);
  return 0;
}
