// Bounded-stat MPMC message queue (reference work/msg queues,
// `system/work_queue.cpp`, `system/msg_queue.cpp` — boost::lockfree there;
// mutex+condvar here: the hot path is batched, so queue ops are amortized
// over whole message batches and contention is negligible).
#pragma once

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>
#include <utility>

namespace deneva {

template <typename T>
class MpmcQueue {
 public:
  // cap = 0: unbounded.  A bounded queue blocks producers when full —
  // the receiver thread blocking here is what turns into TCP backpressure
  // on the wire (the reference gets the same effect from its bounded
  // boost::lockfree ring buffers).
  explicit MpmcQueue(size_t cap = 0) : cap_(cap) {}

  void push(T v) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      if (cap_) {
        cv_space_.wait(lk, [&] { return q_.size() < cap_ || stopped_; });
        if (stopped_) return;  // shutting down: drop, consumers are gone
      }
      q_.push_back(std::move(v));
    }
    cv_.notify_one();
  }

  // timeout_us < 0: block until item or shutdown; 0: non-blocking.
  // Returns false on timeout/shutdown-empty.
  bool pop(T *out, long timeout_us) {
    std::unique_lock<std::mutex> lk(mu_);
    if (q_.empty()) {
      if (timeout_us == 0) return false;
      auto ready = [&] { return !q_.empty() || stopped_; };
      if (timeout_us < 0) {
        cv_.wait(lk, ready);
      } else {
        cv_.wait_for(lk, std::chrono::microseconds(timeout_us), ready);
      }
      if (q_.empty()) return false;
    }
    *out = std::move(q_.front());
    q_.pop_front();
    if (cap_) cv_space_.notify_one();
    return true;
  }

  // Pop the head only if `accept(head)` returns true, all under one lock
  // (no pointer escapes, FIFO preserved).  Returns 1 popped, 0 head
  // rejected (stays at the front), -1 timeout/empty.
  template <typename F>
  int pop_if(T *out, F &&accept, long timeout_us) {
    std::unique_lock<std::mutex> lk(mu_);
    if (q_.empty()) {
      if (timeout_us == 0) return -1;
      auto ready = [&] { return !q_.empty() || stopped_; };
      if (timeout_us < 0) {
        cv_.wait(lk, ready);
      } else {
        cv_.wait_for(lk, std::chrono::microseconds(timeout_us), ready);
      }
      if (q_.empty()) return -1;
    }
    if (!accept(q_.front())) return 0;
    *out = std::move(q_.front());
    q_.pop_front();
    if (cap_) cv_space_.notify_one();
    return 1;
  }

  void stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stopped_ = true;
    }
    cv_.notify_all();
    cv_space_.notify_all();
  }

  size_t size() const {
    std::lock_guard<std::mutex> lk(mu_);
    return q_.size();
  }

 private:
  mutable std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable cv_space_;
  std::deque<T> q_;
  size_t cap_ = 0;
  bool stopped_ = false;
};

}  // namespace deneva
