"""Elastic membership tests (runtime/membership.py).

Three layers:

* pure units — slot-map degeneracy (boot map == exact modulo striping),
  rebalance-plan properties (deterministic, balanced, covering), wire
  codec roundtrips, dense slot->keys enumeration;
* workload/routing units — elastic YCSB full-residency load, slot-map
  ownership masks vs the striped baseline, control-plane exclusion from
  `state_digest`;
* runtime integration — the rebalance-off bit-identity bar (an elastic
  run with no rebalance triggered must produce byte-identical command
  logs, replica streams, state digests and acked tags vs elastic=off;
  same harness as ``test_host_overlap_bit_identical``) and the live
  grow/drain/kill-with-reassignment chaos scenarios (slow marks).
"""

import os
import threading
import time as _time
import uuid

import numpy as np
import pytest

from deneva_tpu.config import CCAlg, Config, WorkloadKind
from deneva_tpu.runtime import membership as M


def elastic_cfg(**kw):
    base = dict(
        workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
        epoch_batch=128, conflict_buckets=512, synth_table_size=4096,
        max_txn_in_flight=1024, req_per_query=4, max_accesses=4,
        zipf_theta=0.6, warmup_secs=0.5, done_secs=1.5, elastic=True)
    base.update(kw)
    return Config(**base)


# ---- slot map ----------------------------------------------------------

@pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 8])
def test_boot_map_degenerates_to_exact_modulo(n):
    """The aliasing contract: owners[key % S] == key % node_cnt for every
    key — S is rounded to a multiple of the boot active count, so the
    membership subsystem is routing-identical to GET_NODE_ID striping
    until a rebalance moves a slot."""
    cfg = elastic_cfg(node_cnt=n, part_cnt=n)
    m = M.initial_map(cfg)
    assert m.version == 0
    assert m.n_slots % n == 0 and m.n_slots >= 256
    keys = np.arange(100_000)
    np.testing.assert_array_equal(m.owner_of(keys), keys % n)


def test_boot_map_spares_are_slotless():
    cfg = elastic_cfg(node_cnt=3, part_cnt=3, elastic_spare_cnt=1)
    m = M.initial_map(cfg)
    assert m.active_nodes() == [0, 1]
    assert len(m.slots_of(2)) == 0
    keys = np.arange(10_000)
    np.testing.assert_array_equal(m.owner_of(keys), keys % 2)


def test_plan_grow_is_deterministic_balanced_and_covering():
    cfg = elastic_cfg(node_cnt=3, part_cnt=3, elastic_spare_cnt=1)
    m = M.initial_map(cfg)
    g1, g2 = M.plan_grow(m, 2), M.plan_grow(m, 2)
    np.testing.assert_array_equal(g1.owners, g2.owners)   # deterministic
    assert g1.version == 1
    cnt = g1.counts()
    assert set(cnt) == {0, 1, 2}
    assert max(cnt.values()) - min(cnt.values()) <= 1     # balanced
    assert sum(cnt.values()) == m.n_slots                 # covering
    # only slots that MOVED changed owner; every move targets node 2
    for (d, r), slots in M.moves(m, g1).items():
        assert r == 2 and d in (0, 1) and len(slots) > 0


def test_plan_drain_and_reassign_empty_the_subject():
    cfg = elastic_cfg(node_cnt=3, part_cnt=3)
    m = M.initial_map(cfg)
    d = M.plan_drain(m, 1)
    assert d.version == 1
    assert len(d.slots_of(1)) == 0
    assert sum(d.counts().values()) == m.n_slots
    # reassign is the same movement (recipients rebuild by replay)
    np.testing.assert_array_equal(M.plan_reassign(m, 1).owners, d.owners)
    with pytest.raises(ValueError):
        M.plan_drain(M.plan_drain(M.initial_map(
            elastic_cfg(node_cnt=2, part_cnt=2)), 1), 0)  # last owner


def test_map_msg_roundtrip():
    cfg = elastic_cfg(node_cnt=3, part_cnt=3)
    m = M.plan_grow(M.initial_map(cfg), 2)
    buf = M.encode_map_msg(m, cutover_epoch=64, reason=M.REASON_GROW,
                           subject=2)
    m2, cut, reason, subject = M.decode_map_msg(buf)
    assert (m2.owners == m.owners).all() and m2.version == m.version
    assert (cut, reason, subject) == (64, M.REASON_GROW, 2)


def test_migrate_rows_roundtrip_preserves_dtype_and_shape():
    keys = np.arange(7, dtype=np.int32) * 3
    cols = {"MAIN_TABLE/F0": (np.arange(7) * 11).astype(np.uint32),
            "T/bytes": np.arange(7 * 4, dtype=np.uint8).reshape(7, 4),
            "T/f": np.linspace(0, 1, 7, dtype=np.float32)}
    buf = M.encode_migrate_rows(9, keys, cols)
    assert M.peek_rows_version(buf) == 9
    v, k2, c2 = M.decode_migrate_rows(buf)
    assert v == 9
    np.testing.assert_array_equal(k2, keys)
    assert set(c2) == set(cols)
    for n in cols:
        assert c2[n].dtype == cols[n].dtype
        np.testing.assert_array_equal(c2[n], cols[n])


def test_keys_of_slots_enumerates_the_dense_keyspace():
    ks = M.keys_of_slots(np.array([1, 2]), n_rows=11, n_slots=4)
    assert ks.tolist() == [1, 2, 5, 6, 9, 10]
    # a full slot cover enumerates every key exactly once
    all_k = M.keys_of_slots(np.arange(4), 11, 4)
    assert sorted(all_k.tolist()) == list(range(11))


def test_membership_line_parses_back():
    from deneva_tpu.harness.parse import parse_membership

    cfg = elastic_cfg(node_cnt=2, part_cnt=2)
    m = M.plan_drain(M.initial_map(cfg), 1)
    line = M.membership_line(0, m, epoch=32, reason=M.REASON_DRAIN,
                             subject=1, slots_moved=128, rows_in=2048,
                             rows_out=0, stall_ms=12.5)
    rows = parse_membership([line, "unrelated line", "[summary] tput=1"])
    assert len(rows) == 1
    r = rows[0]
    assert r["node"] == 0 and r["version"] == 1 and r["epoch"] == 32
    assert r["reason"] == "drain" and r["subject"] == 1
    assert r["rows_in"] == 2048 and r["stall_ms"] == 12.5
    # logs predating the subsystem parse to []
    assert parse_membership(["[summary] tput=1", "[timeline] x"]) == []


# ---- config gates ------------------------------------------------------

def test_config_rejects_unsupported_elastic_combos():
    with pytest.raises(ValueError, match="deterministic backend"):
        Config(elastic=True, cc_alg=CCAlg.OCC).validate()
    with pytest.raises(ValueError, match="YCSB"):
        Config(elastic=True, workload=WorkloadKind.TPCC,
               max_accesses=18, cc_alg=CCAlg.CALVIN).validate()
    with pytest.raises(ValueError, match="elastic"):
        Config(elastic_spare_cnt=1, node_cnt=2).validate()
    with pytest.raises(ValueError, match="elastic_plan"):
        Config(elastic=True, cc_alg=CCAlg.CALVIN, node_cnt=2,
               elastic_plan="shrink:1:0").validate()
    with pytest.raises(ValueError, match="node 0"):
        Config(elastic=True, cc_alg=CCAlg.CALVIN, node_cnt=2,
               fault_kill="0:8", logging=True).validate()
    # supported shapes validate
    Config(elastic=True, cc_alg=CCAlg.CALVIN, node_cnt=3,
           elastic_spare_cnt=1, elastic_plan="grow:2:16").validate()


# ---- workload routing --------------------------------------------------

def test_elastic_ycsb_full_residency_and_slot_mask():
    import jax.numpy as jnp

    from deneva_tpu.workloads import get_workload

    cfg = elastic_cfg(node_cnt=2, part_cnt=2, node_id=1,
                      synth_table_size=1024)
    wl = get_workload(cfg)
    assert wl.n_local == 1024           # full residency
    db = wl.load()
    assert M.MEMBER_KEY in db
    keys = jnp.arange(64, dtype=jnp.int32)
    slots = np.asarray(wl._local_slots(db, keys))
    # boot map == modulo striping: node 1 owns odd keys at slot == key,
    # even keys steer to the trash slot
    np.testing.assert_array_equal(slots[1::2], np.arange(64)[1::2])
    assert (slots[0::2] == wl.n_local).all()
    # a rebalance is a data update: hand slot (key%S)==0 to node 1
    owners = np.asarray(db[M.MEMBER_KEY]).copy()
    owners[0] = 1
    db[M.MEMBER_KEY] = jnp.asarray(owners)
    slots2 = np.asarray(wl._local_slots(db, keys))
    assert slots2[0] == 0               # key 0 now local
    np.testing.assert_array_equal(slots2[1::2], np.arange(64)[1::2])


def test_state_digest_excludes_the_control_plane():
    import jax.numpy as jnp

    from deneva_tpu.runtime.logger import state_digest
    from deneva_tpu.workloads import get_workload

    cfg = elastic_cfg(node_cnt=2, part_cnt=2, synth_table_size=512)
    wl = get_workload(cfg)
    db = wl.load()
    d0 = state_digest(db)
    db[M.MEMBER_KEY] = jnp.asarray(
        np.roll(np.asarray(db[M.MEMBER_KEY]), 1))
    assert state_digest(db) == d0       # ownership is not row state
    # ...but row state still changes the digest
    tab = db["MAIN_TABLE"]
    db["MAIN_TABLE"] = tab._replace(
        columns={**tab.columns,
                 "F0": tab.columns["F0"].at[0].add(1)})
    assert state_digest(db) != d0


# ---- rebalance-off bit-identity (the acceptance bar) -------------------

def _drive_elastic_run(tmp_path, elastic: bool):
    """One single-server + replica run driven by a raw transport client
    (the ``test_host_overlap_bit_identical`` harness), elastic on/off."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deneva_tpu.runtime import wire
    from deneva_tpu.runtime.logger import state_digest
    from deneva_tpu.runtime.native import NativeTransport, ipc_endpoints
    from deneva_tpu.runtime.replica import ReplicaNode
    from deneva_tpu.runtime.server import ServerNode
    from deneva_tpu.workloads import get_workload

    log_dir = str(tmp_path / f"logs_elastic_{elastic}")
    cfg = Config(workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
                 node_cnt=1, client_node_cnt=1, epoch_batch=64,
                 conflict_buckets=512, synth_table_size=512,
                 req_per_query=4, max_accesses=4, zipf_theta=0.9,
                 pipeline_epochs=2, pipeline_groups=2, logging=True,
                 replica_cnt=1, log_dir=log_dir, warmup_secs=0.0,
                 done_secs=0.0, host_overlap="off", elastic=elastic)
    eps = ipc_endpoints(3, uuid.uuid4().hex[:8])
    wl = get_workload(cfg.replace(elastic=False))
    batches = []
    for s in range(4):          # 256 txns, distinct tag ranges
        q = wl.generate(jax.random.PRNGKey(100 + s), 64)
        k, t, sc = wl.to_wire(q)
        batches.append((np.arange(64, dtype=np.int64) + 64 * s, k, t, sc))

    out: dict = {}

    def run_server():
        node = ServerNode(cfg.replace(node_id=0, part_cnt=1), eps, "cpu")
        try:
            node.run()
            out["digest"] = state_digest(node.db)
        except Exception as e:      # surface instead of hanging the test
            out["err"] = repr(e)
        finally:
            node.close()

    def run_replica():
        node = ReplicaNode(cfg.replace(node_id=2, part_cnt=1), eps)
        try:
            node.run()
        finally:
            node.close()

    ts_srv = threading.Thread(target=run_server)
    ts_rep = threading.Thread(target=run_replica)
    ts_srv.start()
    ts_rep.start()
    cl = NativeTransport(1, eps, 3)
    cl.start()
    acked: list[int] = []
    try:
        for tags, k, t, sc in batches:
            cl.sendv(0, "CL_QRY_BATCH", wire.qry_block_parts(tags, k, t, sc))
        cl.flush()

        def on_other(src, rtype, payload):
            if rtype == "CL_RSP":
                acked.extend(wire.decode_cl_rsp(payload).tolist())

        wire.run_barrier(cl, 1, 3, on_other, "elastic-test client", 300.0)
        t0 = _time.monotonic()
        stopped = False
        while not stopped and _time.monotonic() - t0 < 300:
            m = cl.recv(50_000)
            if m is None:
                continue
            if m[1] == "CL_RSP":
                acked.extend(wire.decode_cl_rsp(m[2]).tolist())
            elif m[1] == "SHUTDOWN":
                stopped = True
        assert stopped, "server never announced SHUTDOWN"
    finally:
        ts_srv.join(timeout=300)
        ts_rep.join(timeout=60)
        cl.close()
    assert "err" not in out, out["err"]
    with open(os.path.join(log_dir, "node0.log.bin"), "rb") as f:
        out["log"] = f.read()
    with open(os.path.join(log_dir, "replica2.log.bin"), "rb") as f:
        out["rlog"] = f.read()
    out["acked"] = sorted(acked)
    return out


def test_elastic_no_rebalance_bit_identical(tmp_path):
    """The rebalance-off acceptance bar: the membership subsystem
    compiled in (elastic=True) with NO rebalance triggered must produce
    byte-identical command logs, byte-identical replica streams,
    identical state digests (the control plane is excluded by contract)
    and the same acked-tag multiset as elastic=False — under a retrying
    backend shape (zipf 0.9) so admission feedback is exercised."""
    on = _drive_elastic_run(tmp_path, True)
    off = _drive_elastic_run(tmp_path, False)
    assert len(on["log"]) > 0
    assert on["log"] == off["log"]
    assert on["rlog"] == off["rlog"]
    assert on["digest"] == off["digest"]
    assert on["acked"] == off["acked"] and len(on["acked"]) > 0


# ---- live rebalance scenarios (real IPC clusters) ----------------------

def test_elastic_drain_scenario_short():
    """Mid-run scale-in N=3 -> 2 on a real cluster: one cutover, the
    drained node ends slotless, rows stream to both survivors, commit
    counts agree across the cutover, zero lost/duplicated txns."""
    from deneva_tpu.harness.chaos import run_scenario

    # owner_check=true arms the thread-ownership runtime asserts on a
    # live cluster in tier-1 (cheap: wrap-at-init + per-mutator check)
    report = run_scenario("elastic-drain", quick=True, quiet=True,
                          owner_check=True)
    assert len(set(report["commits"])) == 1 and report["commits"][0] > 0
    assert report["owned_slots"][2] == 0
    assert all(a > 0 for a in report["client_acked"])


@pytest.mark.slow
def test_elastic_grow_scenario():
    """Mid-run scale-out N=2 active -> 3: the slotless warm spare
    absorbs an even share of slots (rows streamed over MIGRATE_ROWS) and
    serves them; every server agrees on commits across the cutover."""
    from deneva_tpu.harness.chaos import run_scenario

    report = run_scenario("elastic-grow", quiet=True)
    assert len(set(report["commits"])) == 1 and report["commits"][0] > 0
    assert report["owned_slots"][2] > 0
    assert report["rows_migrated"][2] > 0


@pytest.mark.slow
def test_elastic_kill_with_reassignment():
    """Failover-with-reassignment: a killed server's slots move to the
    survivors (rows rebuilt by log replay) WITHOUT restarting the dead
    node; the run reaches liveness and exactly-once holds across the
    takeover (resends re-ack from the survivors' committed sets).
    Runs with owner_check=true: the thread-ownership runtime asserts
    (runtime/ownercheck.py) are armed across the reassignment replay."""
    from deneva_tpu.harness.chaos import run_scenario

    report = run_scenario("elastic-kill-reassign", quiet=True,
                          owner_check=True)
    assert len(set(report["commits"])) == 1 and report["commits"][0] > 0
    assert 2 not in report["owned_slots"]   # the dead node never reports
    assert all(a > 0 for a in report["client_acked"])
