"""DGCC wavefront backend: scripted wave assignment + the audit oracle.

The dependency-graph backend's contract is three-sided: (1) wave levels
are EXACT longest dependency paths under the executor's
gather-then-scatter wave semantics (wr/ww increment, rw and blind-ww
share a wave), (2) the only non-commit outcome is a DEFER of over-deep
closures — ``abort`` is identically zero, and (3) the pre-commit graph
the waves were planned from agrees with the audit plane's post-commit
DSG: every derived edge is explained by the claimed wave order and the
committed-edge graph is acyclic (the cross-check oracle from ISSUE
acceptance).
"""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp

from deneva_tpu.config import Config, CCAlg
from deneva_tpu.cc import get_backend
from deneva_tpu.cc.dgcc import validate_dgcc
from tests.test_cc import CFG, make_batch, run, check_verdict
from tests.test_audit import _batch as audit_batch
from tests.test_audit import _cfg as audit_cfg
from tests.test_audit import _observe


def _v(verdict):
    c, a, d = (np.asarray(verdict.commit), np.asarray(verdict.abort),
               np.asarray(verdict.defer))
    return c, a, d, np.asarray(verdict.level), np.asarray(verdict.order)


# ---- scripted wave assignment ------------------------------------------

def test_chain_levels_exact():
    """w -> rw -> r on one key is a depth-3 chain: waves 0/1/2, the
    unrelated reader rides wave 0, everything commits, nothing aborts."""
    txns = [[(5, "w")], [(5, "rw")], [(5, "r")], [(9, "r")]]
    v, _, b = run("DGCC", txns)
    c, a, d = check_verdict(v, b, txns, chained=True)
    assert c[:4].all() and not a.any() and not d.any()
    lv = np.asarray(v.level)
    assert list(lv[:4]) == [0, 1, 2, 0]


def test_wr_forces_next_wave():
    txns = [[(5, "w")], [(5, "r")]]
    v, _, b = run("DGCC", txns)
    c, a, d = check_verdict(v, b, txns, chained=True)
    assert c[:2].all() and not a.any()
    assert list(np.asarray(v.level)[:2]) == [0, 1]


def test_rw_antidep_shares_wave():
    """Reader-then-writer of one key needs no chaining: within a wave
    all reads gather before writes scatter, so the anti-dependency is
    satisfied at equal level."""
    txns = [[(5, "r")], [(5, "w")]]
    v, _, b = run("DGCC", txns)
    c, a, d = check_verdict(v, b, txns, chained=True)
    assert c[:2].all() and not a.any()
    assert list(np.asarray(v.level)[:2]) == [0, 0]


def test_blind_ww_shares_wave_distinct_order():
    """Blind writes serialize by the executor's last_writer order
    tournament (DGCC runs the tournament path, not the conflict-free
    level_exec fast path), so they share wave 0 with distinct orders."""
    txns = [[(5, "w")], [(5, "w")]]
    v, _, b = run("DGCC", txns)
    c, a, d = check_verdict(v, b, txns, chained=True)
    assert c[:2].all() and not a.any() and not d.any()
    lv, od = np.asarray(v.level), np.asarray(v.order)
    assert list(lv[:2]) == [0, 0] and od[0] != od[1]


def test_overdeep_closure_defers_never_aborts():
    """A hot-key rw chain deeper than dgcc_levels saturates: the prefix
    that fits the wave budget commits at exact levels, the excess falls
    to the DEFER retry queue — the cyclic fallback — with abort pinned
    at zero (the near-zero-abort claim is by construction)."""
    cfg = CFG.replace(dgcc_levels=4)
    txns = [[(5, "rw")] for _ in range(10)]
    v, _, b = run("DGCC", txns, cfg=cfg)
    c, a, d = check_verdict(v, b, txns, chained=True)
    assert not a.any()
    assert c[:4].all() and d[4:].all()
    assert list(np.asarray(v.level)[:4]) == [0, 1, 2, 3]


def test_dependent_of_saturated_txn_defers():
    """Committed waves never read a hole: a reader downstream of a
    saturated writer saturates with it, while an independent reader
    still commits in wave 0."""
    cfg = CFG.replace(dgcc_levels=4)
    txns = [[(5, "rw")] for _ in range(6)] + [[(5, "r")], [(9, "r")]]
    v, _, b = run("DGCC", txns, cfg=cfg)
    c, a, d = check_verdict(v, b, txns, chained=True)
    assert not a.any()
    assert d[6] and not d[7] and c[7]
    assert np.asarray(v.level)[7] == 0


def test_order_free_lanes_exempt_commit_wave_zero():
    """Escrow (order_free) lanes carry no ordering claim: five
    commutative rw txns on one hot key contribute no lanes and all
    commit in wave 0 — the same exemption the audit plane applies."""
    be = get_backend("DGCC")
    txns = [[(7, "rw")] for _ in range(5)]
    batch = make_batch(txns)
    batch = dataclasses.replace(
        batch, order_free=jnp.asarray(
            np.ones(batch.valid.shape, bool) & np.asarray(batch.valid)))
    v, _ = validate_dgcc(CFG, be.init_state(CFG), batch)
    c, a, d, lv, _ = _v(v)
    assert c[:5].all() and not a.any() and not d.any()
    assert (lv[:5] == 0).all()


def test_verdict_pure_replicated_bit_identical():
    """The verdict is a pure function of the merged batch (sort + scans,
    no RNG, no cross-epoch state): two independent jit instances and the
    eager path produce bit-identical planes — the invariant the merged
    cluster path and dp>1 mesh shards rely on to ship DGCC verdicts the
    way CALVIN's are shipped."""
    rng = np.random.default_rng(7)
    txns = [[(int(rng.integers(0, 6)),
              str(rng.choice(["r", "w", "rw"])))
             for _ in range(int(rng.integers(1, 4)))] for _ in range(12)]
    be = get_backend("DGCC")
    batch = make_batch(txns)
    st = be.init_state(CFG)
    planes = []
    for fn in (jax.jit(validate_dgcc, static_argnums=0),
               jax.jit(validate_dgcc, static_argnums=0),
               validate_dgcc):
        v, _ = fn(CFG, st, batch)
        planes.append(tuple(np.asarray(x) for x in
                            (v.commit, v.abort, v.defer, v.order,
                             v.level)))
    for p in planes[1:]:
        for x, y in zip(planes[0], p):
            assert (x == y).all()


def test_randomized_serializability_dgcc():
    """The cross-algorithm oracle from test_cc, pointed at DGCC: random
    hot-keyspace batches must commit a serializable set under the
    chained-level stale-read rule, with zero aborts ever."""
    rng = np.random.default_rng(1234)
    be = get_backend("DGCC")
    st = be.init_state(CFG)
    for _ in range(6):
        txns = []
        for _ in range(12):
            script = [(int(rng.integers(0, 8)),
                       str(rng.choice(["r", "w", "rw"])))
                      for _ in range(int(rng.integers(1, 5)))]
            txns.append(script)
        v, st, b = run("DGCC", txns, state=st)
        check_verdict(v, b, txns, chained=be.chained)
        assert not np.asarray(v.abort).any()
        assert np.asarray(v.commit).sum() >= 1


# ---- audit cross-check oracle ------------------------------------------

def test_audit_edges_agree_with_wave_order():
    """ISSUE acceptance: the pre-commit dependency graph DGCC planned
    its waves from must agree with the audit plane's post-commit DSG.
    Every derived edge is explained by the claimed wave order — wr
    strictly increases the level, ww respects (level, order), rw never
    goes down a level — and the committed-edge graph is acyclic (a
    clean serializability certificate)."""
    acfg = audit_cfg()
    scripts = [
        [(10, "w")],                 # 0: wave 0
        [(10, "r"), (20, "w")],      # 1: wr 0->1
        [(20, "rw")],                # 2: wr/ww 1->2
        [(10, "r")],                 # 3: wr 0->3
        [(30, "r"), (10, "w")],      # 4: rw 1->4, rw 3->4, ww 0->4
        [(30, "w")],                 # 5: rw 4->5
    ]
    batch = audit_batch(scripts)
    v, _ = validate_dgcc(acfg, None, batch)
    c, a, d, lv, od = _v(v)
    assert c[:6].all() and not a.any() and not d.any()
    assert lv[:6].max() >= 1        # anti-inert: the graph really chains

    _, es, cnt, drop, _, _ = _observe(acfg, batch, v.commit, lvl=v.level)
    assert cnt > 0 and drop == 0    # anti-inert: edges were derived
    adj = {i: set() for i in range(len(scripts))}
    for kind, src, dst in es:
        if kind == 1:               # wr true dependency: next wave up
            assert lv[dst] > lv[src], (kind, src, dst, lv[:6])
        elif kind == 0:             # ww: last_writer tournament order
            assert (lv[src], od[src]) < (lv[dst], od[dst]), \
                (kind, src, dst)
        else:                       # rw anti-dep: never down a level
            assert (lv[dst], od[dst]) >= (lv[src], od[src]), \
                (kind, src, dst)
        adj[src].add(dst)
    # acyclicity of the committed DSG (iterative three-color DFS)
    state = {}
    for root in adj:
        if state.get(root):
            continue
        stack = [(root, iter(sorted(adj[root])))]
        state[root] = 1
        while stack:
            node, it = stack[-1]
            for nxt in it:
                assert state.get(nxt) != 1, f"cycle through {nxt}"
                if not state.get(nxt):
                    state[nxt] = 1
                    stack.append((nxt, iter(sorted(adj[nxt]))))
                    break
            else:
                state[node] = 2
                stack.pop()


# ---- default-off pin (the smoke gate's off half) -----------------------

def test_dgcc_off_pin():
    """Default-off contract: without CC_ALG=DGCC or ctrl_dgcc the
    wavefront backend contributes nothing observable — the router
    candidate tuple and the controller's backend map stay the pre-DGCC
    triples (three routed branches exactly), a hot OCC run leaves every
    dgcc_* device counter identically zero, and a default server's blob
    broadcast stays byte-identical to the bare codec output (the wire
    pin)."""
    from deneva_tpu.cc.router import CANDIDATES, candidates
    from deneva_tpu.engine import Engine
    from deneva_tpu.runtime import wire
    from deneva_tpu.runtime.controller import (CLASS_BACKEND,
                                               default_backend_map)
    from deneva_tpu.workloads import get_workload
    from tests.test_chaos import _solo_server

    cfg0 = Config()
    assert cfg0.ctrl_dgcc is False and cfg0.cc_alg != CCAlg.DGCC
    assert candidates(cfg0) == CANDIDATES
    assert CCAlg.DGCC not in CANDIDATES
    assert default_backend_map(cfg0) == CLASS_BACKEND == (0, 1, 2)

    cfg = Config(cc_alg=CCAlg.OCC, epoch_batch=256, conflict_buckets=512,
                 max_accesses=4, req_per_query=4, synth_table_size=1024,
                 zipf_theta=0.9, read_perc=0.1, write_perc=0.9,
                 max_txn_in_flight=1024).validate()
    eng = Engine(cfg, get_workload(cfg))
    stats = jax.device_get(eng.jit_run(eng.init_state(seed=1), 10).stats)
    dk = [k for k in stats if k.startswith("dgcc_")]
    assert dk and all(int(stats[k]) == 0 for k in dk)

    node = _solo_server("dgcc_off_pin")
    try:
        blk = wire.QueryBlock(
            keys=np.arange(8, dtype=np.int32).reshape(4, 2),
            types=np.ones((4, 2), np.int8),
            scalars=np.zeros((4, 0), np.int32),
            tags=np.arange(4, dtype=np.int64))
        ts = np.arange(4, dtype=np.int64) + 100
        blob = wire.encode_epoch_blob(7, blk, ts)
        sent = []
        node.tp.sendv_many = \
            lambda dests, rt, parts: sent.append((list(dests), rt, parts))
        node.tp.send = lambda d, rt, pl=b"": sent.append(([d], rt, [pl]))
        node.n_srv = 2          # pretend a peer so the bcast emits
        node._bcast_views(7, blk, ts)
        (_dests, rt, parts), = sent
        assert rt == "EPOCH_BLOB"
        assert b"".join(bytes(p) for p in parts) == blob
        assert not any(k.startswith("dgcc") for k in node.stats.counters)
    finally:
        node.n_srv = 1
        node.close()


# ---- engine integration (anti-inert) -----------------------------------

def test_engine_hot_zipf_waves_chain_zero_aborts():
    """zipf-0.9 write-heavy YCSB through the full jitted engine: the
    wavefront must actually chain (dgcc_wave_max > 1 — the smoke gate's
    anti-inert signal), commit real work, and never abort."""
    from deneva_tpu.engine import Engine
    from deneva_tpu.workloads import get_workload

    cfg = Config(cc_alg=CCAlg.DGCC, epoch_batch=256, conflict_buckets=512,
                 max_accesses=4, req_per_query=4, synth_table_size=1024,
                 zipf_theta=0.9, read_perc=0.1, write_perc=0.9,
                 max_txn_in_flight=1024).validate()
    eng = Engine(cfg, get_workload(cfg))
    stats = jax.device_get(eng.jit_run(eng.init_state(seed=1), 30).stats)
    commits = int(stats["total_txn_commit_cnt"])
    aborts = int(stats["total_txn_abort_cnt"])
    assert commits > 0 and aborts == 0
    assert int(stats["dgcc_wave_max"]) > 1
    assert int(stats["dgcc_wave_cnt"]) > 30      # > #epochs: it chained
    assert int(stats["dgcc_edge_cnt"]) > 0
