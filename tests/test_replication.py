"""Geo-replication tier unit tests (runtime/replication.py): region
assignment, quorum math, WAN profile parsing, config gating, and the
follower state machine's apply/serve/catch-up/verification contracts.
The full-cluster scenarios live in the chaos harness (`geo` gate)."""

import json
import os

import numpy as np
import pytest

from deneva_tpu.config import CCAlg, Config, WorkloadKind
from deneva_tpu.runtime import replication as R


def geo_cfg(**kw):
    base = dict(
        workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
        node_cnt=3, client_node_cnt=1, replica_cnt=1, logging=True,
        elastic=True, geo=True, geo_region_cnt=3,
        epoch_batch=64, conflict_buckets=256, synth_table_size=1024,
        req_per_query=4, max_accesses=4)
    base.update(kw)
    return Config(**base).validate()


# ---- region assignment / geo map ---------------------------------------

def test_region_assignment_places_replicas_off_primary_region():
    cfg = geo_cfg()
    assert [R.region_of(cfg, s) for s in range(3)] == [0, 1, 2]
    # replica of primary p never homes in p's region (the placement that
    # makes region loss survivable)
    for p in range(3):
        rid = R.replica_ids_of(cfg, p)[0]
        assert R.region_of(cfg, rid) != R.region_of(cfg, p)
    # clients deal block-wise like servers
    assert R.region_of(cfg, 3) == 0


def test_region_assignment_single_region_degenerates():
    cfg = geo_cfg(geo_region_cnt=1)
    n_all = 3 + 1 + 3
    assert {R.region_of(cfg, t) for t in range(n_all)} == {0}


def test_geo_map_triple_follows_slot_map():
    from deneva_tpu.runtime.membership import initial_map, plan_reassign

    cfg = geo_cfg()
    m = initial_map(cfg)
    gm = R.GeoMap(cfg, m)
    p, replicas, region = gm.describe(1)
    assert p == 1 and replicas == (5,) and region == 1
    # a dead-peer reassignment re-derives the triple for free
    gm2 = R.GeoMap(cfg, plan_reassign(m, 1))
    assert gm2.primary_of(1) != 1
    assert gm2.region_of_slot(1) == R.region_of(cfg, gm2.primary_of(1))


def test_nearest_ordering_respects_wan_profile():
    cfg = geo_cfg(geo_wan_us="0-1:5000,0-2:40000")
    tiers = R.server_tiers(cfg, 0)
    assert tiers == [[0], [1], [2]]       # same region, 5ms, 40ms
    # followers: replica-of-2 homes in region 0 (nearest), then the
    # region-1 one (5ms), then region-2 (40ms)
    assert R.follower_order(cfg, 0) == [6, 4, 5]
    # without a profile, same-region first then id order
    assert R.server_tiers(geo_cfg(), 1) == [[1], [0, 2]]


def test_quorum_ack_math():
    assert R.quorum_ack([], 0) == -1
    assert R.quorum_ack([7], 0) == 7
    assert R.quorum_ack([3, 9, 6], 0) == 3     # 0 = all (pre-geo gate)
    assert R.quorum_ack([3, 9, 6], 1) == 9
    assert R.quorum_ack([3, 9, 6], 2) == 6
    assert R.quorum_ack([3, 9, 6], 3) == 3


def test_durable_quorum_survives_dead_followers():
    """Region loss must DEGRADE the quorum to the live follower set,
    never freeze the commit horizon behind an ack that cannot come."""
    acked = {4: 9, 5: 3}
    alive = {4: True, 5: True}
    dq = lambda q, f: R.durable_quorum(acked, alive.get, q, f)  # noqa: E731
    assert dq(1, 100) == 9          # both alive: q-th highest ack
    assert dq(0, 100) == 3          # 0 = all
    assert dq(1, 7) == 7            # local flush can be the binding cap
    alive[4] = False
    assert dq(1, 100) == 3          # dead follower leaves the ack set
    assert dq(2, 100) == 3          # quorum clamps to the survivors
    alive[5] = False
    assert dq(1, 100) == 100        # no follower left: local flush alone


# ---- WAN profile + config gating ---------------------------------------

def test_wan_spec_symmetric_directed_and_errors():
    cfg = geo_cfg(geo_wan_us="0-1:20000,1>2:7000")
    wan = cfg.geo_wan_spec()
    assert wan[(0, 1)] == wan[(1, 0)] == 20000
    assert wan[(1, 2)] == 7000 and (2, 1) not in wan
    with pytest.raises(ValueError, match="geo_wan_us"):
        geo_cfg(geo_wan_us="0:1:bad")
    with pytest.raises(ValueError, match="regions must be"):
        geo_cfg(geo_wan_us="0-9:100")


def test_geo_config_gating():
    with pytest.raises(ValueError, match="needs --elastic"):
        geo_cfg(elastic=False)
    with pytest.raises(ValueError, match="replica_cnt"):
        geo_cfg(replica_cnt=0)
    with pytest.raises(ValueError, match="geo_quorum"):
        geo_cfg(geo_quorum=2)
    # TPCC is rejected twice over: the elastic prerequisite's YCSB-only
    # check fires today, and geo's own YCSB-scoped check stands behind
    # it for whenever elastic grows TPCC support
    with pytest.raises(ValueError, match="YCSB"):
        geo_cfg(workload=WorkloadKind.TPCC, num_wh=2, max_accesses=18)
    with pytest.raises(ValueError, match="need --geo"):
        Config(geo_region_cnt=2).validate()
    # defaults keep the tier fully off
    assert Config().geo is False


def test_apply_wan_profile_sets_per_link_delays():
    class FakeTp:
        def __init__(self):
            self.delays = {}

        def set_peer_delay_us(self, peer, us):
            self.delays[peer] = us

    cfg = geo_cfg(geo_wan_us="0>1:5000,0>2:40000")
    tp = FakeTp()
    # node 0 (region 0): delayed links to region-1 and region-2 peers
    n = R.apply_wan_profile(tp, cfg, 0)
    # peers in region 1: server 1, replica-of-0 (tid 4); region 2:
    # server 2, replica-of-1 (tid 5)
    assert tp.delays == {1: 5000, 4: 5000, 2: 40000, 5: 40000}
    assert n == 4
    # a region-1 node has no profiled outbound entries
    tp2 = FakeTp()
    assert R.apply_wan_profile(tp2, cfg, 1) == 0 and tp2.delays == {}


# ---- geo=off wire bit-identity -----------------------------------------

def test_geo_off_replica_wire_unchanged(tmp_path):
    """With geo off a replica speaks the PRE-GEO wire exactly: a
    LOG_MSG is answered by LOG_RSP carrying `wire.encode_shutdown`
    bytes (never LOG_ACK), the appended log bytes are the payload
    verbatim, and no follower state machine is ever constructed — the
    acceptance contract that geo=off runs stay bit-identical to the
    pre-geo tier on every byte a peer can observe."""
    import threading

    from deneva_tpu.runtime import wire
    from deneva_tpu.runtime.logger import pack_record
    from deneva_tpu.runtime.native import NativeTransport, ipc_endpoints
    from deneva_tpu.runtime.replica import ReplicaNode

    cfg = geo_cfg(geo=False, geo_region_cnt=1, node_cnt=1,
                  client_node_cnt=0, node_id=1,
                  log_dir=str(tmp_path))
    eps = ipc_endpoints(2, f"geooff_{os.getpid()}")
    box = {}

    def run_replica():
        # construction joins the mesh, so it must overlap the primary's
        # dt_start (both sides dial until the full mesh is up)
        try:
            box["node"] = node = ReplicaNode(cfg, eps)
            box["stats"] = node.run()
        except Exception as e:           # surfaces in the main thread
            box["err"] = e

    t = threading.Thread(target=run_replica)
    t.start()
    tp = NativeTransport(0, eps, 2)
    tp.start()
    try:
        wire.run_barrier(tp, 0, 2, lambda *_: None, "primary", 30.0)
        payload = pack_record(7, b"\x01\x02\x03\x04", np.ones(8, np.uint8))
        tp.send(1, "LOG_MSG", payload)
        src, rtype, rsp = tp.recv(10_000_000)
        assert (src, rtype) == (1, "LOG_RSP")
        assert rsp == wire.encode_shutdown(7)     # pre-geo ack bytes
        tp.send(1, "SHUTDOWN")
        t.join(timeout=30)
        assert "err" not in box and not t.is_alive()
        assert box["node"].follower is None   # no GeoFollower booted
        with open(os.path.join(str(tmp_path),
                               "replica1.log.bin"), "rb") as f:
            assert f.read() == payload            # log bytes verbatim
        s = box["stats"].summary_fields()
        assert "follower_read_cnt" not in s and "geo_region" not in s
    finally:
        if "node" in box:
            box["node"].close()
        tp.close()


# ---- wire codec edge cases (round trips live in test_wire_registry) ----

def test_region_read_rsp_empty_batch():
    tag, boundary, vals, vers = R.decode_region_read_rsp(
        R.encode_region_read_rsp(3, 16, np.zeros(0, np.uint32),
                                 np.zeros(0, np.int32)))
    assert (tag, boundary, len(vals), len(vers)) == (3, 16, 0, 0)


# ---- follower state machine --------------------------------------------

@pytest.fixture(scope="module")
def follower_rig():
    """One small single-primary stream: the follower cfg, a 6-record
    framed log (C=4 so one full group + a partial tail), and the
    workload used to build it."""
    import jax

    from deneva_tpu.runtime import wire
    from deneva_tpu.runtime.logger import pack_record

    jax.config.update("jax_platforms", "cpu")
    cfg = geo_cfg(node_cnt=1, geo_region_cnt=1, pipeline_epochs=4)
    rcfg = cfg.replace(node_id=2, part_cnt=1)
    fol = R.GeoFollower(rcfg, 2)
    b = fol.b
    key = jax.random.PRNGKey(3)
    recs, blocks = [], []
    for e in range(6):
        q = fol.wl.generate(jax.random.fold_in(key, e), b)
        keys, types, scal = fol.wl.to_wire(q)
        blk = wire.QueryBlock(keys, types, scal,
                              np.arange(b, dtype=np.int64))
        ts = np.int64(e + 1) * b + np.arange(b, dtype=np.int64)
        blob = wire.encode_epoch_blob(e, blk, ts)
        recs.append(pack_record(e, blob, np.ones(b, np.uint8)))
        blocks.append(blk)
    return rcfg, fol, recs, blocks


def test_follower_applies_whole_groups_only(follower_rig):
    _, fol, recs, _ = follower_rig
    fol.offer(recs[0])
    fol.offer(recs[2])            # hole at epoch 1
    assert fol.tick() is False and fol.boundary == 0
    fol.offer(recs[1])
    fol.offer(recs[3])
    assert fol.tick() is True
    assert (fol.applied, fol.boundary) == (3, 4)
    assert fol.last_seen == 3


def test_follower_serve_boundary_snapshot_and_version_stamps(follower_rig):
    _, fol, recs, blocks = follower_rig
    assert fol.boundary == 4      # ordered after the apply test
    written = np.unique(np.concatenate(
        [b.keys[b.types == 2] for b in blocks[:4]]))
    probe_w = written[:4]
    untouched = np.setdiff1d(np.arange(1024), written)[:4]
    keys = np.concatenate([probe_w, untouched])
    boundary, vals, vers = fol.serve(keys)
    assert boundary == 4
    # version stamps: rows the applied group overwrote carry the
    # boundary id, untouched rows the load-base 0 — and none may ever
    # exceed the served boundary (the client-side lockless check)
    assert (vers[:4] == 4).all() and (vers[4:] == 0).all()
    assert (vers <= boundary).all()
    # untouched rows still serve the load-time fingerprint
    from deneva_tpu.workloads.ycsb import _field_fingerprint
    np.testing.assert_array_equal(
        vals[4:], np.asarray(_field_fingerprint(untouched, 0)))
    assert fol.rows_served >= len(keys) and fol.reads_served >= 1


def test_follower_catch_up_and_replay_digest(follower_rig, tmp_path):
    from deneva_tpu.runtime.logger import replay_into, state_digest

    rcfg, fol, recs, _ = follower_rig
    fol.offer(recs[4])
    fol.offer(recs[5])
    assert fol.tick() is False     # partial tail group never auto-applies
    assert fol.catch_up() == 5 and fol.boundary == 6
    # duplicate offers (rejoin resends) are dropped
    fol.offer(recs[4])
    assert not fol.pending
    # independent full-ownership replay of the same stream reproduces
    # the follower's state digest bit for bit (the chaos oracle)
    log = tmp_path / "stream.log.bin"
    log.write_bytes(b"".join(bytes(r) for r in recs))
    _, wl, step, db, cc0, st0 = R.follower_boot(rcfg, 0)
    db, _, _, last = replay_into(str(log), rcfg, wl, step, db, cc0, st0)
    assert last == 5
    assert state_digest(db) == fol.digest()
    # sidecar carries the same digest + counters
    side_path = tmp_path / "side.json"
    fol.write_sidecar(str(side_path))
    side = json.loads(side_path.read_text())
    assert side["applied_epoch"] == 5
    assert side["state_digest"] == fol.digest()
    assert side["stale_read_max_epochs"] >= 0


def test_follower_resync_rebuilds_from_truncated_log(follower_rig,
                                                     tmp_path):
    rcfg, fol, recs, _ = follower_rig
    assert fol.applied == 5       # ordered after catch-up
    log = tmp_path / "trunc.log.bin"
    log.write_bytes(b"".join(bytes(r) for r in recs[:4]))
    fol.resync(str(log), resume=4)
    # applied ran past the truncation point -> full rebuild off the file
    assert fol.last_seen == 3 and fol.applied == -1
    assert fol.tick() is True and fol.applied == 3


def test_follower_read_keys_clamped(follower_rig):
    _, fol, _, _ = follower_rig
    # out-of-range keys clamp on BOTH sides (a negative key must not
    # wrap to the table tail), never crash
    boundary, vals, vers = fol.serve(np.array([10**9, -1], np.int64))
    assert len(vals) == 2
    b2, vals2, vers2 = fol.serve(np.array([fol.wl.n_rows - 1, 0],
                                          np.int64))
    assert vals[0] == vals2[0] and vals[1] == vals2[1]
