"""Unit tests for the config + stats layers (SURVEY §1 L1/L11)."""

import pytest

from deneva_tpu.config import Config, CCAlg, WorkloadKind
from deneva_tpu.stats import Stats, StatsArr, parse_summary


def test_config_defaults_validate():
    cfg = Config().validate()
    assert cfg.cc_alg == CCAlg.TPU_BATCH
    assert cfg.workload == WorkloadKind.YCSB


def test_config_from_args_roundtrip():
    cfg = Config.from_args([
        "--cc-alg=OCC", "--zipf-theta", "0.9", "--epoch_batch=1024",
        "--node_cnt=4", "--backoff=false", "--mesh_shape=(8,)",
    ])
    assert cfg.cc_alg == CCAlg.OCC
    assert cfg.zipf_theta == 0.9
    assert cfg.epoch_batch == 1024
    assert cfg.node_cnt == 4
    assert cfg.backoff is False
    assert cfg.mesh_shape == (8,)


def test_config_rejects_unknown_and_bad():
    with pytest.raises(ValueError):
        Config.from_args(["--nonsense=1"])
    with pytest.raises(ValueError):
        Config(epoch_batch=1000).validate()  # not a power of two
    with pytest.raises(ValueError):
        Config().validate().replace(epoch_batch=1000)  # replace re-validates


def test_stats_arr_percentiles():
    # weighted nearest-rank, matching the reference's sorted-array
    # indexing (stats_array.cpp:127-146 get_idx)
    a = StatsArr(cap=4)
    a.extend(range(1, 101))
    assert a.percentile(50) == pytest.approx(50.0)
    assert a.percentile(99) == pytest.approx(99.0)
    assert len(a) == 100


def test_stats_arr_weighted_equals_expanded():
    """extend_weighted(values, counts) is exactly the expanded multiset —
    the driver feeds whole latency histograms through this path with no
    sample cap (round-1 weakness #7 fixed)."""
    import numpy as np
    vals = np.array([0.5, 1.5, 2.5, 3.5])
    counts = np.array([500_000, 300_000, 150_000, 50_000])
    w = StatsArr()
    w.extend_weighted(vals, counts)
    e = StatsArr()
    e.extend(np.repeat(vals, counts))
    assert len(w) == counts.sum() == len(e)
    for p in (50, 90, 95, 99):
        assert w.percentile(p) == e.percentile(p)
    assert w.mean() == pytest.approx(e.mean())


def test_stats_merge_and_summary_roundtrip():
    s1, s2 = Stats(), Stats()
    s1.incr("total_txn_commit_cnt", 100)
    s1.incr("total_txn_abort_cnt", 7)
    s2.incr("total_txn_commit_cnt", 50)
    s2.arr("client_client_latency").extend([1.0, 2.0, 3.0])
    s1.merge(s2)
    s1.set("total_runtime", 2.0)

    line = s1.summary_line()
    assert line.startswith("[summary] total_runtime=2,tput=75,txn_cnt=150")
    fields = parse_summary(line)
    assert fields["total_txn_commit_cnt"] == 150
    assert fields["total_txn_abort_cnt"] == 7
    assert fields["client_client_latency_p50"] == 2.0


def test_prog_line_and_proc_utilization():
    """[prog] tick parity (system/thread.cpp:86-105 + stats.h:311-316
    mem/cpu utilization from /proc/self)."""
    import sys

    from deneva_tpu.stats import proc_utilization

    u = proc_utilization()
    if sys.platform == "linux":     # zeros are the documented non-/proc fallback
        assert u["mem_util"] > 1.0  # this process surely exceeds 1 MiB RSS
        assert u["cpu_util"] > 0.0
    assert u["cpu_util"] >= 0.0
    s = Stats()
    s.incr("total_txn_commit_cnt", 40)
    s.set("total_runtime", 2.0)
    line = s.prog_line({"epoch_cnt": 9})
    assert line.startswith("[prog] total_runtime=2,tput=20,txn_cnt=40")
    assert "mem_util=" in line and "cpu_util=" in line
    assert line.endswith("epoch_cnt=9")


def test_stats_arr_boundary_ranks():
    """Weighted nearest-rank at the boundary ranks: p0 is the min, p100
    the max, a single bucket answers every percentile with its value,
    and huge weights neither overflow nor skew the rank arithmetic."""
    from deneva_tpu.stats import StatsArr, weighted_nearest_rank

    a = StatsArr()
    a.extend([5.0, 1.0, 9.0])
    assert a.percentile(0) == 1.0
    assert a.percentile(100) == 9.0
    # single bucket: every rank answers the one value
    b = StatsArr()
    b.extend_weighted([42.0], [7])
    for p in (0, 1, 50, 99, 100):
        assert b.percentile(p) == 42.0
    assert len(b) == 7
    # huge weights: 1e12 copies of 1.0 vs one copy of 100.0 — p99 must
    # stay at the heavy value (float64 cumsum holds the exact rank)
    c = StatsArr()
    c.extend_weighted([1.0, 100.0], [1e12, 1.0])
    assert c.percentile(99) == 1.0
    assert c.percentile(100) == 100.0
    # empty / zero-weight input answers 0 by contract
    assert StatsArr().percentile(50) == 0.0
    assert weighted_nearest_rank([], None, 50) == 0.0
    assert weighted_nearest_rank([3.0], [0.0], 50) == 0.0
    # the shared helper agrees with the array path (one definition)
    assert weighted_nearest_rank([5.0, 1.0, 9.0], None, 0) == 1.0
    assert weighted_nearest_rank([5.0, 1.0, 9.0], None, 100) == 9.0


def test_stats_arr_merge_from_grown_buffers():
    """merge_from on arrays that outgrew their initial capacity: the
    splice must copy only the LIVE prefix (amortized growth leaves
    np.resize garbage past _n) and weighted entries merge exactly."""
    import numpy as np

    from deneva_tpu.stats import StatsArr

    a = StatsArr(cap=4)
    a.extend(np.arange(100, dtype=np.float64))     # grows 4 -> 128
    assert len(a) == 100
    b = StatsArr(cap=4)
    b.extend_weighted([1000.0, 2000.0], [50, 50])  # weighted source
    b.extend(np.arange(100, 170, dtype=np.float64))  # grown + mixed
    a.merge_from(b)
    assert len(a) == 100 + 100 + 70
    # the merged multiset ranks exactly: 170 unit samples 0..169 below
    # the 100 heavy samples at 1000/2000
    assert a.percentile(100) == 2000.0
    assert a.percentile(0) == 0.0
    # 170/270 ~ 63% of mass below 170: p50 lands inside the unit ramp,
    # p75 inside the heavy tail
    assert a.percentile(50) < 170.0
    assert a.percentile(75) == 1000.0
    # view() expands weights for small series — the oracle the
    # percentile path must match
    v = np.sort(a.view())
    assert len(v) == 270
    assert v[-1] == 2000.0 and (v[:170] == np.arange(170)).all()
    # merging an EMPTY grown array is a no-op
    c = StatsArr(cap=4)
    n0 = len(a)
    a.merge_from(c)
    assert len(a) == n0
