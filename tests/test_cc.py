"""CC backends: scripted interleavings + serializability oracles.

Each txn script is a list of (key, mode) with mode 'r' | 'w' | 'rw'.
The oracle checks the *semantic* contract of a Verdict under epoch-snapshot
execution: committed reads must be correct in the claimed serialization
order (no committed writer of a key ordered before a committed
snapshot-reader of it, unless the backend chains levels and the reader's
level is above the writer's).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from deneva_tpu.config import Config, CCAlg
from deneva_tpu.cc import AccessBatch, build_incidence, get_backend


CFG = Config(epoch_batch=16, conflict_buckets=4096, max_accesses=4,
             req_per_query=4, synth_table_size=1024)


def make_batch(txns, ts=None, rank=None, a=4):
    # pad every batch to a fixed B so jit compiles once per algorithm
    b, bp = len(txns), CFG.epoch_batch
    assert b <= bp
    keys = np.zeros((bp, a), np.int32)
    is_r = np.zeros((bp, a), bool)
    is_w = np.zeros((bp, a), bool)
    valid = np.zeros((bp, a), bool)
    for i, script in enumerate(txns):
        assert len(script) <= a
        for s, (key, mode) in enumerate(script):
            keys[i, s] = key
            valid[i, s] = True
            is_r[i, s] = "r" in mode
            is_w[i, s] = "w" in mode
    ts = np.arange(1, b + 1, dtype=np.int32) if ts is None else np.asarray(ts, np.int32)
    rank = np.arange(b, dtype=np.int32) if rank is None else np.asarray(rank, np.int32)
    ts = np.concatenate([ts, np.full(bp - b, ts.max() + 1, np.int32)])
    rank = np.concatenate([rank, np.arange(bp - b, dtype=np.int32) + rank.max() + 1])
    active = np.zeros(bp, bool)
    active[:b] = True
    return AccessBatch(
        table_ids=jnp.zeros((bp, a), jnp.int32), keys=jnp.asarray(keys),
        is_read=jnp.asarray(is_r), is_write=jnp.asarray(is_w),
        valid=jnp.asarray(valid), ts=jnp.asarray(ts), rank=jnp.asarray(rank),
        active=jnp.asarray(active))


import functools
import jax


@functools.lru_cache(maxsize=32)
def _jitted_validate(alg, cfg):
    be = get_backend(alg)

    @jax.jit
    def go(state, batch):
        inc = build_incidence(batch, cfg.conflict_buckets, cfg.conflict_exact) \
            if be.needs_incidence else None
        return be.validate(cfg, state, batch, inc)
    return go


def run(alg, txns, cfg=CFG, state=None, **kw):
    be = get_backend(alg)
    batch = make_batch(txns, **kw)
    if state is None:
        state = be.init_state(cfg)
    verdict, state = _jitted_validate(alg, cfg)(state, batch)
    return verdict, state, batch


def check_verdict(verdict, batch, txns, chained=False):
    commit = np.asarray(verdict.commit)
    abort = np.asarray(verdict.abort)
    defer = np.asarray(verdict.defer)
    order = np.asarray(verdict.order)
    level = np.asarray(verdict.level)
    active = np.asarray(batch.active)
    # disjoint partition covering active
    assert not (commit & abort).any() and not (commit & defer).any() \
        and not (abort & defer).any()
    assert ((commit | abort | defer) == active).all()
    # serializability of the committed set
    reads = [set(k for k, m in s if "r" in m) for s in txns]
    writes = [set(k for k, m in s if "w" in m) for s in txns]
    b = len(txns)
    for i in range(b):
        for j in range(b):
            if i == j or not (commit[i] and commit[j]):
                continue
            if order[j] < order[i] and (writes[j] & reads[i]):
                # j's write ordered before i's snapshot read of same key
                if chained:
                    assert level[i] > level[j], (i, j)
                else:
                    raise AssertionError(f"stale read: writer {j} < reader {i}")
            if writes[i] & writes[j]:
                assert order[i] != order[j]
    return commit[:b], abort[:b], defer[:b]


# ---- NO_WAIT -----------------------------------------------------------

def test_no_wait_conflict_aborts_later():
    v, _, batch = run("NO_WAIT", [[(5, "w")], [(5, "r")], [(7, "r")]])
    c, a, d = check_verdict(v, batch, [[(5, "w")], [(5, "r")], [(7, "r")]])
    assert c[0] and a[1] and c[2]

def test_no_wait_read_read_no_conflict():
    v, _, b = run("NO_WAIT", [[(5, "r")], [(5, "r")]])
    c, a, d = check_verdict(v, b, [[(5, "r")], [(5, "r")]])
    assert c.all()

def test_no_wait_rank_decides():
    v, _, b = run("NO_WAIT", [[(5, "w")], [(5, "w")]], rank=[9, 2])
    c, a, d = check_verdict(v, b, [[(5, "w")], [(5, "w")]])
    assert a[0] and c[1]


# ---- WAIT_DIE ----------------------------------------------------------

def test_wait_die_older_waits_younger_dies():
    # txn0 owns (rank 0); txn1 older (smaller ts) -> waits; txn2 younger -> dies
    txns = [[(5, "w")], [(5, "w")], [(5, "w")]]
    v, _, b = run("WAIT_DIE", txns, ts=[50, 10, 90], rank=[0, 1, 2])
    c, a, d = check_verdict(v, b, txns)
    assert c[0] and d[1] and a[2]


# ---- OCC ---------------------------------------------------------------

def test_occ_reader_first_commits_both():
    txns = [[(5, "r")], [(5, "w")]]
    v, _, b = run("OCC", txns)
    c, a, d = check_verdict(v, b, txns)
    assert c.all()   # reader rank 0, writer rank 1: serial r->w valid

def test_occ_writer_first_aborts_reader():
    txns = [[(5, "w")], [(5, "r")]]
    v, _, b = run("OCC", txns)
    c, a, d = check_verdict(v, b, txns)
    assert c[0] and a[1]

def test_occ_blind_ww_conflicts():
    txns = [[(5, "w")], [(5, "w")]]
    v, _, b = run("OCC", txns)
    c, a, d = check_verdict(v, b, txns)
    assert c[0] and a[1]


# ---- TIMESTAMP ---------------------------------------------------------

def test_to_reader_after_writer_waits():
    # buffered read (row_ts.cpp:63-80): the later reader parks until the
    # writer's value is committed — defer, not abort
    txns = [[(5, "w")], [(5, "r")]]
    v, st, b = run("TIMESTAMP", txns, ts=[1, 2])
    c, a, d = check_verdict(v, b, txns)
    assert c[0] and d[1] and not a[1]
    # next epoch the parked reader finds the committed value (wts=1 < 2)
    v, st, b = run("TIMESTAMP", [[(5, "r")]], ts=[2], state=st)
    assert np.asarray(v.commit)[0]

def test_to_reader_before_writer_both_commit():
    txns = [[(5, "r")], [(5, "w")]]
    v, _, b = run("TIMESTAMP", txns, ts=[1, 2])
    c, a, d = check_verdict(v, b, txns)
    assert c.all()

def test_to_blind_ww_thomas_rule():
    txns = [[(5, "w")], [(5, "w")]]
    v, _, b = run("TIMESTAMP", txns, ts=[1, 2])
    c, a, d = check_verdict(v, b, txns)
    assert c.all()
    assert np.asarray(v.order)[1] > np.asarray(v.order)[0]

def test_to_watermarks_cross_epoch():
    be = get_backend("TIMESTAMP")
    st = be.init_state(CFG)
    # epoch 1: writer at ts 10 commits
    v, st, _ = run("TIMESTAMP", [[(5, "w")]], ts=[10], state=st)
    assert np.asarray(v.commit)[0]
    # epoch 2: stale reader ts 5 aborts; fresh reader ts 15 commits;
    # stale writer ts 7 aborts
    txns = [[(5, "r")], [(5, "r")], [(5, "w")]]
    v, st, b = run("TIMESTAMP", txns, ts=[5, 15, 7], state=st)
    c, a, d = check_verdict(v, b, txns)
    assert a[0] and c[1] and a[2]


# ---- MVCC --------------------------------------------------------------

def test_mvcc_readonly_always_commits():
    be = get_backend("MVCC")
    st = be.init_state(CFG)
    v, st, _ = run("MVCC", [[(5, "w")]], ts=[10], state=st)
    # stale read-only txn commits under MVCC (old version), aborts under T/O
    v, st, b = run("MVCC", [[(5, "r")]], ts=[5], state=st)
    assert np.asarray(v.commit)[0]

def test_mvcc_rw_txn_still_validates():
    be = get_backend("MVCC")
    st = be.init_state(CFG)
    v, st, _ = run("MVCC", [[(5, "w")]], ts=[10], state=st)
    # RMW with stale ts aborts: it must read latest AND its write hits
    # the wts watermark (row_mvcc.cpp P_REQ conflict)
    v, st, b = run("MVCC", [[(5, "rw")]], ts=[7], state=st)
    assert np.asarray(v.abort)[0]


def test_mvcc_version_ring_serves_stale_read():
    """The round-1 divergence, fixed: a read-WRITE txn whose pure read
    hits ``wts > ts`` commits when the needed version is retained in the
    bounded history ring (reference serves the old version,
    row_mvcc.cpp:264-270) — under TIMESTAMP the same txn aborts."""
    be = get_backend("MVCC")
    st = be.init_state(CFG)
    v, st, _ = run("MVCC", [[(5, "w")]], ts=[10], state=st)
    txns = [[(5, "r"), (6, "w")]]          # stale read + fresh blind write
    v, st, b = run("MVCC", txns, ts=[7], state=st)
    assert np.asarray(v.commit)[0]
    # same interleaving under single-version T/O: abort
    be_to = get_backend("TIMESTAMP")
    st2 = be_to.init_state(CFG)
    v2, st2, _ = run("TIMESTAMP", [[(5, "w")]], ts=[10], state=st2)
    v2, st2, _ = run("TIMESTAMP", txns, ts=[7], state=st2)
    assert np.asarray(v2.abort)[0]


def test_mvcc_recycled_version_aborts():
    """Reads older than the retained history abort, mirroring
    HIS_RECYCLE_LEN garbage collection (row_mvcc.cpp:303-321): after
    mvcc_his_len version boundaries, the oldest retained boundary rises
    above a sufficiently stale reader's ts."""
    be = get_backend("MVCC")
    st = be.init_state(CFG)
    for wts in (10, 20, 30, 40):           # mvcc_his_len = 4 boundaries
        v, st, _ = run("MVCC", [[(5, "w")]], ts=[wts], state=st)
        assert np.asarray(v.commit)[0]
    # ring now [10, 20, 30, 40]: ts 5 predates every retained version
    v, _, _ = run("MVCC", [[(5, "r"), (6, "w")]], ts=[5], state=st)
    assert np.asarray(v.abort)[0]
    # ts 15 is covered by the ts-10 version: served, commits
    v, _, _ = run("MVCC", [[(5, "r"), (6, "w")]], ts=[15], state=st)
    assert np.asarray(v.commit)[0]


def test_mvcc_serves_historical_bytes():
    """Multi-version value oracle (VERDICT round-2 #3): a committed stale
    read must return the HISTORICAL bytes of the version current at its
    timestamp — matching serial execution value-for-value
    (`row_mvcc.cpp:172-196`) — while read-only snapshot txns read the
    live epoch-start state."""
    from deneva_tpu.config import WorkloadKind
    from deneva_tpu.engine.step import init_device_stats
    from deneva_tpu.workloads import get_workload
    from deneva_tpu.workloads.ycsb import (VER_TABLE, YCSBQuery,
                                           _field_fingerprint)

    cfg = Config(workload=WorkloadKind.YCSB, cc_alg=CCAlg.MVCC,
                 synth_table_size=1024, req_per_query=2, max_accesses=2,
                 epoch_batch=4, conflict_buckets=512,
                 max_txn_in_flight=4)
    wl = get_workload(cfg)
    db = wl.load()
    assert VER_TABLE in db, "MVCC must allocate the version-value ring"
    be = get_backend(CCAlg.MVCC)
    st = be.init_state(cfg)
    stats = init_device_stats(len(wl.txn_type_names))

    def epoch(db, st, stats, keys, is_write, ts):
        n = len(keys)
        q = YCSBQuery(keys=jnp.asarray(keys, jnp.int32),
                      is_write=jnp.asarray(is_write))
        p = wl.plan(db, q)
        batch = AccessBatch(
            table_ids=p["table_ids"], keys=p["keys"], is_read=p["is_read"],
            is_write=p["is_write"], valid=p["valid"],
            ts=jnp.asarray(ts, jnp.int32),
            rank=jnp.arange(n, dtype=jnp.int32),
            active=jnp.ones(n, bool))
        inc = build_incidence(batch, cfg.conflict_buckets, cfg.conflict_exact)
        v, st = be.validate(cfg, st, batch, inc)
        db = wl.execute(db, q, v.commit & batch.active, v.order, stats)
        return db, st, v, stats

    def f(key, ver):
        return int(np.asarray(_field_fingerprint(np.int32(key),
                                                 np.int32(ver))))

    def cks(stats):
        return int(np.asarray(stats["read_checksum"]))

    # epoch 1: blind write of key 5 at ts 10 -> value f(5, 10)
    db, st, v, stats = epoch(db, st, stats, [[5, 5]], [[True, True]], [10])
    assert np.asarray(v.commit)[0]
    # epoch 2: overwrite key 5 at ts 20 -> value f(5, 20); ring now holds
    # (wts=10, old=f(5,0)) and (wts=20, old=f(5,10))
    db, st, v, stats = epoch(db, st, stats, [[5, 5]], [[True, True]], [20])
    assert np.asarray(v.commit)[0]
    c0 = cks(stats)
    # epoch 3: three committed readers of key 5 —
    #   rw txn at ts 5   -> the pre-10 base version      f(5, 0)
    #   rw txn at ts 15  -> the version written at ts 10 f(5, 10)
    #   read-only txn    -> the live snapshot            f(5, 20) twice
    db, st, v, stats = epoch(
        db, st, stats,
        [[5, 7], [5, 9], [5, 5]],
        [[False, True], [False, True], [False, False]],
        [5, 15, 30])
    assert np.asarray(v.commit)[:3].all()
    got = (cks(stats) - c0) & 0xFFFFFFFF
    want = (f(5, 0) + f(5, 10) + 2 * f(5, 20)) & 0xFFFFFFFF
    assert got == want, f"stale reads returned wrong bytes: {got} != {want}"


# ---- MAAT --------------------------------------------------------------

def test_maat_reader_writer_any_rank_commit():
    # writer arrives first by rank; MAAT dynamically orders reader before it
    txns = [[(5, "w")], [(5, "r")]]
    v, _, b = run("MAAT", txns)
    c, a, d = check_verdict(v, b, txns)
    assert c.all()
    assert np.asarray(v.order)[1] < np.asarray(v.order)[0]

def test_maat_write_skew_cycle_aborts():
    txns = [[(1, "r"), (2, "w")], [(2, "r"), (1, "w")]]
    v, _, b = run("MAAT", txns)
    c, a, d = check_verdict(v, b, txns)
    assert a.any() and not c.all()

def test_maat_blind_ww_both_commit():
    txns = [[(5, "w")], [(5, "w")], [(5, "r")]]
    v, _, b = run("MAAT", txns)
    c, a, d = check_verdict(v, b, txns)
    assert c.all()

def test_maat_hot_key_rmw_clique_commits_winner():
    # round-2 liveness cliff (VERDICT r3 next #3): m txns RMW one hot
    # key form m*(m-1)/2 mutual pairs; the old fixed-budget cycle peel
    # aborted such cliques WHOLESALE — winners included — and MAAT
    # posted 0 txn/s on TPC-C warehouse rows.  The mutual-pair MIS
    # sweep must admit exactly the lex-first winner.
    m = 12
    txns = [[(7, "rw")] for _ in range(m)]
    v, _, b = run("MAAT", txns)
    c, a, d = check_verdict(v, b, txns)
    assert c[0] and c.sum() == 1
    assert a.sum() == m - 1 and d.sum() == 0

def test_maat_deep_acyclic_chain_commits_wholesale():
    # ADVICE r3 (medium): deep ACYCLIC chain middles used to be
    # misclassified as cycle members and aborted.  Cycle detection is
    # now self-reachability (exact) and acyclic order is ancestor count,
    # so a chain of ANY depth commits WHOLE — matching serial
    # validation, where real-valued ranges make any DAG feasible.
    cfg = CFG.replace(sweep_rounds=4)
    n = 16
    txns = [[(0, "r")]] + [[(i, "r"), (i - 1, "w")] for i in range(1, n)]
    v, _, b = run("MAAT", txns, cfg=cfg)
    c, a, d = check_verdict(v, b, txns)
    assert a.sum() == 0 and d.sum() == 0
    assert c.all()

def test_maat_cycle_peels_youngest_rest_commit():
    # pure 3-cycle (write-skew triangle, no mutual pairs): serial
    # validation commits the two earlier validators with a dynamic order
    # and closes only the latest one's range — the peel must abort
    # exactly the lex-youngest member, THIS epoch, no defers.
    txns = [[(10, "r"), (11, "w")],
            [(11, "r"), (12, "w")],
            [(12, "r"), (10, "w")]]
    v, _, b = run("MAAT", txns)
    c, a, d = check_verdict(v, b, txns)
    assert a.sum() == 1 and a[2]
    assert c[0] and c[1] and d.sum() == 0


# ---- CALVIN / TPU_BATCH ------------------------------------------------

@pytest.mark.parametrize("alg", ["CALVIN", "TPU_BATCH"])
def test_calvin_never_aborts_levels_chain(alg):
    txns = [[(5, "w")], [(5, "rw")], [(5, "r")], [(9, "r")]]
    v, _, b = run(alg, txns)
    c, a, d = check_verdict(v, b, txns, chained=True)
    assert not a.any()
    assert c.all()
    lv = np.asarray(v.level)
    assert lv[0] == 0 and lv[1] == 1 and lv[2] == 2 and lv[3] == 0

@pytest.mark.parametrize("alg", ["CALVIN", "TPU_BATCH"])
def test_calvin_deep_chain_defers_deterministically(alg):
    txns = [[(5, "rw")] for _ in range(10)]   # chain depth 10 > exec_subrounds
    v, _, b = run(alg, txns)
    c, a, d = check_verdict(v, b, txns, chained=True)
    assert not a.any()
    s = CFG.exec_subrounds
    assert c[:s].all() and d[s:].all()


# ---- NOCC + randomized cross-algorithm oracle --------------------------

def test_nocc_commits_everything():
    txns = [[(5, "w")], [(5, "w")], [(5, "rw")]]
    v, _, b = run("NOCC", txns)
    assert np.asarray(v.commit)[:3].all()

@pytest.mark.parametrize("alg", ["NO_WAIT", "WAIT_DIE", "OCC", "TIMESTAMP",
                                 "MVCC", "MAAT", "CALVIN", "TPU_BATCH",
                                 "DGCC"])
def test_randomized_serializability(alg):
    rng = np.random.default_rng(42)
    be = get_backend(alg)
    st = be.init_state(CFG)
    ts_base = 1
    for trial in range(6):
        txns = []
        for _ in range(12):
            script = []
            for _ in range(rng.integers(1, 5)):
                key = int(rng.integers(0, 8))       # tiny keyspace: hot
                mode = rng.choice(["r", "w", "rw"])
                script.append((key, mode))
            txns.append(script)
        ts = ts_base + rng.permutation(12).astype(np.int32)
        ts_base += 12
        v, st, b = run(alg, txns, state=st, ts=ts)
        check_verdict(v, b, txns, chained=be.chained)
        assert np.asarray(v.commit).sum() >= 1


# ---- isolation levels (reference config.h:102,337-340) -----------------

def _iso_cfg(level):
    return CFG.replace(isolation_level=level)


def test_isolation_serializable_reader_blocks_writer():
    # earlier pure reader of key 5 blocks a later writer under long locks
    v, _, _ = run("NO_WAIT", [[(5, "r")], [(5, "w")]])
    assert bool(v.commit[0]) and bool(v.abort[1])


@pytest.mark.parametrize("level", ["READ_COMMITTED", "READ_UNCOMMITTED"])
def test_isolation_relaxed_reader_does_not_block_writer(level):
    v, _, _ = run("NO_WAIT", [[(5, "r")], [(5, "w")]],
                  cfg=_iso_cfg(level))
    assert bool(v.commit[0]) and bool(v.commit[1])


def test_isolation_read_committed_reader_behind_writer_conflicts():
    # writer earlier in rank still holds the lock when the reader asks
    v, _, _ = run("NO_WAIT", [[(5, "w")], [(5, "r")]],
                  cfg=_iso_cfg("READ_COMMITTED"))
    assert bool(v.commit[0]) and bool(v.abort[1])


def test_isolation_read_uncommitted_only_ww_conflicts():
    v, _, _ = run("NO_WAIT", [[(5, "w")], [(5, "r")], [(5, "w")]],
                  cfg=_iso_cfg("READ_UNCOMMITTED"))
    assert bool(v.commit[0])
    assert bool(v.commit[1])      # read bypasses the lock table
    assert bool(v.abort[2])       # WW still conflicts


def test_isolation_nolock_commits_everything():
    v, _, _ = run("NO_WAIT", [[(5, "w")], [(5, "w")], [(5, "rw")]],
                  cfg=_iso_cfg("NOLOCK"))
    assert bool(np.asarray(v.commit)[:3].all())


def test_isolation_wait_die_relaxed_wait_rule_still_applies():
    # two writers, older arrives later in rank: waits instead of dying
    v, _, _ = run("WAIT_DIE", [[(5, "w")], [(5, "w")]],
                  ts=[2, 1], cfg=_iso_cfg("READ_UNCOMMITTED"))
    assert bool(v.commit[0]) and bool(v.defer[1])


def test_isolation_monotone_commit_counts():
    # same contended batch; commits must not decrease as isolation relaxes
    txns = [[(k % 3, "w" if i % 2 else "r")] for i, k in enumerate(range(8))]
    counts = []
    for lvl in ["SERIALIZABLE", "READ_COMMITTED", "READ_UNCOMMITTED", "NOLOCK"]:
        v, _, _ = run("NO_WAIT", txns, cfg=_iso_cfg(lvl))
        counts.append(int(np.asarray(v.commit).sum()))
    assert counts == sorted(counts)


# ---- distributed VOTE prepare classification ---------------------------

def test_mvcc_ro_hint_overrides_local_view():
    """VOTE-mode soundness: a cross-partition rw-txn whose writes live on
    another node must NOT take the read-only fast path locally — the
    global ro_hint (from the unmasked plan) forces read validation, so a
    recycled-version read still aborts (the review-found hole)."""
    import dataclasses
    be = get_backend("MVCC")
    st = be.init_state(CFG)
    for wts in (10, 20, 30, 40):
        v, st, _ = run("MVCC", [[(5, "w")]], ts=[wts], state=st)
    # locally: only the read of key 5 is owned (the write of key 6 is
    # masked invalid, as the vote prepare does for remote accesses)
    batch = make_batch([[(5, "r")]], ts=[5])
    batch = dataclasses.replace(batch,
                                ro_hint=jnp.zeros(CFG.epoch_batch, bool))
    inc = build_incidence(batch, CFG.conflict_buckets, CFG.conflict_exact)
    v, _ = be.validate(CFG, st, batch, inc)
    assert np.asarray(v.abort)[0]          # recycled version -> abort
    # without the hint the same local view looks read-only and commits
    batch2 = make_batch([[(5, "r")]], ts=[5])
    v2, _ = be.validate(CFG, st, batch2, inc)
    assert np.asarray(v2.commit)[0]


def test_to_watermark_width_no_false_aborts():
    """Wide watermark tables (watermark_buckets >> incidence buckets):
    uncontended TIMESTAMP traffic must not abort on bucket false sharing
    — the round-2 fidelity fix (the reference tracks per-row ts state;
    8k shared buckets at 32k accesses/epoch aborted >50% at theta=0)."""
    import jax
    from deneva_tpu.config import Config
    from deneva_tpu.engine import Engine
    from deneva_tpu.workloads import get_workload

    cfg = Config(cc_alg="TIMESTAMP", epoch_batch=256, conflict_buckets=512,
                 max_accesses=4, req_per_query=4, synth_table_size=1 << 16,
                 zipf_theta=0.0, max_txn_in_flight=1024)
    eng = Engine(cfg, get_workload(cfg))
    stats = jax.device_get(eng.jit_run(eng.init_state(seed=1), 30).stats)
    commits = int(stats["total_txn_commit_cnt"])
    aborts = int(stats["total_txn_abort_cnt"])
    assert commits > 0
    # uniform keys on 64k rows, 1k accesses/epoch, 1M watermark buckets:
    # real ts conflicts are rare and false sharing rarer
    assert aborts / max(commits + aborts, 1) < 0.05


def test_mvcc_value_ring_boundary_depth():
    """Round-5 review regression: the ts-only VersionRing must retain the
    FULL mvcc_his_len entries.  A servable read may have his_len-1
    overwrites postdating its ts (the decision ring's commit rule allows
    exactly that many), and the reconstruction reads the newest entry
    <= ts — one MORE retained entry than the old displaced-bytes ring
    needed.  With his_len=4: overwrites at ts 10/20/30/40, reader at 15
    commits and must see f(5, 10), not the load base f(5, 0)."""
    from deneva_tpu.config import WorkloadKind
    from deneva_tpu.engine.step import init_device_stats
    from deneva_tpu.workloads import get_workload
    from deneva_tpu.workloads.ycsb import (VER_TABLE, YCSBQuery,
                                           _field_fingerprint)

    cfg = Config(workload=WorkloadKind.YCSB, cc_alg=CCAlg.MVCC,
                 synth_table_size=1024, req_per_query=2, max_accesses=2,
                 epoch_batch=2, conflict_buckets=512,
                 max_txn_in_flight=2)
    wl = get_workload(cfg)
    db = wl.load()
    be = get_backend(CCAlg.MVCC)
    st = be.init_state(cfg)
    stats = init_device_stats(len(wl.txn_type_names))

    def epoch(db, st, stats, keys, is_write, ts):
        n = len(keys)
        q = YCSBQuery(keys=jnp.asarray(keys, jnp.int32),
                      is_write=jnp.asarray(is_write))
        p = wl.plan(db, q)
        batch = AccessBatch(
            table_ids=p["table_ids"], keys=p["keys"], is_read=p["is_read"],
            is_write=p["is_write"], valid=p["valid"],
            ts=jnp.asarray(ts, jnp.int32),
            rank=jnp.arange(n, dtype=jnp.int32),
            active=jnp.ones(n, bool))
        inc = build_incidence(batch, cfg.conflict_buckets, cfg.conflict_exact)
        v, st = be.validate(cfg, st, batch, inc)
        db = wl.execute(db, q, v.commit & batch.active, v.order, stats)
        return db, st, v, stats

    for wts in (10, 20, 30, 40):          # his_len=4 overwrites of key 5
        db, st, v, stats = epoch(db, st, stats, [[5, 5]],
                                 [[True, True]], [wts])
        assert np.asarray(v.commit)[0]
    c0 = int(np.asarray(stats["read_checksum"]))
    # reader at ts 15: 3 = his_len-1 overwrites (20/30/40) postdate it;
    # the needed v*=10 entry must still be retained
    db, st, v, stats = epoch(db, st, stats, [[5, 7]],
                             [[False, True]], [15])
    assert np.asarray(v.commit)[0], "decision ring must serve ts 15"
    got = (int(np.asarray(stats["read_checksum"])) - c0) & 0xFFFFFFFF
    want = int(np.asarray(_field_fingerprint(np.int32(5),
                                             np.int32(10))))
    assert got == want, f"boundary-depth read got {got} != f(5,10)={want}"


def test_timestamp_staleness_abort_after_queueing_age():
    """The theta=0.7-cliff mechanism, scripted (BASELINE round-5 note): a
    txn stamped at admission but validated epochs later aborts iff some
    NEWER-ts txn committed its key meanwhile — the cross-epoch watermark
    staleness term that lock backends don't have.  Epoch 1: writer W2
    (ts 20) commits key 5.  Epoch 2: aged reader R (ts 15, stamped before
    W2 but queued behind it) must watermark-abort its read of key 5,
    while a fresh reader (ts 30) sails through; same for writers."""
    be = get_backend("TIMESTAMP")
    st = be.init_state(CFG)
    v, st, _ = run("TIMESTAMP", [[(5, "w")]], ts=[20], state=st)
    assert np.asarray(v.commit)[0]
    # aged reader (15 < 20) + fresh reader (30 > 20), one epoch later
    v, st, _ = run("TIMESTAMP", [[(5, "r")], [(5, "r")]],
                   ts=[15, 30], state=st)
    assert np.asarray(v.abort)[0], "aged reader must hit wts>ts"
    assert np.asarray(v.commit)[1], "fresh reader unaffected"
    # aged writer aborts on BOTH watermarks; fresh writer commits
    v, st, _ = run("TIMESTAMP", [[(5, "w")], [(5, "w")]],
                   ts=[18, 40], state=st)
    assert np.asarray(v.abort)[0], "aged writer must hit wts>ts"
    assert np.asarray(v.commit)[1]
