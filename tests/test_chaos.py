"""Chaos harness tests (fault injection, idempotent admission, crash
recovery — SURVEY §5.3: the reference has no failure story at all).

Tier-1 layer: transport-level fault injection (drop/dup/jitter semantics,
seed determinism, protocol-traffic protection), the server's idempotent
admission unit, log truncation, and the short cluster scenarios
(lossy-net / dup-storm / jittery-net — each a real 2s1c cluster boot,
~12 s).  The long kill/recover soak is marked ``slow``.
"""

import threading
import time

import numpy as np
import pytest

from deneva_tpu.config import CCAlg, Config, WorkloadKind
from deneva_tpu.runtime.native import (FAULT_RTYPE_MASK, NativeTransport,
                                       ipc_endpoints)


def _mesh_pair(tag):
    eps = ipc_endpoints(2, tag)
    a = NativeTransport(0, eps, 2)
    b = NativeTransport(1, eps, 2)
    ta = threading.Thread(target=a.start)
    tb = threading.Thread(target=b.start)
    ta.start(); tb.start(); ta.join(); tb.join()
    return a, b


def _drain_all(tp, timeout_us=50_000):
    out = []
    while True:
        m = tp.recv(timeout_us)
        if m is None:
            return out
        out.append(m)
        timeout_us = 20_000


def test_fault_drop_is_seeded_and_bounded():
    """Seeded drops land near the configured probability, conservation
    holds (delivered + dropped == sent), and the same seed reproduces
    the identical drop pattern on a fresh transport."""
    a, b = _mesh_pair("chaos_drop")
    try:
        a.set_fault(drop_prob=0.3, seed=42)
        n = 1000
        for i in range(n):
            a.send(1, "CL_QRY_BATCH", bytes([i % 251]))
        a.flush()
        time.sleep(0.2)
        got = _drain_all(b)
        dropped = a.stats()["msg_dropped"]
        assert len(got) + dropped == n
        assert 0.2 * n < dropped < 0.4 * n
    finally:
        a.close(); b.close()

    # determinism: an unstarted transport still draws the fault stream
    # at enqueue time — same seed, same sends => same drop pattern
    def pattern(run):
        t = NativeTransport(0, ipc_endpoints(2, f"chaos_det{run}"), 2)
        try:
            t.set_fault(drop_prob=0.3, seed=42)
            outs = []
            for _ in range(200):
                before = t.stats()["msg_dropped"]
                t.send(1, "CL_QRY_BATCH", b"z")
                outs.append(t.stats()["msg_dropped"] > before)
            assert any(outs) and not all(outs)
            return outs
        finally:
            t.close()

    assert pattern(0) == pattern(1)


def test_fault_dup_duplicates_bytes_verbatim():
    a, b = _mesh_pair("chaos_dup")
    try:
        a.set_fault(dup_prob=0.5, seed=7)
        n = 400
        for i in range(n):
            a.send(1, "CL_QRY_BATCH", bytes([i % 256]))
        a.flush()
        time.sleep(0.2)
        got = _drain_all(b)
        dup = a.stats()["msg_dup"]
        assert 0.35 * n < dup < 0.65 * n
        assert len(got) == n + dup
        # every delivered frame is a byte-exact copy of a sent one, and
        # each original arrives at least once
        seen: dict[bytes, int] = {}
        for _, rtype, payload in got:
            assert rtype == "CL_QRY_BATCH"
            seen[payload] = seen.get(payload, 0) + 1
        for i in range(256):
            if i < n:
                assert seen.get(bytes([i % 256]), 0) >= 1
    finally:
        a.close(); b.close()


def test_fault_mask_protects_protocol_traffic():
    """EPOCH_BLOB / VOTE / LOG_MSG / SHUTDOWN are the commit protocol —
    they must pass untouched even at 99% drop on the eligible mask."""
    a, b = _mesh_pair("chaos_mask")
    try:
        a.set_fault(drop_prob=0.99, seed=3, rtype_mask=FAULT_RTYPE_MASK)
        for rtype in ("EPOCH_BLOB", "VOTE", "LOG_MSG", "SHUTDOWN",
                      "MEASURE", "INIT_DONE"):
            for _ in range(20):
                a.send(1, rtype, b"p")
        a.flush()
        time.sleep(0.2)
        got = _drain_all(b)
        assert len(got) == 6 * 20
        assert a.stats()["msg_dropped"] == 0
    finally:
        a.close(); b.close()


def test_fault_jitter_delays_but_delivers_everything():
    a, b = _mesh_pair("chaos_jit")
    try:
        a.set_fault(jitter_us=60_000, seed=11)
        n = 100
        t0 = time.monotonic()
        for i in range(n):
            a.send(1, "CL_RSP", bytes([i]))
        a.flush()
        got = []
        deadline = time.monotonic() + 2.0
        while len(got) < n and time.monotonic() < deadline:
            got.extend(_drain_all(b, timeout_us=20_000))
        spread = time.monotonic() - t0
        assert len(got) == n, "jitter must delay, never lose"
        assert a.stats()["msg_dropped"] == 0
        assert spread > 0.02, "uniform [0,60ms) jitter should spread arrivals"
    finally:
        a.close(); b.close()


# ---- server idempotent admission (unit) --------------------------------

def _solo_server(tag, **kw):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deneva_tpu.runtime.server import ServerNode

    base = dict(
        workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
        node_cnt=1, part_cnt=1, client_node_cnt=0,
        epoch_batch=32, conflict_buckets=256, synth_table_size=1024,
        req_per_query=2, max_accesses=2, warmup_secs=0.2, done_secs=0.5)
    base.update(kw)
    cfg = Config(**base)
    return ServerNode(cfg, ipc_endpoints(1, tag), "cpu")


def test_admit_dedup_blocks_dups_and_reacks_committed():
    """Idempotent admission: an in-system packed id is dropped, a
    committed one is re-acked (the lost-CL_RSP repair), and only fresh
    txns reach the pending queue."""
    from deneva_tpu.runtime import wire

    node = _solo_server("chaos_dedup", fault_dup_prob=0.01)
    try:
        assert node._dedup_on
        blk = wire.QueryBlock(
            keys=np.zeros((4, 2), np.int32),
            types=np.ones((4, 2), np.int8),
            scalars=np.zeros((4, 0), np.int32),
            tags=np.arange(4, dtype=np.int64))
        src = 0   # loopback: the solo mesh has one node, so re-acks
        #           come back on our own recv queue
        out = node._admit_dedup(src, blk)
        assert out is not None and len(out) == 4
        assert len(node._in_system) == 4
        # duplicate arrival: everything already in system -> dropped
        assert node._admit_dedup(src, blk) is None
        assert node._dup_admits == 4
        # same raw tags from ANOTHER client are distinct packed ids
        out2 = node._admit_dedup(2, blk)
        assert out2 is not None and len(out2) == 4
        # retire two tags as committed (packed ids), then re-offer all 4:
        # the two committed ones re-ack (to our own loopback), the two
        # still in-system drop
        packed = (np.int64(src) << 40) | blk.tags
        node._retire_dedup(packed[:2])
        assert len(node._committed_set) == 2
        assert node._admit_dedup(src, blk) is None
        assert node._reacks == 2
        m = node.tp.recv(200_000)
        assert m is not None and m[1] == "CL_RSP"
        assert (wire.decode_cl_rsp(m[2]) == blk.tags[:2]).all()
    finally:
        node.close()


def test_committed_ring_is_bounded():
    node = _solo_server("chaos_ring", fault_dup_prob=0.01)
    try:
        node._committed_cap = 8
        node._retire_dedup(np.arange(20, dtype=np.int64))
        assert len(node._committed_set) == 8
        assert len(node._committed_recent) == 8
        # oldest ids were evicted, newest kept
        assert 19 in node._committed_set and 0 not in node._committed_set
    finally:
        node.close()


def test_default_config_has_no_chaos_machinery():
    """The fault path is fully gated: a default config runs with dedup
    off, no kill point, no failover waits and no fault stats keys."""
    node = _solo_server("chaos_gate")
    try:
        assert not node._dedup_on and not node._failover
        assert node._kill_at is None
        assert node._resume_epoch == 0
    finally:
        node.close()


# ---- log truncation (recovery's crash-tail handling) -------------------

def test_truncate_log_to_epoch_drops_tail_and_torn_bytes(tmp_path):
    from deneva_tpu.runtime.logger import (iter_record_spans, pack_record,
                                           truncate_log_to_epoch,
                                           unpack_records)

    path = str(tmp_path / "trunc.log.bin")
    recs = [pack_record(e, f"blob{e}".encode(), np.ones(4, bool))
            for e in range(10)]
    with open(path, "wb") as f:
        for r in recs:
            f.write(r)
        f.write(recs[0][:7])   # torn tail from a mid-write crash
    spans = list(iter_record_spans(open(path, "rb").read()))
    assert [e for e, _, _ in spans] == list(range(10))
    last = truncate_log_to_epoch(path, 8)
    assert last == 7
    with open(path, "rb") as f:
        buf = f.read()
    assert [e for e, _, _ in unpack_records(buf)] == list(range(8))
    # idempotent: truncating again at the same epoch is a no-op
    assert truncate_log_to_epoch(path, 8) == 7
    assert open(path, "rb").read() == buf
    # truncating everything leaves an empty log
    assert truncate_log_to_epoch(path, 0) == -1
    assert open(path, "rb").read() == b""


# ---- short cluster scenarios (tier-1: each is a ~12 s 2s1c boot) -------

@pytest.mark.parametrize("scenario",
                         ["lossy-net", "dup-storm", "jittery-net"])
def test_chaos_scenario_short(scenario):
    """Deterministic seeded fault scenarios over a real 2-server +
    1-client IPC cluster: completes with every committed tag acked
    exactly once (no hang, no double-count), server commit counts
    identical.  run_scenario raises ChaosViolation on any breach."""
    from deneva_tpu.harness.chaos import run_scenario

    report = run_scenario(scenario, quick=True, quiet=True)
    assert report["commits"][0] == report["commits"][1] > 0
    assert all(a > 0 for a in report["client_acked"])


def test_audit_mutation_scenario_caught():
    """The isolation-audit anti-inert contract over a REAL cluster
    (the tools/smoke.sh ``audit`` gate's mutation half): the seeded
    occ-read-skip fault commits stale readers on epochs [48, 56) and
    the serializability certifier must reject the run with rw-anomaly
    witnesses naming epochs inside exactly that window.  (The clean
    half — certification of an unmutated run — already stands on every
    tier-1 short scenario above, whose configs arm audit=true.)"""
    from deneva_tpu.harness.chaos import run_scenario

    report = run_scenario("audit-mutation", quick=True, quiet=True)
    assert report["audit_ok"] is False
    assert report["audit_witness_epochs"]
    assert all(48 <= e < 56 for e in report["audit_witness_epochs"])
    assert report["audit_anomaly"] in ("G-single", "G2-item")


@pytest.mark.slow
def test_chaos_kill_one_server_recovers_by_replay():
    """The full failover soak: fault_kill crashes server 1 at an epoch
    boundary; the launcher restarts it in recovery mode; it truncates +
    replays its command log, rejoins the mesh (transport redial, blob
    resend, replica resync) and the run completes.  Safety: recovered
    state is bit-identical to an independent replay of the same log
    prefix, logs stay epoch-contiguous, replica logs stay byte
    prefixes.  Runs with owner_check=true: the thread-ownership runtime
    asserts (runtime/ownercheck.py, the graftlint `own` family's dynamic
    half) are armed for the whole kill/recover/rejoin path."""
    from deneva_tpu.harness.chaos import run_scenario

    report = run_scenario("kill-one-server", quiet=True, owner_check=True)
    assert report["digest_match"]
    assert report["replica_prefix_ok"]
    assert report["resume_epoch"] > 0
    assert all(a > 0 for a in report["client_acked"])
