"""Experiment harness tests (SURVEY §2.9): experiment map, runner output
files, `[summary]` parsing round-trip."""

import pytest

from deneva_tpu.config import CCAlg, Config, Mode
from deneva_tpu.harness import (experiment_map, get_experiment, load_results,
                                outfile_name, parse_file, results_table)
from deneva_tpu.harness.run import run_point


def test_experiment_map_builds_valid_configs():
    for name in experiment_map:
        cfgs = get_experiment(name, quick=True)
        assert cfgs, name
        for cfg in cfgs:
            assert isinstance(cfg, Config)
            cfg.validate()


def test_experiment_sweeps_cover_paper_axes():
    skew = get_experiment("ycsb_skew", quick=True)
    assert {c.zipf_theta for c in skew} == {0.0, 0.6, 0.9}
    algs = {c.cc_alg for c in skew}
    assert {CCAlg.NO_WAIT, CCAlg.WAIT_DIE, CCAlg.TIMESTAMP, CCAlg.MVCC,
            CCAlg.OCC, CCAlg.MAAT, CCAlg.CALVIN, CCAlg.TPU_BATCH} <= algs
    iso = get_experiment("isolation_levels", quick=True)
    assert {c.isolation_level for c in iso} == {
        "SERIALIZABLE", "READ_COMMITTED", "READ_UNCOMMITTED", "NOLOCK"}
    scaling = get_experiment("ycsb_scaling", quick=True)
    by_part = {c.part_cnt for c in scaling}
    assert by_part == {1, 2, 4}
    # table grows with part count like the reference's 16M rows/node
    one = next(c for c in scaling if c.part_cnt == 1)
    four = next(c for c in scaling if c.part_cnt == 4)
    assert four.synth_table_size == 4 * one.synth_table_size


def test_outfile_name_encodes_sweep_fields():
    cfg = Config(zipf_theta=0.9, cc_alg=CCAlg.OCC)
    name = outfile_name(cfg)
    assert name.startswith("YCSB_OCC") and "SKEW-0.9" in name
    assert name != outfile_name(cfg.replace(zipf_theta=0.8))
    # fields outside SHORTNAMES must still change the name (hash suffix)
    assert name != outfile_name(cfg.replace(seed=7))
    assert name != outfile_name(cfg.replace(synth_table_size=1 << 10))


@pytest.mark.slow
def test_run_point_and_parse_roundtrip(tmp_path):
    cfg = Config(
        workload="YCSB", cc_alg=CCAlg.TPU_BATCH, mode=Mode.NORMAL,
        synth_table_size=1 << 12, epoch_batch=64, conflict_buckets=256,
        max_txn_in_flight=256, req_per_query=4, max_accesses=4,
        warmup_secs=0.1, done_secs=0.3)
    path = run_point(cfg, str(tmp_path))
    fields = parse_file(path)
    assert fields is not None and fields["total_txn_commit_cnt"] > 0
    # per-txn latency ledger (VERDICT r3 next #6): the [summary] carries
    # real per-type percentile families, wall-clock calibrated per
    # chunk, plus the TxnStats-style restart/wait decomposition
    assert fields["ycsb_rw_latency_p50"] > 0
    assert fields["ycsb_rw_latency_p99"] >= fields["ycsb_rw_latency_p50"]
    # every committed txn contributes a restart/wait sample (all-zero
    # for TPU_BATCH, which never aborts — but the family must exist)
    assert fields["txn_retries_p99"] == 0 and fields["txn_waits_p99"] == 0
    rows = load_results(str(tmp_path))
    assert len(rows) == 1
    row = rows[0]
    # config echo merged in
    assert row["cc_alg"] == "TPU_BATCH" and row["epoch_batch"] == 64
    assert row["tput"] > 0
    table = results_table(str(tmp_path), x="zipf_theta")
    assert "TPU_BATCH" in table
    x, y = table["TPU_BATCH"][0]
    assert x == 0.6 and y == row["tput"]


def test_parse_file_none_when_no_summary(tmp_path):
    p = tmp_path / "x.out"
    p.write_text("# cfg cc_alg=OCC\n# run failed\n")
    assert parse_file(str(p)) is None
    rows = load_results(str(tmp_path))
    assert rows[0]["cc_alg"] == "OCC" and "tput" not in rows[0]


@pytest.mark.slow
def test_plot_renders_pivot(tmp_path):
    from deneva_tpu.harness.plot import render
    from deneva_tpu.harness.run import run_point
    from deneva_tpu.config import Config
    for theta in (0.0, 0.9):
        run_point(Config(cc_alg="OCC", epoch_batch=64, conflict_buckets=256,
                         max_accesses=4, req_per_query=4,
                         synth_table_size=1024, max_txn_in_flight=128,
                         zipf_theta=theta, warmup_secs=0.0, done_secs=0.2),
                  str(tmp_path))
    out = render(str(tmp_path), x="zipf_theta", y="tput", series="cc_alg")
    assert "OCC" in out and "0.9" in out
    tsv = render(str(tmp_path), x="zipf_theta", y="tput", series="cc_alg",
                 tsv=True)
    assert "\t" in tsv


def test_timeline_parse_and_render(tmp_path):
    """`scripts/timeline.py` analogue: aggregate [timeline] phase lines."""
    from deneva_tpu.harness.timeline import parse_timeline, phase_table, render

    log = tmp_path / "run.log"
    log.write_text(
        "noise\n"
        "[timeline] node=0 epoch=1 loop=1.0ms respond=3.0ms\n"
        "[timeline] node=0 epoch=2 loop=2.0ms respond=1.0ms\n"
        "[timeline] node=1 epoch=1 loop=4.0ms\n")
    rows = parse_timeline(log.read_text().splitlines())
    assert len(rows) == 3 and rows[0]["phases"] == {"loop": 1.0, "respond": 3.0}
    tab = phase_table(rows)
    by = {(r[0], r[1]): r for r in tab[1:]}
    assert by[("0", "loop")][3] == "3.0"        # total ms
    assert by[("0", "loop")][6] == "42.9%"      # 3 of 7ms on node 0
    assert by[("1", "loop")][2] == "1"          # epochs seen
    out = render(tab)
    assert "share" in out.splitlines()[0]
    assert render(phase_table([])).startswith("(no [timeline]")
    # node filter
    assert all(r[0] == "1" for r in phase_table(rows, node=1)[1:])


def test_timeline_chrome_trace_export(tmp_path):
    """--trace: [timeline] spans export as Chrome-trace complete events
    — one process track per node, per-node running clock, epoch in the
    args — so cutover/migration stalls are visible on a real timeline."""
    import json

    from deneva_tpu.harness.timeline import chrome_trace, main, \
        parse_timeline

    lines = ["[timeline] node=0 epoch=1 loop=1.0ms admit=2.0ms\n",
             "[timeline] node=0 epoch=2 loop=0.5ms membership=12.0ms\n",
             "[timeline] node=1 epoch=1 loop=4.0ms\n"]
    trace = chrome_trace(parse_timeline(lines))
    ev = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert {m["pid"] for m in meta} == {0, 1}
    # node 0's clock runs: loop@0 (1ms), admit@1000us (2ms), then epoch 2
    # continues at 3000us
    n0 = [e for e in ev if e["pid"] == 0]
    assert [e["name"] for e in n0] == ["loop", "admit", "loop",
                                      "membership"]
    assert n0[0]["ts"] == 0 and n0[1]["ts"] == 1000.0
    assert n0[2]["ts"] == 3000.0 and n0[3]["dur"] == 12000.0
    assert n0[3]["args"]["epoch"] == 2
    # node 1 has its own track starting at 0
    assert [e["ts"] for e in ev if e["pid"] == 1] == [0]
    # CLI round trip writes valid JSON
    log = tmp_path / "run.log"
    log.write_text("".join(lines))
    out = tmp_path / "trace.json"
    assert main([str(log), "--trace", str(out)]) == 0
    with open(out) as f:
        loaded = json.load(f)
    assert loaded["traceEvents"] and loaded["displayTimeUnit"] == "ms"


def test_parse_tolerates_membership_lines(tmp_path):
    """Forward/backward compat (membership [summary]/[membership]
    satellite): old logs (no membership lines) still parse, and new logs
    with [membership] lines neither crash nor perturb the summary,
    timeline, or cfg-echo parsers."""
    from deneva_tpu.harness.parse import parse_file, parse_membership
    from deneva_tpu.harness.timeline import parse_timeline

    new_log = tmp_path / "new.out"
    new_log.write_text(
        "# cfg node_cnt=3\n"
        "[membership] node=0 version=1 epoch=40 reason=grow subject=2 "
        "slots_moved=85 owned=85 rows_in=0 rows_out=688 stall_ms=112.9\n"
        "[timeline] node=0 epoch=41 loop=1.0ms membership=112.9ms\n"
        "[summary] total_runtime=1.5,tput=100,txn_cnt=150,"
        "rebalance_cnt=1,rows_migrated=688,cutover_stall_ms=112.9,"
        "redirect_resend_cnt=0\n")
    row = parse_file(str(new_log))
    assert row["tput"] == 100 and row["rebalance_cnt"] == 1
    assert row["rows_migrated"] == 688 and row["cutover_stall_ms"] == 112.9
    text = new_log.read_text().splitlines()
    mem = parse_membership(text)
    assert len(mem) == 1 and mem[0]["reason"] == "grow"
    assert len(parse_timeline(text)) == 1   # [membership] didn't confuse it
    # old log: no membership lines anywhere -> [] and unchanged parsing
    old_log = tmp_path / "old.out"
    old_log.write_text("# cfg node_cnt=2\n[summary] total_runtime=1,tput=5\n")
    assert parse_membership(old_log.read_text().splitlines()) == []
    assert parse_file(str(old_log))["tput"] == 5


def test_parse_replication_forward_backward_compat(tmp_path):
    """[replication] summary lines (geo tier satellite): primaries and
    followers each carry their own key set, old logs yield [], and the
    new lines perturb no other parser."""
    from deneva_tpu.harness.parse import parse_file, parse_membership, \
        parse_replication
    from deneva_tpu.harness.timeline import parse_timeline

    new_log = tmp_path / "geo.out"
    new_log.write_text(
        "# cfg node_cnt=2\n"
        "[replication] node=0 role=primary region=0 quorum=1 "
        "quorum_acked=118 repl_applied_min=112 quorum_stall_ms=41.5 "
        "promote_cnt=1\n"
        "[replication] node=4 role=follower region=1 primary=0 "
        "applied_epoch=118 follower_read_cnt=2048 "
        "stale_read_max_epochs=9 follower_read_ms=12.0 apply_ms=310.2\n"
        "[timeline] node=0 epoch=120 loop=1.0ms quorum=41.5ms\n"
        "[summary] total_runtime=2,tput=50,txn_cnt=100,"
        "quorum_stall_ms=41.5,promote_cnt=1\n")
    rows = parse_replication(new_log.read_text().splitlines())
    assert len(rows) == 2
    prim, fol = rows
    assert prim["role"] == "primary" and prim["quorum_stall_ms"] == 41.5
    assert prim["promote_cnt"] == 1
    assert fol["role"] == "follower" and fol["follower_read_cnt"] == 2048
    assert fol["stale_read_max_epochs"] == 9 and fol["applied_epoch"] == 118
    # other parsers ignore the new lines entirely
    row = parse_file(str(new_log))
    assert row["tput"] == 50 and row["quorum_stall_ms"] == 41.5
    assert parse_membership(new_log.read_text().splitlines()) == []
    assert len(parse_timeline(new_log.read_text().splitlines())) == 1
    # old log: no replication lines -> []
    old_log = tmp_path / "old.out"
    old_log.write_text("# cfg node_cnt=2\n[summary] total_runtime=1,tput=5\n")
    assert parse_replication(old_log.read_text().splitlines()) == []


def test_timeline_chrome_trace_replication_track(tmp_path):
    """Replication spans (quorum wait, follower-read serve, failover
    promote, group apply) export on a separate per-node "replication"
    thread track: latency ledgers drawn beside the phase clock, never
    inside it."""
    from deneva_tpu.harness.timeline import chrome_trace, parse_timeline

    lines = [
        "[timeline] node=0 epoch=8 loop=1.0ms admit=2.0ms quorum=40.0ms\n",
        "[timeline] node=0 epoch=16 loop=1.0ms promote=900.0ms\n",
        "[timeline] node=4 epoch=8 apply=12.0ms follower_read=3.0ms\n",
        # all-zero spans must still name the track (idle follower)
        "[timeline] node=5 epoch=8 apply=0.0ms follower_read=0.0ms\n",
    ]
    trace = chrome_trace(parse_timeline(lines))
    ev = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    phase = [e for e in ev if e["tid"] == 0]
    repl = [e for e in ev if e["tid"] == 1]
    # phase track clock is untouched by the replication spans
    n0 = [e for e in phase if e["pid"] == 0]
    assert [e["name"] for e in n0] == ["loop", "admit", "loop"]
    assert n0[2]["ts"] == 3000.0          # 1ms + 2ms, no 40ms gap
    # replication track has its own running clock and category
    r0 = [e for e in repl if e["pid"] == 0]
    assert [e["name"] for e in r0] == ["quorum", "promote"]
    assert r0[0]["ts"] == 0 and r0[1]["ts"] == 40000.0
    assert all(e["cat"] == "replication" for e in repl)
    # follower-side spans ride the same mechanism
    assert [e["name"] for e in repl if e["pid"] == 4] \
        == ["apply", "follower_read"]
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert {m["args"]["name"] for m in meta if m["tid"] == 1} \
        == {"replication"}
    # every node with tid-1 events gets a named track — including node
    # 5, whose spans are all zero-duration
    assert {m["pid"] for m in meta if m["tid"] == 1} == {0, 4, 5}


def test_parse_admission_forward_backward_compat(tmp_path):
    """[admission] lines (overload tier satellite): per-tenant rows plus
    a tenant=-1 node aggregate with queue-delay quantiles; old logs
    yield [], and the new lines perturb no other parser."""
    from deneva_tpu.harness.parse import (parse_admission, parse_file,
                                          parse_membership,
                                          parse_replication)
    from deneva_tpu.harness.timeline import parse_timeline

    new_log = tmp_path / "overload.out"
    new_log.write_text(
        "# cfg node_cnt=2\n"
        "[admission] node=0 tenant=-1 admitted=9000 nacked=1200 shed=300 "
        "qdelay_p50_ms=4.100 qdelay_p95_ms=18.000 qdelay_p99_ms=31.500 "
        "depth_max=4096 breach_groups=3\n"
        "[admission] node=0 tenant=0 admitted=6000 nacked=10 shed=0\n"
        "[admission] node=0 tenant=1 admitted=3000 nacked=1190 shed=300\n"
        "[timeline] node=0 epoch=64 loop=1.0ms adm_wait=31.5ms\n"
        "[summary] total_runtime=2,tput=70,txn_cnt=140,"
        "adm_admit_cnt=9000,adm_nack_cnt=1200,adm_shed_cnt=300,"
        "adm_queue_depth_max=4096\n")
    rows = parse_admission(new_log.read_text().splitlines())
    assert len(rows) == 3
    agg, t0, t1 = rows
    assert agg["tenant"] == -1 and agg["qdelay_p99_ms"] == 31.5
    assert agg["depth_max"] == 4096 and agg["breach_groups"] == 3
    assert t0["tenant"] == 0 and t0["shed"] == 0
    assert t1["tenant"] == 1 and t1["nacked"] == 1190
    # other parsers ignore the new lines entirely
    row = parse_file(str(new_log))
    assert row["tput"] == 70 and row["adm_nack_cnt"] == 1200
    text = new_log.read_text().splitlines()
    assert parse_membership(text) == []
    assert parse_replication(text) == []
    assert len(parse_timeline(text)) == 1
    # old log: no admission lines -> [] and unchanged parsing
    old_log = tmp_path / "old.out"
    old_log.write_text("# cfg node_cnt=2\n[summary] total_runtime=1,tput=5\n")
    assert parse_admission(old_log.read_text().splitlines()) == []
    assert parse_file(str(old_log))["tput"] == 5


def test_parse_repair_forward_backward_compat(tmp_path):
    """[repair] lines (transaction-repair satellite): per-node salvage
    accounting; old logs yield [], the new lines perturb no other
    parser, and the [summary] rep_* fields parse through the standard
    summary path with abort semantics preserved (salvaged txns are NOT
    in total_txn_abort_cnt — rep_salvaged_cnt carries them)."""
    from deneva_tpu.harness.parse import (parse_admission, parse_file,
                                          parse_membership, parse_repair,
                                          parse_replication)
    from deneva_tpu.harness.timeline import parse_timeline

    new_log = tmp_path / "repair.out"
    new_log.write_text(
        "# cfg node_cnt=2\n"
        "[repair] node=0 salvaged=1750 frontier=4196 fallback=11544 "
        "rounds=2 plane_cnt=1422\n"
        "[timeline] node=0 epoch=64 loop=1.0ms repair=0.2ms\n"
        "[summary] total_runtime=2,tput=1800,txn_cnt=3600,"
        "total_txn_commit_cnt=3600,total_txn_abort_cnt=11544,"
        "rep_salvaged_cnt=1750,rep_frontier_cnt=4196,"
        "rep_fallback_cnt=11544\n")
    rows = parse_repair(new_log.read_text().splitlines())
    assert len(rows) == 1
    r = rows[0]
    assert r["node"] == 0 and r["salvaged"] == 1750
    assert r["fallback"] == 11544 and r["rounds"] == 2
    assert r["plane_cnt"] == 1422
    # abort-semantics contract: fallbacks ARE the aborts, salvage rides
    # its own counter — a pre-repair consumer reading abort_rate sees
    # retry-queue behavior unchanged
    row = parse_file(str(new_log))
    assert row["total_txn_abort_cnt"] == row["rep_fallback_cnt"]
    assert row["rep_salvaged_cnt"] == 1750
    # other parsers ignore the new lines entirely
    text = new_log.read_text().splitlines()
    assert parse_membership(text) == []
    assert parse_replication(text) == []
    assert parse_admission(text) == []
    assert len(parse_timeline(text)) == 1
    # old log: no repair lines -> [] and unchanged parsing
    old_log = tmp_path / "old.out"
    old_log.write_text("# cfg node_cnt=2\n[summary] total_runtime=1,tput=5\n")
    assert parse_repair(old_log.read_text().splitlines()) == []
    assert parse_file(str(old_log))["tput"] == 5


def test_parse_audit_forward_backward_compat(tmp_path):
    """[audit] lines (isolation-audit satellite): per-node export
    accounting; old logs yield [], the new lines perturb no other
    parser, and the [summary] audit_* fields (incl. the anti-inert
    audit_edges_exported the regression gate reads) parse through the
    standard summary path."""
    from deneva_tpu.harness.parse import (parse_audit, parse_file,
                                          parse_membership,
                                          parse_metrics, parse_repair,
                                          parse_replication)
    from deneva_tpu.harness.timeline import parse_timeline

    new_log = tmp_path / "audit.out"
    new_log.write_text(
        "# cfg node_cnt=2\n"
        "[audit] node=0 epochs=412 edges=3180 edge_lanes=3991 "
        "dropped=0 cadence=1 export_ms=41.7\n"
        "[timeline] node=0 epoch=64 loop=1.0ms audit=0.3ms\n"
        "[summary] total_runtime=2,tput=1800,txn_cnt=3600,"
        "total_txn_commit_cnt=3600,audit_edge_cnt=3991,"
        "audit_drop_cnt=0,audit_edges_exported=3180,"
        "audit_epochs_exported=412,audit_edges_dropped=0\n")
    rows = parse_audit(new_log.read_text().splitlines())
    assert len(rows) == 1
    r = rows[0]
    assert r["node"] == 0 and r["epochs"] == 412
    assert r["edges"] == 3180 and r["dropped"] == 0
    assert r["export_ms"] == 41.7
    row = parse_file(str(new_log))
    assert row["audit_edges_exported"] == 3180
    assert row["audit_edges_dropped"] == 0
    # other parsers ignore the new lines entirely
    text = new_log.read_text().splitlines()
    assert parse_membership(text) == []
    assert parse_replication(text) == []
    assert parse_repair(text) == []
    assert parse_metrics(text) == []
    assert len(parse_timeline(text)) == 1
    # the "audit" timeline span lands on the declared tid-6 track
    from deneva_tpu.harness.timeline import AUDIT_TRACK, SPAN_TRACK
    assert SPAN_TRACK["audit"] is AUDIT_TRACK
    assert AUDIT_TRACK.tid == 6
    # old log: no audit lines -> [] and unchanged parsing
    old_log = tmp_path / "old.out"
    old_log.write_text("# cfg node_cnt=2\n[summary] total_runtime=1,tput=5\n")
    assert parse_audit(old_log.read_text().splitlines()) == []
    assert parse_file(str(old_log))["tput"] == 5


def test_parse_fencing_forward_backward_compat(tmp_path):
    """[fencing] lines (partition-tolerance satellite): per-node
    suspicion/fence/heal accounting, including a fenced node's
    self_halt=1 final line; old logs yield [], the new lines perturb
    no other parser, and the [summary] fencing fields parse through
    the standard summary path."""
    from deneva_tpu.harness.parse import (parse_admission, parse_fencing,
                                          parse_file, parse_membership,
                                          parse_repair, parse_replication)
    from deneva_tpu.harness.timeline import parse_timeline

    new_log = tmp_path / "fencing.out"
    new_log.write_text(
        "# cfg node_cnt=3\n"
        "[fencing] node=2 phi_peak=54.26 suspect_cnt=2 fence_nack_cnt=1 "
        "fence_nack_rx=0 self_halt=1 heal_cnt=0 reassign_epoch=752 "
        "last_acked_epoch=732 reason=minority epoch=752\n"
        "[fencing] node=0 phi_peak=8.70 suspect_cnt=1 fence_nack_cnt=1 "
        "fence_nack_rx=0 self_halt=0 heal_cnt=0 reassign_epoch=752 "
        "last_acked_epoch=767\n"
        "[timeline] node=0 epoch=760 loop=1.0ms suspect=2100.0ms\n"
        "[summary] total_runtime=10,tput=6000,txn_cnt=60000,"
        "fence_nack_cnt=1,suspect_cnt=1,heal_cnt=0,phi_peak=8.7,"
        "fence_reassign_epoch=752\n")
    rows = parse_fencing(new_log.read_text().splitlines())
    assert len(rows) == 2
    halted = rows[0]
    assert halted["node"] == 2 and halted["self_halt"] == 1
    assert halted["reason"] == "minority" and halted["phi_peak"] == 54.26
    assert halted["last_acked_epoch"] == 732
    assert rows[1]["self_halt"] == 0 and rows[1]["suspect_cnt"] == 1
    row = parse_file(str(new_log))
    assert row["fence_nack_cnt"] == 1 and row["phi_peak"] == 8.7
    assert row["fence_reassign_epoch"] == 752
    # other parsers ignore the new lines entirely
    text = new_log.read_text().splitlines()
    assert parse_membership(text) == []
    assert parse_replication(text) == []
    assert parse_admission(text) == []
    assert parse_repair(text) == []
    assert len(parse_timeline(text)) == 1
    # old log: no fencing lines -> [] and unchanged parsing
    old_log = tmp_path / "old.out"
    old_log.write_text("# cfg node_cnt=2\n[summary] total_runtime=1,tput=5\n")
    assert parse_fencing(old_log.read_text().splitlines()) == []
    assert parse_file(str(old_log))["tput"] == 5


def test_timeline_chrome_trace_fencing_track(tmp_path):
    """Fencing spans (suspicion windows, heal gaps, fence rejections)
    export on their own per-node "fencing" thread track (tid 3), beside
    — never inside — the phase/replication/admission clocks."""
    from deneva_tpu.harness.timeline import chrome_trace, parse_timeline

    lines = [
        "[timeline] node=0 epoch=8 loop=1.0ms suspect=2100.0ms\n",
        "[timeline] node=0 epoch=16 loop=1.0ms heal=1200.0ms "
        "adm_wait=5.0ms\n",
    ]
    trace = chrome_trace(parse_timeline(lines))
    ev = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    phase = [e for e in ev if e["tid"] == 0]
    fen = [e for e in ev if e["tid"] == 3]
    # phase clock untouched by the fencing (and admission) spans
    assert [e["name"] for e in phase] == ["loop", "loop"]
    assert phase[1]["ts"] == 1000.0
    # fencing track has its own running clock and category
    assert [e["name"] for e in fen] == ["suspect", "heal"]
    assert fen[0]["ts"] == 0 and fen[1]["ts"] == 2100000.0
    assert all(e["cat"] == "fencing" for e in fen)
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert {m["args"]["name"] for m in meta if m["tid"] == 3} \
        == {"fencing"}


def test_timeline_chrome_trace_admission_track(tmp_path):
    """Admission spans (per-group max queue delay) export on their own
    per-node "admission" thread track (tid 2), beside — never inside —
    the phase and replication clocks."""
    from deneva_tpu.harness.timeline import chrome_trace, parse_timeline

    lines = [
        "[timeline] node=0 epoch=8 loop=1.0ms admit=2.0ms adm_wait=25.0ms\n",
        "[timeline] node=0 epoch=16 loop=1.0ms adm_wait=40.0ms "
        "quorum=5.0ms\n",
    ]
    trace = chrome_trace(parse_timeline(lines))
    ev = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    phase = [e for e in ev if e["tid"] == 0]
    adm = [e for e in ev if e["tid"] == 2]
    # phase clock untouched by the admission (and replication) spans
    assert [e["name"] for e in phase] == ["loop", "admit", "loop"]
    assert phase[2]["ts"] == 3000.0
    # admission track has its own running clock and category
    assert [e["name"] for e in adm] == ["adm_wait", "adm_wait"]
    assert adm[0]["ts"] == 0 and adm[1]["ts"] == 25000.0
    assert all(e["cat"] == "admission" for e in adm)
    # replication spans still land on tid 1
    assert [e["name"] for e in ev if e["tid"] == 1] == ["quorum"]
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert {m["args"]["name"] for m in meta if m["tid"] == 2} \
        == {"admission"}


def test_parse_telemetry_forward_backward_compat(tmp_path):
    """[telemetry] lines (flight-recorder satellite): per-node sampling
    health accounting from every node kind; old logs yield [], the new
    lines perturb no other parser, and the [summary] telemetry fields
    parse through the standard summary path."""
    from deneva_tpu.harness.parse import (parse_admission, parse_fencing,
                                          parse_file, parse_membership,
                                          parse_repair, parse_replication,
                                          parse_telemetry)
    from deneva_tpu.harness.timeline import parse_timeline

    new_log = tmp_path / "telemetry.out"
    new_log.write_text(
        "# cfg node_cnt=2\n"
        "[telemetry] node=0 sampled_cnt=23304 dropped_cnt=0 "
        "ring_highwater=23304 flush_ms=1.466 sample=1024\n"
        "[telemetry] node=2 sampled_cnt=18816 dropped_cnt=3 "
        "ring_highwater=32768 flush_ms=0.42 sample=1024\n"
        "[summary] total_runtime=2,tput=29588,txn_cnt=59328,"
        "tel_sampled_cnt=23304,tel_dropped_cnt=0,"
        "tel_ring_highwater=23304,tel_flush_ms=1.466,metrics_lines=720\n")
    rows = parse_telemetry(new_log.read_text().splitlines())
    assert len(rows) == 2
    assert rows[0]["node"] == 0 and rows[0]["sampled_cnt"] == 23304
    assert rows[0]["flush_ms"] == 1.466 and rows[0]["sample"] == 1024
    assert rows[1]["dropped_cnt"] == 3 and rows[1]["ring_highwater"] == 32768
    row = parse_file(str(new_log))
    assert row["tel_sampled_cnt"] == 23304 and row["metrics_lines"] == 720
    # other parsers ignore the new lines entirely
    text = new_log.read_text().splitlines()
    assert parse_membership(text) == []
    assert parse_replication(text) == []
    assert parse_admission(text) == []
    assert parse_repair(text) == []
    assert parse_fencing(text) == []
    assert parse_timeline(text) == []
    # old log: no telemetry lines -> [] and unchanged parsing
    old_log = tmp_path / "old.out"
    old_log.write_text("# cfg node_cnt=2\n[summary] total_runtime=1,tput=5\n")
    assert parse_telemetry(old_log.read_text().splitlines()) == []
    assert parse_file(str(old_log))["tput"] == 5


def test_parse_metrics_forward_backward_compat(tmp_path):
    """[crit]/[watch] lines (metrics-bus satellite): critical-path
    attribution + anomaly watchdog events via the shared _parse_tagged
    body; old logs yield [], the new lines perturb no other parser, and
    the [summary] bus fields parse through the standard summary path."""
    from deneva_tpu.harness.parse import (parse_admission, parse_fencing,
                                          parse_file, parse_membership,
                                          parse_metrics, parse_repair,
                                          parse_replication,
                                          parse_telemetry)
    from deneva_tpu.harness.timeline import parse_timeline

    new_log = tmp_path / "metricsbus.out"
    new_log.write_text(
        "# cfg node_cnt=3\n"
        "[crit] node=0 epoch=96 admit_ms=3.1 wire_ms=41.7 device_ms=9.2 "
        "retire_ms=2.4 other_ms=1.1 quorum_ms=0.0 wall_ms=57.5 "
        "gate=wire\n"
        "[watch] node=0 kind=straggler subject=1 lag_ms=1480.2 "
        "cluster_ms=2.1 epoch=211\n"
        "[watch] node=0 kind=jit_recompile subject=2 device_ms=912.0 "
        "median_ms=8.4 epoch=340\n"
        "[summary] total_runtime=2,tput=29588,txn_cnt=59328,"
        "mb_frames_sent=720,mb_frames_rx=2103,mb_crit_cnt=9,"
        "mb_watch_cnt=3,mb_density_p0=4412,mb_density_p1=391\n")
    rows = parse_metrics(new_log.read_text().splitlines())
    assert len(rows) == 3
    crit = [r for r in rows if r["family"] == "crit"]
    watch = [r for r in rows if r["family"] == "watch"]
    assert crit[0]["gate"] == "wire" and crit[0]["wall_ms"] == 57.5
    # the attribution contract: wall stages sum to wall_ms (within 5%)
    stages = sum(crit[0][s + "_ms"] for s in
                 ("admit", "wire", "device", "retire", "other"))
    assert abs(stages - crit[0]["wall_ms"]) <= 0.05 * crit[0]["wall_ms"]
    assert {w["kind"] for w in watch} == {"straggler", "jit_recompile"}
    assert watch[0]["subject"] == 1 and watch[0]["lag_ms"] == 1480.2
    row = parse_file(str(new_log))
    assert row["mb_frames_sent"] == 720 and row["mb_density_p0"] == 4412
    # other parsers ignore the new lines entirely
    text = new_log.read_text().splitlines()
    assert parse_membership(text) == []
    assert parse_replication(text) == []
    assert parse_admission(text) == []
    assert parse_repair(text) == []
    assert parse_fencing(text) == []
    assert parse_telemetry(text) == []
    assert parse_timeline(text) == []
    # old log: no bus lines -> [] and unchanged parsing
    old_log = tmp_path / "old.out"
    old_log.write_text("# cfg node_cnt=2\n[summary] total_runtime=1,tput=5\n")
    assert parse_metrics(old_log.read_text().splitlines()) == []
    assert parse_file(str(old_log))["tput"] == 5


def test_parse_ctrl_forward_backward_compat(tmp_path):
    """[ctrl] lines (control-plane tentpole): one row per controller
    boundary tick carrying BOTH the recorded signals and the decision
    (the replay contract's whole input); old logs yield [], the new
    lines perturb no other parser, the colon-joined per-partition
    vectors come back as strings, and the "ctrl" timeline span lands
    on the declared tid-7 track."""
    from deneva_tpu.harness.parse import (parse_admission, parse_ctrl,
                                          parse_file, parse_membership,
                                          parse_metrics, parse_repair,
                                          parse_replication)
    from deneva_tpu.harness.timeline import parse_timeline

    new_log = tmp_path / "ctrl.out"
    new_log.write_text(
        "# cfg node_cnt=2\n"
        "[ctrl] node=0 seq=3 epoch=150 epochs=50 dens=120:4 fb=2 sv=9 "
        "wit=0 slo=1 gap_us=81234 gov=armed heal=0 trips=1 "
        "assign=2:0 gshift=0:2 cap=2 cad=4 qidx=1\n"
        "[timeline] node=0 epoch=64 loop=1.0ms ctrl=0.1ms\n"
        "[summary] total_runtime=2,tput=1800,txn_cnt=3600,"
        "total_txn_commit_cnt=3600,ctrl_decisions=52,ctrl_trips=1\n")
    rows = parse_ctrl(new_log.read_text().splitlines())
    assert len(rows) == 1
    r = rows[0]
    assert r["node"] == 0 and r["seq"] == 3 and r["epochs"] == 50
    assert r["gov"] == "armed" and r["trips"] == 1 and r["qidx"] == 1
    # per-partition vectors stay colon-joined strings (split to consume)
    assert r["dens"] == "120:4" and r["assign"] == "2:0"
    assert [int(x) for x in r["gshift"].split(":")] == [0, 2]
    # the row round-trips through the controller's signal inverse
    from deneva_tpu.runtime.controller import signals_of_row
    sig = signals_of_row(r)
    assert sig.dens == [120, 4] and sig.gap_us == 81234
    assert sig.breaches == 1 and sig.witnesses == 0
    row = parse_file(str(new_log))
    assert row["ctrl_decisions"] == 52 and row["ctrl_trips"] == 1
    # other parsers ignore the new lines entirely
    text = new_log.read_text().splitlines()
    assert parse_membership(text) == []
    assert parse_replication(text) == []
    assert parse_admission(text) == []
    assert parse_repair(text) == []
    assert parse_metrics(text) == []
    assert len(parse_timeline(text)) == 1
    from deneva_tpu.harness.timeline import CTRL_TRACK, SPAN_TRACK
    assert SPAN_TRACK["ctrl"] is CTRL_TRACK
    assert CTRL_TRACK.tid == 7
    # old log: no ctrl lines -> [] and unchanged parsing
    old_log = tmp_path / "old.out"
    old_log.write_text("# cfg node_cnt=2\n[summary] total_runtime=1,tput=5\n")
    assert parse_ctrl(old_log.read_text().splitlines()) == []
    assert parse_file(str(old_log))["tput"] == 5


def test_parse_mesh_forward_backward_compat(tmp_path):
    """[mesh] lines (pod-scale measured path): one row per mesh-armed
    server at summary time — shards, the static all_to_all estimate,
    the d2h prefetch overlap ratio and the group count behind it; old
    logs and single-device runs yield [], the new lines perturb no
    other parser, and the "mesh_prefetch" timeline span lands on the
    declared tid-8 track."""
    from deneva_tpu.harness.parse import (parse_admission, parse_ctrl,
                                          parse_file, parse_membership,
                                          parse_mesh, parse_metrics,
                                          parse_repair, parse_replication)
    from deneva_tpu.harness.timeline import parse_timeline
    from deneva_tpu.parallel.mesh import mesh_line

    new_log = tmp_path / "mesh.out"
    new_log.write_text(
        "# cfg node_cnt=1\n"
        + mesh_line(0, {"shards": 8, "a2a_bytes": 147456,
                        "prefetch_overlap": "0.8750", "groups": 16})
        + "\n"
        "[timeline] node=0 epoch=64 loop=1.0ms mesh_prefetch=0.4ms\n"
        "[summary] total_runtime=2,tput=1800,txn_cnt=3600,"
        "total_txn_commit_cnt=3600,mesh_shards=8,"
        "mesh_prefetch_overlap=0.875\n")
    rows = parse_mesh(new_log.read_text().splitlines())
    assert len(rows) == 1
    r = rows[0]
    assert r["node"] == 0 and r["shards"] == 8
    assert r["a2a_bytes"] == 147456 and r["groups"] == 16
    assert r["prefetch_overlap"] == pytest.approx(0.875)
    row = parse_file(str(new_log))
    assert row["mesh_shards"] == 8
    assert row["mesh_prefetch_overlap"] == pytest.approx(0.875)
    # other parsers ignore the new lines entirely
    text = new_log.read_text().splitlines()
    assert parse_membership(text) == []
    assert parse_replication(text) == []
    assert parse_admission(text) == []
    assert parse_repair(text) == []
    assert parse_metrics(text) == []
    assert parse_ctrl(text) == []
    assert len(parse_timeline(text)) == 1
    from deneva_tpu.harness.timeline import MESH_TRACK, SPAN_TRACK
    assert SPAN_TRACK["mesh_prefetch"] is MESH_TRACK
    assert MESH_TRACK.tid == 8
    # old log (pre-mesh, or any single-device run): [] and unchanged
    old_log = tmp_path / "old.out"
    old_log.write_text("# cfg node_cnt=2\n[summary] total_runtime=1,tput=5\n")
    assert parse_mesh(old_log.read_text().splitlines()) == []
    assert parse_file(str(old_log))["tput"] == 5


def test_parse_dgcc_forward_backward_compat(tmp_path):
    """[dgcc] lines (wavefront-backend tentpole): one row per node at
    summary time carrying the wave ledger — waves summed over the
    measured window (> #epochs proves the backend chained, the smoke
    gate's anti-inert signal), the deepest single-epoch wavefront, the
    over-deep DEFER fallbacks and the pre-commit edge census; old logs
    yield [], the new lines perturb no other parser, the [summary]
    dgcc_* fields parse through the standard summary path, and the
    "dgcc_waves" span name maps onto the declared tid-9 track."""
    from deneva_tpu.harness.parse import (parse_admission, parse_ctrl,
                                          parse_dgcc, parse_file,
                                          parse_membership, parse_mesh,
                                          parse_metrics, parse_repair,
                                          parse_replication)
    from deneva_tpu.harness.timeline import parse_timeline
    from deneva_tpu.stats import tagged_line

    new_log = tmp_path / "dgcc.out"
    new_log.write_text(
        "# cfg node_cnt=2\n"
        + tagged_line("dgcc", {"node": 1, "waves": 640, "wave_max": 17,
                               "fallback": 12, "edges": 48311})
        + "\n"
        "[timeline] node=1 epoch=64 loop=1.0ms validate=0.3ms\n"
        "[summary] total_runtime=2,tput=1800,txn_cnt=3600,"
        "total_txn_commit_cnt=3600,total_txn_abort_cnt=0,"
        "dgcc_wave_cnt=640,dgcc_wave_max=17,dgcc_fallback_cnt=12,"
        "dgcc_edge_cnt=48311\n")
    rows = parse_dgcc(new_log.read_text().splitlines())
    assert len(rows) == 1
    r = rows[0]
    assert r["node"] == 1 and r["waves"] == 640 and r["wave_max"] == 17
    assert r["fallback"] == 12 and r["edges"] == 48311
    row = parse_file(str(new_log))
    assert row["dgcc_wave_cnt"] == 640 and row["dgcc_fallback_cnt"] == 12
    # the abort contract the backend ships with: fallbacks are DEFERS,
    # aborts stay zero in the standard summary fields
    assert row["total_txn_abort_cnt"] == 0
    # other parsers ignore the new line entirely
    text = new_log.read_text().splitlines()
    assert parse_membership(text) == []
    assert parse_replication(text) == []
    assert parse_admission(text) == []
    assert parse_repair(text) == []
    assert parse_metrics(text) == []
    assert parse_ctrl(text) == []
    assert parse_mesh(text) == []
    assert len(parse_timeline(text)) == 1
    from deneva_tpu.harness.timeline import DGCC_TRACK, SPAN_TRACK
    assert SPAN_TRACK["dgcc_waves"] is DGCC_TRACK
    assert DGCC_TRACK.tid == 9
    # old log (pre-DGCC, or any other backend): [] and unchanged parsing
    old_log = tmp_path / "old.out"
    old_log.write_text("# cfg node_cnt=2\n[summary] total_runtime=1,tput=5\n")
    assert parse_dgcc(old_log.read_text().splitlines()) == []
    assert parse_file(str(old_log))["tput"] == 5


def test_track_registry_covers_every_span_family():
    """The declared track registry (timeline.TRACKS) replaces the magic
    Chrome-trace tids: every tagged-line ledger family maps to exactly
    one registered track, tids and names are unique, the phase track is
    tid 0, and the txntrace export's track is registered alongside —
    so a new subsystem's spans cannot silently collide with an
    existing tid."""
    from deneva_tpu.harness.timeline import (ADMISSION_SPANS,
                                             CRITPATH_SPANS,
                                             FENCING_SPANS, PHASE_TRACK,
                                             REPLICATION_SPANS,
                                             SPAN_TRACK, TRACKS,
                                             TXN_TRACK)

    tids = [t.tid for t in TRACKS]
    names = [t.name for t in TRACKS]
    assert len(set(tids)) == len(tids), "duplicate track tid"
    assert len(set(names)) == len(names), "duplicate track name"
    assert PHASE_TRACK.tid == 0 and PHASE_TRACK in TRACKS
    assert TXN_TRACK in TRACKS and TXN_TRACK.tid != 0
    # every ledger span family is registered, with no overlap
    for fam in (REPLICATION_SPANS, ADMISSION_SPANS, FENCING_SPANS,
                CRITPATH_SPANS):
        assert fam, "an exported span family went empty"
        for name in fam:
            assert SPAN_TRACK[name].spans == fam
    all_spans = [n for t in TRACKS for n in t.spans]
    assert len(set(all_spans)) == len(all_spans), \
        "a span name is claimed by two tracks"
    # the registry is what chrome_trace actually uses: an unregistered
    # span lands on the phase track by contract
    assert SPAN_TRACK.get("loop", PHASE_TRACK) is PHASE_TRACK


def test_regression_gate_telemetry_pairs(tmp_path, monkeypatch):
    """The telemetry-overhead gate (tools/regression_gate.py): an ok
    on/off pair passes; an INERT armed run (tel_sampled_cnt == 0), a
    >2%-slower armed run, a dropping recorder, and a missing _off twin
    each raise a violation."""
    import tools.regression_gate as rg

    def point(name, tput, sampled=None, dropped=0.0):
        body = f"total_runtime=4,tput={tput},txn_cnt={int(tput) * 4}"
        if sampled is not None:
            body += f",tel_sampled_cnt={sampled},tel_dropped_cnt={dropped}"
        (tmp_path / name).write_text(
            "# cfg node_cnt=2\n[summary] " + body + "\n")

    point("good_off.out", 50000)
    point("good_on.out", 49600, sampled=300)        # -0.8%: inside 2%
    point("inert_off.out", 50000)
    point("inert_on.out", 50000, sampled=0)         # recorder dead
    point("slow_off.out", 50000)
    point("slow_on.out", 48000, sampled=300)        # -4%: over the gate
    point("droppy_off.out", 50000)
    point("droppy_on.out", 49900, sampled=300, dropped=17.0)
    point("lonely_on.out", 50000, sampled=300)      # no _off twin
    monkeypatch.setattr(rg, "TELEMETRY_DIR", str(tmp_path))
    viol = rg.telemetry_violations()
    assert len(viol) == 4
    kinds = "\n".join(viol)
    assert "inert_on.out" in kinds and "INERT" in kinds
    assert "slow_on.out" in kinds and "overhead exceeds" in kinds
    assert "droppy_on.out" in kinds and "dropped" in kinds
    assert "lonely_on.out" in kinds and "twin" in kinds
    assert not any("good_on" in v for v in viol)
