"""Partition & gray-failure tolerance (runtime/faildet.py + the server
fencing integration): detector math, quorum decisions, the fence
envelope, route-level fencing behaviors on a loopback ServerNode, the
fencing-off wire pin (bytes verbatim, no detector, no envelope — the
default-off bit-identity contract), and the slow end-to-end
partition-split scenario."""

import json
import os

import numpy as np
import pytest

from deneva_tpu.config import CCAlg, Config, WorkloadKind
from deneva_tpu.runtime import faildet as FD
from deneva_tpu.runtime import wire

from tests.test_chaos import _solo_server


# ---- failure detector --------------------------------------------------

def _cfg(**kw):
    base = dict(fencing_phi=8.0, fencing_heartbeat_ms=100.0,
                fencing_suspect_s=2.0)
    base.update(kw)
    return Config(**base)


def test_detector_steady_traffic_stays_fresh():
    fd = FD.FailureDetector(_cfg(), [1, 2], now_s=0.0)
    t = 0.0
    for _ in range(50):
        t += 0.1
        fd.observe(1, t)
        fd.observe(2, t)
    assert fd.phi(1, t + 0.1) < 1.0
    assert not fd.suspected(1, t + 0.1)
    assert fd.suspect_cnt == 0 and fd.heal_cnt == 0


def test_detector_silence_suspects_then_heals():
    fd = FD.FailureDetector(_cfg(), [1], now_s=0.0)
    for i in range(10):
        fd.observe(1, 0.1 * (i + 1))
    t0 = 1.0
    # phi crosses 8.0 at ~1.84 s of silence (mean gap floored at the
    # 100 ms cadence); the fence additionally needs the 2 s floor
    assert not fd.suspected(1, t0 + 1.0)
    assert fd.suspected(1, t0 + 1.9)
    assert fd.suspect_cnt == 1
    assert not fd.fence_ready(1, t0 + 1.9)       # floor not yet cleared
    assert fd.fence_ready(1, t0 + 2.1)
    # latched until traffic resumes; the heal returns the silence gap
    gap = fd.observe(1, t0 + 2.5)
    assert gap == pytest.approx(2.5)
    assert fd.heal_cnt == 1 and not fd.suspected(1, t0 + 2.6)
    assert fd.phi_peak > 8.0


def test_detector_mean_floored_at_cadence():
    """Heavy epoch traffic (ms-scale gaps) must not shrink the expected
    gap so far that a sub-second stall reads as death."""
    fd = FD.FailureDetector(_cfg(), [1], now_s=0.0)
    t = 0.0
    for _ in range(200):
        t += 0.002
        fd.observe(1, t)
    assert not fd.suspected(1, t + 0.5)
    assert fd.suspect_cnt == 0


def test_detector_warming_half_threshold():
    fd = FD.FailureDetector(_cfg(), [1], now_s=0.0)
    assert not fd.warming(1, 0.5)
    assert fd.warming(1, 1.0)        # phi ~4.3 >= 8/2
    assert not fd.suspected(1, 1.0)  # but not yet suspected


def test_detector_observe_unknown_peer_is_noop():
    fd = FD.FailureDetector(_cfg(), [1], now_s=0.0)
    assert fd.observe(7, 1.0) is None


# ---- quorum decisions --------------------------------------------------

def test_majority_side_strict_and_tiebreak():
    # strict majority wins
    assert FD.majority_side([0, 1], [2])
    assert not FD.majority_side([2], [0, 1])
    # exact tie: the side holding the lowest live id proceeds — both
    # sides compute the same answer from their own view
    assert FD.majority_side([0, 3], [1, 2])
    assert not FD.majority_side([1, 2], [0, 3])


def test_majority_confirms():
    assert FD.majority_confirms(1, 1)          # solo cluster
    assert FD.majority_confirms(3, 2)
    assert not FD.majority_confirms(3, 1)
    assert FD.majority_confirms(2, 2)
    assert not FD.majority_confirms(2, 1)      # 2-node: both must see it


# ---- fence envelope ----------------------------------------------------

def test_fence_envelope_round_trip():
    body = b"\x01\x02payload"
    buf = FD.fence_wrap(body, 5)
    ver, off = FD.fence_peek(buf)
    assert ver == 5 and buf[off:] == body
    # the sendv part prepended on the zero-copy path is the same header
    assert FD.fence_parts(5) + body == buf
    with pytest.raises(ValueError):
        FD.fence_peek(b"\x00" * 16)            # wrong magic


# ---- config gating -----------------------------------------------------

def test_fencing_defaults_off_and_gated():
    cfg = Config()
    assert not cfg.fencing and not cfg.faults_enabled
    with pytest.raises(ValueError, match="fencing needs"):
        Config().replace(fencing=True)
    # the valid arming shape
    cfg = Config().replace(elastic=True, logging=True, fencing=True,
                           cc_alg=CCAlg.CALVIN,
                           workload=WorkloadKind.YCSB)
    assert cfg.fencing


def test_partition_and_stall_specs_validate():
    ok = Config(node_cnt=3).replace(
        fault_partition="2-0:2.5,2>1:3.0", logging=True)
    assert ok.fault_partition_spec() == [(2, 0, True, 2.5),
                                         (2, 1, False, 3.0)]
    assert ok.faults_enabled
    with pytest.raises(ValueError, match="fault_partition"):
        Config(node_cnt=3).replace(fault_partition="2-2:1.0")
    with pytest.raises(ValueError, match="fault_partition"):
        Config(node_cnt=3).replace(fault_partition="2-9:1.0")
    with pytest.raises(ValueError, match="flap"):
        Config().replace(fault_partition_flap_s=1.0)
    assert Config(node_cnt=3).replace(
        fault_peer_stall="1:4000:3.0").fault_peer_stall_spec() \
        == (1, 4000.0, 3.0)
    with pytest.raises(ValueError, match="fault_peer_stall"):
        Config(node_cnt=3).replace(fault_peer_stall="1:4000")
    with pytest.raises(ValueError, match="node 0"):
        Config(node_cnt=3).replace(
            elastic=True, logging=True, fencing=True,
            cc_alg=CCAlg.CALVIN, fault_peer_stall="0:4000:3.0")
    # fencing may not isolate the measure/stop coordinator into a
    # minority; cutting around node >= 1 (or leaving node 0 in the
    # majority component) is fine
    with pytest.raises(ValueError, match="node 0"):
        Config(node_cnt=3).replace(
            elastic=True, logging=True, fencing=True,
            cc_alg=CCAlg.CALVIN, fault_partition="0-1:3.0,0-2:3.0")
    ok = Config(node_cnt=3).replace(
        elastic=True, logging=True, fencing=True,
        cc_alg=CCAlg.CALVIN, fault_partition="2-0:3.0,2-1:3.0")
    assert ok.fencing


# ---- loopback ServerNode: fencing-off wire pin -------------------------

def _blob(epoch=7):
    blk = wire.QueryBlock(
        keys=np.arange(8, dtype=np.int32).reshape(4, 2),
        types=np.ones((4, 2), np.int8),
        scalars=np.zeros((4, 0), np.int32),
        tags=np.arange(4, dtype=np.int64))
    ts = np.arange(4, dtype=np.int64) + 100
    return blk, ts, wire.encode_epoch_blob(epoch, blk, ts)


def test_fencing_off_takes_pre_fencing_path_verbatim():
    """The house contract, executable: with fencing off a server builds
    NO detector, arms no partition surface, routes EPOCH_BLOB payloads
    unstripped, and its blob broadcast is byte-identical to the
    pre-fencing codec output — no envelope, no heartbeat, no new rtype
    ever touches the wire."""
    node = _solo_server("fence_off_pin")
    try:
        assert node._fencing is False
        assert node._fd is None and node._FD is None
        assert node._partitions is None and node._stall is None
        blk, ts, blob = _blob()
        node._route(0, "EPOCH_BLOB", blob)
        stored = node.blob_buf[7][0]
        if isinstance(stored, tuple):          # serial path decodes
            assert wire.encode_qry_block(stored[0]) \
                == wire.encode_qry_block(blk)
        else:                                  # overlap path keeps bytes
            assert stored == blob
        # broadcast bytes == the pre-fencing codec, verbatim
        sent = []
        node.tp.sendv_many = \
            lambda dests, rt, parts: sent.append((list(dests), rt, parts))
        node.tp.send = lambda d, rt, pl=b"": sent.append(([d], rt, [pl]))
        node.n_srv = 2          # pretend a peer so the bcast emits
        node._bcast_views(7, blk, ts)
        (dests, rt, parts), = sent
        assert rt == "EPOCH_BLOB"
        assert b"".join(bytes(p) for p in parts) == blob
        assert not any(k in node.stats.counters
                       for k in ("fence_nack_cnt", "suspect_cnt"))
    finally:
        node.n_srv = 1
        node.close()


# ---- loopback ServerNode: fencing-on route behaviors -------------------

def _fencing_server(tag, tmp_path, **kw):
    base = dict(elastic=True, logging=True, fencing=True,
                log_dir=str(tmp_path), synth_table_size=1024)
    base.update(kw)
    return _solo_server(tag, **base)


def test_fence_nack_and_healed_out_self_halt(tmp_path, monkeypatch):
    """A FENCE_NACK carrying a newer map version (or a HEAL whose map
    no longer includes us) self-halts with the exit-18 sentinel; a nack
    echoing our own version (stale crossing) does not."""
    node = _fencing_server("fence_nack_halt", tmp_path)
    halts = []
    try:
        monkeypatch.setattr(
            node, "_self_fence",
            lambda reason, epoch: halts.append((reason, epoch)))
        node._route(5, "FENCE_NACK", FD.encode_fence_nack(0, 0, 7))
        assert halts == [] and node._fence_nack_rx == 1
        node._route(5, "FENCE_NACK", FD.encode_fence_nack(3, 0, 9))
        assert halts == [("fence_nack", 9)]
        # HEAL with a newer map that still includes us: no halt
        node._route(5, "HEAL", FD.encode_heal(11, 4, np.zeros(4, np.int32)))
        assert len(halts) == 1
        # HEAL with a newer map we were evicted from: healed out
        node._route(5, "HEAL", FD.encode_heal(12, 4, np.ones(4, np.int32)))
        assert halts[-1] == ("healed_out", 12)
    finally:
        node.close()


def test_stale_incarnation_blob_rejected_with_fence_nack(tmp_path):
    """An EPOCH_BLOB from a RETIRED peer's stale incarnation is dropped
    and FENCE_NACKed; a live (non-retired) peer briefly one map version
    behind is accepted — pipeline skew across a deterministic cutover
    is not split-brain."""
    from deneva_tpu.runtime.membership import SlotMap

    node = _fencing_server("fence_stale_blob", tmp_path)
    sent = []
    try:
        node.tp.send = lambda d, rt, pl=b"": sent.append((d, rt, pl))
        node.n_srv = 3                      # pretend peers 1, 2 exist
        node.smap = SlotMap(1, node.smap.owners)   # we are at v1
        node._reassigned.add(2)
        _blk, _ts, blob = _blob(epoch=9)
        # retired peer 2 at v0: rejected + nacked
        node._route(2, "EPOCH_BLOB", FD.fence_wrap(blob, 0))
        assert 9 not in node.blob_buf
        assert node._fence_nacks == 1
        d, rt, pl = sent[-1]
        assert (d, rt) == (2, "FENCE_NACK")
        assert FD.decode_fence_nack(pl)[0] == 1
        # live peer 1 at v0: accepted, envelope stripped, lease ledger
        # records the epoch
        node._route(1, "EPOCH_BLOB", FD.fence_wrap(blob, 0))
        stored = node.blob_buf[9][1]
        if isinstance(stored, tuple):
            assert wire.encode_qry_block(stored[0]) \
                == wire.encode_qry_block(_blk)
        else:
            assert stored == blob
        assert node._blob_seen_from[1] == 9
    finally:
        node.n_srv = 1
        node._reassigned.clear()
        node.close()


def test_ack_lease_needs_majority_blob_confirmation(tmp_path):
    """_fence_ack_ok: an epoch's acks release only once a majority of
    the live set (self included) confirmed its blob via heartbeats."""
    node = _fencing_server("fence_ack_lease", tmp_path)
    try:
        assert node._fence_ack_ok(12)          # solo: majority of 1
        node.n_srv = 3
        node._hb_peer_seen = {1: 5, 2: -1}
        assert node._fence_ack_ok(5)           # self + peer 1 = 2 of 3
        assert not node._fence_ack_ok(6)       # only self has seen 6
        node._reassigned.add(2)                # live set shrinks to 2
        assert node._fence_ack_ok(5)
        assert not node._fence_ack_ok(6)       # 2-node: both must see
        node._hb_peer_seen[1] = 6
        assert node._fence_ack_ok(6)
    finally:
        node.n_srv = 1
        node._reassigned.clear()
        node.close()


def test_self_fence_writes_sidecar_and_exits_18(tmp_path, monkeypatch):
    node = _fencing_server("fence_halt_sidecar", tmp_path)
    codes = []
    try:
        monkeypatch.setattr(os, "_exit", lambda c: codes.append(c))
        node._fence_last_ack = 41
        node._self_fence("minority", 48)
        assert codes == [FD.FENCED_EXIT] == [18]
        with open(os.path.join(str(tmp_path),
                               "node0.fenced.json")) as f:
            side = json.load(f)
        assert side["reason"] == "minority" and side["epoch"] == 48
        assert side["last_acked_epoch"] == 41
        assert side["map_version"] == 0
    finally:
        node.close()


# ---- end-to-end scenario (the smoke gate runs all four) ----------------

@pytest.mark.slow
def test_partition_split_scenario():
    """Symmetric split: majority reassigns, minority self-fences with
    exit 18, single-writer + digest-vs-replay invariants green."""
    from deneva_tpu.harness.chaos import run_scenario

    rep = run_scenario("partition-split", quick=True, quiet=True)
    assert rep["fenced_node"] == 2
    assert rep["fence_reason"] == "minority"
    assert rep["fenced_last_ack"] < rep["reassign_epoch"]
    assert rep["digest_match"]
