"""Zero-copy wire-path equivalence (host-path pipeline PR).

The cluster steady loop ships messages as scatter-send parts
(`dt_sendv`) and packs log records straight from feed-row views; the
contract is BYTE IDENTITY with the original codecs for every shape —
that is what keeps log files, replica streams and verdicts unchanged
whichever path produced them.  Fuzzed over random shapes including the
empty-block and zero-scalar corners.
"""

import numpy as np
import pytest

from deneva_tpu.runtime import wire
from deneva_tpu.runtime.logger import pack_record, pack_record_views


def _cat(parts) -> bytes:
    """Reference concatenation of sendv parts (what the native layer
    frames)."""
    return b"".join(p if isinstance(p, (bytes, bytearray))
                    else np.ascontiguousarray(p).tobytes() for p in parts)


def _rand_block(rng, n, W, S) -> tuple[wire.QueryBlock, np.ndarray]:
    blk = wire.QueryBlock(
        keys=rng.integers(-2**31, 2**31 - 1, (n, W)).astype(np.int32),
        types=rng.integers(-128, 128, (n, W)).astype(np.int8),
        scalars=rng.integers(-2**31, 2**31 - 1, (n, S)).astype(np.int32),
        tags=rng.integers(0, 2**62, n).astype(np.int64))
    ts = rng.integers(1, 2**31, n).astype(np.int64)
    return blk, ts


@pytest.mark.parametrize("seed", range(4))
def test_epoch_blob_parts_fuzz_byte_identical(seed):
    rng = np.random.default_rng(seed)
    for _ in range(25):
        n = int(rng.integers(0, 70))
        W = int(rng.integers(1, 12))
        S = int(rng.integers(0, 6))
        blk, ts = _rand_block(rng, n, W, S)
        epoch = int(rng.integers(0, 2**40))
        old = wire.encode_epoch_blob(epoch, blk, ts)
        parts = wire.epoch_blob_parts(epoch, ts, blk.tags, blk.keys,
                                      blk.types, blk.scalars)
        assert _cat(parts) == old


def test_qry_block_parts_byte_identical_and_sliced():
    rng = np.random.default_rng(7)
    blk, _ = _rand_block(rng, 48, 6, 3)
    assert _cat(wire.qry_block_parts(blk.tags, blk.keys, blk.types,
                                     blk.scalars)) \
        == wire.encode_qry_block(blk)
    # row-sliced views (the client's budget-limited sends) stay
    # C-contiguous and encode like the sliced block
    n = 17
    sl = blk.slice(0, n)
    assert _cat(wire.qry_block_parts(blk.tags[:n], blk.keys[:n],
                                     blk.types[:n], blk.scalars[:n])) \
        == wire.encode_qry_block(sl)


def test_cl_rsp_parts_byte_identical():
    rng = np.random.default_rng(3)
    for n in (0, 1, 33):
        tags = rng.integers(0, 2**62, n).astype(np.int64)
        assert _cat(wire.cl_rsp_parts(tags)) == wire.encode_cl_rsp(tags)


@pytest.mark.parametrize("seed", range(4))
def test_decode_epoch_blob_into_round_trip(seed):
    rng = np.random.default_rng(100 + seed)
    for _ in range(25):
        n = int(rng.integers(0, 70))
        W = int(rng.integers(1, 12))
        S = int(rng.integers(0, 6))
        blk, ts = _rand_block(rng, n, W, S)
        buf = wire.encode_epoch_blob(5, blk, ts)
        cap = n + int(rng.integers(0, 9))
        tg = np.full(cap, -1, np.int64)
        t2 = np.full(cap, -1, np.int64)
        k = np.zeros((cap, W), np.int32)
        ty = np.zeros((cap, W), np.int8)
        sc = np.zeros((cap, S), np.int32)
        epoch, m = wire.decode_epoch_blob_into(buf, tg, t2, k, ty, sc)
        # matches the allocating decoder exactly; rows past n untouched
        e_ref, blk_ref, ts_ref = wire.decode_epoch_blob(buf)
        assert (epoch, m) == (e_ref, n)
        assert (tg[:n] == blk_ref.tags).all() and (t2[:n] == ts_ref).all()
        assert (k[:n] == blk_ref.keys).all()
        assert (ty[:n] == blk_ref.types).all()
        assert (sc[:n] == blk_ref.scalars).all()
        assert (tg[n:] == -1).all() and (t2[n:] == -1).all()


def test_decode_into_rejects_bad_targets():
    rng = np.random.default_rng(1)
    blk, ts = _rand_block(rng, 8, 4, 2)
    buf = wire.encode_epoch_blob(1, blk, ts)
    small = np.zeros(4, np.int64)
    with pytest.raises(ValueError):
        wire.decode_epoch_blob_into(buf, small, np.zeros(8, np.int64),
                                    np.zeros((8, 4), np.int32),
                                    np.zeros((8, 4), np.int8),
                                    np.zeros((8, 2), np.int32))
    with pytest.raises(ValueError):     # wrong minor dim
        wire.decode_epoch_blob_into(buf, np.zeros(8, np.int64),
                                    np.zeros(8, np.int64),
                                    np.zeros((8, 3), np.int32),
                                    np.zeros((8, 4), np.int8),
                                    np.zeros((8, 2), np.int32))


def test_peek_blob_epoch():
    rng = np.random.default_rng(2)
    blk, ts = _rand_block(rng, 4, 4, 0)
    assert wire.peek_blob_epoch(wire.encode_epoch_blob(91, blk, ts)) == 91


@pytest.mark.parametrize("seed", range(3))
def test_pack_record_views_byte_identical(seed):
    """The wire-worker log path must write the exact bytes the serial
    path writes: pack_record_views(feed rows) == pack_record(epoch,
    encode_epoch_blob(merged block), active)."""
    rng = np.random.default_rng(200 + seed)
    for _ in range(20):
        n = int(rng.integers(1, 70))
        W = int(rng.integers(1, 10))
        S = int(rng.integers(0, 5))
        blk, ts = _rand_block(rng, n, W, S)
        active = rng.integers(0, 2, n).astype(bool)
        epoch = int(rng.integers(0, 2**40))
        old = pack_record(epoch, wire.encode_epoch_blob(epoch, blk, ts),
                          active)
        new = pack_record_views(epoch, ts, blk.tags, blk.keys, blk.types,
                                blk.scalars, active)
        assert new.tobytes() == old
