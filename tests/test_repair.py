"""Transaction repair engine (engine/repair.py, Config.repair).

Four claim families:

* **Serial-sum oracle with repair on** — per sweep backend, the TPC-C
  audit invariants (YTD conservation, balance conservation, dense
  per-district o_ids) hold with the escrow exemption OFF and repair ON:
  salvaged txns are serializable commits, and the commit count
  dominates the retry-only floor.
* **Repair-off / no-loser identity** — ``repair=false`` takes the
  pre-repair code paths (structural: the gate family lint enforces it;
  the run here pins behavior), and ``repair=true`` with ZERO losers is
  bit-identical to ``repair=false`` on every data row, cc_state leaf
  and stats counter (the repair no-op path really is a no-op; the
  padded trash slot absorbs the masked waves by design and is excluded
  like `logger.state_digest` excludes control-plane leaves).
* **Scripted frontier cases** — empty frontier (write-only loser
  salvages, zero invalidated lanes), full frontier (the loser's re-read
  observes the winner's value, checksum-exact), cyclic re-invalidation
  (an m-deep hot-key chain salvages exactly ``repair_rounds`` losers
  and the rest fall back to the retry queue), and the escrow contract
  (escrow reads never enter the frontier — repair of an escrow delta
  is a no-op).
* **Floor smoke** (slow) — YCSB zipf-0.9 write-heavy: OCC and MAAT
  commit >= 2x the retry-only run per epoch at the calibrated CPU
  operating point (epoch-rate-free formulation, like the escrow floor
  smoke; wall-clock curves live in results/repair with capture
  provenance).

Accounting contract (the parse-compat satellite): a salvaged txn is a
COMMIT — ``total_txn_abort_cnt`` counts only retry-queue fallbacks, so
``total_txn_abort_cnt == rep_fallback_cnt`` on any forced-free run.
"""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deneva_tpu.cc import (AccessBatch, build_conflict_incidence,
                           committed_write_frontier, get_backend)
from deneva_tpu.config import CCAlg, Config, WorkloadKind
from deneva_tpu.engine import Engine
from deneva_tpu.engine.repair import repair_ts, run_repair
from deneva_tpu.engine.step import init_device_stats
from deneva_tpu.workloads import get_workload
from deneva_tpu.workloads.ycsb import YCSBQuery, _field_fingerprint

SWEEP_ALGS = ("NO_WAIT", "WAIT_DIE", "OCC", "TIMESTAMP", "MVCC", "MAAT")


def ycsb_cfg(**kw):
    base = dict(workload=WorkloadKind.YCSB, synth_table_size=1 << 12,
                req_per_query=4, max_accesses=4, epoch_batch=128,
                conflict_buckets=1024, max_txn_in_flight=512,
                zipf_theta=0.9, read_perc=0.1, write_perc=0.9,
                repair=True, warmup_secs=0.0, done_secs=0.2)
    base.update(kw)
    if "cc_alg" in base:
        base["cc_alg"] = CCAlg(base["cc_alg"])
    return Config(**base).validate()


def tpcc_cfg(**kw):
    base = dict(workload=WorkloadKind.TPCC, num_wh=2, cust_per_dist=120,
                max_items=4096, max_items_per_txn=5, max_accesses=8,
                epoch_batch=64, conflict_buckets=1024,
                max_txn_in_flight=256, insert_table_cap=1 << 14,
                repair=True, escrow_sweep=False,
                warmup_secs=0.0, done_secs=0.2)
    base.update(kw)
    if "cc_alg" in base:
        base["cc_alg"] = CCAlg(base["cc_alg"])
    return Config(**base).validate()


# ---- scripted rig: one epoch, hand-built plans, direct run_repair -----

B, R = 8, 2


def _rig(alg, scripts, rounds=2, cfg_kw=()):
    """scripts: per-txn [(key, 'r'|'w'), ...] (padded to R with reads of
    a per-lane cold key).  Returns (cfg, wl, be, db0, queries, batch,
    inc, verdict, cc_state, stats) after the MAIN round's validate +
    execute — run_repair's exact inputs in Engine.step."""
    cfg = ycsb_cfg(cc_alg=alg, synth_table_size=1024, req_per_query=R,
                   max_accesses=R, epoch_batch=B, zipf_theta=0.0,
                   **dict(cfg_kw))
    wl = get_workload(cfg)
    be = get_backend(cfg.cc_alg)
    db = wl.load()
    keys = np.zeros((B, R), np.int32)
    is_w = np.zeros((B, R), bool)
    for i in range(B):
        for s in range(R):
            # pad: read of a distinct cold key (600+lane*R+s)
            keys[i, s] = 600 + i * R + s
        for s, (key, mode) in enumerate(scripts[i] if i < len(scripts)
                                        else ()):
            keys[i, s] = key
            is_w[i, s] = mode == "w"
    active = np.zeros(B, bool)
    active[:len(scripts)] = True
    queries = YCSBQuery(keys=jnp.asarray(keys), is_write=jnp.asarray(is_w))
    planned = wl.plan(db, queries)
    batch = AccessBatch(
        table_ids=planned["table_ids"], keys=planned["keys"],
        is_read=planned["is_read"], is_write=planned["is_write"],
        valid=planned["valid"],
        ts=jnp.arange(1, B + 1, dtype=jnp.int32),
        rank=jnp.arange(B, dtype=jnp.int32),
        active=jnp.asarray(active))
    inc = build_conflict_incidence(cfg, be, batch, None)
    verdict, cc_state = be.validate(cfg, be.init_state(cfg), batch, inc)
    stats = init_device_stats()
    exec_commit = verdict.commit
    db = wl.execute(db, queries, exec_commit, verdict.order, stats)
    return cfg, wl, be, db, queries, batch, inc, verdict, cc_state, stats


def _repair(rig, rounds=2):
    cfg, wl, be, db, q, batch, inc, v, st, stats = rig
    cfg = cfg.replace(repair_rounds=rounds)
    db, st, v2, salvaged, _rounds = run_repair(cfg, wl, be, db, q,
                                               batch, inc, v, st,
                                               stats, v.commit)
    return db, v2, np.asarray(salvaged), stats


def _f0(db, key):
    from deneva_tpu.workloads.ycsb import TABLE
    return int(np.asarray(db[TABLE].columns["F0"])[key])


def test_empty_frontier_salvages_write_only_loser():
    """A write-write loser has nothing to re-read: empty frontier
    (rep_frontier_cnt == 0), salvaged in the first sub-round, and its
    blind write lands AFTER the winner's (final value = the loser's)."""
    rig = _rig("OCC", [[(5, "w")], [(5, "w")]])
    v0 = rig[7]
    assert np.asarray(v0.commit)[0] and np.asarray(v0.abort)[1]
    db, v, salvaged, stats = _repair(rig)
    assert salvaged[1] and np.asarray(v.commit)[1]
    assert not np.asarray(v.abort)[1]
    assert int(stats["rep_frontier_cnt"]) == 0
    assert int(stats["rep_salvaged_cnt"]) == 1
    assert int(stats["rep_fallback_cnt"]) == 0
    # the salvage wave applies after the winner: f(5, loser order)
    assert _f0(db, 5) == int(_field_fingerprint(5, np.asarray(v.order)[1]))


def test_full_frontier_reader_observes_winner_value():
    """A loser whose ONLY conflict is a stale read re-reads the winner's
    value in the sub-round: frontier names exactly that lane, and the
    read checksum contains f(key, winner order) — the value a serial
    schedule (winner, then loser) reads."""
    # lane0 writes key 5; lane1 reads key 5 (plus its cold pad read)
    rig = _rig("OCC", [[(5, "w")], [(5, "r")]])
    cfg, wl, be, db0, q, batch, inc, v0, st, stats = rig
    assert np.asarray(v0.commit)[0] and np.asarray(v0.abort)[1]
    pre_cks = int(stats["read_checksum"])
    db, v, salvaged, stats = _repair(rig)
    assert salvaged[1]
    assert int(stats["rep_frontier_cnt"]) == 1     # exactly the r5 lane
    # sub-round checksum delta = the re-read values: winner's f(5, ord0)
    # + the loser's two cold pads... lane1 pad read + re-read of 5
    w_ord = int(np.asarray(v0.order)[0])
    delta = (int(stats["read_checksum"]) - pre_cks) % (1 << 32)
    expect = (int(_field_fingerprint(5, w_ord))
              + int(_field_fingerprint(603, 0))) % (1 << 32)
    assert delta == expect, (delta, expect)


def test_cyclic_reinvalidation_falls_back():
    """An m-writer hot-key chain: the main round admits one, each repair
    sub-round admits exactly one more (each pass's winner re-invalidates
    the rest — the cyclic re-invalidation case), and past repair_rounds
    the leftovers fall back to the retry queue as aborts."""
    rig = _rig("OCC", [[(5, "w")], [(5, "w")], [(5, "w")], [(5, "w")]])
    v0 = rig[7]
    assert int(np.asarray(v0.commit).sum()) == 1
    db, v, salvaged, stats = _repair(rig, rounds=2)
    assert int(salvaged.sum()) == 2                # one per sub-round
    assert int(stats["rep_salvaged_cnt"]) == 2
    assert int(stats["rep_fallback_cnt"]) == 1     # lane3 -> retry queue
    assert np.asarray(v.abort)[3] and not np.asarray(v.commit)[3]
    # waves applied in order: final value is the LAST salvaged wave's
    assert _f0(db, 5) == int(_field_fingerprint(5, np.asarray(v.order)[2]))


def test_timestamp_watermark_loser_restamps_and_salvages():
    """A T/O watermark violator (read from its ts-future) is exactly
    what retry-with-fresh-ts fixes next epoch; repair restamps within
    the epoch.  Scripted: seed the watermark with a committed write at
    ts 10, then a reader stamped ts 2 (< 10) aborts the main round and
    salvages at a fresh stamp in the sub-round."""
    # epoch 1: lane0 writes key 5 at its ts; raises wts[bucket(5)]
    rig1 = _rig("TIMESTAMP", [[(5, "w")] for _ in range(8)])
    _, _, be, _, _, _, _, _, st1, _ = rig1
    # epoch 2 against st1: lane0 reads key 5 at ts 1 < recorded wts
    cfg, wl, _, db, q, batch, inc, _, _, _ = _rig("TIMESTAMP",
                                                  [[(5, "r")]])
    v, st2 = be.validate(cfg, st1, batch, inc)
    assert np.asarray(v.abort)[0], "stale reader must abort pre-repair"
    stats = init_device_stats()
    db = wl.execute(db, q, v.commit, v.order, stats)
    cfg = cfg.replace(repair_rounds=2)
    # ts_base: the engine passes its pool's reserved restamp base,
    # which is strictly above every committed watermark; the scripted
    # rig reuses low ts across "epochs", so supply the base explicitly
    # (20 > the epoch-1 writers' recorded wts)
    db, st3, v2, salvaged, _r = run_repair(cfg, wl, be, db, q, batch,
                                           inc, v, st2, stats,
                                           v.commit,
                                           ts_base=jnp.int32(20))
    assert np.asarray(salvaged)[0], "watermark loser must salvage"
    assert int(stats["rep_frontier_cnt"]) >= 1     # the stale-read lane
    assert not np.asarray(v2.abort)[0]
    # the fallback base rule (no authority supplied): fresh stamps sit
    # above every ACTIVE stamp in the epoch
    rts = np.asarray(repair_ts(batch))
    act = np.asarray(batch.active)
    assert rts.min() > int(np.asarray(batch.ts)[act].max())
    # and without a sufficient base the T/O re-check DECLINES the
    # salvage (conservative, never a wrong commit): stamp below the
    # watermark -> still aborted
    stats2 = init_device_stats()
    _, _, v3, salv2, _r2 = run_repair(cfg, wl, be, db, q, batch, inc,
                                      v, st2, stats2, v.commit,
                                      ts_base=jnp.int32(2))
    assert not np.asarray(salv2)[0]
    assert np.asarray(v3.abort)[0]


def test_escrow_reads_never_enter_frontier():
    """The escrow contract: order_free accesses are commutative deltas /
    immutable-column reads — repair of an escrow access is a no-op, so
    escrow READ lanes are excluded from the frontier even when their
    bucket was overwritten."""
    cfg = ycsb_cfg(cc_alg="OCC", synth_table_size=1024, req_per_query=R,
                   max_accesses=R, epoch_batch=B, zipf_theta=0.0)
    wl = get_workload(cfg)
    be = get_backend(cfg.cc_alg)
    db = wl.load()
    keys = np.array([[5, 600], [5, 601]] + [[602 + i, 610 + i]
                                            for i in range(B - 2)],
                    np.int32)
    is_w = np.zeros((B, R), bool)
    is_w[0, 0] = True                  # lane0 writes key 5
    q = YCSBQuery(keys=jnp.asarray(keys), is_write=jnp.asarray(is_w))
    planned = wl.plan(db, q)
    of = np.zeros((B, R), bool)
    of[1, 0] = True                    # lane1's read of key 5 is escrow
    batch = AccessBatch(
        table_ids=planned["table_ids"], keys=planned["keys"],
        is_read=planned["is_read"], is_write=planned["is_write"],
        valid=planned["valid"], ts=jnp.arange(1, B + 1, dtype=jnp.int32),
        rank=jnp.arange(B, dtype=jnp.int32),
        active=jnp.ones(B, bool), order_free=jnp.asarray(of))
    inc = build_conflict_incidence(cfg, be, batch, batch.order_free)
    committed = jnp.zeros(B, bool).at[0].set(True)
    losers = jnp.zeros(B, bool).at[1].set(True)
    fr = np.asarray(committed_write_frontier(cfg, batch, inc, committed,
                                             losers))
    assert not fr[1, 0], "escrow read must not enter the frontier"
    # the same lane WITHOUT the escrow mark is in the frontier
    plain = dataclasses.replace(batch, order_free=None)
    inc2 = build_conflict_incidence(cfg, be, plain, None)
    fr2 = np.asarray(committed_write_frontier(cfg, plain, inc2, committed,
                                              losers))
    assert fr2[1, 0]


# ---- engine-level: accounting + no-loser identity ---------------------

def test_salvaged_txns_are_commits_not_aborts():
    """The parse-compat satellite: total_txn_abort_cnt counts ONLY
    retry-queue fallbacks (== rep_fallback_cnt); salvaged txns ride the
    commit counter and rep_salvaged_cnt."""
    cfg = ycsb_cfg(cc_alg="OCC")
    eng = Engine(cfg, get_workload(cfg))
    st = jax.device_get(eng.jit_run(eng.init_state(0), 20)).stats
    assert int(st["rep_salvaged_cnt"]) > 0, "contention point inert"
    assert int(st["total_txn_abort_cnt"]) == int(st["rep_fallback_cnt"])
    off = cfg.replace(repair=False)
    eng2 = Engine(off, get_workload(off))
    so = jax.device_get(eng2.jit_run(eng2.init_state(0), 20)).stats
    assert int(st["total_txn_commit_cnt"]) > int(so["total_txn_commit_cnt"])


@pytest.mark.parametrize("alg", ["OCC", "TIMESTAMP", "MVCC"])
def test_repair_noop_when_no_losers_bit_identical(alg):
    """All-read workload: no conflicts, no losers — the armed repair
    machinery must be an exact no-op: every DATA row, cc_state leaf,
    pool leaf and stats counter bitwise equals the repair-off run (the
    padded trash slot, which absorbs every masked wave by design, is
    the only writable difference and is excluded exactly like
    state_digest excludes control-plane leaves)."""
    from deneva_tpu.workloads.ycsb import TABLE
    kw = dict(cc_alg=alg, read_perc=1.0, write_perc=0.0)
    on = ycsb_cfg(**kw)
    off = ycsb_cfg(repair=False, **kw)
    s_on = jax.device_get(Engine(on, get_workload(on)).jit_run(
        Engine(on, get_workload(on)).init_state(0), 10))
    s_off = jax.device_get(Engine(off, get_workload(off)).jit_run(
        Engine(off, get_workload(off)).init_state(0), 10))
    n = on.synth_table_size
    np.testing.assert_array_equal(
        np.asarray(s_on.db[TABLE].columns["F0"])[:n],
        np.asarray(s_off.db[TABLE].columns["F0"])[:n])
    for a, b in zip(jax.tree.leaves(s_on.cc_state),
                    jax.tree.leaves(s_off.cc_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(s_on.pool),
                    jax.tree.leaves(s_off.pool)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in s_on.stats:
        np.testing.assert_array_equal(np.asarray(s_on.stats[k]),
                                      np.asarray(s_off.stats[k]), k)
    assert int(s_on.stats["rep_salvaged_cnt"]) == 0
    assert int(s_on.stats["rep_frontier_cnt"]) == 0


def test_repair_rounds_zero_salvages_nothing():
    """The ablation floor: repair armed with rounds=0 runs the pre-
    repair semantics (zero salvage, fallbacks == aborts == the
    repair-off aborts on the same stream)."""
    cfg = ycsb_cfg(cc_alg="OCC", repair_rounds=0)
    st = jax.device_get(Engine(cfg, get_workload(cfg)).jit_run(
        Engine(cfg, get_workload(cfg)).init_state(0), 10)).stats
    off = cfg.replace(repair=False)
    so = jax.device_get(Engine(off, get_workload(off)).jit_run(
        Engine(off, get_workload(off)).init_state(0), 10)).stats
    assert int(st["rep_salvaged_cnt"]) == 0
    assert int(st["total_txn_commit_cnt"]) == int(so["total_txn_commit_cnt"])
    assert int(st["total_txn_abort_cnt"]) == int(so["total_txn_abort_cnt"])


# ---- per-backend serial-sum oracle (TPC-C audit, escrow OFF) ----------

def _tpcc_oracle(alg, n=25):
    import sys
    import os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_escrow import _audit
    cfg = tpcc_cfg(cc_alg=alg)
    eng = Engine(cfg, get_workload(cfg))
    s0 = eng.init_state(0)
    d0 = jax.device_get(s0.db)
    state = jax.device_get(eng.jit_run(s0, n))
    _audit(cfg, state, d0)
    off = cfg.replace(repair=False)
    eng2 = Engine(off, get_workload(off))
    so = jax.device_get(eng2.jit_run(eng2.init_state(0), n))
    on_c = int(state.stats["total_txn_commit_cnt"])
    off_c = int(so.stats["total_txn_commit_cnt"])
    assert int(state.stats["rep_salvaged_cnt"]) > 0, alg
    assert on_c > off_c, (alg, on_c, off_c)
    return on_c, off_c


def test_repair_oracle_occ():
    """Fast-tier representative: OCC's repaired commit set satisfies the
    TPC-C serial-sum audit (YTD/balance conservation + dense o_ids) on
    the re-floored hot rows (escrow off), and dominates retry-only."""
    on, off = _tpcc_oracle("OCC")
    assert on > 2 * off, (on, off)


@pytest.mark.slow
@pytest.mark.parametrize("alg", [a for a in SWEEP_ALGS if a != "OCC"])
def test_repair_oracle_all_backends(alg):
    _tpcc_oracle(alg)


# ---- the floor smoke (slow; acceptance pair, tools/smoke.sh repair) ---

@pytest.mark.slow
@pytest.mark.parametrize("alg", ["OCC", "MAAT"])
def test_ycsb_highwrite_repair_above_floor(alg):
    """YCSB zipf-0.9 write-heavy at the calibrated CPU point (16k rows,
    8 acc/txn, eb=512 — results/repair README): repair-on commits per
    epoch must clear the retry-only floor by >= 1.7x (measured 2.0x OCC
    / 2.4x MAAT; the margin absorbs seed variance).  Epoch-rate-free
    like the escrow floor smoke — wall-clock curves with capture
    provenance live in results/repair."""
    n = 40
    cfg = ycsb_cfg(cc_alg=alg, synth_table_size=1 << 14, req_per_query=8,
                   max_accesses=8, epoch_batch=512, conflict_buckets=2048,
                   max_txn_in_flight=2048)
    eng = Engine(cfg, get_workload(cfg))
    on = jax.device_get(eng.jit_run(eng.init_state(0), n)).stats
    off_cfg = cfg.replace(repair=False)
    eng2 = Engine(off_cfg, get_workload(off_cfg))
    off = jax.device_get(eng2.jit_run(eng2.init_state(0), n)).stats
    on_c = int(on["total_txn_commit_cnt"])
    off_c = int(off["total_txn_commit_cnt"])
    assert on_c >= 1.7 * max(off_c, 1), (alg, on_c, off_c)
    # and a strictly lower abort RATE (raw abort EVENTS can rise:
    # salvage frees slots faster, so more fresh txns enter the
    # contention — the rate is the per-attempt outcome that must drop)
    on_a, off_a = int(on["total_txn_abort_cnt"]), \
        int(off["total_txn_abort_cnt"])
    assert on_a / (on_a + on_c) < off_a / (off_a + off_c), \
        (alg, on_a, on_c, off_a, off_c)
