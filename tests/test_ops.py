"""Ops-layer kernels vs. brute-force numpy oracles."""

import numpy as np
import jax.numpy as jnp
import jax
import pytest

from deneva_tpu.ops import (
    bucket_hash, combine_key, Zipfian, last_writer,
    access_incidence, overlap, earlier_edges, greedy_first_fit,
    wavefront_levels, precedence_levels,
)


def test_bucket_hash_range_and_independence():
    keys = jnp.arange(10000, dtype=jnp.int32)
    ident = combine_key(3, keys)
    h0 = np.asarray(bucket_hash(ident, 1024, family=0))
    h1 = np.asarray(bucket_hash(ident, 1024, family=1))
    assert h0.min() >= 0 and h0.max() < 1024
    # families disagree on most keys
    assert (h0 == h1).mean() < 0.01
    # roughly uniform occupancy
    counts = np.bincount(h0, minlength=1024)
    assert counts.max() < 40

def test_combine_key_separates_tables():
    keys = jnp.arange(1000, dtype=jnp.int32)
    a = np.asarray(bucket_hash(combine_key(0, keys), 4096))
    b = np.asarray(bucket_hash(combine_key(1, keys), 4096))
    assert (a == b).mean() < 0.01


def test_zipfian_uniform_theta0():
    z = Zipfian(1000, 0.0)
    s = np.asarray(z.sample(jax.random.PRNGKey(0), (20000,)))
    assert s.min() >= 0 and s.max() < 1000
    assert abs(s.mean() - 499.5) < 15

def test_zipfian_skew():
    z = Zipfian(1 << 20, 0.9)
    s = np.asarray(z.sample(jax.random.PRNGKey(1), (50000,)))
    assert s.min() >= 0 and s.max() < (1 << 20)
    # theta=0.9 at n=2^20: ~8% of mass on the 10 hottest keys (zeta math)
    assert (s < 10).mean() > 0.06
    assert (s == 0).mean() > 0.015


def test_hotset_two_tier_split():
    from deneva_tpu.ops import HotSet
    h = HotSet(n=1 << 20, hot_max=100, access_perc=0.3)
    s = np.asarray(h.sample(jax.random.PRNGKey(2), (40000,)))
    assert s.min() >= 0 and s.max() < (1 << 20)
    hot_frac = (s < 100).mean()
    assert abs(hot_frac - 0.3) < 0.02          # ACCESS_PERC of accesses...
    hot = s[s < 100]
    assert np.bincount(hot, minlength=100).min() > 0  # ...uniform over DATA_PERC keys


def test_last_writer_oracle():
    rng = np.random.default_rng(0)
    n, cap = 256, 32
    slots = rng.integers(0, cap + 1, n).astype(np.int32)
    order = rng.integers(0, 50, n).astype(np.int32)
    mask = rng.random(n) < 0.8
    got = np.asarray(last_writer(jnp.asarray(slots), jnp.asarray(order),
                                 jnp.asarray(mask), cap))
    # oracle: per slot, winner = max order, tie -> highest index
    for s in range(cap + 1):
        idx = [i for i in range(n) if slots[i] == s and mask[i]]
        winners = [i for i in idx if got[i]]
        if not idx:
            assert not winners
            continue
        assert len(winners) == 1
        w = winners[0]
        best = max(order[i] for i in idx)
        assert order[w] == best
        assert w == max(i for i in idx if order[i] == best)
    # masked-out entries never win
    assert not got[~mask].any()


def _bruteforce_conflict(keysets_a, keysets_b):
    b = len(keysets_a)
    c = np.zeros((b, b), bool)
    for i in range(b):
        for j in range(b):
            c[i, j] = bool(keysets_a[i] & keysets_b[j])
    return c

def test_overlap_exact_with_dual_hash():
    rng = np.random.default_rng(2)
    b, a, k = 32, 6, 4096
    keys = rng.integers(0, 500, (b, a)).astype(np.int32)
    valid = rng.random((b, a)) < 0.9
    ident = combine_key(0, jnp.asarray(keys))
    inc1 = access_incidence(bucket_hash(ident, k, 0), jnp.asarray(valid), k)
    inc2 = access_incidence(bucket_hash(ident, k, 1), jnp.asarray(valid), k)
    got = np.asarray(overlap(inc1, inc1, inc2, inc2))
    sets = [set(keys[i][valid[i]].tolist()) for i in range(b)]
    want = _bruteforce_conflict(sets, sets)
    assert (got == want).all()


def _greedy_oracle(conflict, rank, active):
    b = len(rank)
    order = sorted(range(b), key=lambda i: (rank[i], i))
    win = np.zeros(b, bool)
    for i in order:
        if not active[i]:
            continue
        blocked = any(win[j] and conflict[i, j] for j in range(b) if j != i)
        win[i] = not blocked
    return win

def test_greedy_first_fit_oracle():
    rng = np.random.default_rng(3)
    b = 64
    conflict = rng.random((b, b)) < 0.08
    conflict = conflict | conflict.T
    np.fill_diagonal(conflict, True)
    rank = rng.integers(0, 20, b).astype(np.int32)
    active = rng.random(b) < 0.9
    e = earlier_edges(jnp.asarray(conflict), jnp.asarray(rank),
                      jnp.asarray(active))
    win, lose, und = (np.asarray(x) for x in
                      greedy_first_fit(e, jnp.asarray(active), rounds=b))
    assert not und.any()
    want = _greedy_oracle(conflict, rank, active)
    want &= active
    assert (win == want).all()
    assert (lose == (active & ~want)).all()

def test_greedy_first_fit_round_cap_defers_safely():
    # a chain 0-1-2-...-n: each conflicts with predecessor; few rounds
    b = 32
    conflict = np.zeros((b, b), bool)
    for i in range(1, b):
        conflict[i, i - 1] = conflict[i - 1, i] = True
    rank = np.arange(b, dtype=np.int32)
    active = np.ones(b, bool)
    e = earlier_edges(jnp.asarray(conflict), jnp.asarray(rank), jnp.asarray(active))
    win, lose, und = (np.asarray(x) for x in
                      greedy_first_fit(e, jnp.asarray(active), rounds=4))
    # decided prefix follows alternating pattern; nothing both win&lose
    assert not (win & lose).any()
    dec = win | lose
    assert dec[:4].all()
    # undecided tail exists and no undecided txn is marked winner
    assert und.any() and not (und & win).any()
    # winners among decided = even positions
    for i in range(b):
        if dec[i]:
            assert win[i] == (i % 2 == 0)


def test_wavefront_levels_chain():
    b = 16
    conflict = np.zeros((b, b), bool)
    for i in range(1, b):
        conflict[i, i - 1] = conflict[i - 1, i] = True
    rank = np.arange(b, dtype=np.int32)
    active = np.ones(b, bool)
    e = earlier_edges(jnp.asarray(conflict), jnp.asarray(rank), jnp.asarray(active))
    lv, ovf = (np.asarray(x) for x in wavefront_levels(e, max_level=20))
    assert (lv == np.arange(b)).all()
    assert not ovf.any()
    lv, ovf = (np.asarray(x) for x in wavefront_levels(e, max_level=5))
    assert ovf.sum() == b - 6


def test_precedence_levels_cycle_detection():
    b = 8
    p = np.zeros((b, b), bool)
    # chain 0->1->2, cycle 3<->4, node 5 downstream of cycle, 6,7 free
    p[0, 1] = p[1, 2] = True
    p[3, 4] = p[4, 3] = True
    p[4, 5] = True
    active = np.ones(b, bool)
    lv, unstable = (np.asarray(x) for x in
                    precedence_levels(jnp.asarray(p), jnp.asarray(active), rounds=16))
    assert lv[0] == 0 and lv[1] == 1 and lv[2] == 2
    assert not unstable[[0, 1, 2, 6, 7]].any()
    assert unstable[3] and unstable[4] and unstable[5]


@pytest.mark.slow
def test_seg_scan_matches_serial_reference():
    """The Kogge-Stone segmented scan must be exact for any associative
    combine — including an unflagged first lane and additive combines
    (regression: an earlier fill treated 0 as a combine identity)."""
    import jax.numpy as jnp
    from deneva_tpu.ops.forward import _seg_scan

    rng = np.random.default_rng(0)
    combs = {"max": max, "left": lambda a, b: a, "add": lambda a, b: a + b}
    jcombs = {"max": jnp.maximum, "left": lambda a, b: a,
              "add": lambda a, b: a + b}
    for trial in range(25):
        n = int(rng.integers(1, 50))
        f = rng.random(n) < 0.25          # flags[0] frequently False
        v = rng.integers(-9, 9, n)
        for name in combs:
            got = np.asarray(_seg_scan(jnp.asarray(f),
                                       jnp.asarray(v, jnp.int32),
                                       jcombs[name]))
            ref = np.empty(n, np.int64)
            for i in range(n):
                acc = int(v[i])
                j = i
                while not f[j] and j > 0:
                    j -= 1
                    acc = combs[name](int(v[j]), acc)
                ref[i] = acc
            assert (got == ref).all(), (trial, name)


# ---- in-batch read forwarding (ops/forward.py) -------------------------

def test_last_earlier_writer_basic():
    from deneva_tpu.ops import last_earlier_writer
    # txn0 writes k5; txn1 reads k5; txn2 writes k5; txn3 reads k5, k9
    keys = jnp.array([[5], [5], [5], [5]], jnp.int32)
    keys = jnp.concatenate([keys, jnp.array([[1], [2], [3], [9]], jnp.int32)], 1)
    is_w = jnp.array([[True, False], [False, False],
                      [True, False], [False, False]])
    valid = jnp.ones((4, 2), bool)
    rank = jnp.array([0, 1, 2, 3], jnp.int32)
    fwd = np.asarray(last_earlier_writer(keys, rank, is_w, valid))
    assert fwd[1, 0] == 0     # txn1 reads txn0's write of k5
    assert fwd[3, 0] == 2     # txn3 reads txn2's (later) write of k5
    assert fwd[0, 0] == -1    # first writer has no predecessor
    assert fwd[3, 1] == -1    # k9 never written


def test_last_earlier_writer_same_rank_not_own_write():
    from deneva_tpu.ops import last_earlier_writer
    # one txn reads k7 in lane 0 and writes k7 in lane 1: the read must
    # NOT see its own write (serial semantics: reads before writes)
    keys = jnp.full((1, 2), 7, jnp.int32)
    is_w = jnp.array([[False, True]])
    valid = jnp.ones((1, 2), bool)
    fwd = np.asarray(last_earlier_writer(keys, jnp.array([4], jnp.int32),
                                         is_w, valid))
    assert fwd[0, 0] == -1


@pytest.mark.slow
def test_last_earlier_writer_matches_serial_reference():
    from deneva_tpu.ops import last_earlier_writer
    rng = np.random.default_rng(11)
    B, A, K = 64, 6, 13
    keys = rng.integers(0, K, (B, A)).astype(np.int32)
    is_w = rng.random((B, A)) < 0.5
    valid = rng.random((B, A)) < 0.9
    rank = np.argsort(rng.random(B)).astype(np.int32)  # unique, shuffled
    got = np.asarray(last_earlier_writer(
        jnp.asarray(keys), jnp.asarray(rank), jnp.asarray(is_w),
        jnp.asarray(valid)))
    # serial reference: walk txns in rank order
    last_w = {}
    exp = np.full((B, A), -1, np.int32)
    for i in np.argsort(rank):
        for a in range(A):
            if valid[i, a]:
                exp[i, a] = last_w.get(keys[i, a], -1)
        for a in range(A):
            if valid[i, a] and is_w[i, a]:
                k = keys[i, a]
                last_w[k] = max(last_w.get(k, -1), int(rank[i]))
    # compare only on valid lanes (invalid lanes are unspecified)
    assert (got[valid] == exp[valid]).all()


def test_forward_execute_mono_scatter_matches_legacy():
    """The monotone pre-sorted scatter (mono=True, the hot-path default)
    must be bit-identical to the legacy trash-steered scatter on both
    table state and checksum — winners' values land, losers' duplicate
    rewrites are idempotent, pre-first-winner lanes drop."""
    from deneva_tpu.ops import forward_plan_flat
    from deneva_tpu.workloads.ycsb import _forward_execute_f0

    rng = np.random.default_rng(11)
    n, tab = 4096, 512
    keys = rng.integers(0, 200, n).astype(np.int32)   # heavy duplication
    keys[rng.random(n) < 0.05] = np.iinfo(np.int32).max  # invalid lanes
    rank = np.repeat(np.arange(n // 4, dtype=np.int32), 4)
    w = rng.random(n) < 0.5
    w &= keys != np.iinfo(np.int32).max
    p = forward_plan_flat(jnp.asarray(keys), jnp.asarray(rank),
                          jnp.asarray(w))
    big = jnp.int32(np.iinfo(np.int32).max)
    slots = jnp.where(p.keys != big, p.keys, tab)     # identity index
    f0 = jnp.asarray(rng.integers(0, 2**32, tab + 1, dtype=np.uint32))
    a_f0, a_cks, a_w = _forward_execute_f0(f0, p, slots, tab, mono=False)
    b_f0, b_cks, b_w = _forward_execute_f0(f0, p, slots, tab, mono=True)
    # trash slot may differ (legacy parks losers there); data rows must not
    np.testing.assert_array_equal(np.asarray(a_f0)[:tab],
                                  np.asarray(b_f0)[:tab])
    assert int(a_cks) == int(b_cks) and int(a_w) == int(b_w)


def test_forward_execute_mono_scatter_matches_legacy_full_row():
    from deneva_tpu.ops import forward_plan_flat
    from deneva_tpu.workloads.ycsb import _forward_execute_f0

    rng = np.random.default_rng(12)
    n, tab, width = 1024, 128, 24
    keys = rng.integers(0, 64, n).astype(np.int32)
    rank = np.repeat(np.arange(n // 2, dtype=np.int32), 2)
    w = rng.random(n) < 0.5
    p = forward_plan_flat(jnp.asarray(keys), jnp.asarray(rank),
                          jnp.asarray(w))
    slots = p.keys
    f0 = jnp.asarray(rng.integers(0, 256, (tab + 1, width), dtype=np.uint8))
    a_f0, a_cks, _ = _forward_execute_f0(f0, p, slots, tab, mono=False)
    b_f0, b_cks, _ = _forward_execute_f0(f0, p, slots, tab, mono=True)
    np.testing.assert_array_equal(np.asarray(a_f0)[:tab],
                                  np.asarray(b_f0)[:tab])
    assert int(a_cks) == int(b_cks)
