"""Seeded graftlint violation: a gate guard conjoined with a
device_parts comparison — the silent single-device pin that makes a
default-off subsystem vanish on the mesh-sharded measured path with no
error (gate-device-pin).  The legal shapes beside it must stay silent:
a bare device_parts branch (the measured-path route), a non-gate
conjunction (a workload layout choice), and config.py's validate()
pin (the sanctioned home, exercised via the fixture config module)."""


class ServerFx:
    def __init__(self, cfg):
        self.cfg = cfg

    def ok_mesh_route(self, cfg):
        # a bare device_parts branch routes the measured path: legal
        if cfg.device_parts > 1:
            return "mesh"
        return "single"

    def ok_non_gate_conjunction(self, cfg):
        # the workload MVCC layout idiom: cc_alg is not a gate guard,
        # so this layout choice is not a subsystem pin
        if cfg.cc_alg == "MVCC" and cfg.device_parts == 1:
            return "version-ring"
        return "flat"

    def bad_silent_pin(self, cfg):
        # audit silently vanishes the moment device_parts > 1 — the
        # pin belongs in config.validate, where it refuses out loud
        if cfg.audit and cfg.device_parts == 1:  # EXPECT[gate-device-pin]
            return "audited"
        return "un-audited"

    def bad_negated_pin(self, cfg):
        # same pin spelled through `not`: still silent, still wrong
        if not cfg.device_parts > 1 and cfg.audit:  # EXPECT[gate-device-pin]
            return "audited"
        return "un-audited"
