"""Fixture config: the real audit GateSpec's flags, default OFF (the
registry drift check cross-parses this module), plus the device_parts
knob.  config.py is EXEMPT from gate-device-pin by construction — a
validate() pin is exactly where a multi-chip compatibility constraint
belongs, erroring out loud instead of silently changing the measured
path."""


class Config:
    audit: bool = False
    audit_mutate: bool = False
    device_parts: int = 1
    node_cnt: int = 1

    def validate(self):
        # the SANCTIONED home for a pin: refuse, don't silently drop
        if self.audit_mutate and self.device_parts > 1:
            raise ValueError("audit_mutate is single-device only")
        return self
