"""Seeded graftlint violations: the REAL ``metrics`` GateSpec
(runtime/gates.py) checked against fixture call sites — an unguarded
call into the metrics-bus home module must fail the lint, the guarded
idioms the runtime actually uses (``cfg.metrics`` at construction, the
sender/aggregator handles' ``is not None`` checks, the
``rtype == "METRICS"`` route branch) must stay silent."""

from deneva_tpu.runtime.metricsbus import (Aggregator, BusSender,
                                           crit_line, frame_record)


class ServerFx:
    def __init__(self, cfg):
        self.mbus = None
        self.magg = None
        if cfg.metrics:
            # the runtime idiom: the flag test dominates construction
            self.mbus = BusSender(cfg, 0, 0)
            self.magg = Aggregator(cfg, 0)

    def ok_emit(self, epoch):
        # the sender object doubles as its own guard
        if self.mbus is not None:
            return self.mbus.frame(epoch, {})
        return None

    def ok_route(self, rtype, payload):
        # a gated rtype's route branch establishes the gate (the
        # message only exists once the subsystem armed it)
        if rtype == "METRICS":
            if self.magg is not None:
                self.magg.feed(frame_record(payload))

    def bad_record(self, payload):
        # no dominating metrics-flag test on any path to the call
        return frame_record(payload)      # EXPECT[gate-unguarded-use]

    def bad_line(self):
        return crit_line(0, {})           # EXPECT[gate-unguarded-use]
