"""Fixture stand-in for the metrics-bus subsystem's home module (never
imported at runtime; the checker resolves calls against its dotted
path).  Code HERE is exempt — it only runs once the gate armed it."""


class BusSender:
    def __init__(self, cfg, node, role):
        self.frames_sent = 0

    def frame(self, epoch, counters, density=None):
        return [], {}


class Aggregator:
    def __init__(self, cfg, node, append=False):
        self.frames_rx = 0

    def feed(self, rec):
        pass


def frame_record(buf):
    return {}


def crit_line(node, fields):
    return "[crit]"
