"""Fixture config: just the metrics flag, default OFF (the registry
drift check cross-parses this module against the REAL metrics
GateSpec)."""


class Config:
    metrics: bool = False
    metrics_cadence: int = 1
    node_cnt: int = 1
