"""Fixture stand-in for the router half of the ctrl home (RouterKnobs
construction + key coarsening).  Exempt like the controller module —
the routed step only reaches it once armed."""


def static_knobs(cfg):
    return None


def knobs_from_decision(cfg, assign, gshift, repair_cap, audit_cadence):
    return None


def coarsen_keys(batch, owner, gshift):
    return batch
