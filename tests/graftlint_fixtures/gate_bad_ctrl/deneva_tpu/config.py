"""Fixture config: just the ctrl flags, default OFF (the registry
drift check cross-parses this module against the REAL ctrl
GateSpec)."""


class Config:
    ctrl: bool = False
    zipf_shift: str = ""
    ctrl_lo: float = 0.02
    node_cnt: int = 1
