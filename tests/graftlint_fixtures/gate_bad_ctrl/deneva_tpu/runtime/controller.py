"""Fixture stand-in for the feedback control plane's home module
(never imported at runtime; the checker resolves calls against its
dotted path).  Code HERE is exempt — it only runs once the gate armed
it."""


class Controller:
    def __init__(self, cfg):
        self.seq = 0

    def decide(self, sig):
        return None


def quota_scale(idx):
    return 0.8 ** idx


def ctrl_line(node, sig, dec):
    return "[ctrl]"
