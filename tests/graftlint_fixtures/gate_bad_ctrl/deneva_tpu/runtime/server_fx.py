"""Seeded graftlint violations: the REAL ``ctrl`` GateSpec
(runtime/gates.py) checked against fixture call sites — an unguarded
call into either ctrl home module (runtime/controller.py or
cc/router.py) or an unguarded deep use of the controller handle must
fail the lint, while the guarded idioms the runtime actually uses
(``cfg.ctrl`` at construction, the handle's ``is not None`` check, the
engine's ``knobs is not None`` routing test, ``cfg.zipf_shift`` around
the client's staged ring) stay silent."""

from deneva_tpu.cc.router import coarsen_keys, static_knobs
from deneva_tpu.runtime.controller import (Controller, ctrl_line,
                                           quota_scale)


class ServerFx:
    def __init__(self, cfg):
        self.ctl = None
        if cfg.ctrl:
            # the runtime idiom: the flag test dominates construction
            self.ctl = Controller(cfg)

    def ok_tick(self, sig):
        # the controller handle doubles as its own guard
        if self.ctl is not None:
            dec = self.ctl.decide(sig)
            return quota_scale(0)
        return 1.0

    def ok_routed(self, batch, owner, knobs):
        # the engine idiom: the traced RouterKnobs operand gates the
        # routed step (`step(state, knobs=None)` dispatches on it)
        if knobs is not None:
            return coarsen_keys(batch, owner, knobs)
        return batch

    def ok_shift(self, cfg):
        # the companion load-shape flag gates the client's staged ring
        if cfg.zipf_shift:
            return static_knobs(cfg)
        return None

    def bad_decide(self, sig):
        # no dominating ctrl-flag test on any path to the use
        return self.ctl.decide(sig)       # EXPECT[gate-unguarded-use]

    def bad_knobs(self, cfg):
        return static_knobs(cfg)          # EXPECT[gate-unguarded-use]

    def bad_line(self, sig, dec):
        return ctrl_line(0, sig, dec)     # EXPECT[gate-unguarded-use]
