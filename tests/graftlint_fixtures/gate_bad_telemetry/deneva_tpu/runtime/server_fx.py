"""Seeded graftlint violations: the REAL ``telemetry`` GateSpec
(runtime/gates.py) checked against fixture call sites — an unguarded
call into the telemetry home module must fail the lint, the guarded
idioms the runtime actually uses (``cfg.telemetry`` at construction,
the recorder handle's ``self.tel is not None`` check) must stay
silent."""

from deneva_tpu.runtime.telemetry import (FlightRecorder, sampled_mask,
                                          telemetry_line)


class ServerFx:
    def __init__(self, cfg):
        self.tel = None
        if cfg.telemetry:
            # the runtime idiom: the flag test dominates construction
            self.tel = FlightRecorder(cfg, 0, "node")

    def ok_hook(self, tags):
        # the recorder object doubles as its own guard
        if self.tel is not None:
            self.tel.record(tags, 0)

    def ok_line(self, cfg):
        if cfg.telemetry:
            return telemetry_line(0, {})
        return None

    def bad_mask(self, tags):
        # no dominating telemetry-flag test on any path to the call
        return sampled_mask(tags, 8)      # EXPECT[gate-unguarded-use]

    def bad_line(self):
        return telemetry_line(0, {})      # EXPECT[gate-unguarded-use]
