"""Fixture stand-in for the telemetry subsystem's home module (never
imported at runtime; the checker resolves calls against its dotted
path).  Code HERE is exempt — it only runs once the gate armed it."""


class FlightRecorder:
    def __init__(self, cfg, node, role, append=False):
        self.sampled_cnt = 0

    def record(self, tags, stage, epoch=-1):
        return 0

    def flush(self):
        pass


def sampled_mask(tags, sample):
    return tags


def telemetry_line(node, fields):
    return "[telemetry]"
