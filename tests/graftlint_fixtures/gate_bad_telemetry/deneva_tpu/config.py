"""Fixture config: just the telemetry flag, default OFF (the registry
drift check cross-parses this module against the REAL telemetry
GateSpec)."""


class Config:
    telemetry: bool = False
    telemetry_sample: int = 1024
    telemetry_ring: int = 1 << 16
    telemetry_dir: str = ""
    node_cnt: int = 1
