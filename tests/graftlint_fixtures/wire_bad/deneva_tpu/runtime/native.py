"""Seeded graftlint violations: wire family registry fixture.

A miniature RTYPE registry that disagrees with the MINI model declared
in tests/test_graftlint.py on every axis the checker covers: EXTRA is
registered but unmodeled, the model's GHOST is unregistered, PING sits
inside the fault mask though the model classifies it outside, and the
model declares a decoder (decode_data_gone) that codec_fx.py does not
define.  Never imported.
"""

RTYPE = {"PING": 1, "DATA": 2, "EXTRA": 3}
FAULT_RTYPE_MASK = (1 << RTYPE["PING"]) | (1 << RTYPE["DATA"])  # EXPECT[wire-registry-drift] EXPECT[wire-registry-drift] EXPECT[wire-missing-codec] EXPECT[wire-fault-mask]
