# EXPECT[wire-missing-route] -- route() below has no branch for DATA,
# which the MINI model (tests/test_graftlint.py) says it must consume;
# the checker anchors that finding at line 1 of the handler's module.
"""Codec/handler fixture for the wire family (never imported)."""


def encode_data(x):
    return bytes(x)


def decode_data(buf):
    return buf


def route(self, src, rtype, payload):
    if rtype == "PING":
        return payload
    if rtype == "TYPO":              # EXPECT[wire-unknown-rtype]
        return None                  # dead branch: not in the registry
    return None


def bogus_send(tp):
    tp.send(0, "BOGUS", b"")         # EXPECT[wire-unknown-rtype]
