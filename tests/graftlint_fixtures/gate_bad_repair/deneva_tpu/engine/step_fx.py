"""Seeded graftlint violations: the REAL ``repair`` GateSpec
(runtime/gates.py) checked against fixture call sites — an unguarded
call into the repair home module must fail the lint, the guarded
idioms the runtime actually uses (``cfg.repair`` at the engine call
site, the server's cached ``self._repair``) must stay silent."""

from deneva_tpu.engine.repair import repair_line, run_repair


class EngineFx:
    def __init__(self, cfg):
        self._repair = cfg.repair

    def ok_step(self, cfg, wl, be, db, q, batch, inc, v, st, stats, ec):
        # the engine/step.py idiom: flag test dominates the call
        if cfg.repair and be.repair_rule is not None:
            db, st, v, _ = run_repair(cfg, wl, be, db, q, batch, inc,
                                      v, st, stats, ec)
        return db, st, v

    def ok_summary(self):
        # the server idiom: the cached boolean stamped in __init__
        if self._repair:
            print(repair_line(0, {"salvaged": 1}))

    def bad_step(self, cfg, wl, be, db, q, batch, inc, v, st, stats, ec):
        # no dominating repair-flag test on any path to the call
        return run_repair(cfg, wl, be, db, q,  # EXPECT[gate-unguarded-use]
                          batch, inc, v, st, stats, ec)

    def bad_line(self):
        return repair_line(0, {})            # EXPECT[gate-unguarded-use]
