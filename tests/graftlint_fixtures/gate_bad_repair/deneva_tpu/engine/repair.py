"""Fixture stand-in for the repair subsystem's home module (never
imported at runtime; the checker resolves calls against its dotted
path).  Code HERE is exempt — it only runs once the gate armed it."""


def run_repair(cfg, wl, be, db, queries, batch, inc, verdict, cc_state,
               stats, exec_commit, forced=None):
    return db, cc_state, verdict, None


def repair_line(node, fields):
    return "[repair]"
