"""Fixture config: just the repair flag, default OFF (the registry
drift check cross-parses this module against the REAL repair GateSpec)."""


class Config:
    repair: bool = False
    repair_rounds: int = 2
    node_cnt: int = 1
