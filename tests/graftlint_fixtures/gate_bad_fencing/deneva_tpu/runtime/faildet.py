"""Fixture stand-in for the fencing subsystem's home module (never
imported at runtime; the checker resolves calls against its dotted
path).  Code HERE is exempt — it only runs once the gate armed it."""


class FailureDetector:
    def __init__(self, cfg, peers, now_s):
        self.suspect_cnt = 0

    def observe(self, peer, now_s):
        return None


def fence_parts(map_version):
    return b""


def fence_peek(buf):
    return 0, 12


def encode_heartbeat(map_version, blob_seen, epoch):
    return b""


def fencing_line(node, fields):
    return "[fencing]"
