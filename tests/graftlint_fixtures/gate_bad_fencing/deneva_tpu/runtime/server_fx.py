"""Seeded graftlint violations: the REAL ``fencing`` GateSpec
(runtime/gates.py) checked against fixture call sites — an unguarded
call into the faildet home module must fail the lint, the guarded
idioms the runtime actually uses (``cfg.fencing`` at construction, the
node's cached ``self._fencing``, the detector object's
``self._fd is not None``) must stay silent."""

from deneva_tpu.runtime.faildet import (FailureDetector, fence_parts,
                                        fencing_line)


class ServerFx:
    def __init__(self, cfg):
        self._fencing = cfg.fencing
        self._fd = None
        if cfg.fencing:
            # the runtime idiom: the flag test dominates construction
            self._fd = FailureDetector(cfg, [1, 2], 0.0)

    def ok_route(self, src, now_s):
        # the detector object doubles as its own guard
        if self._fd is not None:
            self._fd.observe(src, now_s)

    def ok_bcast(self, version):
        # the cached boolean stamped in __init__
        if self._fencing:
            return fence_parts(version)
        return None

    def bad_bcast(self, version):
        # no dominating fencing-flag test on any path to the call
        return fence_parts(version)       # EXPECT[gate-unguarded-use]

    def bad_line(self):
        return fencing_line(0, {})        # EXPECT[gate-unguarded-use]
