"""Fixture config: just the fencing flag, default OFF (the registry
drift check cross-parses this module against the REAL fencing
GateSpec)."""


class Config:
    fencing: bool = False
    fencing_phi: float = 8.0
    fencing_heartbeat_ms: float = 100.0
    fencing_suspect_s: float = 2.0
    elastic: bool = False
    node_cnt: int = 1
