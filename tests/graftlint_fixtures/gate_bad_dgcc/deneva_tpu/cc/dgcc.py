"""Fixture stand-in for the DGCC wavefront home module (never
imported at runtime; the checker resolves calls against its dotted
path).  Code HERE is exempt — it only runs once the gate armed it or
the registry dispatched the algorithm."""


def dgcc_levels(cfg, batch):
    return None


def validate_dgcc(cfg, state, batch, inc=None, stats=None):
    return None
