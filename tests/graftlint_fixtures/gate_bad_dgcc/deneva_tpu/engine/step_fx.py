"""Seeded graftlint violations: the REAL ``dgcc`` GateSpec
(runtime/gates.py) checked against fixture call sites — an unguarded
call into the wavefront home module (cc/dgcc.py) or an unguarded
wave-assignment use_call must fail the lint, while the guarded idioms
the runtime uses (``cfg.ctrl_dgcc`` dominating the call, a local alias
of the flag) stay silent."""

from deneva_tpu.cc.dgcc import dgcc_levels, validate_dgcc


class StepFx:
    def ok_routed(self, cfg, state, batch):
        # the runtime idiom: the routing flag dominates the home call
        if cfg.ctrl_dgcc:
            return validate_dgcc(cfg, state, batch)
        return None

    def ok_alias(self, cfg, batch):
        # a local alias of the flag inherits guard-ness
        armed = cfg.ctrl_dgcc
        if armed:
            return dgcc_levels(cfg, batch)
        return None

    def bad_validate(self, cfg, state, batch):
        # no dominating ctrl_dgcc test on any path to the home call
        return validate_dgcc(cfg, state, batch)  # EXPECT[gate-unguarded-use]

    def bad_waves(self, cfg, batch):
        return dgcc_levels(cfg, batch)        # EXPECT[gate-unguarded-use]
