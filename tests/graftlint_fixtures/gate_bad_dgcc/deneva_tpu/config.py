"""Fixture config: the dgcc routing flag, default OFF (the registry
drift check cross-parses this module against the REAL dgcc
GateSpec)."""


class Config:
    ctrl_dgcc: bool = False
    dgcc_levels: int = 32
    node_cnt: int = 1
