# EXPECT[gate-registry-drift] EXPECT[gate-rtype-mask] — line-1 anchors:
# the registry-level findings (unknown flag; gated rtype inside the
# fault mask) have no better source line than the config module head.
class Config:
    fx_flag: bool = False
    bad_flag: int = 3                    # EXPECT[gate-registry-drift]
