"""Seeded graftlint violation: gate-guard-shed (never imported).

A miniature ServerNode that REBINDS a guarded collection outside
__init__ — the owner_check wrapper lives on the object, so the rebind
sheds it (the PR 6 _rejoin_pending lesson).  Checked with
guarded=("pending",) from the test.
"""


class ServerNode:
    def __init__(self):
        self.pending = []                # __init__ builds: pre-install

    def _rejoin(self):
        self.pending = []                # EXPECT[gate-guard-shed]

    def ok_mutate(self):
        self.pending.clear()
        self.pending.append(1)
