"""Fixture subsystem HOME module: code here only runs once armed, so
nothing inside it needs (or gets) gate checking."""


def fx_do():
    return 1


def fx_other():
    return fx_do() + 1
