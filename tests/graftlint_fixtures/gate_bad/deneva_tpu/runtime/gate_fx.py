"""Seeded graftlint violations: gate-consistency family (never
imported).  Checked against a fixture registry (see test_graftlint._GFX)
— one subsystem "fx" with flag fx_flag, home fxsub.py, object attr fxo.

The ok_* shapes pin every gating idiom the checker must accept: plain
if, early return, IfExp, and/or short-circuit, guard alias through a
local, `is not None` on the subsystem object, gated-rtype route branch,
and a helper whose every call site is guarded.
"""

from deneva_tpu.runtime import fxsub


class Node:
    def __init__(self, cfg):
        self._fx = cfg.fx_flag
        self.fxo = fxsub.fx_do if cfg.fx_flag else None

    def ok_if(self):
        if self._fx:
            fxsub.fx_do()

    def ok_early(self):
        if not self._fx:
            return
        fxsub.fx_do()

    def ok_ifexp(self):
        return fxsub.fx_do() if self._fx else None

    def ok_and(self):
        return self._fx and fxsub.fx_do()

    def ok_alias(self, cfg):
        armed = cfg.fx_flag and cfg.node_cnt
        if armed:
            fxsub.fx_do()

    def ok_attr(self):
        if self.fxo is not None:
            self.fxo.poke()

    def ok_route(self, rtype, payload):
        if rtype == "FXMSG":
            fxsub.fx_do()            # arrival implies the sender armed it

    def _helper(self):
        fxsub.fx_other()             # every call site is guarded: silent

    def run(self):
        if self._fx:
            self._helper()

    def bad_call(self):
        fxsub.fx_do()                # EXPECT[gate-unguarded-use]

    def bad_attr(self):
        self.fxo.poke()              # EXPECT[gate-unguarded-use]

    def bad_after_or(self, cfg):
        # `a or b` true edge proves only ONE disjunct; no gate
        if self._fx or cfg.node_cnt:
            fxsub.fx_do()            # EXPECT[gate-unguarded-use]


def esc_ok(cfg, be, planned):
    return fx_gate(cfg, be, planned.get("order_free"))


def esc_bad(planned):
    return planned.get("order_free")     # EXPECT[gate-escrow-raw]


def esc_bad_attr(batch):
    return batch.order_free              # EXPECT[gate-escrow-raw]


def fx_gate(cfg, be, mask):
    """Fixture escrow gate function (registered via the test)."""
    return mask if cfg.fx_flag else None
