"""Seeded graftlint violations: thread-ownership family.

A miniature ServerNode whose worker-entry methods (names taken from the
real runtime/ownercheck.WORKER_ENTRY declarations) mutate dispatch-owned
state.  The path mimics deneva_tpu/runtime/server.py because the
ownership checker anchors there; it is never imported.
"""


class ServerNode:
    def __init__(self):
        self.stats = None
        self.pending = []
        self._held_rsp = []
        self.mystery_attr = 0            # EXPECT[own-undeclared-attr]

    def _bcast_views(self, item):
        self.stats = item                # EXPECT[own-cross-thread-write]
        self.pending.append(item)        # EXPECT[own-cross-thread-write]

    def _prefetch_retire(self, item):
        self._held_rsp.append(item)      # EXPECT[own-cross-thread-write]

    def _dispatch_ok(self, item):
        # not reachable from any worker entry: dispatch-loop code may
        # mutate freely
        self.pending.append(item)
