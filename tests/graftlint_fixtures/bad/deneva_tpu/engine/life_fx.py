"""Seeded graftlint violations: lifecycle family (never imported).

One violation per EXPECT-marker line; the ok_* shapes prove the
try/finally discipline (and `with`, and daemon threads) stay silent.
Path mimics deneva_tpu/engine/ like the other bad fixtures.
"""

import threading


def touch(x):
    return len(x)


def unjoined_thread(work):
    t = threading.Thread(target=work)    # EXPECT[life-unjoined-thread]
    t.start()
    touch(work)
    t.join()                             # not on the exception path


def joined_ok(work):
    t = threading.Thread(target=work)
    t.start()
    try:
        touch(work)
    finally:
        t.join()


def daemon_ok(work):
    t = threading.Thread(target=work, daemon=True)
    t.start()
    touch(work)


def undrained_future(pool, blob):
    f = pool.submit(len, blob)           # EXPECT[life-undrained-future]
    touch(blob)
    return f.result()                    # not on the exception path


def undrained_list(pool, items):
    futs = []
    for it in items:
        futs.append(pool.submit(len, it))  # EXPECT[life-undrained-future]
    touch(items)
    for f in futs:
        f.result()


def drained_ok(pool, blob):
    futs = []
    try:
        futs.append(pool.submit(len, blob))
        touch(blob)
    finally:
        for f in futs:
            f.result()


def unclosed_file(path):
    f = open(path)                       # EXPECT[life-unclosed-resource]
    data = f.read()
    f.close()                            # not on the exception path
    return data


def closed_ok(path):
    f = open(path)
    try:
        return f.read()
    finally:
        f.close()


def with_ok(path):
    with open(path) as f:
        return f.read()


class Keeper:
    """Attr-stored closable with no close anywhere in the class."""

    def __init__(self, path):
        self._f = open(path)             # EXPECT[life-unclosed-resource]

    def read(self):
        return self._f.read()


class Closer:
    def __init__(self, path):
        self._f = open(path)

    def close(self):
        self._f.close()
