"""Seeded graftlint violations: jit-stability family (never imported).

One violation per EXPECT-marker line; the ok_* shapes prove static
positions, immutable tables and shape-metadata calls stay silent.
"""

import functools

import jax
import jax.numpy as jnp

_CACHE = {}                  # mutated below: jit capture goes stale
_TABLE = {"a": 1}            # never mutated: bakeable constant, exempt


def note(k, v):
    _CACHE[k] = v


@jax.jit
def dyn_shape(x):
    idx = jnp.nonzero(x)             # EXPECT[jit-dynamic-shape]
    n = x.sum()
    pad = jnp.zeros(n)               # EXPECT[jit-dynamic-shape]
    ok = jnp.zeros(jnp.shape(x))     # shape metadata is static: silent
    return idx, pad, ok


@jax.jit
def reads_mut_global(x):
    return x + _CACHE["k"]           # EXPECT[jit-mutable-global]


@jax.jit
def reads_const_global(x):
    return x + _TABLE["a"]


@functools.partial(jax.jit, static_argnums=(1,))
def stat_default(x, spec=[]):        # EXPECT[jit-unhashable-static]
    return x


@functools.partial(jax.jit, static_argnums=(1,))
def weak_fx(x, mode):
    return x


def call_weak_fx(db):
    good = weak_fx(db, 1)            # static position: hashes, silent
    bad = weak_fx(0.5, 1)            # EXPECT[jit-weak-dtype]
    return good, bad
