"""Seeded graftlint violations: trace + det families.

One violation per EXPECT-marker line; tests/test_graftlint.py
asserts each rule fires exactly at its marker and nowhere else.  This
file is never imported — it only has to parse.  Its path mimics
deneva_tpu/engine/ so the determinism family (which is scoped to
replay-relevant module prefixes) treats it as in-scope.
"""

import functools
import random
import time

import jax
import numpy as np


@jax.jit
def bad_branch(db, x):
    if x > 0:                        # EXPECT[trace-branch]
        x = x + 1
    y = np.abs(x)                    # EXPECT[trace-np-call]
    z = float(x)                     # EXPECT[trace-host-sync]
    return db, x, y, z


def helper(v):
    return v.item()                  # EXPECT[trace-host-sync]


@jax.jit
def entry(x):
    return helper(x)


@functools.partial(jax.jit, static_argnums=(1,))
def run_fx(db, spec):
    return db


def call_run_fx(db):
    return run_fx(db, {"mode": 1})   # EXPECT[trace-unstable-static]


def draw_fx():
    a = random.random()              # EXPECT[det-unseeded-rng]
    b = np.random.rand(3)            # EXPECT[det-unseeded-rng]
    t = time.time()                  # EXPECT[det-wallclock]
    return a, b, t


def emit_fx(tp, peers):
    for p, payload in peers.items():     # EXPECT[det-unordered-iter]
        tp.send(p, "EPOCH_BLOB", payload)


def emit_wrapped_fx(tp):
    gone = {4, 7}
    # list()/enumerate() copy the set's order, they don't fix it
    for i, p in enumerate(list(gone)):   # EXPECT[det-unordered-iter]
        tp.send(p, "EPOCH_BLOB", bytes([i]))


def emit_taint_fx(tp, d):
    # v2 flow-sensitive shape (the round-9 soft spot): a PLAIN
    # `for k in d:` whose order taint reaches the sink through an
    # accumulator — no dict-view call anywhere near the loop
    d.setdefault(0, b"")
    out = []
    for k in d:                          # EXPECT[det-unordered-iter]
        out.append(k)
    tp.send(0, "EPOCH_BLOB", bytes(out))


def emit_sorted_ok(tp, d):
    # same shape, cleansed: rebinding through sorted() kills the taint
    d.setdefault(0, b"")
    out = []
    for k in d:
        out.append(k)
    out = sorted(out)
    tp.send(0, "EPOCH_BLOB", bytes(out))


def emit_fold_ok(tp, d):
    # commutative fold: order-insensitive by construction
    d.setdefault(0, b"")
    acc = 0
    for k in d:
        acc |= k
    tp.send(0, "EPOCH_BLOB", bytes([acc]))
