"""Seeded graftlint violations: imports family (never imported)."""

import os                            # EXPECT[imp-unused]
import json
import json                          # EXPECT[imp-redefined]


def use():
    return json.dumps({})
