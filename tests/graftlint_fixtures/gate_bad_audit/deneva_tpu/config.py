"""Fixture config: just the audit flags, default OFF (the registry
drift check cross-parses this module against the REAL audit
GateSpec)."""


class Config:
    audit: bool = False
    audit_mutate: str = ""
    audit_cadence: int = 1
    node_cnt: int = 1
