"""Fixture stand-in for the isolation-audit plane's home module (never
imported at runtime; the checker resolves calls against its dotted
path).  Code HERE is exempt — it only runs once the gate armed it."""


class AuditExporter:
    def __init__(self, cfg, node, b_loc, lo, append=False):
        self.epochs_exported = 0

    def export(self, epoch, edges, ebkt, cnt, dropped, vdig, rdig,
               commit, tags):
        pass


def audit_line(node, fields):
    return "[audit]"


def decode_edge(e):
    return 0, 0, 0
