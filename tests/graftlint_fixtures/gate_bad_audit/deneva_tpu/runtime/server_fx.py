"""Seeded graftlint violations: the REAL ``audit`` GateSpec
(runtime/gates.py) checked against fixture call sites — an unguarded
call into the audit home module OR an unguarded use of the declared
device-derivation use_calls (cc/base's audit_observe family) must fail
the lint, while the guarded idioms the runtime actually uses
(``cfg.audit`` at construction, the exporter handle's ``is not None``
check, ``cfg.audit_mutate`` around the seeded fault) stay silent."""

from deneva_tpu.runtime.audit import AuditExporter, audit_line


def audit_observe(cfg, batch):
    # bare-name stand-in for the cc/base device derivation (use_calls
    # match by name wherever they appear)
    return None


class ServerFx:
    def __init__(self, cfg):
        self.aud = None
        if cfg.audit:
            # the runtime idiom: the flag test dominates construction
            self.aud = AuditExporter(cfg, 0, 1, 0)

    def ok_export(self, epoch):
        # the exporter object doubles as its own guard
        if self.aud is not None:
            self.aud.export(epoch, [], [], 0, 0, 0, 0, 0, [])

    def ok_observe(self, cfg, batch):
        if cfg.audit:
            return audit_observe(cfg, batch)
        return None

    def ok_mutate_guard(self, cfg, batch):
        # the chaos fault knob is a flag of the same gate
        if cfg.audit_mutate:
            return audit_observe(cfg, batch)
        return None

    def bad_observe(self, cfg, batch):
        # no dominating audit-flag test on any path to the call
        return audit_observe(cfg, batch)  # EXPECT[gate-unguarded-use]

    def bad_line(self):
        return audit_line(0, {})          # EXPECT[gate-unguarded-use]
