"""Clean fixture: jit-reachable code, replay-relevant path shape, wire
sends — written the way the rules demand.  Every graftlint family must
stay silent on this tree (tests/test_graftlint.py)."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def good_step(db, x):
    # data-dependent decisions stay on the device
    y = jnp.where(x > 0, x, -x)
    return db, y


def host_emit(tp, peers):
    # deterministic iteration order into the transport
    for p in sorted(peers):
        tp.send(p, "EPOCH_BLOB", peers[p])


def host_stats(arr):
    # numpy on host values (not jit-reachable) is fine
    return np.asarray(arr).sum()


def seeded_draw(seed):
    # seeded generator RNG is replay-safe
    return np.random.default_rng(seed).integers(0, 10, 4)


def annotated_emit(tp, ds: "Dataset"):
    # "set" as a SUBSTRING of a type name must not mark `ds` as a set
    # (insertion-ordered mapping: iteration is deterministic)
    for p in ds:
        tp.send(p, "EPOCH_BLOB", ds[p])
