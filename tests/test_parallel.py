"""Partition-parallel engine on the virtual 8-device CPU mesh.

The analogue of the reference's multi-node-on-one-box IPC rig
(SURVEY §4.4): the full sharded path — partitioned tables, sharded
conflict matmul with cross-device reduction — executes for real.
"""

import numpy as np
import jax
import pytest

from deneva_tpu.config import Config
from deneva_tpu.engine import Engine
from deneva_tpu.parallel import make_mesh, make_sharded_run, state_shardings
from deneva_tpu.workloads import get_workload


def cfg_for(alg):
    return Config(cc_alg=alg, epoch_batch=64, conflict_buckets=1024,
                  max_accesses=4, req_per_query=4, synth_table_size=4096,
                  zipf_theta=0.6, max_txn_in_flight=256)


@pytest.mark.slow
@pytest.mark.parametrize("alg", ["OCC", "TPU_BATCH", "TIMESTAMP"])
def test_sharded_run_matches_single_device(alg):
    cfg = cfg_for(alg)
    eng = Engine(cfg, get_workload(cfg))

    s0 = eng.init_state(seed=3)
    ref = eng.jit_run(s0, 12)
    ref_stats = {k: np.asarray(v) for k, v in
                 jax.device_get(ref.stats).items()}

    mesh = make_mesh(8)
    place, run = make_sharded_run(eng, mesh)
    s1 = place(eng.init_state(seed=3))
    out = run(s1, 12)
    out_stats = {k: np.asarray(v) for k, v in
                 jax.device_get(out.stats).items()}

    for k in ref_stats:
        assert (ref_stats[k] == out_stats[k]).all(), k


@pytest.mark.slow
def test_partition_parallel_forwarding_matches_single_device():
    """device_parts=8: tables shard owner-major and each device plans +
    executes only its keyspace partition (ycsb.execute_mc under
    shard_map).  Serial semantics are device-count-invariant, so every
    counter — including the read checksum over forwarded values — must
    be bit-identical to the single-device run."""
    cfg = cfg_for("TPU_BATCH")
    eng = Engine(cfg, get_workload(cfg))
    ref = eng.jit_run(eng.init_state(seed=5), 12)
    ref_stats = {k: np.asarray(v) for k, v in
                 jax.device_get(ref.stats).items()}

    cfg8 = cfg.replace(device_parts=8)
    eng8 = Engine(cfg8, get_workload(cfg8))
    mesh = make_mesh(8)
    place, run = make_sharded_run(eng8, mesh)
    out = run(place(eng8.init_state(seed=5)), 12)
    out_stats = {k: np.asarray(v) for k, v in
                 jax.device_get(out.stats).items()}
    for k in ref_stats:
        assert (ref_stats[k] == out_stats[k]).all(), k


@pytest.mark.slow
def test_partition_parallel_full_pool_and_forced_aborts():
    """The multi-chip executor composes with full-pool epochs and the
    forced-abort sentinel (forced txns leave the batch before the
    per-shard plans are built, so no shard applies their writes)."""
    cfg = cfg_for("TPU_BATCH").replace(
        epoch_batch=256, max_txn_in_flight=256, zipf_theta=0.9,
        synth_table_size=4096, ycsb_abort_mode=True)
    ref = Engine(cfg, get_workload(cfg))
    r = ref.jit_run(ref.init_state(seed=2), 10)
    ref_stats = {k: np.asarray(v) for k, v in jax.device_get(r.stats).items()}

    cfg8 = cfg.replace(device_parts=8)
    eng8 = Engine(cfg8, get_workload(cfg8))
    assert eng8.pool.full_pool
    mesh = make_mesh(8)
    place, run = make_sharded_run(eng8, mesh)
    out = run(place(eng8.init_state(seed=2)), 10)
    out_stats = {k: np.asarray(v) for k, v in
                 jax.device_get(out.stats).items()}
    assert int(out_stats["total_txn_abort_cnt"]) > 0
    for k in ref_stats:
        assert (ref_stats[k] == out_stats[k]).all(), k


def _mc_bit_identity(cfg, seed=7, epochs=10):
    """stats of an 8-partition run must equal the single-device run
    bit-for-bit (serial semantics are device-count-invariant; the mc.py
    executor contract makes every counter exactly reconstructable)."""
    eng = Engine(cfg, get_workload(cfg))
    ref = jax.device_get(eng.jit_run(eng.init_state(seed=seed), epochs).stats)
    cfg8 = cfg.replace(device_parts=8)
    eng8 = Engine(cfg8, get_workload(cfg8))
    place, run = make_sharded_run(eng8, make_mesh(8))
    out = jax.device_get(run(place(eng8.init_state(seed=seed)), epochs).stats)
    for k in ref:
        assert (np.asarray(ref[k]) == np.asarray(out[k])).all(), k
    assert int(out["total_txn_commit_cnt"]) > 0
    return out


TPCC_MC = Config(workload="TPCC", cc_alg="TPU_BATCH", epoch_batch=64,
                 conflict_buckets=1024, num_wh=8, cust_per_dist=30,
                 max_items=100, max_accesses=18, max_txn_in_flight=256,
                 insert_table_cap=1 << 10)
PPS_MC = Config(workload="PPS", cc_alg="TPU_BATCH", epoch_batch=64,
                conflict_buckets=1024, pps_parts_cnt=400,
                pps_products_cnt=80, pps_suppliers_cnt=80, pps_parts_per=4,
                max_accesses=9, max_txn_in_flight=256)


@pytest.mark.parametrize("alg", ["TPU_BATCH", "NO_WAIT"])
def test_tpcc_partition_parallel_matches_single_device(alg):
    """TPC-C multi-chip (VERDICT round-1 #1): warehouses shard owner-major
    (the reference's wh_to_part node partition, `benchmarks/
    tpcc_helper.cpp`, across chips); remote-customer payments and
    remote-supply neworder stock rows split across their owners like the
    reference's remote hops (`tpcc_txn.cpp:332-368`)."""
    _mc_bit_identity(TPCC_MC.replace(cc_alg=alg))


@pytest.mark.parametrize("alg", ["TPU_BATCH", "MAAT"])
def test_pps_partition_parallel_matches_single_device(alg):
    """PPS multi-chip: anchor keys stripe across chips; the replicated
    USES/SUPPLIES mapping tables keep recon local (`pps_wl.cpp`)."""
    _mc_bit_identity(PPS_MC.replace(cc_alg=alg))


def test_ycsb_chained_calvin_partition_parallel():
    """CALVIN's chained wavefront execution runs partition-parallel: the
    replicated verdict plays the sequencer broadcast, each chip executes
    its partition's slice of every level."""
    out = _mc_bit_identity(cfg_for("CALVIN"))
    assert int(out["write_cnt"]) > 0


def test_mc_plan_defer_marks_overflow_txns():
    """Sharded-plan capacity (VERDICT r3 missing #2): txns whose owned
    lanes land past a chip's plan buffer defer — a replicated,
    deterministic decision (the MoE capacity pattern with deferral
    instead of dropping)."""
    import jax.numpy as jnp

    from deneva_tpu.ops import mc_plan_defer

    # 4 txns x 2 lanes, every key even -> all owned by chip 0 of D=2.
    # Flat lanes split into two source slices of 4: slice 0 = txns 0-1,
    # slice 1 = txns 2-3.  Priority is AGE (smallest ts first), not
    # slot order: in slice 0 the SECOND txn is older, so capacity 2
    # keeps it and defers the slot-earlier-but-younger first txn —
    # the starvation-freedom property (a deferred txn ages upward).
    keys = jnp.asarray([[0, 2], [4, 6], [8, 10], [12, 14]], jnp.int32)
    valid = jnp.ones((4, 2), bool)
    ts = jnp.asarray([9, 1, 2, 8], jnp.int32)
    dfr = np.asarray(mc_plan_defer(keys, ts, valid, 2, 2))
    assert list(dfr) == [True, False, False, True]
    # ample capacity: nobody defers
    assert not np.asarray(mc_plan_defer(keys, ts, valid, 2, 4)).any()


@pytest.mark.slow
def test_sharded_plan_path_bit_identical_to_single_device():
    """Bit-identity THROUGH the active sharded-plan path: these shapes
    give pair_cap = 512 < sl = 2048 (the all_to_all routing actually
    runs, unlike the small-shape tests whose mc_pair_cap falls back to
    the replicated plan), while moderate skew plus an ample capacity
    factor keeps defers at zero — so every counter, including the read
    checksum over forwarded values, must equal the single-device run."""
    from deneva_tpu.ops import mc_pair_cap

    cfg = cfg_for("TPU_BATCH").replace(
        epoch_batch=4096, max_txn_in_flight=4096, zipf_theta=0.6,
        synth_table_size=8192)
    assert 0 < mc_pair_cap(4096, 4, 8, cfg.mc_plan_capacity) < 4096 * 4 // 8
    eng = Engine(cfg, get_workload(cfg))
    ref = jax.device_get(eng.jit_run(eng.init_state(seed=6), 8).stats)
    cfg8 = cfg.replace(device_parts=8)
    eng8 = Engine(cfg8, get_workload(cfg8))
    place, run = make_sharded_run(eng8, make_mesh(8))
    out = jax.device_get(run(place(eng8.init_state(seed=6)), 8).stats)
    assert int(np.asarray(out["defer_cnt"])) == 0   # capacity ample
    for k in ref:
        assert (np.asarray(ref[k]) == np.asarray(out[k])).all(), k
    assert int(np.asarray(out["total_txn_commit_cnt"])) > 0


@pytest.mark.slow
def test_mc_plan_capacity_overflow_defers_and_recovers():
    """Engine-level: a deliberately tight plan capacity under hot skew
    forces overflow defers; conservation must hold (no drops) and the
    oldest-first retry keeps committing (liveness)."""
    cfg = cfg_for("TPU_BATCH").replace(
        epoch_batch=4096, max_txn_in_flight=4096, zipf_theta=0.9,
        synth_table_size=4096, device_parts=8, mc_plan_capacity=0.25)
    eng = Engine(cfg, get_workload(cfg))
    place, run = make_sharded_run(eng, make_mesh(8))
    out = run(place(eng.init_state(seed=4)), 8)
    stats = {k: np.asarray(v) for k, v in jax.device_get(out.stats).items()}
    inflight = int(np.asarray(jax.device_get(out.pool.occupied)).sum())
    assert int(stats["defer_cnt"]) > 0          # capacity actually bound
    assert int(stats["total_txn_commit_cnt"]) > 0
    assert int(stats["total_txn_commit_cnt"]) + inflight \
        == int(stats["admitted_cnt"])           # no drops
    assert int(stats["total_txn_abort_cnt"]) == 0


def test_state_shardings_partition_tables():
    cfg = cfg_for("TIMESTAMP")
    eng = Engine(cfg, get_workload(cfg))
    state = eng.init_state()
    mesh = make_mesh(8)
    sh = state_shardings(mesh, state)
    from deneva_tpu.parallel.mesh import AXIS
    f0 = sh.db["MAIN_TABLE"].columns["F0"]
    assert f0.spec == jax.sharding.PartitionSpec(AXIS)
    assert sh.cc_state.rts.spec == jax.sharding.PartitionSpec(AXIS)
    assert sh.pool.ts.spec == jax.sharding.PartitionSpec()
