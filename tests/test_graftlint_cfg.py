"""Unit tests for the graftlint v2 CFG/dataflow core (PR 9).

The checker families lean on three facts — exception edges, dominance,
reaching definitions — so each is pinned directly here, independent of
any rule: a finally intercepts every exit route (normal, exceptional,
early return), dominance answers the gate family's "must this check
have run", and reaching defs kill on rebind (the sorted() cleanse).
"""

import ast
import textwrap

from tools.graftlint.cfg import (CFG, EXC, FALSE, RET, TRUE, _may_raise,
                                 own_nodes, reachable_nodes, stmt_defs)


def _cfg(src: str) -> CFG:
    return CFG(ast.parse(textwrap.dedent(src)).body[0])


def _block_of_call(c: CFG, name: str):
    """The block holding the statement that calls `name` (compound
    statements own only their headers, so a call in an if-BODY resolves
    to the body block, not the branch block)."""
    for b in c.blocks:
        for s in b.stmts:
            for n in own_nodes(s):
                if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                        and n.func.id == name:
                    return b
    raise AssertionError(f"no block calls {name}")


# ---- branch edges + dominance ------------------------------------------

DIAMOND = """
def f(x):
    if x:
        a()
    else:
        b()
    join()
"""


def test_if_edges_are_labeled():
    c = _cfg(DIAMOND)
    branch = next(b for b in c.blocks if b.test is not None)
    kinds = sorted(k for _s, k in branch.succs)
    assert kinds == [FALSE, TRUE]


def test_dominance_diamond():
    c = _cfg(DIAMOND)
    branch = next(b for b in c.blocks if b.test is not None)
    ba, bb = _block_of_call(c, "a"), _block_of_call(c, "b")
    bj = _block_of_call(c, "join")
    assert c.dominates(branch, bj)
    assert not c.dominates(ba, bj) and not c.dominates(bb, bj)
    assert c.idoms()[bj.id] is branch       # idom of the join = branch
    assert c.dominates(c.entry, c.exit)


# ---- exception edges ----------------------------------------------------

def test_call_gets_exception_edge_to_handler():
    c = _cfg("""
    def f():
        try:
            work()
        except ValueError:
            handle()
        after()
    """)
    bw = _block_of_call(c, "work")
    bh = _block_of_call(c, "handle")
    assert any(k == EXC and s is bh for s, k in bw.succs)


def test_call_outside_try_raises_to_exit():
    c = _cfg("""
    def f():
        work()
        after()
    """)
    bw = _block_of_call(c, "work")
    assert any(k == EXC and s is c.exit for s, k in bw.succs)


def test_finally_intercepts_return_and_exception():
    c = _cfg("""
    def f(x):
        t = acquire()
        try:
            if x:
                return 0
            work(t)
        finally:
            t.close()
        return 1
    """)
    fin = next(b for b in c.blocks if b.in_finally)
    # the early return routes THROUGH the finally, not past it
    ret_blocks = [b for b in c.blocks
                  if any(isinstance(s, ast.Return) and s.value is not None
                         and isinstance(s.value, ast.Constant)
                         and s.value.value == 0 for s in b.stmts)]
    assert ret_blocks and all(
        any(k == RET and s.in_finally for s, k in b.succs)
        for b in ret_blocks)
    # work(t) raising also lands in the finally
    bw = _block_of_call(c, "work")
    assert any(k == EXC and s.in_finally for s, k in bw.succs)
    # and the finally, having seen a return, can continue to the exit
    fin_tail = [b for b in c.blocks if b.in_finally]
    assert any(k == RET and s is c.exit
               for b in fin_tail for s, k in b.succs)
    assert fin is not None


def test_nested_def_body_does_not_raise():
    """Defining a closure is not executing it: the def statement must
    not split the block with an exception edge (the wirebench false
    positive class)."""
    stmt = ast.parse(textwrap.dedent("""
    def settle():
        for _ in range(200):
            poll()
    """)).body[0]
    assert not _may_raise(stmt)
    c = _cfg("""
    def f():
        t = acquire()
        def settle():
            poll()
        t.close()
    """)
    bt = _block_of_call(c, "acquire")
    # acquire's block continues into close without an intervening
    # exc-split caused by the nested def
    nxt = [s for s, k in bt.succs if k != EXC]
    assert len(nxt) == 1
    assert any(isinstance(n, ast.Call) and getattr(n.func, "attr", "")
               == "close" for s in nxt[0].stmts for n in ast.walk(s))


# ---- reaching definitions ----------------------------------------------

def test_reaching_defs_branch_join_unions():
    c = _cfg("""
    def f(x):
        if x:
            v = 1
        else:
            v = 2
        sink(v)
    """)
    bj = _block_of_call(c, "sink")
    reach = c.reaching_defs()[bj.id]
    assert len(reach["v"]) == 2             # both defs reach the join


def test_reaching_defs_rebind_kills():
    c = _cfg("""
    def f(d):
        v = list(d)
        v = sorted(v)
        sink(v)
    """)
    bj = _block_of_call(c, "sink")
    reach = c.reaching_defs()[bj.id]
    assert len(reach["v"]) == 1             # the rebind killed def #1


def test_stmt_defs_shapes():
    mod = ast.parse("a, (b, c) = x\nfor k, v in items: pass\n"
                    "with open(p) as f: pass")
    assert stmt_defs(mod.body[0]) == ["a", "b", "c"]
    assert sorted(stmt_defs(mod.body[1])) == ["k", "v"]
    assert stmt_defs(mod.body[2]) == ["f"]


# ---- reachability -------------------------------------------------------

def test_reachable_nodes_skip_dead_code():
    c = _cfg("""
    def f():
        live()
        return 1
        dead()
    """)
    calls = {n.func.id for _s, n in reachable_nodes(c)
             if isinstance(n, ast.Call) and isinstance(n.func, ast.Name)}
    assert "live" in calls and "dead" not in calls
