"""_RetryQueue unit tests (reference `system/abort_queue.cpp:26-50`):
backoff-exponent clamping, partial-block pop slicing, and defer
re-entry semantics — the host-side retry policy the cluster loop
routes every abort/defer through."""

import numpy as np

from deneva_tpu.runtime import wire
from deneva_tpu.runtime.server import _RetryQueue


def _blk(n, tag0=0):
    return wire.QueryBlock(
        keys=np.arange(n * 2, dtype=np.int32).reshape(n, 2),
        types=np.ones((n, 2), np.int8),
        scalars=np.zeros((n, 0), np.int32),
        tags=np.arange(tag0, tag0 + n, dtype=np.int64))


def test_backoff_exponent_clamps_past_cnt_32():
    """2**(cnt-1) overflows int32 past cnt=32; the exponent (not just
    the power) must clamp so the penalty never goes negative and never
    exceeds the cap."""
    q = _RetryQueue(backoff=True, cap=64)
    counts = np.array([1, 2, 7, 32, 33, 40, 1000], np.int32)
    q.push(_blk(7), counts, np.arange(1, 8, dtype=np.int64), epoch=10)
    readies = sorted(r for r, *_ in q.items)
    # penalty = min(2**min(cnt-1, log2(cap)), cap), ready = epoch+1+pen
    want = sorted({10 + 1 + min(2 ** min(c - 1, 6), 64) for c in counts})
    assert readies == want
    assert all(r > 10 for r in readies), "negative/overflowed penalty"


def test_backoff_disabled_is_flat_one_epoch():
    q = _RetryQueue(backoff=False)
    q.push(_blk(3), np.array([1, 5, 31], np.int32),
           np.arange(3, dtype=np.int64), epoch=4)
    assert [r for r, *_ in q.items] == [6]    # epoch + 1 + 1


def test_pop_ready_partial_block_preserves_order_and_counts():
    """A block bigger than the remaining budget splits: the taken slice
    keeps FIFO order, the remainder re-enters at the SAME ready epoch
    with its abort counts and birth timestamps intact."""
    q = _RetryQueue(backoff=False)
    birth = np.arange(100, 110, dtype=np.int64)
    cnts = np.arange(10, dtype=np.int32) + 1
    q.push(_blk(10), cnts, birth, epoch=0,
           aborted=np.ones(10, bool),
           defer_cnt=np.arange(10, dtype=np.int32))
    blocks, counts, tss, abms, dfcs = q.pop_ready(epoch=5, limit=4)
    got = wire.QueryBlock.concat(blocks)
    assert len(got) == 4
    assert (got.tags == np.arange(4)).all(), "partial take lost order"
    assert (np.concatenate(counts) == cnts[:4]).all()
    assert (np.concatenate(tss) == birth[:4]).all()
    assert (np.concatenate(dfcs) == np.arange(4)).all()
    # the remainder waits at the same ready epoch, nothing lost
    assert len(q.items) == 1
    r, blk, cnt, ts, ab, dc = q.items[0]
    assert r == 2 and len(blk) == 6
    assert (blk.tags == np.arange(4, 10)).all()
    assert (ts == birth[4:]).all() and (cnt == cnts[4:]).all()
    # a later pop drains the remainder in order
    blocks2, _, tss2, _, _ = q.pop_ready(epoch=5, limit=100)
    got2 = wire.QueryBlock.concat(blocks2)
    assert (got2.tags == np.arange(4, 10)).all()
    assert (np.concatenate(tss2) == birth[4:]).all()


def test_deferred_entries_reenter_free_and_keep_birth_ts():
    """A deferred (waiting) txn re-enters at epoch+1 with NO backoff
    penalty — the waiter-list analogue — and keeps its birth ts even
    though its abort counter is high (only ABORTED restarts pay)."""
    q = _RetryQueue(backoff=True, cap=64)
    birth = np.array([7, 9, 11], np.int64)
    q.push(_blk(3), np.array([6, 6, 6], np.int32), birth, epoch=20,
           aborted=np.zeros(3, bool),
           defer_cnt=np.array([1, 2, 3], np.int32))
    assert [r for r, *_ in q.items] == [21], "deferred must re-enter free"
    blocks, counts, tss, abms, dfcs = q.pop_ready(epoch=21, limit=16)
    assert (np.concatenate(tss) == birth).all()
    assert not np.concatenate(abms).any()
    assert (np.concatenate(dfcs) == [1, 2, 3]).all()


def test_not_ready_entries_stay_queued():
    q = _RetryQueue(backoff=True, cap=64)
    q.push(_blk(2), np.array([5, 5], np.int32),
           np.array([1, 2], np.int64), epoch=0)   # ready at 0+1+16=17
    blocks, *_ = q.pop_ready(epoch=10, limit=16)
    assert not blocks and len(q.items) == 1
    blocks, *_ = q.pop_ready(epoch=17, limit=16)
    assert sum(len(b) for b in blocks) == 2 and not q.items
