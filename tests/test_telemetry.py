"""Transaction flight recorder (runtime/telemetry.py): deterministic
sampling agreement between client- and server-side tag views, record
ring semantics (drop-not-stall, highwater), sidecar flush/read
round-trips (including the recovery append and torn-tail tolerance),
the metrics stream, the telemetry-off wire pin on a loopback ServerNode
and ClientNode (the default-off bit-identity contract), and the armed
lifecycle hooks on a loopback server."""

import os

import numpy as np
import pytest

from deneva_tpu.config import CCAlg, Config, WorkloadKind
from deneva_tpu.runtime import telemetry as T
from deneva_tpu.runtime import wire

from tests.test_chaos import _solo_server


def _cfg(tmp_path, **kw):
    base = dict(telemetry=True, telemetry_sample=8,
                telemetry_ring=1024, telemetry_dir=str(tmp_path))
    base.update(kw)
    return Config(**base)


# ---- sampling ----------------------------------------------------------

def test_sampling_client_and_server_pick_identical_txns():
    """The zero-coordination contract: the client's raw tag view (lane
    | tenant << 24) and every server's packed view (client << 40 | tag)
    sample the SAME txn subset — the predicate keys on the lane bits
    alone, so tenant ids and the home-client id never perturb it."""
    lanes = np.arange(4096, dtype=np.int64)
    tenants = (lanes * 7) % 256
    wtags = lanes | (tenants << 24)            # client wire view
    packed = (np.int64(3) << 40) | wtags       # server admission view
    for sample in (1, 8, 1024):
        m_cl = T.sampled_mask(wtags, sample)
        m_srv = T.sampled_mask(packed, sample)
        np.testing.assert_array_equal(m_cl, m_srv)
        np.testing.assert_array_equal(m_cl, lanes % sample == 0)
    # sample=1 records everything
    assert T.sampled_mask(wtags, 1).all()


def test_recorder_samples_filters_and_counts(tmp_path):
    rec = T.FlightRecorder(_cfg(tmp_path), 0, "node")
    tags = np.arange(64, dtype=np.int64)
    n = rec.record(tags, T.ST_ADMIT)
    assert n == 8 and rec.sampled_cnt == 8      # 64 / sample=8
    # aligned verdict/aux arrays filter alongside the tags
    v = np.full(64, T.V_ABORT, np.uint8)
    v[0] = T.V_COMMIT
    n = rec.record(tags, T.ST_VERDICT, epoch=3, verdict=v,
                   aux=np.arange(64, dtype=np.int32))
    assert n == 8
    ev = rec.buf[:rec.n]
    verd = ev[ev["stage"] == T.ST_VERDICT]
    assert verd["verdict"][0] == T.V_COMMIT
    assert (verd["verdict"][1:] == T.V_ABORT).all()
    assert list(verd["aux"]) == [0, 8, 16, 24, 32, 40, 48, 56]
    assert (verd["epoch"] == 3).all()


def test_recorder_ring_drops_past_capacity(tmp_path):
    """A full ring DROPS (and counts) instead of stalling or growing —
    the hot loop never blocks on its own instrument."""
    rec = T.FlightRecorder(_cfg(tmp_path, telemetry_sample=1), 0, "node")
    assert rec.cap == 1024
    tags = np.arange(1500, dtype=np.int64)
    rec.record(tags, T.ST_SEND)
    assert rec.n == 1024 and rec.dropped_cnt == 476
    assert rec.highwater == 1024 and rec.should_flush
    rec.flush()
    assert rec.n == 0 and not rec.should_flush
    # post-flush records append again; dropped_cnt is cumulative
    rec.record(tags[:4], T.ST_SEND)
    assert rec.n == 4 and rec.dropped_cnt == 476


# ---- sidecar round-trip ------------------------------------------------

def test_flush_read_roundtrip_and_append(tmp_path):
    cfg = _cfg(tmp_path, telemetry_sample=1)
    rec = T.FlightRecorder(cfg, 2, "client")
    rec.record(np.arange(5, dtype=np.int64), T.ST_SEND, t_us=111)
    rec.flush()
    rec.record(np.arange(3, dtype=np.int64), T.ST_ACK, t_us=222)
    rec.flush()
    meta, recs = T.read_telemetry(rec.path)
    assert meta == {"node": 2, "role": "client", "version": 1}
    assert len(recs) == 8
    assert (recs["stage"][:5] == T.ST_SEND).all()
    assert (recs["stage"][5:] == T.ST_ACK).all()
    assert (recs["node"] == 2).all()
    # recovery-style append (append=True keeps the pre-crash prefix)
    rec2 = T.FlightRecorder(cfg, 2, "client", append=True)
    rec2.record(np.arange(2, dtype=np.int64), T.ST_SEND, t_us=333)
    rec2.flush()
    _, recs = T.read_telemetry(rec2.path)
    assert len(recs) == 10 and recs["t_us"][-1] == 333
    # a torn tail (hard crash mid-write) truncates to whole records
    with open(rec.path, "ab") as f:
        f.write(b"\x01\x02\x03")
    _, recs = T.read_telemetry(rec.path)
    assert len(recs) == 10
    # recovery append AFTER a torn tail: the constructor truncates to a
    # record boundary first (command-log discipline), or every
    # post-recovery record would parse frame-shifted
    rec3 = T.FlightRecorder(cfg, 2, "client", append=True)
    rec3.record(np.arange(2, dtype=np.int64), T.ST_ACK, t_us=444)
    rec3.flush()
    _, recs = T.read_telemetry(rec3.path)
    assert len(recs) == 12
    assert (recs["t_us"][-2:] == 444).all()
    assert (recs["stage"][-2:] == T.ST_ACK).all()
    # recovery over a PARTIAL HEADER (crash on first flush) rewrites it
    stub = T.FlightRecorder(cfg, 5, "node")
    with open(stub.path, "wb") as f:
        f.write(b"\x00\x01")
    rec4 = T.FlightRecorder(cfg, 5, "node", append=True)
    rec4.record(np.arange(1, dtype=np.int64), T.ST_SEND, t_us=1)
    rec4.flush()
    meta, recs = T.read_telemetry(rec4.path)
    assert meta["node"] == 5 and len(recs) == 1


def test_epoch_events_bypass_sampling(tmp_path):
    rec = T.FlightRecorder(_cfg(tmp_path, telemetry_sample=1024), 3,
                           "replica")
    assert rec.record_event(T.ST_APPLY, 17) == 1
    rec.flush()
    _, recs = T.read_telemetry(rec.path)
    assert recs["tag"][0] == -1 and recs["epoch"][0] == 17
    assert recs["stage"][0] == T.ST_APPLY


def test_metrics_stream_roundtrip(tmp_path):
    path = os.path.join(str(tmp_path), "metrics_node0.jsonl")
    ms = T.MetricsStream(path, 0)
    ms.emit(0, commit=64, abort=1)
    ms.emit(1, commit=63, abort=2)
    ms.close()
    rows = T.read_metrics(path)
    assert [r["epoch"] for r in rows] == [0, 1]
    assert rows[1]["commit"] == 63 and rows[0]["node"] == 0
    # torn final line tolerated
    with open(path, "a") as f:
        f.write('{"node":0,"epo')
    assert len(T.read_metrics(path)) == 2


def test_telemetry_line_fields():
    from deneva_tpu.harness.parse import parse_telemetry
    line = T.telemetry_line(4, {"sampled_cnt": 10, "dropped_cnt": 0,
                                "ring_highwater": 7, "flush_ms": 1.25,
                                "sample": 8})
    rows = parse_telemetry([line])
    assert rows == [{"node": 4, "sampled_cnt": 10, "dropped_cnt": 0,
                     "ring_highwater": 7, "flush_ms": 1.25, "sample": 8}]


# ---- loopback ServerNode: telemetry-off wire pin ----------------------

def test_telemetry_off_wire_pin():
    """The house contract, executable: with telemetry off a server
    builds NO recorder and NO metrics stream, writes no sidecar, and
    its blob broadcast is byte-identical to the pre-telemetry codec
    output — the flight recorder is purely observational and its off
    state is the pre-telemetry runtime byte for byte."""
    node = _solo_server("tel_off_pin")
    try:
        assert node.tel is None and node._metrics is None
        blk = wire.QueryBlock(
            keys=np.arange(8, dtype=np.int32).reshape(4, 2),
            types=np.ones((4, 2), np.int8),
            scalars=np.zeros((4, 0), np.int32),
            tags=np.arange(4, dtype=np.int64))
        ts = np.arange(4, dtype=np.int64) + 100
        blob = wire.encode_epoch_blob(7, blk, ts)
        sent = []
        node.tp.sendv_many = \
            lambda dests, rt, parts: sent.append((list(dests), rt, parts))
        node.tp.send = lambda d, rt, pl=b"": sent.append(([d], rt, [pl]))
        node.n_srv = 2          # pretend a peer so the bcast emits
        node._bcast_views(7, blk, ts)
        (dests, rt, parts), = sent
        assert rt == "EPOCH_BLOB"
        assert b"".join(bytes(p) for p in parts) == blob
        assert not any(k.startswith("tel_")
                       for k in node.stats.counters)
    finally:
        node.n_srv = 1
        node.close()


def test_telemetry_off_client_pin():
    """Client half of the off pin: no recorder, no sidecar, the send
    path untouched.  (A bare server-side transport fills the mesh so
    the client's dt_start handshake completes.)"""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deneva_tpu.runtime.client import ClientNode
    from deneva_tpu.runtime.native import NativeTransport, ipc_endpoints

    cfg = Config(workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
                 node_cnt=1, client_node_cnt=1, node_id=1,
                 epoch_batch=32, synth_table_size=1024,
                 req_per_query=2, max_accesses=2)
    import threading

    eps = ipc_endpoints(2, f"tel_off_cl_pin_{os.getpid()}")
    peer = NativeTransport(0, eps, 2)
    # dt_start blocks until the whole mesh connects: start the server-
    # side stub concurrently with the client's own start
    t = threading.Thread(target=peer.start)
    t.start()
    try:
        node = ClientNode(cfg, eps, "cpu")
        try:
            assert node.tel is None
        finally:
            node.close()
    finally:
        t.join()
        peer.close()


# ---- loopback ServerNode: armed lifecycle hooks ------------------------

def _tel_server(tag, tmp_path, **kw):
    base = dict(telemetry=True, telemetry_sample=1,
                telemetry_dir=str(tmp_path), synth_table_size=1024)
    base.update(kw)
    return _solo_server(tag, **base)


def test_armed_route_records_admit_with_packed_tags(tmp_path):
    node = _tel_server("tel_admit", tmp_path)
    try:
        blk = wire.QueryBlock(
            keys=np.zeros((4, 2), np.int32),
            types=np.zeros((4, 2), np.int8),
            scalars=np.zeros((4, 0), np.int32),
            tags=np.arange(4, dtype=np.int64) + 10)
        node._route(1, "CL_QRY_BATCH", wire.encode_qry_block(blk))
        ev = node.tel.buf[:node.tel.n]
        admits = ev[ev["stage"] == T.ST_ADMIT]
        assert len(admits) == 4
        # packed id = src << 40 | tag: join key shared with the client
        assert list(admits["tag"]) == [(1 << 40) | t
                                       for t in range(10, 14)]
        assert len(node.pending) == 1
    finally:
        node.close()


def test_armed_verdict_hook_planes_and_hold(tmp_path):
    node = _tel_server("tel_verd", tmp_path)
    try:
        tags = (np.int64(1) << 40) | np.arange(6, dtype=np.int64)
        blk = wire.QueryBlock(
            keys=np.zeros((6, 2), np.int32),
            types=np.zeros((6, 2), np.int8),
            scalars=np.zeros((6, 0), np.int32), tags=tags)
        commit = np.array([1, 1, 0, 0, 1, 0], bool)
        ab = np.array([0, 0, 1, 0, 0, 0], bool)
        df = np.array([0, 0, 0, 1, 0, 0], bool)
        rep = np.array([0, 1, 0, 0, 0, 0], bool)
        node._tel_verdicts(5, blk, commit, ab, df, rep,
                           np.zeros(6, np.int32), 12345)
        ev = node.tel.buf[:node.tel.n]
        verd = ev[ev["stage"] == T.ST_VERDICT]
        assert (verd["t_us"] == 12345).all()
        got = {int(r["tag"]) & 0xFF: int(r["verdict"]) for r in verd}
        assert got == {0: T.V_COMMIT, 1: T.V_SALVAGE, 2: T.V_ABORT,
                       3: T.V_DEFER, 4: T.V_COMMIT}
        # no logger on this solo node -> no hold events
        assert not (ev["stage"] == T.ST_HOLD).any()
    finally:
        node.close()


# ---- config gating -----------------------------------------------------

def test_telemetry_knobs_validate():
    with pytest.raises(ValueError, match="telemetry_sample"):
        Config().replace(telemetry_sample=0)
    with pytest.raises(ValueError, match="telemetry_ring"):
        Config().replace(telemetry_ring=16)
    cfg = Config().replace(telemetry=True)    # defaults are live
    assert cfg.telemetry_sample == 1024


# ---- end-to-end cluster (slow tier) ------------------------------------

@pytest.mark.slow
def test_cluster_telemetry_chains_complete(tmp_path):
    """2 servers + 1 client + logging: every sampled committed txn's
    chain joins gap-free across the sidecars, with the quorum
    hold->release hop present (held acks), and the telemetry-off twin
    of the same config writes no sidecar at all."""
    from deneva_tpu.harness import txntrace
    from deneva_tpu.runtime.launch import run_cluster

    cfg = Config(workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
                 node_cnt=2, client_node_cnt=1, epoch_batch=128,
                 conflict_buckets=512, synth_table_size=4096,
                 max_txn_in_flight=1024, req_per_query=4, max_accesses=4,
                 warmup_secs=0.3, done_secs=1.0, logging=True,
                 log_dir=str(tmp_path), telemetry=True,
                 telemetry_sample=8)
    out = run_cluster(cfg, platform="cpu", run_id="telsm")
    assert {k for k, (kind, _) in out.items() if kind == "server"} \
        == {0, 1}
    recs, roles = txntrace.load_dir(os.path.join(str(tmp_path), "telsm"))
    assert len(recs) > 0 and roles[2] == "client"
    chains = [txntrace.build_chain(ev)
              for ev in txntrace.index_txns(recs).values()]
    committed, full, viol = txntrace.completeness(chains)
    assert committed > 0 and full > 0 and viol == []
