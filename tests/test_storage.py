"""Storage layer tests (SURVEY §1 L7): catalog parsing against the
reference's actual schema grammar, device tables, indexes."""

import jax.numpy as jnp
import numpy as np
import pytest

from deneva_tpu.storage import (Catalog, DenseIndex, DeviceTable, HashIndex,
                                SortedIndex, parse_schema)

YCSB_SCHEMA = """\
//size, type, name
TABLE=MAIN_TABLE
\t100,string,F0
\t100,string,F1

INDEX=MAIN_INDEX
\tMAIN_TABLE,0
"""

TPCC_FRAGMENT = """\
TABLE=DISTRICT
\t8,int64_t,D_ID
\t8,int64_t,D_W_ID
\t8,double,D_TAX
\t8,int64_t,D_NEXT_O_ID
"""


def test_parse_schema_ycsb():
    cat = parse_schema(YCSB_SCHEMA)
    t = cat.table("MAIN_TABLE")
    assert [c.name for c in t.columns] == ["F0", "F1"]
    assert t.columns[0].ctype == "string" and t.columns[0].size == 100
    assert t.tuple_size == 200
    assert cat.indexes["MAIN_INDEX"].table == "MAIN_TABLE"


def test_parse_schema_mixed_types_and_spaces():
    # the reference files mix tabs and spaces (PPS_schema.txt line 2)
    cat = parse_schema(TPCC_FRAGMENT.replace("\t8,int64_t,D_W_ID", "  8,int64_t,D_W_ID"))
    t = cat.table("DISTRICT")
    assert t.column("D_TAX").ctype == "double"
    assert t.column("D_NEXT_O_ID").index == 3


def test_device_table_gather_scatter_roundtrip():
    cat = parse_schema(TPCC_FRAGMENT)
    tab = DeviceTable.create(cat.table("DISTRICT"), capacity=16)
    slots = jnp.array([0, 3, 7])
    tab = tab.scatter(slots, {"D_NEXT_O_ID": jnp.array([10, 11, 12]),
                              "D_TAX": jnp.array([0.1, 0.2, 0.3])})
    out = tab.gather(slots, ("D_NEXT_O_ID", "D_TAX"))
    np.testing.assert_array_equal(out["D_NEXT_O_ID"], [10, 11, 12])
    np.testing.assert_allclose(out["D_TAX"], [0.1, 0.2, 0.3], rtol=1e-6)


def test_device_table_masked_scatter_goes_to_trash():
    cat = parse_schema(TPCC_FRAGMENT)
    tab = DeviceTable.create(cat.table("DISTRICT"), capacity=8)
    tab = tab.scatter(jnp.array([2, 2]), {"D_ID": jnp.array([5, 9])},
                      mask=jnp.array([False, True]))
    assert int(tab.columns["D_ID"][2]) == 9  # only the unmasked write landed


def test_device_table_scatter_add_duplicates_exact():
    cat = parse_schema(TPCC_FRAGMENT)
    tab = DeviceTable.create(cat.table("DISTRICT"), capacity=8)
    # ten concurrent increments of the same district counter
    tab = tab.scatter_add(jnp.zeros(10, jnp.int32),
                          {"D_NEXT_O_ID": jnp.ones(10, jnp.int32)})
    assert int(tab.columns["D_NEXT_O_ID"][0]) == 10


def test_device_table_append_prefix_sum_and_overflow():
    cat = parse_schema(TPCC_FRAGMENT)
    tab = DeviceTable.create(cat.table("DISTRICT"), capacity=4)
    mask = jnp.array([True, False, True, True])
    tab, slots = tab.append({"D_ID": jnp.array([1, 2, 3, 4])}, mask)
    np.testing.assert_array_equal(slots, [0, 4, 1, 2])  # masked row -> trash(4)
    assert int(tab.row_cnt) == 3
    # overflow: only one slot left
    tab, slots2 = tab.append({"D_ID": jnp.array([7, 8])}, jnp.array([True, True]))
    assert int(slots2[0]) == 3 and int(slots2[1]) == 4  # second insert dropped
    assert int(tab.row_cnt) == 4


def test_dense_index():
    idx = DenseIndex(base=100, stride=1, size=50, miss_slot=999)
    out = idx.lookup(jnp.array([100, 149, 150, 99, 7]))
    np.testing.assert_array_equal(out, [0, 49, 999, 999, 999])


def test_hash_index_roundtrip_and_misses():
    rng = np.random.default_rng(0)
    keys = rng.choice(1_000_000, size=5000, replace=False).astype(np.int32)
    slots = np.arange(5000, dtype=np.int32)
    idx = HashIndex.build(keys, slots, miss_slot=12345)
    out = np.asarray(idx.lookup(jnp.asarray(keys)))
    np.testing.assert_array_equal(out, slots)
    # misses
    miss_keys = np.array([1_000_001, 2_000_000], np.int32)
    out = np.asarray(idx.lookup(jnp.asarray(miss_keys)))
    np.testing.assert_array_equal(out, [12345, 12345])


def test_hash_index_rejects_duplicates():
    with pytest.raises(ValueError):
        HashIndex.build(np.array([5, 5], np.int32), np.array([0, 1], np.int32),
                        miss_slot=0)


def test_sorted_index_lookup_and_misses():
    keys = np.array([40, 10, 30, 20], np.int32)
    slots = np.array([4, 1, 3, 2], np.int32)
    idx = SortedIndex.build(keys, slots, miss_slot=99)
    out = np.asarray(idx.lookup(jnp.array([10, 20, 30, 40, 25, 5, 50])))
    np.testing.assert_array_equal(out, [1, 2, 3, 4, 99, 99, 99])


def test_sorted_index_nonunique_first_and_count():
    # nonunique keys: reference index_btree via itemid_t chains
    keys = np.array([7, 7, 7, 9], np.int32)
    slots = np.array([0, 1, 2, 3], np.int32)
    idx = SortedIndex.build(keys, slots, miss_slot=-1)
    assert int(idx.lookup(jnp.array(7))) == 0  # stable: first inserted
    np.testing.assert_array_equal(
        np.asarray(idx.lookup_count(jnp.array([7, 9, 8]))), [3, 1, 0])


def test_sorted_index_range_scan_padded():
    keys = np.arange(0, 100, 10, dtype=np.int32)          # 0,10,...,90
    slots = np.arange(10, dtype=np.int32)
    idx = SortedIndex.build(keys, slots, miss_slot=-1)
    s, ok = idx.range_slots(jnp.array([35]), width=4)     # keys 40,50,60,70
    np.testing.assert_array_equal(np.asarray(s)[0], [4, 5, 6, 7])
    assert bool(np.all(np.asarray(ok)[0]))
    # past-the-end padding
    s, ok = idx.range_slots(jnp.array([85]), width=4)     # only 90 remains
    np.testing.assert_array_equal(np.asarray(ok)[0], [True, False, False, False])
    np.testing.assert_array_equal(np.asarray(s)[0], [9, -1, -1, -1])


def test_sorted_index_empty_returns_misses():
    idx = SortedIndex.build(np.array([], np.int32), np.array([], np.int32),
                            miss_slot=99)
    np.testing.assert_array_equal(np.asarray(idx.lookup(jnp.array([1, 2]))),
                                  [99, 99])
    np.testing.assert_array_equal(np.asarray(idx.lookup_count(jnp.array([1]))),
                                  [0])
    s, ok = idx.range_slots(jnp.array([0]), width=3)
    np.testing.assert_array_equal(np.asarray(s)[0], [99, 99, 99])
    assert not np.any(np.asarray(ok))
    s, ok = idx.range_between(jnp.array([0]), jnp.array([5]), width=3)
    assert not np.any(np.asarray(ok))


def test_sorted_index_range_between():
    keys = np.arange(0, 100, 10, dtype=np.int32)
    slots = np.arange(10, dtype=np.int32)
    idx = SortedIndex.build(keys, slots, miss_slot=-1)
    s, ok = idx.range_between(jnp.array([20]), jnp.array([45]), width=8)
    np.testing.assert_array_equal(np.asarray(ok)[0],
                                  [True, True, True, False, False, False, False, False])
    np.testing.assert_array_equal(np.asarray(s)[0][:3], [2, 3, 4])


def test_dynamic_sorted_index_insert_merge():
    """Dynamic ordered index (VERDICT r3 next #9, the index_btree insert
    analogue): batched merge-inserts keep probes exact — verified
    against a numpy model across several insert epochs."""
    import jax.numpy as jnp

    from deneva_tpu.storage.index import DynamicSortedIndex

    rng = np.random.default_rng(11)
    idx = DynamicSortedIndex.build(np.asarray([5, 9], np.int32),
                                   np.asarray([50, 90], np.int32),
                                   miss_slot=999, cap=64)
    model: list[tuple[int, int]] = [(5, 50), (9, 90)]
    slot = 100
    for _ in range(4):
        ks = rng.integers(0, 40, size=8).astype(np.int32)
        ss = np.arange(slot, slot + 8, dtype=np.int32)
        slot += 8
        mask = rng.random(8) < 0.75
        idx = idx.insert(jnp.asarray(ks), jnp.asarray(ss),
                         jnp.asarray(mask))
        model += [(int(k), int(s)) for k, s, m in zip(ks, ss, mask) if m]
    model.sort(key=lambda e: e[0])
    # lookup: first slot of each present key; misses -> miss_slot
    for q in range(42):
        want = next((s for k, s in model if k == q), 999)
        got = int(np.asarray(idx.lookup(jnp.asarray([q], jnp.int32)))[0])
        if any(k == q for k, _ in model):
            assert got in [s for k, s in model if k == q], q
        else:
            assert got == 999, q
        cnt = int(np.asarray(idx.lookup_count(
            jnp.asarray([q], jnp.int32)))[0])
        assert cnt == sum(1 for k, _ in model if k == q), q
    # range scan returns exactly the in-range slots, ascending by key
    slots, ok = idx.range_between(jnp.asarray([10], jnp.int32),
                                  jnp.asarray([30], jnp.int32), 64)
    got = sorted(np.asarray(slots)[0][np.asarray(ok)[0]].tolist())
    want = sorted(s for k, s in model if 10 <= k <= 30)
    assert got == want
    assert not bool(np.asarray(idx.overflowed()))


def test_dynamic_sorted_index_overflow_flag():
    from deneva_tpu.storage.index import DynamicSortedIndex
    import jax.numpy as jnp

    idx = DynamicSortedIndex.build(np.zeros(0, np.int32),
                                   np.zeros(0, np.int32),
                                   miss_slot=7, cap=4)
    ks = jnp.asarray([3, 1, 2, 5, 4, 0], jnp.int32)
    idx = idx.insert(ks, jnp.arange(6, dtype=jnp.int32),
                     jnp.ones(6, bool))
    assert bool(np.asarray(idx.overflowed()))
    # the smallest cap keys survive; the dropped tail reads as misses
    assert (np.asarray(idx.keys) == [0, 1, 2, 3]).all()
    assert int(np.asarray(idx.lookup(jnp.asarray([5], jnp.int32)))[0]) == 7


def test_mc_layout_roundtrip_and_geometry():
    """to_mc_layout permutes rows owner-major: block d holds exactly the
    anchors ≡ d (mod D) in anchor order, data is preserved, and pad rows
    are zero (the block-local trash)."""
    from deneva_tpu.storage.table import (fill_columns, mc_block_geometry,
                                          to_mc_layout)

    schema = parse_schema("TABLE=T\n\t8,int64_t,V\n")
    cap, R, D = 24 * 5, 5, 4            # 24 anchors x 5 rows, 4 blocks
    tab = DeviceTable.create(schema.table("T"), cap)
    vals = np.arange(cap, dtype=np.int32) * 7 + 3
    tab = fill_columns(tab, cap, {"V": vals})
    mc = to_mc_layout(tab, D, anchor_rows=R)
    local_rows, lb = mc_block_geometry(cap, R, D)
    assert local_rows == (24 // D) * R and mc.mc_parts == D
    col = np.asarray(mc.columns["V"])
    assert col.shape[0] == D * lb
    for d in range(D):
        block = col[d * lb:(d + 1) * lb]
        anchors = [d + D * j for j in range(24 // D)]
        expect = np.concatenate(
            [vals[a * R:(a + 1) * R] for a in anchors])
        assert (block[:local_rows] == expect).all(), d
        assert (block[local_rows:] == 0).all(), d   # block trash/pad
