"""Overload-tier unit tests: arrival schedules (seeded, deterministic,
correct shapes), the per-tenant admission controller (quota, capacity,
SLO shed-over-quota-first, queue-delay ledger), config gating, and the
admission-off wire pin (pre-admission bytes verbatim, no controller, no
NACK)."""

import numpy as np
import pytest

from deneva_tpu.config import CCAlg, Config, WorkloadKind
from deneva_tpu.runtime import admission as A
from deneva_tpu.runtime import loadgen as L
from deneva_tpu.runtime import wire


def _cfg(**kw):
    base = dict(workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
                synth_table_size=4096, req_per_query=2, max_accesses=2)
    base.update(kw)
    return Config(**base).validate()


# ---- config gating ------------------------------------------------------

def test_overload_defaults_are_fully_off():
    cfg = Config()
    assert cfg.arrival_process == "" and not cfg.admission
    assert cfg.tenant_cnt == 1


def test_arrival_config_gating():
    with pytest.raises(ValueError, match="arrival_rate"):
        _cfg(arrival_process="poisson")
    with pytest.raises(ValueError, match="needs an arrival_process"):
        _cfg(arrival_rate=100.0)
    with pytest.raises(ValueError, match="replaces load_rate"):
        _cfg(arrival_process="poisson", arrival_rate=100.0,
             load_rate=100)
    with pytest.raises(ValueError, match="flash"):
        _cfg(arrival_process="flash", arrival_rate=100.0)
    with pytest.raises(ValueError, match="arrival_amp"):
        _cfg(arrival_process="diurnal", arrival_rate=100.0,
             arrival_amp=1.5)
    # valid shapes construct
    _cfg(arrival_process="flash", arrival_rate=100.0,
         arrival_flash_at_s=1.0, arrival_flash_secs=0.5)


def test_tenant_and_admission_gating():
    with pytest.raises(ValueError, match="tenant_cnt"):
        _cfg(tenant_cnt=0)
    with pytest.raises(ValueError, match="tenant_cnt"):
        _cfg(tenant_cnt=257)
    with pytest.raises(ValueError, match="tenant_weights"):
        _cfg(tenant_cnt=2, tenant_weights="1,2,3")
    with pytest.raises(ValueError, match="need --admission"):
        _cfg(tenant_quota=100.0)
    with pytest.raises(ValueError, match="tenant_quota"):
        _cfg(admission=True, admission_slo_ms=20.0)
    w = _cfg(tenant_cnt=4, tenant_weights="1,1,1,5").tenant_weights_spec()
    assert len(w) == 4 and abs(sum(w) - 1.0) < 1e-9 and w[3] == 5 * w[0]


# ---- tenant tag packing -------------------------------------------------

def test_tenant_packs_into_free_tag_bits():
    lanes = np.arange(0, 1 << 22, 97, dtype=np.int64)[:1000]
    ten = (lanes % 7).astype(np.uint8)
    wtags = L.pack_tenant(lanes, ten)
    assert (L.tenant_of_tags(wtags) == ten).all()
    assert (wtags % (1 << 22) == lanes).all()     # lane survives
    assert (wtags >> 40 == 0).all()               # client-id byte free
    # tenant 0 writes nothing: the default tag bytes are unchanged
    assert (L.pack_tenant(lanes, np.zeros(1000, np.uint8)) == lanes).all()


def test_tenant_column_is_seeded_and_weighted():
    w = np.array([0.2, 0.8])
    a = L.tenant_column(np.random.default_rng(5), w, 8192)
    b = L.tenant_column(np.random.default_rng(5), w, 8192)
    assert (a == b).all()
    frac = (a == 1).mean()
    assert 0.75 < frac < 0.85


# ---- arrival schedules --------------------------------------------------

def _sched(kind, rate=1000.0, **kw):
    cfg = _cfg(arrival_process=kind, arrival_rate=rate, **kw)
    return L.ArrivalSchedule(cfg, node_id=1)


def test_poisson_is_seeded_and_near_rate():
    s1 = _sched("poisson")
    s2 = _sched("poisson")
    for t in (0.5, 1.0, 2.0, 10.0):
        assert s1.target(t) == s2.target(t), "same seed, same schedule"
    n = s1.target(10.0)
    assert 0.9 * 10_000 < n < 1.1 * 10_000
    assert s1.target(0.0) == 0
    # monotone
    ts = np.linspace(0, 10, 101)
    vals = [s1.target(float(t)) for t in ts]
    assert all(b >= a for a, b in zip(vals, vals[1:]))


def test_diurnal_integral_and_mean_rate():
    s = _sched("diurnal", arrival_period_s=2.0, arrival_amp=0.8)
    # over whole periods the sinusoid integrates away: mean rate exact
    assert s.target(4.0) == 4000
    # quarter-period peak runs ahead of the flat schedule
    assert s.target(0.5) > 500


def test_bursty_duty_cycle():
    s = _sched("bursty", arrival_period_s=1.0, arrival_duty=0.25)
    # ON quarter carries the whole period's arrivals at 4x rate
    assert s.target(0.25) == 1000
    assert s.target(0.9) == 1000          # OFF: flat
    assert s.target(1.25) == 2000
    # mean rate preserved over whole periods
    assert s.target(8.0) == 8000


def test_flash_step_and_end():
    s = _sched("flash", arrival_flash_at_s=1.0, arrival_flash_secs=0.5,
               arrival_flash_factor=10.0)
    assert s.target(1.0) == 1000
    assert s.target(1.5) == 1000 + 5000       # 0.5 s at 10x
    assert s.target(3.0) == 3000 + 4500       # post-burst slope back
    assert s.flash_end() == 1.5
    assert _sched("poisson").flash_end() is None


def test_arrival_rate_splits_across_clients():
    cfg = _cfg(arrival_process="poisson", arrival_rate=1000.0,
               client_node_cnt=4)
    s = L.ArrivalSchedule(cfg, node_id=4)
    n = s.target(8.0)
    assert 0.8 * 2000 < n < 1.2 * 2000


# ---- admission controller ----------------------------------------------

US = 1_000_000


def _ctl(**kw):
    base = dict(admission=True, tenant_cnt=2, admission_queue_max=256,
                tenant_quota=100.0, tenant_burst_s=0.1,
                admission_retry_us=10_000.0)
    base.update(kw)
    return A.AdmissionController(_cfg(**base), now_us=0)


def _tags(tenants):
    lanes = np.arange(len(tenants), dtype=np.int64)
    return L.pack_tenant(lanes, np.asarray(tenants, np.uint8))


def test_quota_nacks_past_the_bucket_and_refills():
    ctl = _ctl()          # burst = 100 * 0.1 = 10 tokens
    tags = _tags([0] * 30)
    reason, retry = ctl.admit(tags, now_us=0)
    assert (reason[:10] == A.R_ADMIT).all()
    assert (reason[10:] == A.R_QUOTA).all()
    assert ctl.admitted[0] == 10 and ctl.nacked[0] == 20
    # quota retry hints grow with the deficit and floor at the base
    assert (retry[10:] >= 10_000).all()
    assert retry[29] > retry[10]
    # tokens refill at quota rate: 50 ms -> 5 more grants
    reason2, _ = ctl.admit(_tags([0] * 8), now_us=50_000)
    assert int((reason2 == A.R_ADMIT).sum()) == 5
    # tenant 1's bucket is untouched by tenant 0's burn
    reason3, _ = ctl.admit(_tags([1] * 8), now_us=50_000)
    assert (reason3 == A.R_ADMIT).all()


def test_capacity_bound_nacks_overflow_in_arrival_order():
    ctl = _ctl(tenant_quota=0.0, admission_queue_max=64)
    reason, retry = ctl.admit(_tags([0] * 100), now_us=0)
    assert int((reason == A.R_ADMIT).sum()) == 64
    assert (reason[:64] == A.R_ADMIT).all(), "arrival order preserved"
    assert (reason[64:] == A.R_CAP).all()
    assert (retry[64:] == 10_000).all()
    assert ctl.depth == 64 and ctl.depth_max == 64
    # the queue drains -> room again
    ctl.on_pop(40, now_us=1000)
    reason2, _ = ctl.admit(_tags([0] * 50), now_us=1000)
    assert int((reason2 == A.R_ADMIT).sum()) == 40


def test_slo_breach_sheds_over_quota_tenants_first():
    ctl = _ctl(admission_slo_ms=5.0)
    # tenant 1 (the aggressor) burns its bucket dry; tenant 0 stays in
    ctl.admit(_tags([1] * 10), now_us=0)
    assert ctl.tokens[1] < 1.0 and ctl.tokens[0] >= 10.0
    # queue delay blows past the 5 ms SLO -> breach at the group tick
    ctl.on_pop(10, now_us=20_000)         # 20 ms in queue
    ctl.on_group()
    assert ctl.slo_breached and ctl.breach_groups == 1
    # mixed batch under breach: the aggressor's WHOLE batch sheds (even
    # rows its refilled trickle could have granted), tenant 0 admits
    mixed = _tags([0, 1, 0, 1, 1, 0, 1, 1])
    reason, retry = ctl.admit(mixed, now_us=20_000)
    ten = L.tenant_of_tags(mixed)
    assert (reason[ten == 0] == A.R_ADMIT).all()
    assert (reason[ten == 1] == A.R_SLO).all()
    assert ctl.shed[1] == 5 and ctl.shed[0] == 0
    assert (retry[ten == 1] > 0).all()
    # recovery: fast drains under the SLO clear the breach
    ctl.on_pop(int((reason == A.R_ADMIT).sum()), now_us=21_000)
    ctl.on_group()
    assert not ctl.slo_breached
    reason2, _ = ctl.admit(_tags([1] * 4), now_us=10 * US)
    assert (reason2 == A.R_ADMIT).all(), "post-breach refill re-admits"


def test_queue_delay_ledger_quantiles_and_summary():
    from deneva_tpu.stats import Stats

    ctl = _ctl(tenant_quota=0.0)
    ctl.admit(_tags([0] * 100), now_us=0)
    ctl.on_pop(50, now_us=10_000)      # 10 ms
    ctl.on_pop(50, now_us=40_000)      # 40 ms
    ctl.on_group()
    assert abs(ctl.delay_ms.percentile(50) - 10.0) < 0.1
    assert abs(ctl.delay_ms.percentile(99) - 40.0) < 0.1
    st = Stats()
    ctl.summary_into(st)
    f = st.summary_fields()
    assert f["adm_admit_cnt"] == 100 and f["adm_queue_depth_max"] == 100
    assert "adm_queue_delay_ms_p99" in f
    # [admission] lines round-trip through parse_admission
    from deneva_tpu.harness.parse import parse_admission
    rows = parse_admission(ctl.admission_lines(node=3))
    assert rows[0]["node"] == 3 and rows[0]["tenant"] == -1
    assert rows[0]["admitted"] == 100
    assert {r["tenant"] for r in rows[1:]} == {0, 1}


def test_foreign_tenant_id_clamps_to_last_bucket():
    ctl = _ctl(tenant_cnt=2)
    tags = L.pack_tenant(np.arange(4, dtype=np.int64),
                         np.array([7, 7, 0, 7], np.uint8))
    reason, _ = ctl.admit(tags, now_us=0)     # no IndexError
    assert ctl.admitted.sum() == int((reason == A.R_ADMIT).sum())


# ---- admission-off wire pin --------------------------------------------

def test_admission_off_takes_pre_overload_path_verbatim():
    """The house contract, executable: with admission off a server
    builds NO controller, NACKs nothing, and the block it queues for
    epoch formation re-encodes to the arriving payload byte for byte
    (pre-admission bytes verbatim)."""
    from tests.test_chaos import _solo_server

    node = _solo_server("adm_off_pin")
    try:
        assert node.adm is None
        blk = wire.QueryBlock(
            keys=np.arange(8, dtype=np.int32).reshape(4, 2),
            types=np.ones((4, 2), np.int8),
            scalars=np.zeros((4, 0), np.int32),
            tags=np.arange(4, dtype=np.int64))
        payload = wire.encode_qry_block(blk)
        node._route(0, "CL_QRY_BATCH", payload)
        assert len(node.pending) == 1
        src, queued = node.pending[0]
        assert wire.encode_qry_block(queued) == payload
        assert node.tp.recv(100_000) is None      # no NACK, no anything
        # and the summary carries no admission keys
        assert not any(k.startswith("adm_")
                       for k in node.stats.counters)
    finally:
        node.close()


def test_admission_on_nacks_over_quota_end_to_end():
    """Loopback ServerNode with admission armed: an over-quota batch
    splits — in-quota rows queue, the rest come back as one ADMIT_NACK
    with per-tag retry hints."""
    from tests.test_chaos import _solo_server

    node = _solo_server("adm_on_nack", admission=True, tenant_cnt=2,
                        tenant_quota=50.0, tenant_burst_s=0.2,
                        client_node_cnt=0)
    try:
        assert node.adm is not None          # burst = 10 tokens
        n = 30
        lanes = np.arange(n, dtype=np.int64)
        wtags = L.pack_tenant(lanes, np.zeros(n, np.uint8))
        blk = wire.QueryBlock(
            keys=np.zeros((n, 2), np.int32),
            types=np.ones((n, 2), np.int8),
            scalars=np.zeros((n, 0), np.int32), tags=wtags)
        node._route(0, "CL_QRY_BATCH", wire.encode_qry_block(blk))
        assert len(node.pending) == 1 and len(node.pending[0][1]) == 10
        m = node.tp.recv(500_000)
        assert m is not None and m[1] == "ADMIT_NACK"
        tags, retry = A.decode_admit_nack(m[2])
        assert (tags == wtags[10:]).all()
        assert (retry > 0).all()
        assert node.adm.depth == 10
    finally:
        node.close()


# ---- cluster scenario (tier-1: one full-window overload boot) ----------

def test_overload_flash_scenario():
    """The flash-crowd chaos scenario end to end: x10 open-loop burst
    against per-tenant admission on a real 2s1c cluster — queue depth
    stays bounded, the overflow is NACKed and re-enters via backoff,
    goodput recovers after the burst, and exactly-once holds under
    NACK + resend + seeded drops (run_scenario raises ChaosViolation
    on any breach)."""
    from deneva_tpu.harness.chaos import run_scenario

    report = run_scenario("overload-flash", quick=True, quiet=True)
    assert report["adm_nacked_total"] > 0
    assert report["post_flash_acks"] > 0
    assert report["commits"][0] == report["commits"][1] > 0
