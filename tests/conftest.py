"""Test harness setup.

Tests run on a virtual 8-device CPU mesh (the analogue of the reference's
IPC-on-one-box multi-node rig, `scripts/run_experiments.py:67` /
`transport/transport.cpp:132` — SURVEY §4.4): sharding and collective code
paths execute for real without TPU hardware.

This box's axon sitecustomize force-selects the TPU platform via
``jax.config.update("jax_platforms", "axon,cpu")`` at interpreter start —
env vars alone cannot override it, and initializing the axon backend dials
the (single-client) TPU tunnel, which tests must never do.  So the
override goes through jax.config, before any backend is initialized.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process integration tests (cluster boots)")
