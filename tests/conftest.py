"""Test harness setup.

Tests run on a virtual 8-device CPU mesh (the analogue of the reference's
IPC-on-one-box multi-node rig, `scripts/run_experiments.py:67` /
`transport/transport.cpp:132` — SURVEY §4.4): sharding and collective code
paths execute for real without TPU hardware.  Env vars must be set before
the first `import jax` anywhere, hence this module-level block.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
