"""TPC-C workload: loader invariants, generation distributions, money
conservation, D_NEXT_O_ID / order-insert consistency (the reference's
consistency oracle is `YCSB_ABORT_MODE`-style spot checks; here we assert
TPC-C's actual audit invariants over the device tables)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deneva_tpu.config import Config
from deneva_tpu.engine import Engine
from deneva_tpu.workloads import get_workload
from deneva_tpu.workloads.tpcc import TPCC_NEW_ORDER, TPCC_PAYMENT


def tpcc_cfg(**kw):
    base = dict(workload="TPCC", num_wh=2, cust_per_dist=120,
                max_items=200, max_items_per_txn=5, max_accesses=8,
                epoch_batch=64, conflict_buckets=1024,
                max_txn_in_flight=256, insert_table_cap=1 << 14,
                warmup_secs=0.0, done_secs=0.2)
    base.update(kw)
    from deneva_tpu.config import WorkloadKind, CCAlg
    base["workload"] = WorkloadKind(base["workload"])
    if "cc_alg" in base:
        base["cc_alg"] = CCAlg(base["cc_alg"])
    return Config(**base)


def run_epochs(cfg, n=25, seed=0):
    eng = Engine(cfg, get_workload(cfg))
    state = eng.init_state(seed)
    state = eng.jit_run(state, n)
    return jax.device_get(state)


def test_loader_shapes_and_invariants():
    cfg = tpcc_cfg()
    wl = get_workload(cfg)
    db = wl.load()
    assert set(db) == {"WAREHOUSE", "DISTRICT", "CUSTOMER", "HISTORY",
                       "NEW-ORDER", "ORDER", "ORDER-LINE", "ITEM", "STOCK"}
    assert int(db["DISTRICT"].row_cnt) == 2 * 10
    next_o = db["DISTRICT"].host_column("D_NEXT_O_ID")
    assert (next_o == 3001).all()
    cw = db["CUSTOMER"].host_column("C_W_ID")
    assert cw.min() == 0 and cw.max() == 1
    sq = db["STOCK"].host_column("S_QUANTITY")
    assert sq.min() >= 10 and sq.max() <= 100


@pytest.mark.slow
def test_generation_distributions():
    cfg = tpcc_cfg(perc_payment=0.5)
    wl = get_workload(cfg)
    q = jax.device_get(wl.generate(jax.random.PRNGKey(0), 4096))
    pay = q.txn_type == TPCC_PAYMENT
    assert 0.4 < pay.mean() < 0.6
    assert q.w_id.min() >= 0 and q.w_id.max() < cfg.num_wh
    assert q.d_id.max() < 10
    assert (q.c_id < cfg.cust_per_dist).all()
    # remote payment customer ~15% (tpcc_query.cpp:168-186)
    rem = (q.c_w_id != q.w_id)[pay]
    assert 0.08 < rem.mean() < 0.25
    no = ~pay
    assert q.ol_cnt[no].min() >= 5 and q.ol_cnt[no].max() <= 5
    # valid items are within cnt and distinct
    for i in np.where(no)[0][:50]:
        v = q.item_valid[i]
        ids = q.items[i][v]
        assert len(set(ids.tolist())) == len(ids)


@pytest.mark.parametrize("alg", ["NOCC", "OCC", "TPU_BATCH", "CALVIN",
                                 "NO_WAIT", "MVCC"])
@pytest.mark.slow
def test_tpcc_runs_and_commits(alg):
    cfg = tpcc_cfg(cc_alg=alg)
    state = run_epochs(cfg)
    commits = int(state.stats["total_txn_commit_cnt"])
    assert commits > 0
    if alg in ("CALVIN", "TPU_BATCH"):
        assert int(state.stats["total_txn_abort_cnt"]) == 0


def test_dynamic_order_index_tracks_inserted_orders():
    """--tpcc_order_index: the dynamic ordered index (index_btree insert
    analogue) stays exact under the NewOrder insert stream — every ORDER
    ring row is findable by its composite key at its ring slot, and a
    district range scan walks its o_ids like the reference's leaf walk."""
    cfg = tpcc_cfg(cc_alg="TPU_BATCH", tpcc_order_index=True,
                   insert_table_cap=1 << 14)
    wl = get_workload(cfg)
    eng = Engine(cfg, wl)
    state = eng.jit_run(eng.init_state(0), 20)
    db = jax.device_get(state.db)
    idx = db["ORDER_IDX"]
    n_ord = int(db["ORDER"].row_cnt)
    assert 0 < n_ord < cfg.insert_table_cap and not bool(
        np.asarray(idx.overflowed()))
    o_w = np.asarray(db["ORDER"].columns["O_W_ID"])[:n_ord]
    o_d = np.asarray(db["ORDER"].columns["O_D_ID"])[:n_ord]
    o_id = np.asarray(db["ORDER"].columns["O_ID"])[:n_ord]
    keys = (o_w * wl.n_dist + o_d).astype(np.int64) * (1 << 21) + o_id
    import jax.numpy as jnp
    got = np.asarray(idx.lookup(jnp.asarray(keys.astype(np.int32))))
    assert (got == np.arange(n_ord)).all()   # ring slot = insert order
    # district leaf walk: range over one district == its sorted o_ids
    dk = int(o_w[0]) * wl.n_dist + int(o_d[0])
    lo = np.int32(dk * (1 << 21))
    hi = np.int32(dk * (1 << 21) + (1 << 21) - 1)
    slots, ok = idx.range_between(jnp.asarray([lo]), jnp.asarray([hi]),
                                  256)
    walk = np.asarray(slots)[0][np.asarray(ok)[0]]
    mine = np.where((o_w == o_w[0]) & (o_d == o_d[0]))[0]
    assert sorted(walk.tolist()) == sorted(mine.tolist())
    assert (np.diff(o_id[walk]) >= 1).all()   # ascending o_id walk


def test_mvcc_reads_byte_match_serial_oracle():
    """MVCC value fidelity for TPC-C (VERDICT r3 next #7): every value a
    committed txn READ must byte-match serial execution.  TPC-C's
    executor gathers are structurally protected — pure reads target
    load-immutable columns (W_TAX/D_TAX/C_DISCOUNT), RMW reads
    (D_NEXT_O_ID, S_QUANTITY) are only allowed at the latest version
    (MVCC aborts a stale RMW, cc/timestamp.py), and read-only txns read
    their serialization point (the epoch snapshot) — so no version-value
    ring is needed.  PROOF, not assertion: the ORDER table records
    exactly the committed NewOrders, so the cumulative read checksum is
    recomputable in closed form from the immutable columns — one
    divergent byte in any committed gather breaks the equality."""
    cfg = tpcc_cfg(cc_alg="MVCC", num_wh=2, epoch_batch=64,
                   max_txn_in_flight=256, perc_payment=0.4)
    wl = get_workload(cfg)
    eng = Engine(cfg, wl)
    s0 = eng.init_state(1)
    d0 = jax.device_get(s0.db)
    state = eng.jit_run(s0, 25)
    d1 = jax.device_get(state.db)
    got = int(state.stats["read_checksum"])

    n_ord = int(d1["ORDER"].row_cnt)
    assert 0 < n_ord < cfg.insert_table_cap, "need commits, no ring wrap"
    o_w = np.asarray(d1["ORDER"].columns["O_W_ID"])[:n_ord]
    o_d = np.asarray(d1["ORDER"].columns["O_D_ID"])[:n_ord]
    o_c = np.asarray(d1["ORDER"].columns["O_C_ID"])[:n_ord]
    w_tax = d0["WAREHOUSE"].host_column("W_TAX")
    d_tax = d0["DISTRICT"].host_column("D_TAX")
    c_disc = d0["CUSTOMER"].host_column("C_DISCOUNT")
    # mirror the executor's f32 arithmetic lane-for-lane (tpcc.py
    # _exec_neworder): (w_tax + d_tax + c_disc) * 1000 -> uint32
    dslot = o_w * wl.n_dist + o_d
    cslot = dslot * cfg.cust_per_dist + o_c
    lanes = ((w_tax[o_w].astype(np.float32)
              + d_tax[dslot].astype(np.float32)
              + c_disc[cslot].astype(np.float32)) * np.float32(1000)
             ).astype(np.uint32)
    ref = int(lanes.sum(dtype=np.uint32))
    assert got == ref


@pytest.mark.slow
def test_money_conservation_and_order_consistency():
    """TPC-C audit: sum(D_YTD)+sum(W_YTD) grows by exactly 2x the committed
    payment amounts; orders inserted == sum of D_NEXT_O_ID advances."""
    cfg = tpcc_cfg(cc_alg="TPU_BATCH", perc_payment=0.5)
    wl = get_workload(cfg)
    eng = Engine(cfg, wl)
    state = eng.init_state(0)
    d0 = jax.device_get(state.db)
    state = eng.jit_run(state, 30)
    d1 = jax.device_get(state.db)

    h = d1["HISTORY"]
    n_hist = int(h.row_cnt)
    assert n_hist < cfg.insert_table_cap, "ring wrapped; test invalid"
    paid = np.asarray(h.columns["H_AMOUNT"])[:n_hist].sum()

    dytd = (d1["DISTRICT"].host_column("D_YTD").astype(np.float64).sum()
            - d0["DISTRICT"].host_column("D_YTD").astype(np.float64).sum())
    wytd = (d1["WAREHOUSE"].host_column("W_YTD").astype(np.float64).sum()
            - d0["WAREHOUSE"].host_column("W_YTD").astype(np.float64).sum())
    assert n_hist > 0
    np.testing.assert_allclose(dytd, paid, rtol=1e-5)
    np.testing.assert_allclose(wytd, paid, rtol=1e-5)

    # customer balance decreased by total paid
    bal = (d0["CUSTOMER"].host_column("C_BALANCE").astype(np.float64).sum()
           - d1["CUSTOMER"].host_column("C_BALANCE").astype(np.float64).sum())
    np.testing.assert_allclose(bal, paid, rtol=1e-5)

    # order-id accounting: next_o_id advances == ORDER rows == NEW-ORDER rows
    adv = int((d1["DISTRICT"].host_column("D_NEXT_O_ID")
               - d0["DISTRICT"].host_column("D_NEXT_O_ID")).sum())
    assert adv == int(d1["ORDER"].row_cnt) == int(d1["NEW-ORDER"].row_cnt)
    assert adv > 0

    # per-district order ids are exactly [3001, 3001+adv_d) with no dups
    n_ord = int(d1["ORDER"].row_cnt)
    o_d = np.asarray(d1["ORDER"].columns["O_D_ID"])[:n_ord]
    o_w = np.asarray(d1["ORDER"].columns["O_W_ID"])[:n_ord]
    o_id = np.asarray(d1["ORDER"].columns["O_ID"])[:n_ord]
    next_o = d1["DISTRICT"].host_column("D_NEXT_O_ID")
    for w in range(cfg.num_wh):
        for d in range(10):
            ids = np.sort(o_id[(o_w == w) & (o_d == d)])
            hi = next_o[w * 10 + d]
            assert (ids == np.arange(3001, hi)).all(), (w, d)

    # order lines reference real orders; avg just under ol_cnt because
    # duplicate sampled items are invalidated rather than resampled
    n_ol = int(d1["ORDER-LINE"].row_cnt)
    assert n_ol >= n_ord * 4


@pytest.mark.slow
def test_order_free_exemption_commit_rate():
    """Warehouse/district/customer accesses are order_free (commutative
    scatter-adds + immutable-column reads), so the deterministic
    backends must not chain on them: with every txn hitting one of 2
    warehouses, defers may come only from stock-row collisions —
    row-level conflict declaration would defer nearly everything here."""
    for alg in ("TPU_BATCH", "CALVIN"):
        # max_items large enough that NURand stock collisions are rare;
        # warehouse/district contention stays maximal (2 warehouses)
        cfg = tpcc_cfg(cc_alg=alg, num_wh=2, perc_payment=0.5,
                       max_items=4096)
        state = run_epochs(cfg, n=30)
        commits = int(state.stats["total_txn_commit_cnt"])
        defers = int(state.stats["defer_cnt"])
        assert commits > 0
        assert defers < max(commits // 10, 5), (alg, commits, defers)


@pytest.mark.slow
def test_stock_quantity_rule():
    """S_QUANTITY stays in (0, 101): the new_order_8 replenish rule."""
    cfg = tpcc_cfg(cc_alg="TPU_BATCH", perc_payment=0.0, num_wh=1,
                   max_items=50)
    state = run_epochs(cfg, n=40)
    sq = np.asarray(state.db["STOCK"].columns["S_QUANTITY"])[:50]
    assert sq.min() > -10 and sq.max() <= 101
    assert int(state.stats["total_txn_commit_cnt"]) > 0
    rc = np.asarray(state.db["STOCK"].columns["S_REMOTE_CNT"])[:50]
    assert (rc == 0).all()  # single warehouse -> no remote supplies


def test_ring_append_wraps():
    from deneva_tpu.storage.catalog import parse_schema
    from deneva_tpu.storage.table import DeviceTable
    cat = parse_schema("TABLE=T\n\t8,int64_t,A\n")
    t = DeviceTable.create(cat.table("T"), 8, ring=True)
    for i in range(3):
        t, slots = t.append({"A": jnp.arange(5) + i * 5},
                            jnp.ones(5, bool))
    assert int(t.row_cnt) == 15
    vals = np.sort(np.asarray(t.columns["A"])[:8])
    np.testing.assert_array_equal(vals, np.arange(7, 15))


def test_lastname_index_matches_closed_form():
    """The CUSTOMER_LAST probe path (hash index + postings walk,
    index_hash.cpp:68-100) resolves exactly the customer the arithmetic
    closed form picks when per-lastname counts are uniform — the index is
    the measured path (default on), the closed form the oracle."""
    cfg = tpcc_cfg()                      # cpd=120 -> names=120, uniform
    assert cfg.tpcc_by_last_index
    wl_idx = get_workload(cfg)
    wl_arith = get_workload(cfg.replace(tpcc_by_last_index=False))
    rng = jax.random.PRNGKey(11)
    q1 = wl_idx.generate(rng, 256)
    q2 = wl_arith.generate(rng, 256)
    for f in ("txn_type", "w_id", "d_id", "c_id", "c_w_id", "c_d_id"):
        assert (np.asarray(getattr(q1, f)) ==
                np.asarray(getattr(q2, f))).all(), f


def test_lastname_index_irregular_counts():
    """cust_per_dist=1500 with 1000 lastnames: lastnames < 500 have two
    customers, the rest one — the index returns the true middle of the
    actual run (closed-form arithmetic assumes uniform counts and cannot;
    this is the case that justifies the index machinery)."""
    cfg = tpcc_cfg(cust_per_dist=1500)
    wl = get_workload(cfg)
    L = jnp.asarray([0, 499, 500, 999], jnp.int32)
    mid = np.asarray(wl._lastname_middle(
        jnp.zeros(4, jnp.int32), jnp.zeros(4, jnp.int32), L))
    # count 2 -> postings [L, L+1000], middle idx 1; count 1 -> [L]
    assert mid.tolist() == [1000, 1499, 500, 999]


@pytest.mark.slow
def test_escrow_ablation_flag():
    """--escrow_order_free=false makes the deterministic backends see the
    full RW-sets (no commutativity exemption): still correct, strictly
    more chaining — the ablation that separates algorithm win from
    annotation win in BASELINE.md."""
    cfg = tpcc_cfg(cc_alg="TPU_BATCH", num_wh=2)
    st_on = run_epochs(cfg, n=15).stats
    st_off = run_epochs(cfg.replace(escrow_order_free=False), n=15).stats
    on_c = int(st_on["total_txn_commit_cnt"])
    off_c = int(st_off["total_txn_commit_cnt"])
    assert on_c > 0 and off_c > 0
    # 2 warehouses, payments serialize on warehouse rows: ablation defers
    assert off_c <= on_c


def test_full_schema_mode():
    """TPCC_FULL_SCHEMA (reference benchmarks/TPCC_full_schema.txt): all
    reference columns materialize, loader fills them, and the full-spec
    stock bookkeeping (S_YTD += qty, S_ORDER_CNT++) runs; short-schema
    semantics (commit counts, invariants) are unchanged."""
    cfg = tpcc_cfg(cc_alg="TPU_BATCH", tpcc_full_schema=True)
    wl = get_workload(cfg)
    db = wl.load()
    assert "C_DATA" in db["CUSTOMER"].columns
    assert "S_DIST_07" in db["STOCK"].columns
    assert int(np.asarray(db["CUSTOMER"].columns["C_DATA"][:5]).sum()) != 0
    state = run_epochs(cfg, n=15)
    stats = {k: np.asarray(v) for k, v in state.stats.items()}
    assert int(stats["total_txn_commit_cnt"]) > 0
    # full-spec bookkeeping moved: every committed neworder item adds
    s_ytd = np.asarray(state.db["STOCK"].columns["S_YTD"])
    s_ocnt = np.asarray(state.db["STOCK"].columns["S_ORDER_CNT"])
    assert s_ytd.sum() > 0 and s_ocnt.sum() > 0
    # short-schema run at same seed: identical commit decisions
    s_short = run_epochs(tpcc_cfg(cc_alg="TPU_BATCH"), n=15)
    short_stats = {k: np.asarray(v) for k, v in s_short.stats.items()}
    assert int(short_stats["total_txn_commit_cnt"]) == \
        int(stats["total_txn_commit_cnt"])
