"""Self-driving control plane (runtime/controller.py + cc/router.py,
``Config.ctrl``, PR 16 tentpole).

Five claim families:

* **Oscillation control units** — hysteresis dead band holds the class,
  a single-tick excursion never moves a knob (confirm streak), and a
  knob that moved holds through its cooldown no matter what the
  classes do.
* **Fail-safe governor** — stale signals (stalled epochs or a boundary
  gap past ``ctrl_stale_s``) revert every knob to the static config on
  THAT tick; ``ctrl_heal`` consecutive healthy ticks re-engage; the
  trip counter advances once per trip, not once per stale tick.
* **Decision replay** — the ``[ctrl]`` line stream round-trips through
  `harness.parse.parse_ctrl` + `signals_of_row` and a fresh controller
  replayed over the recorded signals reproduces the decision stream
  bit-for-bit (`replay_decisions` returns []); a tampered row is
  reported.
* **Off pins** — ``ctrl=false`` (the default) builds no controller and
  no sidecar on a loopback ServerNode, broadcasts byte-identical blobs
  (the wire pin), and the ROUTED epoch program driven with
  ``static_knobs`` is value-identical per epoch to the unrouted
  ``jit_run`` on every db/cc_state/pool/stats leaf (the state pin:
  routing is pure mechanism, the static knob vector IS the off
  semantics).
* **Adaptive floor smoke** — on a deterministic hot YCSB stream the
  adaptive plane's committed count stays within the acceptance floor
  of the best single static assignment run through the SAME compiled
  program (the frontier sweep in results/router carries the full
  multi-phase version).
"""

import numpy as np
import jax
import pytest

from deneva_tpu.config import CCAlg, Config, WorkloadKind
from deneva_tpu.runtime.controller import (Controller, CtrlSignals,
                                           GOV_ARMED, GOV_STATIC, HOT,
                                           SPARSE, ctrl_line,
                                           quota_scale, replay_decisions,
                                           signals_of_row)


def ctl_cfg(**kw):
    """Valid armed config (single part unless overridden): the ctrl
    gate pins metrics on, a candidate cc_alg, and the escrow ordering
    exemption off."""
    base = dict(workload=WorkloadKind.YCSB, cc_alg=CCAlg.OCC,
                metrics=True, ctrl=True, escrow_order_free=False,
                repair=True, audit=True,
                synth_table_size=1 << 12, req_per_query=4,
                max_accesses=4, epoch_batch=128, conflict_buckets=1024,
                max_txn_in_flight=512, zipf_theta=0.9,
                read_perc=0.1, write_perc=0.9,
                warmup_secs=0.0, done_secs=0.2)
    base.update(kw)
    return Config(**base)


def sig(epoch=0, epochs=1, dens=(0,), gap_us=1000, **kw):
    return CtrlSignals(epoch=epoch, epochs=epochs, dens=list(dens),
                       gap_us=gap_us, **kw)


# dens value that normalizes to density d for a 1-part cfg:
# d = dens * part_cnt / (epochs * epoch_batch)
def lanes(cfg, d, epochs=1):
    return int(d * epochs * cfg.epoch_batch / max(cfg.part_cnt, 1))


# ---- oscillation control units -----------------------------------------

def test_hysteresis_dead_band_holds_class():
    """Density inside (ctrl_lo, ctrl_hi) never moves the class: the
    initial MID assignment (OCC) survives any in-band stream."""
    cfg = ctl_cfg(ctrl_cooldown=0)
    ctl = Controller(cfg)
    mid = lanes(cfg, 0.10)
    for e in range(8):
        dec = ctl.decide(sig(epoch=e, dens=[mid]))
        assert dec.gov == GOV_ARMED
        assert dec.assign == [1], "in-band tick moved the backend"
        assert dec.gshift == [0]


def test_confirm_streak_blocks_single_tick_flip():
    """One hot tick (then back in band) is noise by contract: with
    ctrl_confirm=2 the class — and therefore the assignment — holds."""
    cfg = ctl_cfg(ctrl_cooldown=0, ctrl_confirm=2)
    ctl = Controller(cfg)
    assert ctl.decide(sig(dens=[lanes(cfg, 0.5)])).assign == [1]
    for e in range(4):
        dec = ctl.decide(sig(epoch=e, dens=[lanes(cfg, 0.1)]))
        assert dec.assign == [1]
    # a SUSTAINED excursion does move it, on the confirm-th tick
    assert ctl.decide(sig(dens=[lanes(cfg, 0.5)])).assign == [1]
    dec = ctl.decide(sig(dens=[lanes(cfg, 0.5)]))
    assert dec.assign == [2] and ctl.cls == [HOT]


def test_cooldown_holds_moved_knob():
    """After a move the knob holds ctrl_cooldown ticks even with the
    opposite class fully confirmed; only the EXPIRY tick moves it."""
    cfg = ctl_cfg(ctrl_cooldown=3, ctrl_confirm=1)
    ctl = Controller(cfg)
    hot, cold = lanes(cfg, 0.5), lanes(cfg, 0.001)
    assert ctl.decide(sig(dens=[hot])).assign == [2]    # move; rearm
    held = [ctl.decide(sig(dens=[cold])).assign for _ in range(2)]
    assert held == [[2], [2]], "cooldown did not hold the knob"
    assert ctl.decide(sig(dens=[cold])).assign == [0]   # expiry tick
    # SPARSE also coarsens the incidence by ctrl_gshift (gshift has its
    # own cooldown, armed on ITS move at the same ticks here)
    assert ctl.gshift == [cfg.ctrl_gshift]


def test_repair_cap_tracks_fallback_rate():
    """Fallback-heavy ticks grow the live sub-round cap toward
    repair_rounds; salvage-free ticks shed it, floored at 1."""
    cfg = ctl_cfg(ctrl_cooldown=0, repair_rounds=3)
    ctl = Controller(cfg)
    mid = lanes(cfg, 0.1)
    d = ctl.decide(sig(dens=[mid], fallback=8, salvaged=2))
    assert d.repair_cap == 3                             # at max: hold
    assert ctl.decide(sig(dens=[mid])).repair_cap == 2   # quiet: shed
    assert ctl.decide(sig(dens=[mid])).repair_cap == 1
    assert ctl.decide(sig(dens=[mid])).repair_cap == 1   # floor
    d = ctl.decide(sig(dens=[mid], fallback=8, salvaged=2))
    assert d.repair_cap == 2                             # 2*fb > total
    d = ctl.decide(sig(dens=[mid], fallback=1, salvaged=8))
    assert d.repair_cap == 2                             # salvage-led: hold


def test_audit_cadence_tightens_on_witness():
    """Any witness tightens the audit cadence to full coverage (1);
    ctrl_confirm quiet ticks relax it back to the static cadence."""
    cfg = ctl_cfg(ctrl_cooldown=0, ctrl_confirm=2, audit_cadence=4)
    ctl = Controller(cfg)
    mid = lanes(cfg, 0.1)
    assert ctl.decide(sig(dens=[mid])).audit_cadence == 4
    assert ctl.decide(sig(dens=[mid], witnesses=3)).audit_cadence == 1
    assert ctl.decide(sig(dens=[mid])).audit_cadence == 1  # quiet=1
    assert ctl.decide(sig(dens=[mid])).audit_cadence == 4  # quiet=2


def test_quota_steps_and_scale():
    """SLO breaches shed admission a step per (cooled-down) tick up to
    ctrl_scale_max; clean ticks heal a step; idx=0 is EXACTLY 1.0."""
    cfg = ctl_cfg(ctrl_cooldown=0, ctrl_scale_max=2)
    ctl = Controller(cfg)
    mid = lanes(cfg, 0.1)
    assert ctl.decide(sig(dens=[mid], breaches=2)).quota_idx == 1
    assert ctl.decide(sig(dens=[mid], breaches=1)).quota_idx == 2
    assert ctl.decide(sig(dens=[mid], breaches=5)).quota_idx == 2  # cap
    assert ctl.decide(sig(dens=[mid])).quota_idx == 1              # heal
    assert quota_scale(0) == 1.0
    assert quota_scale(1) == pytest.approx(0.8)
    assert quota_scale(3) == pytest.approx(0.8 ** 3)


# ---- fail-safe governor ------------------------------------------------

def test_stale_signal_trips_to_static_and_reengages():
    """A stale tick (gap past ctrl_stale_s, or zero epochs) reverts to
    the static knob vector IMMEDIATELY, counts ONE trip per trip, and
    ctrl_heal consecutive healthy ticks re-engage on the heal tick."""
    cfg = ctl_cfg(ctrl_cooldown=0, ctrl_confirm=1, ctrl_heal=3)
    ctl = Controller(cfg)
    hot = lanes(cfg, 0.5)
    assert ctl.decide(sig(dens=[hot])).assign == [2]     # adapted
    stale = int(cfg.ctrl_stale_s * 1e6) + 1
    dec = ctl.decide(sig(dens=[hot], gap_us=stale))
    assert dec.gov == GOV_STATIC and dec.stale_trips == 1
    assert dec.assign == [1] and dec.gshift == [0]       # static = cfg
    assert dec.repair_cap == cfg.repair_rounds
    assert dec.quota_idx == 0
    # a second stale tick (stalled epochs this time) is the SAME trip
    dec = ctl.decide(sig(dens=[hot], epochs=0))
    assert dec.gov == GOV_STATIC and dec.stale_trips == 1
    # healthy ticks 1..heal-1 stay static; the heal tick re-arms and
    # decides adaptively again (the hot class survived the outage)
    for _ in range(cfg.ctrl_heal - 1):
        dec = ctl.decide(sig(dens=[hot]))
        assert dec.gov == GOV_STATIC
    dec = ctl.decide(sig(dens=[hot]))
    assert dec.gov == GOV_ARMED and dec.assign == [2]
    # a later trip increments the counter again
    dec = ctl.decide(sig(dens=[hot], gap_us=stale))
    assert dec.stale_trips == 2


# ---- decision replay ---------------------------------------------------

def _scripted_rows(cfg):
    """A signal script covering adapt, trip, heal, quota and repair
    moves; returns the parsed [ctrl] rows (emit order)."""
    from deneva_tpu.harness.parse import parse_ctrl
    ctl = Controller(cfg)
    hot, cold = lanes(cfg, 0.5), lanes(cfg, 0.001)
    stale = int(cfg.ctrl_stale_s * 1e6) + 1
    script = [sig(epoch=e, dens=[hot], fallback=4, salvaged=1)
              for e in range(3)]
    script += [sig(epoch=3, dens=[hot], gap_us=stale),
               sig(epoch=4, dens=[hot], epochs=0)]
    script += [sig(epoch=5 + i, dens=[cold], breaches=i % 2,
                   witnesses=(1 if i == 2 else 0)) for i in range(6)]
    lines = [ctrl_line(0, s, ctl.decide(s)) for s in script]
    return parse_ctrl(lines)


def test_replay_reproduces_decision_stream():
    cfg = ctl_cfg(ctrl_confirm=2, ctrl_cooldown=2)
    rows = _scripted_rows(cfg)
    assert len(rows) == 11
    govs = {r["gov"] for r in rows}
    assert govs == {GOV_ARMED, GOV_STATIC}, "script never tripped"
    assert replay_decisions(cfg, rows) == []


def test_replay_reports_tampered_row():
    cfg = ctl_cfg(ctrl_confirm=2, ctrl_cooldown=2)
    rows = _scripted_rows(cfg)
    rows[1]["assign"] = "0"
    bad = replay_decisions(cfg, rows)
    assert bad and "assign" in bad[0]


def test_dgcc_arms_fourth_router_class():
    """ctrl_dgcc swaps the HOT class's backend from TPU_BATCH (2) to
    the DGCC wavefront candidate (3): the default map gains the fourth
    class only under the flag, the same hot-density stream that moved
    a plain controller to [2] moves the armed one to [3], and the
    flag-off stream is untouched — the default-off contract."""
    from deneva_tpu.runtime.controller import (CLASS_BACKEND,
                                               CLASS_BACKEND_DGCC,
                                               default_backend_map)
    cfg = ctl_cfg(ctrl_cooldown=0, ctrl_confirm=1)
    dcfg = ctl_cfg(ctrl_cooldown=0, ctrl_confirm=1, ctrl_dgcc=True)
    assert default_backend_map(cfg) == CLASS_BACKEND == (0, 1, 2)
    assert default_backend_map(dcfg) == CLASS_BACKEND_DGCC == (0, 1, 3)
    hot = sig(dens=[lanes(cfg, 0.5)])
    assert Controller(cfg).decide(hot).assign == [2]
    ctl = Controller(dcfg)
    assert ctl.decide(hot).assign == [3] and ctl.cls == [HOT]
    # the cold end is untouched: SPARSE still routes to class 0
    assert Controller(dcfg).decide(
        sig(dens=[lanes(dcfg, 0.001)])).assign == [0]


def test_dgcc_replay_compat_both_directions():
    """Replay stays bit-faithful across the map change: rows recorded
    by a dgcc-armed controller verify under the armed cfg (forward),
    pre-dgcc rows verify under the plain cfg exactly as before
    (backward — test_replay_reproduces_decision_stream), and replaying
    armed rows under the WRONG map is reported, not silently accepted —
    unless the caller pins the recorded map via the backend_map
    parameter (the audit-a-foreign-log path)."""
    from deneva_tpu.runtime.controller import CLASS_BACKEND_DGCC
    dcfg = ctl_cfg(ctrl_confirm=2, ctrl_cooldown=2, ctrl_dgcc=True)
    drows = _scripted_rows(dcfg)
    assert replay_decisions(dcfg, drows) == []
    cfg = ctl_cfg(ctrl_confirm=2, ctrl_cooldown=2)
    bad = replay_decisions(cfg, drows)
    assert bad and any("assign" in m for m in bad)
    assert replay_decisions(cfg, drows,
                            backend_map=CLASS_BACKEND_DGCC) == []


def test_signals_round_trip_through_line():
    s = sig(epoch=7, epochs=3, dens=[5, 0, 9], fallback=2, salvaged=1,
            witnesses=4, breaches=1, gap_us=123456)
    cfg = ctl_cfg(part_cnt=1)
    from deneva_tpu.harness.parse import parse_ctrl
    row, = parse_ctrl([ctrl_line(2, s, Controller(cfg).decide(s))])
    assert signals_of_row(row) == s


# ---- off pins ----------------------------------------------------------

def test_ctrl_off_wire_pin():
    """The house contract, executable: with ctrl off (the default) a
    server builds NO controller, opens NO ctrl sidecar, counts no ctrl
    stat, and its blob broadcast is byte-identical to the pre-ctrl
    codec output — off is the pre-ctrl runtime byte for byte."""
    from deneva_tpu.runtime import wire
    from tests.test_chaos import _solo_server

    node = _solo_server("ctrl_off_pin")
    try:
        assert node.ctl is None
        assert not hasattr(node, "_ctrl_log"), "off run opened a sidecar"
        blk = wire.QueryBlock(
            keys=np.arange(8, dtype=np.int32).reshape(4, 2),
            types=np.ones((4, 2), np.int8),
            scalars=np.zeros((4, 0), np.int32),
            tags=np.arange(4, dtype=np.int64))
        ts = np.arange(4, dtype=np.int64) + 100
        blob = wire.encode_epoch_blob(7, blk, ts)
        sent = []
        node.tp.sendv_many = \
            lambda dests, rt, parts: sent.append((list(dests), rt, parts))
        node.tp.send = lambda d, rt, pl=b"": sent.append(([d], rt, [pl]))
        node.n_srv = 2          # pretend a peer so the bcast emits
        node._bcast_views(7, blk, ts)
        (dests, rt, parts), = sent
        assert rt == "EPOCH_BLOB"
        assert b"".join(bytes(p) for p in parts) == blob
        assert not any(k.startswith("ctrl") for k in node.stats.counters)
    finally:
        node.n_srv = 1
        node.close()


def test_ctrl_off_knobs_value_identity():
    """The state pin: the ROUTED scan driven with `static_knobs` is
    value-identical to the unrouted `jit_run` on every db row, cc_state
    leaf, pool leaf and stats counter — so the governor's fail-safe
    (reverting to the static vector) really is the unrouted config,
    and ctrl-off runs lose nothing by never routing."""
    from deneva_tpu.cc.router import static_knobs
    from deneva_tpu.engine import Engine
    from deneva_tpu.workloads import get_workload
    from deneva_tpu.workloads.ycsb import TABLE

    cfg = ctl_cfg(ctrl=False)
    eng = Engine(cfg, get_workload(cfg))
    s0 = jax.device_get(eng.jit_run(eng.init_state(0), 8))
    s1 = jax.device_get(eng.jit_run_ctrl(eng.init_state(0),
                                         static_knobs(cfg), 8))
    n = cfg.synth_table_size
    np.testing.assert_array_equal(
        np.asarray(s0.db[TABLE].columns["F0"])[:n],
        np.asarray(s1.db[TABLE].columns["F0"])[:n])
    for a, b in zip(jax.tree.leaves(s0.cc_state),
                    jax.tree.leaves(s1.cc_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(s0.pool), jax.tree.leaves(s1.pool)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in s0.stats:
        np.testing.assert_array_equal(np.asarray(s0.stats[k]),
                                      np.asarray(s1.stats[k]), k)


# ---- adaptive floor smoke ----------------------------------------------

def _run_routed(eng, cfg, knob_fn, chunks=6, chunk=8):
    """Run the routed scan chunkwise; knob_fn(ctl, state, epochs) maps
    the post-chunk device stats to the NEXT chunk's knobs (None = keep).
    One ENGINE (so one compiled program) serves every caller — cells
    differ only in knob VALUES — so committed counts compare like for
    like with zero recompiles."""
    from deneva_tpu.cc.router import static_knobs

    state = eng.init_state(0)
    knobs = static_knobs(cfg)
    ctl = Controller(cfg)
    epochs = 0
    for _ in range(chunks):
        state = eng.jit_run_ctrl(state, knobs, chunk)
        epochs += chunk
        nxt = knob_fn(ctl, state, epochs)
        if nxt is not None:
            knobs = nxt
    return int(jax.device_get(state.stats["total_txn_commit_cnt"]))


def test_adaptive_floor_vs_best_static():
    """Deterministic floor smoke (the full multi-phase frontier lives
    in results/router): on a hot zipf-0.9 write-heavy stream the
    adaptive loop — controller ticked on real device counter deltas,
    always-healthy gaps — lands within the RAMP-AWARE floor of the
    best static assignment run through the SAME compiled program for
    the SAME epochs (the first decision applies after the baseline
    tick, so 2 of the 6 chunks run the static cfg knobs by design),
    and clears every non-best static decisively — the adaptation
    claim the single-phase shape can make."""
    from deneva_tpu.cc.router import CANDIDATES, knobs_from_decision
    from deneva_tpu.engine import Engine
    from deneva_tpu.workloads import get_workload

    cfg = ctl_cfg(ctrl_confirm=1, ctrl_cooldown=0, audit=False,
                  repair=False)
    eng = Engine(cfg, get_workload(cfg))
    prev = [None]

    def adaptive(ctl, state, epochs):
        dens = jax.device_get(state.stats["conflict_density"])
        cur = np.asarray(dens).astype(np.int64)
        last, prev[0] = prev[0], (cur, epochs)
        if last is None:
            return None
        sig_ = CtrlSignals(epoch=epochs, epochs=epochs - last[1],
                           dens=[int(x) for x in cur - last[0]],
                           gap_us=1000)
        d = ctl.decide(sig_)
        assert d.gov == GOV_ARMED
        return knobs_from_decision(cfg, d.assign, d.gshift,
                                   d.repair_cap, d.audit_cadence)

    got = _run_routed(eng, cfg, adaptive)
    static = {}
    for i, alg in enumerate(CANDIDATES):
        kn = knobs_from_decision(cfg, [i], [0], cfg.repair_rounds,
                                 max(1, cfg.audit_cadence))
        static[alg.name] = _run_routed(eng, cfg, lambda *_, kn=kn: kn)
    best = max(static.values())
    assert best > 0, "static cells inert"
    # ramp-aware floor: 2/6 chunks on the static OCC knobs before the
    # first armed decision bound the ideal at ~(2*occ + 4*best)/6
    assert got >= 0.8 * best, (got, static)
    # decisive over both non-best statics: the controller found the
    # hot-regime backend instead of averaging the frontier
    for alg, val in static.items():
        if val != best:
            assert got > 2 * val, (alg, got, static)
