"""Logging / replication / replay (reference `system/logger.*` + SURVEY §5.4).

The reference's logger is write-only (no recovery path); here the command
log replays by deterministic re-execution, so the tests can assert the
strongest property available: replayed state == live state, bit for bit.
"""

import os

import numpy as np
import pytest

from deneva_tpu.config import Config, CCAlg, WorkloadKind
from deneva_tpu.runtime.logger import pack_record, unpack_records
from deneva_tpu.stats import parse_summary


def test_log_record_roundtrip_and_torn_tail():
    act = np.array([1, 0, 1, 1, 0, 0, 0, 1, 1], bool)
    rec = pack_record(7, b"payload-bytes", act)
    rec2 = pack_record(8, b"second", np.ones(4, bool))
    out = list(unpack_records(rec + rec2))
    assert [e for e, _, _ in out] == [7, 8]
    assert out[0][1] == b"payload-bytes"
    got = np.unpackbits(out[0][2])[: len(act)].astype(bool)
    assert (got == act).all()
    # torn tail (crash mid-write): parser stops cleanly at the last
    # complete record instead of raising
    torn = rec + rec2[: len(rec2) - 3]
    assert [e for e, _, _ in list(unpack_records(torn))] == [7]


def _cfg(tmp, **kw):
    base = dict(
        workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
        epoch_batch=64, conflict_buckets=512, synth_table_size=2048,
        max_txn_in_flight=512, req_per_query=4, max_accesses=4,
        zipf_theta=0.6, warmup_secs=0.3, done_secs=1.0,
        logging=True, log_dir=str(tmp))
    base.update(kw)
    return Config(**base)


@pytest.mark.slow
def test_replay_matches_live_state(tmp_path):
    """Solo server, seeded admission queue; replaying the log must
    reproduce the live table state exactly."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deneva_tpu.runtime import wire
    from deneva_tpu.runtime.logger import replay_log
    from deneva_tpu.runtime.native import ipc_endpoints
    from deneva_tpu.runtime.server import ServerNode

    cfg = _cfg(tmp_path, node_cnt=1, part_cnt=1, client_node_cnt=0)
    node = ServerNode(cfg, ipc_endpoints(1, "replaytest",
                                         str(tmp_path)), "cpu")
    # seed the admission queue directly (no client process needed)
    rng = jax.random.PRNGKey(3)
    for i in range(30):
        q = node.wl.generate(jax.random.fold_in(rng, i), 64)
        keys, types, scalars = node.wl.to_wire(q)
        blk = wire.QueryBlock(keys=keys, types=types, scalars=scalars,
                              tags=np.arange(64, dtype=np.int64) + i * 64)
        node.pending.append((0, blk))
    node.run()
    live_f0 = np.asarray(node.db["MAIN_TABLE"].columns["F0"])
    commits_live = float(
        jax.device_get(node.dev_stats["total_txn_commit_cnt"]))
    node.close()
    assert commits_live > 0

    db = replay_log(node.log_path, cfg)
    replay_f0 = np.asarray(db["MAIN_TABLE"].columns["F0"])
    assert (replay_f0 == live_f0).all(), "replayed state diverged from live"


@pytest.mark.slow
def test_cluster_with_replicas_logs_identical(tmp_path):
    """2 servers + 1 client + 1 replica each: group commit completes,
    and each replica's log is byte-identical to its primary's."""
    from deneva_tpu.runtime.launch import run_cluster

    cfg = _cfg(tmp_path, node_cnt=2, client_node_cnt=1, replica_cnt=1,
               epoch_batch=128, synth_table_size=4096)
    out = run_cluster(cfg, platform="cpu", run_id="replitest")
    log_dir = os.path.join(tmp_path, "replitest")  # per-run namespacing
    # servers 0,1; client 2; replicas 3,4
    assert set(out) == {0, 1, 2, 3, 4}
    s0 = parse_summary(out[0][1])
    assert s0["total_txn_commit_cnt"] > 0
    assert s0["log_records"] > 0
    # client got acks only for durable txns; it must have seen some
    assert parse_summary(out[2][1])["txn_cnt"] > 0
    for primary, replica in ((0, 3), (1, 4)):
        with open(os.path.join(log_dir, f"node{primary}.log.bin"),
                  "rb") as f:
            p = f.read()
        with open(os.path.join(log_dir, f"replica{replica}.log.bin"),
                  "rb") as f:
            r = f.read()
        assert len(p) > 0
        # the replica may trail by the final in-flight records; it must
        # hold a prefix — and a substantial one (group commit acked it)
        assert p.startswith(r) or r.startswith(p)
        assert min(len(p), len(r)) > 0.5 * max(len(p), len(r))
