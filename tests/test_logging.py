"""Logging / replication / replay (reference `system/logger.*` + SURVEY §5.4).

The reference's logger is write-only (no recovery path); here the command
log replays by deterministic re-execution, so the tests can assert the
strongest property available: replayed state == live state, bit for bit.
"""

import os

import numpy as np
import pytest

from deneva_tpu.config import Config, CCAlg, WorkloadKind
from deneva_tpu.runtime.logger import pack_record, unpack_records
from deneva_tpu.stats import parse_summary


def test_log_record_roundtrip_and_torn_tail():
    act = np.array([1, 0, 1, 1, 0, 0, 0, 1, 1], bool)
    rec = pack_record(7, b"payload-bytes", act)
    rec2 = pack_record(8, b"second", np.ones(4, bool))
    out = list(unpack_records(rec + rec2))
    assert [e for e, _, _ in out] == [7, 8]
    assert out[0][1] == b"payload-bytes"
    got = np.unpackbits(out[0][2])[: len(act)].astype(bool)
    assert (got == act).all()
    # torn tail (crash mid-write): parser stops cleanly at the last
    # complete record instead of raising
    torn = rec + rec2[: len(rec2) - 3]
    assert [e for e, _, _ in list(unpack_records(torn))] == [7]


def test_recovery_invariant_replay_equals_straight_run(tmp_path):
    """The failover contract, in-process and tier-1-fast: a command
    stream written through EpochLogger and replayed with replay_log /
    replay_into rebuilds db AND device stats bit-identical to a
    straight-through run of the same stream through the same per-epoch
    jit (deterministic replay = re-execution, runtime/logger.py)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from deneva_tpu.cc import get_backend
    from deneva_tpu.engine.step import init_device_stats
    from deneva_tpu.runtime import wire
    from deneva_tpu.runtime.logger import (EpochLogger, replay_into,
                                           replay_log, state_digest)
    from deneva_tpu.runtime.server import make_dist_step
    from deneva_tpu.workloads import get_workload

    cfg = Config(
        workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
        epoch_batch=32, conflict_buckets=256, synth_table_size=1024,
        req_per_query=2, max_accesses=2, logging=True,
        log_dir=str(tmp_path))
    wl = get_workload(cfg)
    be = get_backend(cfg.cc_alg)
    step = make_dist_step(cfg, wl, be)
    n_types = len(getattr(wl, "txn_type_names", ("txn",)))

    # one command stream: 6 epochs of 32 txns with varying active masks
    rng = jax.random.PRNGKey(11)
    path = str(tmp_path / "inproc.log.bin")
    log = EpochLogger(path)
    db = wl.load()
    cc_state = be.init_state(cfg)
    stats = init_device_stats(n_types)
    for e in range(6):
        q = wl.generate(jax.random.fold_in(rng, e), 32)
        keys, types, scalars = wl.to_wire(q)
        block = wire.QueryBlock(keys, types, scalars,
                                tags=np.arange(32, dtype=np.int64))
        ts = np.arange(1, 33, dtype=np.int64) + e * 32
        active = np.ones(32, bool)
        active[e % 32] = False          # vary the logged active mask
        log.append(e, wire.encode_epoch_blob(e, block, ts), active)
        # straight-through execution of the same record
        db, cc_state, stats, *_ = step(
            db, cc_state, stats, jnp.int32(e), jnp.asarray(active),
            jnp.asarray(ts.astype(np.int32)),
            wl.from_wire(keys, types, scalars))
    jax.block_until_ready(stats["total_txn_commit_cnt"])
    assert log.wait_flushed(5, timeout=10.0)
    log.close()

    # full-state replay (db + cc_state + stats) must match bit for bit
    rdb, rcc, rstats, last = replay_into(
        path, cfg, wl, step, wl.load(), be.init_state(cfg),
        init_device_stats(n_types))
    assert last == 5
    assert state_digest(rdb) == state_digest(db)
    assert state_digest(rcc) == state_digest(cc_state)
    for k in stats:
        assert (np.asarray(rstats[k]) == np.asarray(stats[k])).all(), k
    # the public one-shot entry point agrees too
    assert state_digest(replay_log(path, cfg)) == state_digest(db)
    # a prefix replay stops exactly where asked (recovery's truncated-
    # boundary replay path)
    pdb, _, _, plast = replay_into(
        path, cfg, wl, step, wl.load(), be.init_state(cfg),
        init_device_stats(n_types), stop_epoch=3)
    assert plast == 2
    assert state_digest(pdb) != state_digest(db)


def _cfg(tmp, **kw):
    base = dict(
        workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
        epoch_batch=64, conflict_buckets=512, synth_table_size=2048,
        max_txn_in_flight=512, req_per_query=4, max_accesses=4,
        zipf_theta=0.6, warmup_secs=0.3, done_secs=1.0,
        logging=True, log_dir=str(tmp))
    base.update(kw)
    return Config(**base)


@pytest.mark.slow
def test_replay_matches_live_state(tmp_path):
    """Solo server, seeded admission queue; replaying the log must
    reproduce the live table state exactly."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deneva_tpu.runtime import wire
    from deneva_tpu.runtime.logger import replay_log
    from deneva_tpu.runtime.native import ipc_endpoints
    from deneva_tpu.runtime.server import ServerNode

    cfg = _cfg(tmp_path, node_cnt=1, part_cnt=1, client_node_cnt=0)
    node = ServerNode(cfg, ipc_endpoints(1, "replaytest",
                                         str(tmp_path)), "cpu")
    # seed the admission queue directly (no client process needed)
    rng = jax.random.PRNGKey(3)
    for i in range(30):
        q = node.wl.generate(jax.random.fold_in(rng, i), 64)
        keys, types, scalars = node.wl.to_wire(q)
        blk = wire.QueryBlock(keys=keys, types=types, scalars=scalars,
                              tags=np.arange(64, dtype=np.int64) + i * 64)
        node.pending.append((0, blk))
    node.run()
    live_f0 = np.asarray(node.db["MAIN_TABLE"].columns["F0"])
    commits_live = float(
        jax.device_get(node.dev_stats["total_txn_commit_cnt"]))
    node.close()
    assert commits_live > 0

    db = replay_log(node.log_path, cfg)
    replay_f0 = np.asarray(db["MAIN_TABLE"].columns["F0"])
    assert (replay_f0 == live_f0).all(), "replayed state diverged from live"


@pytest.mark.slow
def test_cluster_with_replicas_logs_identical(tmp_path):
    """2 servers + 1 client + 1 replica each: group commit completes,
    and each replica's log is byte-identical to its primary's."""
    from deneva_tpu.runtime.launch import run_cluster

    cfg = _cfg(tmp_path, node_cnt=2, client_node_cnt=1, replica_cnt=1,
               epoch_batch=128, synth_table_size=4096)
    out = run_cluster(cfg, platform="cpu", run_id="replitest")
    log_dir = os.path.join(tmp_path, "replitest")  # per-run namespacing
    # servers 0,1; client 2; replicas 3,4
    assert set(out) == {0, 1, 2, 3, 4}
    s0 = parse_summary(out[0][1])
    assert s0["total_txn_commit_cnt"] > 0
    assert s0["log_records"] > 0
    # client got acks only for durable txns; it must have seen some
    assert parse_summary(out[2][1])["txn_cnt"] > 0
    for primary, replica in ((0, 3), (1, 4)):
        with open(os.path.join(log_dir, f"node{primary}.log.bin"),
                  "rb") as f:
            p = f.read()
        with open(os.path.join(log_dir, f"replica{replica}.log.bin"),
                  "rb") as f:
            r = f.read()
        assert len(p) > 0
        # the replica may trail by the final in-flight records; it must
        # hold a prefix — and a substantial one (group commit acked it)
        assert p.startswith(r) or r.startswith(p)
        assert min(len(p), len(r)) > 0.5 * max(len(p), len(r))
