"""Metrics bus (runtime/metricsbus.py): frame codec round-trips +
forward-compat, the shared JSONL schema module, per-partition conflict
density (unit + rank cross-validation against measured abort rates),
critical-path ledger sum contract, anomaly watchdogs, the metrics-off
wire pin on a loopback ServerNode (the default-off bit-identity
contract), armed loopback aggregation, the monitor TUI/Prom renderers,
and the end-to-end cluster stream (slow tier)."""

import json
import os

import numpy as np
import pytest

from deneva_tpu.config import CCAlg, Config, WorkloadKind
from deneva_tpu.runtime import metricsbus as MB
from deneva_tpu.runtime import metricschema as MS
from deneva_tpu.runtime import wire

from tests.test_chaos import _solo_server


def _cfg(tmp_path, **kw):
    base = dict(metrics=True, telemetry_dir=str(tmp_path))
    base.update(kw)
    return Config(**base)


# ---- frame codec -------------------------------------------------------

def test_frame_roundtrip_and_parts_byte_identity():
    fields = MB.pack_fields(dict(commit=12, abort=3, wall_ms=4.5))
    dens = np.array([7, 0, 2], np.int32)
    buf = MB.encode_metrics_frame(2, MB.ROLE_SERVER, 96, 123456,
                                  fields, dens)
    parts = MB.metrics_frame_parts(2, MB.ROLE_SERVER, 96, 123456,
                                   fields, dens)
    assert b"".join(bytes(p) for p in parts) == buf
    node, role, epoch, t_us, f2, d2 = MB.decode_metrics_frame(buf)
    assert (node, role, epoch, t_us) == (2, MB.ROLE_SERVER, 96, 123456)
    np.testing.assert_array_equal(fields, f2)
    np.testing.assert_array_equal(dens, d2)
    # empty density (clients, vote-mode servers) round-trips too
    buf0 = MB.encode_metrics_frame(5, MB.ROLE_CLIENT, -1, 9, fields,
                                   np.zeros(0, np.int32))
    *_, d0 = MB.decode_metrics_frame(buf0)
    assert len(d0) == 0


def test_frame_record_forward_compat():
    """An OLDER sender's shorter field vector reads as zeros for the
    fields it predates — the ignore-unknown posture of the tagged-line
    parsers, applied to the binary frame."""
    short = np.array([5.0, 2.0], np.float32)        # commit, abort only
    buf = MB._FHDR.pack(1, MB.ROLE_SERVER, MB.MB_VERSION, 8, 77,
                        len(short), 0) + short.tobytes()
    rec = MB.frame_record(buf)
    assert rec["commit"] == 5.0 and rec["abort"] == 2.0
    assert rec["wall_ms"] == 0.0 and "density" not in rec
    assert rec["role"] == "server" and rec["epoch"] == 8


def test_pack_fields_rejects_unknown_keys():
    with pytest.raises(ValueError):
        MB.pack_fields(dict(not_a_field=1.0))


# ---- shared schema module ----------------------------------------------

def test_schema_module_is_the_single_writer(tmp_path):
    """The dedupe satellite, executable: the flight recorder's stream
    class IS the schema module's (no second implementation to drift),
    and the bus stream writes the same record shape with a node
    override."""
    from deneva_tpu.runtime import telemetry as T
    assert T.MetricsStream is MS.MetricsStream
    assert T.read_metrics is MS.read_metrics
    path = os.path.join(str(tmp_path), "bus.jsonl")
    ms = MS.MetricsStream(path, 0)
    ms.emit(4, commit=9)                   # owner node
    ms.emit(4, node=2, commit=1)           # bus override
    ms.close()
    rows = MS.read_metrics(path)
    assert [r["node"] for r in rows] == [0, 2]
    assert all("t_us" in r and r["epoch"] == 4 for r in rows)
    # torn tail tolerated (recovered-aggregator append model)
    with open(path, "a") as f:
        f.write('{"node":0,"epo')
    assert len(MS.read_metrics(path)) == 2


def test_telemetry_dir_and_bus_path_share_the_rule(tmp_path):
    from deneva_tpu.runtime.telemetry import telemetry_dir
    cfg = _cfg(tmp_path)
    assert telemetry_dir(cfg) == MS.stream_dir(cfg) == str(tmp_path)
    assert MB.bus_path(cfg, 3) == os.path.join(str(tmp_path),
                                               "metrics_bus_node3.jsonl")


# ---- conflict density --------------------------------------------------

def _batch(keys, is_write, active=None):
    import jax.numpy as jnp
    from deneva_tpu.cc import AccessBatch
    keys = jnp.asarray(keys, jnp.int32)
    b = keys.shape[0]
    return AccessBatch(
        table_ids=jnp.zeros_like(keys), keys=keys,
        is_read=~jnp.asarray(is_write, bool),
        is_write=jnp.asarray(is_write, bool),
        valid=jnp.ones_like(keys, dtype=bool),
        ts=jnp.arange(b, dtype=jnp.int32),
        rank=jnp.arange(b, dtype=jnp.int32),
        active=jnp.ones(b, bool) if active is None
        else jnp.asarray(active, bool))


def test_conflict_density_partitions_and_paths_agree():
    """Write-write contention lands in its owner partition; a
    partition of solo reads stays zero; the incidence-backed and the
    scatter-add (forwarding) paths compute the identical vector."""
    import jax.numpy as jnp
    from deneva_tpu.cc import build_incidence, conflict_density
    cfg = Config(part_cnt=2, conflict_buckets=256)
    # txns 0,1 write key 2 (partition 0) -> both contend; txns 2,3 read
    # distinct partition-1 keys nobody writes -> no conflict
    keys = [[2, 2], [2, 2], [3, 7], [5, 9]]
    w = [[True, True], [True, True], [False, False], [False, False]]
    batch = _batch(keys, w)
    owner = batch.keys % jnp.int32(2)
    d_scatter = np.asarray(conflict_density(cfg, batch, owner, None))
    inc = build_incidence(batch, cfg.conflict_buckets, exact=False)
    d_inc = np.asarray(conflict_density(cfg, batch, owner, inc))
    np.testing.assert_array_equal(d_scatter, d_inc)
    assert d_inc[0] >= 4 and d_inc[1] == 0
    # inactive txns contribute nothing
    b2 = _batch(keys, w, active=[True, False, True, True])
    d2 = np.asarray(conflict_density(cfg, b2, owner, None))
    assert d2[0] == 0 and d2[1] == 0


def test_density_ranks_order_like_abort_rates():
    """The acceptance cross-validation: sweep zipf skew on a
    write-heavy OCC engine — the exported conflict-density series must
    RANK the configs exactly as their measured abort counts do (the
    signal is a usable contention proxy, not just a counter)."""
    import jax
    from deneva_tpu.engine.step import Engine
    from deneva_tpu.workloads import get_workload

    dens, aborts = [], []
    for theta in (0.0, 0.6, 0.9):
        # conflict_buckets >= table size: the density signal is a
        # bucket-space over-approximation, and a bucket space smaller
        # than the keyspace saturates it with hash-collision mass
        # (uniform traffic reads as contended) — the same K-sizing rule
        # every sweep backend documents
        cfg = Config(cc_alg=CCAlg.OCC, epoch_batch=64,
                     conflict_buckets=2048, synth_table_size=1024,
                     max_txn_in_flight=128, req_per_query=4,
                     max_accesses=4, zipf_theta=theta, write_perc=0.9,
                     read_perc=0.1, part_cnt=4, metrics=True)
        eng = Engine(cfg, get_workload(cfg))
        st = eng.init_state()
        st = eng.jit_run(st, 12)
        dens.append(int(np.asarray(
            jax.device_get(st.stats["conflict_density"])).sum()))
        aborts.append(int(jax.device_get(
            st.stats["total_txn_abort_cnt"])))
    assert np.argsort(dens).tolist() == np.argsort(aborts).tolist(), \
        (dens, aborts)
    assert dens[0] < dens[2] and aborts[0] < aborts[2]


def test_density_counter_stays_zero_when_off():
    import jax
    from deneva_tpu.engine.step import Engine
    from deneva_tpu.workloads import get_workload
    cfg = Config(cc_alg=CCAlg.OCC, epoch_batch=64, conflict_buckets=256,
                 synth_table_size=1024, max_txn_in_flight=128,
                 req_per_query=4, max_accesses=4, zipf_theta=0.9,
                 part_cnt=4)
    eng = Engine(cfg, get_workload(cfg))
    st = eng.jit_run(eng.init_state(), 6)
    assert np.asarray(
        jax.device_get(st.stats["conflict_density"])).sum() == 0


# ---- critical-path ledger ----------------------------------------------

def _fake_clock(led):
    t = [100.0]
    led._time = lambda: t[0]
    led.reset()
    return t


def test_crit_ledger_stages_sum_to_wall(capsys):
    """The attribution contract: measured stages + the other bucket sum
    to the window wall EXACTLY (the 5% acceptance bound is measurement
    noise on a live run, not bookkeeping slack), and the gate is the
    argmax stage."""
    led = MB.CritLedger(0)
    t = _fake_clock(led)
    for _ in range(2):
        t[0] += 0.010; led.lap("admit")      # noqa: E702
        t[0] += 0.040; led.lap("wire")       # noqa: E702
        t[0] += 0.020; led.lap("device")     # noqa: E702
        t[0] += 0.005; led.lap("retire")     # noqa: E702
        t[0] += 0.002
        out = led.end_pass(8)
    t[0] += 1.0                              # cross the emit cadence
    out = led.end_pass(16)
    assert out is not None and out[0] == "other"   # the 1s idle gap
    line = capsys.readouterr().out
    from deneva_tpu.harness.parse import parse_metrics
    [row] = parse_metrics(line.splitlines())
    assert row["family"] == "crit" and row["gate"] == "other"
    stages = sum(row[s + "_ms"] for s in
                 ("admit", "wire", "device", "retire", "other"))
    assert abs(stages - row["wall_ms"]) <= 0.05 * row["wall_ms"]
    assert row["wire_ms"] == pytest.approx(80.0, abs=0.5)
    # quorum ledger competes for the gate without joining the wall sum
    t2 = _fake_clock(led)
    t2[0] += 0.010; led.lap("admit")         # noqa: E702
    led.quorum(5.0)
    t2[0] += 1.1
    gate, _ = led.end_pass(24)
    assert gate == "quorum"
    row2 = parse_metrics(capsys.readouterr().out.splitlines())[0]
    assert row2["quorum_ms"] == pytest.approx(5000.0)
    wall_sum = sum(row2[s + "_ms"] for s in
                   ("admit", "wire", "device", "retire", "other"))
    assert abs(wall_sum - row2["wall_ms"]) <= 0.05 * row2["wall_ms"]


# ---- watchdogs ---------------------------------------------------------

def _frame_rec(node, epoch, now_s, lag_s=0.0, role="server", **fields):
    rec = {"node": node, "role": role, "epoch": epoch,
           "frame_t_us": (now_s - lag_s) * 1e6}
    for name in MB.FRAME_FIELDS:
        rec.setdefault(name, 0.0)
    rec.update(fields)
    return rec


def test_straggler_watchdog_names_only_the_slow_node(tmp_path, capsys):
    agg = MB.Aggregator(_cfg(tmp_path), 0)
    now = 50.0
    for i in range(4):
        now += 0.1
        agg.feed(_frame_rec(0, i, now), now_s=now)
        agg.feed(_frame_rec(2, i, now, lag_s=0.002), now_s=now)
        agg.feed(_frame_rec(1, i, now, lag_s=1.5), now_s=now)
    agg.close()
    watches = [r for r in MS.read_metrics(agg.stream.path)
               if "kind" in r]
    assert watches and {w["kind"] for w in watches} == {"straggler"}
    assert {w["subject"] for w in watches} == {1}
    # the tagged line twin went to the log
    from deneva_tpu.harness.parse import parse_metrics
    rows = [r for r in parse_metrics(capsys.readouterr().out.splitlines())
            if r["family"] == "watch"]
    assert rows and all(r["subject"] == 1 for r in rows)
    # rate limit: many triggers, few events
    assert len(watches) < 4


def test_jit_recompile_watchdog(tmp_path):
    agg = MB.Aggregator(_cfg(tmp_path), 0)
    now = 10.0
    for i in range(6):
        now += 0.05
        agg.feed(_frame_rec(0, i, now, device_ms=4.0), now_s=now)
        agg.feed(_frame_rec(1, i, now, device_ms=4.0), now_s=now)
    now += 0.05
    agg.feed(_frame_rec(0, 9, now, device_ms=900.0), now_s=now)
    agg.close()
    watches = [r for r in MS.read_metrics(agg.stream.path)
               if r.get("kind") == "jit_recompile"]
    assert len(watches) == 1 and watches[0]["subject"] == 0
    assert watches[0]["device_ms"] == 900.0


def test_epoch_stall_watchdog(tmp_path):
    agg = MB.Aggregator(_cfg(tmp_path), 0)
    agg.feed(_frame_rec(0, 1, 5.0), now_s=5.0)
    agg.tick(6.0)                      # under the threshold: quiet
    agg.tick(5.0 + MB.WATCH_STALL_S + 1.0)
    agg.tick(5.0 + MB.WATCH_STALL_S + 2.0)   # latched: one event only
    agg.close()
    stalls = [r for r in MS.read_metrics(agg.stream.path)
              if r.get("kind") == "epoch_stall"]
    assert len(stalls) == 1 and stalls[0]["idle_s"] >= MB.WATCH_STALL_S
    # a fresh frame re-arms the watchdog
    agg2 = MB.Aggregator(_cfg(tmp_path), 0, append=True)
    agg2.feed(_frame_rec(0, 2, 20.0), now_s=20.0)
    assert not agg2._stalled
    agg2.close()


# ---- loopback ServerNode: metrics-off wire pin -------------------------

def test_metrics_off_wire_pin():
    """The house contract, executable: with metrics off a server builds
    NO bus sender and NO aggregator, writes no bus stream, and its blob
    broadcast is byte-identical to the pre-bus codec output — the bus
    is purely observational and its off state is the pre-bus runtime
    byte for byte (no METRICS rtype can ever reach the wire: nothing
    constructs a frame)."""
    node = _solo_server("mb_off_pin")
    try:
        assert node.mbus is None and node.magg is None
        blk = wire.QueryBlock(
            keys=np.arange(8, dtype=np.int32).reshape(4, 2),
            types=np.ones((4, 2), np.int8),
            scalars=np.zeros((4, 0), np.int32),
            tags=np.arange(4, dtype=np.int64))
        ts = np.arange(4, dtype=np.int64) + 100
        blob = wire.encode_epoch_blob(7, blk, ts)
        sent = []
        node.tp.sendv_many = \
            lambda dests, rt, parts: sent.append((list(dests), rt, parts))
        node.tp.send = lambda d, rt, pl=b"": sent.append(([d], rt, [pl]))
        node.n_srv = 2          # pretend a peer so the bcast emits
        node._bcast_views(7, blk, ts)
        (dests, rt, parts), = sent
        assert rt == "EPOCH_BLOB"
        assert b"".join(bytes(p) for p in parts) == blob
        assert not any(k.startswith("mb_") for k in node.stats.counters)
    finally:
        node.n_srv = 1
        node.close()


def test_metrics_off_group_outputs():
    """The group jit's output arity is exactly the pre-bus one with
    metrics off (3 state leaves + the packed planes) and grows the
    density plane only when armed — the d2h volume is part of the
    off-contract."""
    import jax
    import numpy as np
    node = _solo_server("mb_off_arity")
    try:
        C, b = node.C, node.b_merged
        W, S = node._width, node._n_scalars
        warm = jax.device_put((
            np.zeros(C * b, bool), np.zeros(C * b, np.int32),
            np.zeros(C * b * W, np.int32), np.zeros(C * b * W, np.int8),
            np.zeros(C * b * S, np.int32)))
        out = node.group_step(node.db, node.cc_state, node.dev_stats,
                              *warm)
        assert len(out) == 4
    finally:
        node.close()


# ---- loopback ServerNode: armed aggregation ----------------------------

def _mb_server(tag, tmp_path, **kw):
    base = dict(metrics=True, telemetry_dir=str(tmp_path),
                synth_table_size=1024)
    base.update(kw)
    return _solo_server(tag, **base)


def test_armed_server_aggregates_and_emits(tmp_path):
    node = _mb_server("mb_armed", tmp_path)
    try:
        assert node.mbus is not None and node.magg is not None
        # a peer's frame routed in lands in the bus stream verbatim
        fields = MB.pack_fields(dict(commit=7, abort=1))
        node._route(1, "METRICS", MB.encode_metrics_frame(
            1, MB.ROLE_SERVER, 12, MS.now_us(), fields,
            np.array([3, 4], np.int32)))
        # a local frame feeds the aggregator without touching the wire
        node._mb_emit(16, np.array([9], np.int32), 5, 1, 0, 0)
        assert node.mbus.frames_sent == 1
        node.magg.close()
        rows = MS.read_metrics(MB.bus_path(node.cfg, 0))
        assert [r["node"] for r in rows] == [1, 0]
        assert rows[0]["commit"] == 7 and rows[0]["density"] == [3, 4]
        assert rows[1]["epoch"] == 16 and rows[1]["density"] == [9]
        assert node.magg.frames_rx == 2
        # armed group jit returns the density plane
        import jax
        C, b = node.C, node.b_merged
        W, S = node._width, node._n_scalars
        warm = jax.device_put((
            np.zeros(C * b, bool), np.zeros(C * b, np.int32),
            np.zeros(C * b * W, np.int32), np.zeros(C * b * W, np.int8),
            np.zeros(C * b * S, np.int32)))
        out = node.group_step(node.db, node.cc_state, node.dev_stats,
                              *warm)
        assert len(out) == 5
        assert np.asarray(out[4]).shape == (C, 1)   # part_cnt=1 solo
    finally:
        node.close()


def test_aggregator_role_follows_lowest_live(tmp_path):
    """Elastic retirement hands the role down: a non-zero server
    becomes the target once every lower id is reassigned, and builds
    its aggregator lazily at the first routed frame."""
    node = _mb_server("mb_role", tmp_path)
    try:
        assert node._mb_agg() == 0
        node._elastic = True
        node._reassigned = {0}
        node.n_srv = 3
        node.me = 1
        assert node._mb_agg() == 1
        node.magg = None
        fields = MB.pack_fields(dict(commit=1))
        node._route(2, "METRICS", MB.encode_metrics_frame(
            2, MB.ROLE_SERVER, 3, MS.now_us(), fields,
            np.zeros(0, np.int32)))
        assert node.magg is not None and node.magg.frames_rx == 1
    finally:
        node.me = 0
        node.n_srv = 1
        node.close()


# ---- monitor tool ------------------------------------------------------

def test_monitor_render_and_prom(tmp_path):
    import importlib
    monitor = importlib.import_module("tools.monitor")
    path = os.path.join(str(tmp_path), "metrics_bus_node0.jsonl")
    ms = MS.MetricsStream(path, 0)
    for e in range(4):
        ms.emit(e, node=0, role="server", frame_t_us=e * 1_000_000,
                commit=100, abort=5, wall_ms=12.0, wire_ms=8.0,
                admit_ms=2.0, device_ms=1.0, retire_ms=0.5,
                other_ms=0.5, density=[4, 1])
        ms.emit(-1, node=3, role="client", frame_t_us=e * 1_000_000,
                commit=90, resend=2, backlog=10)
    ms.emit(7, node=0, kind="straggler", subject=1, lag_ms=1500.0)
    ms.close()
    rows = MS.read_metrics(path)
    table = monitor.render_table(rows)
    assert "straggler" in table and "wire" in table
    assert "client" in table and "4,1" in table
    prom = monitor.prom_dump(rows)
    assert 'deneva_conflict_density{node="0",part="0"} 4' in prom
    assert 'deneva_watch_events_total{kind="straggler"} 1' in prom
    assert "# TYPE deneva_commit_per_frame gauge" in prom
    # directory resolution finds the stream
    assert monitor.resolve_stream(str(tmp_path)) == path


# ---- config gating -----------------------------------------------------

def test_metrics_knobs_validate():
    with pytest.raises(ValueError, match="metrics_cadence"):
        Config().replace(metrics_cadence=0)
    cfg = Config().replace(metrics=True)       # defaults are live
    assert cfg.metrics_cadence == 1


def test_bus_sender_cadence_and_shed():
    snd = MB.BusSender(Config(metrics=True, metrics_cadence=4), 0,
                       MB.ROLE_SERVER)
    assert [e for e in range(8) if snd.due(e)] == [0, 4]
    snd.shed = 3
    _, rec = snd.frame(0, dict(commit=1))
    assert rec["shed"] == 3.0 and snd.shed == 0
    # quorum ledger: hold -> release feeds the crit ledger
    snd.hold(5, 100.0)
    snd.hold(6, 100.5)
    snd.release_through(5, 101.0)
    assert snd.crit.quorum_n == 1
    assert snd.crit.quorum_s == pytest.approx(1.0)
    assert 6 in snd._hold_t


# ---- end-to-end cluster (slow tier) ------------------------------------

@pytest.mark.slow
def test_cluster_bus_stream_and_crit_sums(tmp_path):
    """2 servers + 1 client with the bus armed: the aggregator's stream
    carries frames from every node kind with per-partition density, the
    critical-path decomposition in the frames sums to its wall within
    5%, and the off twin of the same config writes no bus stream."""
    from deneva_tpu.runtime.launch import run_cluster
    from deneva_tpu.stats import parse_summary

    cfg = Config(workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
                 node_cnt=2, client_node_cnt=1, epoch_batch=128,
                 conflict_buckets=512, synth_table_size=4096,
                 max_txn_in_flight=1024, req_per_query=4, max_accesses=4,
                 zipf_theta=0.9, warmup_secs=0.3, done_secs=2.0,
                 log_dir=str(tmp_path), metrics=True)
    out = run_cluster(cfg, platform="cpu", run_id="mbsm")
    srv = [parse_summary(out[s][1]) for s in range(2)]
    for s in srv:
        assert s["mb_frames_sent"] > 0
    assert srv[0]["mb_frames_rx"] > 0
    rows = MS.read_metrics(os.path.join(str(tmp_path), "mbsm",
                                        "metrics_bus_node0.jsonl"))
    frames = [r for r in rows if "kind" not in r and "commit" in r]
    assert {0, 1} <= {r["node"] for r in frames}
    assert any(r["role"] == "client" for r in frames)
    dens = [r for r in frames if r.get("density")]
    assert dens and all(len(r["density"]) == 2 for r in dens)
    crit = [r for r in frames
            if r.get("role") == "server" and r.get("wall_ms", 0) > 0]
    assert crit, "no frame carried a critical-path window"
    for r in crit:
        stages = sum(r[s + "_ms"] for s in
                     ("admit", "wire", "device", "retire", "other"))
        assert abs(stages - r["wall_ms"]) <= 0.05 * r["wall_ms"] + 0.1, r
    # off twin: no stream, no bus fields
    off = run_cluster(cfg.replace(metrics=False), platform="cpu",
                      run_id="mbsm_off")
    assert not os.path.exists(os.path.join(str(tmp_path), "mbsm_off",
                                           "metrics_bus_node0.jsonl"))
    assert "mb_frames_sent" not in parse_summary(off[0][1])
