"""Checkpoint/resume: a resumed run must continue the identical epoch
stream (bit-exact state), because the RNG key is part of the state."""

import numpy as np
import jax
import pytest

from deneva_tpu.config import Config, CCAlg, WorkloadKind
from deneva_tpu.engine import Engine
from deneva_tpu.engine.checkpoint import load_state, save_state
from deneva_tpu.workloads import get_workload


def _engine():
    cfg = Config(cc_alg=CCAlg.TPU_BATCH, workload=WorkloadKind.YCSB,
                 epoch_batch=64, conflict_buckets=256,
                 synth_table_size=1024, max_txn_in_flight=256,
                 req_per_query=4, max_accesses=4)
    return Engine(cfg, get_workload(cfg))


def _leaves(state):
    return [np.asarray(jax.device_get(v))
            for v in jax.tree_util.tree_leaves(state)]


@pytest.mark.slow
def test_resume_is_bit_exact(tmp_path):
    path = str(tmp_path / "ck.npz")
    eng = _engine()
    state = eng.init_state()
    for _ in range(10):
        state = eng.jit_step(state)
    save_state(path, state)
    # continue 10 more epochs uninterrupted
    for _ in range(10):
        state = eng.jit_step(state)
    final_a = _leaves(state)

    # fresh engine, resume from the checkpoint, same 10 epochs
    eng2 = _engine()
    state2 = load_state(path, eng2.init_state())
    for _ in range(10):
        state2 = eng2.jit_step(state2)
    final_b = _leaves(state2)

    assert len(final_a) == len(final_b)
    for a, b in zip(final_a, final_b):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert (a == b).all()


def test_load_rejects_config_mismatch(tmp_path):
    path = str(tmp_path / "ck.npz")
    eng = _engine()
    state = eng.init_state()
    save_state(path, state)
    bad_cfg = eng.cfg.replace(synth_table_size=2048)
    bad_eng = Engine(bad_cfg, get_workload(bad_cfg))
    with pytest.raises(ValueError, match="mismatch"):
        load_state(path, bad_eng.init_state())


@pytest.mark.slow
def test_driver_resume_round_trip(tmp_path):
    """run_simulation writes a final checkpoint; a resumed simulation
    starts from it (epoch counter advanced, commits accumulate)."""
    from deneva_tpu.engine.driver import run_simulation

    path = str(tmp_path / "drv.npz")
    cfg = Config(cc_alg=CCAlg.OCC, workload=WorkloadKind.YCSB,
                 epoch_batch=64, conflict_buckets=256,
                 synth_table_size=1024, max_txn_in_flight=256,
                 req_per_query=4, max_accesses=4,
                 warmup_secs=0.2, done_secs=0.5, checkpoint_path=path)
    run_simulation(cfg, chunk=10, quiet=True)
    eng = Engine(cfg, get_workload(cfg))
    saved = load_state(path, eng.init_state())
    first_epoch = int(jax.device_get(saved.epoch))
    assert first_epoch > 0
    st2 = run_simulation(cfg.replace(resume=True), chunk=10, quiet=True)
    saved2 = load_state(path, eng.init_state())
    assert int(jax.device_get(saved2.epoch)) > first_epoch
    assert st2.summary_fields()["total_txn_commit_cnt"] > 0
