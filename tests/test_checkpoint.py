"""Checkpoint/resume: a resumed run must continue the identical epoch
stream (bit-exact state), because the RNG key is part of the state."""

import numpy as np
import jax
import pytest

from deneva_tpu.config import Config, CCAlg, WorkloadKind
from deneva_tpu.engine import Engine
from deneva_tpu.engine.checkpoint import load_state, save_state
from deneva_tpu.workloads import get_workload


def _engine():
    cfg = Config(cc_alg=CCAlg.TPU_BATCH, workload=WorkloadKind.YCSB,
                 epoch_batch=64, conflict_buckets=256,
                 synth_table_size=1024, max_txn_in_flight=256,
                 req_per_query=4, max_accesses=4)
    return Engine(cfg, get_workload(cfg))


def _leaves(state):
    return [np.asarray(jax.device_get(v))
            for v in jax.tree_util.tree_leaves(state)]


@pytest.mark.slow
def test_resume_is_bit_exact(tmp_path):
    path = str(tmp_path / "ck.npz")
    eng = _engine()
    state = eng.init_state()
    for _ in range(10):
        state = eng.jit_step(state)
    save_state(path, state)
    # continue 10 more epochs uninterrupted
    for _ in range(10):
        state = eng.jit_step(state)
    final_a = _leaves(state)

    # fresh engine, resume from the checkpoint, same 10 epochs
    eng2 = _engine()
    state2 = load_state(path, eng2.init_state())
    for _ in range(10):
        state2 = eng2.jit_step(state2)
    final_b = _leaves(state2)

    assert len(final_a) == len(final_b)
    for a, b in zip(final_a, final_b):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert (a == b).all()


def test_stale_schema_version_fails_with_clear_message(tmp_path,
                                                       monkeypatch):
    """The module docstring's promise: a checkpoint from an older
    EngineState LAYOUT (lower SCHEMA_VERSION) must fail with the
    explicit bumped-version message — naming both versions and the
    remedy — not an opaque pytree/shape error (even when the layout
    difference would ALSO trip the leaf checks)."""
    import deneva_tpu.engine.checkpoint as cp

    path = str(tmp_path / "stale.npz")
    state = {"tab": {"F0": jax.numpy.arange(8)},
             "rng": jax.numpy.zeros(2, jax.numpy.uint32)}
    monkeypatch.setattr(cp, "SCHEMA_VERSION", cp.SCHEMA_VERSION - 1)
    cp.save_state(path, state)
    monkeypatch.undo()
    # template with a DIFFERENT layout too: the schema check must win
    template = {"tab": {"F0": jax.numpy.arange(8),
                        "F1": jax.numpy.arange(8)},
                "rng": jax.numpy.zeros(2, jax.numpy.uint32)}
    with pytest.raises(ValueError) as ei:
        cp.load_state(path, template)
    msg = str(ei.value)
    assert "incompatible checkpoint" in msg
    assert f"schema v{cp.SCHEMA_VERSION - 1}" in msg
    assert f"writes v{cp.SCHEMA_VERSION}" in msg
    assert "re-run from scratch" in msg


def test_preschema_checkpoint_reports_v0(tmp_path):
    """A checkpoint predating the schema stamp entirely (no __schema__
    key) reads as v0 and fails with the same clear message."""
    import deneva_tpu.engine.checkpoint as cp

    path = str(tmp_path / "v0.npz")
    state = {"a": jax.numpy.arange(4)}
    np.savez(path, leaf_0000=np.arange(4),
             __paths__=np.array(["['a']"]))
    with pytest.raises(ValueError, match="schema v0"):
        cp.load_state(path, state)


def test_load_rejects_config_mismatch(tmp_path):
    path = str(tmp_path / "ck.npz")
    eng = _engine()
    state = eng.init_state()
    save_state(path, state)
    bad_cfg = eng.cfg.replace(synth_table_size=2048)
    bad_eng = Engine(bad_cfg, get_workload(bad_cfg))
    with pytest.raises(ValueError, match="mismatch"):
        load_state(path, bad_eng.init_state())


@pytest.mark.slow
def test_driver_resume_round_trip(tmp_path):
    """run_simulation writes a final checkpoint; a resumed simulation
    starts from it (epoch counter advanced, commits accumulate)."""
    from deneva_tpu.engine.driver import run_simulation

    path = str(tmp_path / "drv.npz")
    cfg = Config(cc_alg=CCAlg.OCC, workload=WorkloadKind.YCSB,
                 epoch_batch=64, conflict_buckets=256,
                 synth_table_size=1024, max_txn_in_flight=256,
                 req_per_query=4, max_accesses=4,
                 warmup_secs=0.2, done_secs=0.5, checkpoint_path=path)
    run_simulation(cfg, chunk=10, quiet=True)
    eng = Engine(cfg, get_workload(cfg))
    saved = load_state(path, eng.init_state())
    first_epoch = int(jax.device_get(saved.epoch))
    assert first_epoch > 0
    st2 = run_simulation(cfg.replace(resume=True), chunk=10, quiet=True)
    saved2 = load_state(path, eng.init_state())
    assert int(jax.device_get(saved2.epoch)) > first_epoch
    assert st2.summary_fields()["total_txn_commit_cnt"] > 0
