"""Backoff-ledger unit tests (overload tier, client side of
ADMIT_NACK): retry-after hints honored as floors, jittered exponential
growth capped, and — through a transport-free ClientNode rig — the
interaction with the resend sweep: the inflight bitmap/throttle never
drifts and the NACK-then-late-CL_RSP race never double-counts."""

import numpy as np

from deneva_tpu.runtime import wire
from deneva_tpu.runtime.admission import encode_admit_nack
from deneva_tpu.runtime.client import TAG_RING, ClientNode
from deneva_tpu.runtime.loadgen import BackoffLedger
from deneva_tpu.stats import Stats

MS = 1_000     # us per ms


def _ledger(base_us=10 * MS, max_us=500 * MS, seed=7):
    return BackoffLedger(TAG_RING, base_us, max_us, seed)


def test_retry_after_is_a_floor():
    led = _ledger(base_us=10 * MS)
    tags = np.arange(4, dtype=np.int64)
    led.nack(0, tags, np.full(4, 300 * MS, np.uint32), now_us=0)
    # first attempt's exponential term is ~10ms +/- 50%: far under the
    # 300ms hint, so every ready time sits at/after the hint
    assert led.next_ready_us() >= 300 * MS
    assert led.pop_ready(299 * MS) == []
    out = led.pop_ready(2_000 * MS)
    assert sum(len(t) for _, t in out) == 4


def test_jittered_exponential_growth_and_cap():
    led = _ledger(base_us=10 * MS, max_us=200 * MS)
    tags = np.arange(64, dtype=np.int64)
    retry = np.zeros(64, np.uint32)
    prev_mean = 0.0
    for attempt in range(1, 7):
        led.attempts[tags] = attempt
        d = led.delay_us(tags, retry)
        exp = 10 * MS * 2 ** (attempt - 1)
        lo, hi = 0.5 * exp, 1.5 * exp
        assert (d >= min(lo, 200 * MS) - 1).all()
        assert (d <= 200 * MS).all()
        if exp * 1.5 < 200 * MS:
            assert (d <= hi + 1).all()
            m = float(d.mean())
            assert m > prev_mean, "growth must be exponential in attempts"
            prev_mean = m
    # deep attempts saturate at the cap exactly
    led.attempts[tags] = 30
    assert (led.delay_us(tags, retry) == 200 * MS).all()


def test_jitter_is_seeded_and_spreads():
    a = _ledger(seed=3)
    b = _ledger(seed=3)
    c = _ledger(seed=4)
    tags = np.arange(256, dtype=np.int64)
    r = np.zeros(256, np.uint32)
    a.attempts[tags] = 1
    b.attempts[tags] = 1
    c.attempts[tags] = 1
    da, db, dc = (led.delay_us(tags, r) for led in (a, b, c))
    assert (da == db).all(), "same seed must reproduce the schedule"
    assert (da != dc).any(), "different seed must re-jitter"
    assert len(np.unique(da)) > 10, "jitter must split the herd"


def test_pop_ready_and_reset():
    led = _ledger(base_us=10 * MS)
    led.nack(2, np.arange(8, dtype=np.int64), np.zeros(8, np.uint32),
             now_us=0)
    assert len(led) == 8
    out = led.pop_ready(1_000 * MS)
    assert len(led) == 0
    assert all(srv == 2 for srv, _ in out)
    got = np.sort(np.concatenate([t for _, t in out]))
    assert (got == np.arange(8)).all()
    # ack resets the attempt counter: next nack backs off like the first
    led.reset(np.arange(8, dtype=np.int64))
    assert (led.attempts[:8] == 0).all()


# ---- transport-free ClientNode rig --------------------------------------
# __new__ + hand-set attributes: _route / the sweeps touch only numpy
# state, the stats object and tp.sendv — everything a FakeTp can record.

class FakeTp:
    def __init__(self):
        self.sent = []

    def sendv(self, dest, rtype, parts):
        self.sent.append((dest, rtype, b"".join(bytes(p) for p in parts)))


def _mini_client(n_srv=2, fault_mode=False, chunk=64):
    c = ClientNode.__new__(ClientNode)
    c.cfg = None
    c.n_srv = n_srv
    c._fault_mode = fault_mode
    c._adm = True
    c._elastic = False
    c._geo = False
    c._active = np.ones(n_srv, bool)
    c._rr = 0
    c._unacked = np.zeros(TAG_RING, bool)
    c._nacked = np.zeros(TAG_RING, bool)
    c._ledger = BackoffLedger(TAG_RING, 10 * MS, 500 * MS, seed=11)
    c._tag_srv = None
    c.tel = None                  # flight recorder off (default-off rig)
    c._resend_q = __import__("collections").deque()
    c._resend_us = 100 * MS
    c._resend_cnt = 0
    c._dup_acks = 0
    c._nack_cnt = 0
    c._nack_resend_cnt = 0
    c._flash_end_us = None
    c.inflight = np.zeros(n_srv, np.int64)
    c.send_us = np.zeros(TAG_RING, np.int64)
    c.tag_type = np.zeros(TAG_RING, np.uint8)
    c.type_names = ["txn"]
    c.ring_tenants = None
    c._tenant_on = False
    c._fleet = None
    c._fleet_credits = None
    c.chunk = chunk
    c.ring = [wire.QueryBlock(
        keys=np.zeros((chunk, 2), np.int32),
        types=np.ones((chunk, 2), np.int8),
        scalars=np.zeros((chunk, 1), np.int32),
        tags=np.zeros(chunk, np.int64))]
    c.ring_types = [np.zeros(chunk, np.uint8)]
    c.ring_pos = 0
    c.stats = Stats()
    c.tp = FakeTp()
    return c


def _send(c, srv, tags):
    """Emulate the hot loop's bookkeeping for a sent batch."""
    c._unacked[tags % TAG_RING] = True
    c._nacked[tags % TAG_RING] = False
    c._ledger.reset(tags)
    c.inflight[srv] += len(tags)
    if c._fault_mode:
        n = len(tags)
        c._resend_q.append((0, srv, wire.QueryBlock(
            np.zeros((n, 2), np.int32), np.ones((n, 2), np.int8),
            np.zeros((n, 1), np.int32), tags)))


def test_nack_releases_credit_once_and_dup_nack_is_noop():
    c = _mini_client()
    lat = c.stats.arr("client_client_latency")
    tags = np.arange(10, dtype=np.int64)
    _send(c, 0, tags)
    assert c.inflight[0] == 10
    nack = encode_admit_nack(tags[:4], np.full(4, 50 * MS, np.uint32))
    c._route(0, "ADMIT_NACK", nack, lat)
    assert c.inflight[0] == 6 and c._nack_cnt == 4
    assert c._nacked[:4].all() and not c._nacked[4:10].any()
    # the SAME NACK again (duplicated message): zero further release
    c._route(0, "ADMIT_NACK", nack, lat)
    assert c.inflight[0] == 6 and c._nack_cnt == 4
    assert len(c._ledger) == 4


def test_nack_then_late_cl_rsp_counts_once_and_never_drifts():
    """The race: a duplicate of the query was NACKed while the original
    was admitted and committed.  The late CL_RSP must count the txn
    exactly once and must NOT release the inflight credit the NACK
    already released; the ledger entry dies at the next sweep."""
    c = _mini_client()
    lat = c.stats.arr("client_client_latency")
    tags = np.arange(8, dtype=np.int64)
    _send(c, 0, tags)
    c._route(0, "ADMIT_NACK",
             encode_admit_nack(tags[:3], np.full(3, 20 * MS, np.uint32)),
             lat)
    assert c.inflight[0] == 5
    # late CL_RSP for ALL 8 tags (the 3 NACKed ones raced an admission)
    c._route(0, "CL_RSP", wire.encode_cl_rsp(tags), lat)
    assert c.stats.counters["txn_cnt"] == 8          # counted once each
    assert c.inflight[0] == 0, "NACKed credit must not release twice"
    assert not c._nacked[:8].any() and not c._unacked[:8].any()
    # the ledger entry is stale now: the sweep filters it on unacked
    import time as _t
    c._backoff_sweep(now_us=_t.monotonic_ns() // 1000 + 10_000 * MS)
    assert c.tp.sent == [] and c._nack_resend_cnt == 0
    # and a duplicate CL_RSP is fully absorbed
    c._route(0, "CL_RSP", wire.encode_cl_rsp(tags), lat)
    assert c.stats.counters["txn_cnt"] == 8 and c.inflight[0] == 0


def test_backoff_resend_recharges_credit_and_rejoins_resend_queue():
    c = _mini_client(fault_mode=True)
    lat = c.stats.arr("client_client_latency")
    tags = np.arange(6, dtype=np.int64)
    _send(c, 1, tags)
    c._route(1, "ADMIT_NACK",
             encode_admit_nack(tags, np.full(6, 15 * MS, np.uint32)), lat)
    assert c.inflight[1] == 0 and len(c._resend_q) == 1
    # the fault resend sweep must SKIP nacked tags (the ledger owns them)
    import time as _t
    now = _t.monotonic_ns() // 1000
    c._resend_q[0] = (now - 10_000 * MS, 1, c._resend_q[0][2])
    c._resend_sweep()
    assert c.tp.sent == [] and c._resend_cnt == 0
    # past the backoff the ledger re-enters: credit recharged, fresh
    # rows under the same tags, and (fault mode) a new resend_q entry
    c._backoff_sweep(now_us=now + 10_000 * MS)
    assert c._nack_resend_cnt == 6 and c.inflight[1] == 6
    assert not c._nacked[:6].any() and c._unacked[:6].all()
    assert len(c.tp.sent) == 1
    dest, rtype, payload = c.tp.sent[0]
    assert (dest, rtype) == (1, "CL_QRY_BATCH")
    blk = wire.decode_qry_block(payload)
    assert (blk.tags == tags).all()
    assert len(c._resend_q) == 1       # stale entry gone, fresh one in
    assert (c._resend_q[0][2].tags == tags).all()
    # the ack then drains everything cleanly
    c._route(1, "CL_RSP", wire.encode_cl_rsp(tags), lat)
    assert c.inflight[1] == 0 and c.stats.counters["txn_cnt"] == 6


def test_stale_nack_after_ack_is_ignored():
    c = _mini_client()
    lat = c.stats.arr("client_client_latency")
    tags = np.arange(5, dtype=np.int64)
    _send(c, 0, tags)
    c._route(0, "CL_RSP", wire.encode_cl_rsp(tags), lat)
    assert c.inflight[0] == 0
    # a NACK landing after the ack (reordered duplicate): full no-op
    c._route(0, "ADMIT_NACK",
             encode_admit_nack(tags, np.full(5, 20 * MS, np.uint32)), lat)
    assert c.inflight[0] == 0 and c._nack_cnt == 0 and len(c._ledger) == 0
