"""Native transport tests (SURVEY §2.6): in-process multi-node mesh over
Unix-domain sockets — the reference's IPC single-box integration rig
(`transport/transport.cpp:132-133`, SURVEY §4.4)."""

import os
import threading
import time
import uuid

import numpy as np
import pytest

from deneva_tpu.runtime.native import (NativeTransport, decode_qrybatch,
                                       encode_qrybatch, ensure_built,
                                       ipc_endpoints)


@pytest.fixture(scope="module")
def lib():
    return ensure_built()


def _mesh(n):
    eps = ipc_endpoints(n, uuid.uuid4().hex[:8])
    nodes = [NativeTransport(i, eps, n) for i in range(n)]
    # dt_start blocks until the full mesh is up -> start concurrently
    threads = [threading.Thread(target=t.start) for t in nodes]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    return nodes


def test_build(lib):
    import os
    assert os.path.exists(lib)


def test_two_node_send_recv(lib):
    a, b = _mesh(2)
    try:
        a.send(1, "INIT_DONE", b"hello")
        got = b.recv(timeout_us=2_000_000)
        assert got == (0, "INIT_DONE", b"hello")
        b.send(0, "CL_RSP", b"resp")
        got = a.recv(timeout_us=2_000_000)
        assert got == (1, "CL_RSP", b"resp")
    finally:
        a.close()
        b.close()


def test_loopback_self_send(lib):
    (a,) = _mesh(1)
    try:
        a.send(0, "RDONE", b"x")
        assert a.recv(timeout_us=1_000_000) == (0, "RDONE", b"x")
    finally:
        a.close()


def test_sendv_scatter_gather(lib):
    """dt_sendv frames multi-part bodies identically to a dt_send of the
    concatenation — over the wire, over loopback, with empty segments
    and non-owning row-slice views."""
    a, b = _mesh(2)
    try:
        hdr = b"\x01\x02\x03"
        keys = np.arange(12, dtype=np.int32).reshape(3, 4)
        tail = np.array([7, -9], np.int64)
        a.sendv(1, "EPOCH_BLOB", [hdr, keys, b"", tail])
        a.flush()
        got = b.recv(timeout_us=5_000_000)
        assert got == (0, "EPOCH_BLOB",
                       hdr + keys.tobytes() + tail.tobytes())
        # loopback gathers through the same path (skips the wire)
        b.sendv(1, "CL_RSP", [b"ab", keys[1:]])
        assert b.recv(timeout_us=2_000_000) == (1, "CL_RSP",
                                                b"ab" + keys[1:].tobytes())
        # plain ndarray send frames zero-copy from the array's memory
        a.send(1, "LOG_MSG", keys)
        a.flush()
        assert b.recv(timeout_us=5_000_000) == (0, "LOG_MSG",
                                                keys.tobytes())
    finally:
        a.close()
        b.close()


def test_batching_many_small_messages(lib):
    a, b = _mesh(2)
    try:
        n = 500
        for i in range(n):
            a.send(1, "CL_RSP", i.to_bytes(4, "little"))
        seen = set()
        for _ in range(n):
            got = b.recv(timeout_us=5_000_000)
            assert got is not None and got[1] == "CL_RSP"
            seen.add(int.from_bytes(got[2], "little"))
        assert seen == set(range(n))
        st = a.stats()
        # batching must actually batch: far fewer socket writes than msgs
        assert st["msg_sent"] == n
        assert 0 < st["batches_sent"] < n / 2
    finally:
        a.close()
        b.close()


def test_large_message_grows_recv_buffer(lib):
    a, b = _mesh(2)
    try:
        big = np.arange(1 << 21, dtype=np.uint8).tobytes()  # 2 MiB > 1 MiB buf
        a.send(1, "EPOCH_BLOB", big)
        got = b.recv(timeout_us=10_000_000)
        assert got is not None
        assert got[1] == "EPOCH_BLOB" and got[2] == big
    finally:
        a.close()
        b.close()


def test_large_then_small_preserves_fifo(lib):
    # a too-large head must stay at the front while the receiver grows its
    # buffer: the blob is delivered BEFORE the small trailing message
    a, b = _mesh(2)
    try:
        big = bytes(3 << 20)  # 3 MiB > initial 1 MiB recv buffer
        a.send(1, "EPOCH_BLOB", big)
        a.send(1, "RDONE", b"tail")
        first = b.recv(timeout_us=10_000_000)
        second = b.recv(timeout_us=10_000_000)
        assert first is not None and first[1] == "EPOCH_BLOB"
        assert second is not None and second[1] == "RDONE"
    finally:
        a.close()
        b.close()


def test_three_node_full_mesh(lib):
    nodes = _mesh(3)
    try:
        for i, t in enumerate(nodes):
            for j in range(3):
                if j != i:
                    t.send(j, "INIT_DONE", bytes([i]))
        for i, t in enumerate(nodes):
            srcs = set()
            for _ in range(2):
                got = t.recv(timeout_us=5_000_000)
                assert got is not None
                srcs.add(got[0])
            assert srcs == {0, 1, 2} - {i}
    finally:
        for t in nodes:
            t.close()


def test_ping_and_delay_injection(lib):
    a, b = _mesh(2)
    try:
        rt0 = a.ping(1, rounds=20)
        assert rt0 > 0
        # NETWORK_DELAY_TEST analogue: 20ms injected send delay
        a.set_delay_us(20_000)
        rt1 = a.ping(1, rounds=3)
        assert rt1 > rt0 + 15_000  # µs
        a.set_delay_us(0)
    finally:
        a.close()
        b.close()


def test_qrybatch_codec_roundtrip(lib):
    rng = np.random.default_rng(0)
    n, w = 64, 8
    startts = rng.integers(0, 1 << 60, n, dtype=np.int64)
    keys = rng.integers(0, 1 << 30, (n, w), dtype=np.int32)
    types = rng.integers(0, 3, (n, w), dtype=np.int8)
    scalars = rng.integers(0, 100, (n, 2), dtype=np.int32)
    buf = encode_qrybatch(startts, keys, types, scalars)
    s2, k2, t2, sc2 = decode_qrybatch(buf)
    np.testing.assert_array_equal(s2, startts)
    np.testing.assert_array_equal(k2, keys)
    np.testing.assert_array_equal(t2, types)
    np.testing.assert_array_equal(sc2, scalars)


def test_qrybatch_over_wire(lib):
    a, b = _mesh(2)
    try:
        keys = np.arange(32, dtype=np.int32).reshape(4, 8)
        types = np.ones((4, 8), np.int8)
        startts = np.arange(4, dtype=np.int64)
        a.send(1, "CL_QRY_BATCH", np.frombuffer(
            encode_qrybatch(startts, keys, types), np.uint8))
        got = b.recv(timeout_us=5_000_000)
        assert got is not None and got[1] == "CL_QRY_BATCH"
        s2, k2, _, _ = decode_qrybatch(got[2])
        np.testing.assert_array_equal(k2, keys)
        np.testing.assert_array_equal(s2, startts)
    finally:
        a.close()
        b.close()


def test_stats_counters(lib):
    a, b = _mesh(2)
    try:
        a.send(1, "INIT_DONE", b"abc")
        b.recv(timeout_us=2_000_000)
        # sender-side counters are bumped by the IO thread after the socket
        # write; the receiver can see the message first — poll briefly
        for _ in range(200):
            sa, sb = a.stats(), b.stats()
            if sa["bytes_sent"] >= 15:
                break
            time.sleep(0.005)
        assert sa["msg_sent"] >= 1 and sa["bytes_sent"] >= 15
        assert sb["msg_rcvd"] >= 1 and sb["bytes_rcvd"] >= 15
    finally:
        a.close()
        b.close()


@pytest.mark.slow
@pytest.mark.parametrize("target", ["tsan", "asan"])
def test_sanitizer_stress(target):
    """SURVEY §5.2: race/memory sanitizer gates for the native runtime
    (the reference's DEBUG_RACE flag is dead and its ASan line commented
    out; these are the modern equivalent). Builds and runs the stress
    binary; the sanitizer makes any data race or leak a nonzero exit."""
    import subprocess
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(["make", "-C", os.path.join(root, "native"),
                           target], capture_output=True, text=True,
                          timeout=600)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert "stress ok" in proc.stdout


def test_vote_wire_roundtrip():
    """VOTE codec (batched 2PC prepare): two packed bitsets — plus MAAT's
    optional per-txn position bounds (the RACK_PREP `[lower,upper)` range
    payload analogue, transport/message.cpp:1057-1137) — survive the
    encode/decode round trip at non-multiple-of-8 sizes."""
    from deneva_tpu.runtime import wire

    rng = np.random.default_rng(3)
    for n in (1, 7, 64, 1000):
        commit = rng.random(n) < 0.5
        abort = ~commit & (rng.random(n) < 0.3)
        epoch, c, a, bnd = wire.decode_vote(
            wire.encode_vote(117, commit, abort))
        assert epoch == 117 and len(c) == n
        assert (c == commit).all() and (a == abort).all()
        assert bnd is None
        bounds = rng.integers(0, 1 << 20, n).astype(np.int32)
        epoch, c, a, bnd = wire.decode_vote(
            wire.encode_vote(118, commit, abort, bounds))
        assert epoch == 118 and (c == commit).all() and (a == abort).all()
        assert bnd is not None and (bnd == bounds).all()


def test_sharded_io_threads_full_mesh(lib):
    """Round-5 IO-thread axes (reference SEND_THREAD_CNT/REM_THREAD_CNT):
    a 3-node mesh with 2 sender + 2 receiver shards per node must
    preserve per-(src, dst) FIFO and deliver every frame, including
    under flush and a burst that spans both sender shards."""
    eps = ipc_endpoints(3, uuid.uuid4().hex[:8])
    nodes = [NativeTransport(i, eps, 3, send_threads=2, recv_threads=2)
             for i in range(3)]
    threads = [threading.Thread(target=t.start) for t in nodes]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    try:
        n_msgs = 200
        for src in (0, 1, 2):
            for dst in (0, 1, 2):
                if src == dst:
                    continue
                for k in range(n_msgs):
                    nodes[src].send(dst, "EPOCH_BLOB",
                                    f"{src}->{dst}#{k}".encode())
            nodes[src].flush()
        for dst in (0, 1, 2):
            seen = {src: 0 for src in (0, 1, 2) if src != dst}
            for _ in range(n_msgs * 2):
                got = nodes[dst].recv(timeout_us=2_000_000)
                assert got is not None, f"node {dst} starved at {seen}"
                src, rtype, payload = got
                assert rtype == "EPOCH_BLOB"
                want = f"{src}->{dst}#{seen[src]}".encode()
                assert payload == want, (payload, want)  # per-link FIFO
                seen[src] += 1
            assert all(v == n_msgs for v in seen.values())
    finally:
        for t in nodes:
            t.close()
