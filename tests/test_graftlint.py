"""graftlint self-tests (PR 6; v2 families PR 9).

Fixture trees under tests/graftlint_fixtures/ carry one seeded violation
per `EXPECT[rule]` marker; each rule must fire exactly at its marker
lines and nowhere else, stay silent on the clean tree, and the real repo
tree must be lint-clean.  The runtime half (ownercheck.install guards)
is unit-tested at the bottom; the CFG core has its own tests in
test_graftlint_cfg.py.
"""

import os
import re
import subprocess
import sys
import threading
from collections import Counter, deque

from tools.graftlint import gateconsistency, wireproto
from tools.graftlint.core import FAMILIES, Tree, run_checkers
from tools.graftlint.wiremodel import RtypeSpec, WIRE_MODEL

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(REPO, "tests", "graftlint_fixtures")

_EXPECT = re.compile(r"EXPECT\[([a-z-]+)\]")


def _expected(root):
    """Multiset of (rel path, line, rule) from EXPECT[...] markers."""
    out = Counter()
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            with open(path) as f:
                for i, ln in enumerate(f, 1):
                    for rule in _EXPECT.findall(ln):
                        out[(rel, i, rule)] += 1
    return out


def _got(findings):
    return Counter((f.path, f.line, f.rule) for f in findings)


# ---- each rule fires exactly at its seeded marker ----------------------

def test_bad_fixture_rules_fire_exactly():
    """trace / det / own / imports / life / jit: the bad tree produces
    exactly the marked findings (right rule, right file, right line —
    no extras)."""
    root = os.path.join(FIX, "bad")
    tree = Tree(root, ["."])
    findings = run_checkers(tree, {"trace", "det", "own", "imports",
                                   "life", "jit"})
    assert _got(findings) == _expected(root), \
        "\n".join(f.render() for f in findings)


# the wire fixture is checked against its own miniature model (the real
# WIRE_MODEL describes the real runtime, not the fixture registry)
_MINI = {s.name: s for s in (
    RtypeSpec("PING", False),
    RtypeSpec("DATA", True, ("encode_data",),
              ("decode_data", "decode_data_gone"), ("handler",)),
    RtypeSpec("GHOST", False),
)}


def test_wire_fixture_rules_fire_exactly():
    root = os.path.join(FIX, "wire_bad")
    tree = Tree(root, ["."])
    findings = tree.filter(wireproto.check(
        tree, model=_MINI,
        codec_modules=("deneva_tpu/runtime/codec_fx.py",),
        route_funcs={"handler": ("deneva_tpu/runtime/codec_fx.py",
                                 "route")}))
    assert _got(findings) == _expected(root), \
        "\n".join(f.render() for f in findings)


def test_clean_fixture_is_silent():
    root = os.path.join(FIX, "clean")
    tree = Tree(root, ["."])
    findings = run_checkers(tree, set(FAMILIES))
    assert findings == [], "\n".join(f.render() for f in findings)


def test_repo_tree_is_lint_clean():
    """The acceptance gate: the real tree ends the PR clean under ALL
    families — v2 included — with zero suppressions (every true finding
    fixed)."""
    tree = Tree(REPO, ["deneva_tpu", "tools"])
    findings = run_checkers(tree, set(FAMILIES))
    assert findings == [], "\n".join(f.render() for f in findings)


# ---- gate-consistency fixture (its own registry, like the wire one) ----

def _gate_specs():
    from deneva_tpu.runtime.gates import GateSpec
    return {s.name: s for s in (
        GateSpec("fx", flags=("fx_flag",), guards=("fx_flag", "_fx"),
                 home=("deneva_tpu/runtime/fxsub.py",),
                 use_attrs=("fxo",)),
        # drift seeds: one flag that is not a Config field, one whose
        # default is ON
        GateSpec("fxbad", flags=("bad_flag", "missing_flag")),
    )}


_GFX_MODEL = {s.name: s for s in (
    RtypeSpec("FXMSG", False, gate="fx"),
    RtypeSpec("FXBAD", True, gate="fx"),     # gated AND fault-eligible
)}


def test_gate_fixture_rules_fire_exactly():
    root = os.path.join(FIX, "gate_bad")
    tree = Tree(root, ["."])
    findings = tree.filter(gateconsistency.check(
        tree, gates=_gate_specs(), exempt=(),
        escrow_funcs=("fx_gate",), escrow_home=(),
        config_module="deneva_tpu/config.py",
        guarded=("pending",), model=_GFX_MODEL))
    assert _got(findings) == _expected(root), \
        "\n".join(f.render() for f in findings)


def test_repair_gate_fires_on_unguarded_use():
    """The REAL ``repair`` GateSpec (runtime/gates.py, not a fixture
    registry) catches an unguarded call into engine/repair.py and
    accepts the two guarded idioms the runtime uses (``cfg.repair`` at
    the engine/server call sites, the server's cached ``self._repair``)
    — the CI teeth behind the default-off bit-identity contract."""
    from deneva_tpu.runtime.gates import GATES

    root = os.path.join(FIX, "gate_bad_repair")
    tree = Tree(root, ["."])
    findings = tree.filter(gateconsistency.check(
        tree, gates={"repair": GATES["repair"]}, exempt=(),
        escrow_funcs=(), escrow_home=(),
        config_module="deneva_tpu/config.py", guarded=(), model={}))
    assert _got(findings) == _expected(root), \
        "\n".join(f.render() for f in findings)


def test_fencing_gate_fires_on_unguarded_use():
    """The REAL ``fencing`` GateSpec (runtime/gates.py) catches an
    unguarded call into runtime/faildet.py and accepts the guarded
    idioms the runtime uses (``cfg.fencing`` at construction, the
    cached ``self._fencing``, the detector's ``is not None`` check) —
    the CI teeth behind the fencing default-off bit-identity
    contract."""
    from deneva_tpu.runtime.gates import GATES

    root = os.path.join(FIX, "gate_bad_fencing")
    tree = Tree(root, ["."])
    findings = tree.filter(gateconsistency.check(
        tree, gates={"fencing": GATES["fencing"]}, exempt=(),
        escrow_funcs=(), escrow_home=(),
        config_module="deneva_tpu/config.py", guarded=(), model={}))
    assert _got(findings) == _expected(root), \
        "\n".join(f.render() for f in findings)


def test_telemetry_gate_fires_on_unguarded_use():
    """The REAL ``telemetry`` GateSpec (runtime/gates.py) catches an
    unguarded call into runtime/telemetry.py and accepts the guarded
    idioms the runtime uses (``cfg.telemetry`` at construction, the
    recorder handle's ``is not None`` check) — the CI teeth behind the
    flight recorder's default-off bit-identity contract."""
    from deneva_tpu.runtime.gates import GATES

    root = os.path.join(FIX, "gate_bad_telemetry")
    tree = Tree(root, ["."])
    findings = tree.filter(gateconsistency.check(
        tree, gates={"telemetry": GATES["telemetry"]}, exempt=(),
        escrow_funcs=(), escrow_home=(),
        config_module="deneva_tpu/config.py", guarded=(), model={}))
    assert _got(findings) == _expected(root), \
        "\n".join(f.render() for f in findings)


def test_metrics_gate_fires_on_unguarded_use():
    """The REAL ``metrics`` GateSpec (runtime/gates.py) catches an
    unguarded call into runtime/metricsbus.py and accepts the guarded
    idioms the runtime uses (``cfg.metrics`` at construction, the
    sender/aggregator handles' ``is not None`` checks, and the
    ``rtype == "METRICS"`` route branch — a gated rtype only exists
    once the subsystem armed it) — the CI teeth behind the metrics
    bus's default-off bit-identity contract."""
    from deneva_tpu.runtime.gates import GATES

    root = os.path.join(FIX, "gate_bad_metrics")
    tree = Tree(root, ["."])
    findings = tree.filter(gateconsistency.check(
        tree, gates={"metrics": GATES["metrics"]}, exempt=(),
        escrow_funcs=(), escrow_home=(),
        config_module="deneva_tpu/config.py", guarded=(),
        model={"METRICS": WIRE_MODEL["METRICS"]}))
    assert _got(findings) == _expected(root), \
        "\n".join(f.render() for f in findings)


def test_audit_gate_fires_on_unguarded_use():
    """The REAL ``audit`` GateSpec (runtime/gates.py) catches an
    unguarded call into runtime/audit.py AND an unguarded call to the
    declared device-derivation use_calls (cc/base's audit_observe
    family), while accepting the guarded idioms the runtime uses
    (``cfg.audit`` at construction, the exporter handle's ``is not
    None`` check, ``cfg.audit_mutate`` around the seeded fault) — the
    CI teeth behind the audit plane's default-off bit-identity
    contract."""
    from deneva_tpu.runtime.gates import GATES

    root = os.path.join(FIX, "gate_bad_audit")
    tree = Tree(root, ["."])
    findings = tree.filter(gateconsistency.check(
        tree, gates={"audit": GATES["audit"]}, exempt=(),
        escrow_funcs=(), escrow_home=(),
        config_module="deneva_tpu/config.py", guarded=(), model={}))
    assert _got(findings) == _expected(root), \
        "\n".join(f.render() for f in findings)


def test_ctrl_gate_fires_on_unguarded_use():
    """The REAL ``ctrl`` GateSpec (runtime/gates.py) catches an
    unguarded call into either ctrl home module (runtime/controller.py,
    cc/router.py) and an unguarded deep use of the controller handle,
    while accepting the guarded idioms the runtime uses (``cfg.ctrl``
    at construction, ``self.ctl is not None``, the engine's ``knobs is
    not None`` routing test, ``cfg.zipf_shift`` around the client's
    staged ring) — the CI teeth behind the control plane's default-off
    bit-identity contract."""
    from deneva_tpu.runtime.gates import GATES

    root = os.path.join(FIX, "gate_bad_ctrl")
    tree = Tree(root, ["."])
    findings = tree.filter(gateconsistency.check(
        tree, gates={"ctrl": GATES["ctrl"]}, exempt=(),
        escrow_funcs=(), escrow_home=(),
        config_module="deneva_tpu/config.py", guarded=(), model={}))
    assert _got(findings) == _expected(root), \
        "\n".join(f.render() for f in findings)


def test_dgcc_gate_fires_on_unguarded_use():
    """The REAL ``dgcc`` GateSpec (runtime/gates.py) catches an
    unguarded call into the wavefront home module (cc/dgcc.py) and an
    unguarded wave-assignment use_call, while accepting the guarded
    idioms the runtime uses (``cfg.ctrl_dgcc`` dominating the call, a
    local alias of the flag) — the CI teeth behind the fourth router
    class's default-off bit-identity contract (CC_ALG=DGCC itself is
    registry dispatch, not a gate bypass)."""
    from deneva_tpu.runtime.gates import GATES

    root = os.path.join(FIX, "gate_bad_dgcc")
    tree = Tree(root, ["."])
    findings = tree.filter(gateconsistency.check(
        tree, gates={"dgcc": GATES["dgcc"]}, exempt=(),
        escrow_funcs=(), escrow_home=(),
        config_module="deneva_tpu/config.py", guarded=(), model={}))
    assert _got(findings) == _expected(root), \
        "\n".join(f.render() for f in findings)


def test_device_pin_gate_fires_on_silent_pin():
    """gate-device-pin: conjoining the REAL ``audit`` gate's guard with
    a ``device_parts`` comparison fires — the silent single-device pin
    that drops a subsystem on the mesh-sharded measured path — while
    the legal shapes stay silent (a bare device_parts route branch, a
    non-gate workload-layout conjunction) and config.py itself is
    exempt (validate() is the sanctioned home for multi-chip pins)."""
    from deneva_tpu.runtime.gates import GATES

    root = os.path.join(FIX, "gate_bad_devpin")
    tree = Tree(root, ["."])
    findings = tree.filter(gateconsistency.check(
        tree, gates={"audit": GATES["audit"]}, exempt=(),
        escrow_funcs=(), escrow_home=(),
        config_module="deneva_tpu/config.py", guarded=(), model={}))
    assert _got(findings) == _expected(root), \
        "\n".join(f.render() for f in findings)


def test_gate_registry_matches_config():
    """Executable half of gate-registry-drift: every registered flag is
    a real Config field defaulting OFF, every wiremodel gate names a
    registered subsystem, and every gated rtype sits outside the fault
    mask (the lint checks the ASTs; this pins the live objects)."""
    import dataclasses

    from deneva_tpu.config import Config
    from deneva_tpu.runtime.gates import GATES

    fields = {f.name: f for f in dataclasses.fields(Config)}
    for name, spec in GATES.items():
        for flag in spec.flags:
            assert flag in fields, (name, flag)
            assert not fields[flag].default, (name, flag)
        assert spec.all_guards(), name
        for req in spec.requires:
            assert req in GATES, (name, req)
    for s in WIRE_MODEL.values():
        if s.gate:
            assert s.gate in GATES, s.name
            assert not s.fault_mask, \
                f"gated rtype {s.name} must stay outside FAULT_RTYPE_MASK"


# ---- CLI exit codes (the smoke-gate contract) --------------------------

def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        cwd=REPO, capture_output=True, text=True).returncode


def test_cli_exit_codes():
    assert _cli(f"--root={os.path.join(FIX, 'bad')}", ".") == 1
    assert _cli(f"--root={os.path.join(FIX, 'wire_bad')}", ".") == 1
    assert _cli(f"--root={os.path.join(FIX, 'clean')}", ".") == 0
    assert _cli("deneva_tpu/") == 0
    # the gate fails CLOSED on a typo'd path (never "clean, 0 files")
    assert _cli("deneva_tpuu/") == 2


def test_changed_mode(tmp_path):
    """--changed lints exactly the git-diff-scoped subset: clean exit
    when nothing changed, findings when a changed file carries one, and
    exit 2 on a bad ref (never a silent pass)."""
    def git(*a):
        subprocess.run(["git", "-c", "user.email=ci@fx",
                        "-c", "user.name=ci", *a],
                       cwd=tmp_path, capture_output=True, check=True)

    def cli(*args):
        return subprocess.run(
            [sys.executable, "-m", "tools.graftlint",
             f"--root={tmp_path}", *args],
            cwd=REPO, capture_output=True, text=True)

    git("init", "-q")
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    clean_src = "import json\n\n\ndef f():\n    return json.dumps({})\n"
    (pkg / "mod.py").write_text(clean_src)
    git("add", "-A")
    git("commit", "-qm", "seed")
    r = cli("--changed", "pkg")
    assert r.returncode == 0 and "no python files changed" in r.stderr
    (pkg / "mod.py").write_text("import os\n" + clean_src)
    r = cli("--changed", "pkg")
    assert r.returncode == 1 and "imp-unused" in r.stdout
    r = cli("--changed=not-a-ref", "pkg")
    assert r.returncode == 2


def test_zero_suppressions_in_repo():
    """The acceptance statement: the tree is clean with ZERO
    suppression markers — nothing is waved through."""
    for top in ("deneva_tpu", "tools"):
        for dirpath, dirnames, files in os.walk(os.path.join(REPO, top)):
            # the linter package's own docs DEFINE the marker syntax
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "graftlint")]
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                with open(os.path.join(dirpath, fn)) as f:
                    src = f.read()
                assert "graftlint: ignore" not in src \
                    and "graftlint: skip-file" not in src, \
                    os.path.join(dirpath, fn)


# ---- suppression syntax ------------------------------------------------

_SUPPRESSED = """import jax


@jax.jit
def f(x):
    # device-side decision is deliberate here (fixture reason)
    if x > 0:  # graftlint: ignore[trace-branch]
        x = x + 1
    return x
"""


def test_suppression_marker(tmp_path):
    d = tmp_path / "pkg"
    d.mkdir()
    (d / "sup_fx.py").write_text(_SUPPRESSED)
    tree = Tree(str(tmp_path), ["."])
    assert run_checkers(tree, {"trace"}) == []
    # control: without the marker the same code fires
    (d / "sup_fx.py").write_text(_SUPPRESSED.replace(
        "  # graftlint: ignore[trace-branch]", ""))
    tree = Tree(str(tmp_path), ["."])
    assert [f.rule for f in run_checkers(tree, {"trace"})] \
        == ["trace-branch"]


def test_skip_file_marker(tmp_path):
    d = tmp_path / "pkg"
    d.mkdir()
    (d / "skip_fx.py").write_text(
        "# graftlint: skip-file (generated fixture)\n"
        + _SUPPRESSED.replace("  # graftlint: ignore[trace-branch]", ""))
    tree = Tree(str(tmp_path), ["."])
    assert run_checkers(tree, {"trace"}) == []


# ---- runtime half: ownercheck.install guards ---------------------------

class _Srv:
    pass


def _guarded_server():
    from deneva_tpu.runtime import ownercheck

    s = _Srv()
    s.me = 0
    s.pending = deque([("c", "blk")])
    s._in_system = {11}
    s.repl_acked = {3: -1}
    s._feed_free = [{}]
    n = ownercheck.install(s)
    assert n == 4        # exactly the wrappable GUARDED attrs present
    return ownercheck, s


def test_ownercheck_owner_thread_mutates_freely():
    _oc, s = _guarded_server()
    s.pending.append(("c", "blk2"))
    s._in_system.add(12)
    s.repl_acked[3] = 5
    s._feed_free.pop()
    assert len(s.pending) == 2 and s.repl_acked[3] == 5


def test_ownercheck_cross_thread_mutation_raises():
    oc, s = _guarded_server()
    def _ior():
        buf = s._in_system           # aliased in-place mutation: the
        buf |= {97, 98}              # case only the runtime half sees

    ops = [lambda: s.pending.append(("x", "y")),
           lambda: s._in_system.discard(11),
           lambda: s.repl_acked.update({3: 9}),
           lambda: s.repl_acked.__setitem__(3, 9),
           lambda: s._feed_free.pop(),
           _ior]
    caught = []

    def hostile():
        for op in ops:
            try:
                op()
            except oc.OwnershipViolation as e:
                caught.append(str(e))

    t = threading.Thread(target=hostile, name="wire-worker-fx")
    t.start()
    t.join()
    assert len(caught) == len(ops)
    assert "wire-worker-fx" in caught[0]
    # the guard rejects BEFORE mutating: state is untouched
    assert len(s.pending) == 1 and s.repl_acked[3] == -1
    assert s._in_system == {11} and len(s._feed_free) == 1


def test_ownercheck_cross_thread_reads_are_free():
    _oc, s = _guarded_server()
    got = []

    def reader():
        got.append((len(s.pending), 3 in s.repl_acked,
                    sorted(s._in_system), list(s.pending)))

    t = threading.Thread(target=reader)
    t.start()
    t.join()
    assert got == [(1, True, [11], [("c", "blk")])]


def test_ownercheck_preserves_deque_maxlen():
    from deneva_tpu.runtime import ownercheck

    s = _Srv()
    s.me = 1
    s._committed_recent = deque([1, 2], maxlen=2)
    assert ownercheck.install(s) == 1
    s._committed_recent.append(3)
    assert list(s._committed_recent) == [2, 3]
    assert s._committed_recent.maxlen == 2


def test_ownercheck_owner_map_covers_guarded():
    """Every GUARDED attr must have a declared owner (the static checker
    enforces the server side; this pins the declarations file itself)."""
    from deneva_tpu.runtime import ownercheck as oc

    assert set(oc.GUARDED) <= set(oc.OWNER)
    assert all(oc.OWNER[a] == oc.DISPATCH for a in oc.GUARDED)
    for role in oc.WORKER_ENTRY:
        assert role in (oc.WIRE, oc.RETIRE, oc.CODEC)
