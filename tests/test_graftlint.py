"""graftlint self-tests (PR 6).

Fixture trees under tests/graftlint_fixtures/ carry one seeded violation
per `EXPECT[rule]` marker; each rule must fire exactly at its marker
lines and nowhere else, stay silent on the clean tree, and the real repo
tree must be lint-clean.  The runtime half (ownercheck.install guards)
is unit-tested at the bottom.
"""

import os
import re
import subprocess
import sys
import threading
from collections import Counter, deque

from tools.graftlint import wireproto
from tools.graftlint.core import Tree, run_checkers
from tools.graftlint.wiremodel import RtypeSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(REPO, "tests", "graftlint_fixtures")

_EXPECT = re.compile(r"EXPECT\[([a-z-]+)\]")


def _expected(root):
    """Multiset of (rel path, line, rule) from EXPECT[...] markers."""
    out = Counter()
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            with open(path) as f:
                for i, ln in enumerate(f, 1):
                    for rule in _EXPECT.findall(ln):
                        out[(rel, i, rule)] += 1
    return out


def _got(findings):
    return Counter((f.path, f.line, f.rule) for f in findings)


# ---- each rule fires exactly at its seeded marker ----------------------

def test_bad_fixture_rules_fire_exactly():
    """trace / det / own / imports: the bad tree produces exactly the
    marked findings (right rule, right file, right line — no extras)."""
    root = os.path.join(FIX, "bad")
    tree = Tree(root, ["."])
    findings = run_checkers(tree, {"trace", "det", "own", "imports"})
    assert _got(findings) == _expected(root), \
        "\n".join(f.render() for f in findings)


# the wire fixture is checked against its own miniature model (the real
# WIRE_MODEL describes the real runtime, not the fixture registry)
_MINI = {s.name: s for s in (
    RtypeSpec("PING", False),
    RtypeSpec("DATA", True, ("encode_data",),
              ("decode_data", "decode_data_gone"), ("handler",)),
    RtypeSpec("GHOST", False),
)}


def test_wire_fixture_rules_fire_exactly():
    root = os.path.join(FIX, "wire_bad")
    tree = Tree(root, ["."])
    findings = tree.filter(wireproto.check(
        tree, model=_MINI,
        codec_modules=("deneva_tpu/runtime/codec_fx.py",),
        route_funcs={"handler": ("deneva_tpu/runtime/codec_fx.py",
                                 "route")}))
    assert _got(findings) == _expected(root), \
        "\n".join(f.render() for f in findings)


def test_clean_fixture_is_silent():
    root = os.path.join(FIX, "clean")
    tree = Tree(root, ["."])
    findings = run_checkers(tree, {"trace", "det", "wire", "own",
                                   "imports"})
    assert findings == [], "\n".join(f.render() for f in findings)


def test_repo_tree_is_lint_clean():
    """The acceptance gate: the real tree ends the PR clean (every true
    finding fixed or explicitly suppressed with a reason)."""
    tree = Tree(REPO, ["deneva_tpu", "tools"])
    findings = run_checkers(tree, {"trace", "det", "wire", "own",
                                   "imports"})
    assert findings == [], "\n".join(f.render() for f in findings)


# ---- CLI exit codes (the smoke-gate contract) --------------------------

def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        cwd=REPO, capture_output=True, text=True).returncode


def test_cli_exit_codes():
    assert _cli(f"--root={os.path.join(FIX, 'bad')}", ".") == 1
    assert _cli(f"--root={os.path.join(FIX, 'wire_bad')}", ".") == 1
    assert _cli(f"--root={os.path.join(FIX, 'clean')}", ".") == 0
    assert _cli("deneva_tpu/") == 0
    # the gate fails CLOSED on a typo'd path (never "clean, 0 files")
    assert _cli("deneva_tpuu/") == 2


# ---- suppression syntax ------------------------------------------------

_SUPPRESSED = """import jax


@jax.jit
def f(x):
    # device-side decision is deliberate here (fixture reason)
    if x > 0:  # graftlint: ignore[trace-branch]
        x = x + 1
    return x
"""


def test_suppression_marker(tmp_path):
    d = tmp_path / "pkg"
    d.mkdir()
    (d / "sup_fx.py").write_text(_SUPPRESSED)
    tree = Tree(str(tmp_path), ["."])
    assert run_checkers(tree, {"trace"}) == []
    # control: without the marker the same code fires
    (d / "sup_fx.py").write_text(_SUPPRESSED.replace(
        "  # graftlint: ignore[trace-branch]", ""))
    tree = Tree(str(tmp_path), ["."])
    assert [f.rule for f in run_checkers(tree, {"trace"})] \
        == ["trace-branch"]


def test_skip_file_marker(tmp_path):
    d = tmp_path / "pkg"
    d.mkdir()
    (d / "skip_fx.py").write_text(
        "# graftlint: skip-file (generated fixture)\n"
        + _SUPPRESSED.replace("  # graftlint: ignore[trace-branch]", ""))
    tree = Tree(str(tmp_path), ["."])
    assert run_checkers(tree, {"trace"}) == []


# ---- runtime half: ownercheck.install guards ---------------------------

class _Srv:
    pass


def _guarded_server():
    from deneva_tpu.runtime import ownercheck

    s = _Srv()
    s.me = 0
    s.pending = deque([("c", "blk")])
    s._in_system = {11}
    s.repl_acked = {3: -1}
    s._feed_free = [{}]
    n = ownercheck.install(s)
    assert n == 4        # exactly the wrappable GUARDED attrs present
    return ownercheck, s


def test_ownercheck_owner_thread_mutates_freely():
    _oc, s = _guarded_server()
    s.pending.append(("c", "blk2"))
    s._in_system.add(12)
    s.repl_acked[3] = 5
    s._feed_free.pop()
    assert len(s.pending) == 2 and s.repl_acked[3] == 5


def test_ownercheck_cross_thread_mutation_raises():
    oc, s = _guarded_server()
    def _ior():
        buf = s._in_system           # aliased in-place mutation: the
        buf |= {97, 98}              # case only the runtime half sees

    ops = [lambda: s.pending.append(("x", "y")),
           lambda: s._in_system.discard(11),
           lambda: s.repl_acked.update({3: 9}),
           lambda: s.repl_acked.__setitem__(3, 9),
           lambda: s._feed_free.pop(),
           _ior]
    caught = []

    def hostile():
        for op in ops:
            try:
                op()
            except oc.OwnershipViolation as e:
                caught.append(str(e))

    t = threading.Thread(target=hostile, name="wire-worker-fx")
    t.start()
    t.join()
    assert len(caught) == len(ops)
    assert "wire-worker-fx" in caught[0]
    # the guard rejects BEFORE mutating: state is untouched
    assert len(s.pending) == 1 and s.repl_acked[3] == -1
    assert s._in_system == {11} and len(s._feed_free) == 1


def test_ownercheck_cross_thread_reads_are_free():
    _oc, s = _guarded_server()
    got = []

    def reader():
        got.append((len(s.pending), 3 in s.repl_acked,
                    sorted(s._in_system), list(s.pending)))

    t = threading.Thread(target=reader)
    t.start()
    t.join()
    assert got == [(1, True, [11], [("c", "blk")])]


def test_ownercheck_preserves_deque_maxlen():
    from deneva_tpu.runtime import ownercheck

    s = _Srv()
    s.me = 1
    s._committed_recent = deque([1, 2], maxlen=2)
    assert ownercheck.install(s) == 1
    s._committed_recent.append(3)
    assert list(s._committed_recent) == [2, 3]
    assert s._committed_recent.maxlen == 2


def test_ownercheck_owner_map_covers_guarded():
    """Every GUARDED attr must have a declared owner (the static checker
    enforces the server side; this pins the declarations file itself)."""
    from deneva_tpu.runtime import ownercheck as oc

    assert set(oc.GUARDED) <= set(oc.OWNER)
    assert all(oc.OWNER[a] == oc.DISPATCH for a in oc.GUARDED)
    for role in oc.WORKER_ENTRY:
        assert role in (oc.WIRE, oc.RETIRE, oc.CODEC)
