"""Multi-process client fleet unit tests (pod-scale PR): disjoint
lane-tag / tenant ranges across generators, seeded determinism of the
merged arrival schedule, exactly-once credit accounting under
ADMIT_NACK with multiple generators — including through the real
ClientNode routing paths via the transport-free rig."""

import time as _time
from collections import deque

import numpy as np
import pytest

from deneva_tpu.config import CCAlg, Config, WorkloadKind
from deneva_tpu.runtime import wire
from deneva_tpu.runtime.admission import encode_admit_nack
from deneva_tpu.runtime.client import TAG_RING, ClientNode
from deneva_tpu.runtime.loadgen import (FLEET_LANE_BITS, BackoffLedger,
                                        FleetCredits, FleetGen, LoadFleet,
                                        fleet_gen_of, fleet_tag_range,
                                        fleet_tenant_range)
from deneva_tpu.stats import Stats

MS = 1_000


def _fleet_cfg(**kw) -> Config:
    base = dict(workload=WorkloadKind.YCSB, cc_alg=CCAlg.TPU_BATCH,
                epoch_batch=64, conflict_buckets=512,
                synth_table_size=512, req_per_query=4, max_accesses=4,
                arrival_process="poisson", arrival_rate=200_000.0,
                loadgen_procs=4, tenant_cnt=8)
    base.update(kw)
    cfg = Config(**base)
    cfg.validate()
    return cfg


# ---- range partitioning --------------------------------------------------

def test_fleet_tag_ranges_disjoint_and_owner_decodable():
    span = TAG_RING >> FLEET_LANE_BITS
    prev_hi = 0
    for g in range(64):
        lo, hi = fleet_tag_range(TAG_RING, g)
        assert lo == prev_hi and hi - lo == span
        prev_hi = hi
        tags = np.arange(lo, hi, 997, dtype=np.int64)
        assert (fleet_gen_of(TAG_RING, tags) == g).all()
    assert prev_hi == TAG_RING          # the lanes tile the whole ring
    # tenant / client-id high bits never perturb ownership decoding
    tags = np.arange(*fleet_tag_range(TAG_RING, 3), 1009, dtype=np.int64)
    wtags = tags | (np.int64(5) << 24) | (np.int64(1) << 40)
    assert (fleet_gen_of(TAG_RING, wtags) == 3).all()


def test_fleet_gen_emits_only_its_own_ranges():
    cfg = _fleet_cfg()
    for g in range(cfg.loadgen_procs):
        gen = FleetGen(cfg, node_id=1, gid=g, ring=TAG_RING)
        lo, hi = fleet_tag_range(TAG_RING, g)
        tlo, thi = fleet_tenant_range(cfg.tenant_cnt,
                                      cfg.loadgen_procs, g)
        seen = 0
        t = 0.0
        while seen < 3 * (hi - lo) // 2:     # force a sub-ring wrap
            t += 0.05
            blk = gen.take(t, 4096)
            if blk is None:
                continue
            tags, tenants = blk
            seen += len(tags)
            assert (tags >= lo).all() and (tags < hi).all()
            assert (tenants >= tlo).all() and (tenants < thi).all()
        assert seen > hi - lo               # the wrap actually happened


def test_fleet_tenant_ranges_partition_tenants():
    for tenant_cnt, procs in ((8, 4), (5, 5), (256, 64), (7, 3)):
        covered = []
        for g in range(procs):
            lo, hi = fleet_tenant_range(tenant_cnt, procs, g)
            assert hi > lo, "validate pins tenant_cnt >= loadgen_procs"
            covered.extend(range(lo, hi))
        assert covered == list(range(tenant_cnt))   # disjoint + total
    assert fleet_tenant_range(1, 4, 3) == (0, 1)    # tenants off


def test_fleet_config_validation():
    _fleet_cfg()                                    # sane base composes
    with pytest.raises(ValueError, match="arrival_process"):
        _fleet_cfg(arrival_process="", arrival_rate=0.0)
    with pytest.raises(ValueError, match="64"):
        _fleet_cfg(loadgen_procs=65, tenant_cnt=256)
    with pytest.raises(ValueError, match="tenant_cnt"):
        _fleet_cfg(loadgen_procs=8, tenant_cnt=4)


# ---- seeded determinism of the merged schedule ---------------------------

def test_fleet_merged_schedule_is_deterministic():
    cfg = _fleet_cfg()
    a = LoadFleet(cfg, node_id=1, ring=TAG_RING, chunk=256, start=False)
    b = LoadFleet(cfg, node_id=1, ring=TAG_RING, chunk=256, start=False)
    grid = [0.01, 0.1, 0.37, 0.8, 1.5]
    ta = [a.target(t) for t in grid]
    assert ta == [b.target(t) for t in grid]
    assert all(x <= y for x, y in zip(ta, ta[1:]))       # monotone
    # the merged target is the sum of the per-lane schedules, and the
    # lanes are seeded DIFFERENTLY (independent Poisson gap streams)
    gens = [FleetGen(cfg, 1, g, TAG_RING) for g in range(4)]
    assert a.target(2.0) == sum(g.sched.target(2.0) for g in gens)
    per_lane = [g.sched.target(2.0) for g in gens]
    assert len(set(per_lane)) > 1, "lanes must not share one gap stream"
    # a different seed reshuffles, the same seed reproduces
    c = LoadFleet(_fleet_cfg(seed=1234), 1, TAG_RING, 256, start=False)
    assert c.target(2.0) != a.target(2.0)


def test_fleet_gen_streams_reproduce():
    cfg = _fleet_cfg()
    for g in (0, 3):
        x = FleetGen(cfg, 1, g, TAG_RING)
        y = FleetGen(cfg, 1, g, TAG_RING)
        for t in (0.05, 0.2, 0.21, 0.9):
            bx, by = x.take(t, 300), y.take(t, 300)
            if bx is None:
                assert by is None
                continue
            assert np.array_equal(bx[0], by[0])
            assert np.array_equal(bx[1], by[1])


def test_fleet_worker_processes_match_inline_oracle():
    """Two REAL generator processes: everything each lane streams over
    the queue must equal, in order, what the inline FleetGen (same cfg,
    node, gid) emits — the per-lane stream is deterministic even though
    the cross-lane interleaving is wall-clock."""
    cfg = _fleet_cfg(loadgen_procs=2, tenant_cnt=4)
    fl = LoadFleet(cfg, node_id=1, ring=TAG_RING, chunk=256)
    fl.go()
    got = {0: [], 1: []}
    ten = {0: [], 1: []}
    total = 0
    t0 = _time.monotonic()
    try:
        while total < 2048 and _time.monotonic() - t0 < 60:
            b = fl.take(256)
            if b is None:
                _time.sleep(0.005)
                continue
            tags, tc = b
            g = int(fleet_gen_of(TAG_RING, tags[:1])[0])
            assert (fleet_gen_of(TAG_RING, tags) == g).all(), \
                "a streamed block never mixes lanes"
            got[g].append(tags)
            ten[g].append(tc)
            total += len(tags)
    finally:
        fl.close()
    assert total >= 2048
    assert got[0] and got[1], "both lanes must produce"
    for g in (0, 1):
        ref = FleetGen(cfg, 1, g, TAG_RING)
        n = sum(map(len, got[g]))
        rt, rten = [], []
        t = 0.0
        while sum(map(len, rt)) < n:
            t += 0.01
            blk = ref.take(t, 256)
            if blk is not None:
                rt.append(blk[0])
                rten.append(blk[1])
        assert np.array_equal(np.concatenate(got[g]),
                              np.concatenate(rt)[:n])
        assert np.array_equal(np.concatenate(ten[g]),
                              np.concatenate(rten)[:n])


# ---- exactly-once credit accounting --------------------------------------

def test_fleet_credits_exactly_once():
    rng = np.random.default_rng(7)
    fc = FleetCredits(4, TAG_RING)
    span = TAG_RING >> FLEET_LANE_BITS
    outstanding: list[np.ndarray] = []
    acked = nacked = 0
    for round_ in range(50):
        g = int(rng.integers(4))
        # fresh slots per round: a charge collision would be a test
        # artifact, not a ledger property (double_charge must stay 0)
        tags = g * span + round_ * 64 + np.arange(64, dtype=np.int64)
        fc.charge(tags)
        outstanding.append(tags)
        if rng.random() < 0.5 and outstanding:
            victim = outstanding.pop(int(rng.integers(len(outstanding))))
            if rng.random() < 0.5:
                fc.nack(victim)
                nacked += len(victim)
                fc.nack(victim)        # duplicate NACK: counted, no-op
            else:
                fc.release(victim)
                acked += len(victim)
                fc.release(victim)     # duplicate ack: counted, no-op
    held = sum(map(len, outstanding))
    assert int(fc.outstanding().sum()) == held
    assert (fc.outstanding() >= 0).all()
    assert int(fc.acked.sum()) == acked
    assert int(fc.nacked.sum()) == nacked
    assert fc.double_release == acked + nacked    # one dup per release
    assert fc.double_charge == 0
    # NACK-released tags recharge cleanly (the backoff re-entry path)
    fc2 = FleetCredits(2, TAG_RING)
    tags = np.arange(64, dtype=np.int64)
    fc2.charge(tags)
    fc2.nack(tags)
    fc2.charge(tags)
    fc2.release(tags)
    assert fc2.double_charge == 0 and fc2.double_release == 0
    assert int(fc2.outstanding().sum()) == 0
    assert int(fc2.sent[0]) == 128    # two charges, both legitimate


# ---- through the real ClientNode routing (transport-free rig) ------------

class _FakeTp:
    def __init__(self):
        self.sent = []

    def sendv(self, dest, rtype, parts):
        self.sent.append((dest, rtype, b"".join(bytes(p) for p in parts)))


def _fleet_client(n_procs=2, n_srv=2, chunk=64):
    """ClientNode.__new__ rig (test_backoff.py's pattern) with the fleet
    credit ledger armed: _route / the sweeps exercise the REAL exactly-
    once filters feeding FleetCredits."""
    c = ClientNode.__new__(ClientNode)
    c.cfg = None
    c.n_srv = n_srv
    c._fault_mode = False
    c._adm = True
    c._elastic = False
    c._geo = False
    c._active = np.ones(n_srv, bool)
    c._rr = 0
    c._unacked = np.zeros(TAG_RING, bool)
    c._nacked = np.zeros(TAG_RING, bool)
    c._ledger = BackoffLedger(TAG_RING, 10 * MS, 500 * MS, seed=11)
    c._tag_srv = None
    c.tel = None
    c._resend_q = deque()
    c._resend_us = 100 * MS
    c._resend_cnt = 0
    c._dup_acks = 0
    c._nack_cnt = 0
    c._nack_resend_cnt = 0
    c._flash_end_us = None
    c.inflight = np.zeros(n_srv, np.int64)
    c.send_us = np.zeros(TAG_RING, np.int64)
    c.tag_type = np.zeros(TAG_RING, np.uint8)
    c.type_names = ["txn"]
    c.ring_tenants = None
    c._tenant_on = False
    c._fleet = None
    c._fleet_credits = FleetCredits(n_procs, TAG_RING)
    c.chunk = chunk
    c.ring = [wire.QueryBlock(
        keys=np.zeros((chunk, 2), np.int32),
        types=np.ones((chunk, 2), np.int8),
        scalars=np.zeros((chunk, 1), np.int32),
        tags=np.zeros(chunk, np.int64))]
    c.ring_types = [np.zeros(chunk, np.uint8)]
    c.ring_pos = 0
    c.stats = Stats()
    c.tp = _FakeTp()
    return c


def _send(c, srv, tags):
    """The hot loop's bookkeeping for a sent fleet batch."""
    c._unacked[tags % TAG_RING] = True
    c._nacked[tags % TAG_RING] = False
    c._ledger.reset(tags)
    c.inflight[srv] += len(tags)
    c._fleet_credits.charge(tags)


def test_fleet_credits_exactly_once_through_client_routing():
    """Multiple generators' tags through the REAL _route paths: dup
    NACKs, the NACK-then-late-CL_RSP race and backoff re-entry keep the
    per-lane ledger exactly once (double counters stay 0 — the client's
    freshness filters are the dedup point)."""
    span = TAG_RING >> FLEET_LANE_BITS
    c = _fleet_client(n_procs=2)
    fc = c._fleet_credits
    lat = c.stats.arr("client_client_latency")
    t0 = np.arange(10, dtype=np.int64)              # lane 0
    t1 = span + np.arange(10, dtype=np.int64)       # lane 1
    _send(c, 0, t0)
    _send(c, 1, t1)
    assert (fc.outstanding() == [10, 10]).all()
    # lane 1 takes a NACK for 4 tags, then the same NACK duplicated
    nack = encode_admit_nack(t1[:4], np.full(4, 20 * MS, np.uint32))
    c._route(1, "ADMIT_NACK", nack, lat)
    c._route(1, "ADMIT_NACK", nack, lat)
    assert (fc.outstanding() == [10, 6]).all()
    assert (fc.nacked == [0, 4]).all()
    # the late CL_RSP race: ALL lane-1 tags ack, the 4 NACKed ones must
    # not release twice (their credit is gone)
    c._route(1, "CL_RSP", wire.encode_cl_rsp(t1), lat)
    assert (fc.outstanding() == [10, 0]).all()
    assert (fc.acked == [0, 6]).all()
    # duplicate CL_RSP for lane 0: one release only
    c._route(0, "CL_RSP", wire.encode_cl_rsp(t0), lat)
    c._route(0, "CL_RSP", wire.encode_cl_rsp(t0), lat)
    assert (fc.outstanding() == [0, 0]).all()
    assert (fc.acked == [10, 6]).all()
    assert fc.double_charge == 0 and fc.double_release == 0


def test_fleet_backoff_reentry_recharges_the_owning_lane():
    span = TAG_RING >> FLEET_LANE_BITS
    c = _fleet_client(n_procs=2)
    fc = c._fleet_credits
    lat = c.stats.arr("client_client_latency")
    t1 = span + np.arange(6, dtype=np.int64)
    _send(c, 1, t1)
    c._route(1, "ADMIT_NACK",
             encode_admit_nack(t1, np.full(6, 15 * MS, np.uint32)), lat)
    assert int(fc.outstanding()[1]) == 0 and int(fc.nacked[1]) == 6
    now = _time.monotonic_ns() // 1000
    c._backoff_sweep(now_us=now + 10_000 * MS)
    assert c._nack_resend_cnt == 6
    assert int(fc.outstanding()[1]) == 6, "re-entry recharges lane 1"
    assert int(fc.sent[1]) == 12
    c._route(1, "CL_RSP", wire.encode_cl_rsp(t1), lat)
    assert int(fc.outstanding()[1]) == 0
    assert fc.double_charge == 0 and fc.double_release == 0
