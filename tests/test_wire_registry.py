"""Registry-completeness contract (PR 6 satellite): the executable half
of the graftlint wire-consistency model.

Every rtype declared in native.RTYPE must (a) have a WIRE_MODEL row,
(b) carry an EXPLICIT in/out fault-mask classification that matches
native.FAULT_RTYPE_MASK (the PR 4 "rtypes 15-17 outside the mask" rule,
machine-checked), (c) name only codecs that actually exist, and (d) —
when it carries a payload — round-trip encode → decode bit-exactly.
The ROUNDTRIP table below must stay total over the registry: adding an
rtype without extending it fails test_every_rtype_covered.
"""

import numpy as np
import pytest

from deneva_tpu.runtime import admission as A
from deneva_tpu.runtime import faildet as FD
from deneva_tpu.runtime import membership as M
from deneva_tpu.runtime import metricsbus as MB
from deneva_tpu.runtime import replication as R
from deneva_tpu.runtime import logger, native, wire
from tools.graftlint.wiremodel import WIRE_MODEL

# ---- model <-> registry agreement --------------------------------------

def test_registry_and_model_agree():
    assert set(native.RTYPE) == set(WIRE_MODEL)


def test_fault_mask_classification_is_explicit_and_matches():
    for name, spec in WIRE_MODEL.items():
        in_mask = bool(native.FAULT_RTYPE_MASK >> native.RTYPE[name] & 1)
        assert in_mask == spec.fault_mask, (
            f"{name}: FAULT_RTYPE_MASK says {in_mask}, model says "
            f"{spec.fault_mask} ({spec.note})")
    # the chaos-harness contract: exactly the open-loop client traffic
    assert {n for n, s in WIRE_MODEL.items() if s.fault_mask} \
        == {"CL_QRY_BATCH", "CL_RSP"}


def test_declared_codecs_exist():
    for spec in WIRE_MODEL.values():
        for fn in (*spec.codec_encode, *spec.codec_decode):
            assert any(hasattr(m, fn)
                       for m in (wire, M, logger, R, A, FD, MB)), \
                f"{spec.name}: declared codec {fn} not found"


# ---- per-rtype round trips ---------------------------------------------

def _qb(n=6, w=3, s=2, seed=7):
    r = np.random.default_rng(seed)
    return wire.QueryBlock(
        keys=r.integers(0, 1 << 20, (n, w)).astype(np.int32),
        types=r.integers(0, 4, (n, w)).astype(np.int8),
        scalars=r.integers(0, 99, (n, s)).astype(np.int32),
        tags=r.integers(0, 1 << 40, n).astype(np.int64))


def _assert_qb_equal(a, b):
    np.testing.assert_array_equal(a.keys, b.keys)
    np.testing.assert_array_equal(a.types, b.types)
    np.testing.assert_array_equal(a.scalars, b.scalars)
    np.testing.assert_array_equal(a.tags, b.tags)


def _rt_qry_batch():
    b = _qb()
    _assert_qb_equal(b, wire.decode_qry_block(wire.encode_qry_block(b)))
    # the zero-copy parts path must be byte-identical to the codec
    parts = wire.qry_block_parts(b.tags, b.keys, b.types, b.scalars)
    assert b"".join(bytes(p) for p in parts) == wire.encode_qry_block(b)


def _rt_cl_rsp():
    tags = np.arange(5, dtype=np.int64) * 977
    np.testing.assert_array_equal(
        tags, wire.decode_cl_rsp(wire.encode_cl_rsp(tags)))
    assert b"".join(bytes(p) for p in wire.cl_rsp_parts(tags)) \
        == wire.encode_cl_rsp(tags)


def _rt_epoch_blob():
    b = _qb()
    ts = np.arange(len(b), dtype=np.int64) + 100
    buf = wire.encode_epoch_blob(42, b, ts)
    epoch, b2, ts2 = wire.decode_epoch_blob(buf)
    assert epoch == 42 and wire.peek_blob_epoch(buf) == 42
    _assert_qb_equal(b, b2)
    np.testing.assert_array_equal(ts, ts2)
    # in-place decode into oversized feed views
    n, w, s = len(b), b.keys.shape[1], b.scalars.shape[1]
    tags = np.zeros(n + 3, np.int64)
    ts3 = np.zeros(n + 3, np.int64)
    keys = np.zeros((n + 3, w), np.int32)
    types = np.zeros((n + 3, w), np.int8)
    scalars = np.zeros((n + 3, s), np.int32)
    e2, n2 = wire.decode_epoch_blob_into(buf, tags, ts3, keys, types,
                                         scalars)
    assert (e2, n2) == (42, n)
    np.testing.assert_array_equal(keys[:n], b.keys)
    np.testing.assert_array_equal(tags[:n], b.tags)
    # parts path byte-identity
    parts = wire.epoch_blob_parts(42, ts, b.tags, b.keys, b.types,
                                  b.scalars)
    assert b"".join(bytes(p) for p in parts) == buf


def _rt_log_msg():
    b = _qb()
    ts = np.arange(len(b), dtype=np.int64)
    blob = wire.encode_epoch_blob(3, b, ts)
    active = np.array([1, 0, 1, 1, 0, 1], np.uint8)
    rec = logger.pack_record(3, blob, active)
    [(e, blob2, bits)] = list(logger.unpack_records(rec))
    assert e == 3 and blob2 == blob
    np.testing.assert_array_equal(
        bits, np.packbits(active))
    # one-pass views packer is byte-identical
    rec2 = logger.pack_record_views(3, ts, b.tags, b.keys, b.types,
                                    b.scalars, active)
    assert rec2.tobytes() == rec
    [(e3, lo, hi)] = list(logger.iter_record_spans(rec))
    assert e3 == 3 and (lo, hi) == (0, len(rec))


def _rt_shutdown():
    assert wire.decode_shutdown(wire.encode_shutdown(1234)) == 1234


def _rt_vote():
    r = np.random.default_rng(3)
    commit = r.integers(0, 2, 19).astype(bool)
    abort = ~commit & r.integers(0, 2, 19).astype(bool)
    for bounds in (None, r.integers(0, 999, 19).astype(np.int32)):
        e, c2, a2, b2 = wire.decode_vote(
            wire.encode_vote(9, commit, abort, bounds))
        assert e == 9
        np.testing.assert_array_equal(commit, c2)
        np.testing.assert_array_equal(abort, a2)
        if bounds is None:
            assert b2 is None
        else:
            np.testing.assert_array_equal(bounds, b2)


def _rt_map_msg():
    m = M.SlotMap(5, np.arange(12, dtype=np.int32) % 3)
    buf = M.encode_map_msg(m, cutover_epoch=77, reason=M.REASON_DRAIN,
                           subject=2)
    m2, cutover, reason, subject = M.decode_map_msg(buf)
    assert (m2.version, cutover, reason, subject) \
        == (5, 77, M.REASON_DRAIN, 2)
    np.testing.assert_array_equal(m.owners, m2.owners)


def _rt_migrate_rows():
    keys = np.array([4, 16, 28], np.int32)
    cols = {"val": np.arange(6, dtype=np.int64).reshape(3, 2),
            "flag": np.array([1, 0, 1], np.uint8)}
    buf = M.encode_migrate_rows(8, keys, cols)
    assert M.peek_rows_version(buf) == 8
    v, keys2, cols2 = M.decode_migrate_rows(buf)
    assert v == 8 and set(cols2) == {"val", "flag"}
    np.testing.assert_array_equal(keys, keys2)
    for name in cols:
        np.testing.assert_array_equal(cols[name], cols2[name])


def _rt_log_ack():
    acked, applied = R.decode_log_ack(R.encode_log_ack(1234, 1227))
    assert (acked, applied) == (1234, 1227)


def _rt_region_read():
    keys = np.array([7, 4095, 0, 88], np.int32)
    buf = R.encode_region_read(991, keys)
    tag, keys2 = R.decode_region_read(buf)
    assert tag == 991
    np.testing.assert_array_equal(keys, keys2)
    # zero-copy parts path must be byte-identical to the codec
    parts = R.region_read_parts(991, keys)
    assert b"".join(bytes(p) for p in parts) == buf


def _rt_region_read_rsp():
    r = np.random.default_rng(11)
    values = r.integers(0, 1 << 32, 9, dtype=np.uint32)
    vers = r.integers(0, 500, 9).astype(np.int32)
    buf = R.encode_region_read_rsp(5, 640, values, vers)
    tag, boundary, v2, ver2 = R.decode_region_read_rsp(buf)
    assert (tag, boundary) == (5, 640)
    np.testing.assert_array_equal(values, v2)
    np.testing.assert_array_equal(vers, ver2)
    parts = R.region_read_rsp_parts(5, 640, values, vers)
    assert b"".join(bytes(p) for p in parts) == buf


def _rt_admit_nack():
    r = np.random.default_rng(23)
    tags = r.integers(0, 1 << 32, 7).astype(np.int64)
    retry = r.integers(1, 1 << 22, 7).astype(np.uint32)
    tags2, retry2 = A.decode_admit_nack(A.encode_admit_nack(tags, retry))
    np.testing.assert_array_equal(tags, tags2)
    np.testing.assert_array_equal(retry, retry2)
    # zero-copy parts path must be byte-identical to the codec
    parts = A.admit_nack_parts(tags, retry)
    assert b"".join(bytes(p) for p in parts) \
        == A.encode_admit_nack(tags, retry)
    # empty batch round-trips too (a fully-deduped arrival)
    t0, r0 = A.decode_admit_nack(A.encode_admit_nack(
        np.zeros(0, np.int64), np.zeros(0, np.uint32)))
    assert len(t0) == 0 and len(r0) == 0


def _rt_heartbeat():
    ver, seen, ep = FD.decode_heartbeat(FD.encode_heartbeat(3, 127, 640))
    assert (ver, seen, ep) == (3, 127, 640)
    # zero-copy parts path must be byte-identical to the codec
    parts = FD.heartbeat_parts(3, 127, 640)
    assert b"".join(bytes(p) for p in parts) \
        == FD.encode_heartbeat(3, 127, 640)


def _rt_fence_nack():
    mine, stale, ep = FD.decode_fence_nack(FD.encode_fence_nack(2, 0, 77))
    assert (mine, stale, ep) == (2, 0, 77)
    parts = FD.fence_nack_parts(2, 0, 77)
    assert b"".join(bytes(p) for p in parts) \
        == FD.encode_fence_nack(2, 0, 77)


def _rt_heal():
    owners = np.arange(12, dtype=np.int32) % 3
    buf = FD.encode_heal(88, 5, owners)
    ep, ver, owners2 = FD.decode_heal(buf)
    assert (ep, ver) == (88, 5)
    np.testing.assert_array_equal(owners, owners2)
    parts = FD.heal_parts(88, 5, owners)
    assert b"".join(bytes(p) for p in parts) == buf


def _rt_metrics():
    r = np.random.default_rng(31)
    fields = r.random(len(MB.FRAME_FIELDS)).astype(np.float32) * 100
    for dens in (r.integers(0, 9999, 4).astype(np.int32),
                 np.zeros(0, np.int32)):       # clients ship no density
        buf = MB.encode_metrics_frame(2, MB.ROLE_SERVER, 640, 123456789,
                                      fields, dens)
        node, role, epoch, t_us, f2, d2 = MB.decode_metrics_frame(buf)
        assert (node, role, epoch, t_us) == (2, MB.ROLE_SERVER, 640,
                                             123456789)
        np.testing.assert_array_equal(fields, f2)
        np.testing.assert_array_equal(dens, d2)
        # zero-copy parts path must be byte-identical to the codec
        parts = MB.metrics_frame_parts(2, MB.ROLE_SERVER, 640, 123456789,
                                       fields, dens)
        assert b"".join(bytes(p) for p in parts) == buf


def _rt_payload_free():
    return None     # no payload on the wire: nothing to round-trip


ROUNDTRIP = {
    "INIT_DONE": _rt_payload_free,      # setup barrier
    "CL_QRY_BATCH": _rt_qry_batch,
    "CL_RSP": _rt_cl_rsp,
    "RDONE": _rt_payload_free,          # reserved (EPOCH_BLOB doubles)
    "EPOCH_BLOB": _rt_epoch_blob,
    "LOG_MSG": _rt_log_msg,
    "LOG_RSP": _rt_shutdown,            # epoch-watermark ack
    "PING": _rt_payload_free,           # native-level
    "PONG": _rt_payload_free,           # native-level
    "SHUTDOWN": _rt_shutdown,
    "MEASURE": _rt_shutdown,
    "VOTE": _rt_vote,
    "VOTE2": _rt_vote,
    "REJOIN": _rt_shutdown,
    "MIGRATE_BEGIN": _rt_map_msg,
    "MIGRATE_ROWS": _rt_migrate_rows,
    "MAP_UPDATE": _rt_map_msg,
    "LOG_ACK": _rt_log_ack,
    "REGION_READ": _rt_region_read,
    "REGION_READ_RSP": _rt_region_read_rsp,
    "ADMIT_NACK": _rt_admit_nack,
    "HEARTBEAT": _rt_heartbeat,
    "FENCE_NACK": _rt_fence_nack,
    "HEAL": _rt_heal,
    "METRICS": _rt_metrics,
}


def test_every_rtype_covered():
    assert set(ROUNDTRIP) == set(native.RTYPE)
    # payload-free entries must declare no codecs in the model; payload
    # entries must declare at least an encoder or decoder
    for name, fn in ROUNDTRIP.items():
        spec = WIRE_MODEL[name]
        if fn is _rt_payload_free:
            assert spec.codec_encode == () and spec.codec_decode == (), name
        else:
            assert spec.codec_encode or spec.codec_decode, name


@pytest.mark.parametrize("name", sorted(native.RTYPE))
def test_rtype_round_trips(name):
    ROUNDTRIP[name]()
