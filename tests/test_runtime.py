"""Distributed runtime integration tests (SURVEY §4.4: the reference's
de-facto integration test is N servers + M clients as processes on one box
over IPC; same rig here via `runtime.launch.run_cluster`).

Each test boots a real multi-process cluster: native transport mesh,
INIT_DONE barrier, client open loop with inflight throttle, per-epoch
EPOCH_BLOB exchange, deterministic merged validation, partitioned
execution, CL_RSP acks, SHUTDOWN protocol, per-node [summary] lines.
"""

import numpy as np
import pytest

from deneva_tpu.config import Config, CCAlg, WorkloadKind
from deneva_tpu.stats import parse_summary


def small_cfg(**kw):
    base = dict(
        workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
        epoch_batch=128, conflict_buckets=512, synth_table_size=4096,
        max_txn_in_flight=1024, req_per_query=4, max_accesses=4,
        zipf_theta=0.6, warmup_secs=0.5, done_secs=1.5)
    base.update(kw)
    return Config(**base)


def boot(cfg, **kw):
    from deneva_tpu.runtime.launch import run_cluster
    return run_cluster(cfg, platform="cpu", **kw)


@pytest.mark.slow
def test_cluster_2s1c_calvin_commits_and_agrees():
    cfg = small_cfg(node_cnt=2, client_node_cnt=1)
    out = boot(cfg)
    assert set(out) == {0, 1, 2}
    s0 = parse_summary(out[0][1])
    s1 = parse_summary(out[1][1])
    cl = parse_summary(out[2][1])
    # deterministic replicated validation: identical global commit counts
    assert s0["total_txn_commit_cnt"] == s1["total_txn_commit_cnt"] > 0
    assert s0["epoch_cnt"] == s1["epoch_cnt"]
    # Calvin never aborts (reference: deterministic locks queue, never refuse)
    assert s0["total_txn_abort_cnt"] == 0
    # client measured end-to-end latency for completed txns, with
    # per-txn-type percentile families (VERDICT r3 next #6)
    assert cl["txn_cnt"] > 0
    assert cl["client_client_latency_p50"] > 0
    assert cl["ycsb_rw_latency_p50"] > 0
    # server-side TxnStats decomposition: every committed txn reports
    # its restart/wait counts (CALVIN: zero retries by construction)
    assert s0["txn_retries_p99"] == 0 and "txn_waits_p99" in s0


@pytest.mark.slow
def test_cluster_no_wait_aborts_and_recovers():
    cfg = small_cfg(node_cnt=2, client_node_cnt=1, cc_alg=CCAlg.NO_WAIT,
                    zipf_theta=0.9, synth_table_size=1024)
    out = boot(cfg)
    s0 = parse_summary(out[0][1])
    s1 = parse_summary(out[1][1])
    assert s0["total_txn_commit_cnt"] == s1["total_txn_commit_cnt"] > 0
    # high contention: the abort/backoff/retry path must actually fire
    assert s0["total_txn_abort_cnt"] == s1["total_txn_abort_cnt"] > 0
    assert parse_summary(out[2][1])["txn_cnt"] > 0


@pytest.mark.slow
def test_cluster_3s2c_tpu_batch():
    cfg = small_cfg(node_cnt=3, client_node_cnt=2, cc_alg=CCAlg.TPU_BATCH,
                    synth_table_size=4098)
    out = boot(cfg)
    commits = [parse_summary(out[s][1])["total_txn_commit_cnt"]
               for s in range(3)]
    assert commits[0] == commits[1] == commits[2] > 0
    # both clients served
    assert parse_summary(out[3][1])["txn_cnt"] > 0
    assert parse_summary(out[4][1])["txn_cnt"] > 0


@pytest.mark.slow
def test_cluster_2s1c_tpcc_partitioned():
    """TPC-C over 2 partitioned server nodes (warehouse -> node, reference
    wh_to_part): commits agree, cross-warehouse payments/orders split
    across owners without 2PC."""
    cfg = Config(
        workload=WorkloadKind.TPCC, cc_alg=CCAlg.CALVIN,
        node_cnt=2, client_node_cnt=1,
        num_wh=4, cust_per_dist=64, max_items=128, max_items_per_txn=5,
        insert_table_cap=1 << 12,
        epoch_batch=64, conflict_buckets=512, max_accesses=8,
        max_txn_in_flight=512, warmup_secs=0.5, done_secs=1.5)
    out = boot(cfg)
    s0 = parse_summary(out[0][1])
    s1 = parse_summary(out[1][1])
    assert s0["total_txn_commit_cnt"] == s1["total_txn_commit_cnt"] > 0
    assert parse_summary(out[2][1])["txn_cnt"] > 0


@pytest.mark.slow
def test_cluster_2s1c_pps_partitioned():
    """PPS over 2 partitioned nodes: recon against the replicated
    USES/SUPPLIES maps stays local, commits agree across servers."""
    cfg = Config(
        workload=WorkloadKind.PPS, cc_alg=CCAlg.CALVIN,
        node_cnt=2, client_node_cnt=1,
        pps_parts_cnt=500, pps_products_cnt=100, pps_suppliers_cnt=100,
        pps_parts_per=4,
        epoch_batch=64, conflict_buckets=512, max_accesses=16,
        max_txn_in_flight=512, warmup_secs=0.5, done_secs=1.5)
    out = boot(cfg)
    s0 = parse_summary(out[0][1])
    s1 = parse_summary(out[1][1])
    assert s0["total_txn_commit_cnt"] == s1["total_txn_commit_cnt"] > 0
    assert parse_summary(out[2][1])["txn_cnt"] > 0


@pytest.mark.slow
def test_dead_peer_detected_fast():
    """Failure detection (SURVEY §5.3 — the reference has none and would
    hang): a server whose peer dies mid-run must raise naming the peer,
    long before the 60s blob timeout."""
    import threading
    import time as _time
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deneva_tpu.runtime.native import ipc_endpoints
    from deneva_tpu.runtime.server import ServerNode

    cfg = small_cfg(node_cnt=2, client_node_cnt=0, done_secs=30.0,
                    synth_table_size=4096)
    eps = ipc_endpoints(2, "deadpeer")
    err: dict = {}

    def run_a():
        node = ServerNode(cfg.replace(node_id=0, part_cnt=2), eps, "cpu")
        t0 = _time.monotonic()
        try:
            node.run()
        except RuntimeError as e:
            err["msg"] = str(e)
            err["secs"] = _time.monotonic() - t0
        finally:
            node.close()

    def run_b():
        node = ServerNode(cfg.replace(node_id=1, part_cnt=2), eps, "cpu")
        node.barrier()          # join the mesh, then die without a word
        node.close()

    ta = threading.Thread(target=run_a)
    tb = threading.Thread(target=run_b)
    ta.start(); tb.start()
    tb.join(timeout=60)
    ta.join(timeout=60)
    assert "msg" in err, "server 0 never noticed the dead peer"
    assert "died" in err["msg"] and "[1]" in err["msg"]
    assert err["secs"] < 30, f"detection took {err['secs']:.1f}s"


def test_replica_barrier_timeout_and_clean_close(tmp_path):
    """ReplicaNode.barrier timeout path (previously untested): a peer
    that joins the mesh but never sends INIT_DONE must trip the bounded
    TimeoutError naming the replica, and close() afterwards must release
    the log file handle AND the transport in that order, idempotently —
    teardown after a failed barrier may not leak the open log or hang."""
    import os
    import threading

    from deneva_tpu.runtime.native import NativeTransport, ipc_endpoints
    from deneva_tpu.runtime.replica import ReplicaNode

    # layout [1 server | 0 clients | 1 replica]: replica is node 1
    cfg = small_cfg(node_cnt=1, client_node_cnt=0, replica_cnt=1,
                    node_id=1, logging=True,
                    log_dir=str(tmp_path)).validate()
    eps = ipc_endpoints(2, f"replbar_{os.getpid()}")
    peer_box: dict = {}

    def run_peer():
        # joins the mesh so both dt_starts complete, then stays silent
        tp = NativeTransport(0, eps, 2)
        tp.start()
        peer_box["tp"] = tp
        peer_box["ev"].wait(30)
        tp.close()

    peer_box["ev"] = threading.Event()
    t = threading.Thread(target=run_peer)
    t.start()
    node = ReplicaNode(cfg, eps)
    try:
        with pytest.raises(TimeoutError, match="replica 1"):
            node.barrier(timeout_s=0.8)
    finally:
        node.close()
        peer_box["ev"].set()
        t.join(timeout=30)
    # close ordering: the log handle is released (no dangling fsync
    # target) and a second close is a no-op, not a crash
    assert node._f.closed
    node.close()


@pytest.mark.slow
def test_client_load_rate_throttles():
    """LOAD_RATE mode (reference `config.h:21-22`, client_thread.cpp:35-41):
    a fixed txn/s budget must cap the send rate well below saturation."""
    cfg = small_cfg(node_cnt=1, client_node_cnt=1, load_rate=2000,
                    warmup_secs=0.3, done_secs=2.0)
    out = boot(cfg)
    cl = parse_summary(out[1][1])
    # ~2000 txn/s over the ~3s client lifetime, chunked sends => bound
    # generously above budget (one batch of slack) but far below the
    # saturated rate
    assert cl["sent_cnt"] <= 2000 * cl["total_runtime"]         + 2 * cfg.client_batch_size


@pytest.mark.slow
def test_wait_die_preserves_birth_ts_across_restarts():
    """WAIT_DIE starvation-freedom: a restarted txn must keep its birth
    timestamp (reference preserves them, worker_thread.cpp:492-508);
    fresh-ts backends re-stamp ABORTED restarts only — deferred waiters
    keep their birth ts like the in-process pool and the reference's
    parked requests.  Driven directly through the server's admission
    path."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deneva_tpu.runtime import wire
    from deneva_tpu.runtime.native import ipc_endpoints
    from deneva_tpu.runtime.server import ServerNode

    def probe(alg, aborted):
        cfg = small_cfg(node_cnt=1, part_cnt=1, client_node_cnt=0,
                        cc_alg=alg)
        node = ServerNode(cfg, ipc_endpoints(1, f"tspin_{alg}_{aborted}"),
                          "cpu")
        try:
            blk = wire.QueryBlock(
                keys=np.zeros((4, 4), np.int32),
                types=np.ones((4, 4), np.int8),
                scalars=np.zeros((4, 0), np.int32),
                tags=np.arange(4, dtype=np.int64))
            birth = np.array([7, 9, 11, 13], np.int64)
            node.retry.push(blk, np.full(4, int(aborted), np.int32), birth,
                            epoch=0, aborted=np.full(4, aborted, bool))
            _, _, ts, _ = node._contribution(epoch=5)
            return birth, ts
        finally:
            node.close()

    birth, ts = probe(CCAlg.WAIT_DIE, aborted=True)  # fresh_ts=False
    assert (ts[:4] == birth).all(), "WAIT_DIE restart lost its birth ts"
    birth, ts = probe(CCAlg.OCC, aborted=True)       # fresh_ts=True
    assert not (ts[:4] == birth).any(), "OCC abort-restart kept a stale ts"
    birth, ts = probe(CCAlg.TIMESTAMP, aborted=False)  # deferred waiter
    assert (ts[:4] == birth).all(), \
        "a deferred (waiting) txn must keep its birth ts"


@pytest.mark.slow
def test_wait_die_cluster_commits_agree():
    """WAIT_DIE over the full cluster under heavy contention: the blob-
    carried timestamps keep every node's verdicts identical."""
    cfg = small_cfg(node_cnt=2, client_node_cnt=1, cc_alg=CCAlg.WAIT_DIE,
                    zipf_theta=0.95, synth_table_size=512)
    out = boot(cfg)
    s0 = parse_summary(out[0][1])
    s1 = parse_summary(out[1][1])
    assert s0["total_txn_commit_cnt"] == s1["total_txn_commit_cnt"] > 0
    # WAIT_DIE under contention must actually wait (defer) and/or die
    assert s0["defer_cnt"] + s0["total_txn_abort_cnt"] > 0


@pytest.mark.slow
def test_cluster_tcp_transport():
    """TCP transport mode (reference TPORT_TYPE TCP, config.h:335):
    same cluster protocol over loopback TCP sockets."""
    cfg = small_cfg(node_cnt=2, client_node_cnt=1, tport_type="tcp")
    out = boot(cfg)
    s0 = parse_summary(out[0][1])
    s1 = parse_summary(out[1][1])
    assert s0["total_txn_commit_cnt"] == s1["total_txn_commit_cnt"] > 0
    assert parse_summary(out[2][1])["txn_cnt"] > 0


@pytest.mark.slow
def test_cluster_abort_mode_forces_and_completes():
    """YCSB_ABORT_MODE in the distributed runtime: forced aborts are
    counted identically on every server, forced txns complete (client
    gets acked, no immortal retries) and commits keep flowing."""
    cfg = small_cfg(node_cnt=2, client_node_cnt=1, cc_alg=CCAlg.TPU_BATCH,
                    ycsb_abort_mode=True, zipf_theta=0.9,
                    synth_table_size=8192)
    out = boot(cfg)
    s0 = parse_summary(out[0][1])
    s1 = parse_summary(out[1][1])
    assert s0["total_txn_abort_cnt"] == s1["total_txn_abort_cnt"] > 0
    assert s0["total_txn_commit_cnt"] == s1["total_txn_commit_cnt"] > 0
    assert parse_summary(out[2][1])["txn_cnt"] > 0


@pytest.mark.slow
@pytest.mark.parametrize("alg", [CCAlg.OCC, CCAlg.TIMESTAMP, CCAlg.MVCC])
def test_cluster_vote_protocol_agrees(alg):
    """Batched 2PC (VOTE): each server validates only its partition's
    accesses against local state and the epoch vote exchange decides —
    the coordination shape of the reference's RPREPARE/RACK_PREP
    (system/txn.cpp:498-606), batched.  Global decisions are the same
    AND/OR on every node, so commit counts must agree."""
    cfg = small_cfg(node_cnt=2, client_node_cnt=1, cc_alg=alg,
                    zipf_theta=0.8, synth_table_size=2048)
    assert cfg.dist_protocol == "auto"   # auto routes lock/ts/occ to VOTE
    out = boot(cfg)
    s0 = parse_summary(out[0][1])
    s1 = parse_summary(out[1][1])
    assert s0["total_txn_commit_cnt"] == s1["total_txn_commit_cnt"] > 0
    # partitioned validation under contention must exercise the abort path
    assert s0["total_txn_abort_cnt"] == s1["total_txn_abort_cnt"]
    assert parse_summary(out[2][1])["txn_cnt"] > 0


@pytest.mark.slow
def test_cluster_maat_vote_negotiates_positions():
    """Distributed MAAT (VERDICT r3 next #4): explicit --dist_protocol=
    vote routes MAAT through partition-local validation with per-txn
    position bounds piggybacked on the votes (the reference's
    `[lower,upper)` RACK_PREP range negotiation, maat.cpp:176-190) and a
    verify round that catches cross-node cycles.  Both servers must
    reach identical global decisions and commit under contention."""
    cfg = small_cfg(node_cnt=2, client_node_cnt=1, cc_alg=CCAlg.MAAT,
                    dist_protocol="vote", zipf_theta=0.8,
                    synth_table_size=2048)
    out = boot(cfg)
    s0 = parse_summary(out[0][1])
    s1 = parse_summary(out[1][1])
    assert s0["total_txn_commit_cnt"] == s1["total_txn_commit_cnt"] > 0
    assert s0["total_txn_abort_cnt"] == s1["total_txn_abort_cnt"]
    assert parse_summary(out[2][1])["txn_cnt"] > 0


def test_maat_vote_steps_single_node_equals_merged():
    """Unit-level equivalence (the VERDICT's bar): at node_cnt=1 the
    owner mask covers every access, so the vote path's local prepare IS
    merged validation, the intersected positions are the node's own
    locally-consistent order, and the verify round finds no violated
    edge — verdicts must match validate_maat exactly."""
    import jax.numpy as jnp
    from deneva_tpu.cc import AccessBatch, build_conflict_incidence, \
        get_backend
    from deneva_tpu.runtime.server import make_vote_steps
    from deneva_tpu.workloads import get_workload

    cfg = small_cfg(node_cnt=1, cc_alg=CCAlg.MAAT, dist_protocol="vote",
                    zipf_theta=0.9, synth_table_size=256,
                    epoch_batch=32, req_per_query=4, max_accesses=4)
    wl = get_workload(cfg)
    be = get_backend(cfg.cc_alg)
    db = wl.load()
    import jax
    q = wl.generate(jax.random.PRNGKey(5), 32)
    active = jnp.ones(32, bool)
    ts = jnp.arange(1, 33, dtype=jnp.int32)
    vote, check, _apply = make_vote_steps(cfg, wl, be)
    vc, va, vd, lo = vote(db, be.init_state(cfg), q, active, ts)
    # merged-mode reference verdict on the identical batch
    p = wl.plan(db, q)
    batch = AccessBatch(
        table_ids=p["table_ids"], keys=p["keys"], is_read=p["is_read"],
        is_write=p["is_write"], valid=p["valid"], ts=ts,
        rank=jnp.arange(32, dtype=jnp.int32), active=active)
    inc = build_conflict_incidence(cfg, be, batch, p.get("order_free"))
    verdict, _ = be.validate(cfg, be.init_state(cfg), batch, inc)
    assert (np.asarray(vc) == np.asarray(verdict.commit)).all()
    assert (np.asarray(va) == np.asarray(verdict.abort)).all()
    assert (np.asarray(vd) == np.asarray(verdict.defer)).all()
    # the verify round must pass vacuously on the committed candidates
    order = np.asarray(lo).astype(np.int64) * 32 + np.arange(32)
    ab2 = check(db, q, vc, ts, jnp.asarray(order.astype(np.int32)))
    assert not np.asarray(ab2).any()


def test_maat_vote_detects_cross_node_write_skew():
    """The verify round is exactly the reference's range-intersection
    abort: a write-skew cycle split across two owners is invisible to
    both local validations, but the intersected positions cannot satisfy
    both nodes' edges — one txn's range closes (maat.cpp:176-190)."""
    import jax.numpy as jnp
    from deneva_tpu.cc import get_backend
    from deneva_tpu.runtime.server import make_vote_steps
    from deneva_tpu.workloads import get_workload
    from deneva_tpu.workloads.ycsb import YCSBQuery

    base = small_cfg(node_cnt=2, cc_alg=CCAlg.MAAT, dist_protocol="vote",
                     synth_table_size=256, epoch_batch=2,
                     req_per_query=2, max_accesses=2)
    be = get_backend(base.cc_alg)
    # txn0: r(k0) w(k1); txn1: r(k1) w(k0) — k0 owned by node0, k1 node1
    k0, k1 = 2, 3
    q = YCSBQuery(
        keys=jnp.asarray([[k0, k1], [k1, k0]], jnp.int32),
        is_write=jnp.asarray([[False, True], [False, True]]))
    active = jnp.ones(2, bool)
    ts = jnp.asarray([1, 2], jnp.int32)
    votes, checks = [], []
    for me in (0, 1):
        cfg = base.replace(node_id=me, part_cnt=2)
        wl = get_workload(cfg)
        db = wl.load()
        vote, check, _apply = make_vote_steps(cfg, wl, be)
        vc, va, vd, lo = vote(db, be.init_state(cfg), q, active, ts)
        votes.append((np.asarray(vc), np.asarray(va), np.asarray(lo)))
        checks.append((check, db, wl))
    # both local validations see only their half: everyone prepares yes
    for vc, va, _ in votes:
        assert vc.all() and not va.any()
    # server-side combine: AND votes, MAX bounds, verify, OR the aborts
    commit_g = votes[0][0] & votes[1][0]
    glo = np.maximum(votes[0][2], votes[1][2])
    order = glo.astype(np.int64) * 2 + np.arange(2)
    ab = np.zeros(2, bool)
    for check, db, _wl in checks:
        ab |= np.asarray(check(db, q, jnp.asarray(commit_g), ts,
                               jnp.asarray(order.astype(np.int32))))
    commit_g &= ~ab
    assert ab.sum() == 1 and commit_g.sum() == 1


def _drive_overlap_run(tmp_path, overlap: bool) -> dict:
    """One deterministic single-server cluster run (+ 1 replica, with the
    test posing as the client): every query batch is delivered BEFORE the
    INIT_DONE barrier (per-link FIFO puts them all in the server's
    pending queue ahead of epoch 0) and warmup/done are zero, so the
    measure/stop epochs pin to the 3C group boundary — admission, epochs
    and verdicts are a pure function of the config, which is what makes
    the overlap-on and overlap-off runs byte-comparable."""
    import os
    import threading
    import time as _time
    import uuid

    import jax
    jax.config.update("jax_platforms", "cpu")
    from deneva_tpu.runtime import wire
    from deneva_tpu.runtime.logger import state_digest
    from deneva_tpu.runtime.native import NativeTransport, ipc_endpoints
    from deneva_tpu.runtime.replica import ReplicaNode
    from deneva_tpu.runtime.server import ServerNode
    from deneva_tpu.workloads import get_workload

    log_dir = str(tmp_path / f"logs_overlap_{overlap}")
    cfg = small_cfg(node_cnt=1, client_node_cnt=1, cc_alg=CCAlg.NO_WAIT,
                    zipf_theta=0.9, synth_table_size=512, epoch_batch=64,
                    pipeline_epochs=2, pipeline_groups=2, logging=True,
                    replica_cnt=1, log_dir=log_dir, warmup_secs=0.0,
                    done_secs=0.0,
                    host_overlap="on" if overlap else "off",
                    # arm the thread-ownership runtime asserts on BOTH
                    # sides: with overlap on, the wire/retire workers run
                    # for real against the guards (any staged-work
                    # mutation of dispatch-owned state raises), and the
                    # on==off byte-compare doubles as proof the guards
                    # themselves change nothing
                    owner_check=True)
    eps = ipc_endpoints(3, uuid.uuid4().hex[:8])
    wl = get_workload(cfg)
    batches = []
    for s in range(4):          # 256 txns, distinct tag ranges
        q = wl.generate(jax.random.PRNGKey(100 + s), 64)
        k, t, sc = wl.to_wire(q)
        batches.append((np.arange(64, dtype=np.int64) + 64 * s, k, t, sc))

    out: dict = {}

    def run_server():
        node = ServerNode(cfg.replace(node_id=0, part_cnt=1), eps, "cpu")
        try:
            assert node._overlap == (overlap and True)
            node.run()
            out["digest"] = state_digest(node.db)
            out["commits"] = int(jax.device_get(
                node.dev_stats["total_txn_commit_cnt"]))
        except Exception as e:      # surface instead of hanging the test
            out["err"] = repr(e)
        finally:
            node.close()

    def run_replica():
        node = ReplicaNode(cfg.replace(node_id=2, part_cnt=1), eps)
        try:
            node.run()
        finally:
            node.close()

    ts_srv = threading.Thread(target=run_server)
    ts_rep = threading.Thread(target=run_replica)
    ts_srv.start()
    ts_rep.start()
    cl = NativeTransport(1, eps, 3)
    cl.start()
    acked: list[int] = []
    try:
        for tags, k, t, sc in batches:
            cl.sendv(0, "CL_QRY_BATCH", wire.qry_block_parts(tags, k, t, sc))
        cl.flush()

        def on_other(src, rtype, payload):
            if rtype == "CL_RSP":
                acked.extend(wire.decode_cl_rsp(payload).tolist())

        wire.run_barrier(cl, 1, 3, on_other, "overlap-test client", 300.0)
        t0 = _time.monotonic()
        stopped = False
        while not stopped and _time.monotonic() - t0 < 300:
            m = cl.recv(50_000)
            if m is None:
                continue
            if m[1] == "CL_RSP":
                acked.extend(wire.decode_cl_rsp(m[2]).tolist())
            elif m[1] == "SHUTDOWN":
                stopped = True
        assert stopped, "server never announced SHUTDOWN"
    finally:
        ts_srv.join(timeout=300)
        ts_rep.join(timeout=60)
        cl.close()
    assert "err" not in out, out["err"]
    with open(os.path.join(log_dir, "node0.log.bin"), "rb") as f:
        out["log"] = f.read()
    with open(os.path.join(log_dir, "replica2.log.bin"), "rb") as f:
        out["rlog"] = f.read()
    out["acked"] = sorted(acked)
    return out


def test_host_overlap_bit_identical(tmp_path):
    """The host-path pipeline acceptance bar: host_overlap=off (the
    pre-pipeline serial loop) and =on (staged wire/retire workers,
    zero-copy assembly) must produce bit-identical command logs,
    byte-identical replica logs, identical replayed-state digests and
    the same acked-tag multiset — under a backend that aborts and
    retries (NO_WAIT at zipf 0.9), so the retirement->admission feedback
    path is exercised, not just the happy path."""
    on = _drive_overlap_run(tmp_path, True)
    off = _drive_overlap_run(tmp_path, False)
    assert len(on["log"]) > 0
    assert on["log"] == off["log"]
    assert on["rlog"] == off["rlog"]
    # replica stream is a byte prefix of the primary's log by construction
    assert on["rlog"] == on["log"][:len(on["rlog"])] and len(on["rlog"])
    assert on["digest"] == off["digest"]
    assert on["commits"] == off["commits"] > 0
    assert on["acked"] == off["acked"] and len(on["acked"]) > 0


@pytest.mark.slow
def test_cluster_merged_protocol_still_available():
    """--dist_protocol=merged forces the round-1 replicated-validation
    mode for a non-deterministic backend (the semantics-only comparison
    point next to VOTE's distributed behavior)."""
    cfg = small_cfg(node_cnt=2, client_node_cnt=1, cc_alg=CCAlg.OCC,
                    dist_protocol="merged")
    out = boot(cfg)
    s0 = parse_summary(out[0][1])
    s1 = parse_summary(out[1][1])
    assert s0["total_txn_commit_cnt"] == s1["total_txn_commit_cnt"] > 0
