"""Engine end-to-end: pool plumbing, counters, determinism."""

import numpy as np
import jax
import pytest

from deneva_tpu.config import Config
from deneva_tpu.engine import Engine
from deneva_tpu.workloads import get_workload


def small_cfg(**kw):
    base = dict(epoch_batch=64, conflict_buckets=1024, max_accesses=4,
                req_per_query=4, synth_table_size=4096, zipf_theta=0.6,
                max_txn_in_flight=256, warmup_secs=0.0, done_secs=0.2)
    base.update(kw)
    return Config(**base)


def run_epochs(cfg, n=30, seed=0):
    eng = Engine(cfg, get_workload(cfg))
    state = eng.init_state(seed)
    state = eng.jit_run(state, n)
    return {k: np.asarray(v) for k, v in jax.device_get(state.stats).items()}, \
        jax.device_get(state.pool)


@pytest.mark.parametrize("alg", ["NOCC", "NO_WAIT", "OCC", "WAIT_DIE",
                                 "TIMESTAMP", "MVCC", "MAAT", "CALVIN",
                                 "TPU_BATCH"])
def test_engine_counters_consistent(alg):
    cfg = small_cfg(cc_alg=alg)
    stats, pool = run_epochs(cfg)
    commit = int(stats["total_txn_commit_cnt"])
    abort = int(stats["total_txn_abort_cnt"])
    admitted = int(stats["admitted_cnt"])
    inflight = int(np.asarray(pool.occupied).sum())
    assert commit > 0
    assert admitted <= int(stats["generated_cnt"])
    # conservation: every admitted txn is committed or still in the pool
    assert commit + inflight == admitted
    if alg in ("CALVIN", "TPU_BATCH"):
        assert abort == 0
    assert int(stats["latency_hist"].sum()) == commit


@pytest.mark.parametrize("alg", ["CALVIN", "TPU_BATCH"])
def test_forwarding_full_commit_under_extreme_skew(alg):
    # VERDICT r3 next #3: round-2's CALVIN collapsed at theta=0.9 (4.8k
    # txn/s — the level budget denied hot-key chains the reference's
    # scheduler simply grinds serially).  forward=True makes the
    # forwarding executor the closed form of RFWD: on blind-write YCSB
    # the WHOLE batch commits regardless of chain depth — zero aborts,
    # zero defers, even under extreme skew, at engine level.
    cfg = small_cfg(cc_alg=alg, zipf_theta=0.9)
    stats, pool = run_epochs(cfg, n=20)
    assert int(stats["total_txn_commit_cnt"]) > 0
    assert int(stats["total_txn_abort_cnt"]) == 0
    assert int(stats["defer_cnt"]) == 0
    inflight = int(np.asarray(pool.occupied).sum())
    assert int(stats["total_txn_commit_cnt"]) + inflight \
        == int(stats["admitted_cnt"])


def test_pool_defer_budget_counter():
    # defer_cnt: +1 per deferred epoch, reset by abort (a restart opens a
    # fresh wait budget) and by admission — the defer_rounds_max backstop
    # (engine/step.py) keys off this counter, not txn age (a txn that
    # waited out a long backoff must still be allowed to defer)
    import jax.numpy as jnp
    from deneva_tpu.engine.pool import TxnPool

    pool_mgr = TxnPool(capacity=4, batch=4, gen_chunk=4, backoff=False)
    q = {"k": jnp.zeros((4, 2), jnp.int32)}
    pool = pool_mgr.create(q)
    pool, _ = pool_mgr.refill(pool, q, jnp.int32(0))
    slots = jnp.arange(4, dtype=jnp.int32)
    active = jnp.ones(4, bool)
    no = jnp.zeros(4, bool)
    defer_all = pool_mgr.update(pool, slots, active, no, no,
                                jnp.int32(0), True)
    assert (np.asarray(defer_all.defer_cnt) == 1).all()
    twice = pool_mgr.update(defer_all, slots, active, no, no,
                            jnp.int32(1), True)
    assert (np.asarray(twice.defer_cnt) == 2).all()
    aborted = pool_mgr.update(twice, slots, active, no,
                              jnp.ones(4, bool), jnp.int32(2), True)
    assert (np.asarray(aborted.defer_cnt) == 0).all()


@pytest.mark.parametrize("alg", ["TPU_BATCH", "OCC"])
def test_sim_full_row_matches_fingerprint_decisions(alg):
    """SIM_FULL_ROW (reference storage/row.cpp:30): real payload bytes
    move through gathers/scatters — CC decisions and counters must be
    identical to fingerprint mode (validation never looks at payloads);
    only the byte-level read checksum differs."""
    cfg = small_cfg(cc_alg=alg, sim_full_row=True, tup_size=20,
                    field_per_tuple=4)
    s_full, _ = run_epochs(cfg, n=20, seed=3)
    s_fp, _ = run_epochs(cfg.replace(sim_full_row=False), n=20, seed=3)
    for k in s_full:
        if k != "read_checksum":
            assert (s_full[k] == s_fp[k]).all(), k
    assert int(s_full["read_checksum"]) != 0
    # determinism across runs (forwarded byte values are pure functions)
    s_full2, _ = run_epochs(cfg, n=20, seed=3)
    assert int(s_full2["read_checksum"]) == int(s_full["read_checksum"])


def test_unique_abort_count_exact():
    """`unique_txn_abort_cnt` counts each txn's FIRST abort exactly
    (reference stats.h:60-61): bounded by total aborts AND by the number
    of txns that ever entered the pool (a retrying txn re-aborts without
    re-counting — under high contention total aborts far exceed uniques)."""
    cfg = small_cfg(cc_alg="OCC", zipf_theta=0.9, synth_table_size=512)
    stats, pool = run_epochs(cfg, n=40)
    total = int(stats["total_txn_abort_cnt"])
    unique = int(stats["unique_txn_abort_cnt"])
    admitted = int(stats["admitted_cnt"])
    assert 0 < unique <= total
    assert unique <= admitted
    # at zipf .9 on 512 rows retries dominate: uniques strictly below total
    assert unique < total


def test_engine_deterministic():
    cfg = small_cfg(cc_alg="TPU_BATCH")
    s1, _ = run_epochs(cfg, seed=7)
    s2, _ = run_epochs(cfg, seed=7)
    for k in s1:
        assert (s1[k] == s2[k]).all(), k

def test_engine_seeds_differ():
    cfg = small_cfg(cc_alg="OCC")
    s1, _ = run_epochs(cfg, seed=1)
    s2, _ = run_epochs(cfg, seed=2)
    assert int(s1["read_checksum"]) != int(s2["read_checksum"])


def test_contention_lowers_commits():
    lo, _ = run_epochs(small_cfg(cc_alg="NO_WAIT", zipf_theta=0.0))
    hi, _ = run_epochs(small_cfg(cc_alg="NO_WAIT", zipf_theta=0.95,
                                 synth_table_size=256))
    lo_rate = int(lo["total_txn_commit_cnt"])
    hi_rate = int(hi["total_txn_commit_cnt"])
    assert hi_rate < lo_rate
    assert int(hi["total_txn_abort_cnt"]) > int(lo["total_txn_abort_cnt"])


def test_nocc_mode_oracle_beats_cc():
    occ, _ = run_epochs(small_cfg(cc_alg="OCC", zipf_theta=0.9,
                                  synth_table_size=256))
    nocc, _ = run_epochs(small_cfg(cc_alg="NOCC", zipf_theta=0.9,
                                   synth_table_size=256))
    assert int(nocc["total_txn_commit_cnt"]) >= int(occ["total_txn_commit_cnt"])
    assert int(nocc["total_txn_abort_cnt"]) == 0


def test_forwarding_executor_equals_serial_execution():
    """TPU_BATCH's single-pass forwarding executor must produce exactly
    the read values and final table state of serial execution in rank
    order (the property that makes commit-everything serializable)."""
    import jax.numpy as jnp
    from deneva_tpu.ops import forward_plan
    from deneva_tpu.workloads.ycsb import (YCSBQuery, YCSBWorkload,
                                           _field_fingerprint)

    cfg = small_cfg(cc_alg="TPU_BATCH", synth_table_size=32,
                    req_per_query=4, max_accesses=4, epoch_batch=16)
    wl = YCSBWorkload(cfg)
    db = wl.load()
    rng = np.random.default_rng(5)
    B, R = 16, 4
    keys = rng.integers(0, 8, (B, R)).astype(np.int32)  # heavy contention
    is_w = rng.random((B, R)) < 0.5
    q = YCSBQuery(keys=jnp.asarray(keys), is_write=jnp.asarray(is_w))
    rank = np.arange(B, dtype=np.int32)
    order = jnp.asarray(rank)
    fwd = forward_plan(q.keys, order, q.is_write, jnp.ones((B, R), bool))
    stats = {"read_checksum": jnp.zeros((), jnp.uint32),
             "write_cnt": jnp.zeros((), jnp.uint32)}
    db2 = wl.execute(dict(db), q, None, order, stats, fwd_rank=fwd)
    got_sum = int(stats["read_checksum"])
    got_f0 = np.asarray(db2["MAIN_TABLE"].columns["F0"])[:32]

    # serial reference in rank order (checksum mod 2^32, accumulated in
    # a Python int to avoid numpy overflow warnings)
    f0 = np.asarray(db["MAIN_TABLE"].columns["F0"])[:32].copy()
    sum_ref = 0
    for i in range(B):
        for r in range(R):       # reads first (serial txn semantics)
            if not is_w[i, r]:
                sum_ref = (sum_ref + int(f0[keys[i, r]])) & 0xFFFFFFFF
        for r in range(R):
            if is_w[i, r]:
                f0[keys[i, r]] = np.asarray(
                    _field_fingerprint(keys[i, r], rank[i]))
    assert got_sum == sum_ref
    assert (got_f0 == f0).all()


@pytest.mark.parametrize("alg", ["TPU_BATCH", "NO_WAIT", "OCC"])
def test_full_pool_epoch_mode(alg):
    """epoch_batch == max_txn_in_flight flips the pool to dense
    (indexing-free) refill/select/update; every invariant of the normal
    path must hold, including abort backoff (NO_WAIT/OCC abort on
    conflict; the sentinel mode exercises forced completions)."""
    cfg = small_cfg(cc_alg=alg, epoch_batch=256, max_txn_in_flight=256,
                    zipf_theta=0.9, synth_table_size=256)
    stats, pool = run_epochs(cfg)
    commit = int(stats["total_txn_commit_cnt"])
    admitted = int(stats["admitted_cnt"])
    inflight = int(np.asarray(pool.occupied).sum())
    assert commit > 0
    assert commit + inflight == admitted
    assert int(stats["latency_hist"].sum()) == commit
    if alg != "TPU_BATCH":
        assert int(stats["total_txn_abort_cnt"]) > 0   # contention bites
    # determinism across runs
    s2, _ = run_epochs(cfg)
    for k in stats:
        assert (stats[k] == s2[k]).all(), k


def test_full_pool_serial_shadow():
    """Full-pool TPU_BATCH epochs must be bit-identical to a host-side
    serial shadow: replay generation + dense admission + serial
    execution in seq order in numpy, and compare read checksum, commit
    count, and the entire table after every epoch.  Any mis-stamped seq,
    stale query, or forwarding divergence in the dense pool paths shows
    up as a checksum or table mismatch."""
    import jax.numpy as jnp
    from deneva_tpu.workloads.ycsb import _field_fingerprint

    cfg = small_cfg(cc_alg="TPU_BATCH", epoch_batch=64,
                    max_txn_in_flight=64, req_per_query=4, max_accesses=4,
                    zipf_theta=0.9, synth_table_size=64)
    wl = get_workload(cfg)
    eng = Engine(cfg, wl)
    assert eng.pool.full_pool
    state = eng.init_state(9)
    stepf = jax.jit(eng.step)

    P, R, N = 64, 4, 64
    shadow = np.asarray(state.db["MAIN_TABLE"].columns["F0"])[:N].copy()
    sh_keys = np.zeros((P, R), np.int32)
    sh_w = np.zeros((P, R), bool)
    sh_seq = np.zeros(P, np.int64)
    occupied = np.zeros(P, bool)
    next_seq, checksum, commits = 1, 0, 0
    rng = jax.device_get(state.rng)

    def fp(key, ver):
        return int(np.asarray(_field_fingerprint(jnp.int32(key),
                                                 jnp.int32(ver))))

    for _ in range(3):
        gen_key = jax.random.split(jnp.asarray(rng))[1]
        newq = jax.device_get(wl.generate(gen_key, P))
        free = ~occupied
        sh_keys[free] = np.asarray(newq.keys)[free]
        sh_w[free] = np.asarray(newq.is_write)[free]
        sh_seq[free] = next_seq + np.flatnonzero(free)
        occupied[:] = True
        next_seq += 2 * P
        for s in np.argsort(sh_seq):          # serial, in rank order
            for r in range(R):
                if not sh_w[s, r]:
                    checksum = (checksum + int(shadow[sh_keys[s, r]])) \
                        & 0xFFFFFFFF
            for r in range(R):
                if sh_w[s, r]:
                    shadow[sh_keys[s, r]] = fp(sh_keys[s, r], sh_seq[s])
        commits += P
        occupied[:] = False                   # everything committed

        state = stepf(state)
        rng = jax.device_get(state.rng)
        assert int(state.stats["total_txn_commit_cnt"]) == commits
        assert int(state.stats["read_checksum"]) == checksum
        got = np.asarray(state.db["MAIN_TABLE"].columns["F0"])[:N]
        assert (got == shadow).all()


def test_full_pool_forced_abort_conservation():
    """YCSB_ABORT_MODE under full-pool: forced txns complete-as-aborted
    and release their slot, so commits + forced + inflight == admitted."""
    cfg = small_cfg(cc_alg="TPU_BATCH", epoch_batch=256,
                    max_txn_in_flight=256, zipf_theta=0.9,
                    synth_table_size=64, ycsb_abort_mode=True)
    stats, pool = run_epochs(cfg)
    assert int(stats["total_txn_abort_cnt"]) > 0
    assert int(stats["total_txn_commit_cnt"]) > 0
    commit = int(stats["total_txn_commit_cnt"])
    forced = int(stats["total_txn_abort_cnt"])
    inflight = int(np.asarray(pool.occupied).sum())
    assert commit + forced + inflight == int(stats["admitted_cnt"])


def test_ycsb_hot_skew_and_txn_read_only():
    """HOT skew method + TXN_WRITE_PERC + KEY_ORDER generator parity
    (reference ycsb_query.cpp:205-260, config.h:106,162-171)."""
    from deneva_tpu.workloads.ycsb import YCSBWorkload

    cfg = small_cfg(synth_table_size=4096, req_per_query=4, max_accesses=4,
                    skew_method="HOT", data_perc=16, access_perc=0.5,
                    txn_write_perc=0.25, key_order=True)
    wl = YCSBWorkload(cfg)
    q = wl.generate(jax.random.PRNGKey(7), 2048)
    keys = np.asarray(q.keys)
    is_w = np.asarray(q.is_write)
    # ~half the accesses land on the 16-key hot set
    assert abs((keys < 16).mean() - 0.5) < 0.05
    # KEY_ORDER: ascending within each txn
    assert (np.diff(keys, axis=1) >= 0).all()
    # ~75% of txns are entirely read-only; write rows still mix per tuple
    ro_frac = (~is_w.any(axis=1)).mean()
    assert 0.65 < ro_frac < 0.85
    # HOT mode runs end-to-end through the engine
    stats, _ = run_epochs(cfg, n=10)
    assert int(stats["total_txn_commit_cnt"]) > 0


def test_btree_index_struct_equals_hash_results():
    """INDEX_STRUCT=IDX_BTREE (global.h:320-324) swaps the primary probe
    to the ordered index; same key->slot map, so every counter — including
    the read checksum over actual gathered values — must be identical."""
    a, _ = run_epochs(small_cfg(index_struct="IDX_HASH"), n=15, seed=4)
    b, _ = run_epochs(small_cfg(index_struct="IDX_BTREE"), n=15, seed=4)
    for k in ("total_txn_commit_cnt", "total_txn_abort_cnt",
              "read_checksum", "write_cnt"):
        assert a[k] == b[k], k


def test_ycsb_abort_mode_forces_deterministic_aborts():
    """YCSB_ABORT_MODE (reference config.h:103): sentinel key 0 forces
    logical aborts, exercising abort/backoff deterministically even for
    backends that never abort on conflicts."""
    cfg = small_cfg(cc_alg="TPU_BATCH", synth_table_size=64,
                    zipf_theta=0.9, ycsb_abort_mode=True)
    stats, pool = run_epochs(cfg)
    assert int(stats["total_txn_abort_cnt"]) > 0   # TPU_BATCH never aborts otherwise
    # forced txns abort ONCE and release their slot (no immortal
    # retries), so commits keep flowing alongside the forced aborts
    assert int(stats["total_txn_commit_cnt"]) > 0
    # determinism preserved
    s2, _ = run_epochs(cfg)
    assert int(s2["total_txn_abort_cnt"]) == int(stats["total_txn_abort_cnt"])


def test_per_type_counters_partition_totals():
    """commit_by_type / abort_by_type partition the totals exactly
    (reference Stats_thd per-txn-kind counter families)."""
    cfg = small_cfg(cc_alg="OCC", zipf_theta=0.9, synth_table_size=512,
                    txn_write_perc=0.7)
    stats, _ = run_epochs(cfg, n=25)
    assert stats["commit_by_type"].shape == (2,)   # ycsb_ro, ycsb_rw
    assert stats["commit_by_type"].sum() == stats["total_txn_commit_cnt"]
    assert stats["abort_by_type"].sum() == stats["total_txn_abort_cnt"]
    # read-only txns exist at txn_write_perc<1 and never abort under OCC's
    # reader-first sweep at rank order... they CAN abort (reader later);
    # just require both types to have committed
    assert (stats["commit_by_type"] > 0).all()
