"""PPS workload: loader, mix distribution, recon-path correctness
(planned part accesses must equal the snapshot USES mapping), and
PART_AMOUNT accounting across ORDERPRODUCT/UPDATEPART."""

import numpy as np
import jax
import pytest

from deneva_tpu.config import CCAlg, Config, WorkloadKind
from deneva_tpu.engine import Engine
from deneva_tpu.workloads import get_workload
from deneva_tpu.workloads.pps import (
    GETPARTBYPRODUCT, ORDERPRODUCT, TID, UPDATEPART, UPDATEPRODUCTPART)


def pps_cfg(**kw):
    base = dict(workload=WorkloadKind.PPS, pps_parts_cnt=500,
                pps_products_cnt=100, pps_suppliers_cnt=100, pps_parts_per=4,
                max_accesses=9, epoch_batch=64, conflict_buckets=1024,
                max_txn_in_flight=256, warmup_secs=0.0, done_secs=0.2)
    base.update(kw)
    if "cc_alg" in base:
        base["cc_alg"] = CCAlg(base["cc_alg"])
    return Config(**base)


def test_loader_and_mapping():
    cfg = pps_cfg()
    wl = get_workload(cfg)
    db = wl.load()
    assert set(db) == {"PARTS", "PRODUCTS", "SUPPLIERS", "USES", "SUPPLIES"}
    assert int(db["USES"].row_cnt) == 100 * 4
    pk = db["USES"].host_column("PART_KEY")
    assert pk.min() >= 0 and pk.max() < 500
    assert (db["PARTS"].host_column("PART_AMOUNT") == 10000).all()


def test_mix_distribution():
    cfg = pps_cfg(perc_getpartbyproduct=0.5, perc_orderproduct=0.25,
                  perc_updateproductpart=0.25, perc_updatepart=0.0)
    wl = get_workload(cfg)
    q = jax.device_get(wl.generate(jax.random.PRNGKey(1), 8192))
    frac = np.bincount(q.txn_type, minlength=8) / 8192
    assert abs(frac[GETPARTBYPRODUCT] - 0.5) < 0.05
    assert abs(frac[ORDERPRODUCT] - 0.25) < 0.04
    assert abs(frac[UPDATEPRODUCTPART] - 0.25) < 0.04
    assert frac[UPDATEPART] == 0


def test_recon_plan_matches_snapshot():
    """plan() must declare exactly the part rows the USES snapshot maps:
    the reference's sequencer recon-restart (system/sequencer.cpp:88-115)
    collapsed into one gather."""
    cfg = pps_cfg()
    wl = get_workload(cfg)
    db = wl.load()
    q = wl.generate(jax.random.PRNGKey(2), 64)
    p = jax.device_get(wl.plan(db, q))
    qh = jax.device_get(q)
    uses = db["USES"].host_column("PART_KEY")
    per = cfg.pps_parts_per
    for i in np.where(qh.txn_type == GETPARTBYPRODUCT)[0]:
        want = uses[qh.product_key[i] * per:(qh.product_key[i] + 1) * per]
        got = p["keys"][i, 1 + per:1 + 2 * per]
        np.testing.assert_array_equal(np.sort(got), np.sort(want))
        assert p["table_ids"][i, 1 + per] == TID["PARTS"]
        assert not p["is_write"][i, 1 + per:1 + 2 * per].any()
    for i in np.where(qh.txn_type == ORDERPRODUCT)[0]:
        assert p["is_write"][i, 1 + per:1 + 2 * per].all()


@pytest.mark.parametrize("alg", ["NOCC", "OCC", "TPU_BATCH", "CALVIN"])
def test_pps_runs_and_commits(alg):
    cfg = pps_cfg(cc_alg=alg)
    eng = Engine(cfg, get_workload(cfg))
    state = eng.init_state(0)
    state = eng.jit_run(state, 25)
    stats = jax.device_get(state.stats)
    assert int(stats["total_txn_commit_cnt"]) > 0


def _amount_delta(cfg, epochs=20):
    wl = get_workload(cfg)
    eng = Engine(cfg, wl)
    state = eng.init_state(3)
    a0 = wl.load()["PARTS"].host_column("PART_AMOUNT").astype(np.int64).sum()
    state = eng.jit_run(state, epochs)
    st = jax.device_get(state)
    a1 = np.asarray(st.db["PARTS"].columns["PART_AMOUNT"])[
        :cfg.pps_parts_cnt].astype(np.int64).sum()
    return a1 - a0, int(st.stats["total_txn_commit_cnt"])


def test_escrow_adds_do_not_chain():
    """UPDATEPART / ORDERPRODUCT part updates are order_free escrow
    adds: a pure-add mix must commit (nearly) everything per epoch no
    matter how hot the part rows — add-add pairs carry no conflict
    edges (build_incidence uo) — while the exact accounting above
    guarantees the adds still all land."""
    import jax
    from deneva_tpu.engine import Engine
    from deneva_tpu.workloads import get_workload

    cfg = pps_cfg(cc_alg="TPU_BATCH", pps_parts_cnt=50,
                  perc_getpartbyproduct=0.0, perc_orderproduct=0.5,
                  perc_updateproductpart=0.0, perc_updatepart=0.5)
    eng = Engine(cfg, get_workload(cfg))
    state = eng.jit_run(eng.init_state(1), 25)
    stats = jax.device_get(state.stats)
    commits = int(stats["total_txn_commit_cnt"])
    defers = int(stats["defer_cnt"])
    assert commits > 0
    # GETPART anchors (the remaining ordered reads in this mix) are a
    # small fraction; without the exemption this config defers ~90%
    assert defers < max(commits // 5, 10), (commits, defers)


@pytest.mark.slow
@pytest.mark.parametrize("alg", ["TPU_BATCH", "MVCC"])
def test_part_amount_accounting(alg):
    """Exact accounting per txn type (pure mixes so the audit is exact):
    UPDATEPART adds 100/commit; ORDERPRODUCT subtracts parts_per/commit.
    MVCC included: committed write VALUES must land exactly (the write
    half of MVCC value fidelity, VERDICT r3 next #7)."""
    delta, commits = _amount_delta(pps_cfg(
        cc_alg=alg, perc_getpartbyproduct=0.0, perc_orderproduct=0.0,
        perc_updateproductpart=0.0, perc_updatepart=1.0))
    assert commits > 0 and delta == 100 * commits

    delta, commits = _amount_delta(pps_cfg(
        cc_alg=alg, perc_getpartbyproduct=0.0, perc_orderproduct=1.0,
        perc_updateproductpart=0.0, perc_updatepart=0.0))
    assert commits > 0 and delta == -4 * commits


def test_mvcc_getpart_reads_snapshot_values():
    """MVCC value fidelity for PPS reads (VERDICT r3 next #7): a
    read-only GETPART serializes AT the epoch snapshot, so after
    committed UPDATEPART escrow adds its gathered PART_AMOUNT must be
    the post-update value byte-for-byte — reconstructed exactly by
    regenerating the epoch's query stream and reading the snapshot
    table on the host.  One stale or garbled gather breaks equality."""
    import dataclasses

    import jax

    # phase 1: pure-update MVCC run mutates PART_AMOUNT
    cfg_u = pps_cfg(cc_alg="MVCC", perc_getpartbyproduct=0.0,
                    perc_orderproduct=0.0, perc_updateproductpart=0.0,
                    perc_updatepart=1.0)
    eng_u = Engine(cfg_u, get_workload(cfg_u))
    s_u = eng_u.jit_run(eng_u.init_state(2), 10)
    amt = np.asarray(jax.device_get(
        s_u.db["PARTS"].columns["PART_AMOUNT"]))[:cfg_u.pps_parts_cnt]
    assert (amt != 10000).any(), "phase 1 must mutate the table"

    # phase 2: one full-pool pure-GETPART epoch against the mutated db
    cfg_r = pps_cfg(cc_alg="MVCC", epoch_batch=64, max_txn_in_flight=64,
                    perc_getparts=1.0, perc_getpartbyproduct=0.0,
                    perc_orderproduct=0.0, perc_updateproductpart=0.0,
                    perc_updatepart=0.0)
    wl_r = get_workload(cfg_r)
    eng_r = Engine(cfg_r, wl_r)
    s0 = eng_r.init_state(5)
    # regenerate the epoch's admissions exactly like Engine.step (the
    # rng split) BEFORE the step donates the state buffers
    gen_key = jax.random.split(s0.rng)[1]
    q = jax.device_get(wl_r.generate(gen_key, eng_r.pool.g))
    s0 = dataclasses.replace(s0, db=s_u.db)
    s1 = eng_r.jit_step(s0)
    got = int(jax.device_get(s1.stats["read_checksum"]))

    keys = np.asarray(q.part_key)
    ref = int(amt[keys].astype(np.int64).sum()) & 0xFFFFFFFF
    assert got == ref
