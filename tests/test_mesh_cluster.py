"""Mesh-sharded measured cluster path (pod-scale PR): `device_parts=8`
through the REAL server loop — transport, admission, epoch groups,
verdict planes, CL_RSP acks, command log, replica stream — must be
bit-identical to `device_parts=1` on the same config, per backend.

conftest.py forces an 8-way fake-device CPU mesh
(`--xla_force_host_platform_device_count=8`), so these run in tier-1.
The engine-level bit-identity of `workloads/mc.py` is test_parallel's
job; here the oracle is the full cluster surface: the bytes a client
or replica could observe, plus digest-vs-replay of the sharded state
through the same mesh-wrapped per-epoch jit recovery uses.
"""

import os
import threading
import time as _time
import uuid

import numpy as np
import pytest

from deneva_tpu.config import CCAlg, Config, WorkloadKind


def _mesh_cfg(log_dir: str, device_parts: int, **kw) -> Config:
    base = dict(
        workload=WorkloadKind.YCSB, cc_alg=CCAlg.TPU_BATCH,
        node_cnt=1, client_node_cnt=1, epoch_batch=64,
        conflict_buckets=512, synth_table_size=512, req_per_query=4,
        max_accesses=4, max_txn_in_flight=1024, zipf_theta=0.9,
        pipeline_epochs=2, pipeline_groups=2, logging=True,
        log_dir=log_dir, warmup_secs=0.0, done_secs=0.0,
        device_parts=device_parts, owner_check=True)
    base.update(kw)
    return Config(**base)


def _drive_mesh_run(tmp_path, device_parts: int, replica: bool = True,
                    **kw) -> dict:
    """One deterministic single-server cluster run with the test posing
    as the client (the `_drive_overlap_run` rig from test_runtime.py):
    all query batches are delivered BEFORE the INIT_DONE barrier and
    warmup/done are zero, so admission, epochs and verdicts are a pure
    function of the config — which is what makes the device_parts=1 and
    =8 runs byte-comparable."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deneva_tpu.runtime import wire
    from deneva_tpu.runtime.logger import state_digest
    from deneva_tpu.runtime.native import NativeTransport, ipc_endpoints
    from deneva_tpu.runtime.replica import ReplicaNode
    from deneva_tpu.runtime.server import ServerNode
    from deneva_tpu.workloads import get_workload

    log_dir = str(tmp_path / f"logs_mesh_{device_parts}")
    n_nodes = 3 if replica else 2
    cfg = _mesh_cfg(log_dir, device_parts,
                    replica_cnt=1 if replica else 0, **kw)
    eps = ipc_endpoints(n_nodes, uuid.uuid4().hex[:8])
    wl = get_workload(cfg)
    batches = []
    for s in range(3):          # 192 txns, distinct tag ranges
        q = wl.generate(jax.random.PRNGKey(100 + s), 64)
        k, t, sc = wl.to_wire(q)
        batches.append((np.arange(64, dtype=np.int64) + 64 * s, k, t, sc))

    out: dict = {}

    def run_server():
        node = ServerNode(cfg.replace(node_id=0, part_cnt=1), eps, "cpu")
        try:
            assert (node.mesh is not None) == (device_parts > 1)
            node.run()
            out["digest"] = state_digest(node.db)
            out["commits"] = int(jax.device_get(
                node.dev_stats["total_txn_commit_cnt"]))
            out["aborts"] = int(jax.device_get(
                node.dev_stats["total_txn_abort_cnt"]))
            out["prefetch"] = (node._prefetch_hits, node._prefetch_polls)
        except Exception as e:      # surface instead of hanging the test
            out["err"] = repr(e)
        finally:
            node.close()

    def run_replica():
        node = ReplicaNode(cfg.replace(node_id=2, part_cnt=1), eps)
        try:
            node.run()
        finally:
            node.close()

    ts_srv = threading.Thread(target=run_server)
    ts_srv.start()
    ts_rep = None
    if replica:
        ts_rep = threading.Thread(target=run_replica)
        ts_rep.start()
    cl = NativeTransport(1, eps, n_nodes)
    cl.start()
    acked: list[int] = []
    try:
        for tags, k, t, sc in batches:
            cl.sendv(0, "CL_QRY_BATCH", wire.qry_block_parts(tags, k, t, sc))
        cl.flush()

        def on_other(src, rtype, payload):
            if rtype == "CL_RSP":
                acked.extend(wire.decode_cl_rsp(payload).tolist())

        wire.run_barrier(cl, 1, n_nodes, on_other, "mesh-test client",
                         300.0)
        t0 = _time.monotonic()
        stopped = False
        while not stopped and _time.monotonic() - t0 < 300:
            m = cl.recv(50_000)
            if m is None:
                continue
            if m[1] == "CL_RSP":
                acked.extend(wire.decode_cl_rsp(m[2]).tolist())
            elif m[1] == "SHUTDOWN":
                stopped = True
        assert stopped, "server never announced SHUTDOWN"
    finally:
        ts_srv.join(timeout=300)
        if ts_rep is not None:
            ts_rep.join(timeout=60)
        cl.close()
    assert "err" not in out, out["err"]
    with open(os.path.join(log_dir, "node0.log.bin"), "rb") as f:
        out["log"] = f.read()
    if replica:
        with open(os.path.join(log_dir, "replica2.log.bin"), "rb") as f:
            out["rlog"] = f.read()
    out["acked"] = sorted(acked)
    out["cfg"] = cfg.replace(node_id=0, part_cnt=1)
    out["log_path"] = os.path.join(log_dir, "node0.log.bin")
    return out


def _replay_digest(run: dict) -> str:
    """Digest-vs-replay half of the oracle: re-execute the command log
    through the mesh-wrapped per-epoch jit (exactly what crash recovery
    does) into fresh sharded state and hash the result."""
    import jax

    from deneva_tpu.cc import get_backend
    from deneva_tpu.engine.step import init_device_stats
    from deneva_tpu.parallel.mesh import (make_mesh, state_shardings,
                                          use_mesh)
    from deneva_tpu.runtime.logger import replay_into, state_digest
    from deneva_tpu.runtime.server import make_dist_step
    from deneva_tpu.workloads import get_workload

    cfg = run["cfg"]
    wl = get_workload(cfg)
    be = get_backend(cfg.cc_alg)
    db = wl.load()
    cc = be.init_state(cfg)
    stats = init_device_stats(
        len(getattr(wl, "txn_type_names", ("txn",))))
    step = make_dist_step(cfg, wl, be)
    if cfg.device_parts > 1:
        mesh = make_mesh(cfg.device_parts)
        state = {"db": db, "cc_state": cc, "stats": stats}
        state = jax.device_put(state, state_shardings(mesh, state))
        db, cc, stats = state["db"], state["cc_state"], state["stats"]
        inner = step

        def step(*a, **kw):
            with use_mesh(mesh):
                return inner(*a, **kw)
    db, cc, stats, last = replay_into(run["log_path"], cfg, wl, step,
                                      db, cc, stats)
    assert last >= 0, "empty command log"
    return state_digest(db)


def test_mesh_cluster_ycsb_bit_identical(tmp_path):
    """YCSB/TPU_BATCH (the forwarding executor → `wl.execute_mc` owner
    exchange): device_parts=8 through the measured cluster path must
    reproduce device_parts=1's command log, replica stream, commit
    counters and acked-tag multiset byte for byte, and the sharded
    run's state must replay bit-identically from its own log."""
    m8 = _drive_mesh_run(tmp_path, 8)
    m1 = _drive_mesh_run(tmp_path, 1)
    assert len(m8["log"]) > 0
    assert m8["log"] == m1["log"]
    assert m8["rlog"] == m1["rlog"]
    assert m8["rlog"] == m8["log"][:len(m8["rlog"])] and len(m8["rlog"])
    assert m8["commits"] == m1["commits"] > 0
    assert m8["aborts"] == m1["aborts"]
    assert m8["acked"] == m1["acked"] and len(m8["acked"]) > 0
    # the sharded tables hold the rows in the owner-major mc layout, so
    # their digest is compared against an independent mesh REPLAY of the
    # same log (the recovery path), not against the =1 layout
    assert _replay_digest(m8) == m8["digest"]
    assert _replay_digest(m1) == m1["digest"]


def test_mesh_cluster_tpcc_bit_identical(tmp_path):
    """TPC-C/NO_WAIT (the generic sweep → `workloads.mc.mc_execute`
    shard_map path, with real aborts + retry feedback): same cluster
    bit-identity bar as YCSB, warehouses as the ownership anchor."""
    kw = dict(workload=WorkloadKind.TPCC, cc_alg=CCAlg.NO_WAIT,
              num_wh=8, cust_per_dist=30, max_items=100,
              max_accesses=18, insert_table_cap=1 << 10,
              synth_table_size=4096)
    m8 = _drive_mesh_run(tmp_path, 8, replica=False, **kw)
    m1 = _drive_mesh_run(tmp_path, 1, replica=False, **kw)
    assert len(m8["log"]) > 0
    assert m8["log"] == m1["log"]
    assert m8["commits"] == m1["commits"] > 0
    assert m8["aborts"] == m1["aborts"]
    assert m8["acked"] == m1["acked"] and len(m8["acked"]) > 0
    assert _replay_digest(m8) == m8["digest"]


def test_mesh_pins_are_validated_errors():
    """The former silent `device_parts == 1` skips are config errors
    now: arming an incompatible plane on a mesh config must raise a
    named ValueError, never quietly no-op (engine/step.py drops the
    inline guards in the same PR)."""
    ok = dict(workload=WorkloadKind.YCSB, cc_alg=CCAlg.TPU_BATCH,
              epoch_batch=64, conflict_buckets=512,
              synth_table_size=512, req_per_query=4, max_accesses=4)
    Config(**ok, device_parts=8).validate()     # sane base composes
    with pytest.raises(ValueError, match="metrics"):
        Config(**ok, device_parts=8, metrics=True).validate()
    with pytest.raises(ValueError, match="VOTE"):
        Config(**{**ok, "cc_alg": CCAlg.OCC}, device_parts=8,
               dist_protocol="vote").validate()
