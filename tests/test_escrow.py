"""Escrow-commutative execution for the SWEEP backends (PR: un-floor
TPC-C hot-row throughput).

Three claim families, each tested per backend:

* **Equivalence oracle** — with the escrow exemption on, the committed
  set still satisfies TPC-C's audit invariants against a serial oracle
  on the accumulator SUMS: YTD totals grow by exactly the committed
  payment amounts (HISTORY is the committed-set record), customer
  balances conserve, and per-district o_ids are dense `[3001, next)` —
  the escrow guarantee (delta sums are order-invariant) made checkable.
* **Bit-identity off** — with the gate off (``escrow_sweep=False`` or
  ``escrow_order_free=False``) every backend's verdict is bitwise
  identical to a batch that never declared ``order_free`` at all: the
  ordered incidence views alias r/w/pr and the watermark rules take the
  pre-escrow branches.
* **Ordering semantics** — scripted interleavings: add-add pairs carry
  no edge (all commit), while an ORDERED read of the same accumulator
  still orders against every add, including cross-epoch through the
  recorded wts watermark.
"""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deneva_tpu.config import CCAlg, Config, WorkloadKind
from deneva_tpu.cc import (AccessBatch, build_conflict_incidence,
                           gate_order_free, get_backend)
from deneva_tpu.engine import Engine
from deneva_tpu.workloads import get_workload

SWEEP_ALGS = ("NO_WAIT", "WAIT_DIE", "OCC", "TIMESTAMP", "MVCC", "MAAT")


def tpcc_cfg(**kw):
    base = dict(workload=WorkloadKind.TPCC, num_wh=2, cust_per_dist=120,
                max_items=4096, max_items_per_txn=5, max_accesses=8,
                epoch_batch=64, conflict_buckets=1024,
                max_txn_in_flight=256, insert_table_cap=1 << 14,
                warmup_secs=0.0, done_secs=0.2)
    base.update(kw)
    if "cc_alg" in base:
        base["cc_alg"] = CCAlg(base["cc_alg"])
    return Config(**base)


def run_epochs(cfg, n=25, seed=0):
    eng = Engine(cfg, get_workload(cfg))
    state = eng.jit_run(eng.init_state(seed), n)
    return jax.device_get(state)


def _audit(cfg, state, d0):
    """TPC-C serial-oracle audit on accumulator sums + o_id density."""
    d1 = state.db
    h = d1["HISTORY"]
    n_hist = int(h.row_cnt)
    assert n_hist < cfg.insert_table_cap, "ring wrapped; test invalid"
    paid = np.asarray(h.columns["H_AMOUNT"])[:n_hist].sum()
    col = lambda d, t, c: d[t].host_column(c).astype(np.float64)  # noqa: E731
    dytd = col(d1, "DISTRICT", "D_YTD").sum() - col(d0, "DISTRICT",
                                                    "D_YTD").sum()
    wytd = col(d1, "WAREHOUSE", "W_YTD").sum() - col(d0, "WAREHOUSE",
                                                     "W_YTD").sum()
    bal = col(d0, "CUSTOMER", "C_BALANCE").sum() - col(d1, "CUSTOMER",
                                                       "C_BALANCE").sum()
    np.testing.assert_allclose(dytd, paid, rtol=1e-5)
    np.testing.assert_allclose(wytd, paid, rtol=1e-5)
    np.testing.assert_allclose(bal, paid, rtol=1e-5)
    adv = int((d1["DISTRICT"].host_column("D_NEXT_O_ID")
               - d0["DISTRICT"].host_column("D_NEXT_O_ID")).sum())
    assert adv == int(d1["ORDER"].row_cnt) == int(d1["NEW-ORDER"].row_cnt)
    n_ord = int(d1["ORDER"].row_cnt)
    o_w = np.asarray(d1["ORDER"].columns["O_W_ID"])[:n_ord]
    o_d = np.asarray(d1["ORDER"].columns["O_D_ID"])[:n_ord]
    o_id = np.asarray(d1["ORDER"].columns["O_ID"])[:n_ord]
    next_o = d1["DISTRICT"].host_column("D_NEXT_O_ID")
    for w in range(cfg.num_wh):
        for d in range(10):
            ids = np.sort(o_id[(o_w == w) & (o_d == d)])
            assert (ids == np.arange(3001, next_o[w * 10 + d])).all(), (w, d)
    return n_hist, n_ord


# ---- equivalence oracle: escrow-on AND escrow-off vs the serial sums ---

def _oracle_one(alg):
    for escrow in (True, False):
        cfg = tpcc_cfg(cc_alg=alg, escrow_sweep=escrow)
        eng = Engine(cfg, get_workload(cfg))
        s0 = eng.init_state(0)
        d0 = jax.device_get(s0.db)
        state = jax.device_get(eng.jit_run(s0, 25))
        n_hist, n_ord = _audit(cfg, state, d0)
        assert n_hist > 0 and n_ord > 0, (alg, escrow)
        if escrow:
            on_commits = int(state.stats["total_txn_commit_cnt"])
        else:
            off_commits = int(state.stats["total_txn_commit_cnt"])
    # the exemption can only ADD committed escrow writers
    assert on_commits >= off_commits, (alg, on_commits, off_commits)
    return on_commits, off_commits


def test_escrow_oracle_occ():
    """Fast-tier representative: OCC's commit set under escrow satisfies
    the serial-sum oracle and dominates the escrow-off floor."""
    on, off = _oracle_one("OCC")
    # 2 hot warehouses, 50% payments: the floor admits ~1 payment per
    # warehouse row per epoch; escrow must beat it by a wide margin
    assert on > 2 * off, (on, off)


@pytest.mark.slow
@pytest.mark.parametrize("alg", [a for a in SWEEP_ALGS if a != "OCC"])
def test_escrow_oracle_all_backends(alg):
    _oracle_one(alg)


# ---- bit-identity: gated off == never declared ------------------------

def _tpcc_batch(cfg, wl, db, n):
    q = wl.generate(jax.random.PRNGKey(7), n)
    planned = wl.plan(db, q)
    batch = AccessBatch(
        table_ids=planned["table_ids"], keys=planned["keys"],
        is_read=planned["is_read"], is_write=planned["is_write"],
        valid=planned["valid"],
        ts=jnp.arange(1, n + 1, dtype=jnp.int32),
        rank=jnp.arange(n, dtype=jnp.int32),
        active=jnp.ones(n, bool))
    return batch, planned["order_free"]


@pytest.mark.parametrize("alg", SWEEP_ALGS)
@pytest.mark.parametrize("off_flag", ["escrow_sweep", "escrow_order_free"])
def test_escrow_off_bit_identical(alg, off_flag):
    """Either gate flag off -> verdicts (and T/O state) are bitwise what
    a plan with no order_free declaration produces."""
    cfg = tpcc_cfg(cc_alg=alg, **{off_flag: False})
    be = get_backend(alg)
    wl = get_workload(cfg)
    db = wl.load()
    batch, of = _tpcc_batch(cfg, wl, db, cfg.epoch_batch)
    assert gate_order_free(cfg, be, of) is None

    def verdict(b, declared):
        inc = build_conflict_incidence(cfg, be, b, declared)
        return be.validate(cfg, be.init_state(cfg), b, inc)

    v_off, st_off = verdict(
        dataclasses.replace(batch, order_free=gate_order_free(cfg, be, of)),
        of)
    v_plain, st_plain = verdict(batch, None)
    for f in ("commit", "abort", "defer", "order", "level"):
        np.testing.assert_array_equal(np.asarray(getattr(v_off, f)),
                                      np.asarray(getattr(v_plain, f)), f)
    for a, b in zip(jax.tree.leaves(st_off), jax.tree.leaves(st_plain)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---- scripted ordering semantics --------------------------------------

B = 8


def _script_batch(txns, of_keys=(), ts=None):
    """txns: list of [(key, mode)] with mode 'r'|'w'|'rw'; accesses whose
    key is in ``of_keys`` are declared order_free."""
    a = 4
    keys = np.zeros((B, a), np.int32)
    is_r = np.zeros((B, a), bool)
    is_w = np.zeros((B, a), bool)
    valid = np.zeros((B, a), bool)
    of = np.zeros((B, a), bool)
    for i, script in enumerate(txns):
        for s, (key, mode) in enumerate(script):
            keys[i, s] = key
            valid[i, s] = True
            is_r[i, s] = "r" in mode
            is_w[i, s] = "w" in mode
            of[i, s] = key in of_keys
    n = len(txns)
    ts = np.arange(1, n + 1, dtype=np.int32) if ts is None \
        else np.asarray(ts, np.int32)
    ts = np.concatenate([ts, np.full(B - n, ts.max() + 1, np.int32)])
    active = np.zeros(B, bool)
    active[:n] = True
    return AccessBatch(
        table_ids=jnp.zeros((B, a), jnp.int32), keys=jnp.asarray(keys),
        is_read=jnp.asarray(is_r), is_write=jnp.asarray(is_w),
        valid=jnp.asarray(valid), ts=jnp.asarray(ts),
        rank=jnp.arange(B, dtype=jnp.int32), active=jnp.asarray(active),
        order_free=jnp.asarray(of))


SCRIPT_CFG = Config(epoch_batch=B, conflict_buckets=4096, max_accesses=4,
                    req_per_query=4, synth_table_size=1024)


def _validate(alg, batch, state=None, cfg=SCRIPT_CFG):
    be = get_backend(alg)
    inc = build_conflict_incidence(cfg, be, batch, batch.order_free)
    return be.validate(cfg, be.init_state(cfg) if state is None else state,
                       batch, inc)


@pytest.mark.parametrize("alg", SWEEP_ALGS)
def test_escrow_add_add_pairs_all_commit(alg):
    """The tentpole fact: m escrow writers of ONE hot key commit together
    (the epoch-snapshot analogue of the reference's per-row latch
    serializing them within the window, row_lock.cpp:86-151) — where the
    escrow-off sweep admits a single winner."""
    txns = [[(5, "rw")] for _ in range(6)]
    v, _ = _validate(alg, _script_batch(txns, of_keys=(5,)))
    assert np.asarray(v.commit)[:6].all(), alg
    v_off, _ = _validate(alg, _script_batch(txns))
    assert int(np.asarray(v_off.commit)[:6].sum()) <= 1, alg


@pytest.mark.parametrize("alg", ["NO_WAIT", "WAIT_DIE", "OCC", "MAAT"])
def test_escrow_ordered_read_still_conflicts(alg):
    """An ORDERED read of the accumulator key still conflicts with /
    orders against every add — the exemption is per-access, not per-key.
    The reader here reads key 5 WITHOUT the order_free mark (of_keys
    marks only write accesses via a distinct txn shape)."""
    a = 4
    # txn0/1: escrow adds to key 5; txn2: ordered pure read of key 5
    keys = np.zeros((B, a), np.int32)
    is_r = np.zeros((B, a), bool)
    is_w = np.zeros((B, a), bool)
    valid = np.zeros((B, a), bool)
    of = np.zeros((B, a), bool)
    for i in (0, 1):
        keys[i, 0] = 5
        valid[i, 0] = is_w[i, 0] = of[i, 0] = True
    keys[2, 0] = 5
    valid[2, 0] = is_r[2, 0] = True
    active = np.zeros(B, bool)
    active[:3] = True
    batch = AccessBatch(
        table_ids=jnp.zeros((B, a), jnp.int32), keys=jnp.asarray(keys),
        is_read=jnp.asarray(is_r), is_write=jnp.asarray(is_w),
        valid=jnp.asarray(valid),
        ts=jnp.arange(1, B + 1, dtype=jnp.int32),
        rank=jnp.arange(B, dtype=jnp.int32), active=jnp.asarray(active),
        order_free=jnp.asarray(of))
    v, _ = _validate(alg, batch)
    c = np.asarray(v.commit)
    assert c[0] and c[1], alg                   # adds commute
    if alg == "MAAT":
        # reader orders BEFORE both adds dynamically and commits
        assert c[2]
        assert np.asarray(v.order)[2] < np.asarray(v.order)[:2].min()
    else:
        # later-rank reader lost the lock / failed backward validation
        assert not c[2], alg


def test_escrow_timestamp_cross_epoch_watermarks():
    """Escrow deltas skip wts-vs-wts (add-after-add at lower ts is NOT a
    violation) but still RECORD wts, so a stale ORDERED reader aborts;
    and a committed ordered read still blocks older deltas via rts."""
    be = get_backend("TIMESTAMP")
    st = be.init_state(SCRIPT_CFG)
    # epoch 1: escrow add at ts 10 commits
    v, st = _validate("TIMESTAMP", _script_batch([[(5, "w")]], of_keys=(5,),
                                                 ts=[10]), state=st)
    assert np.asarray(v.commit)[0]
    # epoch 2: OLDER add (ts 5) commits — deltas commute across epochs —
    # while an older ORDERED reader (ts 7) aborts on the recorded wts
    batch = _script_batch([[(5, "w")], [(5, "r")]], of_keys=(), ts=[5, 7])
    ofm = np.zeros((B, 4), bool)
    ofm[0, 0] = True                       # only the add is escrow
    batch = dataclasses.replace(batch, order_free=jnp.asarray(ofm))
    v, st = _validate("TIMESTAMP", batch, state=st)
    assert np.asarray(v.commit)[0], "older escrow delta must commit"
    assert np.asarray(v.abort)[1], "stale ordered reader must abort"
    # epoch 3: a committed ordered read at ts 20 raises rts; an older
    # delta (ts 15) would rewrite the read's ts-past -> aborts
    v, st = _validate("TIMESTAMP", _script_batch([[(5, "r")]], ts=[20]),
                      state=st)
    assert np.asarray(v.commit)[0]
    v, st = _validate("TIMESTAMP", _script_batch([[(5, "w")]], of_keys=(5,),
                                                 ts=[15]), state=st)
    assert np.asarray(v.abort)[0], "delta behind a committed read aborts"


# ---- the floor smoke (tier-1 slow marker set; tools/smoke_escrow.sh) ---

@pytest.mark.slow
@pytest.mark.parametrize("alg", ["NO_WAIT", "TIMESTAMP", "OCC"])
def test_tpcc_escrow_smoke_above_floor(alg):
    """4-warehouse mixed TPC-C: with escrow on, one lock + one ts backend
    (+ OCC, the acceptance pair) must clear the old ~1-winner-per-hot-row
    floor by >= 5x.  Epoch-rate-free formulation: the floor admits ~1
    Payment per warehouse row per epoch, so committed payments per epoch
    bounded by ~num_wh is the floor signature; escrow must commit >= 5x
    the escrow-off run on identical admission."""
    n = 30
    cfg = tpcc_cfg(cc_alg=alg, num_wh=4, epoch_batch=128,
                   max_txn_in_flight=512, perc_payment=0.5)
    on = run_epochs(cfg, n=n)
    off = run_epochs(cfg.replace(escrow_sweep=False), n=n)
    on_c = int(on.stats["total_txn_commit_cnt"])
    off_c = int(off.stats["total_txn_commit_cnt"])
    assert on_c >= 5 * max(off_c, 1), (alg, on_c, off_c)
    # absolute floor signature: escrow-off commits out of n epochs sit
    # near the per-hot-row admission bound; escrow-on must be far above
    # the old ~500 txn/s floor's per-epoch equivalent at ANY epoch rate
    assert on_c / n > 25, (alg, on_c)          # >> 4wh + districts/epoch
