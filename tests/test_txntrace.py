"""Flight-recorder trace merger (harness/txntrace.py): span-tree
construction from synthetic multi-node records, verdict-class
assignment, the completeness oracle's green and red paths, waterfall
tables, and the flow-linked Chrome-trace export against the shared
track registry (harness/timeline.py)."""

import numpy as np

from deneva_tpu.harness import txntrace as X
from deneva_tpu.runtime import telemetry as T


def _rec(tag, t_us, stage, node, epoch=-1, verdict=T.V_NONE, aux=0):
    r = np.zeros(1, T.REC_DTYPE)
    r["tag"], r["t_us"], r["stage"], r["node"] = tag, t_us, stage, node
    r["epoch"], r["verdict"], r["aux"] = epoch, verdict, aux
    return r


def _chain_records(tag=16, client=2, server=0, base=1000,
                   with_quorum=True, retried=False, shed=False,
                   salvage=False):
    """One txn's happy-path lifecycle across client + server records."""
    rows = [_rec(tag, base, T.ST_SEND, client)]
    t = base
    if shed:
        t += 50
        rows.append(_rec(tag, t, T.ST_BACKOFF, client, verdict=T.V_SHED,
                         aux=20_000))
        t += 100
        rows.append(_rec(tag, t, T.ST_RESEND, client))
    t += 100
    rows.append(_rec(tag, t, T.ST_ADMIT, server))
    if retried:
        t += 50
        rows.append(_rec(tag, t, T.ST_BATCH, server, epoch=4))
        t += 50
        rows.append(_rec(tag, t, T.ST_VERDICT, server, epoch=4,
                         verdict=T.V_ABORT))
        t += 50
        rows.append(_rec(tag, t, T.ST_ADMIT, server))
    t += 100
    rows.append(_rec(tag, t, T.ST_BATCH, server, epoch=5))
    t += 300
    rows.append(_rec(tag, t, T.ST_VERDICT, server, epoch=5,
                     verdict=T.V_SALVAGE if salvage else T.V_COMMIT))
    if with_quorum:
        rows.append(_rec(tag, t + 1, T.ST_HOLD, server, epoch=5))
        t += 200
        rows.append(_rec(tag, t, T.ST_RELEASE, server, epoch=5))
    t += 80
    rows.append(_rec(tag, t, T.ST_ACK, client))
    return rows


def _concat(rows):
    recs = np.concatenate(rows)
    return recs[np.argsort(recs["t_us"], kind="stable")]


def test_build_chain_happy_path_and_spans():
    recs = _concat(_chain_records())
    txns = X.index_txns(recs)
    assert set(txns) == {16}
    ch = X.build_chain(txns[16])
    assert ch["klass"] == "committed" and ch["epoch"] == 5
    assert ch["send"] == 1000 and ch["ack"] == 1780
    sp = X.stage_spans(ch)
    assert sp["send-admit"] == 0.1 and sp["batch-verdict"] == 0.3
    assert sp["verdict-release"] == 0.2
    assert abs(sp["release-ack"] - 0.08) < 1e-9
    assert sp["total"] == 0.78


def test_chain_without_quorum_folds_release_into_verdict():
    recs = _concat(_chain_records(with_quorum=False))
    ch = X.build_chain(X.index_txns(recs)[16])
    assert ch["hold"] is None and ch["release"] is None
    sp = X.stage_spans(ch)
    assert sp["verdict-release"] == 0.0
    assert sp["release-ack"] > 0       # verdict -> ack wire time


def test_verdict_class_priority():
    """salvaged > shed > retried > committed, per the class contract."""
    recs = _concat(_chain_records(retried=True))
    assert X.build_chain(X.index_txns(recs)[16])["klass"] == "retried"
    recs = _concat(_chain_records(shed=True))
    assert X.build_chain(X.index_txns(recs)[16])["klass"] == "shed"
    recs = _concat(_chain_records(salvage=True, shed=True, retried=True))
    assert X.build_chain(X.index_txns(recs)[16])["klass"] == "salvaged"
    recs = _concat(_chain_records())
    assert X.build_chain(X.index_txns(recs)[16])["klass"] == "committed"


def test_stage_selection_anchors_on_committing_pass():
    """A retried txn's per-stage attribution describes the committing
    pass (last batch before the commit verdict), while total latency
    keeps measuring from the FIRST send."""
    recs = _concat(_chain_records(retried=True))
    ch = X.build_chain(X.index_txns(recs)[16])
    assert ch["epoch"] == 5                       # not the aborted pass
    sp = X.stage_spans(ch)
    assert sp["batch-verdict"] == 0.3             # the commit pass only
    assert sp["total"] > 0.7                      # first send -> ack


def test_completeness_green_and_red():
    rows = _chain_records(tag=16) + _chain_records(tag=24, base=5000)
    committed, full, viol = X.completeness(
        [X.build_chain(ev) for ev in X.index_txns(_concat(rows)).values()])
    assert (committed, full, viol) == (2, 2, [])
    # red: a committed txn with no ADMIT hop is a recorder gap
    gap = [r for r in _chain_records(tag=32)
           if not (r["stage"] == T.ST_ADMIT).any()]
    committed, full, viol = X.completeness(
        [X.build_chain(ev) for ev in X.index_txns(_concat(gap)).values()])
    assert committed == 1 and len(viol) == 1 and "admit" in viol[0]
    # red: an ack BEFORE the verdict is an ordering inversion
    inv = _chain_records(tag=40, with_quorum=False)
    for r in inv:
        if (r["stage"] == T.ST_ACK).any():
            r["t_us"] = 1050                     # before the verdict
    committed, full, viol = X.completeness(
        [X.build_chain(ev) for ev in X.index_txns(_concat(inv)).values()])
    assert len(viol) == 1 and "inversion" in viol[0]


def test_in_flight_txn_excluded():
    rows = [_rec(8, 100, T.ST_SEND, 2), _rec(8, 200, T.ST_ADMIT, 0)]
    ch = X.build_chain(X.index_txns(_concat(rows))[8])
    assert ch["verdict"] is None and ch["klass"] is None
    committed, full, viol = X.completeness([ch])
    assert (committed, full, viol) == (0, 0, [])
    assert X.stage_spans(ch) is None


def test_waterfall_splits_by_verdict_and_tenant():
    rows = (_chain_records(tag=16)
            + _chain_records(tag=24 | (3 << 24), base=5000, shed=True))
    chains = [X.build_chain(ev)
              for ev in X.index_txns(_concat(rows)).values()]
    tab = X.waterfall(chains, by="verdict")
    keys = {r[0] for r in tab[1:]}
    assert keys == {"committed", "shed"}
    assert tab[0][:3] == ["verdict", "stage", "txns"]
    tab = X.waterfall(chains, by="tenant")
    assert {r[0] for r in tab[1:]} == {"tenant0", "tenant3"}
    tab = X.waterfall(chains, by="none")
    assert {r[0] for r in tab[1:]} == {"all"}
    # every fixed stage reported once per split
    assert [r[1] for r in tab[1:]] == list(X.STAGES)
    assert "p99_ms" in tab[0]
    assert X.render(tab).splitlines()[0].startswith("none")
    assert X.render([tab[0]]).startswith("(no complete")


def test_chrome_trace_flow_arrows_cross_tracks():
    from deneva_tpu.harness.timeline import TXN_TRACK

    rows = _chain_records() + [_rec(-1, 1650, T.ST_APPLY, 3, epoch=5)]
    recs = _concat(rows)
    trace = X.chrome_trace(recs, {2: "client", 0: "node", 3: "replica"})
    ev = trace["traceEvents"]
    xs = [e for e in ev if e["ph"] == "X"]
    assert {e["tid"] for e in xs} == {TXN_TRACK.tid}
    assert [e["name"] for e in xs] == list(X.STAGES[:-1])
    # spans land on the owning node: server hops on pid 0, ack on client
    assert {e["pid"] for e in xs if e["name"] == "batch-verdict"} == {0}
    assert {e["pid"] for e in xs if e["name"] == "release-ack"} == {2}
    flow = [e for e in ev if e["ph"] in ("s", "t", "f")]
    assert [e["ph"] for e in flow] == ["s", "t", "t", "f"]
    assert flow[0]["pid"] == 2 and flow[1]["pid"] == 0
    assert flow[-1]["bp"] == "e"
    # replica apply markers ride the same track as instants
    inst = [e for e in ev if e["ph"] == "i"]
    assert len(inst) == 1 and inst[0]["pid"] == 3
    # track metadata from the shared registry
    meta = [e for e in ev if e["ph"] == "M"]
    assert {m["args"]["name"] for m in meta
            if m["name"] == "thread_name"} == {TXN_TRACK.name}
    assert {m["args"]["name"] for m in meta
            if m["name"] == "process_name"} \
        == {"client 2", "node 0", "replica 3"}


def test_load_dir_merges_sidecars(tmp_path):
    from deneva_tpu.config import Config

    cfg = Config(telemetry=True, telemetry_sample=1,
                 telemetry_dir=str(tmp_path))
    a = T.FlightRecorder(cfg, 0, "node")
    a.record(np.asarray([8]), T.ST_ADMIT, t_us=50)
    a.flush()
    b = T.FlightRecorder(cfg, 2, "client")
    b.record(np.asarray([8]), T.ST_SEND, t_us=10)
    b.flush()
    recs, roles = X.load_dir(str(tmp_path))
    assert len(recs) == 2 and list(recs["t_us"]) == [10, 50]
    assert roles == {0: "node", 2: "client"}
    empty, roles = X.load_dir(str(tmp_path / "nope"))
    assert len(empty) == 0 and roles == {}
