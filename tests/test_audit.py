"""Isolation audit plane (cc/base.audit_observe + runtime/audit.py +
harness/auditgraph.py): scripted edge-derivation semantics per
visibility mode, escrow/self-edge exclusions, export-cap accounting,
the seeded audit_mutate fault, graph certification + Adya
classification + witness forensics, cross-node divergence detection,
the default-off group-output arity, the observation-only contract
(armed == off row state, bit for bit), and the end-to-end
mutation-catch through the real cluster epoch body."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deneva_tpu.config import CCAlg, Config, WorkloadKind
from deneva_tpu.cc import (AUDIT_KEY, AccessBatch, audit_init,
                           audit_mutate_verdict, audit_observe)
from deneva_tpu.harness import auditgraph
from deneva_tpu.runtime import audit as AU

from tests.test_chaos import _solo_server


def _cfg(**kw):
    base = dict(audit=True, audit_cadence=1, audit_buckets=1024,
                audit_edges_max=64, cc_alg=CCAlg.OCC,
                dist_protocol="merged", epoch_batch=128,
                synth_table_size=1024)
    base.update(kw)
    return Config(**base).validate()


def _batch(scripts, B=8, A=2, order_free=None):
    """AccessBatch from per-txn [(key, 'r'|'w'|'rw'), ...] scripts;
    txns beyond the scripts are inactive."""
    keys = np.zeros((B, A), np.int32)
    is_r = np.zeros((B, A), bool)
    is_w = np.zeros((B, A), bool)
    valid = np.zeros((B, A), bool)
    for i, script in enumerate(scripts):
        for s, (key, mode) in enumerate(script):
            keys[i, s] = key
            is_r[i, s] = "r" in mode
            is_w[i, s] = "w" in mode
            valid[i, s] = True
    active = np.zeros(B, bool)
    active[:len(scripts)] = True
    return AccessBatch(
        table_ids=jnp.zeros((B, A), jnp.int32), keys=jnp.asarray(keys),
        is_read=jnp.asarray(is_r), is_write=jnp.asarray(is_w),
        valid=jnp.asarray(valid), ts=jnp.arange(B, dtype=jnp.int32),
        rank=jnp.arange(B, dtype=jnp.int32), active=jnp.asarray(active),
        order_free=None if order_free is None
        else jnp.asarray(order_free))


def _observe(cfg, batch, committed, lvl=None, order_vis=False,
             aud=None, epoch=0):
    b = batch.shape[0]
    committed = jnp.asarray(committed)
    lvl = jnp.zeros(b, jnp.int32) if lvl is None \
        else jnp.asarray(lvl, jnp.int32)
    aud = audit_init(cfg) if aud is None else aud
    out = audit_observe(cfg, batch, committed, batch.rank, lvl,
                        order_vis, aud, jnp.int32(epoch))
    aud2, edges, ebkt, cnt, drop, vdig, rdig = out
    es = sorted(AU.decode_edge(int(e))
                for e in np.asarray(edges)[:int(cnt)])
    return aud2, es, int(cnt), int(drop), int(vdig), int(rdig)


def _mask(B, committed_ids):
    m = np.zeros(B, bool)
    m[list(committed_ids)] = True
    return m


# ---- config gating -----------------------------------------------------

def test_config_gating():
    assert Config().audit is False
    with pytest.raises(ValueError):        # mutate needs audit
        Config(audit_mutate="occ-read-skip:4").validate()
    with pytest.raises(ValueError):        # mutate is OCC-scoped
        _cfg(cc_alg=CCAlg.CALVIN, dist_protocol="auto",
             audit_mutate="occ-read-skip:4")
    with pytest.raises(ValueError):        # malformed spec
        _cfg(audit_mutate="occ-read-skip")
    # MVCC version-select reads are MODELED since the depgraph refactor
    # (per-slot version rings in the stamp state): audit+MVCC validates
    _cfg(cc_alg=CCAlg.MVCC)
    with pytest.raises(ValueError):        # PPS not wired
        _cfg(workload=WorkloadKind.PPS, pps_parts_per=4, max_accesses=16)
    with pytest.raises(ValueError):        # rank packing bound
        _cfg(epoch_batch=32768)
    with pytest.raises(ValueError):        # vote body observes nothing
        _cfg(dist_protocol="vote")
    with pytest.raises(ValueError):
        _cfg(audit_cadence=0)
    spec = _cfg(audit_mutate="occ-read-skip:48:8").audit_mutate_spec()
    assert spec == ("occ-read-skip", 48, 8)
    assert _cfg().audit_mutate_spec() is None


# ---- scripted edge derivation ------------------------------------------

def test_snapshot_write_skew_two_rw_cycle():
    """Level-0 sweep visibility (reads observe the epoch-start
    snapshot): a committed write-skew pair yields exactly the two rw
    anti-dependency edges whose cycle IS the G2 anomaly."""
    cfg = _cfg()
    batch = _batch([[(10, "r"), (20, "w")], [(20, "r"), (10, "w")]])
    _, es, cnt, drop, _, _ = _observe(cfg, batch, _mask(8, [0, 1]))
    assert es == [(2, 0, 1), (2, 1, 0)] and drop == 0


def test_clean_committed_set_no_edges():
    cfg = _cfg()
    batch = _batch([[(10, "r"), (20, "w")], [(30, "r"), (40, "w")]])
    _, es, cnt, *_ = _observe(cfg, batch, _mask(8, [0, 1]))
    assert es == [] and cnt == 0


def test_uncommitted_txns_never_observed():
    """An aborted txn's accesses are not part of the history: the same
    write-skew pair with one side aborted emits only the surviving
    side's (acyclic) rw edge."""
    cfg = _cfg()
    batch = _batch([[(10, "r"), (20, "w")], [(20, "r"), (10, "w")]])
    _, es, *_ = _observe(cfg, batch, _mask(8, [0]))
    assert es == []


def test_forward_visibility_wr_rw_ww():
    """Forwarding (serial-in-order) visibility: T1's read of k observes
    T0's earlier write (wr), the next writer T2 takes T1's rw
    anti-dependency, and the writers chain ww."""
    cfg = _cfg()
    batch = _batch([[(5, "w")], [(5, "r")], [(5, "w")]])
    _, es, *_ = _observe(cfg, batch, _mask(8, [0, 1, 2]),
                         order_vis=True)
    assert es == [(0, 0, 2), (1, 0, 1), (2, 1, 2)]


def test_level_visibility_chained():
    """Chained visibility: a level-1 reader observes the level-0 write
    (wr); a level-0 reader of a level-1 writer's key observes the
    snapshot (rw toward the writer)."""
    cfg = _cfg()
    batch = _batch([[(5, "w"), (7, "r")], [(5, "r"), (7, "w")]])
    _, es, *_ = _observe(cfg, batch, _mask(8, [0, 1]),
                         lvl=[0, 1, 0, 0, 0, 0, 0, 0])
    assert es == [(1, 0, 1), (2, 0, 1)]


def test_escrow_lanes_excluded():
    """order_free (escrow) lanes carry no ordering claim: the same
    conflicting pair with the mask set emits nothing."""
    cfg = _cfg()
    of = np.zeros((8, 2), bool)
    of[0] = of[1] = True
    batch = _batch([[(10, "r"), (20, "w")], [(20, "r"), (10, "w")]],
                   order_free=of)
    _, es, *_ = _observe(cfg, batch, _mask(8, [0, 1]))
    assert es == []


def test_self_rmw_no_self_edges():
    cfg = _cfg()
    batch = _batch([[(5, "rw")]])
    _, es, *_ = _observe(cfg, batch, _mask(8, [0]), order_vis=True)
    assert es == []


def test_edge_cap_overflow_counted():
    """Past audit_edges_max the export truncates and COUNTS — the
    certificate degrades to incomplete, never silently."""
    cfg = _cfg(epoch_batch=64)
    scripts = [[(5, "r"), (5, "w")] for _ in range(40)]
    batch = _batch(scripts, B=64)
    _, es, cnt, drop, _, _ = _observe(cfg, batch, _mask(64, range(40)))
    assert cnt > cfg.audit_edges_max
    assert drop == cnt - cfg.audit_edges_max
    assert len(es) == cfg.audit_edges_max


def test_stamp_tables_and_digests():
    """Version stamps advance per epoch, digests are deterministic, and
    an epoch-start read's rdig depends on what the PREVIOUS epochs
    wrote (the cross-epoch fingerprint)."""
    cfg = _cfg()
    w = _batch([[(5, "w")]])
    r = _batch([[(5, "r")]])
    aud0 = audit_init(cfg)
    aud1, _, _, _, v1, _ = _observe(cfg, w, _mask(8, [0]), epoch=3)
    assert int(np.asarray(aud1["epoch"]).max()) == 3
    # identical inputs -> identical digests (what the cross-node
    # consensus check rests on)
    aud1b, _, _, _, v1b, _ = _observe(cfg, w, _mask(8, [0]), epoch=3)
    assert v1 == v1b
    _, _, _, _, _, r_fresh = _observe(cfg, r, _mask(8, [0]), aud=aud0)
    _, _, _, _, _, r_after = _observe(cfg, r, _mask(8, [0]), aud=aud1)
    assert r_fresh != r_after


def test_mvcc_version_ring_visibility():
    """MVCC per-read observed-version export (the depgraph refactor's
    headroom item): a read's observed stamp is SELECTED BY ITS OWN
    TIMESTAMP from the bucket's version-boundary ring, so a stale
    reader and a fresh reader digest DIFFERENT observations — under
    every other backend's last-writer stamp model they are identical,
    which is exactly the MVCC anomaly the audit plane used to miss."""
    import dataclasses
    from deneva_tpu.cc import depgraph

    # the in-ring select rule: newest boundary <= ts, -1 pre-horizon
    vts = jnp.asarray([[10, 20, 30, -1]], jnp.int32)
    for ts, want in ((15, 0), (25, 1), (99, 2), (5, -1)):
        sel = depgraph.version_select(vts, jnp.asarray([ts], jnp.int32))
        assert int(sel[0]) == want, (ts, want)

    # two writer epochs push boundaries ts=10 and ts=20 into the ring
    cfg = _cfg(cc_alg=CCAlg.MVCC)
    aud = audit_init(cfg)
    assert "vts" in aud            # rings exist only under MVCC
    w = _batch([[(5, "w")]])
    for e, wts in ((1, 10), (2, 20)):
        wb = dataclasses.replace(w, ts=jnp.full(8, wts, jnp.int32))
        aud, _, _, _, _, _ = _observe(cfg, wb, _mask(8, [0]), aud=aud,
                                      epoch=e)
    retained = set(np.asarray(aud["vts"]).ravel().tolist())
    assert {10, 20} <= retained    # both boundaries retained

    def rdig_at(a, ts):
        r = dataclasses.replace(_batch([[(5, "r")]]),
                                ts=jnp.full(8, ts, jnp.int32))
        return _observe(cfg, r, _mask(8, [0]), aud=a)[5]

    stale, fresh, horizon = rdig_at(aud, 12), rdig_at(aud, 25), \
        rdig_at(aud, 5)
    assert stale != fresh          # ts selects the version, not the
    assert horizon not in (stale, fresh)   # last writer; pre-horizon
    # reads observe epoch-start-of-history, distinct from both
    # control: the OCC stamp model cannot see the difference
    ocfg = _cfg()
    oaud = audit_init(ocfg)
    assert "vts" not in oaud
    for e, wts in ((1, 10), (2, 20)):
        wb = dataclasses.replace(w, ts=jnp.full(8, wts, jnp.int32))
        oaud, _, _, _, _, _ = _observe(ocfg, wb, _mask(8, [0]),
                                       aud=oaud, epoch=e)

    def ordig_at(ts):
        r = dataclasses.replace(_batch([[(5, "r")]]),
                                ts=jnp.full(8, ts, jnp.int32))
        return _observe(ocfg, r, _mask(8, [0]), aud=oaud)[5]

    assert ordig_at(12) == ordig_at(25)


# ---- the seeded mutation ----------------------------------------------

def test_mutate_flips_only_clean_losers_inside_window():
    from deneva_tpu.cc import build_conflict_incidence, get_backend

    cfg = _cfg(audit_mutate="occ-read-skip:7:2", epoch_batch=8,
               conflict_buckets=256)
    be = get_backend(cfg.cc_alg)
    # T0 wins writing 5; T1 reads 5 (clean writes) -> flippable;
    # T2 reads 5 AND writes 5 (dirty write) -> stays aborted
    batch = _batch([[(5, "w")], [(5, "r"), (9, "w")],
                    [(5, "r"), (5, "w")]])
    inc = build_conflict_incidence(cfg, be, batch, None)
    verdict, _ = be.validate(cfg, be.init_state(cfg), batch, inc)
    assert bool(np.asarray(verdict.commit)[0])
    assert bool(np.asarray(verdict.abort)[1])
    assert bool(np.asarray(verdict.abort)[2])
    out = audit_mutate_verdict(cfg, batch, inc, verdict, jnp.int32(7))
    assert bool(np.asarray(out.commit)[1])     # flipped
    assert not bool(np.asarray(out.abort)[1])
    assert bool(np.asarray(out.abort)[2])      # dirty write: untouched
    miss = audit_mutate_verdict(cfg, batch, inc, verdict, jnp.int32(9))
    np.testing.assert_array_equal(np.asarray(miss.commit),
                                  np.asarray(verdict.commit))


# ---- graph certification ----------------------------------------------

def test_classify_adya():
    assert auditgraph.classify([0, 0]) == "G0"
    assert auditgraph.classify([0, 1]) == "G1c"
    assert auditgraph.classify([1, 1, 2]) == "G-single"
    assert auditgraph.classify([2, 2]) == "G2-item"


def _pack(kind, src, dst):
    return (kind << 28) | (src << 14) | dst


def _emit(tmp_path, node, epoch, edges, tags, vdig=1, rdig=1,
          lo=0, b_loc=64, dropped=0):
    cfg = _cfg(telemetry_dir=str(tmp_path))
    ex = AU.AuditExporter(cfg, node, b_loc, lo, append=True)
    tag_col = np.zeros(b_loc, np.int64)
    for r, t in tags.items():
        tag_col[r - lo] = t
    ex.export(epoch, np.asarray(edges + [-1], np.int32),
              np.zeros(len(edges) + 1, np.int32),
              len(edges), dropped, vdig, rdig, commit=3, tags=tag_col)
    ex.close()


def test_certify_clean_and_violation(tmp_path):
    # epoch 0: a forward rw edge (legal); epoch 1: a 2-cycle
    _emit(tmp_path, 0, 0, [_pack(2, 1, 2)], {1: 101, 2: 102})
    cert = auditgraph.certify(str(tmp_path))
    assert cert["ok"] and cert["epochs"] == 1 and cert["complete"]
    _emit(tmp_path, 0, 1, [_pack(2, 3, 4), _pack(2, 4, 3)],
          {3: 103, 4: 104})
    cert = auditgraph.certify(str(tmp_path))
    assert not cert["ok"] and len(cert["cycles"]) == 1
    w = cert["cycles"][0]
    assert w["epoch"] == 1 and w["anomaly"] == "G2-item"
    assert {t["tag"] for t in w["txns"]} == {103, 104}
    assert all(t["node"] == 0 for t in w["txns"])
    text = auditgraph.render(cert)
    assert "VIOLATION" in text and "G2-item" in text
    # exit code contract: violation -> 1
    assert auditgraph.main([str(tmp_path)]) == 1


def test_certify_divergence_and_node_filter(tmp_path):
    """Two nodes exporting the SAME epoch must agree bit-for-bit; a
    vdig mismatch is the split-brain signature.  The node filter (the
    chaos oracle excludes fenced/killed nodes) silences it."""
    _emit(tmp_path, 0, 5, [_pack(2, 1, 2)], {1: 11}, vdig=7, lo=0)
    _emit(tmp_path, 1, 5, [_pack(2, 1, 2)], {2: 22}, vdig=8, lo=64)
    cert = auditgraph.certify(str(tmp_path))
    assert cert["divergences"] \
        and cert["divergences"][0]["epoch"] == 5 \
        and "vdig" in cert["divergences"][0]["fields"]
    assert "DIVERGENCE" in auditgraph.render(cert)
    # tag/owner union across the two slices
    assert auditgraph.main([str(tmp_path)]) == 1
    cert1 = auditgraph.certify(str(tmp_path), nodes=[0])
    assert not cert1["divergences"]


def test_certify_incomplete_on_dropped(tmp_path):
    """An epoch whose edge export overflowed the cap degrades the
    certificate to incomplete — reported, never silent."""
    _emit(tmp_path, 0, 2, [_pack(2, 1, 2)], {1: 11}, dropped=17)
    cert = auditgraph.certify(str(tmp_path))
    assert cert["ok"]                    # no cycle in what was seen
    assert not cert["complete"] and cert["dropped_epochs"] == 1
    assert "incomplete" in auditgraph.render(cert)


# ---- default-off contract on the real runtime --------------------------

def test_audit_off_group_outputs():
    """The group jit's output arity is exactly the pre-audit one with
    audit off (state + packed planes), no exporter exists, and the
    [summary] carries no audit_* counters — the d2h volume and the
    sidecar surface are part of the off-contract."""
    node = _solo_server("aud_off_arity")
    try:
        assert node.aud is None
        C, b = node.C, node.b_merged
        W, S = node._width, node._n_scalars
        warm = jax.device_put((
            np.zeros(C * b, bool), np.zeros(C * b, np.int32),
            np.zeros(C * b * W, np.int32), np.zeros(C * b * W, np.int8),
            np.zeros(C * b * S, np.int32)))
        out = node.group_step(node.db, node.cc_state, node.dev_stats,
                              *warm)
        assert len(out) == 4
        assert AUDIT_KEY not in node.db
    finally:
        node.close()


def test_audit_armed_group_outputs_and_export(tmp_path):
    """Armed: the group jit takes the epoch-label feed and returns the
    six-plane audit stack beside the verdict planes; the exporter
    writes a certifiable sidecar record."""
    node = _solo_server("aud_on_arity", audit=True, audit_cadence=1,
                        telemetry_dir=str(tmp_path))
    try:
        assert node.aud is not None and AUDIT_KEY in node.db
        C, b = node.C, node.b_merged
        W, S = node._width, node._n_scalars
        warm = jax.device_put((
            np.zeros(C * b, bool), np.zeros(C * b, np.int32),
            np.zeros(C * b * W, np.int32), np.zeros(C * b * W, np.int8),
            np.zeros(C * b * S, np.int32),
            np.full(C, -1, np.int32)))
        out = node.group_step(node.db, node.cc_state, node.dev_stats,
                              *warm)
        assert len(out) == 5 and len(out[4]) == 6
        edges = np.asarray(out[4][0])
        assert edges.shape == (C, node.cfg.audit_edges_max)
        node.aud.export(0, edges[0], np.asarray(out[4][1])[0], 0, 0,
                        1, 2, commit=0,
                        tags=np.zeros(node.b_loc, np.int64))
        node.aud.close()
        cert = auditgraph.certify(str(tmp_path))
        assert cert["ok"] and cert["epochs"] == 1
        fields = node.aud.fields()
        assert fields["epochs"] == 1
        line = AU.audit_line(0, fields)
        from deneva_tpu.harness.parse import parse_audit
        rows = parse_audit([line])
        assert rows and rows[0]["epochs"] == 1
    finally:
        node.close()


def test_audit_observation_only_row_state():
    """The armed engine's ROW state and verdict counters are
    bit-identical to the off run's — the audit plane observes, never
    decides (the wire-pin/digest half of the acceptance contract; the
    cluster wire bytes are untouched by construction since the audit
    adds no message and no codec)."""
    from deneva_tpu.engine.step import Engine
    from deneva_tpu.runtime.logger import state_digest
    from deneva_tpu.workloads import get_workload

    digests, commits, edge_cnts = [], [], []
    for armed in (False, True):
        cfg = Config(workload=WorkloadKind.YCSB, cc_alg=CCAlg.OCC,
                     audit=armed, audit_cadence=1, epoch_batch=32, conflict_buckets=256,
                     synth_table_size=256, req_per_query=2,
                     max_accesses=2, zipf_theta=0.9,
                     max_txn_in_flight=64)
        eng = Engine(cfg, get_workload(cfg))
        state = eng.init_state()
        for _ in range(6):
            state = eng.jit_step(state)
        digests.append(state_digest(state.db))
        commits.append(int(state.stats["total_txn_commit_cnt"]))
        edge_cnts.append(int(state.stats["audit_edge_cnt"]))
    assert digests[0] == digests[1]
    assert commits[0] == commits[1]
    assert edge_cnts[0] == 0           # off: counter never moves


def test_engine_forwarding_anti_inert():
    """The in-process CALVIN engine at zipf 0.9 produces real in-batch
    wr/rw dependencies — the armed counter must move (a zero here means
    the instrument is dead)."""
    from deneva_tpu.engine.step import Engine
    from deneva_tpu.workloads import get_workload

    cfg = Config(workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
                 audit=True, audit_cadence=1, epoch_batch=64, conflict_buckets=256,
                 synth_table_size=256, req_per_query=2, max_accesses=2,
                 zipf_theta=0.9, max_txn_in_flight=128)
    eng = Engine(cfg, get_workload(cfg))
    state = eng.init_state()
    for _ in range(4):
        state = eng.jit_step(state)
    assert int(state.stats["audit_edge_cnt"]) > 0


def test_checkpoint_roundtrip_with_audit(tmp_path):
    """Schema v8: the armed EngineState (audit stamp tables in db +
    the new counters) checkpoints and resumes bit-exactly."""
    from deneva_tpu.engine.checkpoint import load_state, save_state
    from deneva_tpu.engine.step import Engine
    from deneva_tpu.workloads import get_workload

    cfg = Config(workload=WorkloadKind.YCSB, cc_alg=CCAlg.OCC,
                 audit=True, audit_cadence=1, epoch_batch=32, conflict_buckets=256,
                 synth_table_size=256, req_per_query=2, max_accesses=2,
                 max_txn_in_flight=64)
    eng = Engine(cfg, get_workload(cfg))
    state = eng.init_state()
    state = eng.jit_step(state)
    path = str(tmp_path / "aud.npz")
    save_state(path, state)
    restored = load_state(path, eng.init_state())
    np.testing.assert_array_equal(
        np.asarray(state.db[AUDIT_KEY]["epoch"]),
        np.asarray(restored.db[AUDIT_KEY]["epoch"]))


def test_monitor_audit_panel(tmp_path):
    """tools/monitor.py surfaces the latest per-node audit verdict
    (clean / edges-observed / export-overflow) + Prometheus gauges."""
    import importlib
    monitor = importlib.import_module("tools.monitor")

    _emit(tmp_path, 0, 4, [], {})
    _emit(tmp_path, 1, 4, [_pack(2, 1, 2)], {1: 11}, lo=64)
    by_node = monitor.load_audit_dir(str(tmp_path))
    assert sorted(by_node) == [0, 1]
    text = monitor.render_audit(by_node)
    assert "clean" in text and "edges-observed" in text
    prom = monitor.prom_audit(by_node)
    assert 'deneva_audit_edges_total{node="1"} 1' in prom
    assert 'deneva_audit_epochs_total{node="0"} 1' in prom


# ---- end-to-end mutation catch through the cluster epoch body ----------

def test_mutation_caught_and_clean_run_certifies(tmp_path):
    """The anti-inert contract end to end through the REAL merged epoch
    body (make_dist_step): a clean contended OCC run certifies
    serializable; the same run with occ-read-skip seeded on epochs
    [2, 4) is rejected with rw-anomaly witnesses naming epochs inside
    exactly that window."""
    from deneva_tpu.cc import get_backend
    from deneva_tpu.engine.step import init_device_stats
    from deneva_tpu.runtime.server import make_dist_step
    from deneva_tpu.workloads import get_workload

    def run(mutate, d):
        cfg = Config(workload=WorkloadKind.YCSB, cc_alg=CCAlg.OCC,
                     dist_protocol="merged", audit=True,
                     audit_cadence=1, audit_mutate=mutate,
                     epoch_batch=128,
                     conflict_buckets=512, synth_table_size=1024,
                     req_per_query=4, max_accesses=4, zipf_theta=0.9,
                     telemetry_dir=str(d))
        wl = get_workload(cfg)
        be = get_backend(cfg.cc_alg)
        step = make_dist_step(cfg, wl, be)
        db, cc = wl.load(), be.init_state(cfg)
        stats = init_device_stats(len(wl.txn_type_names))
        ex = AU.AuditExporter(cfg, 0, 128, 0)
        rng = jax.random.PRNGKey(0)
        for e in range(6):
            rng, k = jax.random.split(rng)
            q = wl.generate(k, 128)
            out = step(db, cc, stats, jnp.int32(e),
                       jnp.ones(128, bool),
                       jnp.arange(128, dtype=jnp.int32) + e * 128, q)
            db, cc, stats, done = out[:4]
            edges, ebkt, cnt, drop, vdig, rdig = \
                (np.asarray(x) for x in out[8])
            ex.export(e, edges, ebkt, int(cnt), int(drop), int(vdig),
                      int(rdig), commit=int(np.asarray(done).sum()),
                      tags=np.arange(128, dtype=np.int64))
        ex.close()
        return auditgraph.certify(str(d))

    clean_dir = tmp_path / "clean"
    clean_dir.mkdir()
    cert = run("", clean_dir)
    assert cert["ok"] and cert["epochs"] == 6
    assert cert["edge_lanes"] > 0      # legal forward rw edges exist
    mut_dir = tmp_path / "mut"
    mut_dir.mkdir()
    cert = run("occ-read-skip:2:2", mut_dir)
    assert not cert["ok"]
    eps = {w["epoch"] for w in cert["cycles"]}
    assert eps and all(2 <= e < 4 for e in eps)
    assert all(w["anomaly"] in ("G-single", "G2-item")
               for w in cert["cycles"])
    w = cert["cycles"][0]
    assert all(t["tag"] is not None and t["node"] == 0
               for t in w["txns"])
