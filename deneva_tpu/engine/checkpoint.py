"""Checkpoint / resume of engine state.

The reference has **no checkpointing** (SURVEY §5.4: logging+replication
are the closest thing; recovery is unimplemented).  Here the whole
`EngineState` is one pytree — tables, CC watermarks, txn pool, RNG, epoch
counter, stats — so a checkpoint is a flat dump of its leaves and resume
is bit-exact: a resumed run continues the *identical* epoch stream the
uninterrupted run would have produced (the RNG key is state, not ambient).

Format: one ``.npz`` with leaves in flatten order plus their key-paths for
a structure sanity check.  The config is not serialized — the caller
recreates the engine from the same `Config` (the reference pins config at
compile time; we pin it at restore time and verify leaf shapes agree).
"""

from __future__ import annotations

import io
import os

import jax
import numpy as np

# Bump whenever the EngineState pytree LAYOUT changes (new/renamed state
# fields, cc_state reshapes, db companion tables) so a stale checkpoint
# fails with a clear message instead of an opaque tree/shape error.
# History: 1 = round-2 (TOState->MVCCState, watermark_buckets split);
#          2 = round-3 (MVCC per-row VersionRing joins the db pytree);
#          3 = round-4 (PoolState.defer_cnt for the defer budget);
#          4 = round-4 (per-type latency_hist + retry/wait hist leaves);
#          5 = round-5 (VersionRing flattened to [R*H] storage);
#          6 = round-13 (rep_* transaction-repair counters in
#              device stats);
#          7 = round-16 (conflict_density per-partition counter in
#              device stats — the metrics bus's contention signal);
#          8 = round-17 (isolation audit plane: audit_edge_cnt/
#              audit_drop_cnt device counters, and with audit armed the
#              db pytree gains the __audit__ version-stamp tables).
SCHEMA_VERSION = 8


def save_state(path: str, state) -> None:
    """Dump a state pytree (EngineState or any pytree of arrays)."""
    leaves_p = jax.tree_util.tree_flatten_with_path(state)[0]
    payload = {f"leaf_{i:04d}": np.asarray(jax.device_get(v))
               for i, (_, v) in enumerate(leaves_p)}
    payload["__schema__"] = np.int64(SCHEMA_VERSION)
    payload["__paths__"] = np.array(
        [jax.tree_util.keystr(p) for p, _ in leaves_p])
    buf = io.BytesIO()
    np.savez(buf, **payload)
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)          # atomic: no torn checkpoints


def load_state(path: str, template):
    """Rebuild a state pytree from ``path`` using ``template`` (a freshly
    initialized state of the same config) for structure and placement."""
    with np.load(path, allow_pickle=False) as z:
        saved_schema = int(z["__schema__"]) if "__schema__" in z else 0
        if saved_schema != SCHEMA_VERSION:
            raise ValueError(
                f"incompatible checkpoint: schema v{saved_schema} "
                f"(this build writes v{SCHEMA_VERSION}) — the engine "
                "state layout changed between builds; re-run from "
                "scratch (checkpoints are not migrated)")
        paths = list(z["__paths__"])
        leaves_t, treedef = jax.tree_util.tree_flatten_with_path(template)
        if len(paths) != len(leaves_t):
            raise ValueError(
                f"checkpoint has {len(paths)} leaves, template has "
                f"{len(leaves_t)} — config mismatch?")
        leaves = []
        for i, ((p, t), saved_path) in enumerate(zip(leaves_t, paths)):
            if jax.tree_util.keystr(p) != str(saved_path):
                raise ValueError(
                    f"leaf {i} path mismatch: checkpoint "
                    f"{saved_path!r} vs template {jax.tree_util.keystr(p)!r}")
            v = z[f"leaf_{i:04d}"]
            if hasattr(t, "shape") and tuple(t.shape) != v.shape:
                raise ValueError(
                    f"leaf {jax.tree_util.keystr(p)}: shape {v.shape} != "
                    f"template {tuple(t.shape)} — config mismatch?")
            leaves.append(jax.numpy.asarray(v, dtype=getattr(t, "dtype",
                                                             None)))
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(template), leaves)
