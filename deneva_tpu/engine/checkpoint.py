"""Checkpoint / resume of engine state.

The reference has **no checkpointing** (SURVEY §5.4: logging+replication
are the closest thing; recovery is unimplemented).  Here the whole
`EngineState` is one pytree — tables, CC watermarks, txn pool, RNG, epoch
counter, stats — so a checkpoint is a flat dump of its leaves and resume
is bit-exact: a resumed run continues the *identical* epoch stream the
uninterrupted run would have produced (the RNG key is state, not ambient).

Format: one ``.npz`` with leaves in flatten order plus their key-paths for
a structure sanity check.  The config is not serialized — the caller
recreates the engine from the same `Config` (the reference pins config at
compile time; we pin it at restore time and verify leaf shapes agree).
"""

from __future__ import annotations

import io
import os

import jax
import numpy as np


def save_state(path: str, state) -> None:
    """Dump a state pytree (EngineState or any pytree of arrays)."""
    leaves_p = jax.tree_util.tree_flatten_with_path(state)[0]
    payload = {f"leaf_{i:04d}": np.asarray(jax.device_get(v))
               for i, (_, v) in enumerate(leaves_p)}
    payload["__paths__"] = np.array(
        [jax.tree_util.keystr(p) for p, _ in leaves_p])
    buf = io.BytesIO()
    np.savez(buf, **payload)
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)          # atomic: no torn checkpoints


def load_state(path: str, template):
    """Rebuild a state pytree from ``path`` using ``template`` (a freshly
    initialized state of the same config) for structure and placement."""
    with np.load(path, allow_pickle=False) as z:
        paths = list(z["__paths__"])
        leaves_t, treedef = jax.tree_util.tree_flatten_with_path(template)
        if len(paths) != len(leaves_t):
            raise ValueError(
                f"checkpoint has {len(paths)} leaves, template has "
                f"{len(leaves_t)} — config mismatch?")
        leaves = []
        for i, ((p, t), saved_path) in enumerate(zip(leaves_t, paths)):
            if jax.tree_util.keystr(p) != str(saved_path):
                raise ValueError(
                    f"leaf {i} path mismatch: checkpoint "
                    f"{saved_path!r} vs template {jax.tree_util.keystr(p)!r}")
            v = z[f"leaf_{i:04d}"]
            if hasattr(t, "shape") and tuple(t.shape) != v.shape:
                raise ValueError(
                    f"leaf {jax.tree_util.keystr(p)}: shape {v.shape} != "
                    f"template {tuple(t.shape)} — config mismatch?")
            leaves.append(jax.numpy.asarray(v, dtype=getattr(t, "dtype",
                                                             None)))
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(template), leaves)
