"""Transaction repair engine: salvage aborts by re-executing only the
invalidated slice (PAPERS: *Transaction Repair: Full Serializability
Without Locks*; DGCC's dependency-graph batching, arXiv:1503.03642).

The retry queue treats every abort as total loss: the txn re-enters
admission, re-plans, re-reads everything and pays an exponential backoff
— even when only a fraction of its reads were invalidated by the epoch's
winners.  But every sweep backend already materializes the conflict
incidence the repair literature needs (`cc.base.build_incidence`), so
the invalidated-read frontier of each loser is one matvec away, and the
Calvin chained sub-round machinery (`cc/calvin.py`, `engine/step.
_run_levels`) is the template for executing a second dependent wave
inside the same epoch.  Repair turns the losers of a sweep round into
that second wave:

1. **Frontier** — the backend's invalidation rule
   (``CCBackend.repair_rule``: OCC read-set vs winner write-set, 2PL
   lock-edge losers, T/O wts/rts watermark re-check, MAAT range
   re-intersection) names, per access, which of a loser's reads saw a
   value the committed set overwrote.  Losers with an EMPTY frontier
   lost on write-only conflicts (blind writes recompute — nothing to
   re-read) or on hash collisions; they salvage in the first sub-round.
2. **Mini-validation restricted to the repaired set** — the backend's
   OWN ``validate`` runs on the loser-masked batch (``active=losers``;
   fresh-ts backends restamp above every stamp in the epoch, WAIT_DIE
   keeps its birth ts exactly like its retry path).  Reusing the main
   round's edge derivation is what makes the sub-round sound per
   backend: T/O's later-reader-waits sweep, OCC's serial admission,
   MAAT's mutual-pair/cycle machinery all apply one snapshot later.
3. **Masked re-read + recomputed writes + scatter-apply** — the
   sub-round's winners re-execute through the workload's pure
   re-execution closure (``wl.re_execute``, keyed by txn slot: the
   query pytree row IS the captured plan).  Reads gather the
   post-winner state; lanes OUTSIDE the frontier re-read values nothing
   overwrote, so the full re-gather is bit-identical to a masked
   re-read of only the invalidated keys (the frontier is a bucket-space
   SUPERSET of the true overwrites — `cc.base.committed_write_frontier`).
4. **Chaining** — sub-round r+1's losers re-validate against a
   committed set that includes sub-round r's winners (state threading
   carries T/O watermarks across rounds).  After ``repair_rounds``
   passes the leftovers — cyclic re-invalidation: each pass's winners
   keep invalidating the rest — fall back to the retry queue exactly as
   before.

Serialization order: main-round winners in their verdict order, then
sub-round 1's winners, then sub-round 2's, each sub-round internally
ordered by its own verdict (executed as separate scatter waves, so the
physical apply order IS the serial order).  Each repaired txn re-read
every value it consumes at its new position, and each sub-round's
commit set is conflict-free under the backend's own rule — the chained
sub-round argument of `cc/calvin.py`, applied to salvage.  For the T/O
family the honest caveat mirrors escrow's: repaired txns serialize in
ROUND order at fresh stamps, so commit order — not birth-ts order — is
the serial order, and a cross-round intra-epoch conflict simply fails
the watermark re-check and retries (conservative, never a wrong
commit).

Default-off contract: with ``repair=false`` (default) no caller invokes
anything here and every code path, log byte, verdict plane and ack is
bit-identical to pre-repair — enforced by the graftlint gate family
(``repair`` in `runtime/gates.py`).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


def repair_ts(batch, ts_base=None):
    """Fresh per-lane serialization stamps for the repair sub-rounds:
    unique per lane, preserving lane order (the same relative order the
    retry path's restamp space ``next_seq - B + lane`` would assign).

    ``ts_base`` is the caller's monotone stamp authority when it has
    one: the in-process engine passes its pool's reserved restamp base
    (``next_seq - B`` — strictly above every committed watermark AND
    every stamp in the epoch, exactly like `engine.pool.TxnPool.update`
    restamps aborts).  Without it (the cluster epoch body, which is a
    pure function of its feed — no epoch counter by the replay-
    determinism contract), the fallback is ``max(active ts) + 1``:
    above every watermark whenever the epoch carries at least one fresh
    arrival (the server stamps fresh arrivals monotonically past all
    prior commits); an epoch of ONLY old parked retries can leave the
    fallback at or below a watermark, in which case the T/O re-check
    simply declines the salvage — conservative, never a wrong commit.

    A cross-EPOCH equality collision with a later stamp is benign: the
    T/O checks are strict (``>``), so an equal-ts reader/writer pair
    resolves as reads-committed-value / overwrites-after — consistent
    with the actual commit order — and intra-batch ties are broken by
    lane everywhere (`ops.earlier_edges`)."""
    lane = jnp.arange(batch.ts.shape[0], dtype=jnp.int32)
    if ts_base is None:
        ts_base = jnp.max(jnp.where(batch.active, batch.ts, 0)) + 1
    return ts_base + lane


def run_repair(cfg, wl, be, db, queries, batch, inc, verdict, cc_state,
               stats, exec_commit, forced=None, ts_base=None,
               rounds_cap=None):
    """Run ``cfg.repair_rounds`` fused repair sub-rounds over the epoch's
    losers, inside the SAME jitted epoch program as the main round.

    Inputs are the main round's artifacts: the planned ``batch``, its
    ``inc``idence views, the backend ``verdict`` (post defer-budget
    merge), the threaded ``cc_state`` and the executed commit mask
    ``exec_commit``.  Returns ``(db, cc_state, verdict', salvaged,
    rounds)`` where ``rounds`` is int32[B] naming each salvaged txn's
    sub-round (1-based; 0 = main-round/not salvaged — the audit
    plane's visibility level: a round-r salvage re-read state that
    includes every wave < r) and ``verdict'`` has the salvaged txns
    moved from ``abort`` to ``commit`` — so retry routing, ack planes
    and the abort counters downstream never see a salvaged txn as
    aborted
    (``rep_salvaged_cnt`` counts them instead; the satellite contract
    for `harness/parse.py` compatibility).  Device-counter contract:
    ``rep_salvaged_cnt + rep_fallback_cnt`` equals the repair-eligible
    losers of the epoch, and ``rep_frontier_cnt`` totals invalidated
    read lanes observed across sub-rounds.

    ``forced`` (the ycsb_abort_mode sentinel) txns are logical aborts —
    final answers, never salvaged.

    ``rounds_cap`` (the ctrl plane's repair-budget knob, int32 traced
    scalar): statically-unrolled rounds at index >= cap skip their
    whole body via ``lax.cond`` — real compute saved at low fallback
    rates, not just masked lanes.  None (default) compiles the exact
    pre-ctrl graph; cap == cfg.repair_rounds is value-identical to it
    (every cond takes the live branch)."""
    import jax

    losers = verdict.abort & batch.active
    if forced is not None:
        losers = losers & ~forced
    committed = exec_commit & batch.active
    salvaged = jnp.zeros_like(losers)
    rounds = jnp.zeros_like(batch.rank)
    fresh = repair_ts(batch, ts_base)
    frontier_cnt = stats["rep_frontier_cnt"]

    def one_round(rnd, carry):
        db, cc_state, committed, losers, salvaged, rounds, fcnt, \
            stats_r = carry
        frontier = be.repair_rule(cfg, cc_state, batch, inc, committed,
                                  losers)
        fcnt = fcnt + frontier.sum(dtype=jnp.uint32)
        rb = dataclasses.replace(batch, active=losers)
        if be.fresh_ts_on_restart:
            # restamp like the retry path would — but NOW, not an epoch
            # (plus backoff) later; WAIT_DIE keeps its birth ts (its
            # starvation-freedom) exactly as its retries do
            rb = dataclasses.replace(rb, ts=jnp.where(losers, fresh,
                                                      batch.ts))
        rv, cc_state = be.validate(cfg, cc_state, rb, inc)
        rep = rv.commit & losers
        # masked re-read + recomputed writes + scatter-apply: the
        # workload's pure re-execution closure against CURRENT state
        # (which includes every prior wave's writes — the chained
        # sub-round dataflow)
        stats_r = dict(stats_r)
        db = wl.re_execute(db, queries, rep, rv.order, stats_r)
        salvaged = salvaged | rep
        rounds = jnp.where(rep, jnp.int32(rnd + 1), rounds)
        committed = committed | rep
        # the sub-round's own aborts/defers (still-conflicting losers)
        # chain into the next pass; leftovers past the budget fall back
        losers = losers & ~rep
        return (db, cc_state, committed, losers, salvaged, rounds,
                fcnt, stats_r)

    carry = (db, cc_state, committed, losers, salvaged, rounds,
             frontier_cnt, stats)
    for rnd in range(cfg.repair_rounds):
        if rounds_cap is None:
            carry = one_round(rnd, carry)
        else:
            carry = jax.lax.cond(
                jnp.int32(rnd) < rounds_cap,
                lambda c, r=rnd: one_round(r, c), lambda c: c, carry)
    (db, cc_state, committed, losers, salvaged, rounds, frontier_cnt,
     stats_out) = carry
    # write back through the CALLER'S dict (run_repair's contract is
    # in-place stats mutation, like wl.execute's)
    for k, v in stats_out.items():
        stats[k] = v
    stats["rep_frontier_cnt"] = frontier_cnt
    stats["rep_salvaged_cnt"] = stats["rep_salvaged_cnt"] \
        + salvaged.sum(dtype=jnp.uint32)
    stats["rep_fallback_cnt"] = stats["rep_fallback_cnt"] \
        + losers.sum(dtype=jnp.uint32)
    verdict = dataclasses.replace(
        verdict, commit=verdict.commit | salvaged,
        abort=verdict.abort & ~salvaged)
    return db, cc_state, verdict, salvaged, rounds


def repair_line(node: int, fields: dict) -> str:
    """Per-node ``[repair]`` summary line (parsed by
    `harness.parse.parse_repair`; same fwd/bwd-compat contract as the
    ``[membership]``/``[replication]``/``[admission]`` families)."""
    from deneva_tpu.stats import tagged_line
    return tagged_line("repair", {"node": node, **fields})
