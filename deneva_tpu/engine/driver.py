"""Run lifecycle: warmup / measure / summary (reference `system/sim_manager.*`).

The reference runs free threads against wall-clock timers (WARMUP_TIMER /
DONE_TIMER, `config.h:346-350`; `SimManager::timeout`).  Here the unit of
progress is a compiled chunk of epochs: the driver scans chunks until the
wall-clock window closes, then diffs device counters across the measured
window and emits the reference-compatible ``[summary]`` line
(`statistics/stats.cpp:1470`; parsed by `scripts/parse_results.py`).

Latency: the engine histograms commit latency in *epochs*; the driver
scales bucket centers by the measured seconds/epoch to report
``client_client_latency`` percentiles like `scripts/latency_stats.py:20`.
"""

from __future__ import annotations

import time

import jax
import numpy as np

from deneva_tpu.config import Config
from deneva_tpu.engine.step import Engine, EngineState
from deneva_tpu.stats import Stats
from deneva_tpu.workloads import get_workload


def _counters(state: EngineState) -> dict:
    host = jax.device_get(state.stats)
    return {k: np.asarray(v) for k, v in host.items()}


def _sync(state: EngineState) -> tuple[int, int, np.ndarray, bool]:
    """Real device->host transfer as the pacing barrier.

    `jax.block_until_ready` on a donated scan output can return before
    the execution finishes on tunneled TPU backends (the aliased buffer's
    definition event is already set), letting a wall-clock-bounded loop
    enqueue an unbounded backlog — which wedges the single-client tunnel
    and, past ~50 s of queued work, kills the worker.  A scalar transfer
    cannot complete early, so it both paces the loop and surfaces any
    execution error at the call site.

    Returns (commit_cnt, next_seq, latency_hist, index_overflowed) from
    ONE transfer: a tunnel round trip costs tens of ms, so the seq-wrap
    guard, the per-chunk latency snapshot (the wall-clock calibration
    data, ~512 B) AND the capacity-bounded-index overflow bit must ride
    the pacing fetch rather than pay their own (a second round trip per
    ~1 s chunk measured ~15 % off the headline)."""
    ovf = [t.overflowed()
           for t in (state.db.values() if isinstance(state.db, dict) else ())
           if hasattr(t, "overflowed")]
    c, s, h, o = jax.device_get((state.stats["total_txn_commit_cnt"],
                                 state.pool.next_seq,
                                 state.stats["latency_hist"],
                                 ovf))
    return int(c), int(s), np.asarray(h), any(bool(np.asarray(x))
                                              for x in o)


def run_simulation(cfg: Config, chunk: int = 50,
                   quiet: bool = False) -> Stats:
    """Warmup for ``warmup_secs``, measure for ``done_secs``; returns Stats."""
    wl = get_workload(cfg)
    eng = Engine(cfg, wl)
    state = eng.init_state()
    if cfg.resume and cfg.checkpoint_path:
        from deneva_tpu.engine.checkpoint import load_state
        state = load_state(cfg.checkpoint_path, state)
    if cfg.device_parts > 1:
        # multi-chip: lay the state out over the partition mesh and run
        # under it (tables owner-major sharded, workloads/mc executor)
        from deneva_tpu.parallel import make_mesh, make_sharded_run
        place, run_n = make_sharded_run(eng, make_mesh(cfg.device_parts))
        state = place(state)
    else:
        run_n = eng.jit_run

    ctl = None
    if cfg.ctrl:
        # self-driving control plane (runtime/controller.py): the
        # routed scan replaces jit_run; each chunk boundary folds the
        # device counter deltas into one deterministic decision tick
        # and re-arms the knob pytree for the NEXT chunk (values only —
        # the compile is shared).  config.validate pins ctrl to the
        # single-device metrics-on shape, so this arm never races the
        # multi-chip placement above.
        from deneva_tpu.cc.router import knobs_from_decision, static_knobs
        from deneva_tpu.runtime.controller import (Controller, CtrlSignals,
                                                   ctrl_line)
        ctl = Controller(cfg)
        knobs = [static_knobs(cfg)]
        ctrl_prev = [None]          # baseline counter snapshot

        def run_n(state, n):
            return eng.jit_run_ctrl(state, knobs[0], n)

    ckpt_bound = cfg.checkpoint_every_epochs \
        if cfg.checkpoint_path and cfg.checkpoint_every_epochs else 0
    ckpt_due = [cfg.checkpoint_every_epochs]
    run_t0 = time.monotonic()
    prog_next = [run_t0 + cfg.prog_timer_secs]
    epochs_total = [0]      # cumulative across warmup+measure windows
    seq_per_chunk = [(eng.pool.g + eng.pool.b) * chunk]

    def prog_tick(state):
        # [prog] line every prog_timer_secs (reference PROG_TIMER,
        # system/thread.cpp:86-105)
        now = time.monotonic()
        if quiet or cfg.prog_timer_secs <= 0 or now < prog_next[0]:
            return
        prog_next[0] = now + cfg.prog_timer_secs
        from deneva_tpu.stats import make_prog_line
        print(make_prog_line(now - run_t0, _counters(state),
                             {"epoch_cnt": float(epochs_total[0])}),
              flush=True)

    def _guard_seq(head: int):
        # int32 seq/ts wrap guard (see pool.py docstring): next_seq
        # advances (G + B) per epoch; refuse to run another chunk that
        # could cross 2^31 (checked post-chunk with a 2-chunk margin;
        # `head < 0` catches a wrap that somehow slipped past).  The head
        # value rides _sync's transfer — an extra per-chunk round trip
        # measured ~15 % off the headline on the tunneled chip.
        if head < 0 or head > 2**31 - 2 * seq_per_chunk[0]:
            raise RuntimeError(
                f"int32 txn-sequence space nearly exhausted (next_seq="
                f"{head}); shorten the run window or shrink epoch_batch "
                "(seq advances epoch_batch+gen_chunk per epoch)")

    # per-chunk latency calibration records (epochs, wall secs, hist
    # snapshot): the summary maps each chunk's epoch-valued buckets to
    # wall seconds with THAT chunk's measured pace — not one global mean
    # (round-3's mean-scaled buckets, VERDICT r3 next #6)
    chunk_log: list[tuple[int, float, np.ndarray]] = []
    last_t = [time.monotonic()]

    def _ctrl_tick(state):
        """One controller decision per chunk boundary: diff the device
        counters against the previous tick's snapshot, decide, re-arm.
        The first call only establishes the baseline (the pre-baseline
        chunks run on `static_knobs`, i.e. the unrouted values)."""
        # witness density = CLAIM-VIOLATING edge count (audit_wit_cnt,
        # cc/depgraph.witness_count), not the raw edge-lane volume —
        # chained/DGCC epochs legitimately emit edges, so the raw count
        # would spuriously pin audit_cadence to 1 under any contention
        dens, fb, sv, wit = jax.device_get(
            (state.stats["conflict_density"],
             state.stats["rep_fallback_cnt"],
             state.stats["rep_salvaged_cnt"],
             state.stats["audit_wit_cnt"]))
        now = time.monotonic()
        cur = (np.asarray(dens).astype(np.int64), int(fb), int(sv),
               int(wit), epochs_total[0], now)
        prev, ctrl_prev[0] = ctrl_prev[0], cur
        if prev is None:
            return
        sig = CtrlSignals(
            epoch=epochs_total[0], epochs=cur[4] - prev[4],
            dens=[int(x) for x in cur[0] - prev[0]],
            fallback=cur[1] - prev[1], salvaged=cur[2] - prev[2],
            witnesses=cur[3] - prev[3], breaches=0,
            gap_us=int((now - prev[5]) * 1e6))
        dec = ctl.decide(sig)
        knobs[0] = knobs_from_decision(cfg, dec.assign, dec.gshift,
                                       dec.repair_cap, dec.audit_cadence)
        if not quiet:
            print(ctrl_line(0, sig, dec), flush=True)

    def _after_chunk(state):
        """Shared per-chunk bookkeeping: pacing sync + wrap guard +
        overflow fail-fast + progress + checkpoint cadence."""
        _, head, hist, ovf = _sync(state)
        _guard_seq(head)
        _guard_overflow(ovf)
        if ctl is not None:
            _ctrl_tick(state)
        now = time.monotonic()
        chunk_log.append((chunk, now - last_t[0], hist))
        epochs_total[0] += chunk
        prog_tick(state)
        if ckpt_bound:
            ckpt_due[0] -= chunk
            if ckpt_due[0] <= 0:
                from deneva_tpu.engine.checkpoint import save_state
                save_state(cfg.checkpoint_path, state)
                ckpt_due[0] = ckpt_bound
        # reset AFTER the host-side bookkeeping (prog fetch, checkpoint
        # write) so its cost is charged to no chunk's latency pace
        last_t[0] = time.monotonic()

    def _retarget(state, epochs_per_sec: float, spread: int):
        """ONE resize rule for both calibrations: aim each device call at
        ``chunk_target_secs`` of work, capped by the 20k ceiling (tunnel
        RPC safety) and the checkpoint interval; recompile only when the
        current chunk is off by more than ``spread``x."""
        nonlocal chunk
        target = max(1, min(int(epochs_per_sec * cfg.chunk_target_secs),
                            20_000))
        if ckpt_bound:
            target = min(target, ckpt_bound)
        if target > chunk * spread or target < chunk // spread \
                or (ckpt_bound and chunk > ckpt_bound):
            chunk = target
            seq_per_chunk[0] = (eng.pool.g + eng.pool.b) * chunk
            state = run_n(state, chunk)     # one compile at the new n
            _after_chunk(state)
        return state

    def _guard_overflow(ovf: bool):
        # fail-fast surfacing for capacity-bounded index structures
        # (DynamicSortedIndex contract): past overflow, probes may return
        # slots of ring-overwritten rows — refuse at the FIRST overflowed
        # chunk instead of burning the whole window (ADVICE r4); the bit
        # rides the existing pacing fetch so it costs no extra round trip
        if ovf:
            raise RuntimeError(
                "a capacity-bounded index overflowed during the run "
                "(stale lookups possible); raise its capacity "
                "(insert_table_cap) or shorten the run")

    # pre-flight wrap check (a resumed checkpoint may sit near int32 seq
    # exhaustion, e.g. after an epoch_batch change): refuse before the
    # first unguarded calibration chunk, not after
    _guard_seq(int(jax.device_get(state.pool.next_seq)))
    # compile once (excluded from both windows, like the reference's setup
    # barrier, system/thread.cpp:62-84)
    state = run_n(state, chunk)
    _guard_seq(_sync(state)[1])
    last_t[0] = time.monotonic()
    # adaptive chunking: size each device call to ~chunk_target_secs —
    # large enough that the per-call sync round-trip (tens of ms on a
    # tunneled chip) stays in the noise, small enough that no single
    # execution approaches the tunnel's multi-second RPC limits
    t1 = time.monotonic()
    state = run_n(state, chunk)
    _guard_seq(_sync(state)[1])
    last_t[0] = time.monotonic()
    per_chunk = max(last_t[0] - t1, 1e-4)
    state = _retarget(state, chunk / per_chunk, spread=2)

    def run_window(state, secs):
        t0 = time.monotonic()
        ep0 = epochs_total[0]
        while time.monotonic() - t0 < secs:
            state = run_n(state, chunk)
            _after_chunk(state)
        return state, epochs_total[0] - ep0, time.monotonic() - t0

    state, ep_w, el_w = run_window(state, cfg.warmup_secs)
    # re-calibrate against STEADY-STATE epoch time: early epochs can be
    # far cheaper than saturated ones (e.g. T/O at high contention — hot
    # retry keys serialize the watermark scatters), and an optimistic
    # chunk would run one multi-minute device call in the measure window
    # (unsafe past ~50 s on the tunneled chip)
    if ep_w:
        state = _retarget(state, ep_w / max(el_w, 1e-4), spread=3)
    before = _counters(state)
    chunk_log.clear()                 # calibrate over the measure window
    last_t[0] = time.monotonic()
    t_start = time.monotonic()
    state, epochs, elapsed = run_window(state, cfg.done_secs)
    after = _counters(state)

    st = Stats()
    st._t_start = t_start
    st._t_end = t_start + elapsed
    st.set("total_runtime", elapsed)
    st.set("epoch_cnt", float(epochs))
    for k in ("generated_cnt", "admitted_cnt", "total_txn_commit_cnt",
              "total_txn_abort_cnt", "unique_txn_abort_cnt", "defer_cnt",
              "write_cnt"):
        st.set(k, float(after[k] - before[k]))
    if cfg.repair:
        # repair counters ([summary] satellite): salvaged txns committed
        # (NOT double-counted as aborts — total_txn_abort_cnt already
        # excludes them at the source, engine/repair.run_repair),
        # invalidated read lanes, and retry-queue fallbacks.  Emitted
        # only when armed so the default summary line is byte-identical.
        for k in ("rep_salvaged_cnt", "rep_frontier_cnt",
                  "rep_fallback_cnt"):
            st.set(k, float(after[k] - before[k]))
    if cfg.metrics:
        # metrics bus ([summary] satellite): cumulative per-partition
        # observed-conflict density over the measured window (the
        # per-epoch series is the cluster bus's job; in-process runs
        # get the window totals).  Emitted only when armed so the
        # default summary line is byte-identical.
        dens = (after["conflict_density"]
                - before["conflict_density"]).astype(np.float64)
        for i, d in enumerate(dens):
            st.set(f"mb_density_p{i}", float(d))
        st.set("mb_density_total", float(dens.sum()))
    if cfg.audit:
        # isolation audit ([summary] satellite): dependency edge lanes
        # observed among committed txns + export-cap overflows over the
        # measured window (the sidecar export is the cluster runtime's
        # job — in-process runs surface the device counters).  Emitted
        # only when armed so the default summary line is byte-identical.
        for k in ("audit_edge_cnt", "audit_drop_cnt", "audit_wit_cnt"):
            st.set(k, float(after[k] - before[k]))
    if cfg.ctrl:
        # control plane ([summary] satellite): decision ticks taken and
        # governor trips over the whole run (the per-tick record is the
        # [ctrl] line stream).  Emitted only when armed so the default
        # summary line is byte-identical.
        st.set("ctrl_decisions", float(ctl.seq))
        st.set("ctrl_trips", float(ctl.stale_trips))
    from deneva_tpu.config import CCAlg
    if cfg.cc_alg == CCAlg.DGCC or cfg.ctrl_dgcc:
        # DGCC wavefront ledger ([summary] satellite + the [dgcc] line,
        # parsed by harness.parse.parse_dgcc): waves executed over the
        # window, the deepest single-epoch wavefront of the WHOLE run
        # (a device-side running max — no windowed delta exists),
        # over-deep closures deferred (the cyclic fallback), and
        # pre-commit dependency edges.  Emitted only when DGCC can
        # validate so every other config's output is byte-identical.
        for k in ("dgcc_wave_cnt", "dgcc_fallback_cnt", "dgcc_edge_cnt"):
            st.set(k, float(after[k] - before[k]))
        st.set("dgcc_wave_max", float(after["dgcc_wave_max"]))
        if not quiet:
            from deneva_tpu.stats import tagged_line
            print(tagged_line("dgcc", {
                "node": 0,
                "waves": int(after["dgcc_wave_cnt"]
                             - before["dgcc_wave_cnt"]),
                "wave_max": int(after["dgcc_wave_max"]),
                "fallback": int(after["dgcc_fallback_cnt"]
                                - before["dgcc_fallback_cnt"]),
                "edges": int(after["dgcc_edge_cnt"]
                             - before["dgcc_edge_cnt"])}), flush=True)
    for i, nm in enumerate(getattr(wl, "txn_type_names", ())):
        for fam in ("commit", "abort"):
            key = f"{fam}_by_type"
            st.set(f"{nm}_{fam}_cnt", float(after[key][i] - before[key][i]))
    commits = after["total_txn_commit_cnt"] - before["total_txn_commit_cnt"]
    aborts = after["total_txn_abort_cnt"] - before["total_txn_abort_cnt"]
    # every committed txn contributes exactly one latency sample (its
    # commit-epoch minus entry-epoch, engine latency_hist), calibrated
    # to wall seconds with the PACE OF ITS OWN CHUNK (epoch timestamps
    # per chunk; the weighted StatsArr keeps the full multiset — no
    # cap, no synthesis).  Per-type families feed {type}_latency_*;
    # the combined series keeps the reference-compatible name.
    type_names = list(getattr(wl, "txn_type_names", ("txn",)))
    lb = after["latency_hist"].shape[-1]
    prev = before["latency_hist"].astype(np.float64)
    for n_ep, secs, snap in chunk_log:
        cur = snap.astype(np.float64)
        delta = cur - prev
        prev = cur
        spe = secs / max(n_ep, 1)
        centers = (np.arange(lb) + 0.5) * spe
        for i, nm in enumerate(type_names):
            row = delta[i] if delta.ndim == 2 else delta
            if row.sum() > 0:
                st.arr(f"{nm}_latency").extend_weighted(centers, row)
                st.arr("client_client_latency").extend_weighted(
                    centers, row)
    # per-txn restart/wait decomposition (TxnStats analogue): counts of
    # retries and waited epochs each committed txn paid
    for key, name in (("retry_hist", "txn_retries"),
                      ("wait_hist", "txn_waits")):
        d = (after[key] - before[key]).astype(np.float64)
        if d.sum() > 0:
            st.arr(name).extend_weighted(np.arange(len(d)), d)
    st.set("abort_rate", float(aborts) / max(float(commits + aborts), 1.0))
    # named backstop for the per-chunk _guard_overflow fail-fast (also
    # covers overflow in the final partial chunk): past overflow, probes
    # may return slots of ring-overwritten rows — refuse to report
    for name, t in (state.db.items() if isinstance(state.db, dict) else ()):
        if hasattr(t, "overflowed") and bool(
                np.asarray(jax.device_get(t.overflowed()))):
            raise RuntimeError(
                f"index {name!r} overflowed its capacity during the run "
                "(stale lookups possible); raise its capacity "
                "(insert_table_cap) or shorten the run")
    if cfg.checkpoint_path:
        from deneva_tpu.engine.checkpoint import save_state
        save_state(cfg.checkpoint_path, state)
    if not quiet:
        print(st.summary_line())
    return st
