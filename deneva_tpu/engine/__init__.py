"""The epoch engine — L2-L5 of the reference collapsed into a jitted step.

Worker threads + work/abort queues + txn table (`system/worker_thread.cpp`,
`work_queue.cpp`, `abort_queue.cpp`, `txn_table.cpp`) become a
device-resident transaction pool plus one compiled epoch step:

    refill -> select -> plan -> validate (CC) -> execute -> update pool

scanned over epochs without host round-trips (`lax.scan`).
"""

from deneva_tpu.engine.pool import TxnPool, PoolState  # noqa: F401
from deneva_tpu.engine.step import Engine, EngineState  # noqa: F401
