"""The jitted epoch step + scan driver (reference call stack §3.B collapsed).

One epoch performs what the reference spreads over client threads, IO
threads, worker threads, the CC managers and 2PC:

    refill   — admit fresh queries        (client_thread + new_txn_queue)
    select   — oldest-B runnable txns     (work_queue dequeue loop)
    plan     — declare padded RW-sets     (ycsb/tpcc/pps txn state machines)
    validate — CC backend verdict         (concurrency_control/*)
    execute  — gather/compute/scatter     (row_t reads + return_row commits)
    update   — free/backoff/park slots    (txn_table + abort_queue)

Everything is one XLA program; `run_epochs` wraps it in `lax.scan` so a
benchmark window runs thousands of epochs without leaving the device.
2PC itself has no analogue: epoch-snapshot validation decides all
participants of a txn at once (the conflict matrix *is* the vote), which
is precisely why the TPU build can win — prepare/ack round-trips
(`system/txn.cpp:498-606`) become matmul cycles.

Chained backends (CALVIN/TPU_BATCH) execute ``exec_subrounds`` waves:
level-l txns read state that already includes writes of levels < l —
deterministic dataflow equal to serial execution in sequence order.
"""

from __future__ import annotations

import dataclasses
import functools
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from deneva_tpu.cc import (AccessBatch, build_conflict_incidence,
                           conflict_density, gate_order_free, get_backend)
from deneva_tpu.config import CCAlg, Config, Mode
from deneva_tpu.engine.pool import PoolState, TxnPool
from deneva_tpu.ops import (forward_verdict, forwarding_applies,
                            mc_defer_verdict)

LAT_BUCKETS = 64
RETRY_BUCKETS = 8      # per-txn restart/wait counts at commit (clipped)


def forced_sentinel_mask(batch):
    """YCSB_ABORT_MODE (reference `config.h:103`, `ycsb_txn.cpp:243-246`):
    a sentinel condition forces a logical abort, exercising the abort
    accounting deterministically.  Batch analogue: a txn whose RW-set
    touches key 0 logically aborts — ONCE: it releases its slot like a
    completed txn (a logical abort is a final answer, not a retry; an
    ever-firing sentinel would otherwise fill the pool with immortal
    txns).  Under the forwarding executor the forced txns are removed
    from the batch BEFORE dependency resolution, so no reader ever
    observes an aborted txn's write."""
    return ((batch.keys == 0) & batch.valid).any(axis=1) & batch.active


@dataclass
class EngineState:
    db: Any                 # dict[str, DeviceTable]
    cc_state: Any
    pool: PoolState
    rng: jax.Array
    epoch: jax.Array        # int32
    stats: dict             # str -> device scalar / latency histogram


jax.tree_util.register_dataclass(
    EngineState,
    data_fields=["db", "cc_state", "pool", "rng", "epoch", "stats"],
    meta_fields=[])


def init_device_stats(n_txn_types: int = 1, n_parts: int = 1) -> dict:
    z = lambda: jnp.zeros((), jnp.uint32)  # noqa: E731
    return {
        # per-partition observed-conflict density (cc/base.
        # conflict_density; the metrics bus's contention signal and the
        # contention-adaptive router's input).  Always present so the
        # stats pytree shape depends only on the config; stays zero
        # unless metrics is armed.
        "conflict_density": jnp.zeros((max(n_parts, 1),), jnp.uint32),
        "generated_cnt": z(), "admitted_cnt": z(),
        "total_txn_commit_cnt": z(), "total_txn_abort_cnt": z(),
        "unique_txn_abort_cnt": z(),
        "defer_cnt": z(), "write_cnt": z(), "read_checksum": z(),
        # commit latency in epochs, PER TXN TYPE (round-4: the
        # reference's per-txn StatsArr families, stats_array.cpp);
        # the driver calibrates buckets to wall seconds per chunk
        "latency_hist": jnp.zeros((n_txn_types, LAT_BUCKETS), jnp.uint32),
        # per-txn work decomposition at commit time (reference TxnStats,
        # system/txn.h:72-114): how many restarts (abort_cnt) and how
        # many waited epochs (defer_cnt) each committed txn paid
        "retry_hist": jnp.zeros((RETRY_BUCKETS,), jnp.uint32),
        "wait_hist": jnp.zeros((RETRY_BUCKETS,), jnp.uint32),
        # transaction repair (engine/repair.py, Config.repair): txns
        # salvaged by in-epoch re-execution (committed, NOT counted in
        # total_txn_abort_cnt), invalidated read lanes observed, and
        # losers that exhausted repair_rounds and fell back to the
        # retry queue.  Always present (pytree structure is config-
        # independent); stay zero unless repair is armed.
        "rep_salvaged_cnt": z(), "rep_frontier_cnt": z(),
        "rep_fallback_cnt": z(),
        # isolation audit plane (cc/base.audit_observe, Config.audit):
        # dependency edge-lanes observed among committed txns, export-
        # cap overflows, and CLAIM-VIOLATING edges (both endpoints at
        # level 0 of a zero-edge-claim backend — cc/depgraph.
        # witness_count, the controller's witness-density signal).
        # Always present (pytree structure is config-independent); stay
        # zero unless audit is armed.
        "audit_edge_cnt": z(), "audit_drop_cnt": z(),
        "audit_wit_cnt": z(),
        # DGCC wavefront backend (cc/dgcc.py, CC_ALG=DGCC): waves
        # executed (sum over epochs), deepest single-epoch wavefront,
        # over-deep closures deferred to the retry queue (the cyclic
        # fallback), and dependency edges in the pre-commit lane graph.
        # Always present; stay zero unless DGCC validates.
        "dgcc_wave_cnt": z(), "dgcc_wave_max": z(),
        "dgcc_fallback_cnt": z(), "dgcc_edge_cnt": z(),
        # per-txn-kind commit/abort breakdown (reference Stats_thd's
        # per-type counter families); names come from
        # Workload.txn_type_names at summary time
        "commit_by_type": jnp.zeros((n_txn_types,), jnp.uint32),
        "abort_by_type": jnp.zeros((n_txn_types,), jnp.uint32),
    }


def count_by_type(stats: dict, wl, queries, commit, abort) -> None:
    """Fold per-type commit/abort one-hots into the device stats (cheap
    dense compare-and-sum, same shape trick as the latency histogram)."""
    tt = wl.txn_type_of(queries)
    n = stats["commit_by_type"].shape[0]
    onehot = tt[:, None] == jnp.arange(n, dtype=jnp.int32)[None, :]
    stats["commit_by_type"] = stats["commit_by_type"] + \
        (onehot & commit[:, None]).sum(axis=0, dtype=jnp.uint32)
    stats["abort_by_type"] = stats["abort_by_type"] + \
        (onehot & abort[:, None]).sum(axis=0, dtype=jnp.uint32)


def _run_levels(cfg, wl, db, queries, exec_commit, verdict, stats,
                level_exec=True):
    """Chained sub-round execution to the DYNAMIC depth of this epoch:
    the wavefront executor — wave k re-reads only rows written by waves
    < k (each pass gathers from the db the previous passes scattered).

    Level-l txns read state that includes all writes of levels < l (the
    deterministic lock-queue order).  A `lax.while_loop` runs exactly
    ``max committed level + 1`` passes instead of unrolling the full
    ``exec_subrounds`` budget — at low contention most epochs execute 1-2
    levels, so a generous budget (deep-chain admission) no longer costs
    idle full-batch passes on shallow epochs.

    ``level_exec=True`` (CALVIN/TPU_BATCH): each level's committed set
    is write-conflict-free by construction (true conflicts are a subset
    of the hashed over-approximation), so executors skip the
    ``last_writer`` scatter-max tournament.  ``level_exec=False``
    (DGCC): a wave may carry several writers of one key — rw anti-
    dependencies and blind ww chains serialize by the in-wave order
    tournament instead of extra waves, which is what keeps DGCC's
    wavefront shallow at write-heavy contention.
    """
    lv_max = jnp.max(jnp.where(exec_commit, verdict.level, 0))

    def cond(carry):
        lvl, _, _ = carry
        return lvl <= lv_max

    def body(carry):
        lvl, db, stats = carry
        m = exec_commit & (verdict.level == lvl)
        stats = dict(stats)
        db = wl.execute(db, queries, m, verdict.order, stats,
                        level_exec=level_exec)
        return lvl + 1, db, stats

    _, db, stats = jax.lax.while_loop(
        cond, body, (jnp.zeros((), jnp.int32), db, stats))
    return db, stats


class Engine:
    """Binds (config, workload, cc backend) into jitted step/scan fns."""

    def __init__(self, cfg: Config, workload):
        self.cfg = cfg
        self.workload = workload
        self.backend = get_backend(cfg.cc_alg)
        cap = max(cfg.max_txn_in_flight, cfg.epoch_batch)
        self.pool = TxnPool(capacity=cap, batch=cfg.epoch_batch,
                            gen_chunk=cfg.epoch_batch,
                            backoff=cfg.backoff)

    # ------------------------------------------------------------------
    def init_state(self, seed: int | None = None) -> EngineState:
        cfg = self.cfg
        db = self.workload.load()
        empty_q = self.workload.generate(
            jax.random.PRNGKey(0), self.pool.p)
        pool = self.pool.create(jax.tree.map(jnp.zeros_like, empty_q))
        return EngineState(
            db=db, cc_state=self.backend.init_state(cfg), pool=pool,
            rng=jax.random.PRNGKey(cfg.seed if seed is None else seed),
            epoch=jnp.zeros((), jnp.int32),
            stats=init_device_stats(len(self.workload.txn_type_names),
                                    max(cfg.part_cnt, 1)))

    # ------------------------------------------------------------------
    def step(self, state: EngineState, knobs=None) -> EngineState:
        if knobs is not None:
            # contention-adaptive router (Config.ctrl, cc/router.py):
            # the controller's per-epoch knob pytree selects the CC
            # branch + incidence granularity per partition.  knobs=None
            # (the default, and the only path when ctrl is off) is this
            # exact pre-ctrl body, untouched.
            return self._routed_step(state, knobs)
        cfg, wl, be = self.cfg, self.workload, self.backend
        rng, gen_key = jax.random.split(state.rng)
        stats = dict(state.stats)

        # 1. admit fresh queries
        newq = wl.generate(gen_key, self.pool.g)
        pool, admitted = self.pool.refill(state.pool, newq, state.epoch)
        stats["generated_cnt"] += jnp.uint32(self.pool.g)
        stats["admitted_cnt"] += admitted.astype(jnp.uint32)

        # 2. select epoch batch (full-pool mode: identity, no gathers)
        slots, active, queries = self.pool.select(pool, state.epoch)
        sel = (lambda v: v) if self.pool.full_pool \
            else (lambda v: jnp.take(v, slots))

        # 3. plan RW-sets (order_free rides the batch pre-gated so the
        # incidence builder and the T/O watermark rules cannot disagree)
        planned = wl.plan(state.db, queries)
        batch = AccessBatch(
            table_ids=planned["table_ids"], keys=planned["keys"],
            is_read=planned["is_read"], is_write=planned["is_write"],
            valid=planned["valid"],
            ts=sel(pool.ts), rank=sel(pool.seq),
            active=active,
            order_free=gate_order_free(cfg, be,
                                       planned.get("order_free")))

        # 4. validate
        forwarding = forwarding_applies(be, wl) and cfg.mode == Mode.NORMAL
        fwd = None
        inc = None
        forced = forced_sentinel_mask(batch) if cfg.ycsb_abort_mode else None
        if cfg.mode == Mode.NOCC:
            nocc = get_backend("NOCC")
            verdict, cc_state = nocc.validate(cfg, state.cc_state, batch, None)
        elif forwarding:
            # single-pass forwarding executor (ops/forward): everything
            # commits in rank order; the sort IS the validation.  Forced
            # sentinel txns leave the batch before dependency resolution
            # so their (never-applied) writes are invisible to readers.
            fbatch = batch if forced is None else dataclasses.replace(
                batch, active=batch.active & ~forced)
            if cfg.device_parts > 1:
                # multi-chip: plans are built per-shard inside
                # wl.execute_mc, which also decides the capacity-
                # overflow defers shard-locally (O(N/D)) and returns the
                # replicated mask — the verdict is built after execution
                # (`mc_defer_verdict`; forwarding implies Mode.NORMAL,
                # so the execute below always runs)
                verdict = None
                mc_batch = fbatch
            else:
                verdict, fwd = forward_verdict(fbatch)
                mc_batch = None
            cc_state = state.cc_state
        else:
            inc = build_conflict_incidence(cfg, be, batch,
                                           batch.order_free)
            if be.alg == CCAlg.DGCC:
                # DGCC takes the stats dict (repair-engine contract):
                # its wave/fallback/edge counters come from inside the
                # wave assignment, where the lane graph is in hand
                verdict, cc_state = be.validate(cfg, state.cc_state,
                                                batch, inc, stats=stats)
            else:
                verdict, cc_state = be.validate(cfg, state.cc_state,
                                                batch, inc)
            if cfg.audit_mutate:
                # seeded edge-derivation fault (the audit plane's
                # anti-inert knob): flipped losers execute and ack like
                # any commit — a real violation the certifier must catch
                from deneva_tpu.cc import audit_mutate_verdict
                verdict = audit_mutate_verdict(cfg, batch, inc, verdict,
                                               state.epoch)
        if cfg.metrics:
            # metrics bus (runtime/metricsbus.py): accumulate the
            # per-partition observed-conflict density off the incidence
            # views (the sweep already materialized them; forwarding
            # backends pay two bucket scatter-adds).  Multi-chip is
            # pinned OUT by config.validate (sharded tables have no
            # single bucket space to fold) — a validated error, not a
            # silent skip, so an armed knob can never quietly no-op.
            owner = planned.get("owner",
                                batch.keys % jnp.int32(max(cfg.part_cnt,
                                                           1)))
            stats["conflict_density"] = stats["conflict_density"] + \
                conflict_density(cfg, batch, owner, inc).astype(jnp.uint32)
        # defer budget (defer_rounds_max, WAIT_DIE-style wait timeout): a
        # txn deferred past the budget force-restarts with fresh ts +
        # backoff — the liveness backstop for waits that never resolve
        # on their own (e.g. a MAAT cycle longer than 2^closure_rounds
        # evading conviction).  Deterministic backends are exempt: their
        # defers are part of the replicated decision and resolve by
        # construction (the committed prefix always advances).
        if not be.chained and cfg.defer_rounds_max > 0:
            stuck = verdict.defer & active \
                & (sel(pool.defer_cnt) >= jnp.int32(cfg.defer_rounds_max))
            verdict = dataclasses.replace(
                verdict, abort=verdict.abort | stuck,
                defer=verdict.defer & ~stuck)
        def finalize(verdict, forced):
            # a forced txn completes-as-aborted only when the CC would
            # not retry it anyway (CC aborts/defers follow their normal
            # path); released slots are real commits + forced completions
            if forced is None:
                return None, verdict.commit, verdict.commit
            forced = forced & ~(verdict.abort | verdict.defer)
            return (forced, verdict.commit & ~forced,
                    verdict.commit | forced)

        if verdict is not None:
            forced, exec_commit, release = finalize(verdict, forced)

        # 5. execute committed txns (the multi-chip forwarding path
        # produces its verdict here, from the capacity defer mask)
        db = state.db
        if cfg.mode in (Mode.NORMAL, Mode.NOCC):
            if forwarding:
                if cfg.device_parts > 1:
                    db, mc_dfr = wl.execute_mc(db, mc_batch, stats)
                    verdict = mc_defer_verdict(fbatch, mc_dfr)
                    forced, exec_commit, release = finalize(verdict,
                                                            forced)
                else:
                    # commit set baked into the plan (fbatch.active);
                    # mask=None is asserted by the executor so the two
                    # cannot diverge
                    db = wl.execute(db, queries, None, verdict.order,
                                    stats, fwd_rank=fwd)
            elif cfg.device_parts > 1:
                # generic partition-parallel execution (workloads/mc):
                # replicated verdict, owner-major sharded tables, the
                # workload's own execute body per chip under shard_map
                from deneva_tpu.workloads.mc import mc_execute
                db = mc_execute(cfg, wl, db, queries, exec_commit,
                                verdict.order, verdict.level, stats,
                                chained=be.chained and cfg.mode == Mode.NORMAL,
                                level_exec=be.alg != CCAlg.DGCC,
                                n_levels=cfg.dgcc_levels
                                if be.alg == CCAlg.DGCC else None)
            elif be.chained and cfg.mode == Mode.NORMAL:
                db, stats = _run_levels(cfg, wl, db, queries, exec_commit,
                                        verdict, stats,
                                        level_exec=be.alg != CCAlg.DGCC)
            else:
                db = wl.execute(db, queries, exec_commit, verdict.order,
                                stats)
        # Mode.SIMPLE / QRY_ONLY: ack without touching tables
        # (reference SIMPLE_MODE / QRY_ONLY_MODE, config.h:276-281)

        srounds = None
        # 5b. transaction repair (engine/repair.py, default off): the
        # losers of the sweep re-execute as chained sub-rounds against
        # the post-winner state inside this same jitted step; salvaged
        # txns move abort -> commit (and release their slot like any
        # commit) before the pool update and the counters below ever
        # see them.  Gated exactly like the validate path it extends:
        # sweep backend, NORMAL mode (multi-chip is a config.validate
        # error, never a silent skip here).
        if cfg.repair and cfg.mode == Mode.NORMAL and not forwarding \
                and be.repair_rule is not None:
            from deneva_tpu.engine.repair import run_repair
            # ts_base: the pool's reserved restamp space — the exact
            # stamp authority pool.update uses for abort restamps, so
            # repaired stamps sit strictly above every committed
            # watermark and every stamp in this epoch
            db, cc_state, verdict, salvaged, srounds = run_repair(
                cfg, wl, be, db, queries, batch, inc, verdict, cc_state,
                stats, exec_commit, forced,
                ts_base=pool.next_seq - jnp.int32(self.pool.b))
            exec_commit = exec_commit | salvaged
            release = release | salvaged

        # 5c. isolation audit (cc/base.audit_observe, default off): an
        # OBSERVATION of the final committed set — never an input to any
        # verdict or table write, so armed-vs-off row state is
        # bit-identical (tested).  The in-process engine keeps the stamp
        # tables + device counters; the sidecar export is the cluster
        # runtime's job (runtime/audit.py).
        # (multi-chip is a config.validate error, never a silent skip)
        if cfg.audit and cfg.mode == Mode.NORMAL:
            from deneva_tpu.cc import AUDIT_KEY, audit_observe
            order_vis = forwarding
            if forwarding:
                lvl = jnp.zeros_like(verdict.level)
            elif be.chained:
                lvl = verdict.level
            else:
                lvl = srounds if srounds is not None \
                    else jnp.zeros_like(verdict.level)
            aud2, _e, _bk, cnt, drop, _vd, _rd = audit_observe(
                cfg, batch, exec_commit & active, verdict.order, lvl,
                order_vis, db[AUDIT_KEY], state.epoch)
            db = dict(db)
            db[AUDIT_KEY] = aud2
            stats["audit_edge_cnt"] += cnt.astype(jnp.uint32)
            stats["audit_drop_cnt"] += drop.astype(jnp.uint32)
            if not forwarding and not be.chained:
                # witness density (the controller's certificate-pressure
                # signal): a level-0 sweep backend claims a conflict-
                # free committed set, so any edge between two level-0
                # commits is a claim violation — chained waves and
                # forwarded ranks carry legitimate edges and skip this
                from deneva_tpu.cc.depgraph import witness_count
                stats["audit_wit_cnt"] += witness_count(
                    _e, lvl).astype(jnp.uint32)

        # 6. update pool + counters (forced txns release like commits)
        pre_abort_cnt = sel(pool.abort_cnt)   # pre-update: 0 = never aborted
        pool = self.pool.update(pool, slots, active, release,
                                verdict.abort, state.epoch,
                                be.fresh_ts_on_restart)
        ncommit = (exec_commit & active).sum(dtype=jnp.uint32)
        stats["total_txn_commit_cnt"] += ncommit
        aborts = verdict.abort if forced is None else verdict.abort | forced
        stats["total_txn_abort_cnt"] += (aborts & active).sum(dtype=jnp.uint32)
        # exact unique-txn aborts (reference stats.h:60-61 counts each
        # txn's FIRST abort): the slot's abort_cnt — reset on admission,
        # bumped per abort — is zero exactly at a txn's first abort
        stats["unique_txn_abort_cnt"] += (
            aborts & active & (pre_abort_cnt == 0)).sum(dtype=jnp.uint32)
        count_by_type(stats, wl, queries, exec_commit & active,
                      aborts & active)
        stats["defer_cnt"] += (verdict.defer & active).sum(dtype=jnp.uint32)
        # histograms as one-hot reductions: a 64-bucket scatter-add over
        # the batch serializes on bucket contention on TPU (~4.5 ms at
        # 64k lanes on v5e); the dense compare-and-sum is ~free.
        # latency_hist is PER TYPE (static unrolled — n_types is 2-8):
        # the reference's per-txn-kind StatsArr latency families
        committed = exec_commit & active
        lat = jnp.clip(state.epoch - sel(pool.entry_epoch),
                       0, LAT_BUCKETS - 1)
        onehot = (lat[:, None] == jnp.arange(LAT_BUCKETS, dtype=jnp.int32)) \
            & committed[:, None]
        ttype = wl.txn_type_of(queries) if len(
            getattr(wl, "txn_type_names", ("txn",))) > 1 else None
        rows = []
        for t in range(stats["latency_hist"].shape[0]):
            m = onehot if ttype is None \
                else onehot & (ttype == t)[:, None]
            rows.append(m.sum(axis=0, dtype=jnp.uint32))
        stats["latency_hist"] = stats["latency_hist"] + jnp.stack(rows)
        # per-txn restart/wait decomposition at commit (TxnStats
        # analogue, system/txn.h:72-114): pre-update counters are the
        # txn's whole-life totals since its slot (re)admission
        rb = jnp.arange(RETRY_BUCKETS, dtype=jnp.int32)
        retries = jnp.clip(pre_abort_cnt, 0, RETRY_BUCKETS - 1)
        waits = jnp.clip(sel(pool.defer_cnt), 0, RETRY_BUCKETS - 1)
        stats["retry_hist"] = stats["retry_hist"] + (
            (retries[:, None] == rb) & committed[:, None]).sum(
            axis=0, dtype=jnp.uint32)
        stats["wait_hist"] = stats["wait_hist"] + (
            (waits[:, None] == rb) & committed[:, None]).sum(
            axis=0, dtype=jnp.uint32)

        return EngineState(db=db, cc_state=cc_state, pool=pool, rng=rng,
                           epoch=state.epoch + 1, stats=stats)

    # ------------------------------------------------------------------
    def _routed_step(self, state: EngineState, knobs) -> EngineState:
        """One epoch under the contention-adaptive router (PR 16
        tentpole; only reachable through ``step(state, knobs)`` with a
        non-None ``RouterKnobs``, which config.validate arms only under
        ``ctrl`` — metrics on, Mode.NORMAL, single device, candidate
        cc_alg, no forced-abort/audit-mutate/escrow special paths).

        Sections 1-3 (admit/select/plan) and section 6 (pool update +
        counters) are the static step's, shared OUTSIDE the routed
        switch.  Section 4-5 becomes a ``lax.switch`` over
        ``candidates(cfg)``: one branch per uniform candidate backend —
        each replicating the static step's exact
        validate/execute/repair/audit dataflow for that backend — plus
        a mixed-assignment branch (always last) that validates each
        backend's sub-batch against the shared (coarsened) incidence
        and defers the cross-group conflict surface symmetrically
        (`cc/router.cross_group_defer`).  Under ``ctrl_dgcc`` a fourth
        uniform branch runs the DGCC wavefront (index 3, the
        controller's HOT class), and the mixed branch moves to index 4;
        unarmed, the compiled 4-way program is bit-identical to the
        PR 16 plane.  With ``static_knobs(cfg)``
        every epoch takes the uniform branch of ``cfg.cc_alg`` with
        gshift=0 / cap=repair_rounds / cadence=cfg.audit_cadence, and
        the outputs are value-identical to the unrouted step (pinned by
        tests/test_ctrl.py).

        Branch contract: each returns ``(db, stats, exec_commit,
        release, abort, defer)`` with identical pytree structure (every
        stats key pre-exists in `init_device_stats`), so the switch is
        shape-stable and knob VALUES never recompile.
        """
        from deneva_tpu.cc import Verdict
        from deneva_tpu.cc.router import (candidates, coarsen_keys,
                                          cross_group_defer, txn_backend)
        cfg, wl = self.cfg, self.workload
        rng, gen_key = jax.random.split(state.rng)
        stats = dict(state.stats)

        # 1. admit fresh queries (identical to the static step)
        newq = wl.generate(gen_key, self.pool.g)
        pool, admitted = self.pool.refill(state.pool, newq, state.epoch)
        stats["generated_cnt"] += jnp.uint32(self.pool.g)
        stats["admitted_cnt"] += admitted.astype(jnp.uint32)

        # 2. select epoch batch
        slots, active, queries = self.pool.select(pool, state.epoch)
        sel = (lambda v: v) if self.pool.full_pool \
            else (lambda v: jnp.take(v, slots))

        # 3. plan RW-sets (exact keys; the router only ever coarsens
        # the conflict-derivation VIEW below)
        planned = wl.plan(state.db, queries)
        batch = AccessBatch(
            table_ids=planned["table_ids"], keys=planned["keys"],
            is_read=planned["is_read"], is_write=planned["is_write"],
            valid=planned["valid"],
            ts=sel(pool.ts), rank=sel(pool.seq),
            active=active,
            order_free=gate_order_free(cfg, self.backend,
                                       planned.get("order_free")))

        # router views: owner partitions anchor both the per-partition
        # knob lookups and the density fold (same fallback hash as the
        # static metrics block); cbatch carries the per-partition
        # coarsened conflict keys (gshift=0 -> bit-identical to batch)
        owner = planned.get("owner",
                            batch.keys % jnp.int32(max(cfg.part_cnt, 1)))
        cbatch = coarsen_keys(batch, owner, knobs.gshift)
        group = txn_backend(knobs, owner)
        # config-dependent candidate list: without ctrl_dgcc this is
        # exactly the 3-class tuple, so the compiled 4-way switch (and
        # every [ctrl] replay) is bit-identical to the pre-DGCC plane
        backends = [get_backend(a) for a in candidates(cfg)]

        def density_into(st, inc):
            st["conflict_density"] = st["conflict_density"] + \
                conflict_density(cfg, cbatch, owner, inc).astype(jnp.uint32)

        def audit_into(db, st, exec_commit, order, lvl, order_vis,
                       claim_zero=False):
            # static step's 5c with the cadence knob as a traced operand
            if not cfg.audit:
                return db, st
            from deneva_tpu.cc import AUDIT_KEY, audit_observe
            from deneva_tpu.cc.depgraph import witness_count
            aud2, _e, _bk, cnt, drop, _vd, _rd = audit_observe(
                cfg, batch, exec_commit & active, order, lvl, order_vis,
                db[AUDIT_KEY], state.epoch, cadence=knobs.audit_cadence)
            db = dict(db)
            db[AUDIT_KEY] = aud2
            st["audit_edge_cnt"] += cnt.astype(jnp.uint32)
            st["audit_drop_cnt"] += drop.astype(jnp.uint32)
            if claim_zero:
                # sweep branches claim a conflict-free level-0 commit
                # set: any level-0/level-0 edge is a claim witness
                # (repair-salvaged endpoints sit at lvl >= 1, excluded)
                st["audit_wit_cnt"] += witness_count(
                    _e, lvl).astype(jnp.uint32)
            return db, st

        def budget_merge(verdict, eligible=None):
            # static step's defer budget (liveness backstop); `eligible`
            # narrows it in the mixed branch
            if cfg.defer_rounds_max <= 0:
                return verdict
            stuck = verdict.defer & active \
                & (sel(pool.defer_cnt) >= jnp.int32(cfg.defer_rounds_max))
            if eligible is not None:
                stuck = stuck & eligible
            return dataclasses.replace(
                verdict, abort=verdict.abort | stuck,
                defer=verdict.defer & ~stuck)

        def sweep_branch(be_s):
            # uniform NO_WAIT / OCC epoch — the static step's sweep path
            # over the coarsened conflict view
            def body(_):
                st = dict(stats)
                inc = build_conflict_incidence(cfg, be_s, cbatch,
                                               cbatch.order_free)
                verdict, _cc = be_s.validate(cfg, state.cc_state, cbatch,
                                             inc)
                density_into(st, inc)
                verdict = budget_merge(verdict)
                exec_commit = verdict.commit
                db = wl.execute(state.db, queries, exec_commit,
                                verdict.order, st)
                srounds = None
                if cfg.repair and be_s.repair_rule is not None:
                    from deneva_tpu.engine.repair import run_repair
                    db, _cc, verdict, salvaged, srounds = run_repair(
                        cfg, wl, be_s, db, queries, cbatch, inc, verdict,
                        state.cc_state, st, exec_commit, None,
                        ts_base=pool.next_seq - jnp.int32(self.pool.b),
                        rounds_cap=knobs.repair_cap)
                    exec_commit = exec_commit | salvaged
                lvl = srounds if srounds is not None \
                    else jnp.zeros_like(verdict.level)
                db, st = audit_into(db, st, exec_commit, verdict.order,
                                    lvl, False, claim_zero=True)
                return (db, st, exec_commit, exec_commit, verdict.abort,
                        verdict.defer)
            return body

        def tb_branch():
            # uniform TPU_BATCH epoch: exactly the static step's path
            # for this backend — forwarding executor when the workload
            # is blind-write (density via the scatter-add path, inc
            # never built), chained level waves otherwise
            tb = backends[2]
            if forwarding_applies(tb, wl):
                def body(_):
                    st = dict(stats)
                    verdict, fwd = forward_verdict(batch)
                    density_into(st, None)
                    db = wl.execute(state.db, queries, None,
                                    verdict.order, st, fwd_rank=fwd)
                    db, st = audit_into(db, st, verdict.commit,
                                        verdict.order,
                                        jnp.zeros_like(verdict.level),
                                        True)
                    return (db, st, verdict.commit, verdict.commit,
                            verdict.abort, verdict.defer)
            else:
                def body(_):
                    st = dict(stats)
                    inc = build_conflict_incidence(cfg, tb, cbatch,
                                                   cbatch.order_free)
                    verdict, _cc = tb.validate(cfg, state.cc_state,
                                               cbatch, inc)
                    density_into(st, inc)
                    db, st = _run_levels(cfg, wl, state.db, queries,
                                         verdict.commit, verdict, st)
                    db, st = audit_into(db, st, verdict.commit,
                                        verdict.order, verdict.level,
                                        False)
                    return (db, st, verdict.commit, verdict.commit,
                            verdict.abort, verdict.defer)
            return body

        def dgcc_branch():
            # uniform DGCC epoch (the controller's HOT class under
            # ctrl_dgcc): the static step's wavefront path over the
            # coarsened conflict view — coarsening composes soundly
            # with the exact-key lane graph (merged keys only ADD
            # dependencies, deepening waves but never hiding one) while
            # execution/audit keep exact keys as everywhere.  No
            # incidence (density via the scatter-add path), no repair
            # (DGCC never aborts), no defer budget (chained exemption:
            # its defers are the bounded cyclic fallback).
            dg = backends[3]

            def body(_):
                st = dict(stats)
                verdict, _cc = dg.validate(cfg, state.cc_state, cbatch,
                                           None, stats=st)
                density_into(st, None)
                db, st = _run_levels(cfg, wl, state.db, queries,
                                     verdict.commit, verdict, st,
                                     level_exec=False)
                db, st = audit_into(db, st, verdict.commit,
                                    verdict.order, verdict.level, False)
                return (db, st, verdict.commit, verdict.commit,
                        verdict.abort, verdict.defer)
            return body

        def mixed_branch(_):
            # mixed assignment: one shared coarse incidence; each
            # backend validates its own sub-batch with the cross-group
            # conflict surface deferred symmetrically, so the merged
            # committed set needs no cross-group ordering.  Sweep
            # winners commit at level 0 beside TPU_BATCH's level-0 wave
            # (the union stays write-conflict-free: each group's wave
            # is conflict-free by its own verdict invariant and every
            # cross-group conflicting txn was deferred).  Repair is
            # skipped in mixed epochs (its frontier algebra is
            # per-backend; the next uniform epoch resumes it).
            st = dict(stats)
            inc = build_conflict_incidence(cfg, backends[0], cbatch,
                                           cbatch.order_free)
            crossdef = cross_group_defer(inc, cbatch, group,
                                         n_groups=len(backends))
            commit = jnp.zeros_like(active)
            abort = jnp.zeros_like(active)
            defer = crossdef
            level = jnp.zeros_like(batch.rank)
            for g, be_g in enumerate(backends):
                m = active & (group == g) & ~crossdef
                sb = dataclasses.replace(cbatch, active=m)
                if be_g.alg == CCAlg.DGCC:
                    # DGCC ignores the incidence (exact-key lane graph
                    # over its masked sub-batch) but keeps the [dgcc]
                    # counters flowing in mixed epochs too
                    v_g, _cc = be_g.validate(cfg, state.cc_state, sb,
                                             None, stats=st)
                else:
                    v_g, _cc = be_g.validate(cfg, state.cc_state, sb,
                                             inc)
                commit = commit | (v_g.commit & m)
                abort = abort | (v_g.abort & m)
                defer = defer | (v_g.defer & m)
                if be_g.chained:
                    level = jnp.where(m, v_g.level, level)
            density_into(st, inc)
            # budget covers sweep-group txns and cross-group defers;
            # chained groups' internal defers resolve by construction
            # (TPU_BATCH) or are the bounded cyclic fallback (DGCC) —
            # the static step's chained exemption, per group
            nonchained = functools.reduce(
                jnp.logical_or,
                [group == g for g, be_g in enumerate(backends)
                 if not be_g.chained])
            verdict = budget_merge(
                Verdict(commit=commit, abort=abort, defer=defer,
                        order=batch.rank, level=level),
                eligible=nonchained | crossdef)
            # the union executes through one level chain: sweep winners
            # at level 0 beside the chained groups' waves (cross-group
            # conflicts all deferred).  With DGCC armed the executor
            # takes the order-tournament path — for the conflict-free
            # non-DGCC waves it degenerates to the fast path's result,
            # so the static python flag keeps PR 16 programs untouched
            db, st = _run_levels(cfg, wl, state.db, queries,
                                 verdict.commit, verdict, st,
                                 level_exec=not cfg.ctrl_dgcc)
            db, st = audit_into(db, st, verdict.commit, verdict.order,
                                verdict.level, False)
            return (db, st, verdict.commit, verdict.commit,
                    verdict.abort, verdict.defer)

        # 4+5. routed validate/execute/repair/audit: uniform epochs take
        # their backend's exact static branch; disagreement routes to
        # the mixed branch (always last)
        branches = [sweep_branch(backends[0]), sweep_branch(backends[1]),
                    tb_branch()]
        if len(backends) > 3:
            branches.append(dgcc_branch())
        branches.append(mixed_branch)
        uniform = (knobs.assign == knobs.assign[0]).all()
        idx = jnp.where(uniform, knobs.assign[0],
                        jnp.int32(len(backends)))
        db, stats, exec_commit, release, aborts, defers = jax.lax.switch(
            idx, branches, None)

        # 6. update pool + counters (identical to the static step with
        # forced=None; every candidate restamps aborts with fresh ts)
        pre_abort_cnt = sel(pool.abort_cnt)
        pool = self.pool.update(pool, slots, active, release, aborts,
                                state.epoch, True)
        ncommit = (exec_commit & active).sum(dtype=jnp.uint32)
        stats["total_txn_commit_cnt"] += ncommit
        stats["total_txn_abort_cnt"] += (aborts & active).sum(
            dtype=jnp.uint32)
        stats["unique_txn_abort_cnt"] += (
            aborts & active & (pre_abort_cnt == 0)).sum(dtype=jnp.uint32)
        count_by_type(stats, wl, queries, exec_commit & active,
                      aborts & active)
        stats["defer_cnt"] += (defers & active).sum(dtype=jnp.uint32)
        committed = exec_commit & active
        lat = jnp.clip(state.epoch - sel(pool.entry_epoch),
                       0, LAT_BUCKETS - 1)
        onehot = (lat[:, None] == jnp.arange(LAT_BUCKETS, dtype=jnp.int32)) \
            & committed[:, None]
        ttype = wl.txn_type_of(queries) if len(
            getattr(wl, "txn_type_names", ("txn",))) > 1 else None
        rows = []
        for t in range(stats["latency_hist"].shape[0]):
            m = onehot if ttype is None \
                else onehot & (ttype == t)[:, None]
            rows.append(m.sum(axis=0, dtype=jnp.uint32))
        stats["latency_hist"] = stats["latency_hist"] + jnp.stack(rows)
        rb = jnp.arange(RETRY_BUCKETS, dtype=jnp.int32)
        retries = jnp.clip(pre_abort_cnt, 0, RETRY_BUCKETS - 1)
        waits = jnp.clip(sel(pool.defer_cnt), 0, RETRY_BUCKETS - 1)
        stats["retry_hist"] = stats["retry_hist"] + (
            (retries[:, None] == rb) & committed[:, None]).sum(
            axis=0, dtype=jnp.uint32)
        stats["wait_hist"] = stats["wait_hist"] + (
            (waits[:, None] == rb) & committed[:, None]).sum(
            axis=0, dtype=jnp.uint32)

        return EngineState(db=db, cc_state=state.cc_state, pool=pool,
                           rng=rng, epoch=state.epoch + 1, stats=stats)

    # ------------------------------------------------------------------
    @functools.cached_property
    def jit_step(self):
        return jax.jit(self.step, donate_argnums=0)

    @functools.cached_property
    def jit_run(self):
        """scan ``n`` epochs on device; n is static per compile."""

        @functools.partial(jax.jit, static_argnums=1, donate_argnums=0)
        def run(state: EngineState, n: int) -> EngineState:
            return jax.lax.scan(lambda s, _: (self.step(s), None), state,
                                None, length=n)[0]
        return run

    @functools.cached_property
    def jit_run_ctrl(self):
        """Routed scan: ``n`` epochs under ONE knob decision (the
        controller decides at chunk boundaries; knobs are traced
        operands, so re-arming with new VALUES reuses the compile)."""

        @functools.partial(jax.jit, static_argnums=2, donate_argnums=0)
        def run(state: EngineState, knobs, n: int) -> EngineState:
            return jax.lax.scan(
                lambda s, _: (self.step(s, knobs), None), state,
                None, length=n)[0]
        return run
