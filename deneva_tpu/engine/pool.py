"""Device-resident transaction pool.

Replaces four reference components at once (SURVEY §2.1):

* `txn_table` — active txns keyed by id (`system/txn_table.cpp:79-134`):
  here a fixed array of ``capacity = max_txn_in_flight`` slots.
* `work_queue`/`new_txn_queue` — dequeue-oldest-first scheduling
  (`system/work_queue.cpp:188-200`): here top-B-by-sequence selection.
* `abort_queue` — exponential-backoff restarts
  (`system/abort_queue.cpp:26-50`): here a per-slot ``ready_epoch``
  computed as ``epoch + min(2^aborts, cap)`` (BACKOFF `config.h:114`).
* client inflight throttle (`client/client_txn.cpp:25-46`): admission
  stops when no slot is free; dropped generations are counted like the
  reference's client-side admission stalls.

The WAIT/restart machinery the survey ranks hardest (§7: txns parked
mid-state-machine, resumed via `restart_txn`) is simply: deferred txns
keep their slot and sequence number, so next epoch's selection picks them
first and the CC sweep sees them as earliest — a parked txn *is* its
pool slot.

Sequence numbers double as timestamps: ``next_seq`` advances by a static
``G + B`` per epoch, giving globally unique, monotone int32 ts.  Concrete
wrap horizon: at full-pool 64k epochs (G + B = 131072) and the measured
~80 epochs/s that is ~2^31 / 131072 / 80 ≈ 200 s of wall time; smaller
epochs push it out proportionally (eb=2048, ~1.5k eps ≈ 6 min).  The
driver guards the horizon at run time (`driver.run_simulation` raises
before ``next_seq`` can wrap mid-window) rather than paying TPU-emulated
int64 compares in the sort/sweep hot paths; the reference's 64-bit ts
has the same finite-horizon caveat at a scale no run reaches.

**Full-pool epochs** (``batch == capacity``): when one epoch spans the
entire inflight window — the natural operating point for the forwarding
executor, where every inflight txn commits each epoch — select/update/
refill degenerate to dense elementwise ops with NO slot indexing at all.
On TPU that removes every per-slot gather/scatter from the pool
bookkeeping (each ~1.5 ms per 64k slots on v5e, vs ~0 for the same math
as a dense where), and oldest-first selection is trivially satisfied
because everyone runnable is selected.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp


@dataclass
class PoolState:
    queries: Any            # workload query pytree, leaves [P, ...]
    ts: jax.Array           # int32[P]
    seq: jax.Array          # int32[P] arrival sequence (selection priority)
    abort_cnt: jax.Array    # int32[P]
    defer_cnt: jax.Array    # int32[P] defers since last (re)start — the
    #                         defer_rounds_max budget counter (reset on
    #                         admission AND on abort/restart)
    ready_epoch: jax.Array  # int32[P]
    entry_epoch: jax.Array  # int32[P] (latency measurement)
    occupied: jax.Array     # bool[P]
    next_seq: jax.Array     # int32 scalar


jax.tree_util.register_dataclass(
    PoolState,
    data_fields=["queries", "ts", "seq", "abort_cnt", "defer_cnt",
                 "ready_epoch",
                 "entry_epoch", "occupied", "next_seq"],
    meta_fields=[])


class TxnPool:
    """Static pool logic bound to (capacity P, epoch batch B, gen chunk G)."""

    def __init__(self, capacity: int, batch: int, gen_chunk: int,
                 backoff: bool, backoff_cap: int = 64,
                 dense: bool | None = None):
        assert capacity >= batch
        self.p = capacity
        self.b = batch
        self.g = gen_chunk
        self.backoff = backoff
        self.backoff_cap = backoff_cap
        # ONE decision for the dense fast paths (refill/select/update and
        # Engine.step's sel all key off this); `dense` forces it for
        # equivalence tests
        self.full_pool = (batch == capacity) if dense is None \
            else bool(dense)
        if self.full_pool:
            assert batch == capacity and gen_chunk == capacity, \
                "full-pool mode requires batch == gen_chunk == capacity"

    # ------------------------------------------------------------------
    def create(self, empty_queries: Any) -> PoolState:
        p = self.p
        return PoolState(
            queries=empty_queries,
            ts=jnp.zeros((p,), jnp.int32),
            seq=jnp.zeros((p,), jnp.int32),
            abort_cnt=jnp.zeros((p,), jnp.int32),
            defer_cnt=jnp.zeros((p,), jnp.int32),
            ready_epoch=jnp.zeros((p,), jnp.int32),
            entry_epoch=jnp.zeros((p,), jnp.int32),
            occupied=jnp.zeros((p,), bool),
            # starts at 1, never 0: ts==0 is reserved as the MVCC
            # read-only serialization sentinel (cc/timestamp.py order,
            # ycsb.py ver_ts); the cluster path enforces the same
            # invariant at its stamping site (server._contribution)
            next_seq=jnp.ones((), jnp.int32))

    # ------------------------------------------------------------------
    def refill(self, pool: PoolState, new_queries: Any, epoch: jax.Array
               ) -> tuple[PoolState, jax.Array]:
        """Admit up to G fresh queries into free slots (client admission,
        `system/client_thread.cpp:57-104`).  Returns (pool, admitted)."""
        free = ~pool.occupied
        if self.full_pool:
            # full-pool fast path: one fresh query per slot, so slot i
            # admits new_queries[i] directly — no compaction gather.
            # Seq stays unique: base advances past the whole window
            # each epoch, and slot index disambiguates within it.
            take = free
            newseq = pool.next_seq + jnp.arange(self.p, dtype=jnp.int32)

            def place_dense(old, new):
                m = take.reshape((-1,) + (1,) * (old.ndim - 1))
                return jnp.where(m, new, old)

            return PoolState(
                queries=jax.tree.map(place_dense, pool.queries,
                                     new_queries),
                ts=jnp.where(take, newseq, pool.ts),
                seq=jnp.where(take, newseq, pool.seq),
                abort_cnt=jnp.where(take, 0, pool.abort_cnt),
                defer_cnt=jnp.where(take, 0, pool.defer_cnt),
                ready_epoch=jnp.where(take, epoch, pool.ready_epoch),
                entry_epoch=jnp.where(take, epoch, pool.entry_epoch),
                occupied=jnp.ones_like(pool.occupied),
                next_seq=pool.next_seq + jnp.int32(self.g + self.b),
            ), take.sum(dtype=jnp.int32)
        pos = jnp.cumsum(free.astype(jnp.int32)) - 1    # rank among free slots
        take = free & (pos < self.g)
        src = jnp.clip(pos, 0, self.g - 1)

        def place(old, new):
            picked = jnp.take(new, src, axis=0)
            m = take.reshape((-1,) + (1,) * (old.ndim - 1))
            return jnp.where(m, picked, old)

        queries = jax.tree.map(place, pool.queries, new_queries)
        newseq = pool.next_seq + pos.astype(jnp.int32)
        admitted = take.sum(dtype=jnp.int32)
        return PoolState(
            queries=queries,
            ts=jnp.where(take, newseq, pool.ts),
            seq=jnp.where(take, newseq, pool.seq),
            abort_cnt=jnp.where(take, 0, pool.abort_cnt),
            defer_cnt=jnp.where(take, 0, pool.defer_cnt),
            ready_epoch=jnp.where(take, epoch, pool.ready_epoch),
            entry_epoch=jnp.where(take, epoch, pool.entry_epoch),
            occupied=pool.occupied | take,
            # static advance: G admissions + B potential restamps per epoch
            next_seq=pool.next_seq + jnp.int32(self.g + self.b),
        ), admitted

    # ------------------------------------------------------------------
    def select(self, pool: PoolState, epoch: jax.Array
               ) -> tuple[jax.Array, jax.Array, Any]:
        """Top-B runnable slots by sequence (oldest-work-first,
        `system/work_queue.cpp:188-200`).  Returns (slots, active, queries)."""
        runnable = pool.occupied & (pool.ready_epoch <= epoch)
        if self.full_pool:
            # full-pool fast path: everyone runnable runs — identity
            # selection, zero gathers
            return jnp.arange(self.p, dtype=jnp.int32), runnable, \
                pool.queries
        big = jnp.iinfo(jnp.int32).max
        key = jnp.where(runnable, pool.seq, big)
        # top_k beats a full argsort 8x at large pools (measured 5 ms vs
        # 40 ms at P=100k on v5e — the round-2 ycsb_inflight TIF=100k
        # regression); -key selects the B smallest seqs, descending
        # top_k order = ascending seq, ties index-stable like argsort
        _, slots = jax.lax.top_k(-key, self.b)
        slots = slots.astype(jnp.int32)
        active = jnp.take(runnable, slots)
        queries = jax.tree.map(lambda l: jnp.take(l, slots, axis=0),
                               pool.queries)
        return slots, active, queries

    # ------------------------------------------------------------------
    def update(self, pool: PoolState, slots: jax.Array, active: jax.Array,
               commit: jax.Array, abort: jax.Array, epoch: jax.Array,
               fresh_ts_on_restart: bool) -> PoolState:
        """Apply verdicts: committed slots free; aborted slots back off
        exponentially; deferred slots stay runnable with their seq."""
        commit = commit & active
        abort = abort & active

        def backoff_penalty(ac):
            if self.backoff:
                return jnp.minimum(
                    jnp.left_shift(jnp.int32(1), jnp.clip(ac - 1, 0, 30)),
                    self.backoff_cap)
            return jnp.ones_like(ac)

        defer = active & ~commit & ~abort
        if self.full_pool:
            # full-pool fast path: slots is the identity, so every
            # per-slot scatter collapses to a dense elementwise update
            ac = pool.abort_cnt + abort.astype(jnp.int32)
            # an abort is a restart: the wait budget opens afresh
            dc = jnp.where(abort, 0, pool.defer_cnt + defer.astype(jnp.int32))
            ready = jnp.where(abort, epoch + 1 + backoff_penalty(ac),
                              pool.ready_epoch)
            ts = pool.ts
            if fresh_ts_on_restart:
                lane = jnp.arange(self.p, dtype=jnp.int32)
                ts = jnp.where(abort, pool.next_seq - self.b + lane, ts)
            return PoolState(
                queries=pool.queries, ts=ts, seq=pool.seq, abort_cnt=ac,
                defer_cnt=dc, ready_epoch=ready,
                entry_epoch=pool.entry_epoch,
                occupied=pool.occupied & ~commit, next_seq=pool.next_seq)

        occ_sel = jnp.take(pool.occupied, slots) & ~commit
        ac_sel = jnp.take(pool.abort_cnt, slots) + abort.astype(jnp.int32)
        dc_sel = jnp.where(abort, 0, jnp.take(pool.defer_cnt, slots)
                           + defer.astype(jnp.int32))
        ready_sel = jnp.where(abort, epoch + 1 + backoff_penalty(ac_sel),
                              jnp.take(pool.ready_epoch, slots))
        ts_sel = jnp.take(pool.ts, slots)
        if fresh_ts_on_restart:
            lane = jnp.arange(self.b, dtype=jnp.int32)
            ts_sel = jnp.where(abort, pool.next_seq - self.b + lane, ts_sel)
        return PoolState(
            queries=pool.queries,
            ts=pool.ts.at[slots].set(ts_sel),
            seq=pool.seq,
            abort_cnt=pool.abort_cnt.at[slots].set(ac_sel),
            defer_cnt=pool.defer_cnt.at[slots].set(dc_sel),
            ready_epoch=pool.ready_epoch.at[slots].set(ready_sel),
            entry_epoch=pool.entry_epoch,
            occupied=pool.occupied.at[slots].set(occ_sel),
            next_seq=pool.next_seq)
