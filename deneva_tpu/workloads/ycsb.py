"""YCSB (reference `benchmarks/ycsb_wl.cpp`, `ycsb_query.cpp`, `ycsb_txn.cpp`).

One table of ``synth_table_size`` rows with 10 string fields
(`benchmarks/YCSB_schema.txt`); queries are ``req_per_query`` accesses with
zipfian keys and a per-request write probability
(`ycsb_query.cpp:303-376`).  A request reads field F0 or blindly
overwrites it (`ycsb_txn.cpp:177-209` does `get_value/set_value` on one
field per request).

TPU shape: the table is a `DeviceTable` (SoA, fingerprint strings), the
primary index is the identity `DenseIndex` (YCSB keys are dense,
`ycsb_wl.cpp:70-74`), queries are generated on device per epoch, and
execute is one gather (reads, checksummed into stats so XLA cannot
dead-code them) plus one last-writer scatter (writes).

Multi-partition control (`FIRST_PART_LOCAL`, `PART_PER_TXN`, MPR
`ycsb_query.cpp:303-376`) maps to the mesh build: keys are striped
``slot % n_parts`` across devices, so a zipfian batch is naturally
multi-partition; `deneva_tpu.parallel` documents the layout.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from deneva_tpu.config import CCAlg, Config
from deneva_tpu.ops import HotSet, Zipfian, forward_plan, last_writer
from deneva_tpu.storage.catalog import parse_schema
from deneva_tpu.storage.index import DenseIndex, SortedIndex
from deneva_tpu.storage.table import DeviceTable, VersionRing, to_mc_layout

# benchmarks/YCSB_schema.txt: MAIN_TABLE, 10 x 100-byte string fields
YCSB_SCHEMA = "TABLE=MAIN_TABLE\n" + "".join(
    f"\t100,string,F{i}\n" for i in range(10)) + "INDEX=MAIN_INDEX\n\tMAIN_TABLE,0\n"

TABLE = "MAIN_TABLE"
TABLE_ID = 0
VER_TABLE = "MAIN_TABLE.F0.ver"   # MVCC per-row version-value ring


@dataclass
class YCSBQuery:
    """One epoch's queries; pytree with leading dim n."""

    keys: jax.Array      # int32[n, R]
    is_write: jax.Array  # bool[n, R]


jax.tree_util.register_dataclass(YCSBQuery, data_fields=["keys", "is_write"],
                                 meta_fields=[])


def _field_fingerprint(key: jax.Array | np.ndarray, version):
    """Deterministic field value = f(key, version): lets consistency tests
    recompute expected content without storing 100-byte payloads."""
    k = jnp.asarray(key).astype(jnp.uint32)
    v = jnp.asarray(version).astype(jnp.uint32)
    return (k * jnp.uint32(2654435761)) ^ (v * jnp.uint32(0x9E3779B9)) | jnp.uint32(1)


def _field_bytes(key, version, nbytes: int) -> jax.Array:
    """SIM_FULL_ROW payload: uint8[..., nbytes] real field bytes, still a
    pure function of (key, version) so consistency tests can recompute
    expected content (reference `storage/row.cpp:30`; the reference fills
    'hello' + garbage, `ycsb_wl.cpp` init — ours must be
    version-dependent so forwarded reads are checkable)."""
    fp = _field_fingerprint(key, version)
    i = jnp.arange(nbytes, dtype=jnp.uint32)
    mixed = fp[..., None] * (i * jnp.uint32(2654435761)
                             + jnp.uint32(0x9E3779B9))
    return ((mixed >> jnp.uint32(13)) & jnp.uint32(0xFF)).astype(jnp.uint8)


def _forward_execute_f0(f0: jax.Array, p, slots: jax.Array, trash,
                        mono: bool = False):
    """THE forwarding-executor data path, shared verbatim by the
    single-chip `execute` and each shard of `execute_mc` so their
    semantics cannot diverge: reads gather F0 (forwarded lanes take
    f(key, writer rank) instead), the checksum folds over reads, and
    only final writers scatter.  Returns (f0', checksum, write_cnt) —
    the caller decides whether the scalars need a psum.

    ``f0`` is uint32[N] in fingerprint mode or uint8[N, S] under
    SIM_FULL_ROW — the full-row branch moves the real payload bytes, so
    benchmark numbers measure reference-width HBM traffic.

    ``mono`` (callers with key-monotone slot maps, i.e. every current
    caller: slot order follows the plan's sorted key order and masked
    lanes steer to a trash at/above the top): the write scatter hands
    XLA MONOTONE, pre-sorted indices — ``cummax`` carries the latest
    winner's slot into following lanes and two head-propagation scans
    carry its (key, rank) so the duplicate lanes rewrite the same value
    idempotently; lanes before the first winner drop (index -1,
    mode='drop').  This skips the sort XLA otherwise inserts inside
    every scatter lowering (~0.6 ms at 655k lanes on v5e — the roofline
    ledger's sort.67).  The legacy trash-steered scatter remains for
    non-monotone slot maps (mono=False)."""
    vals = jnp.take(f0, jnp.where(p.is_read, slots, trash), axis=0)
    if f0.ndim == 2:
        nbytes = f0.shape[1]
        vals = jnp.where((p.fwd >= 0)[:, None],
                         _field_bytes(p.keys, p.fwd, nbytes), vals)
        cks = jnp.sum(jnp.where(p.is_read[:, None], vals, 0),
                      dtype=jnp.uint32)
    else:
        vals = jnp.where(p.fwd >= 0, _field_fingerprint(p.keys, p.fwd), vals)
        cks = jnp.sum(jnp.where(p.is_read, vals, 0), dtype=jnp.uint32)
    if mono:
        from deneva_tpu.ops.forward import seg_first
        # nearest-preceding-winner slot: cummax works because slots
        # ascend (a Kogge-Stone scan here measures slower end-to-end —
        # XLA fuses its concatenate chains into the gather fusion)
        wslot = jax.lax.cummax(jnp.where(p.win, slots, jnp.int32(-1)))
        wkey = seg_first(p.win, p.keys)
        wrank = seg_first(p.win, p.rank)
        wvals = _field_bytes(wkey, wrank, f0.shape[1]) if f0.ndim == 2 \
            else _field_fingerprint(wkey, wrank).astype(f0.dtype)
        f0 = f0.at[wslot].set(wvals, mode="drop", indices_are_sorted=True)
    else:
        wvals = _field_bytes(p.keys, p.rank, f0.shape[1]) if f0.ndim == 2 \
            else _field_fingerprint(p.keys, p.rank).astype(f0.dtype)
        f0 = f0.at[jnp.where(p.win, slots, trash)].set(wvals)
    return f0, cks, p.is_write.sum(dtype=jnp.uint32)


class YCSBWorkload:
    # writes overwrite a field with f(key, order) — independent of any
    # read — so the single-pass forwarding executor applies (ops/forward)
    blind_writes = True
    # per-type statistics (reference Stats_thd per-txn-kind counters)
    txn_type_names = ("ycsb_ro", "ycsb_rw")

    def txn_type_of(self, q: "YCSBQuery") -> jax.Array:
        return q.is_write.any(axis=1).astype(jnp.int32)

    def __init__(self, cfg: Config):
        self.cfg = cfg
        # schema at configured width (TUP_SIZE × FIELD_PER_TUPLE,
        # config.h:150-152); the module-level YCSB_SCHEMA is the
        # reference default (10 × 100B)
        self.catalog = parse_schema(
            "TABLE=MAIN_TABLE\n"
            + "".join(f"\t{cfg.tup_size},string,F{i}\n"
                      for i in range(cfg.field_per_tuple))
            + "INDEX=MAIN_INDEX\n\tMAIN_TABLE,0\n")
        self.n_rows = cfg.synth_table_size
        # partitioned deployment (reference `key % g_part_cnt` node
        # ownership, ycsb_wl.cpp:70-74 / global.h:294): this node stores
        # only keys ≡ node_id (mod part_cnt); the strided index steers
        # remote keys to the trash slot so execution is local-only.
        self.n_parts = max(cfg.part_cnt, 1)
        self.elastic = cfg.elastic
        if self.elastic:
            # elastic membership (runtime/membership.py): ownership is
            # the slot-map MASK, not the storage layout.  Every node
            # holds the FULL keyspace (local slot == key, identity
            # index) so a slot acquired mid-run always has a resident
            # row to install the migrated value into; non-owned lanes
            # steer to the trash slot via `slot_map_owned` at access
            # time (`_local_slots`).  The boot map degenerates to exact
            # modulo striping, so the mask — and therefore every verdict
            # and every ack — is bit-identical to the striped layout
            # until a rebalance moves a slot.
            from deneva_tpu.runtime.membership import initial_map
            self.n_local = self.n_rows
            self.index = DenseIndex(base=0, stride=1, size=self.n_rows,
                                    miss_slot=self.n_rows)
            self._boot_map = initial_map(cfg)
            self.n_slots = self._boot_map.n_slots
        elif self.n_parts > 1:
            assert self.n_rows % self.n_parts == 0, \
                "synth_table_size must divide evenly over part_cnt"
            self.n_local = self.n_rows // self.n_parts
            self.index = DenseIndex(base=cfg.node_id, stride=self.n_parts,
                                    size=self.n_local, miss_slot=self.n_local)
        else:
            self.n_local = self.n_rows
            self.index = DenseIndex(base=0, stride=1, size=self.n_rows,
                                    miss_slot=self.n_rows)
        if cfg.index_struct == "IDX_BTREE":
            # INDEX_STRUCT=IDX_BTREE (global.h:320-324): probe an ordered
            # index (binary-search ladder) instead of the affine perfect
            # hash that dense YCSB keys otherwise admit.  Same key->slot
            # map, so results are identical; this exercises the
            # `index_btree` analogue on the primary path.
            self.index = SortedIndex.build(
                self._owned_keys(),
                np.arange(self.n_local, dtype=np.int32),
                miss_slot=self.n_local)
        # key sampler: Gray zipfian or HOT two-tier uniform
        # (SKEW_METHOD, config.h:162-167)
        if cfg.skew_method == "HOT":
            self.zipf = HotSet(self.n_rows, int(cfg.data_perc),
                               cfg.access_perc)
        else:
            self.zipf = Zipfian(self.n_rows, cfg.zipf_theta)
        self.n_req = cfg.req_per_query

    def _owned_keys(self) -> np.ndarray:
        """Global keys owned by this node, in slot order — the single
        definition of the `key % part_cnt` partition layout
        (ycsb_wl.cpp:70-74); shared by both index kinds and the loader.
        Elastic mode is full-residency: every key has a local row (the
        ownership mask lives in the slot map, not the layout)."""
        if self.elastic:
            return np.arange(self.n_local, dtype=np.int32)
        base = self.cfg.node_id if self.n_parts > 1 else 0
        stride = self.n_parts if self.n_parts > 1 else 1
        return (base + np.arange(self.n_local, dtype=np.int64)
                * stride).astype(np.int32)

    # -- loader (ycsb_wl.cpp:125-203) ----------------------------------
    def load(self):
        full = self.cfg.sim_full_row
        tab = DeviceTable.create(self.catalog.table(TABLE), self.n_local,
                                 full_row=full)
        keys = self._owned_keys()
        if full:
            # SIM_FULL_ROW: every field materializes real payload bytes —
            # rows are reference-width resident data (10 × 100B default)
            init = _field_bytes(jnp.asarray(keys), 0, self.cfg.tup_size)
            for name in tab.columns:
                tab.columns[name] = tab.columns[name].at[
                    : self.n_local].set(init)
        else:
            cols = {"F0": np.asarray(_field_fingerprint(keys, 0))}
            # remaining fields share the same fingerprint law; only F0 is
            # touched by queries (ycsb_txn.cpp reads/writes one field)
            for name, v in cols.items():
                tab.columns[name] = tab.columns[name].at[
                    : self.n_local].set(jnp.asarray(v))
        if self.cfg.device_parts > 1:
            # multi-chip owner-major stacked layout: mesh block d holds
            # exactly the keys ≡ d (mod D) — the reference's strided node
            # partition (ycsb_wl.cpp:70-74) across CHIPS
            tab = to_mc_layout(tab, self.cfg.device_parts)
        db = {TABLE: tab}
        if self.elastic:
            # device-resident owner array: ownership changes are a data
            # update between group dispatches, never a re-jit.  Excluded
            # from state_digest (control plane, not row state).
            from deneva_tpu.runtime.membership import MEMBER_KEY
            db[MEMBER_KEY] = jnp.asarray(self._boot_map.owners)
        if self.cfg.cc_alg == CCAlg.MVCC and self.cfg.device_parts == 1:
            # per-row overwrite-ts ring (row_mvcc.cpp:172-196): stale
            # reads of read-write txns return HISTORICAL bytes of the
            # queried field — reconstructed from the version law
            # f(key, v*) with v* from the ring (VersionRing docstring).
            # Paired with the bucket boundary ring in
            # cc/timestamp.MVCCState, which makes the retention DECISION
            # and bounds this ring's needed depth.
            f0 = tab.columns["F0"]
            # depth must be the FULL mvcc_his_len: a servable read at t
            # may have mvcc_his_len-1 overwrites postdating t (the
            # decision ring's commit rule allows exactly that many), and
            # the ts-only reconstruction needs ONE more retained entry —
            # the newest <= t, which IS v* (the value ring of rounds 3-4
            # stored displaced bytes, so it only needed the >t entries;
            # this one reads v* directly)
            db[VER_TABLE] = VersionRing.create(
                f0.shape[0], self.cfg.mvcc_his_len)
        if self.cfg.audit:
            # isolation audit stamp tables (cc/base.audit_observe):
            # installed by the loader so EVERY db-construction path —
            # engine init, server boot, log replay, follower boot —
            # threads the identical pytree.  Control plane like
            # MEMBER_KEY: excluded from state_digest.
            from deneva_tpu.cc.base import AUDIT_KEY, audit_init
            db[AUDIT_KEY] = audit_init(self.cfg)
        return db

    # -- query generation (ycsb_query.cpp:303-376) ---------------------
    def generate(self, rng: jax.Array, n: int) -> YCSBQuery:
        k1, k2, k3 = jax.random.split(rng, 3)
        keys = self.zipf.sample(k1, (n, self.n_req))
        if self.cfg.key_order:
            # KEY_ORDER (config.h:106): requests sorted ascending by key.
            # acctype is iid per slot so sorting keys alone is
            # distribution-identical to the reference's paired sort.
            keys = jnp.sort(keys, axis=1)
        is_write = jax.random.bernoulli(k2, self.cfg.write_perc,
                                        (n, self.n_req))
        if self.cfg.txn_write_perc < 1.0:
            # TXN_WRITE_PERC: one per-txn draw gates all writes — with prob
            # 1-p the whole txn is read-only (ycsb_query.cpp:313,331)
            may_write = jax.random.bernoulli(
                k3, self.cfg.txn_write_perc, (n, 1))
            is_write = is_write & may_write
        return YCSBQuery(keys=keys, is_write=is_write)

    # -- wire adapters (distributed runtime, CL_QRY/EPOCH_BLOB bodies) --
    def to_wire(self, q: YCSBQuery):
        """(keys int32[n,W], types int8[n,W], scalars int32[n,S]) columnar
        form fed to the native qrybatch codec."""
        keys = np.asarray(q.keys, np.int32)
        types = np.where(np.asarray(q.is_write), 2, 1).astype(np.int8)
        return keys, types, np.zeros((len(keys), 0), np.int32)

    def from_wire(self, keys: np.ndarray, types: np.ndarray,
                  scalars: np.ndarray) -> YCSBQuery:
        return YCSBQuery(keys=jnp.asarray(keys, jnp.int32),
                         is_write=jnp.asarray(types == 2))

    def from_wire_dev(self, keys, types, scalars) -> YCSBQuery:
        """Traceable from_wire: runs INSIDE the cluster dispatch jit so
        the wire columns cross the tunnel flat (layout-padding-free) and
        decode on device."""
        return YCSBQuery(keys=keys.astype(jnp.int32),
                         is_write=types == jnp.int8(2))

    # -- RW-set planning ------------------------------------------------
    def plan(self, db, q: YCSBQuery) -> dict:
        shape = q.keys.shape
        return dict(
            table_ids=jnp.full(shape, TABLE_ID, jnp.int32),
            keys=q.keys,
            is_read=~q.is_write,
            is_write=q.is_write,
            valid=jnp.ones(shape, bool),
            # access owner under modulo striping (GET_NODE_ID,
            # system/global.h:294) — the VOTE protocol's participant map
            owner=q.keys % jnp.int32(max(self.n_parts, 1)),
        )

    # -- multi-chip execution (partition-parallel forwarding) ----------
    def execute_mc(self, db, batch, stats: dict):
        """Calvin-shaped multi-chip epoch: the batch is replicated (every
        chip sees the full deterministic sequence, like the reference
        sequencer's broadcast, `system/sequencer.cpp:283-326`) and each
        chip plans + executes ONLY its keyspace partition — reads gather
        and writes scatter against the local table shard, the read
        checksum reduces with one psum over ICI.

        SHARDED PLANNING (round-4, VERDICT missing #2 — the distributed
        (key, rank) sort over ICI): each chip takes a BALANCED N/D slice
        of the replicated flat lanes (input-partitioned, so zipf skew
        cannot overload a sorter), sorts it by owner (key % D, stable),
        extracts one fixed pair_cap-sized block per destination chip,
        and a single ``all_to_all`` over the mesh delivers every chip
        exactly the lanes it owns — at most factor * N/D of them.  The
        local (key, rank) plan sort, the segmented scans and the
        random-access table passes then all run at N/D scale instead of
        N: the whole epoch divides by ~D/factor rather than only its
        table-access half (the round-3 replicated-plan asymptote was
        ~2.8x).  Skew safety: a txn with a lane past its (slice, owner)
        block capacity DEFERS (the MoE capacity pattern with deferral
        instead of dropping) — computed HERE, shard-locally at O(N/D)
        against `ops.mc_plan_defer`'s replicated spec: each chip sorts
        only its own slice, reduces per-txn overflow bits, and one
        all_gather replicates the identical defer mask to every chip
        (and to the caller, who builds the epoch verdict from it).  Set
        ``mc_plan_capacity=0`` for the round-3 replicated-plan mode
        (zero capacity factors, zero defers, full-batch sort per chip).

        Returns ``(db, defer_mask)``; tables must be in the owner-major
        layout `load()` produces for ``device_parts > 1``; each local
        block's last row is its trash.
        """
        from jax.sharding import PartitionSpec as P

        from deneva_tpu.ops import forward_plan_flat, mc_pair_cap
        from deneva_tpu.parallel import AXIS, current_mesh

        d_parts = self.cfg.device_parts
        mesh = current_mesh()
        assert mesh is not None and mesh.size == d_parts, \
            f"execute_mc needs a use_mesh({d_parts}) context"
        tab: DeviceTable = db[TABLE]
        valid = batch.valid & batch.active[:, None]
        big = jnp.int32(jnp.iinfo(jnp.int32).max)
        b, a = batch.keys.shape
        pair_cap = mc_pair_cap(b, a, d_parts, self.cfg.mc_plan_capacity)
        bD = b // d_parts if pair_cap else b
        sl = bD * a

        def body(f0, keys, rank, ts, is_write, valid):
            me = jax.lax.axis_index(AXIS)
            if pair_cap:
                # my balanced slice of WHOLE txns (row-aligned, so the
                # per-txn defer bits reduce without leaving the shard)
                k2 = jax.lax.dynamic_slice_in_dim(keys, me * bD, bD)
                r2 = jax.lax.dynamic_slice_in_dim(rank, me * bD, bD)
                t2 = jax.lax.dynamic_slice_in_dim(ts, me * bD, bD)
                w2 = jax.lax.dynamic_slice_in_dim(is_write & valid,
                                                  me * bD, bD)
                v2 = jax.lax.dynamic_slice_in_dim(valid, me * bD, bD)
                # invalid lanes carry the big sentinel so the
                # post-exchange ownership mask can never admit them
                ks = jnp.where(v2, k2, big).reshape(-1)
                rs = jnp.broadcast_to(r2[:, None], (bD, a)).reshape(-1)
                tss = jnp.broadcast_to(t2[:, None], (bD, a)).reshape(-1)
                ws = w2.reshape(-1)
                vs = v2.reshape(-1)
                lane = jnp.arange(sl, dtype=jnp.int32)
                owner = jnp.where(vs, ks % d_parts, d_parts)
                # defer pass (O(N/D) analogue of ops.mc_plan_defer):
                # age-priority positions per (slice, owner) block;
                # overflow bits reduce per txn via the sort-by-txn
                # reshape trick, then one all_gather replicates them
                so, _, stx = jax.lax.sort((owner, tss, lane // a),
                                          num_keys=2, is_stable=True)
                head = jnp.concatenate([jnp.ones((1,), bool),
                                        so[1:] != so[:-1]])
                start = jax.lax.cummax(jnp.where(head, lane, 0))
                over = (lane - start >= pair_cap) & (so != d_parts)
                _, sov = jax.lax.sort((stx, over), num_keys=1,
                                      is_stable=True)
                dfr = sov.reshape(bD, a).any(axis=1)
                # each sender excludes ITS deferred txns' lanes before
                # cutting blocks, so no chip ever receives one — the
                # global mask is just the shards concatenated
                # (out_specs P(AXIS)); survivors always fit, their
                # positions only move earlier
                dfr_lane = jnp.broadcast_to(dfr[:, None],
                                            (bD, a)).reshape(-1)
                vs2 = vs & ~dfr_lane
                ks2 = jnp.where(vs2, ks, big)
                ws2 = ws & ~dfr_lane
                # stable (owner, ts) sort: each destination's lanes
                # become one contiguous run, OLDEST txns first (the
                # defer rule's age priority, starvation-free)
                owner2 = jnp.where(vs2, ks2 % d_parts, d_parts)
                _, _, ck, cr, cw = jax.lax.sort(
                    (owner2, tss, ks2, rs, ws2), num_keys=2,
                    is_stable=True)
                cnt = jnp.bincount(owner2, length=d_parts + 1)
                starts = jnp.cumsum(cnt) - cnt
                # fixed-size block per destination (dynamic start is
                # clamped near the tail — stray lanes are masked after
                # the exchange by the owner check)
                blk = [jnp.stack([jax.lax.dynamic_slice_in_dim(
                    x, starts[d], pair_cap) for d in range(d_parts)])
                    for x in (ck, cr, cw)]
                bk, br, bw = [jax.lax.all_to_all(
                    x, AXIS, split_axis=0, concat_axis=0) for x in blk]
                bk, br, bw = (bk.reshape(-1), br.reshape(-1),
                              bw.reshape(-1))
                mine = (bk % d_parts == me) & (bk != big)
                bk = jnp.where(mine, bk, big)
                bw = bw & mine
                p = forward_plan_flat(bk, br, bw)
            else:
                dfr = jnp.zeros((b,), bool)
                owned = valid & (keys % d_parts == me)
                p = forward_plan(keys, rank, is_write, owned)
            # f0 here is one owner-major block (to_mc_layout): its last
            # padded row is the block-local trash
            trash = jnp.int32(f0.shape[0] - 1)
            slots = jnp.where(p.keys != big, p.keys // d_parts, trash)
            # mono holds per shard: plan keys are sorted with non-owned
            # lanes already masked to the big sentinel, so slots ascend
            # toward the block-local trash at the top
            f0, cks, wcnt = _forward_execute_f0(f0, p, slots, trash,
                                                mono=True)
            return (f0, jax.lax.psum(cks, AXIS),
                    jax.lax.psum(wcnt, AXIS), dfr)

        from deneva_tpu.parallel.mesh import shard_map_fn
        f0, cks, wcnt, dfr = shard_map_fn()(
            body, mesh=mesh,
            in_specs=(P(AXIS), P(), P(), P(), P(), P()),
            out_specs=(P(AXIS), P(), P(),
                       P(AXIS) if pair_cap else P()))(
                tab.columns["F0"], batch.keys, batch.rank, batch.ts,
                batch.is_write, valid)
        stats["read_checksum"] = stats["read_checksum"] + cks
        stats["write_cnt"] = stats["write_cnt"] + wcnt
        db = dict(db)
        db[TABLE] = tab._replace(columns={**tab.columns, "F0": f0})
        return db, dfr

    def _local_slots(self, db, keys: jax.Array) -> jax.Array:
        """key -> local slot with ownership applied.  Static striping
        resolves ownership inside the index arithmetic (non-owned keys
        miss); elastic mode indexes the full keyspace and masks by the
        slot map carried in ``db`` instead."""
        slots = self.index.lookup(keys)
        if self.elastic:
            from deneva_tpu.runtime.membership import MEMBER_KEY
            from deneva_tpu.workloads.base import slot_map_owned
            owned = slot_map_owned(keys, db[MEMBER_KEY],
                                   self.cfg.node_id)
            slots = jnp.where(owned, slots, jnp.int32(self.n_local))
        return slots

    # -- repair re-execution (engine/repair.py, Config.repair) ---------
    def re_execute(self, db, q: YCSBQuery, mask: jax.Array,
                   order: jax.Array, stats: dict):
        """Pure re-execution closure, keyed by txn slot: the query
        pytree row IS the captured plan, so re-running a repaired txn is
        ``execute`` on the same row against CURRENT state.  Reads
        re-gather F0 — the masked re-read of the invalidated keys, bit
        for bit: every lane OUTSIDE the frontier re-reads a value no
        committed txn overwrote (the frontier is a bucket-space
        superset of true overwrites, cc/base.committed_write_frontier)
        — and blind writes recompute from ``(key, order)`` exactly as
        any wave's writes do.  One gather + one scatter per sub-round,
        same as the main wave."""
        return self.execute(db, q, mask, order, stats)

    # -- execution (ycsb_txn.cpp:177-209 collapsed to one batch) -------
    def execute(self, db, q: YCSBQuery, mask: jax.Array, order: jax.Array,
                stats: dict, fwd_rank=None, level_exec: bool = False):
        tab: DeviceTable = db[TABLE]
        if fwd_rank is not None:
            assert self.cfg.device_parts == 1, \
                "device_parts > 1 forwarding executes via execute_mc " \
                "under a mesh (the masked path runs through McTableView)"
            # single-pass forwarding executor, in the plan's sorted
            # coordinates (ops/forward.ForwardPlan): a read whose key has
            # an earlier in-batch writer takes that writer's value —
            # f(key, writer rank), computable without the writer having
            # executed (blind writes; RFWD as arithmetic) — and only the
            # final writer of each key touches the table.  Exactly one
            # gather and one scatter against table storage per epoch;
            # checksum and table state are order-independent, so no
            # unsort is needed.  The commit set is BAKED INTO the plan
            # (forward_verdict builds it from batch.valid & batch.active)
            # — a caller with a narrower per-txn mask must rebuild the
            # plan, so we demand mask=None rather than silently ignoring
            # a mask the plan does not reflect.
            assert mask is None, \
                "ForwardPlan embodies the commit set; pass mask=None"
            p = fwd_rank
            slots = self._local_slots(db, p.keys)              # [N]
            # mono: with one partition every valid key is owned, so the
            # slot map follows sorted-key order (DenseIndex identity /
            # SortedIndex rank) and misses steer to capacity at the top;
            # under part_cnt striping (or an elastic mask at n_parts>1)
            # non-owned keys hit miss_slot INTERLEAVED between owned
            # slots — not monotone
            f0, cks, wcnt = _forward_execute_f0(
                tab.columns["F0"], p, slots, tab.capacity,
                mono=self.n_parts == 1)
            stats["read_checksum"] = stats["read_checksum"] + cks
            stats["write_cnt"] = stats["write_cnt"] + wcnt
            db = dict(db)
            db[TABLE] = tab._replace(columns={**tab.columns, "F0": f0})
            return db
        full = self.cfg.sim_full_row
        slots = self._local_slots(db, q.keys)                  # [n, R]
        act = mask[:, None] & jnp.ones_like(q.is_write)
        # reads: gather F0, fold into checksum (keeps the load alive);
        # through .gather so the multi-chip McTableView can interpose
        rmask = act & ~q.is_write
        rslots = jnp.where(rmask, slots, tab.capacity)
        vals = tab.gather(rslots, ("F0",))["F0"]
        ver: VersionRing | None = db.get(VER_TABLE)
        if ver is not None:
            # MVCC stale reads serve HISTORICAL bytes (row_mvcc.cpp:
            # 172-196), reconstructed from the version law f(key, v*)
            # (VersionRing.select_version).  Verdict.order is the
            # serialization ts, with read-only txns forced to 0 (they
            # serialize AT the epoch snapshot, so the live gather already
            # gave them the right version — exclude them by reading "at
            # +inf").  Safe because real txn ts are >= 1 by construction
            # — pool.next_seq starts at 1 and server._contribution raises
            # on a sub-1 stamp.
            big = jnp.int32(jnp.iinfo(jnp.int32).max)
            ver_ts = jnp.where(order > 0, order, big)
            # ONE row gather serves both the version select here and the
            # push below (each gather against the big ring array costs a
            # fixed ~ms-scale pass on v5e; see VersionRing.rows).  Raw
            # slots: write-lane rows are garbage for select (masked by
            # rmask downstream) and exactly what push needs.
            ver_rows = ver.rows(slots)
            vstar, has = ver.version_from(
                ver_rows, jnp.broadcast_to(ver_ts[:, None], slots.shape))
            if full:
                vals = jnp.where(has[..., None],
                                 _field_bytes(q.keys, vstar,
                                              self.cfg.tup_size), vals)
            else:
                vals = jnp.where(has, _field_fingerprint(q.keys, vstar),
                                 vals)
        rm = rmask[..., None] if full else rmask
        stats["read_checksum"] = stats["read_checksum"] + jnp.sum(
            jnp.where(rm, vals, 0), dtype=jnp.uint32)
        # writes: new payload versioned by serialization order
        wmask = (act & q.is_write).reshape(-1)
        wslots = jnp.where(act & q.is_write, slots, tab.capacity).reshape(-1)
        worder = jnp.broadcast_to(order[:, None], slots.shape).reshape(-1)
        if level_exec:
            # caller guarantees the committed set is write-conflict-free
            # (chained sub-round): cross-txn duplicates cannot exist and
            # a txn's own duplicate lanes write identical values, so the
            # scatter-max tournament is redundant
            win = wmask
        else:
            win = last_writer(wslots, worder, wmask, tab.capacity)
        wvals = _field_bytes(q.keys.reshape(-1), worder, self.cfg.tup_size) \
            if full else _field_fingerprint(q.keys.reshape(-1), worder)
        db = dict(db)
        if ver is not None:
            # record each winning overwrite's commit ts (one winner per
            # row per epoch, so each row advances at most one ring slot);
            # no value bytes — reads reconstruct via f(key, v*)
            db[VER_TABLE] = ver.push_rows(
                ver_rows.reshape(-1, ver.depth), wslots, worder, win)
        db[TABLE] = tab.scatter(wslots, {"F0": wvals}, mask=win)
        stats["write_cnt"] = stats["write_cnt"] + wmask.sum(dtype=jnp.uint32)
        return db
