"""PPS — Product-Parts-Suppliers (reference `benchmarks/pps_wl.cpp`,
`pps_query.cpp`, `pps_txn.cpp`).

Five tables (`benchmarks/PPS_schema.txt`): PARTS (10k), PRODUCTS (1k),
SUPPLIERS (1k), USES (product -> 10 parts), SUPPLIES (supplier -> 10
parts).  Eight transaction types mixed by ``perc_*`` config
(`config.h:235-242`):

  GETPART / GETPRODUCT / GETSUPPLIER    — one-row reads
  GETPARTBYPRODUCT / GETPARTBYSUPPLIER — secondary-index walks: read the
      anchor row, the 10 USES/SUPPLIES mapping rows, then the referenced
      part rows (`pps_txn.cpp:729-808,893-960`)
  ORDERPRODUCT    — the mapping walk, then PART_AMOUNT -= 1 on each used
      part (`pps_txn.cpp:962-973` run_orderproduct_5)
  UPDATEPRODUCTPART — write the product's part field
      (`pps_txn.cpp:975-982` set_value(1, part_key))
  UPDATEPART      — PART_AMOUNT += 100 (`pps_txn.cpp:997-1006`)

**The recon path** (SURVEY §7: the most exotic reference machinery): under
Calvin the part keys behind a product are unknown until USES is read, so
the sequencer pre-runs a reconnaissance txn and restarts the real txn with
the keys filled in (`system/sequencer.cpp:88-115`, `:239-257`).  Here every
transaction's RW-set is planned against the epoch snapshot: ``plan`` simply
*gathers* the USES/SUPPLIES mapping rows on device and declares the
resolved part rows in the same RW-set — reconnaissance is one gather,
and the restart loop vanishes.  The mapping reads are declared as CC reads
(exactly the rows the reference locks), so a concurrent writer of the
mapping would conflict and serialize correctly; in PPS (as in the
reference) the USES/SUPPLIES tables are never written after load, so the
snapshot plan is always exact.

TPU shape: all primary keys are dense -> free `DenseIndex`; the nonunique
USES/SUPPLIES indexes (count-suffixed probes `pps_txn.cpp:755-768`) are
dense [anchor*10 + j] layouts — the index walk is an affine gather.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from deneva_tpu.config import Config
from deneva_tpu.ops import last_writer
from deneva_tpu.storage.catalog import parse_schema
from deneva_tpu.workloads.base import partition_owned, partition_slot
from deneva_tpu.storage.table import DeviceTable, fill_columns, to_mc_layout

_FIELDS = "".join(f"\t10,string,FIELD{i}\n" for i in range(1, 11))
PPS_SCHEMA = (
    "TABLE=PARTS\n\t8,int64_t,PART_KEY\n\t8,int64_t,PART_AMOUNT\n" + _FIELDS
    + "TABLE=PRODUCTS\n\t8,int64_t,PRODUCT_KEY\n\t8,int64_t,PRODUCT_PART\n"
    + _FIELDS
    + "TABLE=SUPPLIERS\n\t8,int64_t,SUPPLIER_KEY\n" + _FIELDS
    + "TABLE=USES\n\t8,int64_t,PRODUCT_KEY\n\t8,int64_t,PART_KEY\n"
    + "TABLE=SUPPLIES\n\t8,int64_t,SUPPLIER_KEY\n\t8,int64_t,PART_KEY\n")

TID = {"PARTS": 20, "PRODUCTS": 21, "SUPPLIERS": 22, "USES": 23,
       "SUPPLIES": 24}

(GETPART, GETPRODUCT, GETSUPPLIER, GETPARTBYPRODUCT, GETPARTBYSUPPLIER,
 ORDERPRODUCT, UPDATEPRODUCTPART, UPDATEPART) = range(8)


@dataclass
class PPSQuery:
    """One epoch of PPS queries (reference `PPSQuery`,
    `benchmarks/pps_query.cpp:40-120`); part_keys recon happens in plan."""

    txn_type: jax.Array      # int32[n] 0..7
    part_key: jax.Array      # int32[n]
    product_key: jax.Array   # int32[n]
    supplier_key: jax.Array  # int32[n]


jax.tree_util.register_dataclass(
    PPSQuery,
    data_fields=["txn_type", "part_key", "product_key", "supplier_key"],
    meta_fields=[])


class PPSWorkload:
    txn_type_names = ("pps_getpart", "pps_getproduct", "pps_getsupplier",
                      "pps_getpartbyproduct", "pps_getpartbysupplier",
                      "pps_orderproduct", "pps_updateproductpart",
                      "pps_updatepart")

    def txn_type_of(self, q: PPSQuery) -> jax.Array:
        return q.txn_type

    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.catalog = parse_schema(PPS_SCHEMA)
        self.n_parts = cfg.pps_parts_cnt
        self.n_products = cfg.pps_products_cnt
        self.n_suppliers = cfg.pps_suppliers_cnt
        self.per = cfg.pps_parts_per        # MAX_PPS_PART_PER_PRODUCT (config.h:230)
        # partitioned deployment: PARTS/PRODUCTS/SUPPLIERS stripe by
        # key % part_cnt; the immutable USES/SUPPLIES mapping tables are
        # replicated on every node (like TPCC's read-only ITEM), which is
        # what lets on-device recon (`plan`) stay local — the reference
        # instead ships recon results through the sequencer
        # (`system/sequencer.cpp:88-115`)
        self.n_pt = max(cfg.part_cnt, 1)
        self.me = cfg.node_id if self.n_pt > 1 else 0
        for nm, n in (("pps_parts_cnt", self.n_parts),
                      ("pps_products_cnt", self.n_products),
                      ("pps_suppliers_cnt", self.n_suppliers)):
            if n % self.n_pt != 0:
                raise ValueError(f"{nm} must divide evenly over part_cnt")
        self.n_parts_loc = self.n_parts // self.n_pt
        self.n_products_loc = self.n_products // self.n_pt
        self.n_suppliers_loc = self.n_suppliers // self.n_pt
        need = 1 + 2 * self.per
        if cfg.max_accesses < need:
            raise ValueError(f"PPS needs max_accesses >= {need}")
        # txn-type mix (config.h:235-242); order matches the enum
        self.mix = np.array([
            cfg.perc_getparts, cfg.perc_getproducts, cfg.perc_getsuppliers,
            cfg.perc_getpartbyproduct, cfg.perc_getpartbysupplier,
            cfg.perc_orderproduct, cfg.perc_updateproductpart,
            cfg.perc_updatepart], np.float64)
        assert abs(self.mix.sum() - 1.0) < 1e-6

    # -- local slots (partitioned storage addressing) --------------------
    def _owned(self, key):
        return partition_owned(key, self.n_pt, self.me)

    def _slot(self, key, n_local):
        return partition_slot(key, self.n_pt, self.me, n_local)

    def part_slot(self, key):
        return self._slot(key, self.n_parts_loc)

    def product_slot(self, key):
        return self._slot(key, self.n_products_loc)

    # -- loader (pps_wl.cpp:71-111 threadInit*) -------------------------
    def load(self):
        db = {}
        p, me = self.n_pt, self.me

        def fill(name, cap, cols):
            t = DeviceTable.create(self.catalog.table(name), cap)
            db[name] = fill_columns(t, cap, cols)

        p_ids = me + p * np.arange(self.n_parts_loc, dtype=np.int32)
        fill("PARTS", self.n_parts_loc,
             {"PART_KEY": p_ids,
              "PART_AMOUNT": np.full(self.n_parts_loc, 10000, np.int32)})
        pr_ids = me + p * np.arange(self.n_products_loc, dtype=np.int32)
        fill("PRODUCTS", self.n_products_loc,
             {"PRODUCT_KEY": pr_ids,
              "PRODUCT_PART": _map_part(pr_ids, 0, 0, self.n_parts)})
        s_ids = me + p * np.arange(self.n_suppliers_loc, dtype=np.int32)
        fill("SUPPLIERS", self.n_suppliers_loc, {"SUPPLIER_KEY": s_ids})

        # mapping tables: row (anchor*per + j) -> part (pps_wl.cpp uses
        # URand parts per anchor; here a deterministic hash map)
        u = np.arange(self.n_products * self.per, dtype=np.int32)
        fill("USES", len(u),
             {"PRODUCT_KEY": u // self.per,
              "PART_KEY": _map_part(u // self.per, u % self.per, 1,
                                    self.n_parts)})
        s = np.arange(self.n_suppliers * self.per, dtype=np.int32)
        fill("SUPPLIES", len(s),
             {"SUPPLIER_KEY": s // self.per,
              "PART_KEY": _map_part(s // self.per, s % self.per, 2,
                                    self.n_parts)})
        D = self.cfg.device_parts
        if D > 1:
            # anchor keys stripe across chips; the immutable USES/SUPPLIES
            # mapping tables replicate (what keeps recon local, see class
            # docstring), exactly like the multi-process deployment
            for name in ("PARTS", "PRODUCTS", "SUPPLIERS"):
                db[name] = to_mc_layout(db[name], D)
            for name in ("USES", "SUPPLIES"):
                db[name] = db[name]._replace(mc_replicated=True)
        return db

    # -- generation (pps_query.cpp:40-120) ------------------------------
    def generate(self, rng: jax.Array, n: int) -> PPSQuery:
        k0, k1, k2, k3 = jax.random.split(rng, 4)
        cum = jnp.asarray(np.cumsum(self.mix), jnp.float32)
        r = jax.random.uniform(k0, (n,))
        txn_type = jnp.sum(r[:, None] >= cum[None, :], axis=1
                           ).astype(jnp.int32)
        return PPSQuery(
            txn_type=jnp.clip(txn_type, 0, 7),
            part_key=jax.random.randint(k1, (n,), 0, self.n_parts),
            product_key=jax.random.randint(k2, (n,), 0, self.n_products),
            supplier_key=jax.random.randint(k3, (n,), 0, self.n_suppliers))

    # -- wire adapters (distributed runtime) -----------------------------
    # all four query fields are per-txn scalars; no per-access columns
    def to_wire(self, q: PPSQuery):
        n = int(q.txn_type.shape[0])
        s = np.stack([np.asarray(q.txn_type, np.int32),
                      np.asarray(q.part_key, np.int32),
                      np.asarray(q.product_key, np.int32),
                      np.asarray(q.supplier_key, np.int32)], axis=1)
        return (np.zeros((n, 1), np.int32), np.zeros((n, 1), np.int8), s)

    def from_wire(self, keys: np.ndarray, types: np.ndarray,
                  scalars: np.ndarray) -> PPSQuery:
        scalars = np.ascontiguousarray(scalars, np.int32)
        return PPSQuery(txn_type=jnp.asarray(scalars[:, 0]),
                        part_key=jnp.asarray(scalars[:, 1]),
                        product_key=jnp.asarray(scalars[:, 2]),
                        supplier_key=jnp.asarray(scalars[:, 3]))

    def from_wire_dev(self, keys, types, scalars) -> PPSQuery:
        """Traceable from_wire (cluster dispatch jit)."""
        return PPSQuery(txn_type=scalars[:, 0], part_key=scalars[:, 1],
                        product_key=scalars[:, 2],
                        supplier_key=scalars[:, 3])

    # -- RW-set planning with on-device recon ---------------------------
    def plan(self, db, q: PPSQuery) -> dict:
        n = q.txn_type.shape[0]
        A = self.cfg.max_accesses
        t = q.txn_type
        per = self.per

        anchor_is_part = (t == GETPART) | (t == UPDATEPART)
        anchor_is_supp = (t == GETSUPPLIER) | (t == GETPARTBYSUPPLIER)
        by_prod = ((t == GETPARTBYPRODUCT) | (t == ORDERPRODUCT))
        walks = by_prod | (t == GETPARTBYSUPPLIER)

        tables = jnp.zeros((n, A), jnp.int32)
        keys = jnp.zeros((n, A), jnp.int32)
        is_read = jnp.zeros((n, A), bool)
        is_write = jnp.zeros((n, A), bool)
        valid = jnp.zeros((n, A), bool)
        order_free = jnp.zeros((n, A), bool)
        owner = jnp.zeros((n, A), jnp.int32)
        p_nodes = jnp.int32(self.n_pt)

        # access 0: anchor row
        a_tid = jnp.where(anchor_is_part, TID["PARTS"],
                          jnp.where(anchor_is_supp, TID["SUPPLIERS"],
                                    TID["PRODUCTS"]))
        a_key = jnp.where(anchor_is_part, q.part_key,
                          jnp.where(anchor_is_supp, q.supplier_key,
                                    q.product_key))
        a_write = (t == UPDATEPRODUCTPART) | (t == UPDATEPART)
        tables = tables.at[:, 0].set(a_tid)
        keys = keys.at[:, 0].set(a_key)
        is_read = is_read.at[:, 0].set(True)
        is_write = is_write.at[:, 0].set(a_write)
        valid = valid.at[:, 0].set(True)
        owner = owner.at[:, 0].set(a_key % p_nodes)
        # UPDATEPART is a pure escrow add (PART_AMOUNT += 100, no read
        # used): order_free — adds commute, while GETPART's accumulator
        # READ stays ordered against every add (base.build_incidence)
        order_free = order_free.at[:, 0].set(t == UPDATEPART)

        # accesses 1..per: USES/SUPPLIES mapping rows (reads);
        # recon: gather the referenced part keys from the snapshot
        lane = jnp.arange(per)
        map_key = jnp.where(by_prod[:, None], q.product_key[:, None],
                            q.supplier_key[:, None]) * per + lane[None, :]
        map_tid = jnp.where(by_prod, TID["USES"], TID["SUPPLIES"])
        part_keys = jnp.where(
            by_prod[:, None],
            jnp.take(db["USES"].columns["PART_KEY"], map_key, axis=0),
            jnp.take(db["SUPPLIES"].columns["PART_KEY"], map_key, axis=0))
        wmask = walks[:, None] & jnp.ones((n, per), bool)
        tables = tables.at[:, 1:1 + per].set(map_tid[:, None])
        keys = keys.at[:, 1:1 + per].set(map_key)
        is_read = is_read.at[:, 1:1 + per].set(wmask)
        valid = valid.at[:, 1:1 + per].set(wmask)
        # USES/SUPPLIES replicate; their immutable reads are validated at
        # the walk anchor's owner (one participant, never a conflict)
        anchor = jnp.where(by_prod, q.product_key, q.supplier_key)
        owner = owner.at[:, 1:1 + per].set((anchor % p_nodes)[:, None])

        # accesses 1+per..1+2*per: resolved part rows
        pw = (t == ORDERPRODUCT)[:, None] & wmask
        tables = tables.at[:, 1 + per:1 + 2 * per].set(TID["PARTS"])
        keys = keys.at[:, 1 + per:1 + 2 * per].set(part_keys)
        is_read = is_read.at[:, 1 + per:1 + 2 * per].set(wmask)
        is_write = is_write.at[:, 1 + per:1 + 2 * per].set(pw)
        valid = valid.at[:, 1 + per:1 + 2 * per].set(wmask)
        # ORDERPRODUCT's part lanes are pure escrow decrements
        # (PART_AMOUNT -= 1; the declared read is vestigial): add-add
        # pairs need no ordering, GETPARTBY* reads of the same parts do
        order_free = order_free.at[:, 1 + per:1 + 2 * per].set(pw)
        owner = owner.at[:, 1 + per:1 + 2 * per].set(part_keys % p_nodes)

        return dict(table_ids=tables, keys=keys, is_read=is_read,
                    is_write=is_write, valid=valid, order_free=order_free,
                    owner=owner)

    # -- execution ------------------------------------------------------
    # UPDATE* txns rewrite mapping fields read in the same txn (recon),
    # so the single-pass forwarding executor does not apply
    blind_writes = False

    def execute(self, db, q: PPSQuery, mask: jax.Array, order: jax.Array,
                stats: dict, fwd_rank=None, level_exec: bool = False):
        db = dict(db)
        t = q.txn_type
        per = self.per
        n = t.shape[0]

        # reads feed the checksum (anchor row field); remote anchors read
        # the trash row and stay masked out of this node's stat
        anchor_amt = db["PARTS"].gather(self.part_slot(q.part_key),
                                        ("PART_AMOUNT",))["PART_AMOUNT"]
        stats["read_checksum"] = stats["read_checksum"] + jnp.sum(
            jnp.where(mask & (t == GETPART) & self._owned(q.part_key),
                      anchor_amt, 0)
        ).astype(jnp.uint32)

        # ORDERPRODUCT: PART_AMOUNT -= 1 on each part of the product
        # (parts resolve via the replicated USES map; each node applies
        # the decrements for the part rows it owns)
        om = mask & (t == ORDERPRODUCT)
        lane = jnp.arange(per)
        ukey = q.product_key[:, None] * per + lane[None, :]
        parts = jnp.take(db["USES"].columns["PART_KEY"], ukey, axis=0)
        m2 = om[:, None] & jnp.ones((n, per), bool)
        db["PARTS"] = db["PARTS"].scatter_add(
            self.part_slot(parts).reshape(-1),
            {"PART_AMOUNT": jnp.where(m2, -1, 0).reshape(-1)},
            mask=m2.reshape(-1))

        # UPDATEPART: PART_AMOUNT += 100 (run_updatepart_1)
        um = mask & (t == UPDATEPART)
        db["PARTS"] = db["PARTS"].scatter_add(
            self.part_slot(q.part_key),
            {"PART_AMOUNT": jnp.where(um, 100, 0)}, mask=um)

        # UPDATEPRODUCTPART: product's part field = part_key
        # (run_updateproductpart_1 set_value(1, part_key))
        pm = mask & (t == UPDATEPRODUCTPART)
        pslot = self.product_slot(q.product_key)
        if level_exec:
            # chained sub-round: committed set is write-conflict-free,
            # so each product has at most one writer in this call
            win = pm
        else:
            win = last_writer(jnp.where(pm, pslot, db["PRODUCTS"].capacity),
                              order, pm, db["PRODUCTS"].capacity)
        db["PRODUCTS"] = db["PRODUCTS"].scatter(
            pslot, {"PRODUCT_PART": q.part_key}, mask=win)

        stats["write_cnt"] = stats["write_cnt"] + (
            (om.sum() * per) + um.sum() + pm.sum()).astype(jnp.uint32)
        return db


def _map_part(anchor, j, salt, n_parts) -> np.ndarray:
    """Deterministic anchor->part mapping for USES/SUPPLIES (the
    reference loader draws URand parts, pps_wl.cpp threadInitUses)."""
    h = (np.asarray(anchor).astype(np.int64) * 1000003 + np.asarray(j) * 7919
         + salt * 104729) % 2654435761
    return (h % n_parts).astype(np.int32)
