"""TPC-C (reference `benchmarks/tpcc_wl.cpp`, `tpcc_query.cpp`, `tpcc_txn.cpp`).

Payment + NewOrder only, like the reference (`tpcc_query.cpp:122-141`).
Nine tables per `benchmarks/TPCC_short_schema.txt`; composite keys follow
`benchmarks/tpcc_helper.h:24-30` (distKey/custKey/stockKey) flattened to
dense int32 slot spaces so every primary index is a free `DenseIndex`.

TPU shape — the reference's request-at-a-time state machines
(PAYMENT0-5 / NEWORDER0-9, `tpcc_txn.cpp:247-470`) become:

* ``generate`` — whole-epoch device sampling of query structs with the
  reference's exact distributions (`tpcc_query.cpp:150-260`): payment
  remote-customer prob 0.15, by-last-name prob 60 %, NURand(1023) customer
  and NURand(8191) item selection, ol_cnt ~ URand(5,15), remote supply
  warehouse prob 0.01 gated by MPR.
* ``plan`` — the full RW-set declared up front: warehouse/district/
  customer rows + up to 15 stock rows.  ITEM reads are *excluded* from
  the CC access list: the ITEM table is never written after load (the
  reference still routes item reads through `row_t::get_row`, but they
  can never conflict), so dropping them shrinks the conflict problem by
  ~45 % with identical serializability.
* ``execute`` — one batched pass per epoch (or per chained level):
  commutative balance/YTD updates via ``scatter_add`` (exact under
  duplicates), the non-commutative stock-quantity rule via gather/
  last-writer scatter, and O_ID allocation as a *per-district segmented
  prefix sum* over the committed batch — the epoch analogue of
  D_NEXT_O_ID++ under the district row lock (`tpcc_txn.cpp` new_order_2).
  ORDER / NEW-ORDER / ORDER-LINE / HISTORY inserts append into
  ring-retention tables (`table_t::get_new_row` without the latch).

By-last-name lookup (CUSTOMER_LAST_IDX, a nonunique hash index in the
reference): the loader assigns customer ``c`` the lastname id ``c % 1000``
(the reference's loader uses `Lastname(c_id % 1000)` for the first 1000 and
random beyond, `tpcc_wl.cpp` init_cust).  With ``tpcc_by_last_index``
(default) the lookup resolves through a REAL nonunique HashIndex — bucket
probe + postings walk to the middle matching customer
(`_build_lastname_index`, the analogue of `index_hash.cpp:68-100`); the
closed-form arithmetic bypass (``c_id = L + 1000*(cust_per_dist // 1000
// 2)``) remains as the ablation path and the oracle the index probe is
tested against.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from deneva_tpu.config import Config
from deneva_tpu.ops import last_writer
from deneva_tpu.storage.catalog import parse_schema
from deneva_tpu.workloads.base import partition_owned, partition_slot
from deneva_tpu.storage.table import DeviceTable, fill_columns, to_mc_layout

# ---------------------------------------------------------------------------
# schema (column set of benchmarks/TPCC_short_schema.txt)

_SCHEMA_COLS = {
    "WAREHOUSE": [("W_ID", "int64_t"), ("W_TAX", "double"),
                  ("W_YTD", "double")],
    "DISTRICT": [("D_ID", "int64_t"), ("D_W_ID", "int64_t"),
                 ("D_TAX", "double"), ("D_YTD", "double"),
                 ("D_NEXT_O_ID", "int64_t")],
    "CUSTOMER": [("C_ID", "int64_t"), ("C_D_ID", "int64_t"),
                 ("C_W_ID", "int64_t"), ("C_LAST", "int64_t"),
                 ("C_DISCOUNT", "double"), ("C_BALANCE", "double"),
                 ("C_YTD_PAYMENT", "double"), ("C_PAYMENT_CNT", "int64_t")],
    "HISTORY": [("H_C_ID", "int64_t"), ("H_C_D_ID", "int64_t"),
                ("H_C_W_ID", "int64_t"), ("H_D_ID", "int64_t"),
                ("H_W_ID", "int64_t"), ("H_AMOUNT", "double")],
    "NEW-ORDER": [("NO_O_ID", "int64_t"), ("NO_D_ID", "int64_t"),
                  ("NO_W_ID", "int64_t")],
    "ORDER": [("O_ID", "int64_t"), ("O_C_ID", "int64_t"),
              ("O_D_ID", "int64_t"), ("O_W_ID", "int64_t"),
              ("O_ENTRY_D", "int64_t"), ("O_OL_CNT", "int64_t"),
              ("O_ALL_LOCAL", "int64_t")],
    "ORDER-LINE": [("OL_O_ID", "int64_t"), ("OL_D_ID", "int64_t"),
                   ("OL_W_ID", "int64_t"), ("OL_NUMBER", "int64_t"),
                   ("OL_I_ID", "int64_t"), ("OL_QUANTITY", "int64_t")],
    "ITEM": [("I_ID", "int64_t"), ("I_IM_ID", "int64_t"),
             ("I_PRICE", "int64_t")],
    "STOCK": [("S_I_ID", "int64_t"), ("S_W_ID", "int64_t"),
              ("S_QUANTITY", "int64_t"), ("S_REMOTE_CNT", "int64_t")],
}

TPCC_SCHEMA = "".join(
    f"TABLE={t}\n" + "".join(f"\t8,{ct},{cn}\n" for cn, ct in cols)
    for t, cols in _SCHEMA_COLS.items())

# TPCC_FULL_SCHEMA extras (reference `benchmarks/TPCC_full_schema.txt`):
# the columns the short schema drops.  Strings materialize as fingerprint
# words (storage/table.py); loader fills them deterministically, and the
# full-schema execution deltas below keep S_YTD/S_ORDER_CNT/OL_* live.
_FULL_EXTRA = {
    "WAREHOUSE": [("W_NAME", "string", 10), ("W_STREET_1", "string", 20),
                  ("W_STREET_2", "string", 20), ("W_CITY", "string", 20),
                  ("W_STATE", "string", 2), ("W_ZIP", "string", 9)],
    "DISTRICT": [("D_NAME", "string", 10), ("D_STREET_1", "string", 20),
                 ("D_STREET_2", "string", 20), ("D_CITY", "string", 20),
                 ("D_STATE", "string", 2), ("D_ZIP", "string", 9)],
    "CUSTOMER": [("C_FIRST", "string", 16), ("C_MIDDLE", "string", 2),
                 ("C_STREET_1", "string", 20), ("C_STREET_2", "string", 20),
                 ("C_CITY", "string", 20), ("C_STATE", "string", 2),
                 ("C_ZIP", "string", 9), ("C_PHONE", "string", 16),
                 ("C_SINCE", "int64_t", 8), ("C_CREDIT", "string", 2),
                 ("C_CREDIT_LIM", "int64_t", 8),
                 ("C_DELIVERY_CNT", "uint64_t", 8),
                 ("C_DATA", "string", 500)],
    "HISTORY": [("H_DATE", "int64_t", 8), ("H_DATA", "string", 24)],
    "ORDER": [("O_CARRIER_ID", "int64_t", 8)],
    "ORDER-LINE": [("OL_SUPPLY_W_ID", "int64_t", 8),
                   ("OL_DELIVERY_D", "int64_t", 8),
                   ("OL_AMOUNT", "double", 8),
                   ("OL_DIST_INFO", "string", 24)],
    "ITEM": [("I_NAME", "string", 24), ("I_DATA", "string", 50)],
    "STOCK": [(f"S_DIST_{i:02d}", "string", 24) for i in range(1, 11)]
             + [("S_YTD", "int64_t", 8), ("S_ORDER_CNT", "int64_t", 8),
                ("S_DATA", "string", 50)],
}


def tpcc_schema(full: bool) -> str:
    if not full:
        return TPCC_SCHEMA
    out = []
    for t, cols in _SCHEMA_COLS.items():
        out.append(f"TABLE={t}\n")
        out.extend(f"\t8,{ct},{cn}\n" for cn, ct in cols)
        out.extend(f"\t{sz},{ct},{cn}\n"
                   for cn, ct, sz in _FULL_EXTRA.get(t, ()))
    return "".join(out)

# table ids for CC access identity (order matters: stable across runs)
TID = {name: i for i, name in enumerate(_SCHEMA_COLS)}

TPCC_PAYMENT = 0
TPCC_NEW_ORDER = 1

_LASTNAMES = 1000          # Lastname(NURand(255,0,999)), tpcc_helper.cpp


@dataclass
class TPCCQuery:
    """One epoch of TPC-C queries; pytree with leading dim n.

    Mirrors `TPCCQuery` / `Item_no` (`benchmarks/tpcc_query.h`) with the
    item list padded to ``max_items_per_txn``.
    """

    txn_type: jax.Array     # int32[n]  TPCC_PAYMENT | TPCC_NEW_ORDER
    w_id: jax.Array         # int32[n]  home warehouse (0-based)
    d_id: jax.Array         # int32[n]
    c_id: jax.Array         # int32[n]  resolved customer (by-lastname folded in)
    c_w_id: jax.Array       # int32[n]  payment customer warehouse
    c_d_id: jax.Array       # int32[n]
    h_amount: jax.Array     # float32[n]
    ol_cnt: jax.Array       # int32[n]
    items: jax.Array        # int32[n, I] item ids; duplicates invalidated
    item_valid: jax.Array   # bool[n, I]
    supply_w: jax.Array     # int32[n, I]
    quantity: jax.Array     # int32[n, I]


jax.tree_util.register_dataclass(
    TPCCQuery,
    data_fields=["txn_type", "w_id", "d_id", "c_id", "c_w_id", "c_d_id",
                 "h_amount", "ol_cnt", "items", "item_valid", "supply_w",
                 "quantity"],
    meta_fields=[])


def _nurand(key: jax.Array, A: int, n: int, shape) -> jax.Array:
    """TPC-C NURand(A, 0, n-1) with C=0 (`tpcc_helper.cpp` NURand; the
    reference draws C once per run — a constant offset mod n)."""
    k1, k2 = jax.random.split(key)
    a = jax.random.randint(k1, shape, 0, A + 1)
    b = jax.random.randint(k2, shape, 0, n)
    return (a | b) % n


class TPCCWorkload:
    """Payment + NewOrder over 9 device tables."""

    txn_type_names = ("tpcc_payment", "tpcc_new_order")

    def txn_type_of(self, q: "TPCCQuery") -> jax.Array:
        return q.txn_type

    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.full_schema = cfg.tpcc_full_schema
        self.catalog = parse_schema(tpcc_schema(self.full_schema))
        self.n_wh = cfg.num_wh
        self.n_dist = 10                     # DIST_PER_WARE (tpcc_const.h)
        self.cust_per_dist = cfg.cust_per_dist
        self.max_items = cfg.max_items
        self.ipt = cfg.max_items_per_txn     # MAX_ITEMS_PER_TXN=15 (config.h:189)
        # partitioned deployment: warehouse -> node (reference wh_to_part,
        # `benchmarks/tpcc_helper.cpp`); this node stores warehouses
        # ≡ node_id (mod part_cnt).  ITEM is read-only and replicated
        # everywhere, exactly like the reference.
        self.n_parts = max(cfg.part_cnt, 1)
        self.me = cfg.node_id if self.n_parts > 1 else 0
        if self.n_wh % self.n_parts != 0:
            raise ValueError("num_wh must divide evenly over part_cnt")
        self.n_wh_loc = self.n_wh // self.n_parts
        # effective lastname population: every district must contain at
        # least one customer per lastname for the closed-form lookup
        self.lastnames = min(_LASTNAMES, self.cust_per_dist)
        need = 3 + self.ipt                  # wh + dist + cust + stock rows
        if cfg.max_accesses < need:
            raise ValueError(
                f"TPCC needs max_accesses >= {need}, got {cfg.max_accesses}")
        self.n_districts = self.n_wh * self.n_dist
        self.n_cust = self.n_districts * self.cust_per_dist
        self.n_stock = self.n_wh * self.max_items
        # local (stored) row counts — global counts / n_parts
        self.n_districts_loc = self.n_wh_loc * self.n_dist
        self.n_cust_loc = self.n_districts_loc * self.cust_per_dist
        self.n_stock_loc = self.n_wh_loc * self.max_items
        # flattened composite keys and the per-district sort key must fit
        # int32 (storage/table.py's stated key contract)
        lim = 2**31 - 1
        if max(self.n_stock, self.n_cust) > lim:
            raise ValueError("TPCC key space exceeds int32: shrink "
                             "num_wh/max_items/cust_per_dist")
        if (self.n_districts + 1) * 2 * cfg.epoch_batch > lim:
            raise ValueError("num_wh*10*2*epoch_batch must fit int32")
        if cfg.tpcc_by_last_index:
            self._build_lastname_index()

    def _build_lastname_index(self):
        """CUSTOMER_LAST nonunique secondary index (reference
        `tpcc_wl.cpp` index_insert on custNPKey, probed
        `index_hash.cpp:68-100`): hash probe on (w, d, lastname) ->
        packed (postings start, count); the postings array lists the
        matching customers' c_ids in ascending order, and payment picks
        the middle one (`tpcc_txn.cpp` run_payment by-last-name).  Global
        (every node resolves remote customers — queries are generated
        before planning, like the reference client)."""
        from deneva_tpu.storage.index import HashIndex

        cpd, names = self.cust_per_dist, self.lastnames
        c = np.arange(self.n_cust, dtype=np.int64)
        c_local = (c % cpd).astype(np.int32)
        dist = (c // cpd).astype(np.int64)
        lastkey = dist * names + c_local % names        # (w,d,L) composite
        order = np.lexsort((c_local, lastkey))
        postings = c_local[order]                       # grouped by lastkey
        sorted_keys = lastkey[order]
        uniq, starts, counts = np.unique(sorted_keys, return_index=True,
                                         return_counts=True)
        if counts.max() >= 256 or len(postings) >= (1 << 23):
            raise ValueError("CUSTOMER_LAST packing overflow: shrink "
                             "cust_per_dist or num_wh")
        packed = (starts.astype(np.int64) << 8 | counts).astype(np.int32)
        self.last_idx = HashIndex.build(uniq.astype(np.int32), packed,
                                        miss_slot=0)
        self.last_postings = jnp.asarray(postings)

    def _lastname_middle(self, c_w, c_d, lastname):
        """Middle same-lastname customer via the real index probe."""
        names = self.lastnames
        key = (c_w * self.n_dist + c_d) * names + lastname
        packed = self.last_idx.lookup(key)
        start, cnt = packed >> 8, packed & 0xFF
        return jnp.take(self.last_postings,
                        jnp.clip(start + cnt // 2, 0,
                                 self.last_postings.shape[0] - 1))

    # -- composite keys (tpcc_helper.h:24-30, flattened dense) ----------
    # global keys: CC identity (plan / conflict detection) — same on
    # every node so the merged-epoch validation agrees cluster-wide
    def dist_key(self, w, d):
        return w * self.n_dist + d

    def cust_key(self, w, d, c):
        return self.dist_key(w, d) * self.cust_per_dist + c

    def order_index_key(self, w, d, o_id):
        """Dynamic ORDER-index key, district-major so one district's
        orders are a contiguous ascending o_id run (range scans = the
        B+-tree leaf walk).  o_id stays < 2^21 and districts < 2^10 by
        the tpcc_order_index config guard, so the composite fits int32."""
        return (self.dist_key(w, d) * jnp.int32(1 << 21)
                + o_id.astype(jnp.int32))

    def stock_key(self, w, i):
        return w * self.max_items + i

    # local slots: storage addressing on THIS node — warehouses not owned
    # here resolve to each table's trash slot.  NOTE: the trash row is a
    # spill target, not guaranteed zeros — masked scatters land IN it, so
    # trash-row gathers of scatter-written columns return garbage; every
    # consumer of a remote-lane gather below must stay masked by
    # ownership (they do: o_id/inserts use m & owned, stock writes
    # resolve back into trash)
    def wh_owned(self, w):
        return partition_owned(w, self.n_parts, self.me)

    def _wloc(self, w):
        return w // self.n_parts if self.n_parts > 1 else w

    def wh_slot(self, w):
        return partition_slot(w, self.n_parts, self.me, self.n_wh_loc)

    def dist_slot(self, w, d):
        return jnp.where(self.wh_owned(w),
                         self._wloc(w) * self.n_dist + d,
                         jnp.int32(self.n_districts_loc))

    def cust_slot(self, w, d, c):
        return jnp.where(self.wh_owned(w),
                         (self._wloc(w) * self.n_dist + d)
                         * self.cust_per_dist + c,
                         jnp.int32(self.n_cust_loc))

    def stock_slot(self, w, i):
        return jnp.where(self.wh_owned(w),
                         self._wloc(w) * self.max_items + i,
                         jnp.int32(self.n_stock_loc))

    # -- loader (tpcc_wl.cpp:89-152 parallel loaders) -------------------
    def load(self):
        """Build the initial database ON DEVICE as one jitted program.

        The reference's loaders are parallel host threads writing rows
        (`tpcc_wl.cpp:89-152`); the first cut here mirrored that with
        numpy columns copied to the device — which meant shipping
        hundreds of MB over the host link at num_wh=64 (minutes on a
        tunneled chip).  Every initial value is arithmetic on the row
        index, so the whole load is a single XLA program: zero
        host->device bytes, compile + run in seconds at any scale."""
        db = jax.jit(self._build_db)()
        if self.cfg.audit:
            # isolation audit stamp tables (cc/base.audit_observe):
            # loader-installed so every db-construction path threads the
            # identical pytree; excluded from state_digest (control
            # plane, like the elastic MEMBER_KEY)
            from deneva_tpu.cc.base import AUDIT_KEY, audit_init
            db[AUDIT_KEY] = audit_init(self.cfg)
        return db

    def _build_db(self):
        cfg = self.cfg
        db = {}

        def tab(name, cap, ring=False):
            t = DeviceTable.create(self.catalog.table(name), cap, ring=ring)
            db[name] = t
            return t

        # local slot ℓ stores global warehouse me + n_parts * (ℓ // ...):
        # loader values derive from GLOBAL ids so any node's copy of a row
        # matches what a single-node load would have produced
        p, me = self.n_parts, self.me

        wh = tab("WAREHOUSE", self.n_wh_loc)
        w_glob = me + p * jnp.arange(self.n_wh_loc, dtype=jnp.int32)
        db["WAREHOUSE"] = fill_columns(wh, self.n_wh_loc, {
            "W_ID": w_glob,
            "W_TAX": _rand01(w_glob, 7) * 0.2,      # URand(0,.2) (init_wh)
            "W_YTD": jnp.full(self.n_wh_loc, 300000.0, jnp.float32)})

        dist = tab("DISTRICT", self.n_districts_loc)
        dl = jnp.arange(self.n_districts_loc, dtype=jnp.int32)
        d_w = me + p * (dl // self.n_dist)
        d_id = dl % self.n_dist
        d_glob = d_w * self.n_dist + d_id
        db["DISTRICT"] = fill_columns(dist, self.n_districts_loc, {
            "D_ID": d_id,
            "D_W_ID": d_w,
            "D_TAX": _rand01(d_glob, 11) * 0.2,
            "D_YTD": jnp.full(self.n_districts_loc, 30000.0, jnp.float32),
            "D_NEXT_O_ID": jnp.full(self.n_districts_loc, 3001, jnp.int32)})

        cust = tab("CUSTOMER", self.n_cust_loc)
        cl = jnp.arange(self.n_cust_loc, dtype=jnp.int32)
        c_local = cl % self.cust_per_dist
        c_d = (cl // self.cust_per_dist) % self.n_dist
        c_w = me + p * (cl // (self.cust_per_dist * self.n_dist))
        c_glob = (c_w * self.n_dist + c_d) * self.cust_per_dist + c_local
        db["CUSTOMER"] = fill_columns(cust, self.n_cust_loc, {
            "C_ID": c_local,
            "C_D_ID": c_d,
            "C_W_ID": c_w,
            "C_LAST": c_local % self.lastnames,
            "C_DISCOUNT": _rand01(c_glob, 13) * 0.5,
            "C_BALANCE": jnp.full(self.n_cust_loc, -10.0, jnp.float32),
            "C_YTD_PAYMENT": jnp.full(self.n_cust_loc, 10.0, jnp.float32),
            "C_PAYMENT_CNT": jnp.ones(self.n_cust_loc, jnp.int32)})

        item = tab("ITEM", self.max_items)
        i_ids = jnp.arange(self.max_items, dtype=jnp.int32)
        db["ITEM"] = fill_columns(item, self.max_items, {
            "I_ID": i_ids,
            "I_IM_ID": _mulmod(i_ids, 2654435761, 10000),
            "I_PRICE": 1 + _mulmod(i_ids, 48271, 100)})

        stock = tab("STOCK", self.n_stock_loc)
        sl = jnp.arange(self.n_stock_loc, dtype=jnp.int32)
        s_i = sl % self.max_items
        s_w = me + p * (sl // self.max_items)
        s_glob = s_w * self.max_items + s_i
        db["STOCK"] = fill_columns(stock, self.n_stock_loc, {
            "S_I_ID": s_i,
            "S_W_ID": s_w,
            "S_QUANTITY": 10 + _mulmod(s_glob, 69621, 91),
            "S_REMOTE_CNT": jnp.zeros(self.n_stock_loc, jnp.int32)})

        cap = cfg.insert_table_cap
        tab("HISTORY", cap, ring=True)
        tab("ORDER", cap, ring=True)
        tab("NEW-ORDER", cap, ring=True)
        # lines wrap no earlier than their orders (<= ipt lines per order)
        tab("ORDER-LINE", cap * self.ipt, ring=True)

        if self.full_schema:
            # TPCC_FULL_SCHEMA: fill the extra columns of the fixed
            # tables with deterministic per-row hashes (the reference
            # loader draws random strings, tpcc_wl.cpp init_*; ours must
            # be recomputable for consistency checks)
            counts = {"WAREHOUSE": self.n_wh_loc,
                      "DISTRICT": self.n_districts_loc,
                      "CUSTOMER": self.n_cust_loc, "ITEM": self.max_items,
                      "STOCK": self.n_stock_loc}
            for t, extras in _FULL_EXTRA.items():
                n = counts.get(t)
                if n is None:          # ring tables fill at insert time
                    continue
                cols = dict(db[t].columns)
                ids = jnp.arange(n, dtype=jnp.int32).astype(jnp.uint32)
                for j, (cn, _ct, _sz) in enumerate(extras):
                    if cn in ("S_YTD", "S_ORDER_CNT", "C_DELIVERY_CNT"):
                        continue       # spec-initialized counters: zero
                    v = ids * jnp.uint32(2654435761) \
                        + jnp.uint32(0x9E3779B9) * jnp.uint32(j + 1)
                    cols[cn] = cols[cn].at[:n].set(
                        v.astype(cols[cn].dtype))
                db[t] = db[t]._replace(columns=cols)

        D = cfg.device_parts
        if D > 1:
            # owner-major stacked layout across chips: warehouses are the
            # ownership anchor (reference wh_to_part node partition,
            # `benchmarks/tpcc_helper.cpp`); read-only ITEM replicates
            # like the reference's per-node copy
            db["ITEM"] = db["ITEM"]._replace(mc_replicated=True)
            for name, anchor_rows in (
                    ("WAREHOUSE", 1), ("DISTRICT", self.n_dist),
                    ("CUSTOMER", self.n_dist * self.cust_per_dist),
                    ("STOCK", self.max_items), ("HISTORY", 1),
                    ("ORDER", 1), ("NEW-ORDER", 1), ("ORDER-LINE", 1)):
                db[name] = to_mc_layout(db[name], D, anchor_rows)
        if self.cfg.tpcc_order_index:
            # dynamic ordered ORDER index (reference index_btree over
            # inserted orders, `index_btree.cpp:252-420`): key =
            # district * 2^21 + o_id, merged per epoch as NewOrders
            # commit (`_exec_neworder`), probed by key or district range
            from deneva_tpu.storage.index import DynamicSortedIndex
            db["ORDER_IDX"] = DynamicSortedIndex.build(
                np.zeros(0, np.int32), np.zeros(0, np.int32),
                miss_slot=db["ORDER"].capacity,
                cap=self.cfg.insert_table_cap)
        return db

    # -- generation (tpcc_query.cpp:144-260) ----------------------------
    def generate(self, rng: jax.Array, n: int) -> TPCCQuery:
        cfg = self.cfg
        ks = jax.random.split(rng, 12)
        is_pay = jax.random.bernoulli(ks[0], cfg.perc_payment, (n,))
        w_id = jax.random.randint(ks[1], (n,), 0, self.n_wh)
        d_id = jax.random.randint(ks[2], (n,), 0, self.n_dist)

        # payment customer: remote (w', d') with prob 0.15 (tpcc_query.cpp:168-186)
        remote = jax.random.bernoulli(ks[3], 0.15, (n,)) & (self.n_wh > 1)
        rw = jax.random.randint(ks[4], (n,), 0, max(self.n_wh - 1, 1))
        rw = jnp.where(rw >= w_id, rw + 1, rw)          # != w_id
        c_w_id = jnp.where(remote, rw, w_id)
        c_d_id = jnp.where(remote,
                           jax.random.randint(ks[5], (n,), 0, self.n_dist),
                           d_id)

        # by-last-name 60% resolves to the middle same-lastname customer
        # (customers with lastname L are {L, L+names, L+2*names, ...}) —
        # through the CUSTOMER_LAST index probe (hash + postings walk) on
        # the generation hot path, or the closed form when disabled
        by_last = jax.random.bernoulli(ks[6], 0.6, (n,))
        names = self.lastnames
        lastname = _nurand(ks[7], 255, names, (n,))
        if cfg.tpcc_by_last_index:
            mid = self._lastname_middle(c_w_id, c_d_id, lastname)
        else:
            per_name = self.cust_per_dist // names
            mid = lastname + names * (per_name // 2)
        c_direct = _nurand(ks[8], 1023, self.cust_per_dist, (n,))
        c_id = jnp.where(by_last & is_pay, mid, c_direct)

        h_amount = jax.random.uniform(ks[9], (n,), jnp.float32, 1.0, 5000.0)

        # new-order item list (tpcc_query.cpp:221-256)
        I = self.ipt
        ol_cnt = jax.random.randint(ks[10], (n,), 5, I + 1)
        ki, kq, kr, kw = jax.random.split(ks[11], 4)
        items = _nurand(ki, 8191, self.max_items, (n, I))
        lane = jnp.arange(I)
        in_cnt = lane[None, :] < ol_cnt[:, None]
        # reference rejects duplicate item ids (tpcc_query.cpp:237); here
        # duplicates beyond the first are invalidated (collision odds
        # ~I^2/2/max_items per txn)
        first = jnp.argmax(items[:, :, None] == items[:, None, :], axis=1)
        item_valid = in_cnt & (first == lane[None, :])
        quantity = jax.random.randint(kq, (n, I), 1, 11)
        kr1, kr2 = jax.random.split(kr)
        rem_item = (jax.random.bernoulli(kr1, 0.01, (n, I))
                    & jax.random.bernoulli(kr2, cfg.mpr_neworder, (n, 1))
                    & (self.n_wh > 1))
        rsup = jax.random.randint(kw, (n, I), 0, max(self.n_wh - 1, 1))
        rsup = jnp.where(rsup >= w_id[:, None], rsup + 1, rsup)
        supply_w = jnp.where(rem_item, rsup, w_id[:, None])

        return TPCCQuery(
            txn_type=jnp.where(is_pay, TPCC_PAYMENT, TPCC_NEW_ORDER
                               ).astype(jnp.int32),
            w_id=w_id, d_id=d_id, c_id=c_id, c_w_id=c_w_id, c_d_id=c_d_id,
            h_amount=h_amount, ol_cnt=ol_cnt,
            items=items, item_valid=item_valid, supply_w=supply_w,
            quantity=quantity)

    # -- wire adapters (distributed runtime: CL_QRY / EPOCH_BLOB bodies) --
    # keys[n, 3I] = [items | supply_w | quantity]; types[n, 3I] marks item
    # validity in the first I lanes; scalars[n, 8] carries the per-txn
    # fields (h_amount as raw float32 bits).
    def to_wire(self, q: TPCCQuery):
        k = np.concatenate([np.asarray(q.items, np.int32),
                            np.asarray(q.supply_w, np.int32),
                            np.asarray(q.quantity, np.int32)], axis=1)
        t = np.zeros_like(k, np.int8)
        t[:, : self.ipt] = np.asarray(q.item_valid, np.int8)
        s = np.stack([
            np.asarray(q.txn_type, np.int32), np.asarray(q.w_id, np.int32),
            np.asarray(q.d_id, np.int32), np.asarray(q.c_id, np.int32),
            np.asarray(q.c_w_id, np.int32), np.asarray(q.c_d_id, np.int32),
            np.asarray(q.h_amount, np.float32).view(np.int32),
            np.asarray(q.ol_cnt, np.int32)], axis=1)
        return k, t, s

    def from_wire(self, keys: np.ndarray, types: np.ndarray,
                  scalars: np.ndarray) -> TPCCQuery:
        I = self.ipt
        keys = np.asarray(keys, np.int32)
        scalars = np.ascontiguousarray(scalars, np.int32)
        return TPCCQuery(
            txn_type=jnp.asarray(scalars[:, 0]),
            w_id=jnp.asarray(scalars[:, 1]), d_id=jnp.asarray(scalars[:, 2]),
            c_id=jnp.asarray(scalars[:, 3]),
            c_w_id=jnp.asarray(scalars[:, 4]),
            c_d_id=jnp.asarray(scalars[:, 5]),
            h_amount=jnp.asarray(
                np.ascontiguousarray(scalars[:, 6]).view(np.float32)),
            ol_cnt=jnp.asarray(scalars[:, 7]),
            items=jnp.asarray(keys[:, :I]),
            item_valid=jnp.asarray(types[:, :I] != 0),
            supply_w=jnp.asarray(keys[:, I:2 * I]),
            quantity=jnp.asarray(keys[:, 2 * I:3 * I]))

    def from_wire_dev(self, keys, types, scalars) -> TPCCQuery:
        """Traceable from_wire (cluster dispatch jit): the float32
        h_amount rides the wire as raw int32 bits, so the host's
        ``.view(np.float32)`` becomes a device bitcast."""
        import jax
        I = self.ipt
        return TPCCQuery(
            txn_type=scalars[:, 0], w_id=scalars[:, 1], d_id=scalars[:, 2],
            c_id=scalars[:, 3], c_w_id=scalars[:, 4], c_d_id=scalars[:, 5],
            h_amount=jax.lax.bitcast_convert_type(scalars[:, 6],
                                                  jnp.float32),
            ol_cnt=scalars[:, 7],
            items=keys[:, :I], item_valid=types[:, :I] != 0,
            supply_w=keys[:, I:2 * I], quantity=keys[:, 2 * I:3 * I])

    # -- RW-set planning (tpcc_txn.cpp state machines, declared up front)
    def plan(self, db, q: TPCCQuery) -> dict:
        cfg = self.cfg
        n = q.w_id.shape[0]
        A = cfg.max_accesses
        is_pay = q.txn_type == TPCC_PAYMENT

        tables = jnp.zeros((n, A), jnp.int32)
        keys = jnp.zeros((n, A), jnp.int32)
        is_read = jnp.zeros((n, A), bool)
        is_write = jnp.zeros((n, A), bool)
        valid = jnp.zeros((n, A), bool)
        order_free = jnp.zeros((n, A), bool)
        owner = jnp.zeros((n, A), jnp.int32)

        def put(a, tid, key, r, w, v, of=False, wh=None):
            nonlocal tables, keys, is_read, is_write, valid, order_free, owner
            tables = tables.at[:, a].set(tid)
            keys = keys.at[:, a].set(key)
            is_read = is_read.at[:, a].set(r)
            is_write = is_write.at[:, a].set(w)
            valid = valid.at[:, a].set(v)
            if of is not False:
                order_free = order_free.at[:, a].set(of)
            if wh is not None:
                # access owner = the row's warehouse's node (wh_to_part,
                # benchmarks/tpcc_helper.cpp) — the VOTE participant map
                owner = owner.at[:, a].set(wh % jnp.int32(self.n_parts))

        # The warehouse/district/customer accesses are ``order_free``
        # (escrow/commutative semantics): every write on them is a
        # scatter-add (W_YTD/D_YTD/C_BALANCE/C_YTD_PAYMENT/
        # C_PAYMENT_CNT += ...) or the D_NEXT_O_ID prefix sum
        # (rank-ordered within each chained sub-round, level-major
        # across sub-rounds — serializable as (level, rank) order),
        # and every read is of an immutable column (W_TAX, D_TAX,
        # C_DISCOUNT) — so the batched executor applies them
        # order-exactly with no conflict edges.  The reference's
        # row-level lock managers serialize payments on the warehouse
        # row (`row_lock.cpp`), which is exactly the scaling cliff this
        # column-aware declaration removes for the deterministic
        # backends (lock/ts baselines still see the full RW-sets).
        # Stock is a genuine RMW (quantity rule) and stays ordered.
        one = jnp.ones((n,), bool)
        # 0: warehouse — payment updates W_YTD (run_payment_0), neworder
        #    reads W_TAX (new_order_0)
        wh_write = is_pay & cfg.wh_update
        put(0, TID["WAREHOUSE"], q.w_id, one, wh_write, one, of=one,
            wh=q.w_id)
        # 1: district — payment D_YTD += (run_payment_2/3); neworder
        #    D_NEXT_O_ID++ (new_order_2)
        put(1, TID["DISTRICT"], self.dist_key(q.w_id, q.d_id), one, one, one,
            of=one, wh=q.w_id)
        # 2: customer — payment balance update at (c_w,c_d); neworder
        #    reads C_DISCOUNT at home (new_order_4)
        ck = jnp.where(is_pay, self.cust_key(q.c_w_id, q.c_d_id, q.c_id),
                       self.cust_key(q.w_id, q.d_id, q.c_id))
        put(2, TID["CUSTOMER"], ck, one, is_pay, one, of=one,
            wh=jnp.where(is_pay, q.c_w_id, q.w_id))
        # 3..3+I: stock rows (new_order_8); ITEM reads excluded (immutable)
        sk = self.stock_key(q.supply_w, q.items)
        iv = q.item_valid & ~is_pay[:, None]
        for j in range(self.ipt):
            put(3 + j, TID["STOCK"], sk[:, j], iv[:, j], iv[:, j], iv[:, j],
                wh=q.supply_w[:, j])
        return dict(table_ids=tables, keys=keys, is_read=is_read,
                    is_write=is_write, valid=valid, order_free=order_free,
                    owner=owner)

    # -- repair re-execution (engine/repair.py, Config.repair) ---------
    def re_execute(self, db, q: TPCCQuery, mask: jax.Array,
                   order: jax.Array, stats: dict):
        """Pure re-execution closure, keyed by txn slot: re-running a
        repaired txn is ``execute`` on the same query row against
        CURRENT state.  NewOrder re-reads D_NEXT_O_ID, stock quantities
        and the immutable price columns post-winners — the masked
        re-read (non-frontier gathers return values nothing overwrote)
        — recomputes its RMW writes and appends its ORDER/NEW-ORDER/
        ORDER-LINE rows in the sub-round wave, so per-district o_ids
        stay dense across waves (oracle: tests/test_repair.py audit).
        Escrow contract, documented and tested: repair of an escrow
        (order_free) delta is a NO-OP semantically — the delta
        recomputes identically from the query row (pure function,
        independent of any read) and scatter-adds once, exactly the
        write the main wave would have applied; escrow reads are
        declared-immutable columns and never enter the frontier."""
        return self.execute(db, q, mask, order, stats)

    # -- execution ------------------------------------------------------
    # NewOrder's stock update is a true RMW (the new quantity depends on
    # the read), so the single-pass forwarding executor does not apply
    blind_writes = False

    def execute(self, db, q: TPCCQuery, mask: jax.Array, order: jax.Array,
                stats: dict, fwd_rank=None, level_exec: bool = False):
        # NOTE: payments usually land at wavefront level 0 (all their
        # accesses are order_free), but hash-collision FALSE edges can
        # legitimately assign one a higher level, so every sub-round
        # must execute its payment mask — skipping "provably empty"
        # levels here would silently drop those payments' writes.
        db = dict(db)
        is_pay = q.txn_type == TPCC_PAYMENT
        pay = mask & is_pay
        neworder = mask & ~is_pay
        db = self._exec_payment(db, q, pay, stats)
        db = self._exec_neworder(db, q, neworder, order, stats, level_exec)
        return db

    def _exec_payment(self, db, q, m, stats):
        """run_payment_0..5 (`tpcc_txn.cpp:472-`): YTD/balance updates are
        commutative -> exact batched scatter_add.  Partitioned: each row
        component lands only on its owner (remote slots resolve to trash),
        so a cross-warehouse payment splits naturally across nodes."""
        amt = jnp.where(m, q.h_amount, 0.0)
        if self.cfg.wh_update:
            db["WAREHOUSE"] = db["WAREHOUSE"].scatter_add(
                self.wh_slot(q.w_id), {"W_YTD": amt}, mask=m)
        db["DISTRICT"] = db["DISTRICT"].scatter_add(
            self.dist_slot(q.w_id, q.d_id), {"D_YTD": amt}, mask=m)
        ck = self.cust_slot(q.c_w_id, q.c_d_id, q.c_id)
        db["CUSTOMER"] = db["CUSTOMER"].scatter_add(
            ck, {"C_BALANCE": -amt, "C_YTD_PAYMENT": amt,
                 "C_PAYMENT_CNT": m.astype(jnp.int32)}, mask=m)
        hist_row = {"H_C_ID": q.c_id, "H_C_D_ID": q.c_d_id,
                    "H_C_W_ID": q.c_w_id, "H_D_ID": q.d_id,
                    "H_W_ID": q.w_id, "H_AMOUNT": q.h_amount}
        if self.full_schema:
            n = q.w_id.shape[0]
            hist_row["H_DATE"] = jnp.full((n,), 2013, jnp.int32)
            hist_row["H_DATA"] = (q.c_id.astype(jnp.uint32)
                                  * jnp.uint32(0x9E3779B9))
        hist, _ = db["HISTORY"].append(hist_row,
                                       m & self.wh_owned(q.w_id),
                                       anchor=q.w_id)
        db["HISTORY"] = hist
        # W_YTD + D_YTD + 3 customer cols + HISTORY row per payment
        stats["write_cnt"] = stats["write_cnt"] + \
            (m.sum() * 6).astype(jnp.uint32)
        return db

    def _exec_neworder(self, db, q, m, order, stats,
                       level_exec: bool = False):
        """new_order_0..9 (`tpcc_txn.cpp:`): O_ID allocation is a
        per-district segmented prefix sum over the committed batch in
        serialization order — D_NEXT_O_ID++ under the row latch, batched."""
        n = q.w_id.shape[0]
        dist = db["DISTRICT"]
        dk = self.dist_key(q.w_id, q.d_id)          # global (segment id)
        dslot = self.dist_slot(q.w_id, q.d_id)      # local (storage)
        owned = self.wh_owned(q.w_id)

        # taxes / discount reads feed the checksum (keeps gathers alive)
        w_tax = db["WAREHOUSE"].gather(self.wh_slot(q.w_id),
                                       ("W_TAX",))["W_TAX"]
        d = dist.gather(dslot, ("D_TAX", "D_NEXT_O_ID"))
        c_disc = db["CUSTOMER"].gather(
            self.cust_slot(q.w_id, q.d_id, q.c_id),
            ("C_DISCOUNT",))["C_DISCOUNT"]
        # per-lane integer conversion BEFORE the sum: uint32 addition is
        # associative, so the multi-chip psum of per-chip partial sums is
        # bit-identical to the single-chip value (mc.py contract) — a
        # float sum would round differently per reduction order
        stats["read_checksum"] = stats["read_checksum"] + jnp.sum(
            jnp.where(m, (w_tax + d["D_TAX"] + c_disc) * 1000, 0)
            .astype(jnp.uint32), dtype=jnp.uint32)

        # o_id = snapshot next_o_id + rank among committed same-district
        # neworders ordered by serialization order
        big = jnp.int32(jnp.iinfo(jnp.int32).max)
        # bounded segment id (masked rows share one trailing segment) so
        # the composite sort key stays within int32
        seg = jnp.where(m, dk, jnp.int32(self.n_districts))
        order_rank = jnp.argsort(jnp.argsort(jnp.where(m, order, big)))
        sort_key = seg * (2 * n) + order_rank.astype(jnp.int32)
        perm = jnp.argsort(sort_key)
        sorted_seg = jnp.take(seg, perm)
        new_segment = jnp.concatenate(
            [jnp.ones((1,), bool), sorted_seg[1:] != sorted_seg[:-1]])
        pos = jnp.arange(n) - jax.lax.cummax(
            jnp.where(new_segment, jnp.arange(n), 0))
        rank = jnp.zeros((n,), jnp.int32).at[perm].set(pos.astype(jnp.int32))
        o_id = d["D_NEXT_O_ID"] + rank

        db["DISTRICT"] = dist.scatter_add(
            dslot, {"D_NEXT_O_ID": m.astype(jnp.int32)}, mask=m)

        # stock update (new_order_8): non-commutative quantity rule ->
        # gather/modify/last-writer scatter; S_REMOTE_CNT is scatter_add
        I = self.ipt
        iv = (q.item_valid & m[:, None]).reshape(-1)
        sk = self.stock_slot(q.supply_w, q.items).reshape(-1)
        qty = q.quantity.reshape(-1)
        stock = db["STOCK"]
        s_q = stock.gather(sk, ("S_QUANTITY",))["S_QUANTITY"]
        # strict: replenish at s_q - qty <= 10 (tpcc_txn.cpp new_order_8/9)
        new_q = jnp.where(s_q - qty > 10, s_q - qty, s_q - qty + 91)
        if level_exec:
            # chained sub-round: the level's committed set is stock-
            # conflict-free and item_valid dedups in-txn items, so every
            # valid lane IS the final writer — the scatter-max
            # tournament (4 full-table passes) is redundant
            win = iv
        else:
            worder = jnp.broadcast_to(order[:, None], (n, I)).reshape(-1)
            win = last_writer(jnp.where(iv, sk, stock.capacity), worder, iv,
                              stock.capacity)
        stock = stock.scatter(sk, {"S_QUANTITY": new_q}, mask=win)
        remote = (q.supply_w != q.w_id[:, None]).reshape(-1)
        adds = {"S_REMOTE_CNT": (iv & remote).astype(jnp.int32)}
        if self.full_schema:
            # full-spec stock bookkeeping (TPC-C §2.4.2.2: s_ytd +=
            # quantity, s_order_cnt++) — commutative scatter-adds
            adds["S_YTD"] = jnp.where(iv, qty, 0)
            adds["S_ORDER_CNT"] = iv.astype(jnp.int32)
        db["STOCK"] = stock.scatter_add(sk, adds, mask=iv)

        # inserts: ORDER, NEW-ORDER, ORDER-LINE (new_order_1 / _3 / _9) —
        # at the home warehouse's owner node only
        m_ins = m & owned
        all_local = jnp.all(~q.item_valid | (q.supply_w == q.w_id[:, None]),
                            axis=1)
        order_row = {"O_ID": o_id, "O_C_ID": q.c_id, "O_D_ID": q.d_id,
                     "O_W_ID": q.w_id, "O_ENTRY_D": jnp.full((n,), 2013),
                     "O_OL_CNT": q.ol_cnt,
                     "O_ALL_LOCAL": all_local.astype(jnp.int32)}
        if self.full_schema:
            order_row["O_CARRIER_ID"] = jnp.zeros((n,), jnp.int32)
        db["ORDER"], oslots = db["ORDER"].append(order_row, m_ins,
                                                 anchor=q.w_id)
        if "ORDER_IDX" in db:
            # between-epoch batched merge into the dynamic ordered index
            # (one fused sort per epoch instead of per-key tree descents)
            db["ORDER_IDX"] = db["ORDER_IDX"].insert(
                self.order_index_key(q.w_id, q.d_id, o_id), oslots, m_ins)
        db["NEW-ORDER"], _ = db["NEW-ORDER"].append(
            {"NO_O_ID": o_id, "NO_D_ID": q.d_id, "NO_W_ID": q.w_id}, m_ins,
            anchor=q.w_id)
        ol_m = (q.item_valid & m_ins[:, None]).reshape(-1)
        bcast = lambda x: jnp.broadcast_to(x[:, None], (n, I)).reshape(-1)  # noqa: E731
        ol_row = {"OL_O_ID": bcast(o_id), "OL_D_ID": bcast(q.d_id),
                  "OL_W_ID": bcast(q.w_id),
                  "OL_NUMBER": jnp.broadcast_to(jnp.arange(I)[None], (n, I)
                                                ).reshape(-1),
                  "OL_I_ID": q.items.reshape(-1),
                  "OL_QUANTITY": q.quantity.reshape(-1)}
        if self.full_schema:
            price = jnp.take(db["ITEM"].columns["I_PRICE"],
                             jnp.clip(q.items, 0, self.max_items - 1),
                             axis=0).reshape(-1)
            ol_row["OL_SUPPLY_W_ID"] = q.supply_w.reshape(-1)
            ol_row["OL_DELIVERY_D"] = jnp.zeros((n * I,), jnp.int32)
            ol_row["OL_AMOUNT"] = (q.quantity.reshape(-1) * price
                                   ).astype(jnp.float32)
            ol_row["OL_DIST_INFO"] = (q.items.reshape(-1).astype(jnp.uint32)
                                      * jnp.uint32(2654435761))
        db["ORDER-LINE"], _ = db["ORDER-LINE"].append(ol_row, ol_m,
                                                      anchor=bcast(q.w_id))

        stats["write_cnt"] = stats["write_cnt"] + \
            (iv.sum() + m.sum() * 2).astype(jnp.uint32)
        return db


def _rand01(ids: jax.Array, salt: int) -> jax.Array:
    """Deterministic per-row uniform [0,1) for loader columns (device
    arithmetic; uint32 product keeps the low 32 bits, which is all the
    64-bit golden-ratio multiply contributed)."""
    h = (ids.astype(jnp.uint32) * jnp.uint32(0x7F4A7C15)
         + jnp.uint32(salt))
    # split so each half converts to f32 exactly; one rounding at the add
    hi = (h >> 8).astype(jnp.float32) * jnp.float32(2.0 ** -24)
    lo = (h & 0xFF).astype(jnp.float32) * jnp.float32(2.0 ** -32)
    return hi + lo


def _mulmod(ids: jax.Array, mul: int, mod: int) -> jax.Array:
    """(ids * mul) % mod, bit-exact to the old int64 host loader without
    64-bit device math: (x*y) mod m == ((x mod m) * (y mod m)) mod m,
    and both reduced factors fit comfortably in 32 bits."""
    return ((ids.astype(jnp.uint32) % jnp.uint32(mod))
            * jnp.uint32(mul % mod) % jnp.uint32(mod)).astype(jnp.int32)
