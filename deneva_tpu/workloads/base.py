"""Workload interface (reference `system/wl.{h,cpp}`, `benchmarks/*_wl.*`).

The reference couples workloads to threads: `Workload::get_txn_man` hands a
per-thread txn-manager subclass whose ``run_txn`` advances a request-at-a-
time state machine (`benchmarks/ycsb_txn.cpp:91-209`).  Here a workload is
four pure functions over whole epochs:

* ``load()``      — build device tables (the parallel loaders,
                    `benchmarks/ycsb_wl.cpp:125-203`, become host numpy
                    passes + one device_put).
* ``generate()``  — a fresh batch of queries on device (the client query
                    generators, `benchmarks/*_query.cpp`).
* ``plan()``      — queries -> padded RW-set arrays (keys/tables/modes):
                    what the reference discovers incrementally through its
                    state machines is declared up front so the whole epoch
                    can be validated at once.  Workloads whose keys depend
                    on reads (PPS recon) resolve them here with gathers
                    against the current snapshot.
* ``execute()``   — apply committed txns: gather reads, compute, scatter
                    writes (with last-writer resolution), append inserts.
                    Called once per chained sub-round for deterministic
                    backends.

``DB`` is the carried table state; indexes with static contents live on
the workload object itself (device arrays inside them still ride along as
jit constants).
"""

from __future__ import annotations

from typing import Any, Protocol

import jax
import jax.numpy as jnp

DB = dict  # table name -> DeviceTable; a pytree


def partition_owned(key: jax.Array, n_parts: int, me: int) -> jax.Array:
    """bool mask: does this node own ``key`` under modulo striping
    (reference GET_NODE_ID, `system/global.h:294`)?"""
    if n_parts == 1:
        return jnp.ones(jnp.shape(key), bool)
    return key % n_parts == me


def slot_map_owned(key: jax.Array, owners: jax.Array, me: int) -> jax.Array:
    """bool mask: does this node own ``key`` under the elastic slot map
    (`runtime/membership.py`)?  ``owners`` is the device-resident
    int32[S] owner array carried in the db pytree (MEMBER_KEY), so a
    rebalance is a data update, never a re-jit.  With the boot map this
    is EXACTLY ``partition_owned`` (S is a multiple of the active count;
    the degeneracy contract)."""
    slot = key.astype(jnp.int32) % jnp.int32(owners.shape[0])
    return jnp.take(owners, slot, axis=0) == jnp.int32(me)


def partition_slot(key: jax.Array, n_parts: int, me: int,
                   n_local: int) -> jax.Array:
    """Local storage slot for a striped global key; keys this node does
    not own resolve to ``n_local`` — the table's TRASH slot.  NOTE the
    trash-row contract (see `storage/table.py`): masked scatters land IN
    the trash row, so gathers of scatter-written columns through it
    return garbage — consumers must stay masked by `partition_owned`."""
    loc = key // n_parts if n_parts > 1 else key
    return jnp.where(partition_owned(key, n_parts, me), loc,
                     jnp.int32(n_local))


class Workload(Protocol):
    def load(self) -> DB: ...

    def generate(self, rng: jax.Array, n: int) -> Any:
        """Return a query pytree with leading dim n."""
        ...

    def plan(self, db: DB, queries: Any) -> dict:
        """Return dict(table_ids, keys, is_read, is_write, valid) [n, A]."""
        ...

    def execute(self, db: DB, queries: Any, mask: jax.Array,
                order: jax.Array, stats: dict, fwd_rank=None,
                level_exec: bool = False) -> DB:
        """Apply txns selected by ``mask`` to ``db``; update device stats
        dict in place (read checksums keep gathers alive under XLA).

        ``fwd_rank`` — a `deneva_tpu.ops.ForwardPlan` when the single-pass
        forwarding executor applies (``mask`` must then be None: the plan
        embodies the commit set).  ``level_exec`` — the caller guarantees
        this committed set is write-conflict-free (a chained sub-round),
        so duplicate-writer resolution may be skipped."""
        ...
