"""Workload interface (reference `system/wl.{h,cpp}`, `benchmarks/*_wl.*`).

The reference couples workloads to threads: `Workload::get_txn_man` hands a
per-thread txn-manager subclass whose ``run_txn`` advances a request-at-a-
time state machine (`benchmarks/ycsb_txn.cpp:91-209`).  Here a workload is
four pure functions over whole epochs:

* ``load()``      — build device tables (the parallel loaders,
                    `benchmarks/ycsb_wl.cpp:125-203`, become host numpy
                    passes + one device_put).
* ``generate()``  — a fresh batch of queries on device (the client query
                    generators, `benchmarks/*_query.cpp`).
* ``plan()``      — queries -> padded RW-set arrays (keys/tables/modes):
                    what the reference discovers incrementally through its
                    state machines is declared up front so the whole epoch
                    can be validated at once.  Workloads whose keys depend
                    on reads (PPS recon) resolve them here with gathers
                    against the current snapshot.
* ``execute()``   — apply committed txns: gather reads, compute, scatter
                    writes (with last-writer resolution), append inserts.
                    Called once per chained sub-round for deterministic
                    backends.

``DB`` is the carried table state; indexes with static contents live on
the workload object itself (device arrays inside them still ride along as
jit constants).
"""

from __future__ import annotations

from typing import Any, Protocol

import jax

DB = dict  # table name -> DeviceTable; a pytree


class Workload(Protocol):
    def load(self) -> DB: ...

    def generate(self, rng: jax.Array, n: int) -> Any:
        """Return a query pytree with leading dim n."""
        ...

    def plan(self, db: DB, queries: Any) -> dict:
        """Return dict(table_ids, keys, is_read, is_write, valid) [n, A]."""
        ...

    def execute(self, db: DB, queries: Any, mask: jax.Array,
                order: jax.Array, stats: dict) -> DB:
        """Apply txns selected by ``mask`` to ``db``; update device stats
        dict in place (read checksums keep gathers alive under XLA)."""
        ...
