"""Generic partition-parallel execution over a device mesh.

The reference partitions EVERY benchmark across server nodes — warehouses
map to nodes for TPC-C (`benchmarks/tpcc_helper.cpp` wh_to_part, remote
hops `tpcc_txn.cpp:332-368`), keys stripe for YCSB (`ycsb_wl.cpp:70-74`),
PPS anchors stripe (`pps_wl.cpp`) — and a transaction's per-node work
executes on the owner.  This module is that deployment model across
CHIPS, for any workload and any CC backend:

* The epoch batch is **replicated** (Calvin-sequencer shape: every chip
  sees the full deterministic sequence, `system/sequencer.cpp:283-326`)
  and validation runs on the replicated batch (conflict matmuls contract
  over the bucket dim, which `parallel.mesh.shard_buckets` shards).
* Tables live in the **owner-major stacked layout**
  (`storage.table.to_mc_layout`): block ``d`` of every column holds the
  rows whose ownership anchor ≡ d (mod D), so sharding dim 0 over the
  mesh hands each chip exactly its partition; read-only tables (ITEM /
  USES / SUPPLIES) are replicated like the reference's per-node copies.
* Execution runs the workload's **unmodified** ``execute`` body under
  `shard_map`: each chip passes global slots through a `McTableView`
  that translates them to block-local rows — non-owned lanes read 0 and
  scatter to the block trash — so per-chip work is exactly the owned
  partition and the psum of per-chip read checksums reconstructs the
  single-chip value bit-exactly.

Executor contract (held by ycsb/tpcc/pps, asserted by the bit-identity
tests in `tests/test_parallel.py`):

* every gather-derived statistic folds into ``read_checksum`` with
  per-lane integer conversion (integer sums are associative, so the
  cross-chip psum is exact);
* all other statistics derive from replicated inputs (masks/queries)
  only, so every chip computes the same value and no psum is needed;
* ring appends pass the row's ownership ``anchor`` so inserts land on
  the owner's block.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deneva_tpu.parallel.mesh import AXIS, current_mesh
from deneva_tpu.storage.table import DeviceTable, mc_block_geometry


class McTableView:
    """DeviceTable facade inside a `shard_map` body: global slots in,
    block-local storage ops out.  ``capacity`` stays the GLOBAL trash id
    so caller arithmetic (trash steering, `last_writer` sentinels) is
    unchanged; `_loc` folds global trash, out-of-range and non-owned
    slots into the block-local trash."""

    def __init__(self, tab: DeviceTable, me: jax.Array,
                 local: DeviceTable | None = None):
        self._meta = tab            # shard leaves + global static metadata
        self.d_parts = tab.mc_parts
        self.anchor_rows = tab.anchor_rows
        self.me = me
        if local is None:
            _, lb = mc_block_geometry(tab.capacity, tab.anchor_rows,
                                      tab.mc_parts)
            local_cap = tab.capacity // tab.mc_parts if tab.ring else lb - 1
            local = DeviceTable(
                columns=tab.columns, row_cnt=tab.row_cnt.reshape(()),
                name=tab.name, capacity=local_cap, full_row=tab.full_row,
                ring=tab.ring)
        self.local = local

    @property
    def capacity(self) -> int:
        return self._meta.capacity

    def _with(self, local: DeviceTable) -> "McTableView":
        return McTableView(self._meta, self.me, local=local)

    def _loc(self, slots: jax.Array) -> tuple[jax.Array, jax.Array]:
        slots = slots.astype(jnp.int32)
        a = slots // self.anchor_rows
        owned = ((slots >= 0) & (slots < self.capacity)
                 & (a % self.d_parts == self.me))
        loc = (a // self.d_parts) * self.anchor_rows + slots % self.anchor_rows
        return jnp.where(owned, loc, jnp.int32(self.local.capacity)), owned

    # -- DeviceTable interface -----------------------------------------
    def gather(self, slots: jax.Array, cols: tuple[str, ...] | None = None
               ) -> dict[str, jax.Array]:
        loc, owned = self._loc(slots)
        out = self.local.gather(loc, cols)
        # non-owned lanes read 0 (never block-trash garbage): each row is
        # owned by exactly one chip, so per-chip contributions sum to the
        # single-chip gather and checksums psum exactly
        def zero(v):
            m = owned.reshape(owned.shape + (1,) * (v.ndim - owned.ndim))
            return jnp.where(m, v, 0)
        return {n: zero(v) for n, v in out.items()}

    def scatter(self, slots, updates, mask=None) -> "McTableView":
        loc, _ = self._loc(slots)
        return self._with(self.local.scatter(loc, updates, mask=mask))

    def scatter_add(self, slots, updates, mask=None) -> "McTableView":
        loc, _ = self._loc(slots)
        return self._with(self.local.scatter_add(loc, updates, mask=mask))

    def append(self, rows, mask, anchor=None):
        assert anchor is not None, \
            "multi-chip append needs the row ownership anchor"
        m = mask & (anchor.astype(jnp.int32) % self.d_parts == self.me)
        local, slots = self.local.append(rows, m)
        return self._with(local), slots

    def assemble(self) -> DeviceTable:
        """Back to a shard-leaf DeviceTable for the shard_map output."""
        return self._meta._replace(columns=self.local.columns,
                                   row_cnt=self.local.row_cnt.reshape((1,)))


def table_specs(db: dict) -> dict:
    """shard_map spec tree for a DB dict: stacked tables shard dim 0 over
    the mesh axis, replicated tables ride whole."""
    return {name: jax.tree.map(
        lambda _, s=(P() if tab.mc_parts == 1 else P(AXIS)): s, tab)
        for name, tab in db.items()}


def mc_execute(cfg, wl, db: dict, queries, commit: jax.Array,
               order: jax.Array, level: jax.Array, stats: dict,
               chained: bool, level_exec: bool = True,
               n_levels: int | None = None) -> dict:
    """One epoch's execution, partition-parallel across the mesh.

    ``commit``/``order``/``level`` come from the replicated verdict; for
    chained backends each wavefront level executes as a sub-round against
    the chip-local table state, exactly like the single-chip engine loop
    (`engine/step.py`).  ``level_exec`` follows `engine/step._run_levels`:
    True claims each sub-round's committed set is write-conflict-free
    (CALVIN/TPU_BATCH); False (DGCC) keeps the per-wave ``last_writer``
    order tournament, so same-wave duplicate writers resolve identically
    on every shard (the verdict is replicated, the tournament is a pure
    function of it — dp>1 stays bit-identical to dp=1).  ``n_levels``
    overrides the static sub-round unroll budget (DGCC waves are bounded
    by ``dgcc_levels``, not ``exec_subrounds`` — a committed level past
    the unroll would silently never execute)."""
    mesh = current_mesh()
    assert mesh is not None and mesh.size == cfg.device_parts, \
        f"mc_execute needs a use_mesh({cfg.device_parts}) context"
    db_spec = table_specs(db)

    def body(db, queries, commit, order, level):
        me = jax.lax.axis_index(AXIS)
        dbv = {n: (McTableView(t, me) if t.mc_parts > 1 else t)
               for n, t in db.items()}
        st = {"read_checksum": jnp.zeros((), jnp.uint32),
              "write_cnt": jnp.zeros((), jnp.uint32)}
        if chained:
            for lvl in range(n_levels if n_levels is not None
                             else cfg.exec_subrounds):
                m = commit & (level == lvl)
                dbv = wl.execute(dbv, queries, m, order, st,
                                 level_exec=level_exec)
        else:
            dbv = wl.execute(dbv, queries, commit, order, st)
        out = {n: (v.assemble() if isinstance(v, McTableView) else v)
               for n, v in dbv.items()}
        return out, jax.lax.psum(st["read_checksum"], AXIS), st["write_cnt"]

    from deneva_tpu.parallel.mesh import shard_map_fn
    out_db, cks, wcnt = shard_map_fn()(
        body, mesh=mesh,
        in_specs=(db_spec, P(), P(), P(), P()),
        out_specs=(db_spec, P(), P()))(db, queries, commit, order, level)
    stats["read_checksum"] = stats["read_checksum"] + cks
    stats["write_cnt"] = stats["write_cnt"] + wcnt
    return out_db
