"""Benchmarks (reference `benchmarks/`, SURVEY §2.5): YCSB, TPCC, PPS.

A workload owns its schema/loader (L8), its device-side query generator
(the reference's client-side `*QueryGenerator`), the *plan* that turns a
query batch into padded RW-sets for CC validation, and the *execute* step
that applies committed transactions to the device tables.
"""

from deneva_tpu.workloads.base import Workload, DB  # noqa: F401
from deneva_tpu.workloads.ycsb import YCSBWorkload  # noqa: F401


def get_workload(cfg):
    from deneva_tpu.config import WorkloadKind
    if cfg.workload == WorkloadKind.YCSB:
        return YCSBWorkload(cfg)
    if cfg.workload == WorkloadKind.TPCC:
        from deneva_tpu.workloads.tpcc import TPCCWorkload
        return TPCCWorkload(cfg)
    if cfg.workload == WorkloadKind.PPS:
        from deneva_tpu.workloads.pps import PPSWorkload
        return PPSWorkload(cfg)
    raise ValueError(f"no workload for {cfg.workload}")
