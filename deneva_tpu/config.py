"""Runtime configuration system.

The reference spreads configuration over three tiers: compile-time
``#define``s in ``config.h`` (CC_ALG, WORKLOAD, MODE, every protocol
constant), a hand-rolled CLI parser for a runtime subset
(``system/parser.cpp:77``), and an experiment layer that rewrites
``config.h`` and recompiles per data point (``scripts/run_experiments.py:83-96``).

Here everything is a runtime field on one frozen dataclass.  Algorithm
selection is runtime dispatch behind the `deneva_tpu.cc` interface — the
``#if CC_ALG`` forest in the reference's ``storage/row.cpp:197-310`` is the
thing this design explicitly does not reproduce.  JAX re-jits per config
anyway (config fields are Python-level constants under trace), so we lose
nothing to the reference's recompile-per-config scheme.

Field names keep the reference's vocabulary (``g_node_cnt``,
``g_inflight_max``, ``zipf_theta`` … see ``system/global.h:130-234``) minus
the ``g_`` prefix so experiment configs read the same as the paper's.
"""

from __future__ import annotations

import dataclasses
import enum
from dataclasses import dataclass
from typing import Any


class CCAlg(str, enum.Enum):
    """Concurrency-control algorithm (reference `config.h:101` + README:24-35).

    All are implemented as batched epoch-validation backends; see
    `deneva_tpu.cc` for per-algorithm semantics.
    """

    NO_WAIT = "NO_WAIT"        # 2PL, abort on conflict
    WAIT_DIE = "WAIT_DIE"      # 2PL, older waits / younger dies
    TIMESTAMP = "TIMESTAMP"    # basic T/O
    MVCC = "MVCC"              # multi-version T/O
    OCC = "OCC"                # Kung-Robinson backward validation
    MAAT = "MAAT"              # dynamic timestamp ranges
    CALVIN = "CALVIN"          # deterministic (sequencer + ordered locks)
    TPU_BATCH = "TPU_BATCH"    # headline backend: MXU conflict matrix + greedy serialization
    DGCC = "DGCC"              # dependency-graph wavefront (exact-key lane graph -> chained waves)
    NOCC = "NOCC"              # oracle mode: no concurrency control (reference MODE=NOCC_MODE)


class WorkloadKind(str, enum.Enum):
    """Benchmark selection (reference `config.h` WORKLOAD)."""

    YCSB = "YCSB"
    TPCC = "TPCC"
    PPS = "PPS"
    TEST = "TEST"


class Mode(str, enum.Enum):
    """Degraded oracle modes used as layer-isolation tests (reference
    `config.h:276-281`, SURVEY §4.2)."""

    NORMAL = "NORMAL"
    SIMPLE = "SIMPLE"      # ack immediately, no execution (client+transport only)
    NOCC = "NOCC"          # execute without CC
    QRY_ONLY = "QRY_ONLY"  # execute queries but skip commit protocol


@dataclass(frozen=True)
class Config:
    """One flat, frozen config record.

    Defaults follow the reference's defaults (`config.h`, with the paper's
    experiment defaults from `scripts/experiments.py:346-420`) except where
    a TPU-shaped knob replaces a CPU-shaped one (noted inline).
    """

    # ---- topology (reference config.h:16-23) ----
    node_id: int = 0
    node_cnt: int = 1              # server nodes
    client_node_cnt: int = 1
    part_cnt: int = 1              # keyspace partitions (== node_cnt in reference)
    core_cnt: int = 8
    thread_cnt: int = 1            # host codec worker threads (reference
    #                                THREAD_CNT, main.cpp:196-310): >1 runs
    #                                the cluster loop's per-epoch blob
    #                                encode + feed assembly through a
    #                                thread pool (numpy codecs release the
    #                                GIL, so a multi-core host overlaps
    #                                admit work with itself; this 1-core
    #                                box measures it ~neutral)
    rem_thread_cnt: int = 1        # native receiver IO threads (reference
    #                                REM_THREAD_CNT): peers shard src % n
    send_thread_cnt: int = 1       # native sender IO threads (reference
    #                                SEND_THREAD_CNT): dests shard dest % n
    #                                (per-dest FIFO preserved)
    client_thread_cnt: int = 4

    # ---- replication (reference config.h:24-27) ----
    replica_cnt: int = 0
    repl_type: str = "AP"          # active-passive

    # ---- multi-chip (single process, jax.sharding.Mesh) ----
    device_parts: int = 1          # keyspace partitions ACROSS CHIPS: tables
    #                                shard owner-major over the mesh and the
    #                                forwarding executor runs partition-
    #                                parallel under shard_map (parallel/)

    # ---- workload ----
    workload: WorkloadKind = WorkloadKind.YCSB
    cc_alg: CCAlg = CCAlg.TPU_BATCH
    mode: Mode = Mode.NORMAL
    isolation_level: str = "SERIALIZABLE"  # SERIALIZABLE | READ_COMMITTED | READ_UNCOMMITTED | NOLOCK

    # ---- YCSB (reference config.h:150-176) ----
    synth_table_size: int = 2097152 * 8   # 16M rows/node, paper default
    req_per_query: int = 10
    zipf_theta: float = 0.6
    read_perc: float = 0.5
    write_perc: float = 0.5        # per-tuple write prob (TUP_WRITE_PERC)
    txn_write_perc: float = 1.0    # P(txn may write at all); with prob 1-p the
    #                                whole txn is read-only (TXN_WRITE_PERC,
    #                                ycsb_query.cpp:313,331: r_twr drawn once per txn)
    skew_method: str = "ZIPF"      # ZIPF | HOT (config.h:162-167)
    data_perc: int = 100           # HOT: hot-set size in KEYS (g_data_perc is cast
    #                                to an absolute key count, ycsb_query.cpp:218)
    access_perc: float = 0.03      # HOT: fraction of accesses hitting the hot set
    key_order: bool = False        # sort request keys ascending (KEY_ORDER config.h:106)
    tup_size: int = 100            # bytes per field payload (SIM_FULL_ROW analogue)
    field_per_tuple: int = 10
    sim_full_row: bool = False     # SIM_FULL_ROW (storage/row.cpp:30): tables
    #                                materialize real payload bytes
    #                                (uint8[tup_size] per field); reads
    #                                checksum real bytes, writes store real
    #                                bytes.  Off = fingerprint mode (the
    #                                reference's SIM_FULL_ROW=false default).
    first_part_local: bool = True
    part_per_txn: int = 2
    mpr: float = 0.01              # multi-partition txn rate
    strict_ppt: bool = False
    ycsb_abort_mode: bool = False  # sentinel forced-abort consistency check (config.h:103)

    # ---- TPCC (reference config.h:178-209) ----
    num_wh: int = 4
    perc_payment: float = 0.5
    wh_update: bool = True
    mpr_neworder: float = 0.01     # remote-warehouse item probability
    tpcc_full_schema: bool = False
    cust_per_dist: int = 3000      # CUST_PER_DIST_NORM (config.h:188)
    tpcc_by_last_index: bool = True  # resolve payment-by-lastname through
    #                                  the CUSTOMER_LAST nonunique index
    #                                  (hash probe + postings walk, like
    #                                  index_hash.cpp:68-100); False =
    #                                  closed-form arithmetic bypass
    max_items: int = 100000        # MAX_ITEMS_NORM (config.h:187)
    max_items_per_txn: int = 15    # MAX_ITEMS_PER_TXN (config.h:189)
    insert_table_cap: int = 1 << 17  # ring capacity of HISTORY/ORDER/... tables
    #                                  (ORDER-LINE gets cap*max_items_per_txn)

    # ---- PPS (reference config.h:226-242) ----
    pps_table_size: int = 100000
    pps_parts_cnt: int = 10000       # MAX_PPS_PART_KEY
    pps_products_cnt: int = 1000     # MAX_PPS_PRODUCT_KEY
    pps_suppliers_cnt: int = 1000    # MAX_PPS_SUPPLIER_KEY
    pps_parts_per: int = 10          # MAX_PPS_PART_PER_PRODUCT
    perc_getparts: float = 0.0
    perc_getproducts: float = 0.0
    perc_getsuppliers: float = 0.0
    perc_getpartbyproduct: float = 0.34
    perc_getpartbysupplier: float = 0.0
    perc_orderproduct: float = 0.33
    perc_updateproductpart: float = 0.33
    perc_updatepart: float = 0.0

    # ---- txn / client driving (reference config.h:21-22, 84-90) ----
    max_txn_in_flight: int = 10000
    load_rate: int = 0             # 0 = LOAD_MAX (saturate), else fixed txn/s
    client_batch_size: int = 1024  # txns per CL_QRY_BATCH message: the
    #                                Python client's per-message overhead
    #                                (~3 ms: tag ring + codec + send) is
    #                                the cluster-mode supply ceiling, so
    #                                it must amortize over large batches
    #                                (reference clients batch too,
    #                                message.h:243-340)
    abort_penalty_us: float = 25.0      # base restart backoff (config.h:113)
    abort_penalty_max_us: float = 5000.0
    backoff: bool = True

    # ---- simulation lifecycle (reference config.h:346-350) ----
    warmup_secs: float = 2.0       # reference: 60s; scaled for CI-speed runs
    done_secs: float = 5.0         # measured window; reference: 60s
    prog_timer_secs: float = 10.0
    chunk_target_secs: float = 1.0  # driver aims each device scan at this
    #                                 much work: the per-chunk pacing round
    #                                 trip (tens of ms on a tunneled chip)
    #                                 amortizes over it, but one call must
    #                                 stay far below the tunnel's ~50 s
    #                                 execution kill (keep <= ~3)

    # ---- logging (reference config.h:145-149) ----
    logging: bool = False
    log_buf_timeout_us: float = 10.0
    log_dir: str = "/tmp/deneva_logs"

    # ---- epoch engine (TPU-shaped; replaces thread/latch knobs) ----
    epoch_batch: int = 2048        # txns validated per epoch (Calvin SEQ_BATCH analogue)
    conflict_buckets: int = 8192   # hashed key-bucket width of incidence matrices
    conflict_exact: bool = True    # dual-hash AND to squeeze out false conflicts
    watermark_buckets: int = 1 << 20  # hashed width of the T/O family's
    #                                   cross-epoch rts/wts tables.  These
    #                                   are O(K) memory (not O(B*K) like
    #                                   incidence matrices), so they can be
    #                                   wide enough that false bucket
    #                                   sharing stops inflating abort
    #                                   rates (the reference tracks
    #                                   per-ROW ts state; 1M buckets at
    #                                   4 B each is 4 MB)
    max_accesses: int = 16         # padded RW-set width per txn (covers req_per_query)
    defer_rounds_max: int = 8      # WAIT_DIE-style defer budget before forced abort
    sweep_rounds: int = 24         # serialization-sweep fixpoint iterations (chain depth cap)
    maat_peel_rounds: int = 16     # MAAT cycle-peel iterations per epoch (leftovers defer)
    mc_plan_capacity: float = 2.0  # sharded multi-chip plan: per-chip buffer
    #                                = factor * N/D lanes (0 = replicate
    #                                  the full plan per chip, round-3 mode)
    tpcc_order_index: bool = False  # maintain the dynamic ordered ORDER
    #                                 index (index_btree insert analogue;
    #                                 one merge sort per epoch)
    exec_subrounds: int = 4        # chained-execution levels per epoch (CALVIN/TPU_BATCH)
    dgcc_levels: int = 32          # DGCC wave budget: level-relaxation
    #                                round cap AND max wavefront depth per
    #                                epoch (cc/dgcc.py).  Deeper dependency
    #                                closures DEFER to the next epoch's
    #                                retry queue (repair's cyclic-fallback
    #                                analogue) — never abort.  Far above
    #                                exec_subrounds because DGCC's exact-
    #                                key lane graph has no hashed-bucket
    #                                false conflicts inflating chain depth.
    mvcc_his_len: int = 4          # in-state version history depth (HIS_RECYCLE_LEN analogue)
    escrow_order_free: bool = True  # honor workload order_free (escrow/
    #                                 commutative) declarations in the
    #                                 backends' conflict graphs; False =
    #                                 ablation: every backend sees the
    #                                 full RW-sets (separates the
    #                                 algorithm win from the annotation win
    #                                 in TPC-C/PPS numbers)
    escrow_sweep: bool = True      # extend the escrow exemption to the six
    #                                SWEEP backends (NO_WAIT/WAIT_DIE/OCC/
    #                                TIMESTAMP/MVCC/MAAT): conflict edges
    #                                come from the ordered incidence views
    #                                (escrow add-add pairs carry no edge;
    #                                accumulator READS still order against
    #                                every add) and the T/O watermarks
    #                                apply the escrow check/record rules
    #                                (cc/timestamp.py).  False = the
    #                                reference-faithful baseline: row-level
    #                                conflicts, ~1 hot-row winner per epoch
    #                                (the TPC-C 4-warehouse Payment floor).
    #                                Chained backends ignore this flag
    #                                (their exemption is escrow_order_free
    #                                alone, as before).
    repair: bool = False           # transaction repair (engine/repair.py):
    #                                salvage sweep-backend ABORTS by
    #                                re-executing only the invalidated
    #                                slice as chained sub-rounds within
    #                                the SAME epoch — losers whose
    #                                re-validation passes against the
    #                                post-winner state commit instead of
    #                                re-entering the retry queue (PAPERS:
    #                                *Transaction Repair: Full
    #                                Serializability Without Locks*;
    #                                DGCC's dependency-graph batching).
    #                                Default off: losers take the retry
    #                                queue exactly as before — every
    #                                code path, log byte and verdict
    #                                plane is bit-identical to pre-repair.
    repair_rounds: int = 2         # repair sub-rounds per epoch before
    #                                leftovers (cyclic re-invalidation:
    #                                each pass's winners re-invalidate
    #                                the rest) fall back to the retry
    #                                queue; 0 = arm the machinery but
    #                                salvage nothing (ablation floor)
    seq_batch_timer_us: float = 5000.0  # Calvin epoch cadence (config.h:348)

    # ---- device mesh ----
    mesh_shape: tuple = ()         # () = single device; e.g. (8,) shards keyspace
    mesh_axis: str = "key"

    # ---- storage ----
    index_struct: str = "IDX_HASH"  # IDX_HASH | IDX_BTREE (global.h:320-324)
    bucket_cnt_per_slot: float = 2.0  # hash index load factor headroom

    # ---- transport (reference config.h:94, 334-335) ----
    tport_type: str = "ipc"        # ipc | tcp
    tport_port: int = 17000
    msg_size_max: int = 4096
    msg_time_limit_us: float = 0.0
    net_delay_us: float = 0.0      # NETWORK_DELAY_TEST (msg_queue.cpp:104-125)

    # ---- deployment (harness): in-process engine vs multi-process cluster
    deploy: str = "inproc"         # inproc | cluster
    pipeline_epochs: int = 8       # cluster merged mode: epochs fused into ONE
    #                                device dispatch (lax.scan group).  The
    #                                host<->device round trips (merged-batch
    #                                feed up, commit masks down) amortize over
    #                                the whole group instead of being paid per
    #                                epoch — the round-2 measured 430 ms/epoch
    #                                on the tunneled chip was >99% this
    #                                per-epoch transfer overhead.  1 = the
    #                                round-1 synchronous loop.
    pipeline_groups: int = 2       # cluster merged mode: dispatch groups kept
    #                                in flight before blocking on the oldest
    #                                group's commit masks (double buffering:
    #                                epoch e+1's admission/exchange/codec work
    #                                overlaps epoch e's device step — the
    #                                reference's sequencer-vs-worker thread
    #                                decoupling, system/calvin_thread.cpp:102).
    #                                1 = retire synchronously.
    host_overlap: str = "auto"     # cluster merged mode: run the host half
    #                                of each epoch OFF the dispatch thread
    #                                (the host-path pipeline).  A single
    #                                ordered wire worker carries blob
    #                                encode+broadcast, log-record packing +
    #                                logger append + replica LOG_MSG sends
    #                                (per-link FIFO preserved — one worker,
    #                                program order); a retire worker
    #                                prefetches each group's verdict planes
    #                                (d2h wait + unpackbits + CL_RSP
    #                                payloads) so retirement K groups later
    #                                finds them ready; the device feed is
    #                                assembled zero-copy (contributions and
    #                                peer blobs land directly in reusable
    #                                flat feed buffers, sends go out as
    #                                scatter-gather parts via dt_sendv).
    #                                "off" = the pre-pipeline serial loop:
    #                                same admission policy, same stamping,
    #                                same record bytes — bit-identical
    #                                verdicts and logs (tested).  "auto"
    #                                (default) = on unless this box's
    #                                process count (servers + clients +
    #                                replicas, the single-box launcher
    #                                rig) oversubscribes its cores by
    #                                more than one: overlap threads can
    #                                only overlap DEVICE time if a spare
    #                                cycle exists — measured on the
    #                                2-core box, on wins at N<=2 procs+1
    #                                and loses 29% at 5 procs (BASELINE
    #                                round-7).  Multi-host fleets set
    #                                on/off explicitly.  Vote mode
    #                                ignores it (its epoch is a
    #                                synchronous host round trip by
    #                                construction).
    dist_protocol: str = "auto"    # cluster coordination for non-deterministic
    #                                backends (reference 2PC,
    #                                system/txn.cpp:498-606):
    #                                auto   — deterministic backends use the
    #                                         merged-batch sequencer exchange;
    #                                         lock/ts/occ backends use VOTE
    #                                vote   — batched 2PC: each server
    #                                         validates its partition's
    #                                         accesses locally and the epoch
    #                                         vote exchange is the prepare
    #                                         round (commit = every owner
    #                                         voted yes)
    #                                merged — every server validates the full
    #                                         merged batch with global state
    #                                         (round-1 behavior)

    # ---- fault injection + failover (chaos harness; no reference
    # analogue — SURVEY §5.3: a dead peer hangs the reference forever).
    # All defaults OFF: with every knob at its default the runtime takes
    # exactly the pre-chaos code paths. ----
    fault_drop_prob: float = 0.0   # P(drop) per fault-eligible message
    #                                (client<->server open-loop traffic;
    #                                 see native.FAULT_RTYPE_MASK)
    fault_dup_prob: float = 0.0    # P(duplicate) per eligible message
    fault_delay_jitter_us: float = 0.0  # uniform [0, jitter) extra delay
    fault_kill: str = ""           # "node:epoch" — server `node` calls
    #                                _exit at the first group boundary
    #                                >= `epoch` (crash, no teardown);
    #                                requires logging (recovery replays).
    #                                Killing node 0 (the coordinator) is
    #                                best-effort: peers echo the
    #                                measure/stop epochs on REJOIN, but
    #                                a restart racing the warmup edge
    #                                can still re-announce a later
    #                                window — prefer killing node >= 1
    fault_seed: int = 0            # fault-stream seed; mixed with the
    #                                node id so each node draws its own
    #                                deterministic splitmix64 stream
    fault_resend_us: float = 250_000.0  # client resend timeout for
    #                                unacked batches (fault mode only)
    fault_recovery_timeout_s: float = 120.0  # how long peers wait for a
    #                                dead server to rejoin before raising
    #                                (fault mode only; otherwise the
    #                                pre-chaos dead-peer raise fires)
    recover: bool = False          # start this server in recovery mode:
    #                                replay the command log, rejoin the
    #                                mesh at the next group boundary
    failover_timeout_s: float = 60.0  # the failover wall family: the
    #                                REJOIN replica-handshake wait, the
    #                                MIGRATE_ROWS donor-stream wait and
    #                                the reassignment-replay flush wait
    #                                all read this single knob (they were
    #                                hidden 30/60 s constants — the PR 4
    #                                clamped-window lesson: hidden walls
    #                                flake slow CI boxes; raise it there)
    fault_partition: str = ""      # network partition injection (native
    #                                dt_set_partition blackholes):
    #                                comma-separated "A-B:START"
    #                                (bidirectional) or "A>B:START"
    #                                (one-way: A's frames to B are
    #                                dropped) entries; A/B are SERVER
    #                                ids, START is seconds after the run
    #                                barrier.  Each endpoint applies its
    #                                own TX-side drops at its group
    #                                boundaries, so the first silenced
    #                                epoch is identical on every
    #                                receiver.  "" = off.
    fault_partition_flap_s: float = 0.0  # flapping link: every armed
    #                                partition toggles on/off with this
    #                                period from its START (on for
    #                                flap_s, off for flap_s, ...).  0 =
    #                                partitions are permanent.
    fault_peer_stall: str = ""     # gray-slow peer (native
    #                                dt_set_peer_stall_us): "NODE:MS:
    #                                START_S" — server NODE delays ALL
    #                                its outbound frames by MS
    #                                milliseconds from START_S seconds
    #                                after the barrier.  Models a
    #                                stalled-but-alive process: sockets
    #                                never close, peer_alive stays true,
    #                                only the suspicion score sees it.

    # ---- elastic membership (slot-map routing + live rebalance;
    # runtime/membership.py).  All defaults OFF: with elastic=False every
    # path takes the static modulo-striping code exactly. ----
    elastic: bool = False          # slot-map ownership: S hash slots ->
    #                                owner node replace implicit
    #                                key % node_cnt everywhere.  The boot
    #                                map degenerates to EXACT modulo
    #                                striping (S is rounded to a multiple
    #                                of the boot active count), so with no
    #                                rebalance triggered all routing,
    #                                logs, replica streams and acks are
    #                                bit-identical to elastic=False.
    #                                Tables hold the FULL keyspace
    #                                (ownership is the mask, local slot ==
    #                                key) so acquired slots always have a
    #                                resident row to install into.
    elastic_slots: int = 256       # base slot count S (rounded up to a
    #                                multiple of node_cnt-elastic_spare_cnt)
    elastic_spare_cnt: int = 0     # trailing servers that boot slotless
    #                                (warm spares for mid-run scale-out);
    #                                they join the epoch exchange with
    #                                empty contributions until a grow
    #                                rebalance moves slots onto them
    elastic_plan: str = ""         # controller-driven rebalance:
    #                                "grow:NODE:EPOCH" | "drain:NODE:EPOCH"
    #                                — server 0 announces MIGRATE_BEGIN at
    #                                the first group boundary >= EPOCH,
    #                                cutover lands 3 groups later (same
    #                                margin discipline as the measurement
    #                                window announcement)

    # ---- geo-replication tier (region-aware slot map, quorum group-
    # commit, follower snapshot reads; runtime/replication.py).  All
    # defaults OFF: with geo=False every path takes the pre-geo code
    # exactly (same wire bytes, logs, replica stream, acks). ----
    geo: bool = False              # arm the geo tier.  Requires elastic
    #                                (full-residency tables are what let a
    #                                follower materialize every row from
    #                                the merged log stream) + logging +
    #                                replica_cnt >= 1.  Replicas become
    #                                FOLLOWERS: they replay the merged
    #                                command stream group-by-group and
    #                                serve REGION_READ snapshot reads at
    #                                the last applied group boundary; the
    #                                primary's group commit gates on a
    #                                QUORUM of LOG_ACKs instead of all
    #                                replicas.  In geo mode fault_kill
    #                                "n:e" means REGION LOSS: server n
    #                                dies at epoch e AND every replica
    #                                homed in n's region dies at its own
    #                                first record >= e.
    geo_region_cnt: int = 1        # regions; servers map block-wise
    #                                (s * R // node_cnt), clients likewise,
    #                                and replica k of primary p lands in
    #                                region (region(p) + 1 + k) % R — a
    #                                primary's replicas always live in
    #                                OTHER regions, so region loss never
    #                                takes a primary and all its replicas
    #                                together (runtime/replication.py
    #                                region_of).
    geo_quorum: int = 0            # replica acks a group boundary needs
    #                                before its CL_RSPs release.  0 = all
    #                                replica_cnt (the pre-geo gate); q <
    #                                replica_cnt tolerates slow/dead
    #                                replicas at the cost of a thinner
    #                                durability margin.
    geo_wan_us: str = ""           # WAN latency profile: "0-1:20000"
    #                                (symmetric) and/or "0>1:5000"
    #                                (directed) comma-separated region-
    #                                pair one-way delays in us, applied
    #                                per-link via dt_set_peer_delay_us at
    #                                node start.
    geo_read_perc: float = 0.0     # target fraction of client traffic
    #                                issued as follower snapshot reads
    #                                (REGION_READ to the nearest live
    #                                follower); 0 disables the read path.

    # ---- partition & gray-failure tolerance (heartbeat failure
    # detector, fenced slot ownership, quorum reassignment;
    # runtime/faildet.py).  All defaults OFF: with fencing=False no
    # heartbeat is ever sent, no frame grows a fence header, and every
    # log byte / replica stream / digest / wire byte is bit-identical
    # to the pre-fencing runtime. ----
    fencing: bool = False          # arm the membership fencing layer:
    #                                HEARTBEAT frames feed a phi-accrual
    #                                per-peer suspicion score (gray
    #                                failures that never close a socket);
    #                                EPOCH_BLOB/LOG_MSG carry the
    #                                sender's map_version and receivers
    #                                reject stale incarnations with
    #                                FENCE_NACK; a fenced-out primary
    #                                self-halts with exit 18 instead of
    #                                serving split-brain writes; dead-
    #                                peer reassignment only fires on the
    #                                majority side of the live set
    #                                (minority partitions self-fence,
    #                                ties resolve to the side holding
    #                                the lowest id); and CL_RSPs gate on
    #                                a majority having CONFIRMED receipt
    #                                of the acked epoch's blob (the
    #                                epoch-boundary ack lease that makes
    #                                a partitioned primary's acks
    #                                causally impossible, not just
    #                                unlikely).  Requires elastic +
    #                                logging (reassignment rebuilds rows
    #                                by log replay).
    fencing_phi: float = 8.0       # phi-accrual suspicion threshold: a
    #                                peer is SUSPECTED once
    #                                phi = log10(e) * elapsed/mean_gap
    #                                crosses this (8.0 at the 100 ms
    #                                heartbeat cadence ~= 1.8 s silent)
    fencing_heartbeat_ms: float = 100.0  # standalone HEARTBEAT cadence
    #                                per live peer link (any received
    #                                frame also counts as a heartbeat —
    #                                the epoch exchange piggybacks)
    fencing_suspect_s: float = 2.0  # wall-clock silence floor a
    #                                suspicion must ALSO clear before it
    #                                may drive reassignment / self-
    #                                fencing — hysteresis so a flapping
    #                                link heals instead of fencing

    # ---- overload robustness tier (open-loop load generation +
    # per-tenant admission control + SLO backpressure; runtime/loadgen.py
    # and runtime/admission.py).  All defaults OFF: with every knob at
    # its default the client drives the pre-overload closed loop and the
    # server admits unconditionally — bit-identical wire bytes. ----
    arrival_process: str = ""      # open-loop arrival process replacing
    #                                closed-loop driving: "" (off) |
    #                                "poisson" (steady seeded Poisson) |
    #                                "diurnal" (sinusoid-modulated rate) |
    #                                "bursty" (on/off duty cycle) |
    #                                "flash" (rate step x factor during a
    #                                window — the flash-crowd scenario).
    #                                The client sends whenever its seeded
    #                                cumulative-arrival target runs ahead
    #                                of sent_total, independent of
    #                                responses (open loop) — backlog, not
    #                                acks, drives the send schedule.
    arrival_rate: float = 0.0      # mean arrival rate, txn/s across ALL
    #                                clients (split per client like
    #                                load_rate); required > 0 when a
    #                                process is armed
    arrival_period_s: float = 1.0  # diurnal sinusoid period / bursty
    #                                on-off cycle length (seconds)
    arrival_amp: float = 0.5       # diurnal amplitude fraction in [0, 1):
    #                                rate(t) = rate * (1 + amp sin wt)
    arrival_duty: float = 0.5      # bursty: fraction of each period spent
    #                                ON at rate/duty (mean rate preserved)
    arrival_flash_at_s: float = 0.0    # flash: burst start, seconds after
    #                                    the client's run start
    arrival_flash_secs: float = 0.0    # flash: burst duration (required
    #                                    > 0 for the flash process)
    arrival_flash_factor: float = 10.0  # flash: rate multiplier inside
    #                                     the burst window
    loadgen_procs: int = 1         # open-loop generator FLEET: each client
    #                                process spawns this many seeded
    #                                generator workers (runtime/loadgen
    #                                LoadFleet), each owning a disjoint
    #                                lane-tag range and a disjoint tenant
    #                                sub-range, their arrival schedules
    #                                merged deterministically — offered
    #                                load scales past one process's
    #                                query-gen rate (the pod-scale
    #                                driving side).  1 (default) keeps
    #                                the single in-process generator and
    #                                bit-identical wire bytes.
    zipf_shift: str = ""           # mid-run contention shift "THETA:AT_S":
    #                                the client pre-generates a SECOND
    #                                seeded query ring at zipf theta=THETA
    #                                and swaps to it AT_S seconds after its
    #                                run start — the load-shift stimulus
    #                                the ctrl chaos scenario drives (zipf
    #                                0 -> 0.9 mid-run).  "" (default) =
    #                                off: no second ring is ever built and
    #                                the send path is untouched.  YCSB
    #                                only (theta is a YCSB knob).
    tenant_cnt: int = 1            # tenants sharing the cluster; each
    #                                query carries its tenant id in tag
    #                                bits 24..31 (<= 256 tenants), so the
    #                                wire format is unchanged and
    #                                tenant_cnt=1 leaves every tag byte
    #                                exactly as before
    tenant_weights: str = ""       # comma-separated arrival weights per
    #                                tenant ("1,8" = tenant 1 offers 8x
    #                                tenant 0's load — the aggressor
    #                                shape); "" = uniform
    admission: bool = False        # server-side admission control: token-
    #                                bucket tenant quotas feed a bounded
    #                                queue ahead of epoch-batch formation;
    #                                over-quota / over-capacity queries
    #                                are NACKed (ADMIT_NACK + retry-after
    #                                hint) instead of held forever.  Off
    #                                (default): every decoded CL_QRY_BATCH
    #                                goes straight to pending, no NACK is
    #                                ever sent, no controller exists.
    admission_queue_max: int = 8192    # admission queue bound (txns
    #                                    pending epoch formation); arrivals
    #                                    past it NACK with a retry hint
    tenant_quota: float = 0.0      # per-tenant token-bucket rate, txn/s
    #                                per SERVER (each server meters its own
    #                                arrivals); 0 = no quota (capacity
    #                                shedding only)
    tenant_burst_s: float = 0.5    # bucket depth in seconds of quota
    #                                (burst tolerance = quota * burst_s)
    admission_slo_ms: float = 0.0  # admission-queue-delay SLO (p99 per
    #                                epoch group).  When breached, the
    #                                controller sheds over-quota tenants
    #                                FIRST: a tenant whose bucket drained
    #                                below half depth (it arrives at >=
    #                                quota) loses its whole batch while
    #                                quota-respecting tenants keep
    #                                admitting.  0 = no SLO backpressure.
    admission_retry_us: float = 50_000.0  # base retry-after hint on a
    #                                       capacity NACK (quota NACKs
    #                                       hint the bucket refill time)
    nack_backoff_base_us: float = 20_000.0  # client backoff ledger: first
    #                                retry delay; doubles per consecutive
    #                                NACK of the same tag, jittered
    #                                +/-50%, floored at the server's
    #                                retry-after hint
    nack_backoff_max_us: float = 2_000_000.0  # backoff growth cap

    # ---- transaction flight recorder (cross-node txn lifecycle tracing
    # + structured telemetry stream; runtime/telemetry.py).  All defaults
    # OFF: with telemetry=False no recorder is ever constructed, no
    # sidecar file is written, no [telemetry] line prints, and every
    # wire byte / log byte / verdict is bit-identical to the
    # pre-telemetry runtime (the same contract as chaos/elastic/geo/
    # overload/repair/fencing). ----
    telemetry: bool = False        # arm the flight recorder: every node
    #                                (client, server, replica) records
    #                                per-hop lifecycle events for the
    #                                DETERMINISTICALLY SAMPLED txn subset
    #                                (lane % telemetry_sample == 0 on the
    #                                tag's ring-lane bits, so client and
    #                                every server pick the SAME txns with
    #                                zero coordination) into a
    #                                preallocated numpy record ring,
    #                                flushed as telemetry_*.bin sidecars;
    #                                servers additionally stream
    #                                per-epoch counters to
    #                                metrics_node*.jsonl.  Join + render
    #                                with harness/txntrace.py.
    telemetry_sample: int = 1024   # sampling modulus (depth knob, live
    #                                default like repair_rounds): 1 =
    #                                record every txn (tests/debug);
    #                                1024 = the default production rate
    #                                the <= 2% overhead gate pins
    #                                (tools/regression_gate.py,
    #                                results/telemetry)
    telemetry_ring: int = 1 << 16  # record-ring capacity per node;
    #                                events past a full ring DROP (and
    #                                count) rather than stall the hot
    #                                loop — the ring auto-flushes at
    #                                half full from the epoch loop
    telemetry_dir: str = ""        # sidecar directory; "" = log_dir
    #                                (the launcher namespaces it per run
    #                                exactly like the command logs)

    # ---- live metrics bus (cluster observability plane; runtime/
    # metricsbus.py).  Default OFF: with metrics=False no frame is ever
    # built, no METRICS rtype crosses the wire, no aggregator exists,
    # no [crit]/[watch] line prints, no metrics_bus_*.jsonl is written,
    # and every broadcast/log byte is bit-identical to the pre-bus
    # runtime (the same contract as chaos/elastic/geo/overload/repair/
    # fencing/telemetry). ----
    metrics: bool = False          # arm the bus: every node samples a
    #                                per-epoch metrics frame (host-side
    #                                counters + stage timings + the
    #                                per-partition conflict density the
    #                                incidence matmuls yield for free)
    #                                and ships it as METRICS (rtype 25)
    #                                to the aggregator on the lowest-id
    #                                live server, which writes the
    #                                metrics_bus_*.jsonl stream, emits
    #                                [crit] critical-path attribution +
    #                                [watch] anomaly events, and feeds
    #                                tools/monitor.py (live TUI +
    #                                --prom exposition)
    metrics_cadence: int = 1       # epochs between frames (depth knob,
    #                                live default like telemetry_sample:
    #                                1 = every retired epoch — the rate
    #                                the <=2% overhead gate pins,
    #                                tools/regression_gate.py +
    #                                results/metricsbus); raise it on
    #                                fast chips where per-epoch frames
    #                                would flood the aggregator

    # ---- isolation audit plane (online serializability certifier with
    # cycle-witness forensics; cc/base.audit_observe + runtime/audit.py
    # + harness/auditgraph.py).  Default OFF: with audit=False no
    # observation is ever derived, no audit_*.jsonl sidecar is written,
    # no [audit] line prints, the group jit's outputs are exactly the
    # pre-audit ones and every wire/log byte is bit-identical to the
    # pre-audit runtime (the same contract as chaos/elastic/geo/
    # overload/repair/fencing/telemetry/metrics). ----
    audit: bool = False            # arm the certifier: each epoch derives
    #                                committed-txn dependency observations
    #                                ON DEVICE (ww/wr/rw edge lists between
    #                                committed txns off the planned access
    #                                sets under the backend's visibility
    #                                rule, plus per-bucket version stamps
    #                                — the audit twin of the VersionRing)
    #                                and exports them beside the verdict
    #                                planes into audit_node*.jsonl;
    #                                harness/auditgraph.py joins the
    #                                sidecars across nodes/epochs into the
    #                                cluster-wide Direct Serialization
    #                                Graph and either certifies the run
    #                                serializable or renders a minimal
    #                                cycle witness (Adya G0/G1c/G-single/
    #                                G2 classification)
    audit_cadence: int = 8         # epochs between audited epochs (depth
    #                                knob with a live default, like
    #                                telemetry_sample: the whole device
    #                                derivation skips off-cadence epochs
    #                                via lax.cond, so coverage trades
    #                                against cost — the <=2% overhead
    #                                gate pins THIS default rate
    #                                (tools/audit_bench.py; the exact-key
    #                                lane sort is ~4 ms/epoch at B=1024
    #                                on the CPU rig, so always-on costs
    #                                ~12% there).  1 = certify every
    #                                epoch — what every chaos scenario
    #                                pins (harness/chaos.py chaos_cfg),
    #                                so the standing oracles and the
    #                                mutation catch run at FULL coverage.
    #                                Every node skips the same epochs,
    #                                keeping sidecars consensus-
    #                                comparable.
    audit_edges_max: int = 4096    # per-epoch exported-edge cap (static
    #                                d2h shape); overflow counts as
    #                                audit_drop_cnt and degrades the
    #                                certificate to "incomplete", never
    #                                silently
    audit_buckets: int = 1 << 16   # hashed width of the audit version-
    #                                stamp tables (the cross-epoch
    #                                observation space; O(K) memory like
    #                                the T/O watermarks, so it can be much
    #                                wider than conflict_buckets)
    audit_mutate: str = ""         # seeded edge-derivation fault (the
    #                                anti-inert knob): "occ-read-skip:
    #                                START[:COUNT]" drops OCC's read-set-
    #                                vs-winner-write-set check on epochs
    #                                [START, START+COUNT) — losers whose
    #                                writes miss every winner-written
    #                                bucket commit anyway, a REAL isolation
    #                                violation the certifier must reject
    #                                with a cycle witness naming an epoch
    #                                in the window.  Test/chaos use only.

    # ---- self-driving control plane (contention-adaptive CC router +
    # closed-loop degradation governors; runtime/controller.py +
    # cc/router.py).  Default OFF: with ctrl=False no controller is ever
    # constructed, the engine compiles the exact pre-router epoch
    # program, no [ctrl] line prints, and every log/wire/digest byte is
    # bit-identical to the pre-ctrl runtime (the same contract as
    # chaos/elastic/geo/overload/repair/fencing/telemetry/metrics/
    # audit). ----
    ctrl: bool = False             # arm the control plane: a
    #                                deterministic feedback controller
    #                                consumes epoch e-1's per-partition
    #                                conflict density (cc/base.
    #                                conflict_density via the metrics
    #                                plane) plus the repair/admission/
    #                                audit counters and sets, at epoch
    #                                boundaries: per-partition CC backend
    #                                (NO_WAIT/OCC/TPU_BATCH) + conflict-
    #                                bucket granularity (in-process
    #                                engine), repair-round budget, audit
    #                                cadence, and admission quota scale
    #                                (cluster servers).  Every decision
    #                                is recorded as a [ctrl] line so
    #                                replay reproduces the sequence
    #                                bit-for-bit, and a fail-safe
    #                                governor reverts every knob to the
    #                                static config when signals go stale
    #                                (aggregator death / partition /
    #                                fenced node) and re-engages on heal.
    ctrl_lo: float = 0.02          # hysteresis band floor: per-epoch
    #                                contended access lanes per batch row
    #                                below which a partition classes as
    #                                SPARSE (depth knob, live default)
    ctrl_hi: float = 0.20          # band ceiling: lanes per row above
    #                                which a partition classes as HOT;
    #                                between lo and hi the class HOLDS
    #                                (the hysteresis dead band)
    ctrl_confirm: int = 2          # consecutive boundary ticks a new
    #                                class must persist before any knob
    #                                moves (oscillation damper #1)
    ctrl_cooldown: int = 4         # boundary ticks a knob stays put
    #                                after it moved (oscillation damper
    #                                #2; per knob, not global)
    ctrl_stale_s: float = 2.0      # governor staleness bound: a
    #                                boundary gap (or density silence)
    #                                beyond this wall-clock budget trips
    #                                the fail-safe revert to the static
    #                                config
    ctrl_heal: int = 3             # consecutive healthy ticks before a
    #                                tripped governor re-engages the
    #                                adaptive knobs
    ctrl_gshift: int = 2           # conflict-granularity coarsening for
    #                                SPARSE partitions: incidence keys
    #                                shift right this many bits (merging
    #                                keys only ADDS conflicts — a sound
    #                                over-approximation that shrinks the
    #                                false-sharing surface the OCC-
    #                                granularity paper prices); 0 =
    #                                granularity knob inert
    ctrl_scale_max: int = 4        # max admission quota-scale steps the
    #                                cluster governor may shed (effective
    #                                quota = tenant_quota * 0.8^step)
    ctrl_dgcc: bool = False        # arm the controller's FOURTH router
    #                                class: HOT partitions route to the
    #                                DGCC wavefront backend (cc/dgcc.py)
    #                                instead of TPU_BATCH — conflicting
    #                                txns serialize into chained waves
    #                                rather than abort.  Default off:
    #                                the candidate list, the compiled
    #                                4-way routed program and every
    #                                [ctrl] replay stay exactly the
    #                                3-class plane (bit-identical off).

    # ---- checkpoint / resume (no reference analogue: SURVEY §5.4 notes
    # the reference cannot recover; we can) ----
    checkpoint_path: str = ""      # "" = checkpointing off
    checkpoint_every_epochs: int = 0   # 0 = only at end of run
    resume: bool = False           # load checkpoint_path before running

    # ---- misc ----
    seed: int = 0
    debug_timeline: bool = False
    owner_check: bool = False      # debug mode: wrap the dispatch-owned
    #                                host collections (runtime/
    #                                ownercheck.GUARDED) in subclasses
    #                                whose mutators assert the calling
    #                                thread is the dispatch thread — the
    #                                runtime half of the graftlint
    #                                thread-ownership checker (our
    #                                substitute for TSAN, broken on this
    #                                box).  Default off: nothing is
    #                                wrapped and no code path changes.

    # ------------------------------------------------------------------
    @property
    def faults_enabled(self) -> bool:
        """True iff any chaos knob is armed.  Every fault/failover code
        path in client, server and launcher is gated on this, so the
        default config runs byte-identical to the pre-chaos runtime."""
        return (self.fault_drop_prob > 0 or self.fault_dup_prob > 0
                or self.fault_delay_jitter_us > 0 or bool(self.fault_kill)
                or bool(self.fault_partition) or bool(self.fault_peer_stall)
                or self.recover)

    def fault_kill_spec(self) -> tuple[int, int] | None:
        """Parse fault_kill 'node:epoch' (None when unset)."""
        if not self.fault_kill:
            return None
        node, epoch = self.fault_kill.split(":")
        return int(node), int(epoch)

    def fault_partition_spec(self) -> list[tuple[int, int, bool, float]]:
        """Parse fault_partition into [(a, b, bidirectional, start_s)].
        "A-B:S" blackholes both directions from S seconds after the
        barrier; "A>B:S" only frames A sends to B.  [] when unset."""
        out: list[tuple[int, int, bool, float]] = []
        if not self.fault_partition:
            return out
        for ent in self.fault_partition.split(","):
            ent = ent.strip()
            sep = ">" if ">" in ent else "-"
            try:
                pair, start = ent.split(":")
                a, b = (int(x) for x in pair.split(sep))
                start = float(start)
            except ValueError:
                raise ValueError(
                    f"config: fault_partition entry {ent!r} must be "
                    "'A-B:START_S' (bidirectional) or 'A>B:START_S' "
                    "(one-way)")
            _check(0 <= a < self.node_cnt and 0 <= b < self.node_cnt
                   and a != b and start >= 0,
                   f"fault_partition entry {ent!r}: A/B must name "
                   "distinct server nodes and START_S must be >= 0")
            out.append((a, b, sep == "-", start))
        return out

    def fault_peer_stall_spec(self) -> tuple[int, float, float] | None:
        """Parse fault_peer_stall 'NODE:MS:START_S' (None when unset)."""
        if not self.fault_peer_stall:
            return None
        try:
            node, ms, start = self.fault_peer_stall.split(":")
            node, ms, start = int(node), float(ms), float(start)
        except ValueError:
            raise ValueError(
                f"config: fault_peer_stall {self.fault_peer_stall!r} "
                "must be 'NODE:MS:START_S'")
        _check(0 <= node < self.node_cnt and ms > 0 and start >= 0,
               "fault_peer_stall: NODE must name a server, MS > 0, "
               "START_S >= 0")
        return node, ms, start

    def geo_wan_spec(self) -> dict[tuple[int, int], int]:
        """Parse geo_wan_us into a directed {(region_a, region_b): us}
        matrix.  "A-B:us" sets both directions, "A>B:us" one; later
        entries override earlier ones."""
        out: dict[tuple[int, int], int] = {}
        if not self.geo_wan_us:
            return out
        for ent in self.geo_wan_us.split(","):
            ent = ent.strip()
            sep = ">" if ">" in ent else "-"
            try:
                pair, us = ent.split(":")
                a, b = (int(x) for x in pair.split(sep))
                us = int(us)
            except ValueError:
                raise ValueError(
                    f"config: geo_wan_us entry {ent!r} must be "
                    "'A-B:us' (symmetric) or 'A>B:us' (directed)")
            _check(0 <= a < self.geo_region_cnt
                   and 0 <= b < self.geo_region_cnt and us >= 0,
                   f"geo_wan_us entry {ent!r}: regions must be in "
                   f"[0, {self.geo_region_cnt}) and delay >= 0")
            out[(a, b)] = us
            if sep == "-":
                out[(b, a)] = us
        return out

    def tenant_weights_spec(self) -> list[float]:
        """Per-tenant arrival weights (normalized); uniform when unset."""
        if not self.tenant_weights:
            return [1.0 / self.tenant_cnt] * self.tenant_cnt
        try:
            ws = [float(x) for x in self.tenant_weights.split(",")]
        except ValueError:
            raise ValueError(
                f"config: tenant_weights {self.tenant_weights!r} must be "
                "comma-separated numbers")
        _check(len(ws) == self.tenant_cnt,
               f"tenant_weights has {len(ws)} entries for "
               f"{self.tenant_cnt} tenants")
        _check(all(w > 0 for w in ws), "tenant_weights must be positive")
        s = sum(ws)
        return [w / s for w in ws]

    def audit_mutate_spec(self) -> tuple[str, int, int] | None:
        """Parse audit_mutate 'KIND:START[:COUNT]' into (kind, start,
        count); None when unset.  COUNT defaults to 1."""
        if not self.audit_mutate:
            return None
        parts = self.audit_mutate.split(":")
        if len(parts) not in (2, 3) or parts[0] != "occ-read-skip":
            raise ValueError(
                f"config: audit_mutate {self.audit_mutate!r} must be "
                "'occ-read-skip:START_EPOCH[:COUNT]'")
        try:
            start = int(parts[1])
            count = int(parts[2]) if len(parts) == 3 else 1
        except ValueError:
            raise ValueError(
                f"config: audit_mutate {self.audit_mutate!r}: START/"
                "COUNT must be integers")
        _check(start >= 0 and count >= 1,
               "audit_mutate needs START >= 0 and COUNT >= 1")
        return parts[0], start, count

    def zipf_shift_spec(self) -> tuple[float, float] | None:
        """Parse zipf_shift 'THETA:AT_S' into (theta, at_s); None when
        unset."""
        if not self.zipf_shift:
            return None
        parts = self.zipf_shift.split(":")
        if len(parts) != 2:
            raise ValueError(
                f"config: zipf_shift {self.zipf_shift!r} must be "
                "'THETA:AT_S' (target zipf theta, shift time in seconds "
                "after run start)")
        try:
            theta, at_s = float(parts[0]), float(parts[1])
        except ValueError:
            raise ValueError(
                f"config: zipf_shift {self.zipf_shift!r}: THETA/AT_S "
                "must be numbers")
        _check(0.0 <= theta < 2.0 and at_s > 0,
               "zipf_shift needs THETA in [0, 2) and AT_S > 0")
        return theta, at_s

    def elastic_plan_spec(self) -> tuple[str, int, int] | None:
        """Parse elastic_plan 'grow|drain:node:epoch' (None when unset)."""
        if not self.elastic_plan:
            return None
        kind, node, epoch = self.elastic_plan.split(":")
        return kind, int(node), int(epoch)

    def replace(self, **kw: Any) -> "Config":
        return dataclasses.replace(self, **kw).validate()

    def validate(self) -> "Config":
        # real raises, not asserts: must hold under `python -O` too
        _check(self.node_cnt >= 1 and self.part_cnt >= 1,
               "node_cnt/part_cnt must be >= 1")
        _check(self.device_parts >= 1, "device_parts must be >= 1")
        if self.device_parts > 1:
            _check(self.part_cnt == 1,
                   "device_parts (multi-chip) and part_cnt (multi-process) "
                   "partitioning do not compose yet")
            if self.mc_plan_capacity > 0:
                _check(self.max_accesses <= 128,
                       "sharded multi-chip planning needs max_accesses "
                       "<= 128: a txn's own lanes must fit one capacity "
                       "block (the 128-lane tile floor of mc_pair_cap) "
                       "or it could defer forever — raise "
                       "mc_plan_capacity=0 to use the replicated plan")
            # ownership anchors must deal evenly over the mesh blocks
            # (storage.table.to_mc_layout); each workload's anchor is the
            # reference's node-partition unit across chips
            D = self.device_parts
            if self.workload == WorkloadKind.YCSB:
                _check(self.synth_table_size % D == 0,
                       "synth_table_size must divide over device_parts")
            elif self.workload == WorkloadKind.TPCC:
                _check(self.num_wh % D == 0,
                       "num_wh must divide over device_parts "
                       "(warehouses are the ownership anchor)")
                _check(self.insert_table_cap % D == 0,
                       "insert_table_cap must divide over device_parts")
            elif self.workload == WorkloadKind.PPS:
                for nm, n in (("pps_parts_cnt", self.pps_parts_cnt),
                              ("pps_products_cnt", self.pps_products_cnt),
                              ("pps_suppliers_cnt", self.pps_suppliers_cnt)):
                    _check(n % D == 0,
                           f"{nm} must divide over device_parts")
        _check(self.epoch_batch > 0
               and (self.epoch_batch & (self.epoch_batch - 1)) == 0,
               "epoch_batch must be a power of two (tiling discipline)")
        if self.cc_alg == CCAlg.MAAT:
            _check(self.epoch_batch <= 32768,
                   "MAAT needs epoch_batch <= 32768: its ancestor-count "
                   "order keys span epoch_batch^2 and must fit int32 "
                   "(cc/maat.py closure branch)")
        if self.sim_full_row:
            _check(self.workload == WorkloadKind.YCSB,
                   "sim_full_row materializes YCSB payload bytes; TPCC/PPS "
                   "rows are numeric columns (materialized always)")
        if self.workload == WorkloadKind.YCSB:
            _check(self.max_accesses >= self.req_per_query,
                   "max_accesses must cover req_per_query")
            _check(abs(self.read_perc + self.write_perc - 1.0) < 1e-6,
                   "read_perc + write_perc must sum to 1")
            _check(self.skew_method in ("ZIPF", "HOT"),
                   f"bad skew_method {self.skew_method!r}")
            _check(0.0 <= self.txn_write_perc <= 1.0,
                   "txn_write_perc must be in [0, 1]")
            if self.skew_method == "HOT":
                _check(1 <= self.data_perc < self.synth_table_size,
                       "HOT skew: data_perc (hot-set key count) must be in "
                       "[1, synth_table_size)")
                _check(0.0 <= self.access_perc <= 1.0,
                       "access_perc must be in [0, 1]")
        else:
            _check(not self.ycsb_abort_mode,
                   "ycsb_abort_mode is YCSB-only (the sentinel key would "
                   "force-abort hot TPCC/PPS rows)")
        if self.workload == WorkloadKind.TPCC:
            _check(self.max_accesses >= 3 + self.max_items_per_txn,
                   "TPCC max_accesses must cover wh+dist+cust+items "
                   f"(>= {3 + self.max_items_per_txn})")
        if self.tpcc_order_index:
            _check(self.workload == WorkloadKind.TPCC,
                   "tpcc_order_index is TPC-C only")
            _check(self.device_parts == 1,
                   "tpcc_order_index does not compose with multi-chip "
                   "execution yet")
            _check(self.node_cnt == 1,
                   "tpcc_order_index is single-node only: the cluster "
                   "server path maintains ORDER_IDX but has no "
                   "overflow surfacing (the index's contract requires "
                   "the host to check DynamicSortedIndex.overflowed(); "
                   "only engine/driver.run_simulation does)")
            _check(self.num_wh * 10 < 1024
                   and self.insert_table_cap + 3001 < (1 << 21),
                   "order_index_key packs district * 2^21 + o_id into "
                   "int32: needs num_wh <= 102 and insert_table_cap + "
                   "3001 < 2^21")
        _check(self.isolation_level in (
            "SERIALIZABLE", "READ_COMMITTED", "READ_UNCOMMITTED", "NOLOCK"),
            f"bad isolation_level {self.isolation_level!r}")
        _check(self.index_struct in ("IDX_HASH", "IDX_BTREE"),
               f"bad index_struct {self.index_struct!r}")
        _check(self.tport_type in ("ipc", "tcp"),
               f"bad tport_type {self.tport_type!r}")
        _check(self.deploy in ("inproc", "cluster"),
               f"bad deploy {self.deploy!r}")
        _check(self.pipeline_epochs >= 1 and self.pipeline_groups >= 1,
               "pipeline_epochs/pipeline_groups must be >= 1")
        _check(self.send_thread_cnt >= 1 and self.rem_thread_cnt >= 1
               and self.thread_cnt >= 1,
               "send/rem/worker thread counts must be >= 1")
        _check(self.client_batch_size >= 64,
               "client_batch_size must be >= 64 (the client skips sends "
               "smaller than one minimal message, client.py)")
        _check(self.dist_protocol in ("auto", "vote", "merged"),
               f"bad dist_protocol {self.dist_protocol!r}")
        _check(self.host_overlap in ("auto", "on", "off"),
               f"bad host_overlap {self.host_overlap!r}")
        if (self.logging or self.replica_cnt) and self.node_cnt > 1 \
                and self.cc_alg not in (CCAlg.CALVIN, CCAlg.TPU_BATCH):
            _check(self.dist_protocol == "merged",
                   "deterministic replay (logging/replication) requires "
                   "deterministic decisions: the VOTE protocol's "
                   "partitioned local validation cannot be replayed from "
                   "the command log alone — set --dist_protocol=merged "
                   "or use a deterministic backend")
        if self.dist_protocol == "vote":
            _check(self.cc_alg not in (CCAlg.CALVIN, CCAlg.TPU_BATCH),
                   "deterministic backends coordinate via the merged-batch "
                   "sequencer exchange, not 2PC votes")
            _check(self.device_parts == 1,
                   "the VOTE protocol's per-epoch host round trip "
                   "(prepare -> vote -> decide) does not compose with "
                   "mesh-sharded epoch programs — use the merged "
                   "sequencer exchange with device_parts > 1")
            _check(not self.ycsb_abort_mode,
                   "forced-abort sentinel is a merged-mode debug oracle")
        _check(self.repl_type in ("AP", "AA"),
               f"bad repl_type {self.repl_type!r}")
        _check(0.0 <= self.fault_drop_prob < 1.0
               and 0.0 <= self.fault_dup_prob < 1.0,
               "fault probabilities must be in [0, 1)")
        _check(self.fault_delay_jitter_us >= 0,
               "fault_delay_jitter_us must be >= 0")
        if self.fault_kill:
            parts = self.fault_kill.split(":")
            _check(len(parts) == 2 and parts[0].lstrip("-").isdigit()
                   and parts[1].lstrip("-").isdigit(),
                   f"fault_kill must be 'node:epoch', got "
                   f"{self.fault_kill!r}")
            _check(0 <= int(parts[0]) < self.node_cnt,
                   "fault_kill node must name a server node")
            _check(int(parts[1]) >= 0, "fault_kill epoch must be >= 0")
        if self.fault_kill or self.recover:
            _check(self.logging,
                   "fault_kill/recover need --logging: recovery rebuilds "
                   "state by replaying the command log")
        _check(self.failover_timeout_s > 0,
               "failover_timeout_s must be > 0")
        self.fault_partition_spec()     # raises on a malformed spec
        self.fault_peer_stall_spec()
        _check(self.fault_partition_flap_s >= 0,
               "fault_partition_flap_s must be >= 0")
        if self.fault_partition_flap_s > 0:
            _check(bool(self.fault_partition),
                   "fault_partition_flap_s needs fault_partition entries "
                   "to flap")
        # ---- fencing gating (same discipline as elastic/geo/overload/
        # repair: defaults take the pre-fencing paths exactly) ----
        _check(self.fencing_phi > 0 and self.fencing_heartbeat_ms > 0
               and self.fencing_suspect_s > 0,
               "fencing_phi/fencing_heartbeat_ms/fencing_suspect_s must "
               "be > 0")
        if self.fencing:
            _check(self.elastic and self.logging,
                   "fencing needs --elastic=true and --logging: quorum "
                   "reassignment retires a fenced peer in place and "
                   "rebuilds its rows by log replay")
        if self.elastic:
            _check(self.workload == WorkloadKind.YCSB,
                   "elastic membership currently supports YCSB only (the "
                   "dense keyspace makes slot->rows enumeration and "
                   "full-residency tables exact); TPCC/PPS keep static "
                   "striping")
            _check(self.cc_alg in (CCAlg.CALVIN, CCAlg.TPU_BATCH),
                   "elastic membership requires a deterministic backend "
                   "(CALVIN/TPU_BATCH): cutover at a group boundary and "
                   "failover-by-replay both rely on deterministic merged "
                   "verdicts")
            _check(self.dist_protocol != "vote",
                   "elastic membership runs the merged sequencer "
                   "exchange; the VOTE protocol's static owner map does "
                   "not rebalance")
            _check(self.device_parts == 1,
                   "elastic (process-level) and device_parts (chip-level) "
                   "repartitioning do not compose yet")
            _check(0 <= self.elastic_spare_cnt < self.node_cnt,
                   "elastic_spare_cnt must leave >= 1 active server")
            _check(self.elastic_slots >= 1, "elastic_slots must be >= 1")
        else:
            _check(self.elastic_spare_cnt == 0 and not self.elastic_plan,
                   "elastic_spare_cnt/elastic_plan need --elastic=true")
        if self.elastic_plan:
            parts = self.elastic_plan.split(":")
            _check(len(parts) == 3 and parts[0] in ("grow", "drain")
                   and parts[1].lstrip("-").isdigit()
                   and parts[2].lstrip("-").isdigit(),
                   f"elastic_plan must be 'grow|drain:NODE:EPOCH', got "
                   f"{self.elastic_plan!r}")
            _check(0 <= int(parts[1]) < self.node_cnt,
                   "elastic_plan node must name a server node")
            _check(int(parts[2]) >= 0, "elastic_plan epoch must be >= 0")
        if self.geo:
            _check(self.elastic,
                   "geo needs --elastic=true: followers materialize every "
                   "row from the merged log stream, which requires the "
                   "full-residency elastic tables")
            _check(self.logging and self.replica_cnt >= 1,
                   "geo needs --logging and replica_cnt >= 1 (quorum "
                   "group-commit and follower reads ride the replica "
                   "LOG_MSG stream)")
            _check(1 <= self.geo_region_cnt <= self.node_cnt,
                   "geo_region_cnt must be in [1, node_cnt]")
            _check(0 <= self.geo_quorum <= self.replica_cnt,
                   "geo_quorum must be in [0, replica_cnt] (0 = all)")
            _check(0.0 <= self.geo_read_perc < 1.0,
                   "geo_read_perc must be in [0, 1)")
            _check(not self.sim_full_row,
                   "geo follower reads serve fingerprint values; "
                   "sim_full_row payload serving is not wired yet")
            _check(self.workload == WorkloadKind.YCSB,
                   "geo is YCSB-scoped for now (the follower replay "
                   "state machine and snapshot serving are built over "
                   "the YCSB full-residency table)")
            self.geo_wan_spec()   # raises on a malformed profile
        else:
            _check(self.geo_region_cnt == 1 and self.geo_quorum == 0
                   and not self.geo_wan_us and self.geo_read_perc == 0.0,
                   "geo_region_cnt/geo_quorum/geo_wan_us/geo_read_perc "
                   "need --geo=true")
        # ---- overload tier gating (same discipline as elastic/geo:
        # defaults take the pre-overload paths exactly) ----
        _check(self.arrival_process in
               ("", "poisson", "diurnal", "bursty", "flash"),
               f"bad arrival_process {self.arrival_process!r}")
        if self.arrival_process:
            _check(self.arrival_rate > 0,
                   "an arrival process needs arrival_rate > 0")
            _check(self.load_rate == 0,
                   "arrival_process replaces load_rate (open loop vs "
                   "fixed-budget closed loop); set only one")
            _check(self.arrival_period_s > 0,
                   "arrival_period_s must be > 0")
            _check(0.0 <= self.arrival_amp < 1.0,
                   "arrival_amp must be in [0, 1)")
            _check(0.0 < self.arrival_duty <= 1.0,
                   "arrival_duty must be in (0, 1]")
            if self.arrival_process == "flash":
                _check(self.arrival_flash_secs > 0
                       and self.arrival_flash_at_s >= 0
                       and self.arrival_flash_factor >= 1.0,
                       "flash arrivals need arrival_flash_secs > 0, "
                       "arrival_flash_at_s >= 0 and factor >= 1")
        else:
            _check(self.arrival_rate == 0.0,
                   "arrival_rate needs an arrival_process")
        _check(self.loadgen_procs >= 1, "loadgen_procs must be >= 1")
        if self.loadgen_procs > 1:
            _check(self.arrival_process != "",
                   "a loadgen fleet (loadgen_procs > 1) drives the "
                   "open loop — arm an arrival_process")
            _check(self.loadgen_procs <= 64,
                   "loadgen_procs > 64 exceeds the per-client lane-tag "
                   "budget (tag bits reserve 6 bits of generator lane)")
            if self.tenant_cnt > 1:
                _check(self.tenant_cnt >= self.loadgen_procs,
                       "a loadgen fleet splits [0, tenant_cnt) into "
                       "disjoint per-generator sub-ranges — tenant_cnt "
                       "must be >= loadgen_procs so no generator's "
                       "range is empty")
        if self.zipf_shift:
            self.zipf_shift_spec()      # raises on a malformed spec
            _check(self.workload == WorkloadKind.YCSB,
                   "zipf_shift shifts the YCSB zipf theta mid-run; other "
                   "workloads have no theta to shift")
        _check(1 <= self.tenant_cnt <= 256,
               "tenant_cnt must be in [1, 256] (tenant ids ride tag "
               "bits 24..31)")
        if self.tenant_cnt > 1 or self.tenant_weights:
            self.tenant_weights_spec()   # raises on a malformed spec
        if self.admission:
            _check(self.admission_queue_max >= 64,
                   "admission_queue_max must be >= 64 (one minimal "
                   "client message)")
            _check(self.tenant_quota >= 0 and self.tenant_burst_s > 0,
                   "tenant_quota must be >= 0 and tenant_burst_s > 0")
            _check(self.admission_slo_ms >= 0,
                   "admission_slo_ms must be >= 0")
            _check(self.admission_retry_us > 0
                   and self.nack_backoff_base_us > 0
                   and self.nack_backoff_max_us
                   >= self.nack_backoff_base_us,
                   "admission retry/backoff knobs must be positive and "
                   "nack_backoff_max_us >= nack_backoff_base_us")
            if self.admission_slo_ms > 0:
                _check(self.tenant_quota > 0,
                       "SLO backpressure sheds over-QUOTA tenants first: "
                       "admission_slo_ms needs tenant_quota > 0")
        else:
            _check(self.tenant_quota == 0.0
                   and self.admission_slo_ms == 0.0,
                   "tenant_quota/admission_slo_ms need --admission=true")
        # ---- telemetry gating (same discipline as elastic/geo/overload/
        # repair/fencing: defaults take the pre-telemetry paths exactly;
        # sample/ring/dir are depth knobs with live defaults) ----
        _check(self.telemetry_sample >= 1,
               "telemetry_sample must be >= 1 (1 records every txn)")
        _check(self.telemetry_ring >= 1024,
               "telemetry_ring must be >= 1024 (one client batch of "
               "events must fit between flush points)")
        # ---- metrics bus gating (same discipline: the default takes
        # the pre-bus paths exactly; cadence is a depth knob with a
        # live default) ----
        _check(self.metrics_cadence >= 1,
               "metrics_cadence must be >= 1 (1 frames every epoch)")
        if self.metrics:
            _check(self.device_parts == 1,
                   "the metrics bus's conflict-density fold does not "
                   "compose with multi-chip execution yet (sharded "
                   "tables have no single bucket space to fold)")
        # ---- isolation audit gating (same discipline: the default
        # takes the pre-audit paths exactly; cadence/edges/buckets are
        # depth knobs with live defaults) ----
        _check(self.audit_cadence >= 1,
               "audit_cadence must be >= 1 (1 exports every epoch)")
        _check(self.audit_edges_max >= 64,
               "audit_edges_max must be >= 64")
        _check(self.audit_buckets >= 1024
               and (self.audit_buckets & (self.audit_buckets - 1)) == 0,
               "audit_buckets must be a power of two >= 1024")
        if self.audit:
            _check(self.mode == Mode.NORMAL,
                   "audit certifies executed state; degraded modes "
                   "(SIMPLE/NOCC/QRY_ONLY) execute nothing to certify")
            _check(self.device_parts == 1,
                   "audit observations do not compose with multi-chip "
                   "execution yet (the edge derivation is single-device)")
            # (MVCC is modeled since the depgraph refactor: audit_init
            # carries per-bucket version-boundary rings and reads select
            # their observed version by timestamp —
            # cc/depgraph.version_select)
            _check(self.workload in (WorkloadKind.YCSB, WorkloadKind.TPCC),
                   "audit is wired for YCSB and TPCC (the workload load "
                   "path installs the audit stamp tables)")
            _check(self.epoch_batch <= 16384,
                   "audit needs epoch_batch <= 16384: exported edges "
                   "pack (kind, src, dst) merged-batch ranks into 14-bit "
                   "fields of one int32")
            _check(self.dist_protocol != "vote",
                   "audit needs the merged epoch body (the VOTE "
                   "dispatch path derives no observation, so the "
                   "certifier would be armed but provably inert)")
            if self.node_cnt > 1:
                _check(self.dist_protocol == "merged"
                       or self.cc_alg in (CCAlg.CALVIN, CCAlg.TPU_BATCH,
                                          CCAlg.DGCC),
                       "cluster audit needs the replicated deterministic "
                       "verdict (--dist_protocol=merged or a "
                       "deterministic backend): the VOTE protocol's "
                       "partitioned local validation exports no "
                       "cluster-consistent observation")
        else:
            _check(not self.audit_mutate,
                   "audit_mutate needs --audit=true (the certifier must "
                   "be armed to catch the mutation)")
        if self.audit_mutate:
            self.audit_mutate_spec()    # raises on a malformed spec
            _check(self.cc_alg == CCAlg.OCC,
                   "audit_mutate 'occ-read-skip' weakens OCC's "
                   "read-set-vs-winner-write-set check; set cc_alg=OCC")
        # ---- transaction repair gating (same discipline as elastic/geo/
        # overload: defaults take the pre-repair paths exactly) ----
        _check(self.repair_rounds >= 0 and self.repair_rounds <= 8,
               "repair_rounds must be in [0, 8] (each round is a fused "
               "re-validation + re-execution pass inside the epoch jit)")
        if self.repair:
            _check(self.cc_alg in (CCAlg.NO_WAIT, CCAlg.WAIT_DIE,
                                   CCAlg.OCC, CCAlg.TIMESTAMP, CCAlg.MVCC,
                                   CCAlg.MAAT),
                   "repair applies to the six sweep backends only "
                   "(CALVIN/TPU_BATCH never abort and DGCC defers its "
                   "over-deep closures — there is nothing to salvage; "
                   "NOCC has no conflicts)")
            _check(self.mode == Mode.NORMAL,
                   "repair re-executes committed state; degraded modes "
                   "(SIMPLE/NOCC/QRY_ONLY) have no abort path to salvage")
            _check(self.device_parts == 1,
                   "repair sub-rounds do not compose with multi-chip "
                   "execution yet (the frontier matvec and the chained "
                   "re-execution are single-device)")
            _check(self.workload in (WorkloadKind.YCSB, WorkloadKind.TPCC),
                   "repair re-execution closures are wired for YCSB and "
                   "TPCC (workloads declare re_execute); PPS keeps "
                   "retry-only semantics")
            if self.node_cnt > 1:
                _check(self.dist_protocol == "merged",
                       "cluster repair needs --dist_protocol=merged: the "
                       "repair sub-rounds are part of the replicated "
                       "deterministic verdict, which the VOTE protocol's "
                       "partitioned local validation cannot express")
        # ---- DGCC wavefront gating (cc/dgcc.py) ----
        _check(1 <= self.dgcc_levels <= 256,
               "dgcc_levels must be in [1, 256] (wave budget per epoch; "
               "each relaxation round is two segmented scans inside the "
               "epoch jit)")
        if self.cc_alg == CCAlg.DGCC:
            _check(self.workload in (WorkloadKind.YCSB, WorkloadKind.TPCC),
                   "DGCC's wave re-execution closures are wired for YCSB "
                   "and TPCC (workloads declare chained execution; PPS "
                   "keeps the sweep backends)")
            _check(self.dist_protocol != "vote",
                   "DGCC's verdict is a pure replicated function of the "
                   "merged batch — use the merged sequencer exchange, "
                   "not 2PC votes")
        # ---- control plane gating (same discipline: the default takes
        # the pre-ctrl paths exactly; lo/hi/confirm/cooldown/stale/heal/
        # gshift/scale_max are depth knobs with live defaults) ----
        _check(0.0 <= self.ctrl_lo < self.ctrl_hi,
               "ctrl hysteresis band needs 0 <= ctrl_lo < ctrl_hi")
        _check(self.ctrl_confirm >= 1 and self.ctrl_cooldown >= 0
               and self.ctrl_heal >= 1,
               "ctrl_confirm/ctrl_heal must be >= 1, ctrl_cooldown >= 0")
        _check(self.ctrl_stale_s > 0, "ctrl_stale_s must be > 0")
        _check(0 <= self.ctrl_gshift <= 16,
               "ctrl_gshift must be in [0, 16] (key bits to coarsen)")
        _check(0 <= self.ctrl_scale_max <= 16,
               "ctrl_scale_max must be in [0, 16] quota-scale steps")
        if self.ctrl:
            _check(self.metrics,
                   "ctrl consumes the conflict-density signal: needs "
                   "--metrics=true (the PR 14 observability plane)")
            _check(self.mode == Mode.NORMAL,
                   "ctrl adapts executed-state knobs; degraded modes "
                   "(SIMPLE/NOCC/QRY_ONLY) have nothing to adapt")
            cands = (CCAlg.NO_WAIT, CCAlg.OCC, CCAlg.TPU_BATCH) \
                + ((CCAlg.DGCC,) if self.ctrl_dgcc else ())
            _check(self.cc_alg in cands,
                   "ctrl routes between NO_WAIT/OCC/TPU_BATCH (plus "
                   "DGCC when --ctrl_dgcc=true); the static cc_alg must "
                   "be one of the candidates (it is the governor's "
                   "fail-safe assignment)")
            _check(self.device_parts == 1,
                   "the ctrl router's branched epoch program is "
                   "single-device (multi-chip plans are built per-shard "
                   "inside shard_map)")
            _check(not self.ycsb_abort_mode,
                   "ctrl does not compose with the ycsb_abort_mode "
                   "sentinel (the forced-abort mask is backend-path "
                   "specific)")
            _check(not self.audit_mutate,
                   "ctrl does not compose with audit_mutate (the "
                   "seeded fault targets the static OCC path)")
            _check(not self.escrow_order_free,
                   "ctrl does not compose with escrow ordering "
                   "exemptions yet (the router's cross-backend batch "
                   "carries one shared conflict derivation)")
            if self.node_cnt > 1:
                _check(self.admission,
                       "cluster ctrl actuates admission quota scaling: "
                       "needs --admission=true")
        if self.ctrl_dgcc:
            _check(self.ctrl,
                   "ctrl_dgcc arms the router's fourth (DGCC) class: "
                   "needs --ctrl=true")
        if self.fencing and self.fault_peer_stall:
            # the gray-slow node ends up fenced and retired in place —
            # same coordinator constraint as the elastic kill below
            _check(int(self.fault_peer_stall.split(":")[0]) != 0,
                   "fencing cannot retire node 0 (the measure/stop "
                   "coordinator); stall a node >= 1")
        if self.fencing and self.fault_partition:
            # node 0's partition side must win the quorum decision
            # (majority, or the lowest-id tiebreak — which node 0 holds
            # by construction): a spec that isolates the measure/stop
            # coordinator into a minority would fence it and strand the
            # survivors on multi-minute recovery timeouts instead of
            # failing fast here.  Approximate the sides by connected
            # components over the UNDIRECTED uncut link graph (any
            # entry, either direction, severs its pair).
            cut = {frozenset((a, b))
                   for a, b, _bi, _s in self.fault_partition_spec()}
            comp, frontier = {0}, [0]
            while frontier:
                u = frontier.pop()
                for v in range(self.node_cnt):
                    if v != u and v not in comp \
                            and frozenset((u, v)) not in cut:
                        comp.add(v)
                        frontier.append(v)
            _check(2 * len(comp) >= self.node_cnt,
                   "fencing cannot fence node 0 (the measure/stop "
                   "coordinator): this fault_partition isolates it on "
                   "a minority side — cut around a node >= 1")
        if self.elastic and self.fault_kill:
            # failover-with-reassignment: survivors absorb the dead
            # node's slots by log replay — never restart it
            _check(int(self.fault_kill.split(":")[0]) != 0,
                   "elastic reassignment cannot lose node 0 (the "
                   "measure/stop coordinator); kill node >= 1")
        if self.workload == WorkloadKind.PPS:
            mix = (self.perc_getparts + self.perc_getproducts + self.perc_getsuppliers
                   + self.perc_getpartbyproduct + self.perc_getpartbysupplier
                   + self.perc_orderproduct + self.perc_updateproductpart + self.perc_updatepart)
            _check(abs(mix - 1.0) < 1e-6, "PPS txn mix must sum to 1")
            _check(self.max_accesses >= 1 + 2 * self.pps_parts_per,
                   "PPS max_accesses must cover anchor + mapping + parts "
                   f"(>= {1 + 2 * self.pps_parts_per})")
        return self

    # -- CLI bridge -----------------------------------------------------
    @classmethod
    def from_args(cls, argv: list[str]) -> "Config":
        """Parse ``--field=value`` / ``--field value`` pairs.

        Replaces the reference's hand-rolled ``-nidN -tN -zipfF`` parser
        (`system/parser.cpp:20-262`); any dataclass field is settable.
        """
        kw: dict[str, Any] = {}
        i = 0
        fields = {f.name: f for f in dataclasses.fields(cls)}
        while i < len(argv):
            arg = argv[i]
            if not arg.startswith("--"):
                raise ValueError(f"unrecognized argument {arg!r}")
            if "=" in arg:
                name, val = arg[2:].split("=", 1)
            else:
                if i + 1 >= len(argv):
                    raise ValueError(f"flag {arg!r} is missing a value")
                name, val = arg[2:], argv[i + 1]
                i += 1
            name = name.replace("-", "_")
            if name not in fields:
                raise ValueError(f"unknown config field {name!r}")
            kw[name] = _coerce(fields[name].type, val)
            i += 1
        return cls(**kw).validate()


def _check(ok: bool, msg: str) -> None:
    if not ok:
        raise ValueError(f"config: {msg}")


def _coerce(typ: Any, val: str) -> Any:
    t = str(typ)
    if "CCAlg" in t:
        return CCAlg(val)
    if "WorkloadKind" in t:
        return WorkloadKind(val)
    if "Mode" in t:
        return Mode(val)
    if "bool" in t:
        low = val.lower()
        if low in ("1", "true", "yes", "on"):
            return True
        if low in ("0", "false", "no", "off"):
            return False
        raise ValueError(f"invalid boolean value {val!r}")
    if "int" in t:
        return int(val)
    if "float" in t:
        return float(val)
    if "tuple" in t:
        return tuple(int(x) for x in val.strip("()").split(",") if x)
    return val
