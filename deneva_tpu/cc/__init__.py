"""Concurrency control as batched epoch validation (SURVEY §2.3).

One registry entry per reference algorithm (`config.h:101`, README:24-35),
each a pure ``validate(cfg, state, batch, incidence)`` function — runtime
dispatch replacing the reference's compile-time ``#if CC_ALG`` forest.

``CCBackend`` bundles the algorithm with its cross-epoch state handling
and declares whether the engine must run chained sub-rounds
(``n_levels > 1``: Calvin/TPU_BATCH) and whether incidence matrices are
needed at all (NOCC skips them).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

from deneva_tpu.config import CCAlg, Config
from deneva_tpu.cc.base import (AUDIT_KEY, AccessBatch,  # noqa: F401
                                Incidence, Verdict, audit_init,
                                audit_mutate_verdict, audit_observe,
                                build_conflict_incidence, build_incidence,
                                committed_write_frontier, conflict_density,
                                gate_order_free)
from deneva_tpu.cc import maat as _maat
from deneva_tpu.cc import occ as _occ
from deneva_tpu.cc import timestamp as _tsmod
from deneva_tpu.cc import twopl as _twopl
from deneva_tpu.cc.calvin import validate_calvin, validate_tpu_batch
from deneva_tpu.cc.dgcc import validate_dgcc
from deneva_tpu.cc.maat import validate_maat
from deneva_tpu.cc.nocc import validate_nocc
from deneva_tpu.cc.occ import validate_occ
from deneva_tpu.cc.timestamp import (commit_to_state, init_mvcc_state,
                                     init_to_state, validate_mvcc,
                                     validate_timestamp)
from deneva_tpu.cc.twopl import validate_no_wait, validate_wait_die


@dataclass(frozen=True)
class CCBackend:
    alg: CCAlg
    validate: Callable[..., tuple[Verdict, Any]]
    init_state: Callable[[Config], Any]
    needs_incidence: bool = True
    chained: bool = False      # engine executes commit levels as sub-rounds
    fresh_ts_on_restart: bool = True   # WAIT_DIE keeps its birth ts
    # single-pass forwarding executor (ops/forward): on blind-write
    # workloads the whole batch commits with reads forwarded in-batch —
    # no conflict matrix at all; chained path is the fallback otherwise
    forward: bool = False
    # the backend may EXCLUDE accesses the workload marks ``order_free``
    # from conflict detection (escrow/commutative semantics: scatter-add
    # deltas and immutable-column reads need no ordering; the executor
    # applies deltas order-exactly over every committed winner).  Opted
    # in per backend; the sweep backends' opt-in is additionally gated
    # by ``Config.escrow_sweep`` (cc.base.gate_order_free) so the
    # reference-faithful row-level-conflict baseline stays one flag away.
    exempt_order_free: bool = False
    # distributed VOTE protocol hook: apply cross-epoch state for the
    # GLOBALLY decided commit set (local validation's state output is
    # discarded at prepare time).  None = stateless backend.
    commit_state: Any = None
    # transaction repair hook (engine/repair.py, gated by Config.repair):
    # the backend's invalidated-read frontier rule
    # ``(cfg, cc_state, batch, inc, committed, losers) -> bool[B, A]`` —
    # which of a loser's reads saw a value the committed set overwrote
    # (OCC: read-set vs winner write-set; 2PL: lock-edge losers; T/O:
    # wts/rts watermark re-check; MAAT: range re-intersection).  The
    # repair sub-round re-validates losers through the backend's OWN
    # ``validate`` on the loser-masked batch, so the in-round conflict
    # semantics cannot diverge from the main round's.  None = not
    # repairable (chained backends never abort; NOCC never conflicts).
    repair_rule: Any = None


_NO_STATE = lambda cfg: ()  # noqa: E731

_REGISTRY: dict[CCAlg, CCBackend] = {
    CCAlg.NOCC: CCBackend(CCAlg.NOCC, validate_nocc, _NO_STATE,
                          needs_incidence=False),
    # the six sweep backends opt into the escrow exemption (gated by
    # escrow_order_free AND escrow_sweep): their edge derivations draw
    # from the ordered incidence views, so commutative hot-row updates
    # (TPC-C Payment's W_YTD/D_YTD, PPS PART_AMOUNT) commit many winners
    # per epoch instead of ~1 — the reference's per-row latch serializes
    # them within the window (row_lock.cpp:86-151) where epoch-snapshot
    # validation used to admit a single winner and abort-storm the rest
    CCAlg.NO_WAIT: CCBackend(CCAlg.NO_WAIT, validate_no_wait, _NO_STATE,
                             exempt_order_free=True,
                             repair_rule=_twopl.repair_frontier),
    CCAlg.WAIT_DIE: CCBackend(CCAlg.WAIT_DIE, validate_wait_die, _NO_STATE,
                              fresh_ts_on_restart=False,
                              exempt_order_free=True,
                              repair_rule=_twopl.repair_frontier),
    CCAlg.OCC: CCBackend(CCAlg.OCC, validate_occ, _NO_STATE,
                         exempt_order_free=True,
                         repair_rule=_occ.repair_frontier),
    CCAlg.TIMESTAMP: CCBackend(CCAlg.TIMESTAMP, validate_timestamp,
                               init_to_state, commit_state=commit_to_state,
                               exempt_order_free=True,
                               repair_rule=_tsmod.repair_frontier_timestamp),
    CCAlg.MVCC: CCBackend(CCAlg.MVCC, validate_mvcc, init_mvcc_state,
                          commit_state=commit_to_state,
                          exempt_order_free=True,
                          repair_rule=_tsmod.repair_frontier_mvcc),
    CCAlg.MAAT: CCBackend(CCAlg.MAAT, validate_maat, _NO_STATE,
                          exempt_order_free=True,
                          repair_rule=_maat.repair_frontier),
    # forward=True: on blind-write workloads (YCSB) the forwarding
    # executor is the closed form of the reference Calvin's RFWD dirty-
    # read forwarding — the whole batch commits whatever the chain depth,
    # exactly like the reference's scheduler grinding a hot-key queue
    # serially WITHIN the batch (it never defers a chain to the next
    # epoch).  The chained sub-round path remains for non-blind
    # workloads (TPC-C/PPS), where its level budget models the lock
    # queues.  Round-2 weak #3 (CALVIN collapsing at high skew) was this
    # missing equivalence: the level budget denied what the reference
    # merely serializes.
    CCAlg.CALVIN: CCBackend(CCAlg.CALVIN, validate_calvin, _NO_STATE,
                            chained=True, forward=True,
                            exempt_order_free=True),
    CCAlg.TPU_BATCH: CCBackend(CCAlg.TPU_BATCH, validate_tpu_batch, _NO_STATE,
                               chained=True, forward=True,
                               exempt_order_free=True),
    # DGCC builds the exact-key dependency graph BEFORE commit
    # (cc/depgraph.py lane sort + segmented scans — no hashed-bucket
    # incidence at all, hence needs_incidence=False) and serializes
    # conflicting txns into chained waves; over-deep closures DEFER to
    # the retry queue, so aborts stay zero by construction.  forward
    # stays False on purpose: unlike CALVIN's blind-write forwarding
    # collapse, DGCC always executes its real wavefront — the [dgcc]
    # line's waves>1 is the anti-inert signal the smoke gate pins.
    CCAlg.DGCC: CCBackend(CCAlg.DGCC, validate_dgcc, _NO_STATE,
                          needs_incidence=False, chained=True,
                          exempt_order_free=True),
}


def get_backend(alg: CCAlg | str) -> CCBackend:
    return _REGISTRY[CCAlg(alg)]
