"""Contention-adaptive CC router: per-partition backend + granularity
knobs for the epoch program (``Config.ctrl``, PR 16 tentpole).

The source paper's core result is a *static* frontier: no single CC
algorithm wins every contention regime (Harding et al., VLDB 2017
figs. 6-9).  The router makes the choice dynamic — per partition, per
epoch boundary — while keeping every contract the epoch programs
already guarantee:

* **Knobs ride beside the state, not in it.**  ``RouterKnobs`` is a
  small traced pytree passed as an extra argument to the jitted scan
  (`engine/step.Engine.jit_run_ctrl`), so changing a knob VALUE between
  chunks never recompiles and never perturbs the EngineState pytree
  (checkpoints, digests and the ctrl-off path are untouched).

* **One shared conflict derivation.**  All three candidate backends
  (NO_WAIT / OCC / TPU_BATCH) are stateless and mask inactive txns
  through their edge derivations, so a single (optionally coarsened)
  incidence serves every branch — the property that makes per-partition
  *mixed* assignment sound: validate each backend's sub-batch against
  the SAME bucket space and defer the cross-group conflict surface
  symmetrically (`cross_group_defer`; merging only ever ADDS defers,
  the usual over-approximation direction).

* **Granularity is incidence-only.**  ``coarsen_keys`` right-shifts the
  conflict-derivation key per access by its owner partition's
  ``gshift`` — merging keys can only ADD conflicts (a sound
  over-approximation, the coarse end of the OCC timestamp-granularity
  trade; PAPERS: arXiv:1811.04967) — while planning, execution, audit
  and density owners all keep the exact keys.  ``gshift=0`` reproduces
  the static incidence bit for bit.

Decision-making lives in `runtime/controller.py`; this module is the
*mechanism* half (pure device functions + the knob pytree).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from deneva_tpu.cc.base import AccessBatch
from deneva_tpu.config import CCAlg, Config

# branch indices of the routed `lax.switch` (engine/step.py): the three
# core uniform single-backend branches, the optional DGCC wavefront
# branch (``Config.ctrl_dgcc``, PR 18 — the HOT class's near-zero-abort
# escape hatch), then the mixed-assignment branch last
CANDIDATES: tuple[CCAlg, ...] = (CCAlg.NO_WAIT, CCAlg.OCC, CCAlg.TPU_BATCH)
MIXED = len(CANDIDATES)


def candidates(cfg: Config) -> tuple[CCAlg, ...]:
    """The epoch program's candidate tuple for this config.  Without
    ``ctrl_dgcc`` this is exactly the PR 16 three-class tuple, so the
    compiled switch (and every recorded [ctrl] replay) stays
    bit-identical when the fourth class is unarmed."""
    if cfg.ctrl_dgcc:
        return CANDIDATES + (CCAlg.DGCC,)
    return CANDIDATES


def candidate_index(alg: CCAlg | str) -> int:
    """Branch index of a candidate backend (raises on a non-candidate —
    config.validate pins cc_alg to the candidate set under ctrl).
    DGCC's index is stable at 3 whether or not it is armed: the mixed
    branch always sits LAST, after whatever candidates(cfg) yields."""
    alg = CCAlg(alg)
    if alg == CCAlg.DGCC:
        return len(CANDIDATES)
    return CANDIDATES.index(alg)


@dataclass
class RouterKnobs:
    """One epoch-boundary decision, as traced device operands.

    assign   — int32[P] per-partition backend (index into CANDIDATES)
    gshift   — int32[P] per-partition incidence-key coarsening (bits)
    repair_cap — int32[] live repair sub-rounds (<= cfg.repair_rounds;
                 the statically unrolled rounds past the cap skip via
                 lax.cond — real compute saved, not just masked)
    audit_cadence — int32[] live audit cadence (epochs between audited
                 epochs; density of the witness stream)
    """

    assign: jax.Array
    gshift: jax.Array
    repair_cap: jax.Array
    audit_cadence: jax.Array


jax.tree_util.register_dataclass(
    RouterKnobs,
    data_fields=["assign", "gshift", "repair_cap", "audit_cadence"],
    meta_fields=[])


def static_knobs(cfg: Config) -> RouterKnobs:
    """The knob vector equal to the static config — the governor's
    fail-safe assignment and the ctrl-off-equivalence pin (routing with
    these values is value-identical to the unrouted epoch program)."""
    p = max(cfg.part_cnt, 1)
    return RouterKnobs(
        assign=jnp.full((p,), candidate_index(cfg.cc_alg), jnp.int32),
        gshift=jnp.zeros((p,), jnp.int32),
        repair_cap=jnp.asarray(cfg.repair_rounds, jnp.int32),
        audit_cadence=jnp.asarray(max(1, cfg.audit_cadence), jnp.int32))


def knobs_from_decision(cfg: Config, assign, gshift, repair_cap,
                        audit_cadence) -> RouterKnobs:
    """Host-side decision -> device knob pytree (the controller's
    actuation boundary; plain lists/ints in, traced operands out)."""
    return RouterKnobs(
        assign=jnp.asarray(assign, jnp.int32),
        gshift=jnp.asarray(gshift, jnp.int32),
        repair_cap=jnp.asarray(repair_cap, jnp.int32),
        audit_cadence=jnp.asarray(max(1, int(audit_cadence)), jnp.int32))


def coarsen_keys(batch: AccessBatch, owner, gshift) -> AccessBatch:
    """Conflict-derivation view of the batch with per-access keys
    coarsened by the owner partition's ``gshift`` bits.  Only the
    incidence builder and the validates consume this view; execution,
    audit and repair re-reads keep the exact-key batch.  Soundness:
    two keys that collide after the shift simply share a conflict
    bucket — the same over-approximation a narrower conflict_buckets
    hash already makes — so coarsening can only ADD conflict edges,
    never hide one.  ``gshift=0`` is the identity (bit-identical
    incidence)."""
    sh = jnp.take(gshift, jnp.clip(owner, 0, gshift.shape[0] - 1))
    return dataclasses.replace(
        batch, keys=jax.lax.shift_right_logical(batch.keys, sh))


def txn_backend(knobs: RouterKnobs, owner) -> jax.Array:
    """int32[B] backend index per txn: its HOME partition's assignment
    (the partition of its first planned access — the same anchor the
    VOTE protocol routes coordinators on)."""
    home = owner[:, 0]
    return jnp.take(knobs.assign,
                    jnp.clip(home, 0, knobs.assign.shape[0] - 1))


def cross_group_defer(inc, batch: AccessBatch, group,
                      n_groups: int = MIXED) -> jax.Array:
    """bool[B] txns whose conflict surface crosses backend groups —
    deferred SYMMETRICALLY (both sides) in mixed-assignment epochs, so
    each backend validates a sub-batch whose conflicts are wholly its
    own and the merged committed set needs no cross-group ordering.

    Derivation from the family-1 incidence column masses: a txn
    conflicts across groups iff one of its access buckets is written by
    another group (``u · other_w``) or one of its written buckets is
    touched by another group (``w · other_u``).  Column masses
    accumulate in f32 (bf16 incidence holds exact small counts; the
    einsum keeps the [B,K] operand in bf16 and only the [K] masses in
    f32).  Bucket-space over-approximation as everywhere: a collision
    can only ADD a defer, never hide a real cross-group conflict."""
    u1 = inc.u1
    w1 = inc.w1
    act = batch.active.astype(jnp.float32)
    conf = jnp.zeros(batch.active.shape, jnp.float32)
    # total column masses once, per-group masses by masked einsum
    tot_w = jnp.einsum("bk,b->k", w1, act,
                       preferred_element_type=jnp.float32)
    tot_u = jnp.einsum("bk,b->k", u1, act,
                       preferred_element_type=jnp.float32)
    for g in range(n_groups):
        m = (act * (group == g)).astype(jnp.float32)
        oth_w = tot_w - jnp.einsum("bk,b->k", w1, m,
                                   preferred_element_type=jnp.float32)
        oth_u = tot_u - jnp.einsum("bk,b->k", u1, m,
                                   preferred_element_type=jnp.float32)
        # my accesses vs other groups' writes + my writes vs other
        # groups' accesses (0.5 threshold absorbs bf16 rounding, same
        # margin as cc/base.conflict_density)
        c_g = (jnp.einsum("bk,k->b", u1, oth_w,
                          preferred_element_type=jnp.float32)
               + jnp.einsum("bk,k->b", w1, oth_u,
                            preferred_element_type=jnp.float32))
        conf = jnp.where(group == g, c_g, conf)
    return batch.active & (conf > 0.5)
