"""OCC — Kung-Robinson backward validation (reference `concurrency_control/occ.{h,cpp}`).

The reference copies rows on access (`storage/row.cpp:283-290`) and runs
*central* validation under a global semaphore: a committing txn's read set
is checked against the write sets of txns that committed during its
execution window, and against concurrently-validating writers
(`occ.cpp:116-239`); committed write sets are appended to a history list
(`central_finish` `:248-294`).

Batch semantics collapse the execution window to the epoch: every txn read
the epoch-start snapshot, so validation against *prior* epochs passes
vacuously (their writes were all applied before the snapshot — the
reference prunes its history list with ``his_oldest_active_tn`` the same
way).  Within the epoch, serial validation in rank order admits txn i iff
no already-admitted j has ``W_j ∩ (R_i ∪ W_i) ≠ ∅`` — the Kung-Robinson
serial-equivalence test with j's writes "after" i's snapshot reads.  That
is the lex-first MIS sweep over the *directed* U-vs-W overlap.

Like the reference's central validation, the whole epoch validates in one
place — except "one place" is the MXU, and the critical section is a
matmul instead of a semaphore.
"""

from __future__ import annotations

import jax.numpy as jnp

from deneva_tpu.cc.base import AccessBatch, Incidence, Verdict, get_overlap
from deneva_tpu.ops import earlier_edges, greedy_first_fit


def validate_occ(cfg, state, batch: AccessBatch, inc: Incidence):
    # directed: my accesses vs their writes (their reads never invalidate me)
    ov = get_overlap(cfg)
    uw = ov(inc.u1, inc.w1, inc.u2, inc.w2)
    e = earlier_edges(uw, batch.rank, batch.active)
    win, lose, und = greedy_first_fit(e, batch.active, rounds=cfg.sweep_rounds)
    v = Verdict(commit=win, abort=lose, defer=und,
                order=batch.rank, level=jnp.zeros_like(batch.rank))
    return v, state
