"""OCC — Kung-Robinson backward validation (reference `concurrency_control/occ.{h,cpp}`).

The reference copies rows on access (`storage/row.cpp:283-290`) and runs
*central* validation under a global semaphore: a committing txn's read set
is checked against the write sets of txns that committed during its
execution window, and against concurrently-validating writers
(`occ.cpp:116-239`); committed write sets are appended to a history list
(`central_finish` `:248-294`).

Batch semantics collapse the execution window to the epoch: every txn read
the epoch-start snapshot, so validation against *prior* epochs passes
vacuously (their writes were all applied before the snapshot — the
reference prunes its history list with ``his_oldest_active_tn`` the same
way).  Within the epoch, serial validation in rank order admits txn i iff
no already-admitted j has ``W_j ∩ (R_i ∪ W_i) ≠ ∅`` — the Kung-Robinson
serial-equivalence test with j's writes "after" i's snapshot reads.  That
is the lex-first MIS sweep over the *directed* U-vs-W overlap.

Like the reference's central validation, the whole epoch validates in one
place — except "one place" is the MXU, and the critical section is a
matmul instead of a semaphore.

Escrow (``order_free``) exemption, gated by ``escrow_order_free`` AND
``escrow_sweep``: a txn's escrow accesses leave its validated set —
``W_j ∩ (R_i ∪ W_i)`` is tested against the ORDERED union ``uo_i`` (the
coarse-granularity false-abort class of arXiv:1811.04967: commutative
deltas against one hot record are not read-write conflicts) — while j's
write set stays FULL, so an ordered read of an accumulator still
invalidates against every admitted add.  Add-add pairs carry no edge and
the executor accumulates all their deltas.  With the gate off ``uo``
aliases ``u`` and validation is bit-identical to Kung-Robinson.
"""

from __future__ import annotations

import jax.numpy as jnp

from deneva_tpu.cc.base import (AccessBatch, Incidence, Verdict,
                                committed_write_frontier, get_overlap)
from deneva_tpu.ops import earlier_edges, greedy_first_fit


def repair_frontier(cfg, state, batch: AccessBatch, inc: Incidence,
                    committed, losers):
    """OCC invalidation rule (transaction repair, engine/repair.py):
    read-set vs winner write-set.  A Kung-Robinson loser aborted because
    an admitted j's writes intersected its validated set; the READ half
    of that intersection is what made its execution stale — those reads
    observed the epoch-start snapshot where they should have seen j's
    value.  Re-executing them against the post-winner state moves the
    loser's serialization point after every winner, after which the
    repair sub-round re-runs this module's own serial-admission test
    restricted to the losers (``validate_occ`` on the loser-masked
    batch) — the same validation, one snapshot later.  Write-only
    intersections need no re-read (blind writes recompute); they show up
    as an EMPTY frontier and salvage in the first sub-round."""
    return committed_write_frontier(cfg, batch, inc, committed, losers)


def validate_occ(cfg, state, batch: AccessBatch, inc: Incidence):
    # directed: my ORDERED accesses vs their writes (their reads never
    # invalidate me; my escrow deltas commute with their writes' deltas
    # and an ordered write of theirs on the same key appears in their uo
    # for the mirrored pair, which earlier_edges then directs)
    ov = get_overlap(cfg)
    uo1 = inc.u1 if inc.uo1 is None else inc.uo1
    uo2 = inc.u2 if inc.uo1 is None else inc.uo2
    uw = ov(uo1, inc.w1, uo2, inc.w2)
    e = earlier_edges(uw, batch.rank, batch.active)
    win, lose, und = greedy_first_fit(e, batch.active, rounds=cfg.sweep_rounds)
    v = Verdict(commit=win, abort=lose, defer=und,
                order=batch.rank, level=jnp.zeros_like(batch.rank))
    return v, state
