"""DGCC: dependency-graph wavefront execution backend (CC_ALG=DGCC).

PAPERS: *DGCC: A New Dependency Graph based Concurrency Control
Protocol* (arXiv:1503.03642) — separate dependency resolution from
execution: build the epoch's transaction dependency graph FIRST, then
execute along it, so conflicting transactions serialize instead of
aborting.  Every optimistic backend here detects conflicts after
execution and pays for them with aborts (OCC zipf-0.9 write-heavy:
0.842 abort rate even with repair, `results/repair`); DGCC runs the
audit plane's edge-derivation kernel (`cc/depgraph.py` — one exact-key
lane sort + segmented scans, zero bucket-collision false conflicts)
over the PLANNED access sets of all active txns and assigns each txn an
execution wave, the chained-level machinery CALVIN/TPU_BATCH already
execute through (`engine/step._run_levels`, the repair engine's
re-execution waves generalized): wave k re-reads only rows written by
waves < k.  Near-zero aborts by construction: the only non-commit
outcome is a DEFER of over-deep dependency closures to the next epoch's
retry queue — exactly repair's cyclic fallback, with no abort penalty.

Wave assignment (level relaxation, iterated segmented max over
predecessor levels):

* lanes: every ordered access doubles into a read lane (position
  ``2*r``) and/or a write lane (position ``2*r + 1``) where ``r`` is the
  txn's dense arrival rank — reads sit BELOW the same txn's writes, the
  executor's serial-in-rank gather-then-scatter semantics.
* per round, two exclusive segmented maxima over each key segment
  (`depgraph.seg_excl_max`) relax every txn's wave:
  -  wr/ww TRUE dependency: a READ lane must land strictly after every
     earlier writer of its key — ``lv >= max(earlier writer lv) + 1``;
  -  rw ANTI-dependency: a WRITE lane must not land before an earlier
     reader or writer of its key — ``lv >= max(earlier reader/writer
     lv)`` with NO increment: within one wave the executor gathers all
     reads before scattering writes, and same-wave duplicate writes
     resolve by the ``last_writer`` order tournament (the wavefront
     executor runs the tournament path, not the conflict-free
     ``level_exec`` fast path) — so a same-wave earlier-reader or
     earlier-writer is already serialized correctly.
* iterate to fixpoint (`lax.while_loop`), with candidates CLAMPED at
  the ``Config.dgcc_levels`` wave budget.  Each +1 hop needs its
  predecessor's updated value (~2 rounds per read-after-write
  alternation) but same-level propagation is instantaneous (the scans
  span whole key segments), so an un-clamped chain of true depth d
  converges in ~2d rounds — and the clamp makes saturation itself
  propagate segment-wide in O(1) rounds, bounding convergence at
  ~2*dgcc_levels however deep the hot-key chain really is (the
  ``2 * rounds + 4`` loop budget).  At the fixpoint, levels BELOW the
  clamp are exact longest-path waves and commit; saturated txns
  (``lv >= dgcc_levels`` — over-deep closures, and transitively
  everything downstream of one: a dependent of a saturated txn
  saturates too, so committed waves never read a hole) fall to the
  DEFER retry queue with ``abort`` kept zero.  A fixpoint miss inside
  even that budget (never observed; the anti-inert smoke scenario
  covers the deep-chain regime) defers the whole epoch — sound,
  non-localizable on device.

Escrow (``order_free``) lanes are exempt: commutative deltas carry no
ordering claim, contribute no lanes, and commit in wave 0 — the same
exemption the audit plane and `committed_write_frontier` apply.

The verdict is a pure replicated function of the merged batch (sort +
scans + scatter-max, no RNG, no cross-epoch state), so merged-mode
cluster nodes and mesh shards (dp>1) reproduce it bit-identically —
the cluster path ships the verdict exactly like CALVIN's.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deneva_tpu.cc import depgraph
from deneva_tpu.cc.base import AccessBatch, Verdict
from deneva_tpu.ops import combine_key


def dgcc_levels(cfg, batch: AccessBatch):
    """Wave assignment: returns ``(lv, overflow, edge_cnt)`` — int32[B]
    exact wave per txn, bool[B] defer mask (over-deep closures plus, on
    a cut-short relaxation, every active txn), and the dependency-edge
    count of the epoch's nearest-predecessor graph (the [dgcc] line's
    density signal)."""
    b, a = batch.shape
    act = batch.valid & batch.active[:, None]
    if batch.order_free is not None:
        act = act & ~batch.order_free
    rm = act & batch.is_read
    wm = act & batch.is_write
    ident = combine_key(batch.table_ids, batch.keys)
    big = jnp.uint32(depgraph.LANE_PAD)

    # dense arrival positions over ACTIVE txns (stable iota tiebreak),
    # doubled so a txn's read lanes precede its own write lanes
    okey = jnp.where(batch.active, batch.rank, jnp.int32(2**31 - 1))
    perm = jnp.argsort(okey, stable=True)
    dpos = jnp.zeros((b,), jnp.int32).at[perm].set(
        jnp.arange(b, dtype=jnp.int32))
    rpos = dpos * 2
    wpos = dpos * 2 + 1

    tid = jnp.broadcast_to(jnp.arange(b, dtype=jnp.int32)[:, None],
                           (b, a))
    keys2 = jnp.concatenate([jnp.where(rm, ident, big).reshape(-1),
                             jnp.where(wm, ident, big).reshape(-1)])
    pos2 = jnp.concatenate([
        jnp.broadcast_to(rpos[:, None], (b, a)).reshape(-1),
        jnp.broadcast_to(wpos[:, None], (b, a)).reshape(-1)])
    tid2 = jnp.concatenate([tid.reshape(-1), tid.reshape(-1)])
    sk, sp, sid = depgraph.lane_sort(keys2, pos2, tid2)
    sw = (sp & 1) == 1
    live = sk != big
    head, _tail = depgraph.segment_bounds(sk)

    # static edge census: lanes with a nearest preceding writer (wr/ww)
    # plus write lanes with a nearest preceding reader (rw).  Self-preds
    # (duplicate lanes of one txn) carry no ordering constraint.
    pw = depgraph.prev_writer(head, jnp.where(sw & live, sid,
                                              jnp.int32(-1)))
    pr = depgraph.prev_writer(head, jnp.where(~sw & live, sid,
                                              jnp.int32(-1)))
    dep = live & (((pw >= 0) & (pw != sid))
                  | (sw & (pr >= 0) & (pr != sid)))
    edge_cnt = dep.sum(dtype=jnp.int32)

    rounds = jnp.int32(max(1, cfg.dgcc_levels))

    def relax(lv):
        g = jnp.take(lv, sid)
        exw = depgraph.seg_excl_max(head, jnp.where(sw & live, g,
                                                    jnp.int32(-1)))
        exr = depgraph.seg_excl_max(head, jnp.where(~sw & live, g,
                                                    jnp.int32(-1)))
        # clamp at the wave budget: saturation then propagates like a
        # same-level hop (full-prefix max), so deep chains converge in
        # O(rounds) iterations instead of O(chain length) — and every
        # dependent of a saturated txn saturates with it
        cand = jnp.minimum(jnp.where(
            sw,
            jnp.maximum(jnp.maximum(exw, exr), 0),
            jnp.where(exw >= 0, exw + 1, 0)), rounds)
        return lv.at[sid].max(jnp.where(live, cand, 0))

    def cond(c):
        _lv, changed, i = c
        return changed & (i < 2 * rounds + 4)

    def body(c):
        lv, _changed, i = c
        lv2 = relax(lv)
        return lv2, (lv2 != lv).any(), i + 1

    lv0 = jnp.zeros((b,), jnp.int32)
    lv, changed, _i = jax.lax.while_loop(
        cond, body, (lv0, jnp.bool_(True), jnp.int32(0)))

    # at the fixpoint, sub-clamp levels are exact longest-path waves:
    # commit them; saturated txns are the over-deep closures (plus
    # everything downstream of one) — the cyclic-fallback DEFER.  A
    # fixpoint miss inside even the 2*rounds+4 budget cannot be
    # localized on device, so the whole epoch retries (never observed;
    # the anti-inert smoke scenario covers the deep-chain regime).
    deep = lv >= rounds
    overflow = batch.active & (deep | changed)
    return lv, overflow, edge_cnt


def validate_dgcc(cfg, state, batch: AccessBatch, inc=None, stats=None):
    """DGCC verdict: commit everything whose dependency closure fits the
    wave budget, DEFER the rest to the next epoch (abort stays zero —
    the near-zero-abort claim is by construction, pinned by the smoke
    gate's anti-inert scenario).  ``inc`` is unused: the lane graph is
    exact-key, so watermark coarsening and bucket incidence never
    inflate the wavefront.  ``stats``, when passed by the engine,
    accumulates the [dgcc] summary counters in place (the repair-engine
    stats contract)."""
    b, _a = batch.shape
    lv, overflow, edge_cnt = dgcc_levels(cfg, batch)
    commit = batch.active & ~overflow
    zeros = jnp.zeros((b,), bool)
    level = jnp.where(commit, lv, 0)
    if stats is not None:
        waves = (jnp.max(jnp.where(commit, level, -1))
                 + 1).astype(jnp.uint32)
        stats["dgcc_wave_cnt"] = stats["dgcc_wave_cnt"] + waves
        stats["dgcc_wave_max"] = jnp.maximum(stats["dgcc_wave_max"],
                                             waves)
        stats["dgcc_fallback_cnt"] = (
            stats["dgcc_fallback_cnt"]
            + overflow.sum(dtype=jnp.uint32))
        stats["dgcc_edge_cnt"] = (stats["dgcc_edge_cnt"]
                                  + edge_cnt.astype(jnp.uint32))
    return Verdict(commit=commit, abort=zeros,
                   defer=batch.active & overflow,
                   order=batch.rank, level=level), state
