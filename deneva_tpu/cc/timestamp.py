"""TIMESTAMP (basic T/O) and MVCC (reference `concurrency_control/row_ts.{h,cpp}`,
`row_mvcc.{h,cpp}`).

The reference tracks per-row ``wts``/``rts`` watermarks plus buffered
read/prewrite/write request lists (`row_ts.cpp:63-80`), and MVCC keeps
per-row version histories GC'd against the global min-ts
(`row_mvcc.cpp:303-321`, `system/manager.cpp:71-80`).

Batch mapping.  Cross-epoch watermarks live in per-*bucket* tables
``rts[K]/wts[K]`` (max-aggregated over the keys hashing there — an
over-approximation that can only add aborts, never hide one; the analogue
of the reference's hash-bucketed TimeTable for MAAT).  Within an epoch all
reads observe the epoch-start snapshot, so the only intra-epoch violation
is a *reader ordered after a committing writer* (ts_r > ts_w): the reader
should have seen the writer's value but read the snapshot.  Those RW pairs
are swept in timestamp order and the later reader **waits** — the batch
analogue of the reference parking the read on the row until the prewrite
drains (`row_ts.cpp:63-80` buffer_req / `row_mvcc.cpp:252-258`): the
reader defers with its timestamp intact, and next epoch the writer's value
is the committed snapshot, which the reader then reads — exactly the value
the reference's woken waiter gets.  Writer-after-read pairs serialize
reader-first for free; blind write-write pairs both commit with
last-writer-wins application — Thomas' write rule, exact because
``Verdict.order = ts``.

TIMESTAMP rules (abort conditions, cross-epoch):
* read k:  ``wts[k] > ts``  — value from my future already committed
  (`row_ts.cpp` aborts the same read; we cannot time-travel either).
* write k: ``rts[k] > ts`` or ``wts[k] > ts`` — a future read/write
  already committed against the old value.

MVCC (multi-version) differences:
* Read-only transactions *always commit*: they serialize at the snapshot
  point (reads of old versions never conflict) — the multi-version win,
  mirroring the reference's read-only fast path (`system/txn.cpp:498-530`)
  made unconditional.
* Pure reads of read-write txns serve **old versions**: a per-bucket ring
  of the last ``mvcc_his_len`` version-boundary timestamps (the
  HIS_RECYCLE_LEN-bounded write history, `row_mvcc.cpp:172-196,303-321`)
  decides whether the version a stale read needs is still retained — the
  read commits iff ``ts >= min(ring)`` (the oldest retained boundary;
  version at boundary w serves reads in [w, next boundary)).  Reads older
  than the retained history abort, exactly like the reference's recycled
  versions.  Version boundaries are recorded at epoch granularity (one
  boundary per bucket per epoch — within an epoch the table has a single
  committed state, so finer boundaries are unobservable).
* RMW accesses (read & write of one key) must read latest: ``wts[k] > ts``
  still aborts — serving an old version to a read-modify-write would
  corrupt the newer committed value.
* Old-version *payloads* are materialized per row: the workload's
  version-value ring (`storage.table.VersionRing`, wired in
  `workloads/ycsb.py`) records the bytes each committed write overwrote,
  and a committed stale read gathers the version current at its ts —
  matching `row_mvcc.cpp:172-196` value-for-value (oracle:
  `tests/test_cc.py::test_mvcc_serves_historical_bytes`).  The bucket
  boundary ring here makes the retention DECISION; its commit rule
  (``ts >= min(ring)``) guarantees the per-row ring still holds the
  needed version (at most H-1 boundaries, hence at most H-1 per-row
  overwrites, can exceed a servable ts).  TPC-C/PPS need NO value
  rings to be value-exact (round-4, oracle-proven): every gather their
  executors perform is (a) a load-immutable column (W_TAX / D_TAX /
  C_DISCOUNT; USES/SUPPLIES mappings), (b) an RMW read, which this
  module only permits at the latest version (``wts > ts`` aborts), or
  (c) a read-only txn's gather, whose serialization point IS the epoch
  snapshot it reads — so the live gather is the correct version in
  every committed case
  (`tests/test_tpcc.py::test_mvcc_reads_byte_match_serial_oracle`,
  `tests/test_pps.py::test_mvcc_getpart_reads_snapshot_values`).

Timestamps are epoch-fresh on restart exactly as the reference re-stamps
restarted txns (`system/worker_thread.cpp:492-508`); deferred (waiting)
txns keep their birth ts like the reference's parked requests.

Escrow (``order_free``) rules, gated by ``escrow_order_free`` AND
``escrow_sweep`` (``batch.order_free`` arrives pre-gated — None gives
bit-identical pre-escrow behavior).  An escrow WRITE is a commutative
delta: deltas reorder freely among themselves (their sum is
order-invariant — the escrow guarantee of O'Neil's escrow method /
DGCC's commutative decomposition, arXiv:1503.03642), so
* escrow writes skip the ``wts > ts`` check — an older delta landing
  after a newer delta is not a violation — but KEEP the ``rts > ts``
  check: a committed ORDERED read at higher ts already fixed the
  accumulator value it observed, and a delta slotting before it in ts
  order would invalidate that read;
* escrow writes still RECORD ``wts`` so later ordered readers at lower
  ts correctly abort (they missed a delta in their ts-past);
* escrow READS (declared immutable columns) check nothing and record no
  ``rts`` — a false rts from the accumulator's row bucket would
  re-floor the adds.  Intra-epoch reader-wait edges likewise come from
  the ORDERED read incidence (`overlap(ro, w)`).
Consequence stated honestly: escrow deltas serialize in COMMIT order,
not ts order (two deltas committed in different epochs apply in epoch
order however their ts compare).  Sums, D_NEXT_O_ID uniqueness/density
and every ordered read stay exact — the equivalence is modulo
commutativity, which is the escrow contract.  Workloads must not mix
ordered writes into order_free columns (none do; the executors apply
deltas unconditionally).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from deneva_tpu.cc.base import AccessBatch, Incidence, Verdict, get_overlap
from deneva_tpu.ops import (bucket_hash, combine_key, earlier_edges,
                            greedy_first_fit)


def _wm_bucket(cfg, batch: AccessBatch) -> jax.Array:
    """Per-access bucket ids in the WATERMARK hash space.  Decoupled from
    the incidence bucket space: watermark tables are O(K) memory, so they
    run much wider (``watermark_buckets``) than the O(B*K) incidence
    matrices can afford — per-bucket max-aggregation stays a sound
    over-approximation of the reference's per-row ts state, with false
    sharing driven toward zero."""
    ident = combine_key(batch.table_ids, batch.keys)
    return bucket_hash(ident, cfg.watermark_buckets, family=0)


@dataclass
class TOState:
    """Per-bucket committed watermarks (family-0 hash space)."""

    rts: jax.Array   # int32[K] max committed read ts
    wts: jax.Array   # int32[K] max committed write ts


jax.tree_util.register_dataclass(TOState, data_fields=["rts", "wts"],
                                 meta_fields=[])


@dataclass
class MVCCState:
    """TOState plus the bounded version-boundary ring (write history)."""

    rts: jax.Array   # int32[K]
    wts: jax.Array   # int32[K]
    his: jax.Array   # int32[K, H] recent version-boundary ts (0 = the
    #                  load-time base version, retained until overwritten)
    pos: jax.Array   # int32[K] next ring slot per bucket


jax.tree_util.register_dataclass(
    MVCCState, data_fields=["rts", "wts", "his", "pos"], meta_fields=[])


def init_to_state(cfg) -> TOState:
    k = cfg.watermark_buckets
    return TOState(rts=jnp.zeros((k,), jnp.int32),
                   wts=jnp.zeros((k,), jnp.int32))


def init_mvcc_state(cfg) -> MVCCState:
    k, h = cfg.watermark_buckets, cfg.mvcc_his_len
    return MVCCState(rts=jnp.zeros((k,), jnp.int32),
                     wts=jnp.zeros((k,), jnp.int32),
                     his=jnp.zeros((k, h), jnp.int32),
                     pos=jnp.zeros((k,), jnp.int32))


def _readonly(batch: AccessBatch) -> jax.Array:
    """bool[B]: read-only txns.  Prefers the GLOBAL ``ro_hint`` (set by
    the distributed VOTE prepare, whose valid mask covers only locally
    owned accesses) over the local derivation."""
    if batch.ro_hint is not None:
        return batch.ro_hint
    v = batch.valid & batch.active[:, None]
    return ~(v & batch.is_write).any(axis=1)


def _stale_read_lanes(cfg, state, batch: AccessBatch,
                      mvcc: bool) -> jax.Array:
    """bool[B, A]: read lanes violating the cross-epoch ``wts`` watermark
    at the txn's CURRENT ts (the read half of ``_watermark_aborts``,
    exposed per access so the repair frontier can name exactly which
    reads went stale).  Escrow reads are exempt per the module
    docstring."""
    wm = _wm_bucket(cfg, batch)
    v = batch.valid & batch.active[:, None]
    wts_at = jnp.take(state.wts, wm)                   # [B, A]
    ts = batch.ts[:, None]
    if mvcc:
        # pure reads serve the retained version at their ts; only reads
        # older than the bounded history (version recycled,
        # row_mvcc.cpp:303-321) or RMW reads (must read latest) abort
        his_min = jnp.take(state.his.min(axis=1), wm)
        pure = batch.is_read & ~batch.is_write
        rmw = batch.is_read & batch.is_write
        read_bad = v & ((pure & (wts_at > ts) & (ts < his_min))
                        | (rmw & (wts_at > ts)))
    else:
        read_bad = v & batch.is_read & (wts_at > ts)
    if batch.order_free is not None:
        # escrow reads check nothing (declared-immutable columns)
        read_bad = read_bad & ~batch.order_free
    return read_bad


def _watermark_aborts(cfg, state, batch: AccessBatch,
                      mvcc: bool) -> jax.Array:
    """bool[B]: txn violates a cross-epoch watermark (escrow accesses
    follow the relaxed rules in the module docstring)."""
    wm = _wm_bucket(cfg, batch)
    v = batch.valid & batch.active[:, None]
    wts_at = jnp.take(state.wts, wm)                   # [B, A]
    rts_at = jnp.take(state.rts, wm)
    ts = batch.ts[:, None]
    read_bad = _stale_read_lanes(cfg, state, batch, mvcc)
    if batch.order_free is None:
        write_bad = v & batch.is_write & ((rts_at > ts) | (wts_at > ts))
    else:
        # escrow writes (deltas) check only rts — deltas commute with
        # prior deltas, never with a committed ordered read whose
        # ts-past they would rewrite
        write_bad = v & batch.is_write & jnp.where(
            batch.order_free, rts_at > ts, (rts_at > ts) | (wts_at > ts))
    bad = (read_bad | write_bad).any(axis=1)
    if mvcc:
        bad = bad & ~_readonly(batch)       # read-only: snapshot
    return bad


def _repair_frontier(cfg, state, batch: AccessBatch, inc: Incidence,
                     committed, losers, mvcc: bool):
    """T/O invalidation rule (transaction repair, engine/repair.py):
    the wts/rts watermark re-check.  A T/O loser is a watermark
    violator — its birth ts sits in the PAST of committed state (a
    value "from its future" was already on disk), which whole-txn retry
    fixes by restamping next epoch.  Repair restamps NOW: the frontier
    is the union of (a) this epoch's winner overwrites of the loser's
    ordered reads (the generic bucket frontier) and (b) the cross-epoch
    stale-read lanes that caused the abort (``wts_at > birth ts``, the
    per-access view of ``_watermark_aborts``).  The repair sub-round
    then re-runs this module's validate at a fresh ts above every stamp
    in the epoch — the same watermark check, which now passes exactly
    when the re-read serves the committed value, and the same
    later-reader-waits sweep restricted to the losers.  Repaired
    commits record watermarks at the fresh ts, so a second sub-round's
    reader of a first-sub-round write re-checks against it (and falls
    back to the retry queue if its own stamp is older — conservative,
    never a wrong commit)."""
    from deneva_tpu.cc.base import committed_write_frontier
    base = committed_write_frontier(cfg, batch, inc, committed, losers)
    stale = _stale_read_lanes(cfg, state, batch, mvcc) & losers[:, None]
    return base | stale


def repair_frontier_timestamp(cfg, state, batch, inc, committed, losers):
    return _repair_frontier(cfg, state, batch, inc, committed, losers,
                            mvcc=False)


def repair_frontier_mvcc(cfg, state, batch, inc, committed, losers):
    return _repair_frontier(cfg, state, batch, inc, committed, losers,
                            mvcc=True)


def _rw_later_reader_edges(cfg, batch: AccessBatch, inc: Incidence):
    """E[i,j]: ORDERED reader i (by ts) after writer j on a common key
    (ro aliases r when no escrow exemption applies: declared-immutable
    column reads never wait behind the row's delta writers)."""
    ro1 = inc.r1 if inc.ro1 is None else inc.ro1
    ro2 = inc.r2 if inc.ro1 is None else inc.ro2
    rw = get_overlap(cfg)(ro1, inc.w1, ro2, inc.w2)    # i reads ∩ j writes
    return earlier_edges(rw, batch.ts, batch.active)   # j earlier by ts


def _commit_watermarks(cfg, state, batch: AccessBatch,
                       commit: jax.Array):
    v = batch.valid & commit[:, None]
    ts = jnp.broadcast_to(batch.ts[:, None], batch.keys.shape)
    # escrow reads record no rts (immutable columns; a false rts from
    # the row's shared bucket would abort the row's own deltas); escrow
    # WRITES still record wts so stale ordered readers abort
    r_rec = v & batch.is_read if batch.order_free is None \
        else v & batch.is_read & ~batch.order_free
    r_ts = jnp.where(r_rec, ts, 0)
    w_ts = jnp.where(v & batch.is_write, ts, 0)
    flat = _wm_bucket(cfg, batch).reshape(-1)
    rts = state.rts.at[flat].max(r_ts.reshape(-1))
    wts = state.wts.at[flat].max(w_ts.reshape(-1))
    if not isinstance(state, MVCCState):
        return TOState(rts=rts, wts=wts)
    # record this epoch's version boundary per written bucket: the ring
    # keeps the last H boundaries (bounded write history); epoch
    # granularity is exact because the table exposes one committed state
    # per epoch
    epoch_w = jnp.zeros_like(state.wts).at[flat].max(w_ts.reshape(-1))
    wrote = epoch_w > 0
    h = state.his.shape[1]
    slot = jnp.arange(h, dtype=jnp.int32)[None, :] == state.pos[:, None]
    his = jnp.where(wrote[:, None] & slot, epoch_w[:, None], state.his)
    pos = jnp.where(wrote, (state.pos + 1) % h, state.pos)
    return MVCCState(rts=rts, wts=wts, his=his, pos=pos)


def _validate_to(cfg, state, batch, inc, mvcc: bool):
    wm_abort = _watermark_aborts(cfg, state, batch, mvcc)
    live = batch.active & ~wm_abort
    if mvcc:
        ro = _readonly(batch)
    else:
        ro = jnp.zeros(batch.active.shape, bool)
    # read-only MVCC txns leave the conflict graph entirely
    swept = live & ~ro
    e = _rw_later_reader_edges(cfg, batch, inc)
    e = e & swept[:, None] & swept[None, :]
    win, lose, und = greedy_first_fit(e, swept, rounds=cfg.sweep_rounds)
    commit = win | (live & ro)
    # MVCC read-only txns serialize AT the snapshot: order them before
    # every epoch writer (ts are >= 1), so duplicate-write resolution and
    # the serializability oracle see reader-first order.
    order = jnp.where(ro, 0, batch.ts)
    # a swept-out later reader WAITS (buffered read, row_ts.cpp:63-80):
    # defer with ts intact — next epoch the writer's value is committed
    # state and the read proceeds.  Only watermark violations abort.
    v = Verdict(commit=commit, abort=batch.active & wm_abort,
                defer=und | lose, order=order,
                level=jnp.zeros_like(batch.rank))
    return v, _commit_watermarks(cfg, state, batch, commit)


def commit_to_state(cfg, state, batch: AccessBatch, inc, commit: jax.Array):
    """Post-decision watermark application for the distributed VOTE
    protocol: local validation's state output is discarded and the
    watermarks advance only for *globally* committed txns (the
    reference's row managers likewise update ts state on the 2PC commit
    path, not at prepare).  ``inc`` is unused (watermark buckets are
    self-hashed) and kept for the hook signature."""
    return _commit_watermarks(cfg, state, batch, commit)


def validate_timestamp(cfg, state, batch: AccessBatch, inc: Incidence):
    return _validate_to(cfg, state, batch, inc, mvcc=False)


def validate_mvcc(cfg, state, batch: AccessBatch, inc: Incidence):
    return _validate_to(cfg, state, batch, inc, mvcc=True)
