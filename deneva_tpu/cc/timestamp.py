"""TIMESTAMP (basic T/O) and MVCC (reference `concurrency_control/row_ts.{h,cpp}`,
`row_mvcc.{h,cpp}`).

The reference tracks per-row ``wts``/``rts`` watermarks plus buffered
read/prewrite/write request lists (`row_ts.cpp:63-80`), and MVCC keeps
per-row version histories GC'd against the global min-ts
(`row_mvcc.cpp:303-321`, `system/manager.cpp:71-80`).

Batch mapping.  Cross-epoch watermarks live in per-*bucket* tables
``rts[K]/wts[K]`` (max-aggregated over the keys hashing there — an
over-approximation that can only add aborts, never hide one; the analogue
of the reference's hash-bucketed TimeTable for MAAT).  Within an epoch all
reads observe the epoch-start snapshot, so the only intra-epoch violation
is a *reader ordered after a committing writer* (ts_r > ts_w): the reader
should have seen the writer's value but read the snapshot.  Those RW pairs
are swept in timestamp order and the later reader loses.  Writer-after-read
pairs serialize reader-first for free; blind write-write pairs both commit
with last-writer-wins application — Thomas' write rule, exact because
``Verdict.order = ts``.

TIMESTAMP rules (abort conditions):
* read k:  ``wts[k] > ts``  — value from my future already committed
  (`row_ts.cpp` aborts the same read; we cannot time-travel either).
* write k: ``rts[k] > ts`` or ``wts[k] > ts`` — a future read/write
  already committed against the old value.

MVCC differences:
* Read-only transactions *always commit*: they serialize at the snapshot
  point (reads of old versions never conflict) — the multi-version win,
  mirroring the reference's read-only fast path (`system/txn.cpp:498-530`)
  made unconditional.
* Reads of read-write txns still abort on ``wts[k] > ts``: the version the
  read needs exists in the reference's history list but this build keeps
  single-version tables (device memory economics, SURVEY §7); the case
  only arises for txns that kept a stale ts across epochs, and a restart
  refreshes ts.  Conservative, documented divergence.

Timestamps are epoch-fresh on restart exactly as the reference re-stamps
restarted txns (`system/worker_thread.cpp:492-508`).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from deneva_tpu.cc.base import AccessBatch, Incidence, Verdict, get_overlap
from deneva_tpu.ops import earlier_edges, greedy_first_fit


@dataclass
class TOState:
    """Per-bucket committed watermarks (family-0 hash space)."""

    rts: jax.Array   # int32[K] max committed read ts
    wts: jax.Array   # int32[K] max committed write ts


jax.tree_util.register_dataclass(TOState, data_fields=["rts", "wts"],
                                 meta_fields=[])


def init_to_state(cfg) -> TOState:
    k = cfg.conflict_buckets
    return TOState(rts=jnp.zeros((k,), jnp.int32),
                   wts=jnp.zeros((k,), jnp.int32))


def _watermark_aborts(state: TOState, batch: AccessBatch, inc: Incidence,
                      mvcc: bool) -> jax.Array:
    """bool[B]: txn violates a cross-epoch watermark."""
    v = batch.valid & batch.active[:, None]
    wts_at = jnp.take(state.wts, inc.bucket1)          # [B, A]
    rts_at = jnp.take(state.rts, inc.bucket1)
    ts = batch.ts[:, None]
    read_bad = v & batch.is_read & (wts_at > ts)
    write_bad = v & batch.is_write & ((rts_at > ts) | (wts_at > ts))
    bad = (read_bad | write_bad).any(axis=1)
    if mvcc:
        ro = ~(v & batch.is_write).any(axis=1)         # read-only: snapshot
        bad = bad & ~ro
    return bad


def _rw_later_reader_edges(cfg, batch: AccessBatch, inc: Incidence):
    """E[i,j]: reader i (by ts) ordered after writer j on a common key."""
    rw = get_overlap(cfg)(inc.r1, inc.w1, inc.r2, inc.w2)       # i reads ∩ j writes
    return earlier_edges(rw, batch.ts, batch.active)   # j earlier by ts


def _commit_watermarks(state: TOState, batch: AccessBatch, inc: Incidence,
                       commit: jax.Array) -> TOState:
    v = batch.valid & commit[:, None]
    ts = jnp.broadcast_to(batch.ts[:, None], batch.keys.shape)
    r_ts = jnp.where(v & batch.is_read, ts, 0)
    w_ts = jnp.where(v & batch.is_write, ts, 0)
    flat = inc.bucket1.reshape(-1)
    return TOState(rts=state.rts.at[flat].max(r_ts.reshape(-1)),
                   wts=state.wts.at[flat].max(w_ts.reshape(-1)))


def _validate_to(cfg, state, batch, inc, mvcc: bool):
    wm_abort = _watermark_aborts(state, batch, inc, mvcc)
    live = batch.active & ~wm_abort
    if mvcc:
        v = batch.valid & batch.active[:, None]
        ro = ~(v & batch.is_write).any(axis=1)
    else:
        ro = jnp.zeros(batch.active.shape, bool)
    # read-only MVCC txns leave the conflict graph entirely
    swept = live & ~ro
    e = _rw_later_reader_edges(cfg, batch, inc)
    e = e & swept[:, None] & swept[None, :]
    win, lose, und = greedy_first_fit(e, swept, rounds=cfg.sweep_rounds)
    commit = win | (live & ro)
    # MVCC read-only txns serialize AT the snapshot: order them before
    # every epoch writer (ts are >= 1), so duplicate-write resolution and
    # the serializability oracle see reader-first order.
    order = jnp.where(ro, 0, batch.ts)
    v = Verdict(commit=commit, abort=(batch.active & wm_abort) | lose,
                defer=und, order=order, level=jnp.zeros_like(batch.rank))
    return v, _commit_watermarks(state, batch, inc, commit)


def validate_timestamp(cfg, state, batch: AccessBatch, inc: Incidence):
    return _validate_to(cfg, state, batch, inc, mvcc=False)


def validate_mvcc(cfg, state, batch: AccessBatch, inc: Incidence):
    return _validate_to(cfg, state, batch, inc, mvcc=True)
