"""Dependency-graph primitives: the epoch's exact-key conflict graph as
one lane sort + segmented scans (PR 15's audit kernel, promoted out of
the audit plane into a first-class pre-commit primitive).

The audit plane (`cc/base.audit_observe`) already derives the ww/wr/rw
dependency graph of an epoch ON DEVICE: double every access into a read
lane and a write lane, sort the lanes by (exact combined key, visibility
position), and the nearest preceding/following WRITER of each lane —
two segmented scans — names every dependency edge with zero
bucket-collision false positives.  That machinery is useful *before*
commit too (PAPERS: *DGCC: A New Dependency Graph based Concurrency
Control Protocol*, arXiv:1503.03642 — the protocol IS "build the
dependency graph first, then execute along it"), so the kernel pieces
live here, shared verbatim by three consumers:

* the isolation audit plane (`cc/base._audit_observe_impl`) — post-
  commit observation under the backend's visibility rule; this refactor
  reproduces its edge stream bit for bit (pinned by the existing audit
  tests: every helper keeps the exact op sequence the audit kernel
  compiled before the move);
* the DGCC wavefront backend (`cc/dgcc.py`) — the same sort/scan over
  the PLANNED access sets of all active txns assigns execution waves
  pre-commit, turning would-be aborts into chained commits;
* MVCC's per-read observed-version export (`version_select`) — the
  audit-plane headroom item: a read's observed version is selected by
  its timestamp from the bucket's version-boundary ring, not assumed to
  be the last committed stamp.

Layout contract (shared by all consumers): lane positions are int32
with the lane's WRITE-ness encoded as the position's parity (write
positions odd, read positions even), so the sort carries no extra
operand and `(pos & 1) == 1` recovers write-ness after the sort —
CPU XLA's comparator sort charges per operand (see the audit kernel's
measurement note).  Inactive lanes carry the key sentinel ``LANE_PAD``
and sort to the tail.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deneva_tpu.ops.forward import _seg_scan, _shift1

# key sentinel for dead lanes (uint32 max: sorts after every real
# combined key in either signedness interpretation the callers use)
LANE_PAD = 0xFFFFFFFF

# last-write carry for the prev/next-writer scans: keep the newest
# non-negative value seen in the segment
_keep_last = lambda va, v: jnp.where(v >= 0, v, va)  # noqa: E731


def lane_sort(keys, pos, tid):
    """One fused (key, position) lane sort with the owning txn id as
    payload — the dependency-graph workhorse.  ``is_stable=False``:
    ties are (key, pos) duplicates whose relative order no consumer
    observes (write positions are unique per txn; duplicate read lanes
    of one txn are interchangeable)."""
    return jax.lax.sort((keys, pos, tid), num_keys=2, is_stable=False)


def segment_bounds(sk):
    """(head, tail) masks of the key segments of a sorted key array."""
    head = jnp.concatenate([jnp.ones((1,), bool), sk[1:] != sk[:-1]])
    tail = jnp.concatenate([sk[1:] != sk[:-1], jnp.ones((1,), bool)])
    return head, tail


def prev_writer(head, cand):
    """Nearest PRECEDING writer within each key segment.

    ``cand`` holds the txn id on writer lanes, -1 elsewhere; the result
    is exclusive (a lane never sees itself) and -1 when no writer
    precedes the lane in its segment.  Sort order is position order, so
    "preceding" means "strictly lower visibility position"."""
    p = _shift1(_seg_scan(head, cand, _keep_last), jnp.int32(-1))
    return jnp.where(head, jnp.int32(-1), p)


def next_writer(tail, cand):
    """Nearest FOLLOWING writer within each key segment (the reversed
    twin of `prev_writer`; -1 when no writer follows)."""
    n = _shift1(_seg_scan(tail[::-1], cand[::-1], _keep_last),
                jnp.int32(-1))
    return jnp.where(tail[::-1], jnp.int32(-1), n)[::-1]


def seg_excl_max(head, vals, neutral=-1):
    """Exclusive segmented running max: each lane's max over the
    STRICTLY earlier lanes of its segment (``neutral`` at segment
    heads).  The DGCC level-relaxation carry (`cc/dgcc.py`)."""
    m = _shift1(_seg_scan(head, vals, jnp.maximum), jnp.int32(neutral))
    return jnp.where(head, jnp.int32(neutral), m)


def pack_edge(kind, src, dst):
    """Pack a dependency edge as kind<<28 | src<<14 | dst over merged-
    batch ranks (14-bit fields: epoch_batch <= 16384, config.validate)."""
    return (jnp.int32(kind) << 28) | (src << 14) | dst


def edge_kind(e):
    return (e >> 28) & jnp.int32(0xF)


def edge_src(e):
    return (e >> 14) & jnp.int32(0x3FFF)


def edge_dst(e):
    return e & jnp.int32(0x3FFF)


def compact_lanes(flags, payloads, cap):
    """Prefix-sum compaction of flagged lanes into a static-shape export
    buffer: stable (flagged lanes keep their lane order — deterministic,
    so every node emits the identical list; a sort here measured ~60% of
    the audit plane's armed cost on CPU XLA).  Overflow past ``cap``
    lands in the trash slot and is COUNTED, never silent.

    Returns ``(outs, cnt, dropped)``: one int32[cap] array per payload
    (-1 pad), the total flagged-lane count (pre-cap), and the overflow
    count."""
    cnt = flags.sum(dtype=jnp.int32)
    slot = jnp.cumsum(flags.astype(jnp.int32)) - 1
    tgt = jnp.where(flags, jnp.minimum(slot, cap), cap)
    outs = tuple(
        jnp.full((cap + 1,), -1, jnp.int32).at[tgt].set(
            p, mode="drop")[:cap]
        for p in payloads)
    dropped = jnp.maximum(cnt - jnp.int32(cap), 0)
    return outs, cnt, dropped


def version_select(vts, read_ts):
    """Per-read observed-version select: index of the NEWEST ring entry
    whose boundary stamp is <= the reader's timestamp, -1 when no
    retained version is old enough (the reader observed a version from
    before the ring's horizon — epoch-start-of-history).

    ``vts``: int32[..., H] version-boundary timestamps (-1 = empty
    slot); ``read_ts``: int32[...] reader timestamps.  This is MVCC's
    in-ring version-select rule (`cc/timestamp.py`) restated over the
    audit plane's bucket rings, which is exactly what the audit model
    was missing for MVCC: a read at ts t observes the latest version
    bounded by t, NOT the last committed writer."""
    ok = (vts >= 0) & (vts <= read_ts[..., None])
    best = jnp.where(ok, vts, jnp.int32(-1))
    j = jnp.argmax(best, axis=-1).astype(jnp.int32)
    found = jnp.take_along_axis(best, j[..., None], axis=-1)[..., 0] >= 0
    return jnp.where(found, j, jnp.int32(-1))


def witness_count(edges, lvl):
    """Claim-violating dependency edges: packed edges whose BOTH
    endpoints committed at level/round 0.  A level-0 sweep backend's
    Verdict invariant says its committed set is conflict-free — zero
    edges — so any level-0/level-0 edge is certificate pressure (the
    controller's witness-density signal); repair-salvaged endpoints
    (round >= 1) and chained waves carry legitimate edges and are
    excluded by the level test."""
    valid = edges >= 0
    src = jnp.clip(edge_src(edges), 0, lvl.shape[0] - 1)
    dst = jnp.clip(edge_dst(edges), 0, lvl.shape[0] - 1)
    z = (jnp.take(lvl, src) == 0) & (jnp.take(lvl, dst) == 0)
    return (valid & z).sum(dtype=jnp.int32)
