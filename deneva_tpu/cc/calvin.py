"""CALVIN (deterministic) and TPU_BATCH (the headline backend).

Reference Calvin: a sequencer stamps each txn with ``(batch_id=epoch,
txn_id)`` and broadcasts per-epoch batches (`system/sequencer.cpp:184-326`);
a lock-scheduler thread acquires all locks in strict sequence order —
conflicts enqueue FIFO, never abort (`row_lock.cpp:152-170`) — and workers
execute when granted, forwarding dirty reads to remote peers (RFWD,
`system/txn.cpp:957-974`).  Determinism means zero aborts and no 2PC.

Batch mapping.  The engine's epoch *is* the sequencer batch and ``rank``
is the sequence number.  The per-row FIFO lock queues become wavefront
levels over the conflict matrix: a txn's level is its longest conflict
chain through earlier-ranked txns, and the engine executes levels as
chained sub-rounds — level-l reads see all writes of levels < l, which is
exactly the deterministic serial order Calvin's scheduler enforces (and
subsumes the RFWD dirty-read forwarding: the "forwarded" value is simply
present in table state by the reader's sub-round).  Txns whose chain
exceeds ``exec_subrounds`` defer whole to the next epoch where their
preserved rank keeps them at the head — deterministic order is preserved,
they just commit in a later batch (the reference's epochs likewise bound
batch extent in time, `config.h:348`).

On blind-write workloads (YCSB) both backends take the single-pass
forwarding executor instead of sub-rounds (`cc.__init__` registry,
``forward=True``): a reader of a key with an earlier in-batch writer
receives that writer's value arithmetically (ops/forward), which is the
*closed form* of RFWD — the reference's scheduler likewise executes a
hot-key chain serially WITHIN the batch and commits all of it, whatever
its depth.  This is what makes the deterministic backends flat under
skew (the paper's signature Calvin result); the sub-round level budget
applies only where writes depend on reads (TPC-C/PPS), and execution
runs only the levels that actually occur (`lax.while_loop`, not a fixed
unroll), so raising ``exec_subrounds`` costs nothing at low contention.

TPU_BATCH = the same deterministic executor, minus the fiction of a
separate sequencer node: ranks are pool arrival order, and the conflict
matrix is dual-hash exact.  It commits *everything* (cycle-free by
construction since edges follow rank), so throughput is bounded by chain
depth rather than abort rate — the design SURVEY §7 stage 8 targets.  The
two share an implementation; CALVIN additionally reports the deterministic
``order`` for cross-node replay (`deneva_tpu.runtime` ships per-epoch
verdicts instead of RFWD messages).
"""

from __future__ import annotations

import jax.numpy as jnp

from deneva_tpu.cc.base import AccessBatch, Incidence, Verdict, get_overlap
from deneva_tpu.ops import earlier_edges, wavefront_levels


def validate_calvin(cfg, state, batch: AccessBatch, inc: Incidence):
    ov = get_overlap(cfg)
    # conflict iff the pair overlaps AND at least one side is an
    # ORDERED access: escrow/commutative (order_free) add-add pairs
    # carry no edge, while reads of the same accumulators still order
    # against every write (uo == u when nothing is exempt)
    uo1 = inc.u1 if inc.uo1 is None else inc.uo1
    uo2 = inc.u2 if inc.uo2 is None else inc.uo2
    uw = ov(uo1, inc.w1, uo2, inc.w2)
    c = uw | uw.T
    e = earlier_edges(c, batch.rank, batch.active)
    lv, overflow = wavefront_levels(e, max_level=cfg.exec_subrounds - 1)
    commit = batch.active & ~overflow
    v = Verdict(commit=commit, abort=jnp.zeros_like(batch.active),
                defer=batch.active & overflow,
                order=batch.rank, level=jnp.where(commit, lv, 0))
    return v, state


validate_tpu_batch = validate_calvin
