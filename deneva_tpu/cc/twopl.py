"""2PL variants: NO_WAIT and WAIT_DIE (reference `concurrency_control/row_lock.{h,cpp}`).

The reference keeps a per-row owners/waiters lock table under a pthread
mutex: NO_WAIT aborts any conflicting requester (`row_lock.cpp:86-90`);
WAIT_DIE lets a requester *older* than every conflicting owner wait on a
FIFO list, younger requesters die (`row_lock.cpp:91-151`), and release
promotes waiters via `txn_table.restart_txn` (`:317-357`).

Batch semantics: lock-acquisition order becomes ``rank`` (pool arrival
order).  A txn "reaches the lock table first" iff it wins the lex-first
maximal-independent-set sweep over the RW/WR/WW conflict matrix in rank
order — exactly the set of txns that would have acquired all their locks
had the epoch's requests arrived serially in rank order.

* NO_WAIT: sweep losers abort (with the engine's exponential backoff,
  `system/abort_queue.cpp:26-50`).  Sweep-round-cap leftovers defer —
  they were never refused a lock, merely unresolved this epoch.
* WAIT_DIE: a loser conflicting only with *younger* winners (all winner
  timestamps greater than its own) waits — deferred to the next epoch
  where its lower rank makes it the presumptive owner; otherwise it dies.
  Timestamps are assigned at first arrival and preserved across restarts
  (the reference preserves them the same way, `worker_thread.cpp:492-508`),
  which is what makes WAIT_DIE starvation-free.

Isolation levels (reference `config.h:102,337-340`) relax which lock
requests conflict, exactly mirroring the reference's per-level gating:

* SERIALIZABLE — long read + write locks: any pair sharing a key with at
  least one writer conflicts (RR excluded).
* READ_COMMITTED — read locks are released immediately after the read
  (`benchmarks/ycsb_txn.cpp:233`, cleanup skip `system/txn.cpp:720`):
  writers no longer block behind earlier readers, but a reader still
  contends at acquire time with an *earlier* writer holding the lock —
  directed reader←writer edges stay, reader→writer edges drop.
* READ_UNCOMMITTED — reads bypass the lock table entirely
  (`storage/row.cpp:208,359`): only WW conflicts remain.
* NOLOCK — CC bypassed (`storage/row.cpp:203,355`): everyone commits;
  the engine's last-writer-wins scatter resolves duplicate writes.

Each level's edge set is a subset of the previous, so throughput is
monotone in the isolation ladder — the shape `experiments.py`'s
isolation_levels sweep exists to show.

Escrow (``order_free``) exemption, gated by ``escrow_order_free`` AND
``escrow_sweep``: lock requests for commutative accumulator updates and
immutable-column reads need no lock at all — the reference analogue is
escrow locking (O'Neil) layered under 2PL, where increment locks are
mutually compatible.  Edges therefore come from the ORDERED incidence
views: a pair conflicts iff it overlaps and at least one side's access
is ordered — symmetrizing ``overlap(uo, w)`` (SERIALIZABLE),
``overlap(wo, w)`` (WW), and directing ``overlap(pro, w)`` (RC's
residual read locks) — so Payment add-add pairs on one warehouse row
all acquire their "increment locks" together, while an ordered read of
W_YTD still contends with every add.  With the gate off the views alias
r/w/pr and the edges are bit-identical to the pre-escrow derivation.
"""

from __future__ import annotations

import jax.numpy as jnp

from deneva_tpu.cc.base import (AccessBatch, Incidence, Verdict,
                                committed_write_frontier, get_overlap)
from deneva_tpu.cc.nocc import validate_nocc
from deneva_tpu.ops import earlier_edges, greedy_first_fit


def repair_frontier(cfg, state, batch: AccessBatch, inc: Incidence,
                    committed, losers):
    """2PL invalidation rule (transaction repair, engine/repair.py):
    lock-edge losers.  A NO_WAIT/WAIT_DIE loser was refused a lock some
    winner held; by the repair sub-round every winner has committed and
    "released", so the loser re-acquires against the epoch-end state.
    Its invalidated reads are the ones an earlier winner's WRITE lock
    covered — ordered reads overlapping committed writes — the same
    access set under every isolation level (READ_COMMITTED's early-
    released read locks and READ_UNCOMMITTED's lock-free reads change
    which REQUESTS conflict, not which read VALUES went stale; the
    generic frontier is the conservative superset for both).  Write-only
    lock losers (WW refusals) re-apply their blind writes with an empty
    frontier.  The sub-round's re-acquisition is this module's own edge
    derivation restricted to the losers (``validate_no_wait``/
    ``validate_wait_die`` on the loser-masked batch)."""
    return committed_write_frontier(cfg, batch, inc, committed, losers)


def _lock_edges(cfg, batch: AccessBatch, inc: Incidence):
    """Directed blocked-by edges E[i,j] ("earlier j blocks i") under the
    configured isolation level; None means no locking at all (NOLOCK).
    Ordered incidence views (uo/wo/pro — alias u/w/pr when no escrow
    exemption applies) keep escrow add-add pairs edge-free."""
    iso = cfg.isolation_level
    ov = get_overlap(cfg)
    if iso == "NOLOCK":
        return None
    uo1 = inc.u1 if inc.uo1 is None else inc.uo1
    uo2 = inc.u2 if inc.uo1 is None else inc.uo2
    if iso == "SERIALIZABLE":
        # symmetrized ordered-vs-write overlap: a pair conflicts iff at
        # least one side's ORDERED access meets the other's write
        uw = ov(uo1, inc.w1, uo2, inc.w2)
        return earlier_edges(uw | uw.T, batch.rank, batch.active)
    wo1 = inc.w1 if inc.wo1 is None else inc.wo1
    wo2 = inc.w2 if inc.wo1 is None else inc.wo2
    ww = ov(wo1, inc.w1, wo2, inc.w2)
    e = earlier_edges(ww | ww.T, batch.rank, batch.active)
    if iso == "READ_COMMITTED":
        # i's ordered pure read contends with an earlier writer j of the
        # same key; the reverse direction (writer behind reader) is gone —
        # the read lock is already released by the time the writer asks.
        pro1 = inc.pr1 if inc.pro1 is None else inc.pro1
        pro2 = inc.pr2 if inc.pro1 is None else inc.pro2
        prw = ov(pro1, inc.w1, pro2, inc.w2)
        e = e | earlier_edges(prw, batch.rank, batch.active)
    return e


def validate_no_wait(cfg, state, batch: AccessBatch, inc: Incidence):
    e = _lock_edges(cfg, batch, inc)
    if e is None:
        return validate_nocc(cfg, state, batch, inc)
    win, lose, und = greedy_first_fit(e, batch.active, rounds=cfg.sweep_rounds)
    v = Verdict(commit=win, abort=lose, defer=und,
                order=batch.rank, level=jnp.zeros_like(batch.rank))
    return v, state


def validate_wait_die(cfg, state, batch: AccessBatch, inc: Incidence):
    e = _lock_edges(cfg, batch, inc)
    if e is None:
        return validate_nocc(cfg, state, batch, inc)
    win, lose, und = greedy_first_fit(e, batch.active, rounds=cfg.sweep_rounds)
    # min timestamp over the winning earlier neighbors that blocked me
    blockers = e & win[None, :]
    big = jnp.iinfo(jnp.int32).max
    min_owner_ts = jnp.where(blockers, batch.ts[None, :], big).min(axis=1)
    waits = lose & (batch.ts < min_owner_ts)   # older than every owner -> wait
    v = Verdict(commit=win, abort=lose & ~waits, defer=und | waits,
                order=batch.rank, level=jnp.zeros_like(batch.rank))
    return v, state
