"""2PL variants: NO_WAIT and WAIT_DIE (reference `concurrency_control/row_lock.{h,cpp}`).

The reference keeps a per-row owners/waiters lock table under a pthread
mutex: NO_WAIT aborts any conflicting requester (`row_lock.cpp:86-90`);
WAIT_DIE lets a requester *older* than every conflicting owner wait on a
FIFO list, younger requesters die (`row_lock.cpp:91-151`), and release
promotes waiters via `txn_table.restart_txn` (`:317-357`).

Batch semantics: lock-acquisition order becomes ``rank`` (pool arrival
order).  A txn "reaches the lock table first" iff it wins the lex-first
maximal-independent-set sweep over the RW/WR/WW conflict matrix in rank
order — exactly the set of txns that would have acquired all their locks
had the epoch's requests arrived serially in rank order.

* NO_WAIT: sweep losers abort (with the engine's exponential backoff,
  `system/abort_queue.cpp:26-50`).  Sweep-round-cap leftovers defer —
  they were never refused a lock, merely unresolved this epoch.
* WAIT_DIE: a loser conflicting only with *younger* winners (all winner
  timestamps greater than its own) waits — deferred to the next epoch
  where its lower rank makes it the presumptive owner; otherwise it dies.
  Timestamps are assigned at first arrival and preserved across restarts
  (the reference preserves them the same way, `worker_thread.cpp:492-508`),
  which is what makes WAIT_DIE starvation-free.
"""

from __future__ import annotations

import jax.numpy as jnp

from deneva_tpu.cc.base import AccessBatch, Incidence, Verdict
from deneva_tpu.ops import earlier_edges, greedy_first_fit, overlap


def _conflict_full(inc: Incidence):
    """Symmetric conflict: pairs sharing a key with >=1 writer (RR excluded)."""
    uw = overlap(inc.u1, inc.w1, inc.u2, inc.w2)
    return uw | uw.T


def validate_no_wait(cfg, state, batch: AccessBatch, inc: Incidence):
    c = _conflict_full(inc)
    e = earlier_edges(c, batch.rank, batch.active)
    win, lose, und = greedy_first_fit(e, batch.active, rounds=cfg.sweep_rounds)
    v = Verdict(commit=win, abort=lose, defer=und,
                order=batch.rank, level=jnp.zeros_like(batch.rank))
    return v, state


def validate_wait_die(cfg, state, batch: AccessBatch, inc: Incidence):
    c = _conflict_full(inc)
    e = earlier_edges(c, batch.rank, batch.active)
    win, lose, und = greedy_first_fit(e, batch.active, rounds=cfg.sweep_rounds)
    # min timestamp over the winning earlier neighbors that blocked me
    blockers = e & win[None, :]
    big = jnp.iinfo(jnp.int32).max
    min_owner_ts = jnp.where(blockers, batch.ts[None, :], big).min(axis=1)
    waits = lose & (batch.ts < min_owner_ts)   # older than every owner -> wait
    v = Verdict(commit=win, abort=lose & ~waits, defer=und | waits,
                order=batch.rank, level=jnp.zeros_like(batch.rank))
    return v, state
