"""CC backend interface: batched epoch validation.

The reference's concurrency control is a per-row state machine reached via
`row_t::get_row` / `return_row` (`storage/row.cpp:197-310,351-420`), with a
`#if CC_ALG` branch per algorithm.  Here an algorithm is a *pure function
over one epoch*:

    validate(cfg, state, batch) -> (Verdict, state')

``batch`` carries the epoch's planned accesses (padded RW-sets), ``state``
is whatever survives across epochs (per-bucket timestamp tables for the
T/O family; most algorithms are stateless), and the ``Verdict`` partitions
the batch into commit / abort / defer plus a serialization order and an
execution wavefront level:

* ``order`` — total serialization order among committed txns; duplicate
  committed VALUE writes to one slot are resolved to the max-order writer
  (`deneva_tpu.ops.scatter.last_writer`), the batch analogue of the
  reference applying writes serially under latches.  Escrow (order_free)
  writes are DELTAS, not values: the executors accumulate them over ALL
  committed winners (`DeviceTable.scatter_add`), which is order-invariant
  — the multi-winner commit path that lets many escrow writers of one hot
  row commit in a single epoch.
* ``level`` — sub-round index for algorithms that *chain* intra-epoch
  read-after-write dataflow (Calvin, TPU_BATCH): level-l reads observe
  writes of levels < l.  Algorithms whose committed sets are
  RW-conflict-free always report level 0.
* ``defer`` — retry next epoch without an abort penalty: the batch
  analogue of parking a txn on a row's waiter list and resuming it via
  `txn_table.restart_txn` (`system/txn_table.cpp:151-176`) — the
  reference's subtlest machinery (SURVEY §7 hard-part #1) reduced to a
  mask.

Verdict invariants (asserted in tests): commit/abort/defer are disjoint,
cover ``active``, and the committed set is serializable — for level-0
algorithms it is RW/WR/(RMW)WW-conflict-free under ``order`` over its
ORDERED accesses; for chained algorithms each level is conflict-free and
edges only point to lower levels.  Escrow (``order_free``) accesses are
exempt from the conflict-freedom claim by design: their writes are
commutative deltas whose accumulated sum is order-invariant, so
serializability holds modulo commutativity (oracle: accumulator sums vs
serial, `tests/test_escrow.py`).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax

from deneva_tpu.ops import access_incidence, bucket_hash, combine_key


def get_overlap(cfg):
    """Per-config overlap op.  A hand-written Pallas epilogue-fusion
    kernel lived behind this dispatch in rounds 3-4; round-5 measured it
    0.58-0.96x the XLA path at every sweep operating point (B in
    {512,1024,2048} x K=8192, dual hash on/off, v5e — XLA already keeps
    the compare+AND epilogue fused) and deleted it (BASELINE.md round-5
    notes; kernel retrievable from git history at tag-of-commit 6fba114).
    The dispatch point stays so a future winning kernel has one seam."""
    from deneva_tpu.ops import overlap

    return overlap


@dataclass
class AccessBatch:
    """One epoch's planned accesses.  Pytree of static shape [B, A] / [B]."""

    table_ids: jax.Array   # int32[B, A]
    keys: jax.Array        # int32[B, A] primary keys (pre-index lookup)
    is_read: jax.Array     # bool[B, A]
    is_write: jax.Array    # bool[B, A]  (read & write = RMW)
    valid: jax.Array       # bool[B, A]
    ts: jax.Array          # int32[B] timestamp (T/O priority; WAIT_DIE age)
    rank: jax.Array        # int32[B] arrival/sequence rank (lock/queue order)
    active: jax.Array      # bool[B]
    # bool[B] | None: txn is GLOBALLY read-only.  None (default) = derive
    # from valid & is_write.  The distributed VOTE protocol masks valid
    # down to locally-owned accesses, which would make a cross-partition
    # rw-txn look read-only to a node owning only its reads and skip
    # read validation (MVCC's ro fast path) — the unmasked plan's mask
    # rides here so every node classifies identically.
    ro_hint: jax.Array | None = None
    # bool[B, A] | None: escrow/commutative accesses (workload
    # ``order_free`` declarations, PRE-GATED by ``gate_order_free`` —
    # None whenever the backend or config declines the exemption, so a
    # None here reproduces the pre-escrow semantics bit for bit).  The
    # T/O family consumes it directly for its cross-epoch watermark
    # rules; the incidence builder consumes it for the ordered views.
    order_free: jax.Array | None = None

    @property
    def shape(self) -> tuple[int, int]:
        return self.keys.shape


jax.tree_util.register_dataclass(
    AccessBatch,
    data_fields=["table_ids", "keys", "is_read", "is_write", "valid",
                 "ts", "rank", "active", "ro_hint", "order_free"],
    meta_fields=[],
)


@dataclass
class Verdict:
    commit: jax.Array      # bool[B]
    abort: jax.Array       # bool[B]  -> backoff + restart (abort_queue analogue)
    defer: jax.Array       # bool[B]  -> retry next epoch, no penalty (waiter analogue)
    order: jax.Array       # int32[B] serialization order among committed
    level: jax.Array       # int32[B] execution sub-round (0 = snapshot reads)


jax.tree_util.register_dataclass(
    Verdict, data_fields=["commit", "abort", "defer", "order", "level"],
    meta_fields=[])


@dataclass
class Incidence:
    """Bucket-space incidence matrices of one epoch, both hash families.

    ``r/w/u/pr`` are bfloat16[B, K] (reads / writes / union / pure reads —
    accesses that read without writing; RMW-read incidence is ``r - pr``);
    family-2 copies are None unless ``Config.conflict_exact`` dual hashing
    is on.
    """

    r1: jax.Array
    w1: jax.Array
    u1: jax.Array
    pr1: jax.Array
    r2: jax.Array | None
    w2: jax.Array | None
    u2: jax.Array | None
    pr2: jax.Array | None
    # per-access bucket ids in family 0 (for ts-table gathers/scatters)
    bucket1: jax.Array     # int32[B, A]
    # ordered-union incidence: accesses NOT marked order_free.  The
    # backends that honor the escrow exemption draw conflict edges from
    # overlap(uo, w) — a pair conflicts iff it overlaps AND at least one
    # side needs ordering — so escrow add-add pairs carry no edge while
    # reads of the same accumulators still order against every write.
    # Equals u1/u2 when no exemption applies.
    uo1: jax.Array | None = None
    uo2: jax.Array | None = None
    # ordered read / write / pure-read incidence (r/w/pr minus the
    # order_free accesses): the sweep backends' escrow-aware edge inputs
    # — T/O reader-wait edges come from overlap(ro, w), the relaxed-
    # isolation WW lock edges from overlap(wo, w), READ_COMMITTED's
    # residual read locks from overlap(pro, w).  ALIASES of r/w/pr when
    # no exemption applies (zero extra memory or matmuls).
    ro1: jax.Array | None = None
    ro2: jax.Array | None = None
    wo1: jax.Array | None = None
    wo2: jax.Array | None = None
    pro1: jax.Array | None = None
    pro2: jax.Array | None = None


def gate_order_free(cfg, be, order_free: jax.Array | None
                    ) -> jax.Array | None:
    """The ONE escrow gate: returns the workload's ``order_free`` mask iff
    this backend may consume it, else None (pre-escrow semantics, bit for
    bit).  Chained/deterministic backends gate on ``escrow_order_free``
    alone (their exemption shipped rounds ago); the sweep backends
    additionally require ``escrow_sweep`` so the reference-faithful
    baseline (per-row conflicts, the TPC-C hot-row floor) stays one flag
    away."""
    if order_free is None or not be.exempt_order_free \
            or not cfg.escrow_order_free:
        return None
    if not be.chained and not cfg.escrow_sweep:
        return None
    return order_free


def build_conflict_incidence(cfg, be, batch: AccessBatch,
                             order_free: jax.Array | None):
    """`build_incidence` honoring the backend's ``order_free`` exemption
    (escrow/commutative accesses order only against ordered accesses,
    never against each other).  Shared by the single-node engine and the
    distributed server step so their conflict semantics cannot diverge."""
    if not be.needs_incidence:
        return None
    order_free = gate_order_free(cfg, be, order_free)
    return build_incidence(batch, cfg.conflict_buckets, cfg.conflict_exact,
                           order_free=order_free)


def committed_write_frontier(cfg, batch: AccessBatch, inc: Incidence,
                             committed, losers):
    """Invalidated-read frontier: bool[B, A] marking each LOSER's ordered
    read lanes whose bucket some txn in ``committed`` wrote — the reads
    that observed a value the winners overwrote, i.e. exactly the slice
    transaction repair must re-execute (PAPERS: *Transaction Repair*;
    the conflict incidence the sweep already materialized answers it
    with one [B]x[B,K] matvec per hash family).

    Bucket-space over-approximation, stated the same way as every sweep
    input: a collision can only ADD frontier lanes, never hide one — and
    an added lane is harmless because a re-read of a key nobody
    overwrote returns the identical value (which is also why the
    executors' full re-gather IS the masked re-read, bit for bit).
    Escrow (``order_free``) reads are excluded: they are declared-
    immutable columns, so repair of an escrow access is a no-op by
    contract (cc/timestamp.py escrow rules; documented in README)."""
    import jax.numpy as jnp

    wrote = jnp.matmul(committed.astype(inc.w1.dtype)[None, :], inc.w1,
                       preferred_element_type=jnp.float32)[0] > 0
    hit = jnp.take(wrote, inc.bucket1)
    if inc.w2 is not None:
        ident = combine_key(batch.table_ids, batch.keys)
        b2 = bucket_hash(ident, inc.w2.shape[1], family=1)
        wrote2 = jnp.matmul(committed.astype(inc.w2.dtype)[None, :],
                            inc.w2, preferred_element_type=jnp.float32
                            )[0] > 0
        hit = hit & jnp.take(wrote2, b2)
    rmask = batch.valid & losers[:, None] & batch.is_read
    if batch.order_free is not None:
        rmask = rmask & ~batch.order_free
    return rmask & hit


def conflict_density(cfg, batch: AccessBatch, owner,
                     inc: Incidence | None = None):
    """Per-partition observed-conflict density: int32[P] counting this
    epoch's access lanes that CONTEND — their bucket is written by some
    other txn, or they write a bucket some other txn touches — folded
    by the owning partition (the plan's ``owner`` map, the same
    ``key % part_cnt`` striping the VOTE protocol routes on).

    This is the metrics bus's per-epoch contention signal
    (runtime/metricsbus.py) and the input the contention-adaptive CC
    router item needs (PAPERS: *DGCC* builds its whole protocol on the
    dependency-graph signal; *Timestamp Granularity in OCC* argues the
    protocol/granularity choice should follow observed contention).
    When the sweep already materialized an ``Incidence`` the per-bucket
    counts are two column sums over it — effectively free; forwarding
    backends (no incidence) pay two bucket scatter-adds instead.  Like
    every sweep input it is a bucket-space over-approximation: a hash
    collision can only ADD density, never hide it."""
    import jax.numpy as jnp

    p = max(cfg.part_cnt, 1)
    v = batch.valid & batch.active[:, None]
    w = v & batch.is_write
    if inc is not None:
        bucket = inc.bucket1
        # column sums over the already-materialized incidence: one
        # reduction each, no new [B, K] buffer
        wcol = jnp.sum(inc.w1, axis=0, dtype=jnp.float32)
        ucol = jnp.sum(inc.u1, axis=0, dtype=jnp.float32)
    else:
        # forwarding backends carry no incidence: per-bucket counts via
        # two flat scatter-adds (O(B*A) lanes into [K]; never a [B, K]
        # materialization — measured 22% tput off the armed CALVIN pair
        # when a first cut built full incidence here)
        k = cfg.conflict_buckets
        ident = combine_key(batch.table_ids, batch.keys)
        bucket = bucket_hash(ident, k, family=0)
        cols = jnp.where(v, bucket, 0).ravel()
        wcol = jnp.zeros(k, jnp.float32).at[cols].add(
            w.ravel().astype(jnp.float32))
        ucol = jnp.zeros(k, jnp.float32).at[cols].add(
            v.ravel().astype(jnp.float32))
    # per access: how many of its bucket's touches are SOMEONE ELSE'S —
    # the txn's own same-bucket lanes subtract out (pairwise compare
    # within the row, O(B*A^2) with the small padded A), so a txn
    # revisiting its own bucket never reads as contention
    same = bucket[:, :, None] == bucket[:, None, :]
    own_w = jnp.sum(same & w[:, None, :], axis=-1).astype(jnp.float32)
    own_u = jnp.sum(same & v[:, None, :], axis=-1).astype(jnp.float32)
    w_oth = jnp.take(wcol, bucket) - own_w
    u_oth = jnp.take(ucol, bucket) - own_u
    # a lane contends iff some OTHER txn wrote its bucket, or it writes
    # and some OTHER txn touched it (0.5 threshold absorbs bf16 noise)
    conf = v & ((w_oth > 0.5) | (w & (u_oth > 0.5)))
    onehot = (owner[:, :, None] == jnp.arange(p, dtype=jnp.int32)) \
        & conf[:, :, None]
    return onehot.sum(axis=(0, 1), dtype=jnp.int32)


# ---- isolation audit plane: on-device dependency observations ----------
# (Config.audit; the export half lives in runtime/audit.py, the graph/
# certifier half in harness/auditgraph.py.)

AUDIT_KEY = "__audit__"     # db dict key of the audit stamp tables
#                             (control plane like __membership__:
#                             excluded from logger.state_digest)

# exported edge kinds (packed as kind<<28 | src<<14 | dst over
# merged-batch ranks; decode in runtime/audit.py)
AUDIT_WW, AUDIT_WR, AUDIT_RW = 0, 1, 2


def audit_init(cfg):
    """Fresh audit state: per-bucket version-stamp tables (the audit
    twin of the `storage.table.VersionRing` — last committed writer's
    epoch + merged rank per hashed bucket; -1 = never written).  Lives
    in ``db[AUDIT_KEY]`` so every db-construction path (engine init,
    server boot, log replay, follower boot) threads it identically and
    checkpointing carries it (engine/checkpoint schema v8).

    Under MVCC the state additionally carries per-bucket version-
    boundary RINGS (depth ``mvcc_his_len``, mirroring the backend's own
    in-ring retention): the last H committed writers' boundary
    timestamps plus their (epoch, writer) stamps, so a read's observed
    version can be SELECTED BY ITS TIMESTAMP
    (`cc.depgraph.version_select`) instead of assumed to be the last
    writer — the audit plane's MVCC headroom item.  Gated on the
    algorithm so every non-MVCC artifact (checkpoint schema v8,
    sidecars, replay digests) keeps its exact pre-existing shape;
    MVCC+audit was a `config.validate` error before the rings existed,
    so no prior artifact carries the extended shape."""
    import jax.numpy as jnp

    from deneva_tpu.config import CCAlg

    k = cfg.audit_buckets
    aud = {"epoch": jnp.full((k,), -1, jnp.int32),
           "writer": jnp.full((k,), -1, jnp.int32)}
    if cfg.cc_alg == CCAlg.MVCC:
        h = max(1, cfg.mvcc_his_len)
        aud.update(
            vts=jnp.full((k, h), -1, jnp.int32),
            vepoch=jnp.full((k, h), -1, jnp.int32),
            vwriter=jnp.full((k, h), -1, jnp.int32),
            vpos=jnp.zeros((k,), jnp.int32))
    return aud


def audit_observe(cfg, batch: AccessBatch, committed, order, lvl,
                  order_vis: bool, stamps, epoch, cadence=None):
    """Per-epoch committed-txn dependency observations, derived ON
    DEVICE from the planned access sets under the backend's visibility
    rule — the isolation audit plane's measurement half.  Epochs off
    the ``audit_cadence`` grid skip the whole derivation via
    ``lax.cond`` (every node skips the same epochs, so the sidecar
    streams stay consensus-comparable; the overhead gate pins the
    default cadence, chaos scenarios pin cadence=1 for full-coverage
    certification).

    Model: the executors are mechanical (applies by serialization
    order/level, reads at their visibility point), so the data flow a
    committed set ACTUALLY produced is determined by (committed, order,
    lvl) plus the access sets — and any committed conflicting pair the
    backend's edge derivation wrongly admitted shows up here as
    dependency edges the claimed serial order cannot explain (the
    harness's cycle check).  Visibility per backend class:

    * ``order_vis=True`` (forwarding executor): a read observes the
      latest committed writer of its key with strictly LOWER
      serialization order (`ops.forward` serial-in-rank semantics).
    * ``order_vis=False``: a read observes the latest committed writer
      with strictly lower ``lvl`` (chained levels / repair salvage
      rounds); with every txn at lvl 0 this is the level-0 sweep rule —
      reads observe the epoch-start snapshot only.

    Edges emitted over EXACT combined keys (`ops.combine_key` — no
    bucket-collision false edges): wr (observed writer -> reader), rw
    (reader -> first writer past its observed version), ww (version
    chain).  Escrow (``order_free``) lanes are excluded: commutative
    deltas carry no ordering claim (same exemption as
    `committed_write_frontier`).  Self-edges are dropped (a txn's own
    RMW dataflow is program order, and its ww edge covers the chain).

    Honest level-0 sweep epochs emit ZERO edges (their committed sets
    are conflict-free by the Verdict invariant), so the export is
    empty exactly when the backend kept its claim.

    Returns ``(aud', edges, ebkt, cnt, dropped, vdig, rdig)``:
    updated stamp state, int32[audit_edges_max] packed edges (-1 pad)
    with their audit-bucket forensics column, the total edge-lane count
    (pre-cap, pre-dedup), the overflow count, and two uint32 digests —
    the post-epoch stamp tables (``vdig``) and this epoch's epoch-start
    read observations (``rdig``) — which every node of a merged cluster
    must reproduce bit-identically (harness/auditgraph.py's split-brain
    cross-check)."""
    import jax.numpy as jnp

    if cadence is None:
        # static cadence from config (the pre-ctrl path, bit-exact)
        cad_static = max(1, cfg.audit_cadence)
        if cad_static == 1:
            return _audit_observe_impl(cfg, batch, committed, order, lvl,
                                       order_vis, stamps, epoch)
        due = jnp.asarray(epoch, jnp.int32) % cad_static == 0
    else:
        # traced cadence (the ctrl plane's audit-density knob): the
        # due predicate is data, so the lax.cond is always compiled —
        # value cadence==1 makes every epoch due, same observations as
        # the direct call above
        cad = jnp.maximum(jnp.asarray(cadence, jnp.int32), 1)
        due = jnp.asarray(epoch, jnp.int32) % cad == 0
    e_max = cfg.audit_edges_max

    def live(_):
        return _audit_observe_impl(cfg, batch, committed, order, lvl,
                                   order_vis, stamps, epoch)

    def skip(_):
        z = jnp.zeros((), jnp.int32)
        return (stamps, jnp.full((e_max,), -1, jnp.int32),
                jnp.full((e_max,), -1, jnp.int32), z, z,
                jnp.zeros((), jnp.uint32), jnp.zeros((), jnp.uint32))

    return jax.lax.cond(due, live, skip, None)


def _audit_observe_impl(cfg, batch: AccessBatch, committed, order, lvl,
                        order_vis: bool, stamps, epoch):
    import jax.numpy as jnp

    from deneva_tpu.cc import depgraph

    b, a = batch.shape
    cm = batch.valid & committed[:, None]
    if batch.order_free is not None:
        cm = cm & ~batch.order_free
    rm = cm & batch.is_read
    wm = cm & batch.is_write
    ident = combine_key(batch.table_ids, batch.keys)
    big = jnp.uint32(0xFFFFFFFF)

    # dense serialization positions: opos ranks `order` over committed
    # txns (stable iota tiebreak), banded by lvl so writer positions
    # order lexicographically by (lvl, order) and reader visibility
    # points sit below their band (order_vis) or at its floor (level
    # visibility).  Doubling keeps read and write positions disjoint.
    okey = jnp.where(committed, order, jnp.int32(2**31 - 1))
    perm = jnp.argsort(okey, stable=True)
    opos = jnp.zeros((b,), jnp.int32).at[perm].set(
        jnp.arange(b, dtype=jnp.int32))
    band = lvl * jnp.int32(b + 2)
    wpos = (band + 1 + opos) * 2 + 1
    rpos = (band + 1 + opos) * 2 if order_vis else band * 2

    # flat double-lane view: each access contributes a read lane and/or
    # a write lane (an RMW access is both), sorted by (key, position).
    # Lean operand count: write-ness is the position's PARITY (wpos odd,
    # rpos even) and the audit bucket rehashes from the sorted ident,
    # so only the txn id rides as payload — CPU XLA's comparator sort
    # charges per operand (measured ~35% of the armed cost back)
    n = b * a
    tid = jnp.broadcast_to(jnp.arange(b, dtype=jnp.int32)[:, None],
                           (b, a))
    keys2 = jnp.concatenate([jnp.where(rm, ident, big).reshape(-1),
                             jnp.where(wm, ident, big).reshape(-1)])
    pos2 = jnp.concatenate([
        jnp.broadcast_to(rpos[:, None], (b, a)).reshape(-1),
        jnp.broadcast_to(wpos[:, None], (b, a)).reshape(-1)])
    tid2 = jnp.concatenate([tid.reshape(-1), tid.reshape(-1)])
    sk, sp, sid = depgraph.lane_sort(keys2, pos2, tid2)
    sw = (sp & 1) == 1
    sbk = bucket_hash(sk, cfg.audit_buckets, family=0)
    live = sk != big
    head, tail = depgraph.segment_bounds(sk)
    cand = jnp.where(sw & live, sid, jnp.int32(-1))
    # nearest preceding / following writer within the key segment (sort
    # order IS position order; write positions are unique per txn and
    # never tie a read position, so "preceding" is "strictly lower pos")
    prev = depgraph.prev_writer(head, cand)
    nxt = depgraph.next_writer(tail, cand)

    # per sorted lane: a read's preceding writer is its wr source, its
    # following writer the rw target (next version past the observed);
    # a write's preceding writer is its ww predecessor
    f_prev = live & (prev >= 0) & (prev != sid)
    e_prev = jnp.where(
        f_prev,
        depgraph.pack_edge(jnp.where(sw, AUDIT_WW, AUDIT_WR), prev, sid),
        jnp.int32(-1))
    f_next = live & ~sw & (nxt >= 0) & (nxt != sid)
    e_next = jnp.where(f_next, depgraph.pack_edge(AUDIT_RW, sid, nxt),
                       jnp.int32(-1))
    flags = jnp.concatenate([f_prev, f_next])
    allp = jnp.concatenate([e_prev, e_next])
    allb = jnp.concatenate([sbk, sbk])
    (edges, ebkt), cnt, dropped = depgraph.compact_lanes(
        flags, (allp, allb), cfg.audit_edges_max)

    # epoch-start read observations (reads with no in-epoch visible
    # writer) gather the PRE-update stamps: their digest is the
    # cross-epoch fingerprint every node must reproduce.  With MVCC's
    # version-boundary rings present, the observed stamp is instead
    # SELECTED BY THE READER'S TIMESTAMP from the bucket ring — a read
    # at ts t observes the newest retained version bounded by t, which
    # may be older than the last writer (`depgraph.version_select`).
    m1, m2, m3, m4 = (jnp.uint32(0x9E3779B9), jnp.uint32(0x85EBCA6B),
                      jnp.uint32(0xC2B2AE35), jnp.uint32(0x27D4EB2F))
    obs = live & ~sw & (prev < 0)
    if "vts" in stamps:
        sts = jnp.take(batch.ts, sid)
        ring = lambda f: jnp.take(stamps[f], sbk, axis=0)  # noqa: E731
        sel = depgraph.version_select(ring("vts"), sts)
        pick = lambda f: jnp.take_along_axis(  # noqa: E731
            ring(f), jnp.maximum(sel, 0)[:, None], axis=-1)[:, 0]
        oe = jnp.where(sel >= 0, pick("vepoch"), jnp.int32(-1))
        ow = jnp.where(sel >= 0, pick("vwriter"), jnp.int32(-1))
    else:
        oe = jnp.take(stamps["epoch"], sbk)
        ow = jnp.take(stamps["writer"], sbk)
    mix = ((sid.astype(jnp.uint32) * m1) ^ (sbk.astype(jnp.uint32) * m2)
           ^ (oe.astype(jnp.uint32) * m3) ^ (ow.astype(jnp.uint32) * m4))
    rdig = jnp.where(obs, mix, jnp.uint32(0)).sum(dtype=jnp.uint32)

    # advance the stamp tables: last committed writer per audit bucket
    # by (lvl, order) position — argmax via two scatter-max passes
    k = cfg.audit_buckets
    wl_mask = live & sw
    sbk_safe = jnp.where(wl_mask, sbk, 0)
    top = jnp.zeros((k,), jnp.int32).at[sbk_safe].max(
        jnp.where(wl_mask, sp + 1, 0))
    upd = top > 0
    match = wl_mask & (sp + 1 == jnp.take(top, sbk))
    wid = jnp.zeros((k,), jnp.int32).at[jnp.where(match, sbk, 0)].max(
        jnp.where(match, sid + 1, 0))
    new_e = jnp.where(upd, jnp.asarray(epoch, jnp.int32), stamps["epoch"])
    new_w = jnp.where(upd, wid - 1, stamps["writer"])
    vdig = ((new_e.astype(jnp.uint32) * m1)
            ^ (new_w.astype(jnp.uint32) * m2)).sum(dtype=jnp.uint32)
    nstamps = {"epoch": new_e, "writer": new_w}
    if "vts" in stamps:
        # push this epoch's final writer per updated bucket into the
        # version-boundary ring: boundary ts = the winning writer's own
        # timestamp (MVCC stamps versions with the writer's ts)
        hlen = stamps["vts"].shape[1]
        slot = stamps["vpos"] % hlen
        rows = jnp.arange(k, dtype=jnp.int32)
        wts = jnp.take(batch.ts, jnp.maximum(wid - 1, 0))

        def push(ring_arr, val):
            cur = ring_arr[rows, slot]
            return ring_arr.at[rows, slot].set(jnp.where(upd, val, cur))

        nstamps.update(
            vts=push(stamps["vts"], wts),
            vepoch=push(stamps["vepoch"], jnp.asarray(epoch, jnp.int32)),
            vwriter=push(stamps["vwriter"], wid - 1),
            vpos=stamps["vpos"] + upd.astype(jnp.int32))
    return (nstamps, edges, ebkt, cnt, dropped, vdig, rdig)


def audit_mutate_verdict(cfg, batch: AccessBatch, inc: Incidence,
                         verdict, epoch):
    """Seeded edge-derivation fault (``Config.audit_mutate``, the
    certifier's anti-inert knob): emulate dropping OCC's read-set-vs-
    winner-write-set check on the chosen epoch window.  A Kung-Robinson
    loser whose WRITE lanes miss every winner-written bucket was
    aborted purely for its stale reads — with the check gone it commits
    (and executes, and acks), a real isolation violation: reciprocal
    read/write overlaps among the flipped losers and the winners form
    rw cycles (write skew) that harness/auditgraph.py must reject with
    a witness naming an epoch in the window."""
    import dataclasses

    import jax.numpy as jnp

    _, start, count = cfg.audit_mutate_spec()
    committed = verdict.commit & batch.active
    wrote = jnp.matmul(committed.astype(inc.w1.dtype)[None, :], inc.w1,
                       preferred_element_type=jnp.float32)[0] > 0
    hit = jnp.take(wrote, inc.bucket1)
    if inc.w2 is not None:
        ident = combine_key(batch.table_ids, batch.keys)
        b2 = bucket_hash(ident, inc.w2.shape[1], family=1)
        wrote2 = jnp.matmul(committed.astype(inc.w2.dtype)[None, :],
                            inc.w2, preferred_element_type=jnp.float32
                            )[0] > 0
        hit = hit & jnp.take(wrote2, b2)
    wmask = batch.valid & batch.is_write
    if batch.order_free is not None:
        wmask = wmask & ~batch.order_free
    dirty_writes = (wmask & hit).any(axis=1)
    e = jnp.asarray(epoch, jnp.int32)
    in_window = (e >= start) & (e < start + count)
    flip = verdict.abort & batch.active & ~dirty_writes & in_window
    return dataclasses.replace(
        verdict, commit=verdict.commit | flip,
        abort=verdict.abort & ~flip)


def build_incidence(batch: AccessBatch, n_buckets: int, exact: bool,
                    order_free: jax.Array | None = None) -> Incidence:
    # `shard_buckets` is a no-op single-device; under a parallel.use_mesh
    # context it shards the bucket dim so the conflict matmul contracts
    # over partitions and XLA inserts the cross-device reduction.
    from deneva_tpu.parallel.mesh import shard_buckets
    ident = combine_key(batch.table_ids, batch.keys)
    v = batch.valid & batch.active[:, None]
    rmask = v & batch.is_read
    wmask = v & batch.is_write
    prmask = rmask & ~wmask
    b1 = bucket_hash(ident, n_buckets, family=0)

    def family(b):
        inc = lambda m: shard_buckets(access_incidence(b, m, n_buckets))  # noqa: E731
        r, w = inc(rmask), inc(wmask)
        u, pr = inc(rmask | wmask), inc(prmask)
        if order_free is None:
            # aliases: escrow off (or nothing declared) costs nothing and
            # the ordered views are bitwise the plain ones
            return r, w, u, pr, u, r, w, pr
        of = ~order_free
        return (r, w, u, pr, inc((rmask | wmask) & of), inc(rmask & of),
                inc(wmask & of), inc(prmask & of))

    r1, w1, u1, pr1, uo1, ro1, wo1, pro1 = family(b1)
    r2 = w2 = u2 = pr2 = uo2 = ro2 = wo2 = pro2 = None
    if exact:
        b2 = bucket_hash(ident, n_buckets, family=1)
        r2, w2, u2, pr2, uo2, ro2, wo2, pro2 = family(b2)
    return Incidence(r1=r1, w1=w1, u1=u1, pr1=pr1, r2=r2, w2=w2, u2=u2,
                     pr2=pr2, bucket1=b1, uo1=uo1, uo2=uo2, ro1=ro1,
                     ro2=ro2, wo1=wo1, wo2=wo2, pro1=pro1, pro2=pro2)
