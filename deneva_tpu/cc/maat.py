"""MAAT — dynamic timestamp-range validation (reference
`concurrency_control/maat.{h,cpp}`, `row_maat.{h,cpp}`).

The reference gives every txn a mutable commit-timestamp range
``[lower, upper]`` in a hashed global TimeTable (`maat.cpp:192-323`), has
accesses soft-lock rows by recording uncommitted reader/writer sets
(`row_maat.cpp:54-164`), and at validation shrinks ranges per five
conflict cases so that conflicting txns order *dynamically* — a reader may
serialize before a later-arriving writer instead of aborting
(`maat.cpp:44-162`).  Aborts happen only when a range closes
(lower >= upper).

Batch mapping.  Under epoch-snapshot execution the range algebra
collapses to its essence: every intra-epoch read observed the snapshot,
so the *only* ordering constraint is **reader-before-writer** — if i read
a key j writes, i's commit ts must precede j's.  Those constraints form a
directed must-precede graph P (one MXU matmul).  P decomposes into:

* **Mutual pairs** (``P[i,j] & P[j,i]``): RMW-RMW on a shared key, or
  crossed read/write pairs across two keys.  Both directions required =
  both ranges cannot stay open: in the reference's serial validation the
  first validator commits and the later one's lower bound rises past its
  upper — it ABORTS (`maat.cpp:44-162`; RMW-RMW pairs close the same
  way: each is in the other's uncommitted reader AND writer sets).  The
  batch analogue is the lex-first MIS sweep: winners are the txns a
  serial validation pass would admit first, losers abort with the
  backoff the reference's restart path applies.  (Round-2 cliff fixed
  here: a hot-key RMW clique of m txns is m*(m-1)/2 mutual pairs; the
  old cycle peel removed ONE member per iteration with a fixed budget of
  4, so TPC-C's warehouse-row cliques aborted *wholesale* — winners
  included — and MAAT posted 0 txn/s at 4-16 warehouses.)  Sweep-budget
  leftovers (undecided) defer: a budget artifact, not a closed range.
* **Residual one-directional edges**: a consistent assignment of commit
  timestamps exists iff no directed cycle (length >= 3) remains — and
  with real-valued ranges ANY acyclic structure is feasible in serial
  validation (a range only closes when committed txns sandwich it, which
  needs a cycle), so every acyclic txn must COMMIT, however deep its
  chain (ADVICE r3 redesign: the old level-budget test aborted deep
  acyclic chain middles as false cycle members and deferred the rest).
  Shallow-acyclic epochs (the common case, gated by `ops.level_sweep`
  instability) commit everything with longest-path levels as the
  topological order (= the reference's ``find_bound`` picking the least
  timestamp above all lower bounds, `maat.cpp:176-190`).  Otherwise one
  full-graph transitive closure (log2(B) boolean matmul squarings on
  the MXU) answers both questions exactly: a node is on a cycle iff
  SELF-REACHABLE, and ancestor count is a strict topological key for
  everything else.  Cycles follow serial-validation semantics — the
  LATEST validators are the ones whose ranges close — via
  ``maat_peel_rounds`` bounded peel iterations that abort the
  locally-youngest members of the initially-proven cycle set that are
  still level-unstable (cheap sweeps between closures; see the in-code
  note for the precise approximation); survivors order dynamically and
  commit (a 3-cycle commits two, `maat.cpp:44-162`).

  Liveness: acyclic txns always commit; cycles lose their youngest
  members every peel round; peel leftovers past the budget defer, and
  the engine's defer budget (``defer_rounds_max``) force-restarts them
  — no livelock in any case.

Blind write-write pairs need no edge: any linear extension applies them
last-writer-wins in ``order``, and reader-before-writer edges already
force every epoch reader of that key before both writers.

Cross-epoch state is unnecessary: prior-epoch committers are wholly
before the snapshot (the TimeTable's GC'd steady state).  MAAT is thus
the most permissive sweep backend — pure readers and blind writers never
conflict regardless of rank, and only closed ranges (mutual pairs and
directed cycles) abort — matching its paper's claim of fewer aborts than
OCC/2PL at a (here vanished) validation-cost premium.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deneva_tpu.cc.base import (AccessBatch, Incidence, Verdict,
                                committed_write_frontier, get_overlap)
from deneva_tpu.ops import (earlier_edges, greedy_first_fit,
                            precedence_levels)


def must_precede(cfg, inc: Incidence, b: int):
    """P[i, j] = i must precede j (i read a key j writes; snapshot read),
    minus the RMW self-overlap diagonal.  The ONE edge derivation shared
    by validate_maat and the distributed verify round
    (runtime/server.make_vote_steps.check): the verify round must check
    exactly the edge set the positions were negotiated for.

    Escrow (``order_free``) exemption, gated by ``escrow_order_free``
    AND ``escrow_sweep``: the reader side draws from the ORDERED read
    incidence (ro aliases r when off).  Escrow writes are commutative
    deltas — like blind writes they need no range constraint among
    themselves (any linear extension accumulates the same sum) — and
    escrow reads are declared-immutable columns, so a TPC-C Payment
    epoch contributes NO must-precede edges: the warehouse-row RMW
    clique that used to close m*(m-1)/2 ranges per epoch vanishes,
    while an ordered read of the accumulator still precedes every
    uncommitted delta writer exactly as before."""
    ov = get_overlap(cfg)
    ro1 = inc.r1 if inc.ro1 is None else inc.ro1
    ro2 = inc.r2 if inc.ro1 is None else inc.ro2
    p = ov(ro1, inc.w1, ro2, inc.w2)
    return p & ~jnp.eye(b, dtype=bool)


def repair_frontier(cfg, state, batch: AccessBatch, inc: Incidence,
                    committed, losers):
    """MAAT invalidation rule (transaction repair, engine/repair.py):
    range re-intersection.  A MAAT loser's commit-timestamp range closed
    — a mutual must-precede pair or a peeled cycle pinned its lower
    bound at or above its upper.  Every closing constraint is a
    reader-before-writer edge ``P[i, j]`` (under epoch snapshots the
    ONLY constraint MAAT has), so the loser's range re-opens exactly by
    re-reading the keys on its P-edges into the committed set: the
    re-read inverts the edge (j's value is now i's input, so i orders
    AFTER j with an open upper bound) — in access space that is the
    ordered-read-vs-committed-write frontier.  The repair sub-round then
    re-runs this module's validate restricted to the losers: mutual
    pairs re-sweep, residual cycles re-peel — the range re-intersection
    one snapshot later, against ranges that all start open."""
    return committed_write_frontier(cfg, batch, inc, committed, losers)


def validate_maat(cfg, state, batch: AccessBatch, inc: Incidence):
    b = batch.active.shape[0]
    p = must_precede(cfg, inc, b)
    lane = jnp.arange(b, dtype=jnp.int32)

    # -- stage 1: mutual pairs -> lex-first MIS, losers' ranges close ---
    mutual = p & p.T
    e = earlier_edges(mutual, batch.rank, batch.active)
    win, lose, und = greedy_first_fit(e, batch.active,
                                      rounds=cfg.sweep_rounds)
    closed = lose & batch.active
    defer = und & batch.active

    # -- stage 2: peel true cycles (>= 3) from the residual digraph -----
    live0 = batch.active & ~closed & ~defer
    gt = (batch.rank[None, :] > batch.rank[:, None]) | (
        (batch.rank[None, :] == batch.rank[:, None])
        & (lane[None, :] > lane[:, None]))

    # cheap gate: any instability (cycle members always have lv >=
    # rounds; so do over-deep chains) routes to the closure branch.  The
    # common shallow-acyclic epoch keeps the level order and pays no
    # matmuls beyond the sweeps.
    lv_f, un_f0 = precedence_levels(p, live0, rounds=cfg.sweep_rounds)
    closure_rounds = max(1, (b - 1).bit_length())   # paths up to 2^k >= b

    def fast(_):
        zero = jnp.zeros_like(live0)
        return zero, zero, lv_f

    def closure(_):
        # Full-graph transitive closure by boolean matmul squaring on the
        # MXU (log2(B) squarings cover every simple path).  It answers
        # both open questions at once, exactly:
        # * cycles: a node is on a directed cycle iff self-reachable —
        #   never true for acyclic nodes, so deep chains are spared
        #   (ADVICE r3: the old both-directions-unstable test aborted
        #   them);
        # * order: ancestor COUNT is a strict topological key on the
        #   acyclic part (i -> j implies anc(j) >= anc(i)+1), so every
        #   acyclic txn commits regardless of chain depth — matching
        #   serial validation, where real-valued ranges make any DAG
        #   feasible (`maat.cpp:44-162` only closes a range against
        #   already-committed txns that sandwich it, which needs a
        #   cycle).
        # Serial-validation semantics on cycles: the LATEST validators
        # are the ones whose ranges close, so each peel round aborts the
        # locally-youngest proven cycle members, recomputes
        # reachability, and repeats — survivors order dynamically and
        # COMMIT (a 3-cycle commits two).  Fixed trip count (ADVICE r3:
        # the old fixpoint while_loop was a data-dependent latency
        # cliff); cycle leftovers past the budget defer, and the
        # engine's defer budget backstops their liveness.
        def square(_, r):
            f = r.astype(jnp.bfloat16)
            return r | (jnp.matmul(
                f, f, preferred_element_type=jnp.float32) > 0)

        def reach_of(live):
            sub = p & live[:, None] & live[None, :]
            return jax.lax.fori_loop(0, closure_rounds, square, sub)

        on_cycle0 = jnp.diagonal(reach_of(live0)) & live0
        sym = p | p.T

        # peel rounds are CHEAP (level sweeps, no matmuls — recomputing
        # the closure every round would cost 16x the matmuls): victims
        # are the locally-youngest members of the INITIAL proven cycle
        # set that are still unstable both ways after earlier removals.
        # Approximation, stated precisely: instability is a proxy for
        # "still on a cycle", so an ex-cycle node sitting in a residual
        # chain segment deeper than ~2*sweep_rounds from both ends can
        # still be peeled (conservative: extra abort, never a wrong
        # commit).  A PURE chain node is never on_cycle0, so the ADVICE
        # r3 class — acyclic txns aborted as cycle members — cannot
        # recur; only txns that started the epoch on a real cycle pay.
        def peel(_, aborted):
            live = live0 & ~aborted
            _, un_f = precedence_levels(p, live, rounds=cfg.sweep_rounds)
            _, un_r = precedence_levels(p.T, live,
                                        rounds=cfg.sweep_rounds)
            candr = un_f & un_r & on_cycle0
            nb = sym & candr[:, None] & candr[None, :]
            has_younger = (nb & gt).any(axis=1)
            return aborted | (candr & ~has_younger)

        aborted = jax.lax.fori_loop(0, cfg.maat_peel_rounds, peel,
                                    jnp.zeros_like(batch.active))
        # order + leftover pass on the survivor graph: committed txns
        # are never self-reachable here, so ancestor count is a STRICT
        # topological key for them; still-cyclic leftovers past the
        # peel budget defer (the engine's defer budget backstops them)
        live = live0 & ~aborted
        reach = reach_of(live)
        leftover = jnp.diagonal(reach) & live
        anc = jnp.sum(reach, axis=0, dtype=jnp.int32)
        return aborted, leftover, anc

    aborted, defer2, ordkey = jax.lax.cond(un_f0.any(), closure, fast,
                                           None)
    defer = defer | (defer2 & live0)
    commit = live0 & ~aborted & ~defer2
    order = ordkey * b + lane                 # topological extension of P
    v = Verdict(commit=commit, abort=(closed | aborted) & batch.active,
                defer=defer, order=order,
                level=jnp.zeros_like(batch.rank))
    return v, state
